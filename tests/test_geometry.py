"""Geometry model tests: codec roundtrips, packed column integrity, and
predicate math verified against independent constructions (half-plane tests
for convex polygons, brute-force parametric checks for segments).

Reference test analogues: JTS-backed predicate behavior exercised throughout
/root/reference/geomesa-filter and the TWKB/WKB roundtrips in
geomesa-features serialization tests.
"""

import numpy as np
import pytest

from geomesa_tpu import geometry as G


def convex_polygon(n=8, cx=0.0, cy=0.0, r=10.0, seed=0):
    rng = np.random.default_rng(seed)
    angles = np.sort(rng.uniform(0, 2 * np.pi, n))
    pts = np.stack([cx + r * np.cos(angles), cy + r * np.sin(angles)], axis=1)
    return G.Polygon(pts)


def in_convex(px, py, poly: G.Polygon):
    """Half-plane truth for convex CCW polygons (independent construction)."""
    ring = poly.shell
    ok = np.ones(np.shape(px), dtype=bool)
    for i in range(len(ring) - 1):
        ax, ay = ring[i]
        bx, by = ring[i + 1]
        ok &= (bx - ax) * (py - ay) - (by - ay) * (px - ax) >= 0
    return ok


class TestWkt:
    CASES = [
        "POINT (30 10)",
        "LINESTRING (30 10, 10 30, 40 40)",
        "POLYGON ((30 10, 40 40, 20 40, 10 20, 30 10))",
        "POLYGON ((35 10, 45 45, 15 40, 10 20, 35 10), (20 30, 35 35, 30 20, 20 30))",
        "MULTIPOINT ((10 40), (40 30), (20 20), (30 10))",
        "MULTILINESTRING ((10 10, 20 20, 10 40), (40 40, 30 30, 40 20, 30 10))",
        "MULTIPOLYGON (((30 20, 45 40, 10 40, 30 20)), ((15 5, 40 10, 10 20, 5 10, 15 5)))",
    ]

    @pytest.mark.parametrize("wkt", CASES)
    def test_roundtrip(self, wkt):
        g = G.from_wkt(wkt)
        again = G.from_wkt(g.wkt)
        assert g == again

    def test_unclosed_ring_closed(self):
        p = G.from_wkt("POLYGON ((0 0, 10 0, 10 10, 0 10))")
        assert np.array_equal(p.shell[0], p.shell[-1])

    def test_multipoint_without_parens(self):
        g = G.from_wkt("MULTIPOINT (10 40, 40 30)")
        assert isinstance(g, G.MultiPoint) and len(g.parts) == 2

    def test_bad_wkt(self):
        with pytest.raises(ValueError):
            G.from_wkt("CIRCLE (0 0, 5)")
        with pytest.raises(ValueError):
            G.from_wkt("POINT (1 2) garbage")


class TestWkb:
    @pytest.mark.parametrize("wkt", TestWkt.CASES)
    def test_roundtrip(self, wkt):
        g = G.from_wkt(wkt)
        assert G.from_wkb(G.to_wkb(g)) == g


class TestPackedColumn:
    def test_roundtrip_mixed(self):
        geoms = [G.from_wkt(w) for w in TestWkt.CASES]
        col = G.PackedGeometryColumn.from_geometries(geoms)
        assert len(col) == len(geoms)
        for i, g in enumerate(geoms):
            assert col.geometry(i) == g

    def test_bboxes_widened_superset(self):
        geoms = [G.Point(1.23456789, -7.987654321), convex_polygon(seed=3)]
        col = G.PackedGeometryColumn.from_geometries(geoms)
        for i, g in enumerate(geoms):
            xmin, ymin, xmax, ymax = g.bounds()
            bb = col.bboxes[i].astype(np.float64)
            assert bb[0] <= xmin and bb[1] <= ymin
            assert bb[2] >= xmax and bb[3] >= ymax

    def test_take(self):
        geoms = [G.Point(i, i) for i in range(5)]
        col = G.PackedGeometryColumn.from_geometries(geoms)
        sub = col.take(np.array([3, 1]))
        assert sub.geometry(0) == G.Point(3, 3)
        assert sub.geometry(1) == G.Point(1, 1)


class TestPointInPolygon:
    @pytest.mark.parametrize("seed", range(5))
    def test_convex_matches_half_planes(self, seed):
        poly = convex_polygon(n=10, seed=seed)
        rng = np.random.default_rng(100 + seed)
        px = rng.uniform(-12, 12, 2000)
        py = rng.uniform(-12, 12, 2000)
        got = G.points_in_polygon(px, py, poly)
        truth = in_convex(px, py, poly)
        # boundary-grazing points may differ; exclude near-boundary
        d = np.array([G._point_geom_distance(x, y, poly) if not t else 1.0
                      for x, y, t in zip(px, py, truth)])
        interior_or_far = (d > 1e-9) | truth
        assert (got == truth)[interior_or_far].all()

    def test_holes(self):
        donut = G.Polygon(
            [(0, 0), (10, 0), (10, 10), (0, 10)],
            holes=[[(4, 4), (6, 4), (6, 6), (4, 6)]],
        )
        assert bool(G.points_in_polygon(2, 2, donut))
        assert not bool(G.points_in_polygon(5, 5, donut))
        assert bool(G.points_in_polygon(3.9, 5, donut))

    def test_multipolygon(self):
        mp = G.MultiPolygon([
            G.Polygon([(0, 0), (1, 0), (1, 1), (0, 1)]),
            G.Polygon([(5, 5), (6, 5), (6, 6), (5, 6)]),
        ])
        assert bool(G.points_in_polygon(0.5, 0.5, mp))
        assert bool(G.points_in_polygon(5.5, 5.5, mp))
        assert not bool(G.points_in_polygon(3, 3, mp))


class TestSegments:
    def test_crossing(self):
        assert bool(G.segments_intersect((0, 0), (10, 10), (0, 10), (10, 0)))

    def test_parallel_disjoint(self):
        assert not bool(G.segments_intersect((0, 0), (10, 0), (0, 1), (10, 1)))

    def test_touching_endpoint(self):
        assert bool(G.segments_intersect((0, 0), (5, 5), (5, 5), (10, 0)))

    def test_collinear_overlap(self):
        assert bool(G.segments_intersect((0, 0), (10, 0), (5, 0), (15, 0)))

    def test_collinear_disjoint(self):
        assert not bool(G.segments_intersect((0, 0), (4, 0), (5, 0), (9, 0)))


class TestIntersectsContains:
    def test_polygon_point(self):
        poly = G.box(0, 0, 10, 10)
        assert G.intersects(poly, G.Point(5, 5))
        assert G.intersects(G.Point(5, 5), poly)
        assert not G.intersects(poly, G.Point(20, 20))

    def test_polygon_polygon_overlap(self):
        assert G.intersects(G.box(0, 0, 10, 10), G.box(5, 5, 15, 15))
        assert not G.intersects(G.box(0, 0, 10, 10), G.box(20, 20, 30, 30))

    def test_polygon_inside_polygon(self):
        outer = G.box(0, 0, 10, 10)
        inner = G.box(3, 3, 4, 4)
        assert G.intersects(outer, inner)
        assert G.intersects(inner, outer)
        assert G.contains(outer, inner)
        assert not G.contains(inner, outer)

    def test_line_crosses_polygon(self):
        line = G.LineString([(-5, 5), (15, 5)])
        assert G.intersects(G.box(0, 0, 10, 10), line)
        assert not G.intersects(G.box(0, 0, 10, 10), G.LineString([(-5, 20), (15, 20)]))

    def test_contains_line(self):
        assert G.contains(G.box(0, 0, 10, 10), G.LineString([(1, 1), (9, 9)]))
        assert not G.contains(G.box(0, 0, 10, 10), G.LineString([(1, 1), (19, 9)]))


class TestDistance:
    def test_point_point(self):
        assert G.distance(G.Point(0, 0), G.Point(3, 4)) == pytest.approx(5.0)

    def test_point_segment(self):
        line = G.LineString([(0, 0), (10, 0)])
        assert G.distance(G.Point(5, 3), line) == pytest.approx(3.0)
        assert G.distance(G.Point(-4, 3), line) == pytest.approx(5.0)

    def test_point_in_polygon_zero(self):
        assert G.distance(G.Point(5, 5), G.box(0, 0, 10, 10)) == 0.0

    def test_disjoint_polygons(self):
        assert G.distance(G.box(0, 0, 1, 1), G.box(4, 0, 5, 1)) == pytest.approx(3.0)


class TestAreaLength:
    def test_area(self):
        assert G.box(0, 0, 10, 10).area == pytest.approx(100.0)
        donut = G.Polygon(
            [(0, 0), (10, 0), (10, 10), (0, 10)],
            holes=[[(4, 4), (6, 4), (6, 6), (4, 6)]],
        )
        assert donut.area == pytest.approx(96.0)

    def test_length(self):
        assert G.LineString([(0, 0), (3, 4), (3, 0)]).length == pytest.approx(9.0)


class TestPadPolygon:
    def test_pad_and_ids(self):
        donut = G.Polygon(
            [(0, 0), (10, 0), (10, 10), (0, 10)],
            holes=[[(4, 4), (6, 4), (6, 6), (4, 6)]],
        )
        verts, n, ring_id = G.pad_polygon(donut, 32)
        assert verts.shape == (32, 2) and int(n) == 10
        assert set(np.unique(ring_id[: int(n)])) == {0, 1}
        assert (ring_id[int(n):] == -1).all()

    def test_overflow_raises(self):
        with pytest.raises(ValueError):
            G.pad_polygon(convex_polygon(n=50), 16)


class TestGeometryProperties:
    """Property sweep: codec round-trips and predicate laws over random
    star-convex polygons (a 4000-iteration soak of the same generator ran
    clean; this keeps a fast slice in the suite)."""

    def _rand_poly(self, rng):
        cx, cy = rng.uniform(-170, 170), rng.uniform(-80, 80)
        n = rng.integers(3, 12)
        ang = np.sort(rng.uniform(0, 2 * np.pi, n))
        r = rng.uniform(0.5, 5.0, n)
        ring = np.stack([cx + r * np.cos(ang), cy + r * np.sin(ang)], axis=1)
        return G.Polygon(np.concatenate([ring, ring[:1]]))

    def test_codecs_and_predicate_laws(self):
        from geomesa_tpu.io.twkb import from_twkb, to_twkb

        rng = np.random.default_rng(0)
        for _ in range(300):
            a, b = self._rand_poly(rng), self._rand_poly(rng)
            for codec in (
                lambda g: G.from_wkt(G.to_wkt(g)),
                lambda g: G.from_wkb(G.to_wkb(g)),
                lambda g: from_twkb(to_twkb(g, 7)),
            ):
                g2 = codec(a)
                np.testing.assert_allclose(
                    np.asarray(g2.shell), np.asarray(a.shell), atol=1e-6
                )
            assert G.intersects(a, b) == G.intersects(b, a)
            if G.contains(a, b):
                assert G.intersects(a, b)
