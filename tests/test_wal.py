"""Streaming WAL durability (ISSUE 10; docs/durability.md "Streaming
WAL"): the hot tier's write-ahead log, crash-anywhere recovery, the
seeded chaos harness, and the loss-window contracts per sync policy.

The invariants under test:

- **zero acknowledged-row loss under sync=always**: any write that
  returned survives a kill at ANY fault point, recovered bit-identically
  (hot rows, cold store, query results) for a non-racing op stream;
- **bounded loss window under sync=interval**: a hard kill loses at
  most the writes acknowledged since the last sync;
- **reads exact throughout**: the closed-loop chaos workload's reader
  never observes a state different from the acked oracle, while seeded
  random faults fire across stream.*/streaming.*/persist.*.
"""

import os
import threading
import time

import numpy as np
import pytest

from geomesa_tpu import fault, geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.storage import persist
from geomesa_tpu.streaming import (
    LambdaStore,
    StreamConfig,
    WalConfig,
    WriteAheadLog,
)
from geomesa_tpu.streaming import wal as walmod

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"
T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
DAY = 86_400_000


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    fault.injector().reset()


def _cold(n=300, seed=0):
    ds = DataStore()
    sft = FeatureType.from_spec("t", SPEC)
    ds.create_schema(sft)
    if n:
        rng = np.random.default_rng(seed)
        ds.write("t", FeatureCollection.from_columns(
            sft, [f"c{i}" for i in range(n)],
            {"name": np.array(["n"] * n),
             "dtg": T0 + rng.integers(0, 30 * DAY, n),
             "geom": (rng.uniform(-50, 50, n), rng.uniform(-50, 50, n))},
        ))
        ds.compact("t")
    return ds


def _saved_lambda(tmp_path, n=300, seed=0, sync="always", seg=64 << 20,
                  fold_rows=8, expiry_ms=None, metrics=None):
    """(root, LambdaStore-with-WAL) over a durably saved cold store."""
    ds = _cold(n=n, seed=seed)
    if metrics is not None:
        ds.metrics = metrics
    root = tmp_path / "s"
    persist.save(ds, root)
    lam = LambdaStore(
        ds, "t", expiry_ms=expiry_ms,
        config=StreamConfig(chunk_rows=64, fold_rows=fold_rows),
        wal_dir=str(root / "_wal"),
        wal_config=WalConfig(
            sync=sync, segment_bytes=seg, sync_interval_ms=1e9,
        ),
    )
    return root, lam


def _assert_same_store(a: DataStore, b: DataStore) -> None:
    """Cold-store bit-identity: feature order + values + every index's
    sorted keys and permutation."""
    fa, fb = a.features("t"), b.features("t")
    assert fa.ids.tolist() == fb.ids.tolist()
    for col in fa.columns:
        ca, cb = fa.columns[col], fb.columns[col]
        if hasattr(ca, "x"):
            assert np.array_equal(ca.x, cb.x) and np.array_equal(ca.y, cb.y)
        else:
            assert np.array_equal(np.asarray(ca), np.asarray(cb)), col
    for idx in a.indexes("t"):
        ta, tb = a.table("t", idx.name), b.table("t", idx.name)
        assert np.array_equal(
            np.asarray(ta.zs), np.asarray(tb.zs)
        ), idx.name
        assert np.array_equal(
            np.asarray(ta.perm, np.int64), np.asarray(tb.perm, np.int64)
        ), idx.name


QUERIES = [
    "bbox(geom, -60, -60, 60, 60)",
    "bbox(geom, -20, -20, 20, 20)",
    "bbox(geom, 0, 0, 45, 45) AND dtg DURING "
    "2024-01-01T00:00:00Z/2024-01-20T00:00:00Z",
    "IN ('c0', 'c1', 'h3', 'h7')",
]


def _results(store) -> list:
    out = []
    for q in QUERIES:
        fc = store.query(q)
        ids = [str(i) for i in fc.ids.tolist()]
        names = [str(v) for v in np.asarray(fc.columns["name"]).tolist()]
        out.append(sorted(zip(ids, names)))
    return out


# -- the record codec -------------------------------------------------------


class TestWalCodec:
    def test_value_roundtrip_bit_exact(self):
        rows = [{
            "s": "text", "i": 7, "f": 0.1 + 0.2, "b": True, "n": None,
            "ni": np.int64(9), "nf": np.float64(1 / 3),
            "by": b"\x00\xffpayload",
            "dt": np.datetime64("2024-03-01T12:00:00.123", "ms"),
            "g": geo.Point(0.1 + 0.2, 1 / 3),
            "poly": geo.Polygon([(0, 0), (2, 0), (2, 2), (0, 2)]),
        }]
        import json

        back = walmod.decode_rows(
            json.loads(json.dumps(rows, default=walmod._enc_json))
        )
        r = back[0]
        assert r["s"] == "text" and r["i"] == 7 and r["b"] is True
        assert r["n"] is None
        assert r["f"] == 0.1 + 0.2  # repr round-trip, not decimal
        assert r["ni"] == 9 and r["nf"] == 1 / 3
        assert r["by"] == b"\x00\xffpayload"
        assert r["dt"] == np.datetime64("2024-03-01T12:00:00.123", "ms")
        # geometry through WKB: bit-exact coordinates (WKT would not be)
        assert r["g"].x == 0.1 + 0.2 and r["g"].y == 1 / 3
        assert r["poly"].wkt == rows[0]["poly"].wkt

    def test_pack_upsert_columnar_roundtrip(self):
        import json

        rows = [
            {"name": f"n{i}", "dtg": T0 + i,
             "geom": geo.Point(i * 0.1, 1 / 3 + i)}
            for i in range(5)
        ]
        rec = walmod.pack_upsert(rows)
        assert "cols" in rec and "geom" in rec["pts"]  # the fast path
        back = walmod.unpack_upsert(
            json.loads(json.dumps(rec, default=walmod._enc_json))
        )
        for a, b in zip(rows, back):
            assert a["name"] == b["name"] and a["dtg"] == b["dtg"]
            assert a["geom"].x == b["geom"].x  # bit-exact coords
            assert a["geom"].y == b["geom"].y

    def test_pack_upsert_ragged_batch_falls_back(self):
        import json

        rows = [
            {"name": "a", "geom": geo.Point(1, 2)},
            {"name": "b", "extra": 1},
        ]
        rec = walmod.pack_upsert(rows)
        assert "rows" in rec  # per-row fallback, nothing dropped
        back = walmod.unpack_upsert(
            json.loads(json.dumps(rec, default=walmod._enc_json))
        )
        assert back[1]["extra"] == 1 and back[0]["geom"].x == 1.0

    def test_unsupported_value_fails_before_ack(self, tmp_path):
        root, lam = _saved_lambda(tmp_path, n=10)
        with pytest.raises(walmod.WalError, match="cannot WAL-encode"):
            lam.write([{"name": object(), "dtg": T0,
                        "geom": geo.Point(0, 0)}], ids=["bad"])
        assert "bad" not in lam.hot._rows  # refused pre-ack, pre-apply
        lam.close()

    def test_implausible_frame_length_is_damage_not_torn(self):
        """A bit flip inflating the length varint must read as
        CORRUPTION (quarantine path), not as a torn tail — a torn
        classification would silently truncate intact later records."""
        frames = walmod._frame(b'{"s":0,"k":"u"}')
        bomb = frames + b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x7f"
        recs, bad = walmod._parse_frames(bomb)
        assert [r["s"] for r in recs] == [0]
        assert bad is not None and bad[1] == "checksum"
        assert "implausible" in bad[2]

    def test_frame_parse_detects_torn_and_checksum(self):
        frames = b"".join(
            walmod._frame(b'{"s":%d,"k":"u"}' % i) for i in range(3)
        )
        recs, bad = walmod._parse_frames(frames)
        assert [r["s"] for r in recs] == [0, 1, 2] and bad is None
        recs, bad = walmod._parse_frames(frames[:-4])  # cut mid-frame
        assert [r["s"] for r in recs] == [0, 1]
        assert bad is not None and bad[1] == "torn"
        flipped = bytearray(frames)
        flipped[len(flipped) // 2] ^= 0x40
        recs, bad = walmod._parse_frames(bytes(flipped))
        assert bad is not None and bad[1] == "checksum"
        assert len(recs) < 3


# -- the log itself ---------------------------------------------------------


class TestWriteAheadLog:
    def test_sync_always_acknowledges_durable(self, tmp_path):
        reg = MetricsRegistry()
        wal = WriteAheadLog(
            tmp_path / "w", WalConfig(sync="always"), metrics=reg,
        )
        for i in range(5):
            wal.append("u", {"ids": [f"a{i}"], "rows": [], "nid": 0})
        assert wal.synced_seq == wal.last_seq == 4
        assert reg.counters["geomesa.stream.wal.appends"] == 5
        assert reg.counters["geomesa.stream.wal.syncs"] == 5
        wal.close()

    def test_interval_mode_buffers_until_sync(self, tmp_path):
        wal = WriteAheadLog(
            tmp_path / "w",
            WalConfig(sync="interval", sync_interval_ms=1e9),
        )
        for i in range(4):
            wal.append("u", {"ids": [f"a{i}"], "rows": [], "nid": 0})
        assert wal.synced_seq == -1  # nothing durable yet
        wal.sync()
        assert wal.synced_seq == 3
        wal.close()

    def test_interval_elapsed_triggers_sync(self, tmp_path):
        wal = WriteAheadLog(
            tmp_path / "w",
            WalConfig(sync="interval", sync_interval_ms=1.0),
        )
        wal.append("u", {"ids": ["a"], "rows": [], "nid": 0})
        time.sleep(0.01)
        wal.append("u", {"ids": ["b"], "rows": [], "nid": 0})
        assert wal.synced_seq >= 1  # the elapsed interval forced a sync
        wal.close()

    def test_interval_idle_producer_syncs_in_background(self, tmp_path):
        """The loss window must be time-bounded WITHOUT traffic: an
        idle producer's buffered acknowledged records are fsync'd by
        the background tick, not held until the next append."""
        wal = WriteAheadLog(
            tmp_path / "w",
            WalConfig(sync="interval", sync_interval_ms=20.0),
        )
        wal.append("u", {"ids": ["a"], "rows": [], "nid": 0})
        deadline = time.monotonic() + 5.0
        while wal.synced_seq < wal.last_seq:
            assert time.monotonic() < deadline, "background sync never ran"
            time.sleep(0.01)
        assert wal.synced_seq == 0
        wal.close()

    def test_failed_append_does_not_pin_applied_horizon(self, tmp_path):
        """A write whose sync exhausts its retry budget must un-register
        its pending seqno: otherwise every future checkpoint cover (and
        segment retirement) stays pinned below it forever."""
        wal = WriteAheadLog(tmp_path / "w", WalConfig(sync="always"))
        with fault.inject("stream.wal.sync", kind="io_error", times=None):
            with pytest.raises(OSError):
                wal.append("u", {"ids": ["a"], "rows": [], "nid": 0},
                           pending=True)
        # the failed (never-acknowledged) record no longer holds the
        # horizon back
        assert wal.applied_horizon() == wal.last_seq
        seq = wal.append("u", {"ids": ["b"], "rows": [], "nid": 0},
                         pending=True)
        wal.applied(seq)
        assert wal.applied_horizon() == seq
        wal.close()

    def test_rotation_and_checkpoint_retirement(self, tmp_path):
        reg = MetricsRegistry()
        wal = WriteAheadLog(
            tmp_path / "w",
            WalConfig(sync="always", segment_bytes=1 << 10), metrics=reg,
        )
        for i in range(40):
            wal.append("u", {"ids": [f"a{i}"], "rows": ["x" * 64], "nid": 0})
        segs = sorted(os.listdir(tmp_path / "w"))
        assert len(segs) > 2
        assert reg.counters["geomesa.stream.wal.rotations"] >= 2
        # segment names carry their start seqno, in order
        starts = [WriteAheadLog._seg_start(s) for s in segs]
        assert starts == sorted(starts) and starts[0] == 0
        wal.checkpoint()
        left = sorted(os.listdir(tmp_path / "w"))
        assert len(left) == 1  # every sealed segment retired
        assert reg.counters["geomesa.stream.wal.retired"] >= 2
        # replay after a checkpoint yields nothing
        assert list(wal.replay()) == []
        wal.close()

    def test_failed_seal_fsync_is_never_masked(self, tmp_path, monkeypatch):
        """The rotation seal (docs/concurrency.md: fsync moved OUTSIDE
        the append lock) fsyncs the old segment BEFORE the fd swap: a
        failing seal fsync must leave the active segment unchanged so
        the retry hits the SAME fd — a later sync() of a fresh segment
        must never advance the durability horizon over records that
        only reached the sealed segment's page cache."""
        import geomesa_tpu.streaming.wal as walmod2

        # sync=off: appends never fsync, so the ONLY fsync in play is
        # the rotation seal — the path under test
        wal = WriteAheadLog(
            tmp_path / "w",
            WalConfig(sync="off", segment_bytes=1 << 10),
        )
        real_fsync = os.fsync
        boom = {"armed": False, "hits": 0}

        def flaky_fsync(fd):
            if boom["armed"]:
                boom["hits"] += 1
                raise OSError("injected seal-fsync failure")
            return real_fsync(fd)

        monkeypatch.setattr(walmod2.os, "fsync", flaky_fsync)
        path_before = wal._active_path
        boom["armed"] = True
        with pytest.raises(OSError):
            for i in range(40):  # enough appends to trigger a rotation
                wal.append(
                    "u", {"ids": [f"a{i}"], "rows": ["x" * 64], "nid": 0}
                )
        assert boom["hits"] >= 1
        # the swap never happened: active segment (and fd) unchanged,
        # and the durability horizon did not ride over the failure
        assert wal._active_path == path_before
        synced_after_failure = wal.synced_seq
        boom["armed"] = False
        seq = wal.append("u", {"ids": ["ok"], "rows": [], "nid": 0})
        # the retry fsyncs the ORIGINAL fd: everything buffered there
        # becomes durable and the horizon advances past it
        assert wal.synced_seq == seq > synced_after_failure
        wal.close()
        # every acknowledged record survives a reopen (the mask would
        # have silently dropped the pre-failure suffix on power loss;
        # here we at least prove the log itself is intact and ordered)
        wal2 = WriteAheadLog(tmp_path / "w", WalConfig(sync="always"))
        recs = list(wal2.replay())
        assert [r["s"] for r in recs] == list(range(seq + 1))
        wal2.close()

    def test_reopen_continues_seqnos(self, tmp_path):
        wal = WriteAheadLog(tmp_path / "w", WalConfig(sync="always"))
        for i in range(3):
            wal.append("u", {"ids": [f"a{i}"], "rows": [], "nid": 0})
        wal.close()
        wal2 = WriteAheadLog(tmp_path / "w", WalConfig(sync="always"))
        assert wal2.last_seq == 2
        seq = wal2.append("u", {"ids": ["b"], "rows": [], "nid": 0})
        assert seq == 3
        recs = list(wal2.replay())
        assert [r["s"] for r in recs] == [0, 1, 2, 3]
        wal2.close()

    def test_empty_lone_segment_keeps_seqno_floor(self, tmp_path):
        """A lone ACTIVE segment emptied by damage truncation (its
        sealed predecessors already retired) must floor the seqno at
        its own start: resetting to 0 would hide new records below an
        old checkpoint cover and make the next rotation sort before
        this segment — replay out of append order."""
        wdir = tmp_path / "w"
        wdir.mkdir()
        (wdir / "wal-00000000000000000412.log").write_bytes(b"")
        wal = WriteAheadLog(wdir, WalConfig(sync="always"))
        assert wal.last_seq == 411
        seq = wal.append("u", {"ids": ["a"], "rows": [], "nid": 0})
        assert seq == 412
        wal.close()

    def test_checkpoint_fsyncs_even_under_sync_off(self, tmp_path,
                                                   monkeypatch):
        """checkpoint() deletes sealed segments next — the watermark
        and the active tail must be fsync'd first even when the policy
        is sync=off, or a power loss leaves a hole the retired records
        can no longer fill."""
        calls = []
        real = os.fsync
        monkeypatch.setattr(walmod.os, "fsync",
                            lambda fd: (calls.append(fd), real(fd))[1])
        wal = WriteAheadLog(tmp_path / "w", WalConfig(sync="off"))
        wal.append("u", {"ids": ["a"], "rows": [], "nid": 0})
        assert calls == []  # the policy really never fsyncs on append
        wal.checkpoint()
        assert len(calls) >= 1  # ...but the retirement path must
        wal.close()

    def test_reopen_accepts_watermark_only_sealed_segments(self, tmp_path):
        """A checkpoint's own watermark/'c' records can rotate into a
        sealed segment (seqnos past the cover): a cleanly closed store
        must still reopen through the plain constructor —
        needs_recovery is about unreplayed MUTATIONS, in every segment,
        not about segment count."""
        cfg = WalConfig(sync="always", segment_bytes=1 << 10)
        wal = WriteAheadLog(tmp_path / "w", cfg)
        for i in range(4):
            wal.append("u", {"ids": [f"a{i}"], "rows": ["x" * 300],
                             "nid": 0})
        u_last = wal.last_seq
        wal.append("w", {"ids": [f"a{i}" for i in range(4)] * 20,
                         "inc": True})
        wal.checkpoint(cover=u_last)
        wal.close()
        wal2 = WriteAheadLog(tmp_path / "w", cfg)
        assert wal2.needs_recovery is False
        # ...but an unreplayed MUTATION past the cover flips it
        wal2.append("u", {"ids": ["b"], "rows": [], "nid": 0})
        wal2.close()
        wal3 = WriteAheadLog(tmp_path / "w", cfg)
        assert wal3.needs_recovery is True
        wal3.close()

    def test_closed_log_refuses_appends(self, tmp_path):
        wal = WriteAheadLog(tmp_path / "w", WalConfig(sync="always"))
        wal.close()
        with pytest.raises(walmod.WalError, match="closed"):
            wal.append("u", {"ids": [], "rows": [], "nid": 0})
        wal.close()  # idempotent

    def test_group_commit_under_concurrent_producers(self, tmp_path):
        """N producers under sync=always: every append is durable when
        it returns, and the fsync count stays <= append count (group
        commit: one fsync may cover several producers' records)."""
        reg = MetricsRegistry()
        wal = WriteAheadLog(
            tmp_path / "w", WalConfig(sync="always"), metrics=reg,
        )
        errors: list = []

        def produce(k):
            try:
                for i in range(50):
                    seq = wal.append(
                        "u", {"ids": [f"p{k}_{i}"], "rows": [], "nid": 0}
                    )
                    assert wal.synced_seq >= seq
            except Exception as e:  # surfaced after join
                errors.append(e)

        threads = [
            threading.Thread(target=produce, args=(k,)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert wal.last_seq == 199
        recs = list(wal.replay())
        assert len(recs) == 200
        # seqnos are gapless and ordered on disk
        assert [r["s"] for r in recs] == list(range(200))
        assert reg.counters["geomesa.stream.wal.syncs"] <= 200
        wal.close()

    def test_applied_horizon_lags_pending_records(self, tmp_path):
        """The checkpoint cover: never past a logged-but-not-applied
        record (the acknowledged-loss race the chaos harness caught —
        a checkpoint between a record's append and its hot apply would
        otherwise cover a record whose effect is in neither the
        snapshot nor the save)."""
        wal = WriteAheadLog(tmp_path / "w", WalConfig(sync="always"))
        s0 = wal.append("u", {"ids": ["a"], "rows": [], "nid": 0},
                        pending=True)
        assert wal.applied_horizon() == s0 - 1
        s1 = wal.append("u", {"ids": ["b"], "rows": [], "nid": 0},
                        pending=True)
        wal.applied(s0)
        assert wal.applied_horizon() == s0  # still capped by s1
        wal.applied(s1)
        assert wal.applied_horizon() == s1 == wal.last_seq
        wal.close()

    def test_transient_sync_fault_retried(self, tmp_path):
        reg = MetricsRegistry()
        wal = WriteAheadLog(
            tmp_path / "w", WalConfig(sync="always"), metrics=reg,
        )
        with fault.inject("stream.wal.sync", kind="io_error", times=1):
            wal.append("u", {"ids": ["a"], "rows": [], "nid": 0})
        assert wal.synced_seq == 0
        assert reg.counters["geomesa.fault.retry"] >= 1
        wal.close()


# -- recovery ---------------------------------------------------------------


class TestRecovery:
    def _ops(self, lam, with_flushes=True):
        """A deterministic op stream: updates of cold ids, new ids,
        auto-ids, deletes, micro flushes and a fold-triggering burst."""
        lam.write(
            [{"name": f"u{i}", "dtg": T0 + i, "geom": geo.Point(i * 0.1, 1.0)}
             for i in range(20)],
            ids=[f"c{i}" for i in range(10)] + [f"h{i}" for i in range(10)],
        )
        if with_flushes:
            lam.flush()
        lam.write([{"name": "auto", "dtg": T0, "geom": geo.Point(3.0, 3.0)}])
        lam.write(
            [{"name": f"u2", "dtg": T0 + 5, "geom": geo.Point(-2.0, -2.0)}],
            ids=["h3"],
        )
        lam.delete(["h4"])
        lam.write(
            [{"name": f"b{i}", "dtg": T0 + i, "geom": geo.Point(0.5, i * 0.1)}
             for i in range(12)],
            ids=[f"c{i}" for i in range(20, 32)],
        )
        if with_flushes:
            lam.flush()  # n_upd >= fold_rows=8: the fold path publishes

    def test_restart_reproduces_placement_bit_identically(self, tmp_path):
        """The tentpole contract: same op stream, crash (abandon) +
        recover == the never-crashed store — same hot rows, same query
        results, and a cold tier bit-identical to a clean-restart twin
        (load() canonicalizes row order by partition, so the placement
        oracle is load + the same ops, the state a cleanly restarted
        store would hold)."""
        root, lam = _saved_lambda(tmp_path)
        self._ops(lam)
        live_results = _results(lam)
        lam.wal.crash()  # kill -9
        rec = LambdaStore.recover(root)
        assert rec.cold.store_health.status == "ok"
        # hot tier: same ids AND same row values as the live store
        assert sorted(rec.hot._rows) == sorted(lam.hot._rows)
        for fid, row in rec.hot._rows.items():
            live = lam.hot._rows[fid]
            assert row["name"] == live["name"] and row["dtg"] == live["dtg"]
            assert row["geom"].wkt == live["geom"].wkt, fid
        # cold tier: bit-identical to the clean-restart twin (the flush
        # watermarks replayed exactly the batches the twin publishes)
        twin = LambdaStore(
            persist.load(root), "t",
            config=StreamConfig(chunk_rows=64, fold_rows=8),
        )
        self._ops(twin)
        _assert_same_store(twin.cold, rec.cold)
        assert _results(rec) == live_results
        assert _results(twin) == live_results
        twin.close()
        # the recovered store keeps logging: another cycle + recover
        rec.write([{"name": "post", "dtg": T0, "geom": geo.Point(9.0, 9.0)}],
                  ids=["p0"])
        rec.wal.crash()
        rec2 = LambdaStore.recover(root)
        assert "p0" in rec2.hot._rows
        lam.flusher.close(), rec.flusher.close(), rec2.close()

    def test_constructor_refuses_unreplayed_wal(self, tmp_path):
        """Opening a store over a WAL that holds post-checkpoint records
        through the PLAIN constructor must refuse: continuing would let
        the next checkpoint cover and retire acknowledged records whose
        effects never reached any store (permanent loss through an
        innocent-looking call). recover() is the sanctioned path."""
        root, lam = _saved_lambda(tmp_path)
        lam.write([{"name": "a", "dtg": T0, "geom": geo.Point(1, 1)}],
                  ids=["h0"])
        lam.wal.crash()
        with pytest.raises(walmod.WalError, match="recover"):
            LambdaStore(persist.load(root), "t",
                        wal_dir=str(root / "_wal"),
                        wal_config=WalConfig(sync="interval",
                                             sync_interval_ms=10.0))
        # the refused constructor released its fd + sync thread (no
        # geomesa-wal-sync daemon may outlive the refusal)
        deadline = time.monotonic() + 2.0
        while any(t.name == "geomesa-wal-sync" and t.is_alive()
                  for t in threading.enumerate()):
            assert time.monotonic() < deadline, "sync thread leaked"
            time.sleep(0.01)
        rec = LambdaStore.recover(root)
        assert "h0" in rec.hot._rows
        # a checkpoint drains + saves; the plain constructor is then
        # legitimate again (clean-shutdown reopen)
        rec.checkpoint(root)
        rec.close()
        again = LambdaStore(persist.load(root), "t",
                            wal_dir=str(root / "_wal"))
        assert "h0" in [str(i) for i in again.query("IN ('h0')").ids.tolist()]
        again.close(), lam.flusher.close()

    def test_recover_after_checkpoint_is_empty_replay(self, tmp_path):
        root, lam = _saved_lambda(tmp_path)
        self._ops(lam)
        lam.checkpoint(root)
        post = _results(lam)
        lam.wal.crash()
        rec = LambdaStore.recover(root)
        assert len(rec.hot) == 0  # checkpoint drained; nothing replays
        assert _results(rec) == post
        rec.close(), lam.flusher.close()

    def test_checkpoint_crash_inside_save_keeps_watermark_consistent(
        self, tmp_path
    ):
        """The ISSUE 10 regression satellite: a crash INSIDE
        ``persist.save`` — after the checkpoint's flush already
        published to the in-process cold tier — must leave the previous
        on-disk store loadable AND the WAL watermark consistent: no
        checkpoint record landed, so recover() replays the retained
        records over the OLD store and loses nothing."""
        root, lam = _saved_lambda(tmp_path)
        self._ops(lam, with_flushes=False)
        expect = _results(lam)
        flushed = threading.Event()
        orig = lam.flusher.flush

        def spy(*a, **kw):
            out = orig(*a, **kw)
            flushed.set()
            return out

        lam.flusher.flush = spy
        with fault.inject("persist.manifest.rename", kind="crash"):
            with pytest.raises(fault.InjectedCrash):
                lam.checkpoint(root)
        assert flushed.is_set()  # the flush DID publish before the crash
        # previous on-disk store still loads clean
        assert persist.load(root).store_health.status == "ok"
        lam.wal.crash()
        rec = LambdaStore.recover(root)
        assert _results(rec) == expect
        rec.close(), lam.flusher.close()

    def test_checkpoint_crash_after_manifest_commit_is_idempotent(
        self, tmp_path
    ):
        """Crash AFTER the manifest commit (during GC): recover loads
        the NEW store and replays from the older watermark — replay over
        a store that already holds the records must converge (the
        idempotence direction)."""
        root, lam = _saved_lambda(tmp_path)
        self._ops(lam, with_flushes=False)
        expect = _results(lam)
        with fault.inject("persist.gc", kind="crash"):
            with pytest.raises(fault.InjectedCrash):
                lam.checkpoint(root)
        lam.wal.crash()
        rec = LambdaStore.recover(root)
        assert _results(rec) == expect
        rec.close(), lam.flusher.close()

    def test_write_racing_checkpoint_survives(self, tmp_path):
        """Deterministic replay of the race the seeded chaos run first
        caught: a write acknowledged around a concurrent checkpoint —
        logged before the checkpoint's cover capture, applied to the hot
        tier only after its snapshot — must survive crash + recover
        (the cover is the APPLIED horizon, not the append horizon)."""
        root, lam = _saved_lambda(tmp_path, n=50)
        entered, gate = threading.Event(), threading.Event()
        orig = lam.hot.upsert

        def slow_upsert(rows, ids=None):
            entered.set()
            assert gate.wait(10)
            return orig(rows, ids)

        lam.hot.upsert = slow_upsert
        t = threading.Thread(target=lambda: lam.write(
            [{"name": "raced", "dtg": T0, "geom": geo.Point(1.0, 1.0)}],
            ids=["race0"],
        ))
        t.start()
        assert entered.wait(10)
        # the record is logged (durable) but its hot apply is parked:
        # this checkpoint's snapshot cannot see it, so its cover must
        # not skip it either
        lam.checkpoint(root)
        gate.set()
        t.join()
        lam.hot.upsert = orig
        lam.wal.crash()
        rec = LambdaStore.recover(root)
        assert "race0" in rec.hot._rows  # replayed, not covered away
        assert rec.hot._rows["race0"]["name"] == "raced"
        rec.close(), lam.flusher.close()

    def test_expiry_sweep_replays_exactly(self, tmp_path):
        root, lam = _saved_lambda(tmp_path, expiry_ms=3_600_000)
        lam.write(
            [{"name": "old", "dtg": T0, "geom": geo.Point(1.0, 1.0)}],
            ids=["e0"],
        )
        time.sleep(0.002)
        swept = lam.expire(now_ms=int(time.time() * 1000) + 7_200_000)
        assert swept == 1 and "e0" not in lam.hot._rows
        lam.wal.crash()
        rec = LambdaStore.recover(root, expiry_ms=3_600_000)
        assert "e0" not in rec.hot._rows  # the sweep replayed, not undone
        rec.close(), lam.flusher.close()

    def test_failed_delete_stays_consistent_on_recovery(self, tmp_path):
        """A delete whose WAL append fails AFTER its bytes reached the
        file must never lose acknowledged data on recovery: destructive
        ops apply-then-record (atomically under the hot lock), so a
        durable 'd' describes a removal that really happened, and a
        later acknowledged re-upsert — a higher seqno — always wins
        replay. (Record-then-apply for deletes had the inverse hole:
        a durable 'd' for a removal that never happened would delete
        the acked row at replay.)"""
        root, lam = _saved_lambda(tmp_path, n=20)
        lam.write([{"name": "v1", "dtg": T0, "geom": geo.Point(1, 1)}],
                  ids=["x0"])
        # the delete's sync exhausts retries AFTER the buffer write:
        # the 'd' record is durable, the op raises (unacknowledged)
        with fault.inject("stream.wal.sync", kind="io_error", times=None):
            with pytest.raises(OSError):
                lam.delete(["x0"])
        assert "x0" not in lam.hot._rows  # applied before the record
        # a later acknowledged re-upsert must survive recovery
        lam.write([{"name": "v2", "dtg": T0, "geom": geo.Point(2, 2)}],
                  ids=["x0"])
        lam.wal.crash()
        rec = LambdaStore.recover(root)
        assert rec.hot._rows["x0"]["name"] == "v2"
        rec.close(), lam.flusher.close()

    def test_sliced_fold_advances_watermarks_per_slice(self, tmp_path):
        """Round 11 (docs/streaming.md "Incremental fold"): the fold
        publishes per slice and the WAL flush watermark advances with
        EACH published slice — a crash mid-fold replays only the
        unpublished suffix, with zero acknowledged-row loss and exact
        query results after recovery."""
        metrics = MetricsRegistry()
        root, lam = _saved_lambda(tmp_path, fold_rows=1, metrics=metrics)
        lam.config.slice_rows = 40  # shared with the flusher (same object)
        rows = [
            {"name": f"u{i}", "dtg": T0 + i, "geom": geo.Point(i * 0.05, 2.0)}
            for i in range(120)
        ]
        ids = [f"c{i}" for i in range(100)] + [f"nw{j}" for j in range(20)]
        lam.write([dict(r) for r in rows], ids=ids)
        live = _results(lam)  # the acknowledged state
        # crash entering the SECOND slice: slice 1 published + watermarked
        with fault.inject("stream.fold.slice", kind="crash", after=1, times=1):
            with pytest.raises(fault.InjectedCrash):
                lam.flush()
        assert metrics.counter_value("geomesa.stream.fold.slices") == 1
        lam.wal.crash()  # kill -9 mid-fold
        cfg = StreamConfig(chunk_rows=64, fold_rows=1, slice_rows=40)
        rec = LambdaStore.recover(root, config=cfg)
        assert rec.cold.store_health.status == "ok"
        assert _results(rec) == live  # nothing acknowledged was lost
        # a successful sliced fold writes one watermark PER slice
        assert rec.flush() > 0
        rec.wal.crash()
        reread = WriteAheadLog(str(root / "_wal"))
        kinds = [r.get("k") for r in reread.replay()]
        assert kinds.count("w") >= 3  # slice-grained, not batch-grained
        reread.close()
        rec2 = LambdaStore.recover(root, config=cfg)
        assert _results(rec2) == live
        rec2.close(), rec.flusher.close(), lam.flusher.close()

    def test_recovery_crash_is_restartable(self, tmp_path):
        """A crash DURING replay (stream.wal.replay) leaves the log
        untouched: recovery simply runs again."""
        root, lam = _saved_lambda(tmp_path)
        self._ops(lam)
        expect = _results(lam)
        lam.wal.crash()
        with fault.inject("stream.wal.replay", kind="crash"):
            with pytest.raises(fault.InjectedCrash):
                LambdaStore.recover(root)
        rec = LambdaStore.recover(root)
        assert _results(rec) == expect
        rec.close(), lam.flusher.close()

    def test_torn_tail_truncation_crash_is_restartable(self, tmp_path):
        root, lam = _saved_lambda(tmp_path)
        lam.write([{"name": "a", "dtg": T0, "geom": geo.Point(1, 1)}],
                  ids=["h0"])
        lam.write([{"name": "b", "dtg": T0, "geom": geo.Point(2, 2)}],
                  ids=["h1"])
        lam.wal.crash()
        wdir = root / "_wal"
        seg = sorted(os.listdir(wdir))[-1]
        p = wdir / seg
        with open(p, "rb+") as fh:  # tear the last record mid-frame
            fh.truncate(os.path.getsize(p) - 5)
        with fault.inject("stream.wal.truncate", kind="crash"):
            with pytest.raises(fault.InjectedCrash):
                LambdaStore.recover(root)
        rec = LambdaStore.recover(root)
        # the torn write was never acknowledged-durable in full; the
        # intact prefix survives
        assert "h0" in rec.hot._rows and "h1" not in rec.hot._rows
        assert rec.cold.store_health.status == "ok"  # torn tail != damage
        rec.close(), lam.flusher.close()

    def test_checksum_damage_quarantines_and_degrades(self, tmp_path):
        root, lam = _saved_lambda(tmp_path)
        for i in range(6):
            lam.write([{"name": "z", "dtg": T0, "geom": geo.Point(1, 1)}],
                      ids=[f"h{i}"])
        lam.wal.crash()
        wdir = root / "_wal"
        seg = sorted(os.listdir(wdir))[-1]
        p = wdir / seg
        data = open(p, "rb").read()
        off = len(data) // 2
        with open(p, "rb+") as fh:
            fh.seek(off)
            b = fh.read(1)
            fh.seek(off)
            fh.write(bytes([b[0] ^ 0x40]))
        rec = LambdaStore.recover(root)
        health = rec.cold.store_health
        assert health.status == "degraded"
        recs = [d for d in health.damage if d.type_name == "_wal"]
        assert len(recs) == 1 and recs[0].reason == "checksum"
        # the damaged tail moved into the PR 1 quarantine convention,
        # machine-readably reported
        qdir = root / "_quarantine" / "_wal"
        assert qdir.exists() and len(os.listdir(qdir)) == 1
        report = persist.damage_report(root)
        assert any(r["type"] == "_wal" and r["reason"] == "checksum"
                   for r in report)
        # the intact prefix replayed
        assert 0 < len(rec.hot) < 6
        rec.close(), lam.flusher.close()

    def test_recovery_over_sealed_damage_keeps_active_segment_live(
        self, tmp_path
    ):
        """Mid-log damage must never move the ACTIVE segment aside: the
        recovered store's open fd would keep acknowledging writes into
        the quarantined inode, invisible to the next recovery — acked
        rows written AFTER a damaged recovery must still survive the
        next kill."""
        root, lam = _saved_lambda(tmp_path, n=40, seg=1 << 10)
        for i in range(30):  # force several segment rotations
            lam.write([{"name": "x" * 48, "dtg": T0,
                        "geom": geo.Point(1.0, 1.0)}], ids=[f"h{i}"])
        lam.wal.crash()
        wdir = root / "_wal"
        segs = sorted(os.listdir(wdir))
        assert len(segs) >= 3
        # flip a bit mid-way through the FIRST (sealed) segment
        p = wdir / segs[0]
        data = open(p, "rb").read()
        off = len(data) // 2
        with open(p, "rb+") as fh:
            fh.seek(off)
            b = fh.read(1)
            fh.seek(off)
            fh.write(bytes([b[0] ^ 0x40]))
        rec = LambdaStore.recover(root)
        assert rec.cold.store_health.status == "degraded"
        # the active segment is still a LIVE file in the wal dir
        active = os.path.basename(rec.wal._active_path)
        assert active in os.listdir(wdir)
        # writes acked after the damaged recovery survive another kill
        rec.write([{"name": "post", "dtg": T0, "geom": geo.Point(2, 2)}],
                  ids=["n0"])
        rec.write([{"name": "post", "dtg": T0, "geom": geo.Point(2, 2)}],
                  ids=["n1"])
        rec.wal.crash()
        rec2 = LambdaStore.recover(root)
        assert {"n0", "n1"} <= set(rec2.hot._rows)
        rec2.close(), rec.flusher.close(), lam.flusher.close()

    def test_loss_window_bounded_under_sync_interval(self, tmp_path):
        """sync=interval: a hard kill loses AT MOST the writes
        acknowledged after the last sync — never a synced one, never a
        partial prefix out of order."""
        root, lam = _saved_lambda(tmp_path, sync="interval")
        for i in range(5):
            lam.write([{"name": "s", "dtg": T0, "geom": geo.Point(1, 1)}],
                      ids=[f"s{i}"])
        lam.wal.sync()  # the durable horizon
        for i in range(4):
            lam.write([{"name": "u", "dtg": T0, "geom": geo.Point(1, 1)}],
                      ids=[f"u{i}"])
        lam.wal.crash()  # kill -9: the unsynced window is lost
        rec = LambdaStore.recover(root)
        got = set(rec.hot._rows)
        assert {f"s{i}" for i in range(5)} <= got  # synced prefix intact
        assert not any(i in got for i in (f"u{i}" for i in range(4)))
        rec.close(), lam.flusher.close()

    def test_sync_off_still_replays_written_records(self, tmp_path):
        """sync=off writes through past the buffer threshold; a small
        buffered tail is the (unbounded) loss window, but nothing
        written is ever misparsed."""
        root, lam = _saved_lambda(tmp_path, sync="off")
        for i in range(3):
            lam.write([{"name": "o", "dtg": T0, "geom": geo.Point(1, 1)}],
                      ids=[f"o{i}"])
        lam.wal.close()  # clean close flushes; only a kill loses the tail
        rec = LambdaStore.recover(root)
        assert {f"o{i}" for i in range(3)} <= set(rec.hot._rows)
        rec.close(), lam.flusher.close()


# -- the crash-anywhere fuzz matrix ----------------------------------------


WAL_POINTS = (
    "stream.wal.append", "stream.wal.sync", "stream.wal.rotate",
)
FLUSH_POINTS = (
    "stream.flush.parse", "stream.flush.keys", "stream.flush.sort",
    "streaming.persist", "streaming.evict",
)


class TestCrashMatrix:
    """Crash + recover() vs a never-crashed twin applying the same
    ACKED ops: query results must match exactly (zero acknowledged-row
    loss under sync=always). The op at the crash boundary is allowed to
    be either side of the ack (it never returned)."""

    def _stream(self, rng, n_ops=14):
        ops = []
        hot_ids: list = []
        for i in range(n_ops):
            r = rng.random()
            if r < 0.55 or not hot_ids:
                k = int(rng.integers(1, 9))
                ids = []
                for j in range(k):
                    if rng.random() < 0.4:
                        ids.append(f"c{int(rng.integers(0, 300))}")
                    else:
                        ids.append(f"h{i}_{j}")
                hot_ids.extend(ids)
                ops.append(("write", {
                    "ids": ids,
                    "vals": [f"v{i}_{j}" for j in range(k)],
                    "xy": [(float(x), float(y)) for x, y in zip(
                        rng.uniform(-50, 50, k), rng.uniform(-50, 50, k))],
                }))
            elif r < 0.7:
                pick = [hot_ids[int(rng.integers(0, len(hot_ids)))]]
                ops.append(("delete", {"ids": pick}))
            elif r < 0.9:
                ops.append(("flush", {}))
            else:
                ops.append(("persist", {}))
        return ops

    @staticmethod
    def _apply(lam, op):
        kind, p = op
        if kind == "write":
            lam.write(
                [{"name": v, "dtg": T0 + 3, "geom": geo.Point(x, y)}
                 for v, (x, y) in zip(p["vals"], p["xy"])],
                ids=p["ids"],
            )
        elif kind == "delete":
            lam.delete(p["ids"])
        elif kind == "flush":
            lam.flush()
        else:
            lam.persist_hot()

    def _run_one(self, tmp_path, point, kind, after, seed):
        rng = np.random.default_rng(seed)
        ops = self._stream(rng)
        root, lam = _saved_lambda(tmp_path, n=300, seed=1)
        boundary = None
        exc = fault.InjectedCrash if kind == "crash" else OSError
        with fault.inject(point, kind=kind, after=after, times=None):
            try:
                for i, op in enumerate(ops):
                    self._apply(lam, op)
            except exc:
                boundary = ops[i]
                ops = ops[:i]
        lam.wal.crash()
        rec = LambdaStore.recover(root)
        # the never-crashed twin: same cold base, same acked ops
        oracle = LambdaStore(
            _cold(n=300, seed=1), "t",
            config=StreamConfig(chunk_rows=64, fold_rows=8),
        )
        for op in ops:
            self._apply(oracle, op)
        got, want = _results(rec), _results(oracle)
        if got != want and boundary is not None and boundary[0] in (
            "write", "delete"
        ):
            # ack boundary: the crashed op may have reached the log
            self._apply(oracle, boundary)
            want = _results(oracle)
        assert got == want, (point, kind, after)
        # store health stayed intact (crashes tear nothing)
        assert not [
            d for d in rec.cold.store_health.damage
            if d.type_name != "_wal"
        ]
        rec.close(), lam.flusher.close(), oracle.close()
        return boundary is not None

    @pytest.mark.parametrize("point", WAL_POINTS + FLUSH_POINTS)
    def test_crash_at_point_recovers_exactly(self, tmp_path, point):
        self._run_one(tmp_path, point, "crash", 0, seed=101)

    @pytest.mark.slow
    def test_full_matrix(self, tmp_path):
        """Every point x {crash, io_error} x several hit offsets x
        several seeds — the exhaustive version of the matrix above."""
        step = 0
        for seed in (7, 8):
            for point in WAL_POINTS + FLUSH_POINTS:
                for kind in ("crash", "io_error"):
                    for after in (0, 2, 5):
                        step += 1
                        sub = tmp_path / f"m{step}"
                        sub.mkdir()
                        self._run_one(sub, point, kind, after, seed=seed)

    def test_io_error_blip_never_needs_recovery(self, tmp_path):
        """A single transient io_error at every wal point is absorbed by
        with_retries — the write acks and nothing is lost."""
        for point in ("stream.wal.sync",):
            root, lam = _saved_lambda(tmp_path / point.replace(".", "_"))
            with fault.inject(point, kind="io_error", times=1):
                lam.write([{"name": "a", "dtg": T0,
                            "geom": geo.Point(1, 1)}], ids=["x0"])
            assert "x0" in lam.hot._rows
            lam.close()


# -- the seeded chaos harness ----------------------------------------------


def _chaos_run(tmp_path, seconds, seed, rate=0.03):
    """Closed-loop writer+reader+flusher under a seeded chaos schedule.
    Returns (oracle, attempted, root, spec) after a final hard kill."""
    root, lam = _saved_lambda(tmp_path, n=400, seed=3, fold_rows=64)
    test_lock = threading.Lock()
    oracle: dict = {}     # id -> (name, x, y): the ACKED state
    attempted: dict = {}  # id -> set of values whose ack never returned
    base = lam.cold.features("t")
    bn = np.asarray(base.columns["name"])
    bx, by = base.geom_column.x, base.geom_column.y
    for i, fid in enumerate(base.ids.tolist()):
        oracle[str(fid)] = (str(bn[i]), float(bx[i]), float(by[i]))
    stop = threading.Event()
    errors: list = []
    counter = [0]
    rng = np.random.default_rng(seed)

    def writer():
        known = list(oracle)
        while not stop.is_set():
            k = int(rng.integers(1, 12))
            ids, rows, vals, xys = [], [], [], []
            for _ in range(k):
                if rng.random() < 0.4:
                    fid = known[int(rng.integers(0, len(known)))]
                else:
                    counter[0] += 1
                    fid = f"w{counter[0]}"
                    known.append(fid)
                counter[0] += 1
                v = f"v{counter[0]}"
                x = float(rng.uniform(-50, 50))
                y = float(rng.uniform(-50, 50))
                ids.append(fid), vals.append(v), xys.append((x, y))
                rows.append({"name": v, "dtg": T0, "geom": geo.Point(x, y)})
            with test_lock:
                try:
                    lam.write(rows, ids=ids)
                except (fault.InjectedCrash, OSError):
                    for fid, v in zip(ids, vals):
                        attempted.setdefault(fid, set()).add(v)
                    continue
                for fid, v, (x, y) in zip(ids, vals, xys):
                    oracle[fid] = (v, x, y)
            time.sleep(0.001)

    def flusher():
        i = 0
        while not stop.is_set():
            time.sleep(0.05)
            i += 1
            try:
                if i % 8 == 0:
                    lam.checkpoint(root)
                else:
                    lam.flush()
            except (fault.InjectedCrash, OSError):
                continue
            except Exception as e:  # a real bug, not an injected fault
                errors.append(("flusher", repr(e)))
                stop.set()
                return

    def reader():
        boxes = [(-40, -40, 0, 0), (0, 0, 40, 40), (-25, -25, 25, 25)]
        j = 0
        while not stop.is_set():
            x0, y0, x1, y1 = boxes[j % len(boxes)]
            j += 1
            with test_lock:
                try:
                    got = sorted(
                        str(i) for i in lam.query(
                            f"bbox(geom, {x0}, {y0}, {x1}, {y1})"
                        ).ids.tolist()
                    )
                except (fault.InjectedCrash, OSError):
                    continue  # a cold-scan blip injected mid-query
                want = sorted(
                    fid for fid, (_, x, y) in oracle.items()
                    if x0 <= x <= x1 and y0 <= y <= y1
                )
                if got != want:
                    errors.append(("reader", got, want))
                    stop.set()
                    return
            time.sleep(0.003)

    threads = [
        threading.Thread(target=writer),
        threading.Thread(target=flusher),
        threading.Thread(target=reader),
    ]
    with fault.chaos(
        seed=seed, rate=rate,
        points="stream.*,streaming.*,persist.*",
        kinds=("io_error", "latency", "crash"),
        delay_s=0.002,
    ) as spec:
        for t in threads:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in threads:
            t.join()
    assert not errors, errors[:3]
    assert spec.fired > 0, "the chaos schedule never fired — dead harness"
    lam.wal.crash()
    lam.flusher.close()
    return oracle, attempted, root, spec


def _assert_chaos_invariants(oracle, attempted, root):
    rec = LambdaStore.recover(root)
    fc = rec.query("INCLUDE")
    got = dict(zip(
        (str(i) for i in fc.ids.tolist()),
        (str(v) for v in np.asarray(fc.columns["name"]).tolist()),
    ))
    # 1. ZERO acknowledged-row loss: every acked id is present, with the
    #    acked value (or a later attempted one the log captured pre-ack)
    missing = [fid for fid in oracle if fid not in got]
    assert not missing, f"acknowledged rows lost: {missing[:5]}"
    for fid, (v, _, _) in oracle.items():
        assert got[fid] == v or got[fid] in attempted.get(fid, ()), fid
    # 2. nothing invented: extras only from attempted (unacked) writes
    for fid, v in got.items():
        if fid not in oracle:
            assert v in attempted.get(fid, ()), fid
    # 3. store health intact (chaos crashes tear nothing durable)
    assert not [
        d for d in rec.cold.store_health.damage if d.type_name != "_wal"
    ]
    rec.close()


class TestChaos:
    def test_chaos_smoke(self, tmp_path):
        """Tier-1 confidence: a short fixed-seed chaos run (the slow
        soak below runs the full >= 60 s closed loop)."""
        oracle, attempted, root, spec = _chaos_run(
            tmp_path, seconds=3.0, seed=12061
        )
        _assert_chaos_invariants(oracle, attempted, root)

    @pytest.mark.slow
    def test_chaos_soak(self, tmp_path):
        """The acceptance run: >= 60 s closed-loop writer+reader under
        the seeded schedule, exactness throughout, zero acknowledged-row
        loss after a final hard kill. ``GEOMESA_TPU_CHAOS_SEED`` /
        ``GEOMESA_TPU_CHAOS_SECONDS`` override for soak farms."""
        seed = int(os.environ.get("GEOMESA_TPU_CHAOS_SEED", 90210))
        seconds = float(os.environ.get("GEOMESA_TPU_CHAOS_SECONDS", 60.0))
        oracle, attempted, root, spec = _chaos_run(
            tmp_path, seconds=seconds, seed=seed
        )
        assert spec.hits > 100  # the loop really exercised fault points
        _assert_chaos_invariants(oracle, attempted, root)
