"""Regression tests for review findings: hole-containment, EWKB SRID,
DWITHIN units, NOT-branch imprecision, packed-column array surgery."""

import struct

import numpy as np
import pytest

from geomesa_tpu import geometry as geo
from geomesa_tpu.filter import ecql
from geomesa_tpu.filter.extract import (
    extract_attribute_bounds,
    extract_geometries,
    extract_intervals,
)
from geomesa_tpu.filter.predicates import And, Cmp, During, Not, BBox


class TestContainsWithHoles:
    def test_hole_inside_contained_polygon_rejected(self):
        outer = geo.Polygon(
            [(0, 0), (10, 0), (10, 10), (0, 10)], holes=[[(4, 4), (6, 4), (6, 6), (4, 6)]]
        )
        inner = geo.Polygon([(2, 2), (8, 2), (8, 8), (2, 8)])
        assert not geo.contains(outer, inner)

    def test_hole_outside_contained_polygon_ok(self):
        outer = geo.Polygon(
            [(0, 0), (10, 0), (10, 10), (0, 10)], holes=[[(8.5, 8.5), (9, 8.5), (9, 9), (8.5, 9)]]
        )
        inner = geo.Polygon([(1, 1), (5, 1), (5, 5), (1, 5)])
        assert geo.contains(outer, inner)


class TestEwkb:
    def test_srid_flag_skips_payload(self):
        # EWKB little-endian point with SRID 4326
        data = struct.pack("<BIIdd", 1, 0x20000001, 4326, 1.5, 2.5)
        g = geo.from_wkb(data)
        assert isinstance(g, geo.Point) and g.x == 1.5 and g.y == 2.5

    def test_z_flag_rejected(self):
        data = struct.pack("<BIddd", 1, 0x80000001, 1.0, 2.0, 3.0)
        with pytest.raises(ValueError):
            geo.from_wkb(data)

    def test_iso_z_type_rejected(self):
        data = struct.pack("<BIddd", 1, 1001, 1.0, 2.0, 3.0)
        with pytest.raises(ValueError):
            geo.from_wkb(data)


class TestDwithinUnits:
    def test_two_word_units(self):
        f = ecql.parse("DWITHIN(geom, POINT (0 0), 10, statute miles)")
        assert abs(f.dist - 10 * 1609.34 / 111_320) < 1e-6

    def test_nautical_miles(self):
        f = ecql.parse("DWITHIN(geom, POINT (0 0), 1, nautical miles)")
        assert abs(f.dist - 1852.0 / 111_320) < 1e-9

    def test_unknown_units_rejected(self):
        with pytest.raises(ValueError):
            ecql.parse("DWITHIN(geom, POINT (0 0), 10, furlongs)")


class TestNotImprecision:
    def test_interval_not_branch_imprecise(self):
        f = And([During("d", 0, 100), Not(During("d", 50, 60))])
        fv = extract_intervals(f, "d")
        assert fv.values and not fv.precise

    def test_geometry_not_branch_imprecise(self):
        f = And([BBox("g", 0, 0, 10, 10), Not(BBox("g", 2, 2, 3, 3))])
        fv = extract_geometries(f, "g")
        assert fv.values and not fv.precise

    def test_attr_not_branch_imprecise(self):
        f = And([Cmp("a", ">", 5), Not(Cmp("a", "=", 7))])
        fv = extract_attribute_bounds(f, "a")
        assert fv.values and not fv.precise

    def test_unrelated_not_stays_precise(self):
        f = And([During("d", 0, 100), Not(Cmp("other", "=", 1))])
        fv = extract_intervals(f, "d")
        assert fv.values and fv.precise


class TestPackedColumnSurgery:
    def _col(self):
        geoms = [
            geo.Point(1, 2),
            geo.Polygon([(0, 0), (4, 0), (4, 4)], holes=[[(1, 1), (2, 1), (2, 2)]]),
            geo.MultiLineString([geo.LineString([(0, 0), (1, 1)]), geo.LineString([(2, 2), (3, 3), (4, 4)])]),
            geo.MultiPolygon([geo.Polygon([(0, 0), (1, 0), (1, 1)]), geo.Polygon([(5, 5), (6, 5), (6, 6)])]),
        ]
        return geo.PackedGeometryColumn.from_geometries(geoms), geoms

    def test_take_matches_object_path(self):
        col, geoms = self._col()
        for idx in ([2, 0], [3, 1, 2], [], [1], [0, 1, 2, 3]):
            sub = col.take(np.array(idx, dtype=np.int64))
            assert [g.wkt for g in sub.geometries()] == [geoms[i].wkt for i in idx]
            np.testing.assert_array_equal(sub.bboxes, col.bboxes[np.array(idx, dtype=np.int64)])

    def test_concat_roundtrip(self):
        col, geoms = self._col()
        both = geo.PackedGeometryColumn.concat([col, col.take(np.array([1, 3]))])
        expect = [g.wkt for g in geoms] + [geoms[1].wkt, geoms[3].wkt]
        assert [g.wkt for g in both.geometries()] == expect
