"""Regression tests for review findings: hole-containment, EWKB SRID,
DWITHIN units, NOT-branch imprecision, packed-column array surgery."""

import struct

import numpy as np
import pytest

from geomesa_tpu import geometry as geo
from geomesa_tpu.filter import ecql
from geomesa_tpu.filter.extract import (
    extract_attribute_bounds,
    extract_geometries,
    extract_intervals,
)
from geomesa_tpu.filter.predicates import And, Cmp, During, Not, BBox


class TestContainsWithHoles:
    def test_hole_inside_contained_polygon_rejected(self):
        outer = geo.Polygon(
            [(0, 0), (10, 0), (10, 10), (0, 10)], holes=[[(4, 4), (6, 4), (6, 6), (4, 6)]]
        )
        inner = geo.Polygon([(2, 2), (8, 2), (8, 8), (2, 8)])
        assert not geo.contains(outer, inner)

    def test_hole_outside_contained_polygon_ok(self):
        outer = geo.Polygon(
            [(0, 0), (10, 0), (10, 10), (0, 10)], holes=[[(8.5, 8.5), (9, 8.5), (9, 9), (8.5, 9)]]
        )
        inner = geo.Polygon([(1, 1), (5, 1), (5, 5), (1, 5)])
        assert geo.contains(outer, inner)


class TestEwkb:
    def test_srid_flag_skips_payload(self):
        # EWKB little-endian point with SRID 4326
        data = struct.pack("<BIIdd", 1, 0x20000001, 4326, 1.5, 2.5)
        g = geo.from_wkb(data)
        assert isinstance(g, geo.Point) and g.x == 1.5 and g.y == 2.5

    def test_z_flag_rejected(self):
        data = struct.pack("<BIddd", 1, 0x80000001, 1.0, 2.0, 3.0)
        with pytest.raises(ValueError):
            geo.from_wkb(data)

    def test_iso_z_type_rejected(self):
        data = struct.pack("<BIddd", 1, 1001, 1.0, 2.0, 3.0)
        with pytest.raises(ValueError):
            geo.from_wkb(data)


class TestDwithinUnits:
    def test_two_word_units(self):
        f = ecql.parse("DWITHIN(geom, POINT (0 0), 10, statute miles)")
        assert abs(f.dist - 10 * 1609.34 / 111_320) < 1e-6

    def test_nautical_miles(self):
        f = ecql.parse("DWITHIN(geom, POINT (0 0), 1, nautical miles)")
        assert abs(f.dist - 1852.0 / 111_320) < 1e-9

    def test_unknown_units_rejected(self):
        with pytest.raises(ValueError):
            ecql.parse("DWITHIN(geom, POINT (0 0), 10, furlongs)")


class TestNotImprecision:
    def test_interval_not_branch_imprecise(self):
        f = And([During("d", 0, 100), Not(During("d", 50, 60))])
        fv = extract_intervals(f, "d")
        assert fv.values and not fv.precise

    def test_geometry_not_branch_imprecise(self):
        f = And([BBox("g", 0, 0, 10, 10), Not(BBox("g", 2, 2, 3, 3))])
        fv = extract_geometries(f, "g")
        assert fv.values and not fv.precise

    def test_attr_not_branch_imprecise(self):
        f = And([Cmp("a", ">", 5), Not(Cmp("a", "=", 7))])
        fv = extract_attribute_bounds(f, "a")
        assert fv.values and not fv.precise

    def test_unrelated_not_stays_precise(self):
        f = And([During("d", 0, 100), Not(Cmp("other", "=", 1))])
        fv = extract_intervals(f, "d")
        assert fv.values and fv.precise


class TestPackedColumnSurgery:
    def _col(self):
        geoms = [
            geo.Point(1, 2),
            geo.Polygon([(0, 0), (4, 0), (4, 4)], holes=[[(1, 1), (2, 1), (2, 2)]]),
            geo.MultiLineString([geo.LineString([(0, 0), (1, 1)]), geo.LineString([(2, 2), (3, 3), (4, 4)])]),
            geo.MultiPolygon([geo.Polygon([(0, 0), (1, 0), (1, 1)]), geo.Polygon([(5, 5), (6, 5), (6, 6)])]),
        ]
        return geo.PackedGeometryColumn.from_geometries(geoms), geoms

    def test_take_matches_object_path(self):
        col, geoms = self._col()
        for idx in ([2, 0], [3, 1, 2], [], [1], [0, 1, 2, 3]):
            sub = col.take(np.array(idx, dtype=np.int64))
            assert [g.wkt for g in sub.geometries()] == [geoms[i].wkt for i in idx]
            np.testing.assert_array_equal(sub.bboxes, col.bboxes[np.array(idx, dtype=np.int64)])

    def test_concat_roundtrip(self):
        col, geoms = self._col()
        both = geo.PackedGeometryColumn.concat([col, col.take(np.array([1, 3]))])
        expect = [g.wkt for g in geoms] + [geoms[1].wkt, geoms[3].wkt]
        assert [g.wkt for g in both.geometries()] == expect


class TestAdvisorRound4Fixes:
    """Regressions for ADVICE.md round-4 findings."""

    def test_st_relate_1dim_sets_meet_in_points(self):
        # overlapping boxes: boundaries cross at two POINTS (JTS 212101212,
        # not the generic min-dim 212111212)
        from geomesa_tpu import geometry as geo
        from geomesa_tpu.sql.functions import st_relate, st_relatebool

        a, b = geo.box(0, 0, 2, 2), geo.box(1, 1, 3, 3)
        assert st_relate(a, b) == "212101212"
        # line crossing a polygon: I(L) x B(P) is points -> 101FF0212
        line = geo.from_wkt("LINESTRING(-1 1, 3 1)")
        assert st_relate(line, geo.box(0, 0, 2, 2)) == "101FF0212"
        # edge-adjacent squares share a collinear boundary run: dim 1 kept
        assert st_relate(geo.box(0, 0, 1, 1), geo.box(1, 0, 2, 1)) == "FF2F11212"
        # digit-bearing pattern matching now agrees with JTS
        assert st_relatebool(a, b, "T*T***T*T")
        assert not st_relatebool(a, b, "****1****")  # BB is points, not a run
        assert st_relatebool(a, b, "****0****")

    def test_modify_features_nan_nulls_float_attr(self):
        import numpy as np

        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType

        sft = FeatureType.from_spec("t", "v:Double,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("t", FeatureCollection.from_columns(
            sft, np.arange(4), {"v": np.arange(4.0), "geom": (np.zeros(4), np.zeros(4))}
        ))
        n = ds.modify_features("t", {"v": float("nan")}, "IN ('1', '2')")
        assert n == 2
        out = ds.query("t", "v IS NULL")
        assert sorted(np.asarray(out.ids).tolist()) == [1, 2]
        # lossy casts still refused on int columns
        sft2 = FeatureType.from_spec("t2", "k:Integer,*geom:Point:srid=4326")
        ds.create_schema(sft2)
        ds.write("t2", FeatureCollection.from_columns(
            sft2, np.arange(2), {"k": np.arange(2, dtype=np.int32),
                                 "geom": (np.zeros(2), np.zeros(2))}
        ))
        import pytest

        with pytest.raises(TypeError):
            ds.modify_features("t2", {"k": 1.5})

    def test_geojson_synth_ids_avoid_explicit_collisions(self):
        import json as _json

        from geomesa_tpu.io.geojson import read_geojson

        fc = {"type": "FeatureCollection", "features": [
            {"type": "Feature", "id": 3,
             "geometry": {"type": "Point", "coordinates": [0, 0]},
             "properties": {"a": 1}},
            {"type": "Feature",
             "geometry": {"type": "Point", "coordinates": [1, 1]},
             "properties": {"a": 2}},
            {"type": "Feature",
             "geometry": {"type": "Point", "coordinates": [2, 2]},
             "properties": {"a": 3}},
        ]}
        out = read_geojson(_json.dumps(fc), "g")
        ids = list(out.ids)
        assert ids[0] == "3"
        assert len(set(ids)) == 3  # no collision between synth + explicit

    def test_st_distancesphere_uses_nearest_points(self):
        from geomesa_tpu import geometry as geo
        from geomesa_tpu.process.knn import haversine_m
        from geomesa_tpu.sql.functions import st_distancesphere

        # long line whose NEAR end is 1 degree from the point; the
        # representative-point (midpoint/centroid) distance would be ~25x
        line = geo.from_wkt("LINESTRING(10 0, 60 0)")
        p = geo.Point(9.0, 0.0)
        d = st_distancesphere(line, p)
        expect = float(haversine_m(10.0, 0.0, 9.0, 0.0))
        assert abs(d - expect) < 1.0
        # intersecting geometries are at distance 0
        assert st_distancesphere(line, geo.Point(30.0, 0.0)) == 0.0

    def test_upsert_rollback_on_write_failure(self):
        import numpy as np
        import pytest

        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType

        sft = FeatureType.from_spec("t", "v:Integer,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("t", FeatureCollection.from_columns(
            sft, np.arange(3), {"v": np.arange(3, dtype=np.int32),
                                "geom": (np.zeros(3), np.zeros(3))}
        ))
        repl = FeatureCollection.from_columns(
            sft, np.array([1]), {"v": np.array([9], dtype=np.int32),
                                 "geom": (np.ones(1), np.ones(1))}
        )
        # force write() to fail AFTER the delete (validation passes)
        orig_write = ds.write
        calls = {"n": 0}

        def failing_write(type_name, features, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise MemoryError("simulated device OOM")
            return orig_write(type_name, features, **kw)

        ds.write = failing_write
        with pytest.raises(MemoryError):
            ds.upsert("t", repl)
        ds.write = orig_write
        # the replaced row was restored, not lost
        out = ds.query("t", "IN ('1')")
        assert len(out) == 1
        assert int(np.asarray(out.columns["v"])[0]) == 1


    def test_st_distancesphere_parallel_overlap_ties(self):
        from geomesa_tpu import geometry as geo
        from geomesa_tpu.process.knn import haversine_m
        from geomesa_tpu.sql.functions import st_distancesphere

        a = geo.from_wkt("LINESTRING(0 0, 10 0)")
        b = geo.from_wkt("LINESTRING(5 1, 15 1)")
        # every point of the 5-unit overlap minimizes: pair must be
        # consistent (~1 degree apart), not ends of different ties
        d = st_distancesphere(a, b)
        expect = float(haversine_m(5.0, 0.0, 5.0, 1.0))
        assert abs(d - expect) / expect < 0.01

    def test_modify_features_none_nulls_float_attr(self):
        import numpy as np

        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType

        sft = FeatureType.from_spec("tn", "v:Double,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("tn", FeatureCollection.from_columns(
            sft, np.arange(2), {"v": np.arange(2.0),
                                "geom": (np.zeros(2), np.zeros(2))}
        ))
        assert ds.modify_features("tn", {"v": None}, "IN ('0')") == 1
        assert len(ds.query("tn", "v IS NULL")) == 1
