"""Multi-device sharded scan: result equality with the single-device path.

Runs on the forced 8-device CPU mesh (conftest.py), mirroring the reference
TestGeoMesaDataStore strategy: the full planner + distributed scan stack
with zero infra.
"""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.parallel import make_mesh
from geomesa_tpu.sft import FeatureType

SPEC = "name:String,age:Int,dtg:Date,*geom:Point:srid=4326"


def _points(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    t = t0 + rng.integers(0, 45 * 86400_000, n)
    return x, y, t


def _store(mesh=None, n=4000, tile=64):
    sft = FeatureType.from_spec("pts", SPEC)
    ds = DataStore(tile=tile, mesh=mesh)
    ds.create_schema(sft)
    x, y, t = _points(n)
    fc = FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(n)],
        {
            "name": np.array([f"n{i % 17}" for i in range(n)]),
            "age": np.arange(n) % 90,
            "dtg": t,
            "geom": (x, y),
        },
    )
    ds.write("pts", fc)
    return ds


QUERIES = [
    "bbox(geom, -20, -10, 40, 35) AND dtg DURING 2024-01-03T00:00:00Z/2024-01-20T12:00:00Z",
    "bbox(geom, -180, -90, 180, 90) AND dtg DURING 2024-01-01T00:00:00Z/2024-02-15T00:00:00Z",
    "bbox(geom, 10, 10, 11, 11)",
    "bbox(geom, -150, -80, 150, 80) AND age < 30",
    "bbox(geom, -20, -10, 40, 35) AND dtg DURING 2024-01-03T00:00:00Z/2024-01-20T12:00:00Z AND name = 'n3'",
]


@pytest.fixture(scope="module")
def stores():
    return _store(), _store(make_mesh(8))


@pytest.mark.parametrize("q", QUERIES)
def test_distributed_matches_single(stores, q):
    single, dist = stores
    a = sorted(single.query("pts", q).ids.tolist())
    b = sorted(dist.query("pts", q).ids.tolist())
    assert a == b
    assert len(a) > 0  # queries chosen to hit


def test_distributed_matches_brute_force(stores):
    single, dist = stores
    q = QUERIES[0]
    from geomesa_tpu.filter import ecql

    f = ecql.parse(q)
    fc = dist.features("pts")
    mask = np.asarray(f.evaluate(fc.batch))
    expect = sorted(fc.ids[mask].tolist())
    got = sorted(dist.query("pts", q).ids.tolist())
    assert got == expect


def test_distributed_count(stores):
    single, dist = stores
    # loose count >= exact hits; equal here because the bbox test is precise
    # for points up to f32 widening
    q = "bbox(geom, -20, -10, 40, 35)"
    assert dist.count("pts", q) == single.count("pts", q)


def test_distributed_empty_result(stores):
    _, dist = stores
    out = dist.query("pts", "bbox(geom, 10.00001, 10.00001, 10.00002, 10.00002) AND dtg DURING 2030-01-01T00:00:00Z/2030-01-02T00:00:00Z")
    assert len(out) == 0


def test_mesh_sizes():
    # distributed path works for mesh sizes that do not divide tile counts
    for d in (2, 3, 5):
        ds = _store(make_mesh(d), n=1000, tile=32)
        single = _store(n=1000, tile=32)
        for q in QUERIES[:2]:
            assert sorted(ds.query("pts", q).ids.tolist()) == sorted(
                single.query("pts", q).ids.tolist()
            )


def test_distributed_certainty_vector(stores):
    """The mesh table returns the same exactness tier as the single-chip
    table: identical ordinals AND identical certain flags (VERDICT r3 #1)."""
    from geomesa_tpu.filter import ecql

    single, dist = stores
    for q in QUERIES[:3]:
        f = ecql.parse(q)
        idx = single.indexes("pts")[0]
        cfg = idx.scan_config(f)
        if cfg is None:
            continue
        o1, c1 = single.table("pts", "z3").scan(cfg)
        o2, c2 = dist.table("pts", "z3").scan(cfg)
        assert o1.tolist() == o2.tolist()
        assert c1.tolist() == c2.tolist()
    # the tier is live: at least one query has certain rows
    f = ecql.parse(QUERIES[0])
    cfg = single.indexes("pts")[0].scan_config(f)
    _, c = dist.table("pts", "z3").scan(cfg)
    assert c.any()


def test_distributed_zero_recompiles(stores):
    """After one warmup pass, a mixed query batch triggers NO new XLA
    compiles on the mesh path (the round-2 cap-retry recompile loop is
    gone)."""
    import logging

    _, dist = stores
    import jax

    mix = QUERIES * 4  # 20 queries
    for q in mix:  # warmup: compile every (bucket, flags) variant once
        dist.query("pts", q)
    jax.config.update("jax_log_compiles", True)
    records = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r.getMessage())
    loggers = [logging.getLogger(n) for n in ("jax._src.dispatch", "jax._src.interpreters.pxla", "jax._src.compiler")]
    for lg in loggers:
        lg.addHandler(handler)
        lg.setLevel(logging.DEBUG)
    try:
        for q in mix:
            dist.query("pts", q)
    finally:
        jax.config.update("jax_log_compiles", False)
        for lg in loggers:
            lg.removeHandler(handler)
    compiles = [m for m in records if "Compiling" in m]
    assert compiles == [], f"unexpected recompiles: {compiles}"


def test_distributed_density_and_bounds(stores):
    single, dist = stores
    q = QUERIES[0]
    g1 = single.density("pts", q, envelope=(-20, -10, 40, 35), width=32, height=16)
    g2 = dist.density("pts", q, envelope=(-20, -10, 40, 35), width=32, height=16)
    assert np.array_equal(g1, g2)
    assert g1.sum() > 0
    b1 = single.bounds("pts", q, estimate=True)
    b2 = dist.bounds("pts", q, estimate=True)
    assert b1 == b2 and b1 is not None


def test_mesh_delta_tier():
    """Mesh stores absorb small writes in the host delta tier (no forced
    per-write compaction) and still answer exactly."""
    from geomesa_tpu.storage.delta import TieredTable

    mesh = make_mesh(4)
    single, dist = _store(n=2000), _store(mesh, n=2000)
    sft = single.get_schema("pts")
    x, y, t = _points(300, seed=9)
    fc = FeatureCollection.from_columns(
        sft,
        [f"extra{i}" for i in range(300)],
        {
            "name": np.array([f"n{i % 17}" for i in range(300)]),
            "age": np.arange(300) % 90,
            "dtg": t,
            "geom": (x, y),
        },
    )
    single.write("pts", fc)
    dist.write("pts", fc)
    # the second write stayed in the delta (below the compaction threshold)
    assert isinstance(dist.table("pts", "z3"), TieredTable)
    for q in QUERIES[:3]:
        assert sorted(single.query("pts", q).ids.tolist()) == sorted(
            dist.query("pts", q).ids.tolist()
        )
    assert dist.count("pts", "bbox(geom, -20, -10, 40, 35)") == single.count(
        "pts", "bbox(geom, -20, -10, 40, 35)"
    )


def test_extent_geometries_distributed():
    # polygons via XZ2/XZ3 on the mesh
    sft = FeatureType.from_spec("polys", "name:String,dtg:Date,*geom:Polygon:srid=4326")
    rng = np.random.default_rng(3)
    rows = []
    for i in range(300):
        cx, cy = rng.uniform(-170, 170), rng.uniform(-80, 80)
        w, h = rng.uniform(0.1, 4, 2)
        rows.append(
            {
                "__id__": str(i),
                "name": f"p{i}",
                "dtg": int(np.datetime64("2024-01-05", "ms").astype(np.int64) + i * 3600_000),
                "geom": f"POLYGON(({cx} {cy}, {cx + w} {cy}, {cx + w} {cy + h}, {cx} {cy + h}, {cx} {cy}))",
            }
        )
    q = "bbox(geom, -30, -30, 30, 30)"
    out = {}
    for mesh in (None, make_mesh(4)):
        ds = DataStore(tile=32, mesh=mesh)
        ds.create_schema(sft)
        ds.write("polys", rows)
        out[mesh is None] = sorted(ds.query("polys", q).ids.tolist())
    assert out[True] == out[False]
    assert len(out[True]) > 0


def test_union_plans_on_mesh():
    """Cross-kind OR union plans execute per-branch mesh scans."""
    sft = FeatureType.from_spec(
        "um", "name:String:index=true,dtg:Date,*geom:Point:srid=4326"
    )
    rng = np.random.default_rng(12)
    n = 3000
    t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
    fc = FeatureCollection.from_columns(
        sft, [str(i) for i in range(n)],
        {"name": np.array([f"n{i % 11}" for i in range(n)]),
         "dtg": t0 + rng.integers(0, 30 * 86400_000, n),
         "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n))},
    )
    q = "bbox(geom, -20, -15, 10, 10) OR name = 'n4'"
    out = {}
    for mesh in (None, make_mesh(8)):
        ds = DataStore(mesh=mesh)
        ds.create_schema(sft)
        ds.write("um", fc)
        plan = ds.planner.plan("um", q)
        assert plan.union is not None
        out[mesh is None] = sorted(ds.query("um", q).ids.tolist())
    assert out[True] == out[False] and len(out[True]) > 0


def test_timeout_on_mesh():
    from geomesa_tpu.planning.errors import QueryTimeout
    from geomesa_tpu.planning.hints import QueryHints

    ds = _store(make_mesh(4), n=2000)
    q = QUERIES[0]
    with pytest.raises(QueryTimeout):
        ds.query("pts", q, hints=QueryHints(timeout=1e-9))
    assert len(ds.query("pts", q, hints=QueryHints(timeout=60.0))) > 0


def test_mesh_store_persist_roundtrip(tmp_path):
    """Mesh stores persist and reload (tables rebuilt sharded)."""
    from geomesa_tpu.storage import persist

    mesh = make_mesh(4)
    ds = _store(mesh, n=2500)
    root = str(tmp_path / "cat")
    persist.save(ds, root)
    back = persist.load(root, mesh=mesh)
    from geomesa_tpu.parallel import DistributedIndexTable

    assert isinstance(back._tables[("pts", "z3")], DistributedIndexTable)
    for q in QUERIES[:3]:
        assert sorted(back.query("pts", q).ids.tolist()) == sorted(
            ds.query("pts", q).ids.tolist()
        )


def test_multihost_mesh_layout_and_equality():
    """make_multihost_mesh: host-major 1-D ordering; a store sharded over
    the 2x4 'multi-host' mesh answers identically to single-device."""
    from geomesa_tpu.parallel import make_multihost_mesh

    mesh = make_multihost_mesh(hosts=2, devices_per_host=4)
    assert mesh.devices.shape == (8,)
    import jax
    assert list(mesh.devices) == jax.devices()[:8]  # one process: sliced

    # the grouping logic itself, against stub multi-process devices
    from collections import namedtuple

    from geomesa_tpu.parallel.mesh import _host_major

    D = namedtuple("D", "name process_index")
    stub = [D(f"d{h}_{i}", h) for i in (0, 1, 2, 3) for h in (1, 0)]
    got = _host_major(stub, hosts=2, devices_per_host=3)
    assert [d.name for d in got] == [
        "d0_0", "d0_1", "d0_2", "d1_0", "d1_1", "d1_2"
    ]
    with pytest.raises(ValueError, match="has 4 devices, need 5"):
        _host_major(stub, hosts=2, devices_per_host=5)

    sft = FeatureType.from_spec("mh", "dtg:Date,*geom:Point:srid=4326")
    rng = np.random.default_rng(8)
    n = 4000
    t0 = int(np.datetime64("2024-02-01", "ms").astype(np.int64))
    fc_cols = {
        "dtg": t0 + rng.integers(0, 86400_000 * 10, n),
        "geom": (rng.uniform(-90, 90, n), rng.uniform(-45, 45, n)),
    }
    q = ("bbox(geom, -20, -20, 20, 20) AND dtg DURING "
         "2024-02-02T00:00:00Z/2024-02-06T00:00:00Z")
    out = {}
    for mesh_ in (None, mesh):
        ds = DataStore(tile=32, mesh=mesh_)
        ds.create_schema(sft)
        ds.write("mh", FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)], dict(fc_cols)))
        out[mesh_ is None] = sorted(ds.query("mh", q).ids.tolist())
    assert out[True] == out[False] and len(out[True]) > 0

    with pytest.raises(ValueError):
        make_multihost_mesh(hosts=3)  # 8 devices don't divide over 3
