"""Multi-device sharded scan: result equality with the single-device path.

Runs on the forced 8-device CPU mesh (conftest.py), mirroring the reference
TestGeoMesaDataStore strategy: the full planner + distributed scan stack
with zero infra.
"""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.parallel import make_mesh
from geomesa_tpu.sft import FeatureType

SPEC = "name:String,age:Int,dtg:Date,*geom:Point:srid=4326"


def _points(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    t = t0 + rng.integers(0, 45 * 86400_000, n)
    return x, y, t


def _store(mesh=None, n=4000, tile=64):
    sft = FeatureType.from_spec("pts", SPEC)
    ds = DataStore(tile=tile, mesh=mesh)
    ds.create_schema(sft)
    x, y, t = _points(n)
    fc = FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(n)],
        {
            "name": np.array([f"n{i % 17}" for i in range(n)]),
            "age": np.arange(n) % 90,
            "dtg": t,
            "geom": (x, y),
        },
    )
    ds.write("pts", fc)
    return ds


QUERIES = [
    "bbox(geom, -20, -10, 40, 35) AND dtg DURING 2024-01-03T00:00:00Z/2024-01-20T12:00:00Z",
    "bbox(geom, -180, -90, 180, 90) AND dtg DURING 2024-01-01T00:00:00Z/2024-02-15T00:00:00Z",
    "bbox(geom, 10, 10, 11, 11)",
    "bbox(geom, -150, -80, 150, 80) AND age < 30",
    "bbox(geom, -20, -10, 40, 35) AND dtg DURING 2024-01-03T00:00:00Z/2024-01-20T12:00:00Z AND name = 'n3'",
]


@pytest.fixture(scope="module")
def stores():
    return _store(), _store(make_mesh(8))


@pytest.mark.parametrize("q", QUERIES)
def test_distributed_matches_single(stores, q):
    single, dist = stores
    a = sorted(single.query("pts", q).ids.tolist())
    b = sorted(dist.query("pts", q).ids.tolist())
    assert a == b
    assert len(a) > 0  # queries chosen to hit


def test_distributed_matches_brute_force(stores):
    single, dist = stores
    q = QUERIES[0]
    from geomesa_tpu.filter import ecql

    f = ecql.parse(q)
    fc = dist.features("pts")
    mask = np.asarray(f.evaluate(fc.batch))
    expect = sorted(fc.ids[mask].tolist())
    got = sorted(dist.query("pts", q).ids.tolist())
    assert got == expect


def test_distributed_count(stores):
    single, dist = stores
    # loose count >= exact hits; equal here because the bbox test is precise
    # for points up to f32 widening
    q = "bbox(geom, -20, -10, 40, 35)"
    assert dist.count("pts", q) == single.count("pts", q)


def test_distributed_empty_result(stores):
    _, dist = stores
    out = dist.query("pts", "bbox(geom, 10.00001, 10.00001, 10.00002, 10.00002) AND dtg DURING 2030-01-01T00:00:00Z/2030-01-02T00:00:00Z")
    assert len(out) == 0


def test_mesh_sizes():
    # distributed path works for mesh sizes that do not divide tile counts
    for d in (2, 3, 5):
        ds = _store(make_mesh(d), n=1000, tile=32)
        single = _store(n=1000, tile=32)
        for q in QUERIES[:2]:
            assert sorted(ds.query("pts", q).ids.tolist()) == sorted(
                single.query("pts", q).ids.tolist()
            )


def test_extent_geometries_distributed():
    # polygons via XZ2/XZ3 on the mesh
    sft = FeatureType.from_spec("polys", "name:String,dtg:Date,*geom:Polygon:srid=4326")
    rng = np.random.default_rng(3)
    rows = []
    for i in range(300):
        cx, cy = rng.uniform(-170, 170), rng.uniform(-80, 80)
        w, h = rng.uniform(0.1, 4, 2)
        rows.append(
            {
                "__id__": str(i),
                "name": f"p{i}",
                "dtg": int(np.datetime64("2024-01-05", "ms").astype(np.int64) + i * 3600_000),
                "geom": f"POLYGON(({cx} {cy}, {cx + w} {cy}, {cx + w} {cy + h}, {cx} {cy + h}, {cx} {cy}))",
            }
        )
    q = "bbox(geom, -30, -30, 30, 30)"
    out = {}
    for mesh in (None, make_mesh(4)):
        ds = DataStore(tile=32, mesh=mesh)
        ds.create_schema(sft)
        ds.write("polys", rows)
        out[mesh is None] = sorted(ds.query("polys", q).ids.tolist())
    assert out[True] == out[False]
    assert len(out[True]) > 0
