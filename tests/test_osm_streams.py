"""OSM converter + feature change-stream topology (VERDICT r4 missing
#6/#7)."""

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.io.osm import read_osm
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.streaming import FeatureStream, StreamingFeatureCache

OSM = """<?xml version="1.0"?>
<osm version="0.6">
  <node id="1" lat="48.1" lon="11.5"><tag k="name" v="Stop A"/><tag k="highway" v="bus_stop"/></node>
  <node id="2" lat="48.2" lon="11.6"/>
  <node id="3" lat="48.3" lon="11.7"/>
  <node id="4" lat="48.1" lon="11.8"><tag k="amenity" v="cafe"/><tag k="name" v="Cafe B"/></node>
  <node id="10" lat="48.0" lon="11.0"/>
  <node id="11" lat="48.0" lon="11.1"/>
  <node id="12" lat="48.1" lon="11.1"/>
  <node id="13" lat="48.1" lon="11.0"/>
  <way id="100"><nd ref="2"/><nd ref="3"/><tag k="highway" v="residential"/><tag k="name" v="Main St"/></way>
  <way id="200"><nd ref="10"/><nd ref="11"/><nd ref="12"/><nd ref="13"/><nd ref="10"/><tag k="building" v="yes"/></way>
</osm>
"""


class TestOsm:
    def test_nodes_tagged_only(self):
        fc = read_osm(OSM, kind="nodes")
        assert sorted(fc.ids.tolist()) == ["1", "4"]
        i = fc.ids.tolist().index("1")
        assert fc.columns["highway"][i] == "bus_stop"
        assert fc.columns["name"][i] == "Stop A"
        assert abs(float(fc.geom_column.x[i]) - 11.5) < 1e-9

    def test_nodes_all(self):
        fc = read_osm(OSM, kind="nodes", tagged_only=False)
        assert len(fc) == 8

    def test_ways_line_and_area(self):
        fc = read_osm(OSM, kind="ways")
        assert sorted(fc.ids.tolist()) == ["100", "200"]
        geoms = {fid: g for fid, g in zip(fc.ids.tolist(), fc.geometries())}
        assert isinstance(geoms["100"], geo.LineString)
        assert isinstance(geoms["200"], geo.Polygon)  # closed + building
        assert fc.columns["name"][fc.ids.tolist().index("100")] == "Main St"

    def test_ingest_roundtrip(self):
        from geomesa_tpu.datastore import DataStore

        fc = read_osm(OSM, kind="nodes", type_name="stops")
        ds = DataStore()
        ds.create_schema(fc.sft)
        ds.write("stops", fc)
        out = ds.query("stops", "highway = 'bus_stop'")
        assert out.ids.tolist() == ["1"]


class TestFeatureStream:
    def _row(self, x, y, kind):
        return {"kind": kind, "geom": geo.Point(x, y)}

    def test_filter_map_to_cache(self):
        sft = FeatureType.from_spec("ev", "kind:String,*geom:Point:srid=4326")
        src = StreamingFeatureCache(sft)
        src.upsert([self._row(1, 1, "ship"), self._row(2, 2, "plane")],
                   ids=["a", "b"])
        derived = StreamingFeatureCache(sft)
        FeatureStream.wrap(src).filter(
            lambda r: r["kind"] == "ship"
        ).map(lambda r: {**r, "kind": r["kind"].upper()}).to(derived)
        # replay of existing state
        assert len(derived) == 1
        assert derived.snapshot(["a"]).columns["kind"][0] == "SHIP"
        # future events flow through
        src.upsert([self._row(3, 3, "ship")], ids=["c"])
        src.upsert([self._row(4, 4, "buoy")], ids=["d"])
        assert len(derived) == 2 and len(src) == 4
        # an update that stops matching drops the derived row
        src.upsert([self._row(3, 3, "wreck")], ids=["c"])
        assert len(derived) == 1
        # deletes and expiry propagate
        src.delete(["a"])
        assert len(derived) == 0

    def test_to_callable_sink(self):
        sft = FeatureType.from_spec("ev", "kind:String,*geom:Point:srid=4326")
        src = StreamingFeatureCache(sft)
        events = []
        FeatureStream.wrap(src).to(lambda a, fid, row: events.append((a, fid)))
        src.upsert([self._row(0, 0, "x")], ids=["k"])
        src.delete(["k"])
        assert events == [("upsert", "k"), ("delete", "k")]

    def test_to_lambda_store_sink(self):
        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.streaming import LambdaStore

        sft = FeatureType.from_spec("ev", "kind:String,*geom:Point:srid=4326")
        cold = DataStore()
        cold.create_schema(sft)
        lam = LambdaStore(cold, "ev")
        src = StreamingFeatureCache(sft)
        FeatureStream.wrap(src).filter(lambda r: r["kind"] == "ship").to(lam)
        src.upsert([self._row(1, 1, "ship"), self._row(2, 2, "plane")],
                   ids=["a", "b"])
        assert lam.count() == 1
        src.delete(["a"])  # drops the hot copy
        assert lam.count() == 0

    def test_bad_sink_raises(self):
        import pytest

        sft = FeatureType.from_spec("ev", "kind:String,*geom:Point:srid=4326")
        src = StreamingFeatureCache(sft)
        with pytest.raises(TypeError, match="sink"):
            FeatureStream.wrap(src).to(object())
