"""CRS handling: 4326 <-> 3857 reprojection at the query boundary.

Reference: reprojection hints (geomesa-index-api/.../planning/
QueryPlanner.scala:292) and the BBOX CRS argument through the filter
stack. VERDICT r4 missing #1: BBOX CRS args must reproject or raise —
never silently evaluate in the wrong CRS.
"""

import numpy as np
import pytest

from geomesa_tpu import crs, geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter import ecql
from geomesa_tpu.planning.hints import QueryHints
from geomesa_tpu.sft import FeatureType


def _point_store(n=2000, seed=3):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-179, 179, n)
    y = rng.uniform(-84, 84, n)
    sft = FeatureType.from_spec("pts", "*geom:Point:srid=4326")
    ds = DataStore()
    ds.create_schema(sft)
    ds.write("pts", FeatureCollection.from_columns(
        sft, np.arange(n), {"geom": (x, y)}
    ))
    return ds, x, y


class TestTransforms:
    def test_roundtrip_3857(self):
        rng = np.random.default_rng(1)
        lon = rng.uniform(-180, 180, 1000)
        lat = rng.uniform(-85, 85, 1000)
        x, y = crs.from_4326(lon, lat, "EPSG:3857")
        lon2, lat2 = crs.to_4326(x, y, "EPSG:3857")
        np.testing.assert_allclose(lon2, lon, atol=1e-9)
        np.testing.assert_allclose(lat2, lat, atol=1e-9)

    def test_known_point(self):
        # (10E, 45N) in web mercator — the standard published value
        x, y = crs.from_4326(10.0, 45.0, "EPSG:3857")
        assert abs(float(x) - 1113194.9079327357) < 1e-3
        assert abs(float(y) - 5621521.486192066) < 1e-3

    def test_aliases_and_unsupported(self):
        for a in ("EPSG:4326", "CRS:84", "wgs84", "4326"):
            assert crs.normalize_crs(a) == "EPSG:4326"
        for a in ("EPSG:3857", "900913", "epsg:3857"):
            assert crs.normalize_crs(a) == "EPSG:3857"
        with pytest.raises(ValueError):
            crs.normalize_crs("EPSG:32633")

    def test_geometry_transform_polygon(self):
        g = geo.Polygon([(0, 0), (10, 0), (10, 10), (0, 10)],
                        holes=[[(2, 2), (3, 2), (3, 3), (2, 3)]])
        m = crs.transform_geometry(g, "EPSG:4326", "EPSG:3857")
        back = crs.transform_geometry(m, "EPSG:3857", "EPSG:4326")
        assert np.allclose(np.asarray(back.shell), np.asarray(g.shell), atol=1e-9)
        assert np.allclose(np.asarray(back.holes[0]), np.asarray(g.holes[0]), atol=1e-9)


class TestQueryBoundary:
    def test_bbox_3857_equals_4326_query(self):
        ds, x, y = _point_store()
        q4326 = "bbox(geom, 10, 40, 30, 55)"
        x0, y0 = crs.from_4326(10.0, 40.0, "EPSG:3857")
        x1, y1 = crs.from_4326(30.0, 55.0, "EPSG:3857")
        q3857 = f"bbox(geom, {float(x0)!r}, {float(y0)!r}, {float(x1)!r}, {float(y1)!r}, 'EPSG:3857')"
        a = ds.query("pts", q4326)
        b = ds.query("pts", q3857)
        assert sorted(np.asarray(a.ids).tolist()) == sorted(np.asarray(b.ids).tolist())
        assert len(a) == int(((x >= 10) & (x <= 30) & (y >= 40) & (y <= 55)).sum())

    def test_bbox_unsupported_crs_raises(self):
        with pytest.raises(ValueError, match="unsupported CRS"):
            ecql.parse("bbox(geom, 0, 0, 1, 1, 'EPSG:32633')")

    def test_reproject_hint_points(self):
        ds, x, y = _point_store()
        out = ds.query("pts", "bbox(geom, -20, -20, 20, 20)",
                       hints=QueryHints(reproject="EPSG:3857"))
        base = ds.query("pts", "bbox(geom, -20, -20, 20, 20)")
        assert len(out) == len(base)
        gx, gy = out.geom_column.x, out.geom_column.y
        ex, ey = crs.from_4326(base.geom_column.x, base.geom_column.y, "EPSG:3857")
        np.testing.assert_allclose(gx, ex)
        np.testing.assert_allclose(gy, ey)

    def test_reproject_hint_unsupported_raises(self):
        ds, _, _ = _point_store(n=50)
        with pytest.raises(ValueError, match="unsupported CRS"):
            ds.query("pts", "INCLUDE", hints=QueryHints(reproject="EPSG:2154"))

    def test_reproject_extent_collection(self):
        x0 = np.array([0.0, 10.0]); y0 = np.array([0.0, 40.0])
        col = geo.PackedGeometryColumn.from_boxes(x0, y0, x0 + 1, y0 + 1)
        sft = FeatureType.from_spec("bld", "*geom:Polygon:srid=4326")
        fc = FeatureCollection.from_columns(sft, np.arange(2), {"geom": col})
        out = crs.reproject_collection(fc, "EPSG:3857")
        g0 = out.geom_column.geometry(1)
        ex, ey = crs.from_4326(10.0, 40.0, "EPSG:3857")
        b = g0.bounds()
        assert abs(b[0] - float(ex)) < 1e-6 and abs(b[1] - float(ey)) < 1e-6
        # box_info cache carried forward: still all rectangles
        bmask, bounds = out.geom_column.box_info()
        assert bmask.all()
        assert abs(bounds[1, 0] - float(ex)) < 1e-6


class TestCrsStamping:
    def test_gml_export_stamps_target_crs(self):
        from geomesa_tpu.io.exporters import export
        ds, x, y = _point_store(n=20)
        out = ds.query("pts", "INCLUDE", hints=QueryHints(reproject="EPSG:3857"))
        gml = export(out, "gml")
        assert 'srsName="EPSG:3857"' in gml
        assert 'srsName="EPSG:4326"' not in gml
        # un-reprojected results keep the 4326 stamp
        gml4326 = export(ds.query("pts", "INCLUDE"), "gml")
        assert 'srsName="EPSG:4326"' in gml4326

    def test_reprojected_sft_carries_srid(self):
        ds, _, _ = _point_store(n=5)
        out = ds.query("pts", "INCLUDE", hints=QueryHints(reproject="EPSG:3857"))
        assert out.sft.attr(out.sft.geom_field).options["srid"] == "3857"
        assert out.sft.user_data["geomesa.crs"] == "EPSG:3857"

    def test_geojson_reprojected_carries_crs_member(self):
        import json
        from geomesa_tpu.io.exporters import export
        ds, _, _ = _point_store(n=10)
        out = ds.query("pts", "INCLUDE", hints=QueryHints(reproject="EPSG:3857"))
        gj = json.loads(export(out, "geojson"))
        assert gj["crs"]["properties"]["name"].endswith("EPSG::3857")
        # plain 4326 output has no crs member (RFC 7946 form)
        gj2 = json.loads(export(ds.query("pts", "INCLUDE"), "geojson"))
        assert "crs" not in gj2

    def test_leaflet_rejects_reprojected(self):
        from geomesa_tpu.io.exporters import export
        ds, _, _ = _point_store(n=5)
        out = ds.query("pts", "INCLUDE", hints=QueryHints(reproject="EPSG:3857"))
        with pytest.raises(ValueError, match="4326"):
            export(out, "leaflet")

    def test_shapefile_prj_roundtrip(self, tmp_path):
        from geomesa_tpu.io.shapefile import read_shapefile, write_shapefile
        ds, _, _ = _point_store(n=8)
        out = ds.query("pts", "INCLUDE", hints=QueryHints(reproject="EPSG:3857"))
        base = str(tmp_path / "m")
        write_shapefile(out, base)
        assert "Mercator" in open(base + ".prj").read()
        back = read_shapefile(base + ".shp")
        assert back.sft.user_data.get("geomesa.crs") == "EPSG:3857"
        # 4326 write has a GEOGCS prj and reads back without the stamp
        base2 = str(tmp_path / "d")
        write_shapefile(ds.query("pts", "INCLUDE"), base2)
        assert open(base2 + ".prj").read().startswith("GEOGCS")
        back2 = read_shapefile(base2 + ".shp")
        assert "geomesa.crs" not in back2.sft.user_data
