"""Crash-safe persistence under fault injection: kill the save at every
fault point and reload (either-old-or-new, never torn); quarantine of
bit-flipped/truncated/missing partitions; degraded-mode queries; bounded
retry of transient IO faults; the streaming flush's atomicity."""

import json
import os

import numpy as np
import pytest

from geomesa_tpu import fault
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.planning.explain import Explainer
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.storage import persist
from geomesa_tpu.storage.persist import StoreCorruptionError

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"
T0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)


@pytest.fixture(autouse=True)
def _clean_faults():
    """No fault leaks between tests, whatever a test raised."""
    yield
    fault.injector().reset()


def _store(n=120, seed=0, prefix="f"):
    """A store whose dtg spread covers several coarse time partitions."""
    sft = FeatureType.from_spec("t", SPEC)
    ds = DataStore()
    ds.create_schema(sft)
    rng = np.random.default_rng(seed)
    ds.write("t", FeatureCollection.from_columns(
        sft, [f"{prefix}{i}" for i in range(n)],
        {"name": np.array([f"n{i % 5}" for i in range(n)]),
         "dtg": T0 + rng.integers(0, 80 * 86_400_000, n),
         "geom": (rng.uniform(-50, 50, n), rng.uniform(-50, 50, n))},
    ))
    return ds


def _append(ds, start, n=40, seed=9):
    sft = ds.get_schema("t")
    rng = np.random.default_rng(seed)
    ds.write("t", FeatureCollection.from_columns(
        sft, [f"x{start + i}" for i in range(n)],
        {"name": np.array(["x"] * n),
         "dtg": T0 + rng.integers(0, 80 * 86_400_000, n),
         "geom": (rng.uniform(-50, 50, n), rng.uniform(-50, 50, n))},
    ))


def _ids(ds):
    return sorted(np.asarray(ds.features("t").ids).tolist())


def _flip_byte(path, offset=None):
    with open(path, "rb+") as fh:
        data = fh.read()
        off = len(data) // 2 if offset is None else offset
        fh.seek(off)
        fh.write(bytes([data[off] ^ 0x20]))


SAVE_FAULT_POINTS = [
    "persist.partition.write",
    "persist.partition.rename",
    "persist.manifest.write",
    "persist.manifest.rename",
]


class TestAtomicSave:
    def test_v3_roundtrip_and_manifest(self, tmp_path):
        ds = _store()
        persist.save(ds, tmp_path / "s")
        meta = json.load(open(tmp_path / "s" / "metadata.json"))
        assert meta["version"] == 3
        parts = meta["types"]["t"]["partitions"]
        assert len(parts) >= 2  # dtg spread covers several partitions
        for entry in parts.values():
            assert set(entry) >= {"file", "sig", "checksum", "bytes", "rows"}
            p = tmp_path / "s" / "t" / entry["file"]
            assert p.stat().st_size == entry["bytes"]
        ds2 = persist.load(tmp_path / "s")
        assert _ids(ds2) == _ids(ds)
        assert ds2.store_health.status == "ok"

    def test_incremental_save_reuses_committed_files(self, tmp_path):
        ds = _store()
        root = tmp_path / "s"
        persist.save(ds, root)
        before = {f: (root / "t" / f).stat().st_mtime_ns
                  for f in os.listdir(root / "t")}
        persist.save(ds, root)  # no changes: nothing rewritten
        after = {f: (root / "t" / f).stat().st_mtime_ns
                 for f in os.listdir(root / "t")}
        assert before == after

    @pytest.mark.parametrize("point", SAVE_FAULT_POINTS)
    def test_crash_at_fault_point_leaves_old_or_new(self, tmp_path, point):
        ds = _store()
        root = tmp_path / "s"
        persist.save(ds, root)
        old = _ids(ds)
        _append(ds, 0)
        new = _ids(ds)
        with fault.inject(point, kind="crash"):
            with pytest.raises(fault.InjectedCrash):
                persist.save(ds, root)
        back = persist.load(root)
        assert back.store_health.status == "ok"
        got = _ids(back)
        assert got in (old, new)
        # the next clean save converges on the new state
        persist.save(ds, root)
        assert _ids(persist.load(root)) == new

    def test_partial_write_crash_recovers_old_store(self, tmp_path):
        """A torn partition write (file truncated mid-flush, process
        dies): the manifest never committed, so load sees the OLD store
        — the torn file is an unreferenced orphan."""
        ds = _store()
        root = tmp_path / "s"
        persist.save(ds, root)
        old = _ids(ds)
        _append(ds, 0)
        with fault.inject("persist.partition.commit", kind="partial_write"):
            with pytest.raises(fault.InjectedCrash):
                persist.save(ds, root)
        back = persist.load(root)
        assert _ids(back) == old and back.store_health.status == "ok"

    def test_crash_mid_way_through_partitions(self, tmp_path):
        """Kill at the SECOND partition write: some new files landed,
        none referenced — still cleanly the old store."""
        ds = _store()
        root = tmp_path / "s"
        persist.save(ds, root)
        old = _ids(ds)
        # touch every partition so the incremental skip rewrites them all
        _append(ds, 0, n=60, seed=3)
        with fault.inject("persist.partition.write", kind="crash", after=1):
            with pytest.raises(fault.InjectedCrash):
                persist.save(ds, root)
        assert _ids(persist.load(root)) == old

    def test_gc_crash_leaves_loadable_new_store(self, tmp_path):
        """A crash AFTER the manifest commit (during garbage collection)
        leaves the NEW store plus ignorable orphans."""
        ds = _store()
        root = tmp_path / "s"
        persist.save(ds, root)
        _append(ds, 0)
        new = _ids(ds)
        with fault.inject("persist.gc", kind="crash"):
            with pytest.raises(fault.InjectedCrash):
                persist.save(ds, root)
        back = persist.load(root)
        assert _ids(back) == new and back.store_health.status == "ok"
        persist.save(ds, root)  # next save sweeps the orphans
        files = {e["file"] for e in json.load(open(root / "metadata.json"))
                 ["types"]["t"]["partitions"].values()}
        assert set(os.listdir(root / "t")) == files

    def test_corrupt_manifest_raises_store_corruption(self, tmp_path):
        ds = _store()
        root = tmp_path / "s"
        persist.save(ds, root)
        _flip_byte(root / "metadata.json", offset=2)
        with pytest.raises(StoreCorruptionError):
            persist.load(root)

    @pytest.mark.slow
    def test_randomized_crash_matrix(self, tmp_path):
        """Every save fault point x several hit offsets x growing stores:
        no combination may produce a torn store."""
        ds = _store(n=90, seed=11)
        root = tmp_path / "s"
        persist.save(ds, root)
        states = [_ids(ds)]
        step = 0
        for rounds in range(6):
            _append(ds, 1000 * rounds, n=25, seed=rounds)
            states.append(_ids(ds))
            for point in SAVE_FAULT_POINTS + ["persist.partition.commit"]:
                kind = "partial_write" if "commit" in point else "crash"
                for after in (0, 1, 2):
                    step += 1
                    with fault.inject(point, kind=kind, after=after) as spec:
                        try:
                            persist.save(ds, root)
                        except fault.InjectedCrash:
                            pass
                    got = _ids(persist.load(root))
                    assert got in states, (point, after, step)
            persist.save(ds, root)
            assert _ids(persist.load(root)) == states[-1]


class TestQuarantine:
    def _saved(self, tmp_path, **load_kwargs):
        ds = _store()
        root = tmp_path / "s"
        persist.save(ds, root)
        return ds, root

    def test_bit_flip_injected_at_commit_is_quarantined(self, tmp_path):
        ds = _store()
        root = tmp_path / "s"
        with fault.inject("persist.partition.commit", kind="bit_flip"):
            persist.save(ds, root)  # save succeeds; one durable file damaged
        back = persist.load(root)
        assert back.store_health.status == "degraded"
        [rec] = back.store_health.damage
        assert rec.reason == "checksum" and rec.type_name == "t"
        # the damaged file moved out of the data dir, into quarantine
        assert not (root / "t" / rec.file).exists()
        assert (root / "_quarantine" / "t" / rec.file).exists()
        # surviving partitions still answer
        assert 0 < len(back.features("t")) < len(ds.features("t"))

    def test_truncated_partition_quarantined(self, tmp_path):
        ds, root = self._saved(tmp_path)
        f = sorted(os.listdir(root / "t"))[0]
        with open(root / "t" / f, "rb+") as fh:
            fh.truncate(os.path.getsize(root / "t" / f) // 2)
        back = persist.load(root)
        [rec] = back.store_health.damage
        assert rec.reason == "truncated"
        assert rec.rows_lost > 0

    def test_missing_partition_reported(self, tmp_path):
        ds, root = self._saved(tmp_path)
        f = sorted(os.listdir(root / "t"))[0]
        os.remove(root / "t" / f)
        back = persist.load(root)
        [rec] = back.store_health.damage
        assert rec.reason == "missing" and rec.quarantined_to is None

    def test_damage_report_is_machine_readable(self, tmp_path):
        ds, root = self._saved(tmp_path)
        f = sorted(os.listdir(root / "t"))[0]
        _flip_byte(root / "t" / f)
        persist.load(root)
        report = persist.damage_report(root)
        assert len(report) == 1
        assert set(report[0]) >= {
            "type", "file", "reason", "rows_lost", "quarantined_to", "time",
        }
        assert report[0]["file"] == f

    def test_on_damage_raise(self, tmp_path):
        ds, root = self._saved(tmp_path)
        f = sorted(os.listdir(root / "t"))[0]
        _flip_byte(root / "t" / f)
        with pytest.raises(StoreCorruptionError):
            persist.load(root, on_damage="raise")
        # strict mode must not have quarantined anything
        assert (root / "t" / f).exists()

    def test_degraded_query_warns_and_counts(self, tmp_path):
        ds, root = self._saved(tmp_path)
        f = sorted(os.listdir(root / "t"))[0]
        _flip_byte(root / "t" / f)
        reg = MetricsRegistry()
        back = persist.load(root, metrics=reg)
        assert reg.counters["geomesa.store.quarantined"] == 1
        exp = Explainer()
        out = back.query("t", "bbox(geom, -60, -60, 60, 60)", explain=exp)
        assert len(out) > 0  # degraded, not dead: survivors answer
        assert any("quarantined" in w for w in exp.warnings)
        assert any("WARNING" in line for line in exp.lines)
        assert reg.counters["geomesa.query.degraded"] == 1
        # healthy types on the same store would not warn; the damaged one
        # warns on every plan
        back.query("t", "bbox(geom, 0, 0, 1, 1)")
        assert reg.counters["geomesa.query.degraded"] == 2

    def test_repeated_loads_do_not_duplicate_report_records(self, tmp_path):
        """Re-loading an already-degraded store re-detects the same hole
        (the quarantined file now reads as "missing") but must keep ONE
        report record per damaged file — and count ONE quarantine metric
        event — not one per load."""
        ds, root = self._saved(tmp_path)
        f = sorted(os.listdir(root / "t"))[0]
        _flip_byte(root / "t" / f)
        counts = []
        for _ in range(3):
            reg = MetricsRegistry()
            back = persist.load(root, metrics=reg)
            assert back.store_health.status == "degraded"
            counts.append(reg.counters.get("geomesa.store.quarantined", 0))
        assert len(persist.damage_report(root)) == 1
        assert counts == [1, 0, 0]  # only the first sighting counts

    def test_malformed_manifest_entry_contained(self, tmp_path):
        """A torn per-entry record (missing 'file' field) inside a valid
        manifest is its own damage: the intact partitions still load,
        and on_damage='raise' gets a typed StoreCorruptionError."""
        ds, root = self._saved(tmp_path)
        meta = json.load(open(root / "metadata.json"))
        parts = meta["types"]["t"]["partitions"]
        bad = sorted(parts)[0]
        del parts[bad]["file"]
        json.dump(meta, open(root / "metadata.json", "w"))
        back = persist.load(root)
        assert back.store_health.status == "degraded"
        [rec] = back.store_health.damage
        assert rec.reason == "manifest"
        assert 0 < len(back.features("t")) < len(ds.features("t"))
        with pytest.raises(StoreCorruptionError):
            persist.load(root, on_damage="raise")

    def test_unwritable_store_still_loads_degraded(self, tmp_path):
        """A damaged store on a read-only mount: quarantine moves and the
        report write fail, but the load must still produce a degraded
        store answering from the survivors — not crash."""
        from unittest import mock

        ds, root = self._saved(tmp_path)
        f = sorted(os.listdir(root / "t"))[0]
        _flip_byte(root / "t" / f)
        with mock.patch(
            "os.makedirs", side_effect=OSError(30, "Read-only file system")
        ):
            back = persist.load(root)
        assert back.store_health.status == "degraded"
        [rec] = back.store_health.damage
        assert rec.reason == "checksum" and rec.quarantined_to is None
        assert 0 < len(back.features("t")) < len(ds.features("t"))
        assert persist.damage_report(root) == []  # nothing loggable

    def test_quarantine_name_collision_rejected(self, tmp_path):
        """A feature type literally named '_quarantine' would mix live
        partitions with damage artifacts — both save and load refuse."""
        sft = FeatureType.from_spec(
            "_quarantine", "name:String,*geom:Point:srid=4326"
        )
        ds = DataStore()
        ds.create_schema(sft)
        with pytest.raises(ValueError, match="_quarantine"):
            persist.save(ds, tmp_path / "s")

    def test_quarantined_rows_reappear_after_resave(self, tmp_path):
        """Repair path: re-saving a full store over a damaged directory
        restores a clean manifest."""
        ds, root = self._saved(tmp_path)
        f = sorted(os.listdir(root / "t"))[0]
        _flip_byte(root / "t" / f)
        persist.load(root)  # quarantines
        persist.save(ds, root)  # full store still in memory: heal the dir
        back = persist.load(root)
        assert back.store_health.status == "ok"
        assert _ids(back) == _ids(ds)


class TestRetryAndEnv:
    def test_transient_io_error_is_retried(self, tmp_path):
        ds = _store()
        with fault.inject("persist.partition.write", kind="io_error", times=2):
            persist.save(ds, tmp_path / "s")  # 3 attempts by default
        assert _ids(persist.load(tmp_path / "s")) == _ids(ds)

    def test_persistent_io_error_raises_after_retries(self, tmp_path):
        ds = _store()
        with fault.inject("persist.partition.write", kind="io_error", times=None):
            with pytest.raises(OSError):
                persist.save(ds, tmp_path / "s")
        assert not (tmp_path / "s" / "metadata.json").exists()

    def test_latency_fault_only_slows(self, tmp_path):
        ds = _store(n=30)
        with fault.inject("persist.*", kind="latency", times=None, delay_s=0.001):
            persist.save(ds, tmp_path / "s")
        assert _ids(persist.load(tmp_path / "s")) == _ids(ds)

    def test_env_var_armed_faults(self, tmp_path, monkeypatch):
        ds = _store(n=30)
        monkeypatch.setenv(
            "GEOMESA_TPU_FAULTS", "persist.manifest.rename:crash:0:1"
        )
        specs = fault.injector().load_env()
        try:
            with pytest.raises(fault.InjectedCrash):
                persist.save(ds, tmp_path / "s")
        finally:
            for s in specs:
                fault.injector().remove(s)
        persist.save(ds, tmp_path / "s")  # spec exhausted/removed
        assert _ids(persist.load(tmp_path / "s")) == _ids(ds)

    def test_env_latency_carries_delay(self, monkeypatch):
        """The 5th env field is the latency sleep — without it an
        env-armed latency fault would be a silent no-op."""
        monkeypatch.setenv(
            "GEOMESA_TPU_FAULTS", "persist.*:latency::-1:0.05"
        )
        specs = fault.injector().load_env()
        try:
            [spec] = specs
            assert spec.kind == "latency" and spec.delay_s == 0.05
            assert spec.after == 0 and spec.times is None
        finally:
            for s in specs:
                fault.injector().remove(s)

    def test_bad_env_entry_rejected(self, monkeypatch):
        monkeypatch.setenv("GEOMESA_TPU_FAULTS", "justapoint")
        with pytest.raises(ValueError):
            fault.injector().load_env()

    def test_with_retries_backoff_jitter_bounds(self):
        """Decorrelated jitter (the thundering-herd fix): every sleep
        draws from U(base, min(cap, 3 * prev)) with cap = base *
        2^(attempts-1) — bounded like the old exponential schedule, but
        concurrent workers no longer retry in lockstep."""
        sleeps = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 4:
                raise OSError("blip")
            return "ok"

        out = fault.with_retries(
            flaky, attempts=4, backoff_s=0.01, sleep=sleeps.append
        )
        assert out == "ok"
        assert len(sleeps) == 3
        assert all(0.01 <= s <= 0.08 for s in sleeps), sleeps
        # the rng seam pins the exact schedule for deterministic tests:
        # hi_i = min(cap, 3 * prev) starting from prev = base
        sleeps2, calls["n"] = [], 0
        fault.with_retries(
            flaky, attempts=4, backoff_s=0.01, sleep=sleeps2.append,
            rng=lambda lo, hi: hi,
        )
        assert sleeps2 == [0.03, 0.08, 0.08]

    def test_with_retries_counters(self):
        """geomesa.fault.retry / retries_exhausted observability: every
        absorbed transient counts, every budget exhaustion counts."""
        reg = MetricsRegistry()
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("blip")
            return "ok"

        fault.with_retries(
            flaky, attempts=3, backoff_s=0.0001, metrics=reg
        )
        assert reg.counter_value("geomesa.fault.retry") == 2
        assert reg.counter_value("geomesa.fault.retries_exhausted") == 0

        def dead():
            raise OSError("down")

        with pytest.raises(OSError):
            fault.with_retries(dead, attempts=2, backoff_s=0.0001, metrics=reg)
        assert reg.counter_value("geomesa.fault.retry") == 3
        assert reg.counter_value("geomesa.fault.retries_exhausted") == 1

    def test_crash_is_never_retried(self):
        calls = {"n": 0}

        def dies():
            calls["n"] += 1
            raise fault.InjectedCrash("dead")

        with pytest.raises(fault.InjectedCrash):
            fault.with_retries(dies, attempts=5, backoff_s=0.0)
        assert calls["n"] == 1


class TestStreamingFlush:
    def _lambda(self, n_cold=30):
        from geomesa_tpu.streaming import LambdaStore

        ds = _store(n=n_cold, prefix="c")
        return ds, LambdaStore(ds, "t")

    @staticmethod
    def _rows(k, name="hot"):
        from geomesa_tpu import geometry as geo

        return [
            {"name": name, "dtg": "2024-01-05T00:00:00Z",
             "geom": geo.Point(float(i), float(i))}
            for i in range(k)
        ]

    def test_failed_flush_keeps_hot_and_cold_intact(self, tmp_path):
        ds, lam = self._lambda()
        lam.write(self._rows(3), ids=["h0", "h1", "c0"])  # c0 = hot update
        cold_before = _ids(ds)
        with fault.inject("streaming.persist", kind="io_error", times=None):
            with pytest.raises(OSError):
                lam.persist_hot()
        assert len(lam.hot) == 3           # hot cache not dropped
        assert _ids(ds) == cold_before     # cold tier untouched
        # the retry path succeeds once the fault clears
        assert lam.persist_hot() == 3
        assert len(lam.hot) == 0
        assert "h0" in _ids(ds) and "c0" in _ids(ds)

    def test_transient_flush_fault_retries_internally(self):
        ds, lam = self._lambda()
        lam.write(self._rows(2), ids=["h0", "h1"])
        with fault.inject("streaming.persist", kind="io_error", times=1):
            assert lam.persist_hot() == 2  # one blip, retried, flushed
        assert len(lam.hot) == 0

    def test_checkpoint_crash_leaves_old_on_disk_store(self, tmp_path):
        ds, lam = self._lambda()
        root = tmp_path / "cold"
        lam.checkpoint(root)
        old = _ids(persist.load(root))
        lam.write(self._rows(2), ids=["h0", "h1"])
        with fault.inject("persist.manifest.rename", kind="crash"):
            with pytest.raises(fault.InjectedCrash):
                lam.checkpoint(root)
        assert _ids(persist.load(root)) == old  # on-disk store intact
        lam.checkpoint(root)  # hot already flushed to cold; save converges
        assert sorted(old + ["h0", "h1"]) == _ids(persist.load(root))


class TestChaosSchedule:
    """fault.chaos: the seeded background schedule (the closed-loop
    harness lives in tests/test_wal.py; this pins the API contract)."""

    def test_schedule_is_deterministic(self):
        runs = []
        for _ in range(2):
            fired = []
            with fault.chaos(seed=5, rate=0.5, points="demo.*",
                             kinds=("io_error",), delay_s=0.0) as spec:
                for i in range(30):
                    try:
                        fault.fault_point("demo.p")
                    except OSError:
                        fired.append(i)
            assert spec.hits == 30 and spec.fired == len(fired)
            runs.append(fired)
        assert runs[0] == runs[1] and runs[0]  # same seed, same schedule

    def test_non_matching_points_never_fire(self):
        with fault.chaos(seed=1, rate=1.0, points="persist.*") as spec:
            fault.fault_point("stream.wal.append")
        assert spec.hits == 0 and spec.fired == 0

    def test_validation_and_single_schedule(self):
        with pytest.raises(ValueError, match="rate"):
            fault.ChaosSpec(1, rate=1.5)
        with pytest.raises(ValueError, match="kind"):
            fault.ChaosSpec(1, kinds=("segfault",))
        with fault.chaos(seed=1):
            with pytest.raises(RuntimeError, match="already installed"):
                fault.injector().install_chaos(fault.ChaosSpec(2))
        # the exit released the slot
        with fault.chaos(seed=3):
            pass


class TestFaultPointCoverage:
    """Every FAULT_POINTS entry must be exercised by some test (the
    fault-point-unknown lint rule's coverage direction); these arm the
    points no recovery scenario above reaches, asserting the spec FIRED
    — a renamed point turns these into hard failures, not vacuous
    passes."""

    def test_load_partition_read_transient_fault_retried(self, tmp_path):
        ds = _store(n=40)
        persist.save(ds, tmp_path / "s")
        with fault.inject("load.partition.read", kind="io_error",
                          times=1) as spec:
            back = persist.load(tmp_path / "s")
        assert spec.fired == 1
        assert _ids(back) == _ids(ds)
        assert back.store_health.status == "ok"

    def test_metadata_write_and_rename_points_fire(self, tmp_path):
        from geomesa_tpu.storage.metadata import FileMetadata

        md = FileMetadata(str(tmp_path / "md"))
        with fault.inject("metadata.write", kind="latency", times=None,
                          delay_s=0.0) as w:
            with fault.inject("metadata.rename", kind="io_error",
                              times=1) as r:
                md.insert("schema/t", "spec")  # one blip, retried inside
        assert w.fired >= 1 and r.fired == 1
        assert md.get("schema/t") == "spec"

    def test_adapter_create_table_point_fires(self):
        with fault.inject("adapter.create_table", kind="latency",
                          times=None, delay_s=0.0) as spec:
            ds = _store(n=30)
            ds.compact("t")
        assert spec.fired >= 1
        assert ds.count("t") == 30

    def test_ingest_parse_point_fires(self, tmp_path):
        from geomesa_tpu import ingest as ing
        from geomesa_tpu.io.converters import Converter, FieldSpec

        p = tmp_path / "d.csv"
        p.write_text("name,lon,lat\n" + "".join(
            f"r{i},{i % 50},{i % 40}\n" for i in range(30)
        ))
        sft = FeatureType.from_spec("t", "name:String,*geom:Point:srid=4326")
        conv = Converter(
            sft=sft, fmt="delimited", skip_lines=1, id_field="$1",
            fields=[FieldSpec("name", "$1"),
                    FieldSpec("geom", "point($2, $3)")],
        )
        ds = DataStore()
        ds.create_schema(sft)
        with fault.inject("ingest.parse", kind="latency", times=None,
                          delay_s=0.0) as spec:
            res = ing.ingest_files(ds, conv, [str(p)], workers=0)
        assert spec.fired >= 1
        assert res.written == 30 == ds.count("t")


class TestSignature:
    """Satellite regression: the partition content signature must hash a
    stable, collision-free per-id encoding for object-dtype id arrays."""

    def _sig(self, ids, names=None):
        sft = FeatureType.from_spec("t", SPEC)
        n = len(ids)
        fc = FeatureCollection.from_columns(
            sft, ids,
            {"name": np.array(["a"] * n if names is None else names),
             "dtg": np.full(n, T0, dtype=np.int64),
             "geom": (np.zeros(n), np.zeros(n))},
        )
        return persist._signature(
            np.asarray(fc.ids), persist._pack_columns(sft, fc)
        )

    def test_mixed_type_ids_do_not_collide(self):
        def obj(vals):
            a = np.empty(len(vals), dtype=object)
            a[:] = vals
            return a

        sigs = {
            self._sig(obj(["1", "2"])),
            self._sig(obj([1, 2])),
            self._sig(obj([b"1", b"2"])),
            self._sig(obj(["1", 2])),
        }
        assert len(sigs) == 4  # str/int/bytes forms all hash apart

    def test_separator_injection_does_not_collide(self):
        # under the old "\n".join encoding both hashed "a\nb\nc"
        a = np.empty(2, dtype=object); a[:] = ["a\nb", "c"]
        b = np.empty(2, dtype=object); b[:] = ["a", "b\nc"]
        assert self._sig(a) != self._sig(b)

    def test_signature_stable_across_unicode_width(self):
        # fixed-width unicode padding must not leak into the signature
        assert self._sig(np.array(["a", "b"])) == self._sig(
            np.array(["a", "b", "longerid"])[:2]
        )

    def test_signature_covers_attribute_values(self):
        # same ids, different values: updates (upsert / streaming flush)
        # must change the signature or they never persist
        ids = np.array(["1", "2"])
        assert self._sig(ids, ["a", "a"]) != self._sig(ids, ["a", "B"])

    def test_value_only_update_persists_through_incremental_save(self, tmp_path):
        """The full data-loss scenario: a flush that changes VALUES under
        unchanged ids must rewrite the touched partition, not be skipped
        by the incremental signature."""
        from geomesa_tpu import geometry as geo
        from geomesa_tpu.streaming import LambdaStore

        ds = _store(n=40)
        root = tmp_path / "s"
        persist.save(ds, root)
        lam = LambdaStore(ds, "t")
        lam.write(
            [{"name": "UPDATED", "dtg": "2024-01-02T00:00:00Z",
              "geom": geo.Point(0.0, 0.0)}],
            ids=["f0"],
        )
        lam.persist_hot()
        persist.save(ds, root)  # incremental save over the old manifest
        back = persist.load(root)
        row = back.query("t", "IN ('f0')")
        assert np.asarray(row.columns["name"])[0] == "UPDATED"

    def test_roundtrip_with_object_ids_persists(self, tmp_path):
        # object-dtype ids with embedded separators (mixed int/str ids
        # cannot pass the store's sorted duplicate-id check — np.unique
        # can't order them — so the store boundary is same-kind objects)
        sft = FeatureType.from_spec("m", "name:String,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        ids = np.empty(3, dtype=object)
        ids[:] = ["a\nb", "c:d", "e"]
        ds.write("m", FeatureCollection.from_columns(
            sft, ids,
            {"name": np.array(["a", "b", "c"]),
             "geom": (np.zeros(3), np.zeros(3))},
        ))
        persist.save(ds, tmp_path / "s")
        back = persist.load(tmp_path / "s")
        assert len(back.features("m")) == 3


class TestCacheQuarantineInterplay:
    """Degraded-mode x cache tier (ISSUE 2 satellite): a quarantined
    partition INVALIDATES overlapping cached entries — a warm cache
    carried across a reload must never serve rows from the hole."""

    def test_quarantine_invalidates_overlapping_cache_entries(self, tmp_path):
        from geomesa_tpu.cache import QueryCache
        from geomesa_tpu.planning.hints import QueryHints

        ds = _store()
        cache = QueryCache()
        ds.attach_cache(cache)
        root = tmp_path / "s"
        persist.save(ds, root)
        # warm the cache AFTER the save: entries reflect the on-disk rows
        q = "bbox(geom, -60, -60, 60, 60)"  # covers the whole store
        n_full = len(ds.query("t", q))
        assert len(cache.result) >= 1
        # damage one durable partition -> quarantined on the next load
        fname = sorted(os.listdir(root / "t"))[0]
        _flip_byte(root / "t" / fname)
        back = persist.load(root, cache=cache)
        assert back.store_health.status == "degraded"
        [rec] = back.store_health.damage
        assert rec.rows_lost > 0
        # the warm cache was INVALIDATED (eagerly dropped), not warned
        # about: nothing overlapping the quarantined range is resident
        assert len(cache.result) == 0
        assert len(cache.tiles) == 0
        # degraded queries answer from survivors only, cached and
        # uncached paths byte-identical (no stale full-store entry)
        got = back.query("t", q)
        raw = back.query("t", q, hints=QueryHints(cache="bypass"))
        assert sorted(np.asarray(got.ids).tolist()) == sorted(
            np.asarray(raw.ids).tolist()
        )
        assert len(got) == n_full - rec.rows_lost
        # counts compose from fresh tiles, never the pre-damage ones
        assert back.count("t", q) == len(got)

    def test_reload_invalidates_warm_entries_even_for_empty_types(
        self, tmp_path
    ):
        """A type saved EMPTY, then written and queried (warming the
        cache), then reloaded: the reload rolls the unsaved rows back, no
        write-path bump fires (zero rows load), yet the warm entry must
        NOT be served — load bumps every loaded type unconditionally."""
        from geomesa_tpu.cache import QueryCache

        sft = FeatureType.from_spec("t", SPEC)
        ds = DataStore()
        ds.create_schema(sft)
        cache = QueryCache()
        ds.attach_cache(cache)
        root = tmp_path / "s"
        persist.save(ds, root)  # the type is durable but EMPTY
        ds.write("t", FeatureCollection.from_columns(
            sft, ["u0", "u1"],
            {"name": np.array(["a", "b"]),
             "dtg": np.full(2, int(T0)),
             "geom": (np.zeros(2), np.zeros(2))},
        ))
        q = "bbox(geom, -10, -10, 10, 10)"
        assert len(ds.query("t", q)) == 2  # warms the cache post-save
        back = persist.load(root, cache=cache)
        assert len(back.query("t", q)) == 0  # rolled back, never stale

    def test_quarantine_generation_bump_scopes_to_partition_bucket(
        self, tmp_path
    ):
        """on_quarantine bumps the damaged partition's TIME bucket: a
        warm entry over a disjoint time window on another type survives
        the reload untouched."""
        from geomesa_tpu.cache import KeyRange, QueryCache

        ds = _store()
        root = tmp_path / "s"
        persist.save(ds, root)
        cache = QueryCache()
        # a synthetic warm entry for an UNRELATED type: quarantine bumps
        # must be per-type, so this entry survives every load below
        tick = cache.generations.tick()
        fname = sorted(os.listdir(root / "t"))[0]
        _flip_byte(root / "t" / fname)
        back = persist.load(root, cache=cache)
        assert back.store_health.status == "degraded"
        assert not cache.generations.stale(
            "other_type", KeyRange.everything(), tick
        )
        assert cache.generations.stale(
            "t", KeyRange.everything(), tick
        )


class TestPipelineFaultAtomicity:
    """Fault injection in the staged ingest pipeline (docs/ingest.md):
    an io_error/crash in ANY worker stage fails the ingest atomically —
    no partial table visible, `_quarantine/` untouched."""

    def _sft(self):
        return FeatureType.from_spec("t", SPEC)

    def _chunks(self, n_chunks=4, n=300):
        sft = self._sft()
        rng = np.random.default_rng(5)
        out, base = [], 0
        for _ in range(n_chunks):
            out.append(FeatureCollection.from_columns(
                sft, [f"f{base + i}" for i in range(n)],
                {"name": np.array(["x"] * n),
                 "dtg": T0 + rng.integers(0, 80 * 86_400_000, n),
                 "geom": (rng.uniform(-50, 50, n), rng.uniform(-50, 50, n))},
            ))
            base += n
        return out

    def _assert_untouched(self, ds, root=None):
        assert ds.count("t") == 0
        assert ds._chunks["t"] == []
        assert ("t", "z3") not in ds._tables
        assert ds.stats_for("t") is None
        if root is not None:
            assert not os.path.exists(os.path.join(str(root), "_quarantine"))

    @pytest.mark.parametrize("point,kind", [
        ("ingest.keys", "io_error"),
        ("ingest.keys", "crash"),
        ("ingest.sort", "io_error"),
        ("ingest.sort", "crash"),
        ("ingest.commit", "crash"),
        ("ingest.finalize", "io_error"),
    ])
    def test_stage_fault_aborts_atomically(self, tmp_path, point, kind):
        from geomesa_tpu.fault import InjectedCrash, InjectedIOError
        from geomesa_tpu.ingest import BulkLoader, PipelineConfig

        ds = DataStore()
        ds.create_schema(self._sft())
        loader = BulkLoader(ds, "t", config=PipelineConfig(workers=2))
        expected = InjectedCrash if kind == "crash" else InjectedIOError
        with fault.inject(point, kind=kind):
            with pytest.raises((expected, RuntimeError)):
                for fc in self._chunks():
                    loader.put(fc)
                loader.close()
        self._assert_untouched(ds, tmp_path)

    def test_worker_fault_then_clean_retry_succeeds(self):
        """After an aborted ingest the store accepts a fresh bulk load of
        the same rows (nothing half-registered blocks the retry)."""
        from geomesa_tpu.fault import InjectedIOError
        from geomesa_tpu.ingest import BulkLoader, PipelineConfig

        ds = DataStore()
        ds.create_schema(self._sft())
        chunks = self._chunks()
        loader = BulkLoader(ds, "t", config=PipelineConfig(workers=2))
        with fault.inject("ingest.sort", kind="io_error"):
            with pytest.raises((InjectedIOError, RuntimeError)):
                for fc in chunks:
                    loader.put(fc)
                loader.close()
        self._assert_untouched(ds)
        loader = BulkLoader(ds, "t", config=PipelineConfig(workers=2))
        for fc in chunks:
            loader.put(fc)
        res = loader.close()
        assert res.written == sum(len(c) for c in chunks) == ds.count("t")

    def test_file_ingest_split_read_fault_atomic(self, tmp_path):
        """Exhausted split-read retries (every-hit io_error at
        ingest.split.read) abort the PIPELINED file ingest atomically and
        surface the worker traceback."""
        from geomesa_tpu import ingest as ing
        from geomesa_tpu.io.converters import Converter, FieldSpec

        p = tmp_path / "d.csv"
        p.write_text("name,lon,lat,when\n" + "".join(
            f"r{i},{i % 60},{i % 40},2024-02-01T00:00:00Z\n" for i in range(200)
        ))
        sft = FeatureType.from_spec(
            "t", "name:String,dtg:Date,*geom:Point:srid=4326"
        )
        conv = Converter(
            sft=sft, fmt="delimited", skip_lines=1, id_field="$1",
            fields=[FieldSpec("name", "$1"), FieldSpec("geom", "point($2, $3)"),
                    FieldSpec("dtg", "datetime($4)")],
        )
        ds = DataStore()
        ds.create_schema(sft)
        os.environ["GEOMESA_TPU_IO_BACKOFF_S"] = "0.001"
        try:
            with fault.inject("ingest.split.read", kind="io_error", times=None):
                with pytest.raises(ing.IngestError) as ei:
                    ing.ingest_files(ds, conv, [str(p)], workers=0)
        finally:
            os.environ.pop("GEOMESA_TPU_IO_BACKOFF_S", None)
        assert "InjectedIOError" in str(ei.value)
        assert ei.value.split_index == 0
        assert ds.count("t") == 0
        assert not os.path.exists(str(tmp_path / "_quarantine"))

    def test_transient_split_read_fault_is_retried(self, tmp_path):
        """ONE io_error at the split read is absorbed by with_retries:
        the ingest completes with every row."""
        from geomesa_tpu import ingest as ing
        from geomesa_tpu.io.converters import Converter, FieldSpec

        p = tmp_path / "d.csv"
        p.write_text("name,lon,lat,when\n" + "".join(
            f"r{i},{i % 60},{i % 40},2024-02-01T00:00:00Z\n" for i in range(50)
        ))
        sft = FeatureType.from_spec(
            "t", "name:String,dtg:Date,*geom:Point:srid=4326"
        )
        conv = Converter(
            sft=sft, fmt="delimited", skip_lines=1, id_field="$1",
            fields=[FieldSpec("name", "$1"), FieldSpec("geom", "point($2, $3)"),
                    FieldSpec("dtg", "datetime($4)")],
        )
        ds = DataStore()
        ds.create_schema(sft)
        os.environ["GEOMESA_TPU_IO_BACKOFF_S"] = "0.001"
        try:
            with fault.inject("ingest.split.read", kind="io_error", times=1):
                res = ing.ingest_files(ds, conv, [str(p)], workers=0)
        finally:
            os.environ.pop("GEOMESA_TPU_IO_BACKOFF_S", None)
        assert res.written == 50 == ds.count("t")
