"""Concurrent query serving (geomesa_tpu.serving): shed/deadline
semantics, backpressure, identical-fingerprint coalescing, cache-aware
admission, the adaptive window, and mixed-hints fused dispatches.

The sequential-equivalence matrix (threaded scheduler == sequential
query(), single-device and mesh4) lives in tests/test_query_many.py; the
cases here pin the scheduler's OWN behaviors, mostly on unstarted
schedulers so queue states are deterministic."""

import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.planning.errors import QueryTimeout
from geomesa_tpu.planning.explain import Explainer
from geomesa_tpu.planning.hints import QueryHints
from geomesa_tpu.serving import QueryScheduler, ServingConfig, ServingRejected
from geomesa_tpu.sft import FeatureType

DAY = 86400_000
Q = "bbox(geom, -10, -10, 10, 10)"


def _store(metrics=None, cache=None, n=4000):
    sft = FeatureType.from_spec(
        "ev", "kind:String:index=true,dtg:Date,*geom:Point:srid=4326"
    )
    ds = DataStore(tile=64, metrics=metrics, cache=cache)
    ds.create_schema(sft)
    rng = np.random.default_rng(7)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    ds.write("ev", FeatureCollection.from_columns(
        sft, [str(i) for i in range(n)],
        {
            "kind": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
            "dtg": t0 + rng.integers(0, 20 * DAY, n),
            "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n)),
        },
    ))
    return ds


@pytest.fixture(scope="module")
def ds():
    return _store(metrics=MetricsRegistry())


def test_serve_attach_surface(ds):
    s1 = ds.serve()
    assert ds.scheduler is s1
    assert ds.serve() is s1  # idempotent while open
    s1.close()
    s2 = ds.serve()
    assert s2 is not s1 and not s2.closed  # closed scheduler replaced
    s2.close()


def test_scheduler_query_equals_datastore_query(ds):
    with QueryScheduler(ds, ServingConfig()) as sched:
        out = sched.query("ev", Q)
    np.testing.assert_array_equal(
        np.asarray(out.ids), np.asarray(ds.query("ev", Q).ids)
    )


def test_shed_at_admission_when_timeout_inside_window():
    reg = MetricsRegistry()
    store = _store(metrics=reg)
    sched = QueryScheduler(store, ServingConfig(window_ms=50.0), metrics=reg)
    sched._window_s = 0.05  # as if load grew the window to its cap
    exp = Explainer()
    fut = sched.submit("ev", Q, hints=QueryHints(timeout=0.001), explain=exp)
    with pytest.raises(QueryTimeout, match="shed before dispatch"):
        fut.result(1)
    assert reg.counters["geomesa.serving.shed"] == 1
    assert any("shed" in w for w in exp.warnings)


def test_shed_at_dispatch_when_deadline_expired_queued():
    reg = MetricsRegistry()
    store = _store(metrics=reg)
    sched = QueryScheduler(store, ServingConfig(), metrics=reg)  # not started
    fut = sched.submit("ev", Q, hints=QueryHints(timeout=0.02))
    ok = sched.submit("ev", Q)  # no deadline: survives the stall
    time.sleep(0.08)
    sched.start()
    with pytest.raises(QueryTimeout, match="deadline expired"):
        fut.result(5)
    assert len(ok.result(5)) == len(store.query("ev", Q))
    assert reg.counters["geomesa.serving.shed"] == 1
    sched.close()


def test_queue_full_backpressure_and_shed():
    reg = MetricsRegistry()
    store = _store(metrics=reg)
    sched = QueryScheduler(store, ServingConfig(queue_max=1), metrics=reg)
    f1 = sched.submit("ev", Q)  # fills the queue
    f2 = sched.submit("ev", "kind = 'b'", block=False)  # full -> shed
    with pytest.raises(ServingRejected):
        f2.result(1)
    assert reg.counters["geomesa.serving.shed"] == 1
    # block=True + an expired deadline while waiting for space -> shed
    f3 = sched.submit("ev", Q, hints=QueryHints(timeout=0.01))
    with pytest.raises(QueryTimeout, match="queue full"):
        f3.result(1)
    assert reg.counters["geomesa.serving.shed"] == 2
    # backpressure path: a blocking submit parks until the dispatcher
    # frees a slot, then resolves normally
    with ThreadPoolExecutor(1) as ex:
        blocked = ex.submit(sched.submit, "ev", Q)
        time.sleep(0.05)
        sched.start()
        f4 = blocked.result(5)
        assert len(f4.result(10)) == len(store.query("ev", Q))
    assert len(f1.result(10)) == len(store.query("ev", Q))
    sched.close()


def test_identical_fingerprints_coalesce_into_one_slot():
    reg = MetricsRegistry()
    store = _store(metrics=reg)
    sched = QueryScheduler(store, ServingConfig(), metrics=reg)  # staged queue
    futs = [sched.submit("ev", Q) for _ in range(3)]
    other = sched.submit("ev", "kind = 'b'")
    sched.start()
    outs = [f.result(10) for f in futs]
    assert outs[1] is outs[0] and outs[2] is outs[0]  # ONE shared result
    np.testing.assert_array_equal(
        np.sort(np.asarray(outs[0].ids)),
        np.sort(np.asarray(store.query("ev", Q).ids)),
    )
    assert len(other.result(10)) == len(store.query("ev", "kind = 'b'"))
    assert reg.counters["geomesa.serving.coalesced"] == 2
    assert reg.counters["geomesa.serving.batches"] == 1
    assert reg.counters["geomesa.serving.batched_queries"] == 2  # leaders only
    # coalesced followers are still audited like their own queries
    assert reg.counters["geomesa.query.count"] == 4 + 2  # 4 via sched + oracle x2
    sched.close()


def test_mixed_hints_fuse_into_one_dispatch():
    """Different result-shaping hints ride ONE fused dispatch (hints
    shape post-processing, not the device scan): each caller gets the
    result sequential query() gives for its own hints."""
    reg = MetricsRegistry()
    store = _store(metrics=reg)
    sched = QueryScheduler(store, ServingConfig(), metrics=reg)
    h1 = QueryHints(sort_by="kind")
    h2 = QueryHints(transforms=["kind"])
    f1 = sched.submit("ev", Q, hints=h1)
    f2 = sched.submit("ev", Q, hints=h2)
    f3 = sched.submit("ev", Q, limit=5)
    sched.start()
    a, b, c = f1.result(10), f2.result(10), f3.result(10)
    assert reg.counters["geomesa.serving.batches"] == 1
    assert reg.counters["geomesa.serving.batched_queries"] == 3  # no coalesce
    oa = store.query("ev", Q, hints=h1)
    np.testing.assert_array_equal(np.asarray(a.ids), np.asarray(oa.ids))
    ob = store.query("ev", Q, hints=h2)
    assert list(b.columns) == list(ob.columns) == ["kind"]
    np.testing.assert_array_equal(np.asarray(b.ids), np.asarray(ob.ids))
    oc = store.query("ev", Q, limit=5)
    np.testing.assert_array_equal(np.asarray(c.ids), np.asarray(oc.ids))
    sched.close()


def test_cache_hits_never_queue():
    reg = MetricsRegistry()
    store = _store(metrics=reg, cache=True)
    sched = store.serve()
    first = sched.query("ev", Q)  # miss: fused dispatch + cache populate
    batches = reg.counters["geomesa.serving.batches"]
    assert batches >= 1
    h0 = reg.counters.get("geomesa.cache.hit", 0)
    second = sched.query("ev", Q)  # admission peek -> served in-caller
    assert reg.counters["geomesa.serving.batches"] == batches  # no dispatch
    assert reg.counters["geomesa.cache.hit"] == h0 + 1
    np.testing.assert_array_equal(
        np.asarray(first.ids), np.asarray(second.ids)
    )
    # bypass skips both the admission peek and the populate
    third = sched.query("ev", Q, hints=QueryHints(cache="bypass"))
    assert reg.counters["geomesa.serving.batches"] == batches + 1
    np.testing.assert_array_equal(np.asarray(first.ids), np.asarray(third.ids))
    sched.close()


def test_scheduled_miss_populates_result_cache():
    store = _store(metrics=MetricsRegistry(), cache=True)
    sched = store.serve()
    sched.query("ev", Q)
    assert len(store.cache.result) == 1  # admitted by the dispatch path
    # ... and a later PLAIN query() serves from it
    plan = store.planner.plan("ev", Q)
    out = store.planner.execute(plan)
    assert plan.cache_status == "hit"
    assert len(out) == len(store.query("ev", Q, hints=QueryHints(cache="bypass")))
    sched.close()


def test_adaptive_window_grows_and_shrinks():
    store = _store(metrics=MetricsRegistry())
    sched = QueryScheduler(store, ServingConfig(window_ms=4.0))
    assert sched.window_s == 0.0  # idle start: lone queries pay nothing
    sched._adapt(8)
    assert sched.window_s == pytest.approx(0.0005)  # cap/8 seed
    for _ in range(10):
        sched._adapt(8)
    assert sched.window_s == pytest.approx(0.004)  # grows to the cap
    sched._adapt(1)
    assert sched.window_s == pytest.approx(0.002)  # halves when singular
    for _ in range(10):
        sched._adapt(1)
    assert sched.window_s == 0.0  # collapses back to zero when idle


def test_partial_config_resolves_unset_knobs_from_properties():
    """ServingConfig(window_ms=...) must still honor the property tier
    (env/set overrides) for the knobs it does NOT name."""
    from geomesa_tpu import conf

    conf.SERVING_QUEUE_MAX.set(7)
    try:
        c = ServingConfig(window_ms=5.0)
        assert c.window_ms == 5.0
        assert c.queue_max == 7
        assert c.batch_max == conf.SERVING_BATCH_MAX.get()
    finally:
        conf.SERVING_QUEUE_MAX.clear()
    assert ServingConfig().queue_max == conf.SERVING_QUEUE_MAX.get()


def test_admission_anchored_deadlines_in_submit_many():
    """submit_many's ``deadlines`` anchor a scan's budget at admission:
    a budget already burned in the queue times the scan out, instead of
    restarting the clock at finish()."""
    import time as _t

    from geomesa_tpu.planning.errors import Deadline

    store = _store(metrics=MetricsRegistry())
    now = _t.monotonic()
    plan = store.planner.plan("ev", Q)
    burned = Deadline(start=now - 1.0, budget_s=0.5, cutoff=now - 0.5)
    fin = store.planner.submit_many([plan], deadlines=[burned])[0]
    with pytest.raises(QueryTimeout):
        fin()
    plan2 = store.planner.plan("ev", Q)
    fresh = Deadline(start=now, budget_s=30.0, cutoff=now + 30.0)
    out = store.planner.submit_many([plan2], deadlines=[fresh])[0]()
    assert len(out) == len(store.query("ev", Q))
    # non-simple plans (here a union) honor the anchor through their
    # synchronous execute() fallback too
    union_q = f"{Q} OR kind = 'c'"
    plan3 = store.planner.plan("ev", union_q)
    assert plan3.union is not None
    fin3 = store.planner.submit_many([plan3], deadlines=[burned])[0]
    with pytest.raises(QueryTimeout):
        fin3()
    out3 = store.planner.submit_many(
        [store.planner.plan("ev", union_q)], deadlines=[fresh]
    )[0]()
    assert len(out3) == len(store.query("ev", union_q))


def test_cancelled_future_does_not_poison_the_batch():
    """A client-side cancel() (disconnect) on one queued future must not
    fail the co-batched queries sharing its fused dispatch."""
    store = _store(metrics=MetricsRegistry())
    sched = QueryScheduler(store, ServingConfig())  # staged queue
    f1 = sched.submit("ev", Q)
    f2 = sched.submit("ev", Q)        # coalesces onto f1's slot
    f3 = sched.submit("ev", "kind = 'b'")
    assert f1.cancel()
    sched.start()
    assert len(f2.result(10)) == len(store.query("ev", Q))
    assert len(f3.result(10)) == len(store.query("ev", "kind = 'b'"))
    sched.close()


def test_no_coalescing_across_a_mutation():
    """Identical queries admitted on opposite sides of a committed write
    land in different mutation epochs: they must NOT share one result —
    the later submitter sees its own write."""
    reg = MetricsRegistry()
    store = _store(metrics=reg)
    e0 = store.planner.mutation_epoch
    sched = QueryScheduler(store, ServingConfig(), metrics=reg)  # staged
    f1 = sched.submit("ev", Q)
    sft = store.get_schema("ev")
    store.write("ev", FeatureCollection.from_columns(
        sft, ["w1", "w2"],
        {
            "kind": np.array(["a", "a"]),
            "dtg": np.full(2, np.datetime64("2024-01-02", "ms").astype(np.int64)),
            "geom": (np.array([1.0, 2.0]), np.array([1.0, 2.0])),
        },
    ))
    assert store.planner.mutation_epoch > e0
    f2 = sched.submit("ev", Q)  # same fingerprint, NEW epoch
    sched.start()
    r1, r2 = f1.result(10), f2.result(10)
    assert reg.counters.get("geomesa.serving.coalesced", 0) == 0
    assert r2 is not r1
    ids2 = set(np.asarray(r2.ids).tolist())
    assert {"w1", "w2"} <= ids2  # read-your-writes for the later caller
    sched.close()


def test_plan_errors_raise_at_submit():
    store = _store(metrics=MetricsRegistry())
    with QueryScheduler(store, ServingConfig()) as sched:
        with pytest.raises(KeyError):
            sched.submit("nope", Q)  # unknown type: caller-thread raise
        with pytest.raises(Exception):
            sched.submit("ev", "this is not ecql (")
        with pytest.raises(ValueError, match="sample"):
            # bad hints raise at submit too, never poisoning a batch
            sched.submit("ev", Q, hints=QueryHints(sample=5.0))


def test_execution_errors_land_on_the_future(monkeypatch):
    store = _store(metrics=MetricsRegistry())
    sched = QueryScheduler(store, ServingConfig())  # staged
    fut = sched.submit("ev", Q)  # planned against the healthy store

    def boom(*a, **k):
        raise RuntimeError("device gone")

    monkeypatch.setattr(store, "table", boom)  # dispatch-time failure
    sched.start()
    with pytest.raises(RuntimeError, match="device gone"):
        fut.result(10)
    sched.close()


def test_close_fails_pending_and_refuses_new():
    store = _store(metrics=MetricsRegistry())
    sched = QueryScheduler(store, ServingConfig())  # never started
    fut = sched.submit("ev", Q)
    sched.close()
    with pytest.raises(RuntimeError, match="closed"):
        fut.result(1)
    with pytest.raises(RuntimeError, match="closed"):
        sched.submit("ev", Q)


def test_queue_wait_attribution(ds):
    """Queue wait is attributed separately from scan time: the live
    histogram lands in metrics and the explain trace carries both."""
    reg = ds.metrics
    sched = ds.serve()
    exp = Explainer()
    sched.submit("ev", Q, explain=exp).result(10)
    sched.close()
    snap = reg.snapshot()
    assert snap["histograms"]["geomesa.serving.queue_wait"]["count"] >= 1
    line = next(l for l in exp.lines if l.strip().startswith("serving:"))
    assert "queue wait" in line and "scan" in line and "fused batch" in line
    # the device-scan trace reaches the caller's explainer even through
    # the fused dispatch (submit_many per-plan explains)
    assert any("Device scan" in l for l in exp.lines)


def test_admission_gap_drains_and_bounds(ds):
    """The fold's between-slice yield (docs/streaming.md "Incremental
    fold"): an idle queue returns immediately; a queue that cannot drain
    (unstarted dispatcher) returns False at the bound; once the
    dispatcher runs, the gap closes."""
    sched = QueryScheduler(ds, ServingConfig(window_ms=0.0))
    assert sched.admission_gap(0.01) is True  # idle: immediate
    fut = sched.submit("ev", Q)               # queued, nothing drains it
    t0 = time.perf_counter()
    assert sched.admission_gap(0.05) is False
    assert time.perf_counter() - t0 < 2.0     # bounded wait
    sched.start()
    assert sched.admission_gap(5.0) is True
    assert len(fut.result(10)) > 0
    sched.close()
