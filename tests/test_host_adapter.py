"""Second IndexAdapter implementation (SURVEY §2.2 'partial' row: the
SPI seam untested by a second impl): the pure-host backend must answer
every query exactly like the device-backed default."""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.storage.adapter import HostAdapter, IndexAdapter

DAY = 86400_000


@pytest.fixture(scope="module")
def pair():
    rng = np.random.default_rng(12)
    n = 4000
    sft_spec = "name:String:index=true,dtg:Date,*geom:Point:srid=4326"
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    cols = {
        "name": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
        "dtg": t0 + rng.integers(0, 30 * DAY, n),
        "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
    }
    stores = []
    for adapter in (None, HostAdapter()):
        ds = DataStore(adapter=adapter, tile=64)
        ds.create_schema(FeatureType.from_spec("t", sft_spec))
        ds.write("t", FeatureCollection.from_columns(
            ds.get_schema("t"), [str(i) for i in range(n)], dict(cols)))
        stores.append(ds)
    return stores


QUERIES = [
    "bbox(geom, -40, -20, 40, 20)",
    "bbox(geom, 0, 0, 90, 45) AND dtg DURING 2024-01-03T00:00:00Z/2024-01-12T00:00:00Z",
    "name = 'b'",
    "name = 'a' AND bbox(geom, -90, -45, 90, 45)",
    "INTERSECTS(geom, POLYGON((0 0, 60 0, 30 40, 0 0)))",
]


class TestHostAdapter:
    def test_protocol_conformance(self):
        assert isinstance(HostAdapter(), IndexAdapter)

    @pytest.mark.parametrize("q", QUERIES)
    def test_queries_match_device_backend(self, pair, q):
        dev, host = pair
        a = sorted(dev.query("t", q).ids.tolist())
        b = sorted(host.query("t", q).ids.tolist())
        assert a == b and len(a) > 0

    def test_aggregations_match(self, pair):
        dev, host = pair
        q = "bbox(geom, -60, -30, 60, 30)"
        assert dev.count("t", q) == host.count("t", q)
        ga = dev.density("t", q, envelope=(-60, -30, 60, 30), width=16, height=8)
        gb = host.density("t", q, envelope=(-60, -30, 60, 30), width=16, height=8)
        np.testing.assert_array_equal(ga, gb)
        assert dev.bounds("t", q) == host.bounds("t", q)

    def test_mutations_through_host_adapter(self, pair):
        _, host = pair
        from geomesa_tpu import geometry as geo

        n0 = len(host.features("t"))
        host.upsert("t", FeatureCollection.from_columns(
            host.get_schema("t"), ["0"],
            {"name": np.array(["z"]),
             "dtg": np.array([1704067200000]),
             "geom": (np.array([1.0]), np.array([1.0]))}))
        out = host.query("t", "name = 'z'")
        assert out.ids.tolist() == ["0"]
        assert len(host.features("t")) == n0
