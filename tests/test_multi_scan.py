"""Fused multi-query scan (round 5): scan_submit_many == per-query scans.

One kernel dispatch covers many queries' candidate blocks — slot i scans
block bids[i] under query qids[i]'s packed params (block_kernels.
block_scan_multi). The contract under test: for EVERY config mix, the
fused path returns exactly what per-query IndexTable.scan would."""

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu import geometry as geo
from geomesa_tpu.filter.predicates import BBox, During, Intersects
from geomesa_tpu.scan import block_kernels as bk


def make_store(n=60_000, seed=11, index="z3", mesh=None):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-60, 60, n)
    y = rng.uniform(-45, 45, n)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    t = t0 + rng.integers(0, 28 * 86400_000, n)
    sft = FeatureType.from_spec("pts", "dtg:Date,*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = index
    ds = DataStore(mesh=mesh)
    ds.create_schema(sft)
    fc = FeatureCollection.from_columns(sft, np.arange(n), {"dtg": t, "geom": (x, y)})
    ds.write("pts", fc, check_ids=False)
    return ds, t0


def assert_batched_equals_sequential(ds, type_name, queries):
    batched = ds.query_many(type_name, queries)
    for q, got in zip(queries, batched):
        want = ds.query(type_name, q)
        assert np.array_equal(
            np.sort(np.asarray(want.ids)), np.sort(np.asarray(got.ids))
        ), q
    assert sum(len(b) for b in batched) > 0


def rand_bbox(rng, span=25.0):
    x0 = rng.uniform(-60, 35)
    y0 = rng.uniform(-45, 20)
    return BBox("geom", x0, y0, x0 + rng.uniform(0.5, span), y0 + rng.uniform(0.5, span))


def assert_matches(table, cfgs):
    got = [f() for f in table.scan_submit_many(list(cfgs))]
    assert len(got) == len(cfgs)
    for cfg, (rows, certain) in zip(cfgs, got):
        er, ec = table.scan(cfg)
        assert np.array_equal(rows, er)
        assert np.array_equal(certain, ec)


class TestFusedScan:
    def test_z3_boxes_and_windows(self):
        ds, t0 = make_store()
        idx = next(i for i in ds.indexes("pts") if i.name == "z3")
        table = ds.table("pts", "z3")
        rng = np.random.default_rng(5)
        cfgs = []
        for _ in range(23):
            f = rand_bbox(rng)
            if rng.random() < 0.7:
                lo = t0 + rng.integers(0, 20 * 86400_000)
                f = f & During("dtg", lo, lo + rng.integers(3600_000, 7 * 86400_000))
            else:
                # whole-period window (z3 needs a time constraint at all)
                f = f & During("dtg", t0 - 86400_000, t0 + 40 * 86400_000)
            cfgs.append(idx.scan_config(f))
        assert_matches(table, cfgs)

    def test_z2_boxes(self):
        ds, _ = make_store(index="z2")
        idx = next(i for i in ds.indexes("pts") if i.name == "z2")
        rng = np.random.default_rng(6)
        assert_matches(ds.table("pts", "z2"), [idx.scan_config(rand_bbox(rng)) for _ in range(17)])

    def test_mixed_eligibility(self):
        """Disjoint, empty-candidate, PIP-edge polygon and plain box
        configs in one batch: each routes correctly and results stay in
        input order."""
        ds, _ = make_store(index="z2")
        idx = next(i for i in ds.indexes("pts") if i.name == "z2")
        table = ds.table("pts", "z2")
        rng = np.random.default_rng(7)
        tri = geo.from_wkt("POLYGON ((0 0, 24 4, 6 21, 0 0))")
        cfgs = [
            idx.scan_config(rand_bbox(rng)),
            idx.scan_config(BBox("geom", 120.0, 60.0, 130.0, 70.0)),  # empty region: no blocks
            idx.scan_config(Intersects("geom", tri)),  # PIP tier: per-query path
            idx.scan_config(rand_bbox(rng)),
            idx.scan_config(rand_bbox(rng)),
        ]
        assert_matches(table, [c for c in cfgs if c is not None])

    def test_single_member_group_falls_back(self):
        ds, _ = make_store(n=20_000, index="z2")
        idx = next(i for i in ds.indexes("pts") if i.name == "z2")
        rng = np.random.default_rng(8)
        assert_matches(ds.table("pts", "z2"), [idx.scan_config(rand_bbox(rng))])

    def test_delta_tier(self):
        """Un-compacted writes wrap the table in TieredTable: fused main
        scan + per-query host delta hits."""
        ds, t0 = make_store(n=30_000, index="z3")
        rng = np.random.default_rng(9)
        sft = ds.get_schema("pts")
        m = 4_000
        t = t0 + rng.integers(0, 28 * 86400_000, m)
        fc = FeatureCollection.from_columns(
            sft, 30_000 + np.arange(m),
            {"dtg": t, "geom": (rng.uniform(-60, 60, m), rng.uniform(-45, 45, m))},
        )
        ds.write("pts", fc, check_ids=False)
        idx = next(i for i in ds.indexes("pts") if i.name == "z3")
        table = ds.table("pts", "z3")
        from geomesa_tpu.storage.delta import TieredTable

        assert isinstance(table, TieredTable)
        cfgs = []
        for _ in range(9):
            lo = int(t0 + rng.integers(0, 20 * 86400_000))
            cfgs.append(idx.scan_config(
                rand_bbox(rng) & During("dtg", lo, lo + 3 * 86400_000)
            ))
        assert_matches(table, cfgs)

    def test_packed_time_store(self):
        from geomesa_tpu.index.z3 import PACKED_KEY

        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        ds2 = DataStore()
        sft2 = FeatureType.from_spec("pts", "dtg:Date,*geom:Point:srid=4326")
        sft2.user_data["geomesa.indices.enabled"] = "z3"
        sft2.user_data[PACKED_KEY] = "true"
        ds2.create_schema(sft2)
        rng = np.random.default_rng(10)
        n = 30_000
        t = t0 + rng.integers(0, 28 * 86400_000, n)
        ds2.write("pts", FeatureCollection.from_columns(
            sft2, np.arange(n),
            {"dtg": t, "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n))},
        ), check_ids=False)
        idx = next(i for i in ds2.indexes("pts") if i.name == "z3")
        cfgs = []
        for _ in range(11):
            f = rand_bbox(rng)
            lo = int(t0 + rng.integers(0, 20 * 86400_000))
            cfgs.append(idx.scan_config(f & During("dtg", lo, lo + 2 * 86400_000)))
        assert_matches(ds2.table("pts", "z3"), cfgs)

    def test_xz2_extent_store(self):
        """Fused scans on an EXTENT table: the inner plane is skipped
        (bbox-intersects can never certify), so the multi kernel's
        single-output variant must match per-query scans — including
        polygon INTERSECTS configs (extent kernels ignore poly edges in
        both paths)."""
        rng = np.random.default_rng(41)
        n = 20_000
        x0 = rng.uniform(-60, 59, n)
        y0 = rng.uniform(-45, 44, n)
        polys = geo.PackedGeometryColumn.from_boxes(
            x0, y0, x0 + rng.uniform(0.01, 0.8, n), y0 + rng.uniform(0.01, 0.6, n)
        )
        sft = FeatureType.from_spec("bld", "*geom:Polygon:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "xz2"
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("bld", FeatureCollection.from_columns(
            sft, np.arange(n), {"geom": polys}), check_ids=False)
        idx = next(i for i in ds.indexes("bld") if i.name == "xz2")
        tri = geo.from_wkt("POLYGON ((-20 -15, 25 -10, 0 30, -20 -15))")
        cfgs = [idx.scan_config(rand_bbox(rng)) for _ in range(9)]
        cfgs.append(idx.scan_config(Intersects("geom", tri)))
        cfgs.extend(idx.scan_config(rand_bbox(rng)) for _ in range(4))
        assert all(c is not None for c in cfgs)  # esp. the INTERSECTS case
        assert_matches(ds.table("bld", "xz2"), cfgs)

    def test_chunking_cap(self, monkeypatch):
        """With a tiny chunk shape the batch must split into many fused
        chunks (and broad members dispatch alone) — results unchanged."""
        from geomesa_tpu.storage import table as tbl

        monkeypatch.setattr(tbl, "FUSED_CHUNK_SLOTS", 8)
        monkeypatch.setattr(tbl, "FUSED_CHUNK_Q", 4)
        ds, _ = make_store(n=40_000, index="z2")
        idx = next(i for i in ds.indexes("pts") if i.name == "z2")
        rng = np.random.default_rng(31)
        cfgs = [idx.scan_config(rand_bbox(rng)) for _ in range(13)]
        # a broad query: nearly the whole extent -> blocks > cap/2
        cfgs.append(idx.scan_config(BBox("geom", -59.0, -44.0, 59.0, 44.0)))
        assert_matches(ds.table("pts", "z2"), cfgs)

    def test_host_adapter_passthrough(self):
        from geomesa_tpu.storage.adapter import HostAdapter

        ds, _ = make_store(n=20_000, index="z2")
        hs = DataStore(adapter=HostAdapter())
        sft = FeatureType.from_spec("pts", "dtg:Date,*geom:Point:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "z2"
        hs.create_schema(sft)
        hs.write("pts", ds.features("pts"), check_ids=False)
        idx = next(i for i in hs.indexes("pts") if i.name == "z2")
        rng = np.random.default_rng(12)
        assert_matches(hs.table("pts", "z2"), [idx.scan_config(rand_bbox(rng)) for _ in range(7)])


def _poly(kind, cx, cy, r, rng=None, holes=False):
    """Concave / convex / holed polygons (the PIP fuzz shapes of
    test_pip_kernel, round 6: now exercised through the FUSED path)."""
    if kind == "triangle":
        pts = [(cx - r, cy - r), (cx + r, cy - r), (cx, cy + r)]
    elif kind == "hex":
        a = np.linspace(0, 2 * np.pi, 7)[:-1] + (rng.uniform(0, 1) if rng else 0.3)
        pts = [(cx + r * np.cos(t), cy + 0.7 * r * np.sin(t)) for t in a]
    elif kind == "lshape":
        pts = [
            (cx - r, cy - r), (cx + r, cy - r), (cx + r, cy),
            (cx, cy), (cx, cy + r), (cx - r, cy + r),
        ]
    else:  # star-ish concave
        a = np.linspace(0, 2 * np.pi, 11)[:-1]
        rad = np.where(np.arange(10) % 2 == 0, r, 0.4 * r)
        pts = [(cx + rr * np.cos(t), cy + rr * np.sin(t)) for t, rr in zip(a, rad)]
    hh = (
        [[(cx - 0.3 * r, cy - 0.3 * r), (cx + 0.3 * r, cy - 0.3 * r),
          (cx, cy + 0.2 * r)]]
        if holes else None
    )
    return geo.Polygon(pts, holes=hh)


class TestFusedPip:
    """Round 6: polygon-INTERSECTS (device PIP) members fuse — the chunk
    carries a [Q, E, 128] edge stack and a per-slot selector. Contract:
    fused == per-query scan, bit-identical, for every polygon shape mix,
    and polygon batches actually take the fused dispatch."""

    def _spy(self, monkeypatch):
        calls = {"fused": 0, "edged": 0}
        orig = bk.block_scan_multi

        def spy(*a, **kw):
            calls["fused"] += 1
            if kw.get("n_edges", 0):
                calls["edged"] += 1
            return orig(*a, **kw)

        monkeypatch.setattr(bk, "block_scan_multi", spy)
        return calls

    @pytest.mark.parametrize("seed", range(8))
    def test_z2_polygon_batches(self, seed, monkeypatch):
        ds, _ = make_store(n=40_000, seed=60 + seed, index="z2")
        idx = next(i for i in ds.indexes("pts") if i.name == "z2")
        table = ds.table("pts", "z2")
        calls = self._spy(monkeypatch)
        rng = np.random.default_rng(6000 + seed)
        kinds = ["triangle", "hex", "lshape", "star"]
        cfgs = []
        for k in range(12):
            cx, cy = rng.uniform(-40, 40), rng.uniform(-30, 30)
            if k % 3 == 2:  # mixed chunk: boxes ride zero-edge slots
                cfgs.append(idx.scan_config(rand_bbox(rng, span=10)))
            else:
                p = _poly(kinds[(seed + k) % 4], cx, cy, rng.uniform(3, 8),
                          rng, holes=(k % 4 == 1))
                cfgs.append(idx.scan_config(Intersects("geom", p)))
        assert any(c.poly is not None for c in cfgs)
        assert_matches(table, cfgs)
        assert calls["edged"] >= 1, "polygon batch never took the fused PIP path"

    def test_z3_polygon_time_batches(self, monkeypatch):
        ds, t0 = make_store(n=40_000, seed=71, index="z3")
        idx = next(i for i in ds.indexes("pts") if i.name == "z3")
        calls = self._spy(monkeypatch)
        rng = np.random.default_rng(6100)
        cfgs = []
        for k in range(10):
            cx, cy = rng.uniform(-40, 40), rng.uniform(-30, 30)
            p = _poly(["star", "lshape"][k % 2], cx, cy, rng.uniform(3, 7), rng)
            lo = t0 + rng.integers(0, 20 * 86400_000)
            f = Intersects("geom", p) & During("dtg", lo, lo + 5 * 86400_000)
            cfgs.append(idx.scan_config(f))
        assert_matches(ds.table("pts", "z3"), cfgs)
        assert calls["edged"] >= 1

    def test_e_bucket_ladder(self):
        assert bk.fused_e_bucket(0) == 0
        assert bk.fused_e_bucket(1) == 16
        assert bk.fused_e_bucket(16) == 16
        assert bk.fused_e_bucket(17) == 64
        assert bk.fused_e_bucket(200) == 256
        # every pack_edges output fits a fused bucket
        assert bk.FUSED_E_BUCKETS[-1] == bk.E_BUCKETS[-1]

    def test_mixed_edge_sizes_and_bucket_grouping(self, monkeypatch):
        """Polygons with different edge counts in the SAME fused bucket
        zero-pad into one chunk; a bigger-bucket ring and the box members
        group separately (the E bucket is part of the variant key, so box
        slots never pay edge work) — results exact throughout. Raster
        approximations are disabled: this test pins the PIP edge-ladder
        grouping specifically (the raster tier has its own suite,
        test_raster_join.py)."""
        from geomesa_tpu.conf import RASTER_ENABLED
        from geomesa_tpu.filter import raster as fr

        monkeypatch.setattr(RASTER_ENABLED, "_override", False)
        fr.clear_cache()
        ds, _ = make_store(n=30_000, seed=75, index="z2")
        idx = next(i for i in ds.indexes("pts") if i.name == "z2")
        e_seen = []
        orig = bk.block_scan_multi

        def spy(*a, **kw):
            e_seen.append(kw.get("n_edges", 0))
            return orig(*a, **kw)

        monkeypatch.setattr(bk, "block_scan_multi", spy)
        rng = np.random.default_rng(6200)
        a = np.linspace(0, 2 * np.pi, 41)[:-1]
        ring = geo.Polygon([(10 * np.cos(t), 8 * np.sin(t)) for t in a])
        # 3-, 6- and 10-edge polygons all bucket to FUSED_E_BUCKETS[0]
        small = [
            _poly(k, rng.uniform(-30, 30), rng.uniform(-20, 20), 6.0, rng)
            for k in ("triangle", "lshape", "star", "triangle", "star", "lshape")
        ]
        cfgs = (
            [idx.scan_config(Intersects("geom", p)) for p in small]
            + [idx.scan_config(Intersects("geom", ring))]
            + [idx.scan_config(rand_bbox(rng, span=8)) for _ in range(6)]
        )
        assert bk.n_edges_of(cfgs[len(small)].poly) > bk.FUSED_E_BUCKETS[0]
        assert_matches(ds.table("pts", "z2"), cfgs)
        # the small polygons fused at the smallest bucket; no box chunk
        # ever dispatched with edge work
        assert bk.FUSED_E_BUCKETS[0] in e_seen
        assert all(e in (0,) + bk.FUSED_E_BUCKETS for e in e_seen)


class TestFusedExtentXZ3:
    """XZ3 (extent + time) batches fuse on the wide-only plane layout
    (skip_inner_plane): fused == per-query, including polygon-INTERSECTS
    configs, whose edges extent kernels ignore in both paths."""

    def test_xz3_box_time_batch(self, monkeypatch):
        rng = np.random.default_rng(81)
        n = 15_000
        t0 = np.datetime64("2024-03-01T00:00:00", "ms").astype(np.int64)
        sft = FeatureType.from_spec("tx", "dtg:Date,*geom:Polygon:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "xz3"
        ds = DataStore()
        ds.create_schema(sft)
        x0 = rng.uniform(-60, 58, n)
        y0 = rng.uniform(-45, 43, n)
        col = geo.PackedGeometryColumn.from_boxes(
            x0, y0, x0 + rng.uniform(0.01, 1.0, n), y0 + rng.uniform(0.01, 0.8, n)
        )
        t = t0 + rng.integers(0, 30 * 86400_000, n)
        ds.write("tx", FeatureCollection.from_columns(
            sft, np.arange(n), {"dtg": t, "geom": col}), check_ids=False)
        idx = next(i for i in ds.indexes("tx") if i.name == "xz3")
        calls = {"fused": 0}
        orig = bk.block_scan_multi

        def spy(*a, **kw):
            calls["fused"] += 1
            assert kw.get("n_edges", 0) == 0  # extent chunks ride E = 0
            return orig(*a, **kw)

        monkeypatch.setattr(bk, "block_scan_multi", spy)
        tri = geo.from_wkt("POLYGON ((-20 -15, 25 -10, 0 30, -20 -15))")
        cfgs = []
        for k in range(11):
            f = rand_bbox(rng, span=15) if k % 4 else Intersects("geom", tri)
            lo = t0 + rng.integers(0, 20 * 86400_000)
            cfgs.append(idx.scan_config(
                f & During("dtg", int(lo), int(lo) + 6 * 86400_000)
            ))
        assert all(c is not None for c in cfgs)
        assert_matches(ds.table("tx", "xz3"), cfgs)
        assert calls["fused"] >= 1, "xz3 batch never fused"


class TestPlannerSubmitMany:
    def test_mixed_types_and_indexes(self):
        """submit_many groups per (type, index) and falls back for
        non-simple plans; results equal sequential execution."""
        ds, t0 = make_store(n=25_000, index="z3,z2")
        sft2 = FeatureType.from_spec("aux", "dtg:Date,*geom:Point:srid=4326")
        sft2.user_data["geomesa.indices.enabled"] = "z2"
        ds.create_schema(sft2)
        rng = np.random.default_rng(21)
        m = 8_000
        ds.write("aux", FeatureCollection.from_columns(
            sft2, np.arange(m),
            {"dtg": t0 + rng.integers(0, 86400_000, m),
             "geom": (rng.uniform(-60, 60, m), rng.uniform(-45, 45, m))},
        ), check_ids=False)
        queries = [
            ("pts", "bbox(geom, -20, -20, 10, 10)"),
            ("aux", "bbox(geom, -10, -30, 30, 0)"),
            ("pts", "bbox(geom, 0, 0, 25, 25) AND dtg DURING 2024-01-02T00:00:00Z/2024-01-06T00:00:00Z"),
            ("aux", "bbox(geom, -50, -40, -20, -10)"),
            ("pts", "IN ('3', '99')"),
            ("pts", "bbox(geom, 5, -40, 45, 5)"),
        ]
        plans = [ds.planner.plan(t, q) for t, q in queries]
        batched = [f() for f in ds.planner.submit_many(plans)]
        for (t, q), got in zip(queries, batched):
            want = ds.query(t, q)
            assert np.array_equal(
                np.sort(np.asarray(want.ids)), np.sort(np.asarray(got.ids))
            )
        assert sum(len(b) for b in batched) > 0


class TestMeshFused:
    def test_query_many_on_mesh_store(self):
        """A mesh-sharded store's batches dispatch through the shard_map
        FUSED kernel (round 6: one mesh-wide dispatch per chunk, one
        batched plane pull) — batched results equal sequential ones."""
        from geomesa_tpu.parallel import dtable, make_mesh

        ds, _ = make_store(n=30_000, seed=51, index="z2", mesh=make_mesh(8))
        calls = {"n": 0}
        orig = dtable._dist_scan_multi

        def spy(*a, **kw):
            calls["n"] += 1
            return orig(*a, **kw)

        dtable._dist_scan_multi = spy
        try:
            rng = np.random.default_rng(52)
            qs = []
            for _ in range(12):
                qx, qy = rng.uniform(-55, 30), rng.uniform(-40, 15)
                w, h = rng.uniform(1, 15), rng.uniform(1, 10)
                qs.append(f"bbox(geom, {qx}, {qy}, {qx + w}, {qy + h})")
            assert_batched_equals_sequential(ds, "pts", qs)
        finally:
            dtable._dist_scan_multi = orig
        assert calls["n"] >= 1, "mesh batch never took the fused dispatch"

    def test_mesh_fused_matches_single_device(self):
        """mesh4 fused == single-device fused == sequential, on a batch
        mixing boxes and polygon-PIP members (the differential the round-6
        acceptance pins)."""
        from geomesa_tpu.parallel import make_mesh

        ds_m, _ = make_store(n=25_000, seed=55, index="z2", mesh=make_mesh(4))
        ds_s, _ = make_store(n=25_000, seed=55, index="z2")
        idx_m = next(i for i in ds_m.indexes("pts") if i.name == "z2")
        idx_s = next(i for i in ds_s.indexes("pts") if i.name == "z2")
        rng = np.random.default_rng(56)
        filters = []
        for k in range(10):
            cx, cy = rng.uniform(-40, 40), rng.uniform(-30, 30)
            if k % 3 == 0:
                filters.append(Intersects("geom", _poly(
                    ["star", "lshape", "hex"][k % 3], cx, cy, 6.0, rng
                )))
            else:
                filters.append(rand_bbox(rng, span=10))
        cfg_m = [idx_m.scan_config(f) for f in filters]
        cfg_s = [idx_s.scan_config(f) for f in filters]
        got_m = [f() for f in ds_m.table("pts", "z2").scan_submit_many(cfg_m)]
        got_s = [f() for f in ds_s.table("pts", "z2").scan_submit_many(cfg_s)]
        for cm, cs, (rm, km), (rs, ks) in zip(cfg_m, cfg_s, got_m, got_s):
            er, ec = ds_m.table("pts", "z2").scan(cm)
            assert np.array_equal(rm, er) and np.array_equal(km, ec)
            # same seed -> same data -> identical ordinal sets and
            # certainty across the two layouts
            assert np.array_equal(rm, rs)
            assert np.array_equal(km, ks)

    def test_mesh_zero_recompiles_warm_fused_batch(self):
        """After ONE fused batch (the warmup dispatch for its chunk
        variants), re-running the same mixed batch triggers NO new XLA
        compiles — the round-6 mesh-fusion acceptance bar (the compile
        key is the static (slots, Q, columns, flags, E) tuple)."""
        import logging

        import jax

        from geomesa_tpu.parallel import make_mesh

        ds, _ = make_store(n=30_000, seed=57, index="z2", mesh=make_mesh(4))
        rng = np.random.default_rng(58)
        qs = []
        for k in range(10):
            if k % 3 == 0:
                cx, cy = rng.uniform(-40, 40), rng.uniform(-30, 30)
                p = _poly("star", cx, cy, 6.0, rng)
                qs.append(f"INTERSECTS(geom, {p.wkt})")
            else:
                qx, qy = rng.uniform(-55, 30), rng.uniform(-40, 15)
                qs.append(f"bbox(geom, {qx}, {qy}, {qx + 9}, {qy + 7})")
        ds.query_many("pts", qs)  # warm: compiles the fused chunk variants
        jax.config.update("jax_log_compiles", True)
        records: list = []
        handler = logging.Handler()
        handler.emit = lambda r: records.append(r.getMessage())
        loggers = [logging.getLogger(n) for n in (
            "jax._src.dispatch", "jax._src.interpreters.pxla", "jax._src.compiler"
        )]
        prior = [lg.level for lg in loggers]
        for lg in loggers:
            lg.addHandler(handler)
            lg.setLevel(logging.DEBUG)
        try:
            ds.query_many("pts", qs)
        finally:
            jax.config.update("jax_log_compiles", False)
            for lg, lvl in zip(loggers, prior):
                lg.removeHandler(handler)
                lg.setLevel(lvl)
        compiles = [m for m in records if "Compiling" in m]
        assert compiles == [], f"unexpected recompiles: {compiles}"

    def test_indexed_join_on_mesh_store(self):
        """spatial_join_indexed against a mesh-sharded point store (the
        shard_map scan fallback) must produce exactly the host grid
        join's pairs."""
        from geomesa_tpu.parallel import make_mesh
        from geomesa_tpu.sql import spatial_join, spatial_join_indexed

        ds, _ = make_store(n=25_000, seed=53, index="z2", mesh=make_mesh(8))
        rng = np.random.default_rng(54)
        npoly = 24
        px0 = rng.uniform(-55, 35, npoly)
        py0 = rng.uniform(-40, 25, npoly)
        pw = rng.uniform(1, 14, npoly)
        ph = rng.uniform(1, 9, npoly)
        polys = geo.PackedGeometryColumn.from_boxes(px0, py0, px0 + pw, py0 + ph)
        gsft = FeatureType.from_spec("adm", "*geom:Polygon:srid=4326")
        pfc = FeatureCollection.from_columns(gsft, np.arange(npoly), {"geom": polys})
        li, ri = spatial_join_indexed(ds, "pts", pfc, "contains")
        hl, hr = spatial_join(pfc, ds.features("pts"), "contains")
        assert set(zip(li.tolist(), ri.tolist())) == set(zip(hl.tolist(), hr.tolist()))
        assert len(li) > 0


class TestMultiKernelParity:
    """Pallas-interpret vs XLA parity for the fused kernel itself."""

    SUB = 256

    def _cols(self, nb=4, seed=13):
        rng = np.random.default_rng(seed)
        import jax.numpy as jnp

        x = rng.uniform(-50, 50, (nb, self.SUB, 128)).astype(np.float32)
        y = rng.uniform(-50, 50, (nb, self.SUB, 128)).astype(np.float32)
        return tuple(jnp.asarray(a) for a in (x, y))

    def test_interpret_parity_boxes(self):
        cols3 = self._cols()
        q = 3
        boxes = np.zeros((bk.bucket_q(q), 8, bk.LANES), np.float32)
        wins = np.zeros((bk.bucket_q(q), 8, bk.LANES), np.int32)
        rng = np.random.default_rng(14)
        for k in range(q):
            x0, y0 = rng.uniform(-40, 20, 2)
            wide = np.array([[x0, y0, x0 + 25, y0 + 25]])
            inner = wide + np.array([[1.0, 1.0, -1.0, -1.0]])
            boxes[k] = bk.pack_boxes(wide, inner)
            wins[k] = bk.pack_windows(None, None)
        bids = np.array([0, 1, 2, 3, 0, 2, 1, 3], np.int32)
        qids = np.array([0, 0, 0, 1, 1, 2, 2, 2], np.int32)
        kw = dict(col_names=("x", "y"), has_boxes=True, has_windows=False, extent=False)
        w_ref, i_ref = bk._xla_block_scan_multi(cols3, bids, qids, boxes, wins, **kw)
        w_got, i_got = bk._pallas_block_scan_multi(
            cols3, bids, qids, boxes, wins, interpret=True, **kw
        )
        assert np.array_equal(np.asarray(w_ref), np.asarray(w_got))
        assert np.array_equal(np.asarray(i_ref), np.asarray(i_got))

    def test_interpret_parity_extent_skip_inner(self):
        """Extent mode: the fused kernel emits ONE plane (skip_inner);
        Pallas-interpret must match the vmapped XLA fallback."""
        import jax.numpy as jnp

        rng = np.random.default_rng(16)
        nb = 3
        cols3 = tuple(
            jnp.asarray(rng.uniform(-50, 50, (nb, self.SUB, 128)).astype(np.float32))
            for _ in range(4)
        )
        q = 2
        boxes = np.zeros((bk.bucket_q(q), 8, bk.LANES), np.float32)
        wins = np.zeros((bk.bucket_q(q), 8, bk.LANES), np.int32)
        for k in range(q):
            xx, yy = rng.uniform(-40, 10, 2)
            boxes[k] = bk.pack_boxes(np.array([[xx, yy, xx + 30, yy + 25]]), None)
            wins[k] = bk.pack_windows(None, None)
        bids = np.array([0, 1, 2, 2, 1], np.int32)
        qids = np.array([0, 0, 1, 0, 1], np.int32)
        kw = dict(
            col_names=("gxmax", "gxmin", "gymax", "gymin"),
            has_boxes=True, has_windows=False, extent=True,
        )
        w_ref, i_ref = bk._xla_block_scan_multi(cols3, bids, qids, boxes, wins, **kw)
        w_got, i_got = bk._pallas_block_scan_multi(
            cols3, bids, qids, boxes, wins, interpret=True, **kw
        )
        assert i_ref is None and i_got is None
        assert np.array_equal(np.asarray(w_ref), np.asarray(w_got))

    def test_interpret_parity_pip_fused(self):
        """PIP-fused multi kernel: Pallas-interpret == XLA, with a mixed
        chunk (polygon slots + box slots selected by spip)."""
        cols3 = self._cols(seed=17)
        q = 3
        E = 16
        boxes = np.zeros((bk.bucket_q(q), 8, bk.LANES), np.float32)
        wins = np.zeros((bk.bucket_q(q), 8, bk.LANES), np.int32)
        edges = np.zeros((bk.bucket_q(q), E, bk.LANES), np.float32)
        rng = np.random.default_rng(18)
        tri = geo.from_wkt("POLYGON ((-30 -20, 20 -25, 5 30, -30 -20))")
        packed = bk.pack_edges(tri)
        assert packed is not None and packed.shape[0] == E
        for k in range(q):
            x0, y0 = rng.uniform(-40, 10, 2)
            boxes[k] = bk.pack_boxes(np.array([[x0, y0, x0 + 25, y0 + 20]]), None)
            wins[k] = bk.pack_windows(None, None)
        edges[1] = packed  # query 1 is the polygon; 0 and 2 stay boxes
        bids = np.array([0, 1, 2, 3, 0, 2, 1, 3], np.int32)
        qids = np.array([0, 0, 1, 1, 1, 2, 2, 2], np.int32)
        spip = (qids == 1).astype(np.int32)
        kw = dict(
            col_names=("x", "y"), has_boxes=True, has_windows=False,
            extent=False, n_edges=E,
        )
        w_ref, i_ref = bk._xla_block_scan_multi(
            cols3, bids, qids, boxes, wins, edges, spip, **kw
        )
        w_got, i_got = bk._pallas_block_scan_multi(
            cols3, bids, qids, boxes, wins, edges, spip, interpret=True, **kw
        )
        assert np.array_equal(np.asarray(w_ref), np.asarray(w_got))
        assert np.array_equal(np.asarray(i_ref), np.asarray(i_got))
        # and the polygon slots equal the single-query PIP kernel
        sl = qids == 1
        w_s, i_s = bk._xla_block_scan(
            cols3, bids[sl], boxes[1], wins[1], edges[1],
            col_names=("x", "y"), has_boxes=True, has_windows=False,
            extent=False, n_edges=E,
        )
        assert np.array_equal(np.asarray(w_ref)[sl], np.asarray(w_s))
        assert np.array_equal(np.asarray(i_ref)[sl], np.asarray(i_s))

    def test_slotwise_equals_single_kernel(self):
        """Each fused slot must equal the single-query kernel run with that
        slot's params — the fused grid is just a re-indexed batch."""
        cols3 = self._cols()
        rng = np.random.default_rng(15)
        x0, y0 = -10.0, -5.0
        b0 = bk.pack_boxes(np.array([[x0, y0, x0 + 30, y0 + 20]]), None)
        b1 = bk.pack_boxes(np.array([[-40.0, -40.0, 0.0, 0.0]]), None)
        wins = bk.pack_windows(None, None)
        boxes_m = np.zeros((8, 8, bk.LANES), np.float32)
        wins_m = np.zeros((8, 8, bk.LANES), np.int32)
        boxes_m[0], boxes_m[1] = b0, b1
        wins_m[0] = wins_m[1] = wins
        bids = np.array([0, 1, 2, 3, 1, 2], np.int32)
        qids = np.array([0, 0, 0, 1, 1, 1], np.int32)
        kw = dict(col_names=("x", "y"), has_boxes=True, has_windows=False, extent=False)
        w_m, i_m = bk._xla_block_scan_multi(cols3, bids, qids, boxes_m, wins_m, **kw)
        for q, params in ((0, b0), (1, b1)):
            sl = qids == q
            w_s, i_s = bk._xla_block_scan(
                cols3, bids[sl], params, wins, **kw
            )
            assert np.array_equal(np.asarray(w_m)[sl], np.asarray(w_s))
            assert np.array_equal(np.asarray(i_m)[sl], np.asarray(i_s))
