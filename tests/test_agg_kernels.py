"""Aggregation kernels: XLA vs Pallas-interpret parity + no-predicate masks.

The Pallas TPU path cannot compile on CPU, but interpret mode runs the
same kernel logic (including the MXU one-hot matmul histogram and the
per-slot bounds blocks); parity with the XLA block-gather implementations
pins the contract. TPU-compiled parity is asserted by scripts/probe_agg.py
on hardware (see PERF.md).
"""

import numpy as np
import pytest

import jax

from geomesa_tpu.scan import aggregations as agg
from geomesa_tpu.scan import block_kernels as bk

SUB = 32  # 4096-row blocks
NB = 8
N = NB * SUB * bk.LANES


@pytest.fixture(scope="module")
def cols3():
    rng = np.random.default_rng(5)
    x = rng.uniform(-50, 50, N).astype(np.float32)
    y = rng.uniform(-50, 50, N).astype(np.float32)
    tb = rng.integers(100, 104, N).astype(np.int32)
    to = rng.integers(0, 1000, N).astype(np.int32)
    # sentinel-pad the tail like a real table
    x[-500:] = np.inf
    y[-500:] = np.inf
    tb[-500:] = -1
    shape = (NB, SUB, bk.LANES)
    return {
        "tbin": jax.numpy.asarray(tb.reshape(shape)),
        "toff": jax.numpy.asarray(to.reshape(shape)),
        "x": jax.numpy.asarray(x.reshape(shape)),
        "y": jax.numpy.asarray(y.reshape(shape)),
    }


NAMES = ("tbin", "toff", "x", "y")
BOXES = bk.pack_boxes(np.array([[-20.0, -15.0, 25.0, 30.0]]), None)
WINS = bk.pack_windows(np.array([[101, 102, 0, 700]]), None)


def _args(cols3):
    bids, _ = bk.pad_bids(np.array([0, 2, 3, 5, 7]), NB, pad=-1)
    return tuple(cols3[k] for k in NAMES), bids


class TestPallasInterpretParity:
    def test_density(self, cols3):
        cols, bids = _args(cols3)
        gb = np.array([-30, -30, 40, 40], np.float32)
        kw = dict(col_names=NAMES, has_boxes=True, has_windows=True,
                  extent=False, width=96, height=48)
        ref = agg._xla_density(cols, bids, BOXES, WINS, gb, **kw)
        got = agg._pallas_density(
            cols, bids, BOXES, WINS, gb, interpret=True, chunk=SUB, **kw
        )
        assert np.array_equal(np.asarray(ref), np.asarray(got))
        assert np.asarray(ref).sum() > 0

    def test_density_nonaligned_grid(self, cols3):
        cols, bids = _args(cols3)
        gb = np.array([-50, -50, 50, 50], np.float32)
        kw = dict(col_names=NAMES, has_boxes=True, has_windows=False,
                  extent=False, width=33, height=17)
        ref = agg._xla_density(cols, bids, BOXES, WINS, gb, **kw)
        got = agg._pallas_density(
            cols, bids, BOXES, WINS, gb, interpret=True, chunk=SUB, **kw
        )
        assert got.shape == (17, 33)
        assert np.array_equal(np.asarray(ref), np.asarray(got))

    def test_bounds(self, cols3):
        cols, bids = _args(cols3)
        kw = dict(col_names=NAMES, has_boxes=True, has_windows=True, extent=False)
        ref = np.asarray(agg._xla_bounds(cols, bids, BOXES, WINS, **kw))
        got = np.asarray(agg._pallas_bounds(cols, bids, BOXES, WINS, interpret=True, **kw))
        assert np.allclose(ref, got)
        cnt, env = agg.reduce_bounds(got, 5)
        assert cnt > 0 and env is not None

    def test_scan_planes(self, cols3):
        cols, _ = _args(cols3)
        bids, _ = bk.pad_bids(np.array([1, 4, 6]), NB)
        kw = dict(col_names=NAMES, has_boxes=True, has_windows=True, extent=False)
        w_ref, i_ref = bk._xla_block_scan(cols, bids, BOXES, WINS, **kw)
        w_got, i_got = bk._pallas_block_scan(cols, bids, BOXES, WINS, interpret=True, **kw)
        assert np.array_equal(np.asarray(w_ref), np.asarray(w_got))
        assert np.array_equal(np.asarray(i_ref), np.asarray(i_got))


class TestNoPredicateMask:
    def test_validity_mask_excludes_sentinels(self, cols3):
        cols, bids = _args(cols3)
        kw = dict(col_names=NAMES, has_boxes=False, has_windows=False, extent=False)
        stats = np.asarray(agg._xla_bounds(cols, bids, BOXES, WINS, **kw))
        cnt, env = agg.reduce_bounds(stats, 5)
        # block 7 holds the 500 sentinel rows: they must not count and must
        # not blow the envelope to +/-inf
        assert cnt > 0
        assert np.isfinite(env).all()

    def test_include_density_api(self):
        from geomesa_tpu import DataStore, FeatureCollection, FeatureType

        rng = np.random.default_rng(6)
        n = 3000
        sft = FeatureType.from_spec("d", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        fc = FeatureCollection.from_columns(
            sft, np.arange(n),
            {"dtg": t0 + rng.integers(0, 86400_000, n),
             "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))},
        )
        ds.write("d", fc, check_ids=False)
        grid = ds.density("d", envelope=(-10, -10, 10, 10), width=16, height=16)
        assert grid.sum() == n
