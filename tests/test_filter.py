"""Filter model tests: ECQL parsing, columnar evaluation vs hand-computed
truth, and extraction algebra (geometries / intervals / ids / bounds).

Reference analogues: geomesa-filter's FilterHelperTest / ECQL-driven tests.
"""

import numpy as np
import pytest

from geomesa_tpu import geometry as geo
from geomesa_tpu import filter as flt


def batch(n=10, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "geom": flt.PointColumn(rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
        "dtg": rng.integers(1_500_000_000_000, 1_600_000_000_000, n),
        "age": rng.integers(0, 100, n).astype(np.int32),
        "score": rng.uniform(0, 1, n),
        "name": np.array([f"user{i % 3}" for i in range(n)]),
        "__id__": np.array([f"fid{i}" for i in range(n)]),
    }


class TestEcqlParse:
    def test_bbox(self):
        f = flt.parse("BBOX(geom, -10, -5, 10, 5)")
        assert f == flt.BBox("geom", -10, -5, 10, 5)

    def test_during(self):
        f = flt.parse("dtg DURING 2018-01-01T00:00:00Z/2018-01-08T00:00:00Z")
        assert isinstance(f, flt.During)
        assert f.lo_ms == 1514764800000
        assert f.hi_ms == 1514764800000 + 7 * 86400000

    def test_and_or_not_precedence(self):
        f = flt.parse("age > 5 AND age < 10 OR NOT name = 'x'")
        assert isinstance(f, flt.Or)
        assert isinstance(f.filters[0], flt.And)
        assert isinstance(f.filters[1], flt.Not)

    def test_intersects_wkt(self):
        f = flt.parse("INTERSECTS(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))")
        assert isinstance(f, flt.Intersects)
        assert isinstance(f.geom, geo.Polygon)
        assert f.geom.bounds() == (0, 0, 10, 10)

    def test_dwithin_units(self):
        f = flt.parse("DWITHIN(geom, POINT (1 2), 111320, meters)")
        assert isinstance(f, flt.DWithin)
        assert f.dist == pytest.approx(1.0)

    def test_in_and_id_in(self):
        f = flt.parse("name IN ('a', 'b')")
        assert f == flt.In("name", ("a", "b"))
        f2 = flt.parse("IN ('fid1', 'fid2')")
        assert f2 == flt.IdFilter(("fid1", "fid2"))

    def test_between_dates(self):
        f = flt.parse("dtg BETWEEN '2018-01-01T00:00:00' AND '2018-02-01T00:00:00'")
        assert isinstance(f, flt.Between)
        assert isinstance(f.lo, int) and f.lo == 1514764800000

    def test_like_null_include(self):
        assert flt.parse("name LIKE 'user%'") == flt.Like("name", "user%")
        assert flt.parse("name IS NULL") == flt.IsNull("name")
        assert flt.parse("INCLUDE") is flt.INCLUDE
        assert flt.parse("EXCLUDE") is flt.EXCLUDE

    def test_errors(self):
        with pytest.raises(ValueError):
            flt.parse("BBOX(geom, 1, 2)")
        with pytest.raises(ValueError):
            flt.parse("age >")
        with pytest.raises(ValueError):
            flt.parse("age = 5 garbage")


class TestEvaluate:
    def test_bbox_points(self):
        b = batch(500)
        f = flt.parse("BBOX(geom, -50, -20, 30, 40)")
        got = f.evaluate(b)
        x, y = b["geom"].x, b["geom"].y
        truth = (x >= -50) & (x <= 30) & (y >= -20) & (y <= 40)
        assert np.array_equal(got, truth)

    def test_temporal_and_attr(self):
        b = batch(500)
        lo, hi = 1_520_000_000_000, 1_560_000_000_000
        f = flt.parse(
            f"dtg DURING 2018-03-02T14:13:20Z/2019-06-09T16:53:20Z AND age >= 50"
        )
        got = f.evaluate(b)
        truth = (b["dtg"] >= lo) & (b["dtg"] < hi) & (b["age"] >= 50)
        assert np.array_equal(got, truth)

    def test_or_not(self):
        b = batch(200)
        f = flt.parse("age < 10 OR NOT score <= 0.5")
        truth = (b["age"] < 10) | ~(b["score"] <= 0.5)
        assert np.array_equal(f.evaluate(b), truth)

    def test_string_ops(self):
        b = batch(30)
        assert np.array_equal(
            flt.parse("name = 'user1'").evaluate(b), b["name"] == "user1"
        )
        assert np.array_equal(
            flt.parse("name IN ('user0', 'user2')").evaluate(b),
            np.isin(b["name"], ["user0", "user2"]),
        )
        assert np.array_equal(
            flt.parse("name LIKE 'user_'").evaluate(b), np.ones(30, dtype=bool)
        )

    def test_id_filter(self):
        b = batch(10)
        got = flt.parse("IN ('fid2', 'fid5')").evaluate(b)
        assert list(np.nonzero(got)[0]) == [2, 5]

    def test_intersects_points(self):
        b = batch(300)
        poly = geo.Polygon([(-50, -50), (50, -50), (0, 60)])
        f = flt.Intersects("geom", poly)
        got = f.evaluate(b)
        truth = geo.points_in_polygon(b["geom"].x, b["geom"].y, poly)
        # boundary-inclusive semantics may add grazing points; interior match
        assert np.array_equal(got & truth, truth)
        assert (got & ~truth).sum() <= 2

    def test_packed_geometry_column(self):
        polys = [geo.box(i * 10, 0, i * 10 + 5, 5) for i in range(5)]
        b = {"geom": geo.PackedGeometryColumn.from_geometries(polys)}
        got = flt.parse("BBOX(geom, 12, 1, 23, 4)").evaluate(b)
        assert list(got) == [False, True, True, False, False]


class TestExtraction:
    def test_geometries_simple_bbox(self):
        f = flt.parse("BBOX(geom, -10, -5, 10, 5) AND age > 3")
        fv = flt.extract_geometries(f, "geom")
        assert fv.precise and len(fv.values) == 1
        assert fv.values[0].bounds() == (-10, -5, 10, 5)

    def test_geometries_and_intersection(self):
        f = flt.parse("BBOX(geom, 0, 0, 10, 10) AND BBOX(geom, 5, 5, 20, 20)")
        fv = flt.extract_geometries(f, "geom")
        assert len(fv.values) == 1
        assert fv.values[0].bounds() == (5, 5, 10, 10)

    def test_geometries_disjoint_and(self):
        f = flt.parse("BBOX(geom, 0, 0, 1, 1) AND BBOX(geom, 5, 5, 6, 6)")
        assert flt.extract_geometries(f, "geom").disjoint

    def test_geometries_or_union(self):
        f = flt.parse("BBOX(geom, 0, 0, 1, 1) OR BBOX(geom, 5, 5, 6, 6)")
        fv = flt.extract_geometries(f, "geom")
        assert len(fv.values) == 2

    def test_geometries_or_unconstrained_branch(self):
        f = flt.parse("BBOX(geom, 0, 0, 1, 1) OR age > 5")
        assert flt.extract_geometries(f, "geom").empty

    def test_polygon_kept_inside_box(self):
        f = flt.parse(
            "BBOX(geom, -100, -100, 100, 100) AND "
            "INTERSECTS(geom, POLYGON ((0 0, 10 0, 5 10, 0 0)))"
        )
        fv = flt.extract_geometries(f, "geom")
        assert len(fv.values) == 1
        assert isinstance(fv.values[0], geo.Polygon)
        assert fv.values[0].bounds() == (0, 0, 10, 10)
        assert fv.precise

    def test_intervals(self):
        f = flt.parse(
            "dtg DURING 2018-01-01T00:00:00Z/2018-02-01T00:00:00Z AND "
            "dtg DURING 2018-01-15T00:00:00Z/2018-03-01T00:00:00Z"
        )
        fv = flt.extract_intervals(f, "dtg")
        assert len(fv.values) == 1
        iv = fv.values[0]
        assert iv.lo == flt.parse_dt_millis("2018-01-15T00:00:00")
        assert iv.hi == flt.parse_dt_millis("2018-02-01T00:00:00")

    def test_intervals_one_sided(self):
        f = flt.parse("dtg AFTER 2018-01-01T00:00:00Z")
        fv = flt.extract_intervals(f, "dtg")
        assert len(fv.values) == 1
        assert fv.values[0].lo == flt.parse_dt_millis("2018-01-01T00:00:00") + 1

    def test_intervals_or_merged(self):
        f = flt.parse(
            "dtg DURING 2018-01-01T00:00:00Z/2018-01-10T00:00:00Z OR "
            "dtg DURING 2018-01-05T00:00:00Z/2018-01-20T00:00:00Z"
        )
        fv = flt.extract_intervals(f, "dtg")
        assert len(fv.values) == 1

    def test_ids(self):
        f = flt.parse("IN ('a', 'b', 'c') AND IN ('b', 'c', 'd')")
        assert flt.extract_ids(f).values == ["b", "c"]

    def test_attribute_bounds(self):
        f = flt.parse("age > 5 AND age <= 20")
        fv = flt.extract_attribute_bounds(f, "age")
        assert len(fv.values) == 1
        b = fv.values[0]
        assert (b.lo, b.lo_inclusive, b.hi, b.hi_inclusive) == (5, False, 20, True)

    def test_attribute_bounds_disjoint(self):
        f = flt.parse("age > 20 AND age < 10")
        assert flt.extract_attribute_bounds(f, "age").disjoint


class TestPackedBoxIntersectsFastTier:
    """Vectorized vertex-accept tier for arbitrary-polygon columns vs
    per-geometry brute force."""

    def test_matches_brute_force_on_triangles(self):
        import time

        from geomesa_tpu import geometry as geo
        from geomesa_tpu.filter.predicates import _packed_box_intersects

        rng = np.random.default_rng(0)
        n = 20_000
        cx, cy = rng.uniform(-50, 50, n), rng.uniform(-30, 30, n)
        tris = []
        for i in range(n):  # irregular triangles: never classified as rects
            a = rng.uniform(0, 2 * np.pi, 3)
            r = rng.uniform(0.01, 0.3, 3)
            ring = np.stack([cx[i] + r * np.cos(a), cy[i] + r * np.sin(a)], 1)
            tris.append(geo.Polygon(np.concatenate([ring, ring[:1]])))
        col = geo.PackedGeometryColumn.from_geometries(tris)
        q = np.array([-10.0, -5.0, 15.0, 10.0])
        bx = geo.box(*q)
        got = _packed_box_intersects(col, q, bx)
        want = np.array([geo.intersects(t, bx) for t in tris])
        np.testing.assert_array_equal(got, want)

    def test_vertex_free_overlaps_still_exact(self):
        from geomesa_tpu import geometry as geo
        from geomesa_tpu.filter.predicates import _packed_box_intersects

        # big diamond fully containing the query rect (no vertex inside),
        # plus a diamond whose edge crosses the rect corner region, plus a
        # diamond whose BBOX overlaps the rect corner while its body stays
        # disjoint (the vertex-free REJECT path)
        diamonds = [
            geo.Polygon(np.array([[0, -9], [9, 0], [0, 9], [-9, 0], [0, -9]], float)),
            geo.Polygon(np.array([[4, -9], [13, 0], [4, 9], [-5, 0], [4, -9]], float)),
            geo.Polygon(np.array([[6, 11], [11, 6], [6, 1], [1, 6], [6, 11]], float)),
        ]
        col = geo.PackedGeometryColumn.from_geometries(diamonds)
        q = np.array([-2.0, -2.0, 2.0, 2.0])
        got = _packed_box_intersects(col, q, geo.box(*q))
        want = np.array([geo.intersects(d, geo.box(*q)) for d in diamonds])
        np.testing.assert_array_equal(got, want)
        assert got[0]      # containment: no vertex in the rect, still true
        assert not want[2]  # bbox overlaps yet disjoint: reject path live
        # exercise the VECTORIZED tier's reject too (needs > 64 hard rows)
        many = geo.PackedGeometryColumn.from_geometries(diamonds * 40)
        got_many = _packed_box_intersects(many, q, geo.box(*q))
        np.testing.assert_array_equal(got_many, np.tile(want, 40))


class TestSpatialPrefilters:
    """Bbox prefilters on Within/Contains/DWithin and the polygon
    vertex-accept tier on non-rect INTERSECTS: results must equal the
    exhaustive per-geometry evaluation."""

    @staticmethod
    def _col(n=3000, seed=0):
        rng = np.random.default_rng(seed)
        cx, cy = rng.uniform(-40, 40, n), rng.uniform(-25, 25, n)
        polys = []
        for i in range(n):
            a = np.sort(rng.uniform(0, 2 * np.pi, 4))
            r = rng.uniform(0.05, 0.8, 4)
            ring = np.stack([cx[i] + r * np.cos(a), cy[i] + r * np.sin(a)], 1)
            polys.append(geo.Polygon(np.concatenate([ring, ring[:1]])))
        return polys, geo.PackedGeometryColumn.from_geometries(polys)

    def test_within_contains_dwithin(self):
        from geomesa_tpu.filter.predicates import Contains, DWithin, Within

        polys, col = self._col()
        batch = {"geom": col}
        big = geo.Polygon(np.array(
            [[-10, -10], [20, -12], [22, 15], [-12, 14], [-10, -10]], float))
        w = Within("geom", big).evaluate(batch)
        want_w = np.array([geo.contains(big, p) for p in polys])
        np.testing.assert_array_equal(w, want_w)
        assert want_w.any()
        tiny = geo.Point(polys[7].shell[:-1].mean(axis=0)[0],
                         polys[7].shell[:-1].mean(axis=0)[1])
        c = Contains("geom", tiny).evaluate(batch)
        want_c = np.array([geo.contains(p, tiny) for p in polys])
        np.testing.assert_array_equal(c, want_c)
        d = DWithin("geom", geo.Point(0.0, 0.0), 5.0).evaluate(batch)
        want_d = np.array([geo.distance(p, geo.Point(0.0, 0.0)) <= 5.0 for p in polys])
        np.testing.assert_array_equal(d, want_d)
        assert want_d.any()

    def test_dwithin_points_line(self):
        from geomesa_tpu.filter.predicates import DWithin, PointColumn

        rng = np.random.default_rng(1)
        n = 5000
        px, py = rng.uniform(-30, 30, n), rng.uniform(-30, 30, n)
        line = geo.LineString(np.array([[-10, -10], [0, 5], [12, 3]], float))
        got = DWithin("geom", line, 2.5).evaluate(
            {"geom": PointColumn(px, py)})
        want = np.array([
            geo._point_geom_distance(float(px[i]), float(py[i]), line) <= 2.5
            for i in range(n)])
        np.testing.assert_array_equal(got, want)
        assert want.any()

    def test_intersects_concave_query_polygon(self):
        from geomesa_tpu.filter.predicates import Intersects

        polys, col = self._col(n=2000, seed=2)
        # concave star query: the vertex-accept tier plus exact fallback
        t = np.linspace(0, 2 * np.pi, 11)
        r = np.where(np.arange(11) % 2 == 0, 18.0, 6.0)
        star = geo.Polygon(np.stack(
            [5 + r * np.cos(t), 2 + r * np.sin(t)], 1))
        got = Intersects("geom", star).evaluate({"geom": col})
        want = np.array([geo.intersects(p, star) for p in polys])
        np.testing.assert_array_equal(got, want)
        assert want.any() and not want.all()


class TestWithinBoundaryBand:
    def test_protruding_vertex_rejected(self):
        from geomesa_tpu.filter.predicates import Within

        rect = geo.box(0, 0, 100, 100)
        inside = geo.Polygon(np.array(
            [[10, 10], [20, 10], [15, 20], [10, 10]], float))
        # vertex 1 f32-ulp past the edge: widened-bbox prefilter alone
        # would accept it; the boundary band must reject exactly
        poke = geo.Polygon(np.array(
            [[90, 10], [100.000003, 10], [95, 20], [90, 10]], float))
        far = geo.Polygon(np.array(
            [[200, 10], [210, 10], [205, 20], [200, 10]], float))
        col = geo.PackedGeometryColumn.from_geometries([inside, poke, far])
        got = Within("geom", rect).evaluate({"geom": col})
        want = [geo.contains(rect, g) for g in (inside, poke, far)]
        np.testing.assert_array_equal(got, np.array(want))
        assert got[0] and not got[1] and not got[2]
