"""Known-bad fixture: fires a fault point no registry declares — the
typo'd/renamed-point failure mode where a crash test arms a name the
code never reaches and passes vacuously (fault-point-unknown)."""

from geomesa_tpu import fault


def save_with_typo():
    fault.fault_point("streem.wal.append")  # typo: streem
