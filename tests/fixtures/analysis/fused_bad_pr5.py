"""KNOWN-BAD fixture: the PR 5 fused-chunk grouping-key defect, replayed.

This is the shape `storage/table.py` had before PR 5's post-review
hardening: the chunk grouping key carries the R bucket but OMITS the
E (edge) bucket, while `_chunk_edge_stack` still sizes each chunk's
edge stack with `fused_e_bucket` over the members. A 256-edge polygon
grouped with box queries then inflates every slot in its chunk to
256-edge PIP work and knocks the chunk off the Pallas path.

The `fused-key-dimension` rule must produce exactly one finding here
(dimension E missing from the key in scan_submit_many).
"""


def fused_e_bucket(n):
    return 0 if n <= 0 else max(16, n)


def fused_r_bucket(n):
    return 0 if n <= 0 else max(16, n)


def n_edges_of(poly):
    return 0 if poly is None else len(poly)


def n_rints_of(rast):
    return 0 if rast is None else len(rast) - 1


def block_scan_multi(members, n_edges=0, n_rints=0):
    return members, n_edges, n_rints


class Table:
    def scan_submit_many(self, configs):
        groups = {}
        for j, config in enumerate(configs):
            names = self._scan_cols(config)
            r_bucket = fused_r_bucket(n_rints_of(config.rast))
            # BUG under test: no e_bucket term — polygon members with
            # different edge ladders share one chunk
            key = (
                names, config.boxes is not None,
                config.windows is not None, r_bucket,
            )
            groups.setdefault(key, []).append((j, config))
        for _key, members in groups.items():
            self._submit_fused_chunk(members)

    def _chunk_edge_stack(self, members):
        return fused_e_bucket(max(n_edges_of(m[1].poly) for m in members))

    def _submit_fused_chunk(self, members, stats={}):
        chunk_e = self._chunk_edge_stack(members)
        chunk_r = fused_r_bucket(
            max(n_rints_of(m[1].rast) for m in members)
        )
        # incidental NON-tuple setdefault: must not turn this function
        # into a "grouping function" and mask the missing-E detection
        stats.setdefault(chunk_e, []).append(len(members))
        return block_scan_multi(members, n_edges=chunk_e, n_rints=chunk_r)

    def _scan_cols(self, config):
        return ("x", "y")
