"""KNOWN-BAD fixture: a FOLD-side bucket ladder outside the grouping key.

Round 11's incremental fold ships no static-bucket shapes (its device
plan runs eager ops), but the `fused-key-dimension` rule was widened to
`fold_<dim>_bucket` so a future fold ladder cannot silently recreate
the PR 5 defect class: here a fold slice ladder (`fold_s_bucket`) sizes
the fold-plan operands while the module's grouping key omits that
dimension — the rule must produce exactly one finding (dimension S).
"""


def fold_s_bucket(n):
    return 0 if n <= 0 else max(256, n)


def fused_r_bucket(n):
    return 0 if n <= 0 else max(16, n)


def n_rints_of(rast):
    return 0 if rast is None else len(rast) - 1


def block_scan_multi(members, n_rints=0, n_slice=0):
    return members, n_rints, n_slice


class Table:
    def scan_submit_many(self, configs):
        groups = {}
        for j, config in enumerate(configs):
            r_bucket = fused_r_bucket(n_rints_of(config.rast))
            # BUG under test: no fold-slice bucket term in the key
            key = (config.boxes is not None, r_bucket)
            groups.setdefault(key, []).append((j, config))
        for _key, members in groups.items():
            self._submit_fold_chunk(members)

    def _submit_fold_chunk(self, members):
        n_slice = fold_s_bucket(max(len(m[1].rows) for m in members))
        chunk_r = fused_r_bucket(
            max(n_rints_of(m[1].rast) for m in members)
        )
        return block_scan_multi(members, n_rints=chunk_r, n_slice=n_slice)
