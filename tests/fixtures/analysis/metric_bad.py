"""KNOWN-BAD fixture: metric naming + instrument-kind defects.

Two seeded defects for the metrics family:

- ``geomesa.Fixture-Area.hits`` breaks the geomesa.<area>.<name>
  convention (uppercase + hyphen) -> `metric-convention`;
- ``geomesa.fixture.depth`` is used as BOTH a counter and a gauge ->
  `metric-type-conflict`.
"""


class Probe:
    def __init__(self, metrics):
        self.metrics = metrics

    def record_hit(self):
        self.metrics.counter("geomesa.Fixture-Area.hits")

    def record_depth_a(self, n):
        self.metrics.counter("geomesa.fixture.depth", n)

    def record_depth_b(self, n):
        self.metrics.gauge("geomesa.fixture.depth", n)
