"""KNOWN-BAD fixture: warmup() missing a fused variant-key ladder.

A table class that groups fused chunks (scan_submit_many) and whose
warmup walks the E ladder but never the R ladder — first raster-fused
queries would pay the compile at query time. Expected: one
`warmup-coverage` finding for dimension R (and none for E).

The module must mention block_scan_multi so the rule treats it as a
kernel-dispatching table (host-only backends are exempt).
"""

FUSED_E_BUCKETS = (16, 64, 256)
FUSED_R_BUCKETS = (16, 32, 64, 256)


def block_scan_multi(*args, **kwargs):
    return args, kwargs


class Table:
    def scan_submit_many(self, configs):
        groups = {}
        for j, config in enumerate(configs):
            key = (j,)
            groups.setdefault(key, []).append(config)
        return groups

    def warmup(self):
        calls = 0
        for e in FUSED_E_BUCKETS:  # R ladder missing: the seeded gap
            block_scan_multi(n_edges=e)
            calls += 1
        return calls
