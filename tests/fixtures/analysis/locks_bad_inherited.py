"""KNOWN-BAD fixture: guarded-by annotation with an INHERITED lock.

The subclass never assigns ``self._lock`` itself (the base class owns
it), so lock detection finds no locks in this class — but the
``# guarded-by:`` annotation must stay ENFORCED: with-blocks name the
lock attribute, so held-ness is still checkable (the regression where
annotations in lock-less classes were silently ignored).

Expected: one `lock-guarded-mutation` finding on ``add`` (and none on
``drain``, whose mutation sits inside ``with self._lock``), with no
bad-annotation finding.
"""


class Base:
    pass  # owns self._lock in the real hierarchy


class Child(Base):
    def __init__(self):
        super().__init__()
        self._items = []  # guarded-by: _lock

    def add(self, x):
        # BUG under test: mutation outside the inherited lock
        self._items.append(x)

    def drain(self):
        with self._lock:
            out, self._items = self._items, []
        return out
