"""KNOWN-BAD fixture: an undeclared family named through an f-string.

The f-string below names a nonexistent "bogus" family via a literal
fragment that ends at a substitution. Expected: exactly ONE
`knob-undeclared` finding — the JoinedStr fragment must not be scanned
a second time when ast.walk reaches the fragment's own Constant node
(the duplicate-findings regression).
"""


def render(kind: str) -> str:
    return f"set geomesa.bogus.{kind}.target"
