"""KNOWN-BAD fixture: a concurrent-tier lock with no LOCKS entry.

Staged under a synthetic ``geomesa_tpu/streaming/`` path (an ENFORCED
scope): a new lock in the concurrent tiers that nobody registered has
no declared rank, so the order checker cannot place it — the
"undeclared lock rank" findings this PR fixed in the production tree
by writing the registry.

Expected: one ``lock-order-cycle`` finding (``unregistered:``) on the
construction line.
"""

import threading


class UnrankedBuffer:
    def __init__(self):
        self._buf_lock = threading.Lock()
        self._pending = []  # guarded-by: _buf_lock

    def push(self, item):
        with self._buf_lock:
            self._pending.append(item)

    def drain(self):
        with self._buf_lock:
            out, self._pending = self._pending, []
        return out
