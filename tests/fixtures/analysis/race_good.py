"""KNOWN-GOOD fixture: the disciplined twin of the race_bad_* files.

Consistent rank-increasing lock order, check-then-act merged into one
hold (and the write-back variant re-validating against current state),
blocking work staged under the lock but executed outside it, and
copy/swap-and-drain escapes only. Every geomesa-race rule must stay
silent.
"""

import os
import threading


class DisciplinedLedger:
    def __init__(self):
        self._hot_lock = threading.Lock()    # lock-rank: 13 hot
        self._audit_lock = threading.Lock()  # lock-rank: 17
        self._rows = {}    # guarded-by: _hot_lock
        self._trail = []   # guarded-by: _audit_lock
        self._staged = []  # guarded-by: _audit_lock

    def transfer(self, key, value):
        with self._hot_lock:
            self._rows[key] = value
            with self._audit_lock:      # always 13 -> 17
                self._trail.append(key)

    def audit(self):
        with self._hot_lock:
            with self._audit_lock:
                return [self._rows.get(k) for k in list(self._trail)]

    def take(self, wanted):
        # the check and the act share ONE hold: nothing staged
        # concurrently can be clobbered
        with self._audit_lock:
            consumed = [c for c in self._staged if c in wanted]
            self._staged = [c for c in self._staged if c not in wanted]
        return consumed

    def flush(self, fh):
        # capture under the lock, block OUTSIDE it
        with self._hot_lock:
            batch = dict(self._rows)
        os.fsync(fh.fileno())
        return batch

    def drain_trail(self):
        with self._audit_lock:
            out, self._trail = self._trail, []
        return out
