"""KNOWN-GOOD fixture: the post-hardening fused grouping key.

Identical to fused_bad_pr5.py except the grouping key derives BOTH
ladder dimensions (`e_bucket` and `r_bucket`), matching what
`storage/table.py` ships today. The `fused-key-dimension` rule must
stay silent here.
"""


def fused_e_bucket(n):
    return 0 if n <= 0 else max(16, n)


def fused_r_bucket(n):
    return 0 if n <= 0 else max(16, n)


def n_edges_of(poly):
    return 0 if poly is None else len(poly)


def n_rints_of(rast):
    return 0 if rast is None else len(rast) - 1


def block_scan_multi(members, n_edges=0, n_rints=0):
    return members, n_edges, n_rints


class Table:
    def scan_submit_many(self, configs):
        groups = {}
        for j, config in enumerate(configs):
            names = self._scan_cols(config)
            e_bucket = fused_e_bucket(n_edges_of(config.poly))
            r_bucket = fused_r_bucket(n_rints_of(config.rast))
            key = (
                names, config.boxes is not None,
                config.windows is not None, e_bucket, r_bucket,
            )
            groups.setdefault(key, []).append((j, config))
        for _key, members in groups.items():
            self._submit_fused_chunk(members)

    def _chunk_edge_stack(self, members):
        return fused_e_bucket(max(n_edges_of(m[1].poly) for m in members))

    def _submit_fused_chunk(self, members):
        chunk_e = self._chunk_edge_stack(members)
        chunk_r = fused_r_bucket(
            max(n_rints_of(m[1].rast) for m in members)
        )
        return block_scan_multi(members, n_edges=chunk_e, n_rints=chunk_r)

    def _scan_cols(self, config):
        return ("x", "y")
