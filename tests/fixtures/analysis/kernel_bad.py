"""KNOWN-BAD fixture: kernel-purity hazards inside a jitted function.

Two seeded defects:

- ``float(x)`` coerces a traced parameter (concretization hazard) ->
  `kernel-traced-coercion`; the ``int(n_pad)`` coercion of a
  static_argnames parameter is the LEGAL pattern and must not be
  flagged;
- ``jnp.nonzero`` produces a data-dependent shape ->
  `kernel-dynamic-shape`.
"""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_pad",))
def bad_kernel(x, n_pad):
    pad = int(n_pad)  # static: fine
    scale = float(x)  # BUG under test: traced coercion
    hits = jnp.nonzero(x > scale)  # BUG under test: dynamic shape
    return hits, pad


@partial(jax.jit, static_argnames="n_pad")
def scalar_static_kernel(x, n_pad):
    """jax's bare-scalar static_argnames form: int(n_pad) is the legal
    trace-time pattern and must NOT be flagged (regression: the rule
    once only recognized the tuple/list spelling)."""
    return x + int(n_pad)
