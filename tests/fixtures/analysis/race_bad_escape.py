"""KNOWN-BAD fixture: a guarded container escaping its lock.

The adopted-row-dict aliasing class: the hot tier's row map is guarded,
but one accessor returns the live dict bare and another stores it into
an unguarded attribute — callers then iterate/mutate it with no lock,
racing every guarded writer.

Expected: two ``guarded-escape`` findings (the bare return and the
unguarded store); ``snapshot`` (copy) and ``drain`` (swap-and-drain
into a local) are silent.
"""

import threading


class LeakyCache:
    def __init__(self):
        self._lock = threading.Lock()  # lock-rank: 35
        self._rows = {}                # guarded-by: _lock
        self.exported = None

    def rows(self):
        with self._lock:
            return self._rows          # BUG: live guarded dict escapes

    def publish(self):
        with self._lock:
            self.exported = self._rows  # BUG: unguarded alias

    def snapshot(self):
        with self._lock:
            return dict(self._rows)    # copy: fine

    def drain(self):
        with self._lock:
            out, self._rows = self._rows, {}
        return out                     # swap-and-drain: fine
