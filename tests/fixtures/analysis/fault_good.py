"""Known-good fixture: a registered, test-exercised fault point plus a
non-literal name (skipped — covered at its literal call sites)."""

from geomesa_tpu import fault


def publish():
    fault.fault_point("streaming.persist")


def dynamic(point: str):
    fault.fault_point(f"{point}.write")  # non-literal: out of scope
