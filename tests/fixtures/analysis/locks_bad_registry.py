"""KNOWN-BAD fixture: the pre-PR 3 unlocked MetricsRegistry mutation.

Shape of `metrics.py` before PR 3 retrofitted locking: the class owns a
lock and uses it on some paths (``reset``), but the hot ``counter``
increment is a bare read-modify-write — the exact lost-update race the
review caught. No annotations here: this exercises the lock rule's
INFERENCE mode (an attribute mutated under the lock somewhere is
guarded everywhere).

Expected: one `lock-guarded-mutation` finding on the ``counter`` body.
"""

import threading
from collections import defaultdict


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters = defaultdict(int)

    def counter(self, name, inc=1):
        # BUG under test: unlocked += on a dict the lock guards elsewhere
        self.counters[name] += inc

    def reset(self):
        with self._lock:
            self.counters.clear()

    def snapshot(self):
        with self._lock:
            return dict(self.counters)
