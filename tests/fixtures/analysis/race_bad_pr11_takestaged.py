"""KNOWN-BAD fixture: the PR 11 ``_take_staged`` write-back race.

The shipped bug (two post-review hardening rounds): the fold snapshots
the staged-chunk list under the stage lock, filters it unlocked, then
writes the filtered list BACK wholesale — clobbering chunks a
concurrent ``stage()`` registered (double-publish) and resurrecting
chunks a concurrent ``unstage()`` dropped (folding deleted rows). The
production fix re-reads ``self._staged`` inside the write-back scope
and reconciles by identity.

Expected: one ``atomicity-check-then-act`` finding on the write-back
scope of ``take``.
"""

import threading


class MiniFlusher:
    def __init__(self):
        self._stage_lock = threading.Lock()  # lock-rank: 33
        self._staged = []                    # guarded-by: _stage_lock

    def stage(self, chunk):
        with self._stage_lock:
            self._staged.append(chunk)

    def take(self, wanted):
        with self._stage_lock:
            staged = list(self._staged)
        consumed = []
        retained = []
        for ch in staged:  # the slow filter runs unlocked (by design)
            if ch in wanted:
                consumed.append(ch)
            else:
                retained.append(ch)
        with self._stage_lock:
            # BUG under test: wholesale write-back of the stale filter
            # result — concurrent stage()/unstage() calls are undone
            self._staged = retained
        return consumed
