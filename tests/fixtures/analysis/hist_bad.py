"""KNOWN-BAD fixture: the histogram instrument is registry-covered.

An unregistered histogram — one whose name the metric registries cannot
accept — must fail the build exactly like a bad counter (ISSUE 13: the
``observe``/``histogram_quantile`` instrument methods joined
INSTRUMENT_METHODS). Two seeded defects:

- ``geomesa.Fixture-Hist.latency`` breaks the geomesa.<area>.<name>
  convention through ``observe()`` -> `metric-convention` (proves the
  registry extraction sees the NEW instrument kind);
- ``geomesa.fixture.wait`` is observed as a histogram AND incremented
  as a counter -> `metric-type-conflict` (one name, two exposition
  families).
"""


class HistProbe:
    def __init__(self, metrics):
        self.metrics = metrics

    def record_latency(self, seconds):
        self.metrics.observe("geomesa.Fixture-Hist.latency", seconds)

    def read_latency(self):
        return self.metrics.histogram_quantile(
            "geomesa.Fixture-Hist.latency", 0.99
        )

    def record_wait_histogram(self, seconds):
        self.metrics.observe("geomesa.fixture.wait", seconds)

    def record_wait_counter(self):
        self.metrics.counter("geomesa.fixture.wait")
