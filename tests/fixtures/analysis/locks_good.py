"""KNOWN-GOOD fixture: lock discipline held.

Every mutation of the annotated/inferred fields happens under the lock,
through a ``*_locked`` helper (caller-holds-the-lock convention), or in
a method declaring ``# holds-lock:``. The lock rule must stay silent.
"""

import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}   # guarded-by: _lock
        self._bytes = 0      # guarded-by: _lock

    def put(self, key, value):
        with self._lock:
            self._store_locked(key, value)

    def _store_locked(self, key, value):
        self._entries[key] = value
        self._bytes += len(value)

    def drain(self):  # holds-lock: _lock
        out, self._entries = self._entries, {}
        self._bytes = 0
        return out

    def snapshot(self):
        with self._lock:
            return dict(self._entries)
