"""Must-fail fixture for controller-registry (docs/analysis.md).

One spec trips every checked direction at once: a name CONTROLLERS
never registers, a knob conf.py never declares, inverted bounds, and
an objective metric no instrument site emits.
"""

from geomesa_tpu.tuning.controllers import ControllerSpec

BAD = ControllerSpec(
    name="bogus_controller",
    knob="geomesa.bogus.knob",  # lint: ignore[knob-undeclared]
    lo=10.0,
    hi=1.0,
    objective="geomesa.bogus.metric",  # lint: ignore[knob-undeclared]
    objective_kind="counter",
    higher_is_better=True,
    step=0.5,
    policy="hill",
    doc="fixture",
)

# the disciplined twin: registered name, declared knob, ordered literal
# bounds, emitted objective — zero controller-registry findings
GOOD = ControllerSpec(
    name="fused_chunk_slots",
    knob="geomesa.scan.fused.slots",
    lo=256.0,
    hi=2048.0,
    objective="geomesa.tuning.link.rtt",
    objective_kind="gauge",
    higher_is_better=False,
    step=0.0,
    policy="derive",
    integral=True,
    doc="fixture twin of the shipped derive controller",
)
