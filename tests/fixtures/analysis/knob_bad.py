"""KNOWN-BAD fixture: an undeclared geomesa.* knob citation.

An error message cites a property no registry declares (a typo drops a
letter from the scan-ranges knob) — the drift the knob-registry family
exists to catch. Expected: one `knob-undeclared` finding; the correctly
spelled name on the next line resolves and must NOT be flagged.
"""


def explain_limit() -> str:
    return (
        "covering ranges exceeded geomesa.scan.rangs.target; "
        "raise geomesa.scan.ranges.target to widen the plan"
    )
