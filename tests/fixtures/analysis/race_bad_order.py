"""KNOWN-BAD fixture: a lock-order cycle (geomesa-race).

Two locks with declared ranks (inline ``# lock-rank:``, the fixture/
adopter form of the LOCKS registry), acquired in OPPOSITE orders by two
methods — the deadlock shape the LambdaStore hot-lock / cache-lock
nesting would take if any inner tier ever called back out. Two threads
running ``transfer`` and ``audit`` concurrently deadlock.

Expected: one ``lock-order-cycle`` cycle finding plus one rank
violation on the inverted edge (``_audit_lock`` -> ``_hot_lock``
acquires rank 11 under rank 19).
"""

import threading


class RaceyLedger:
    def __init__(self):
        self._hot_lock = threading.Lock()    # lock-rank: 11
        self._audit_lock = threading.Lock()  # lock-rank: 19
        self._rows = {}    # guarded-by: _hot_lock
        self._trail = []   # guarded-by: _audit_lock

    def transfer(self, key, value):
        with self._hot_lock:
            self._rows[key] = value
            with self._audit_lock:       # 11 -> 19: legal
                self._trail.append(key)

    def audit(self):
        with self._audit_lock:
            seen = list(self._trail)
            with self._hot_lock:         # BUG: 19 -> 11, the inversion
                return [self._rows.get(k) for k in seen]
