"""KNOWN-BAD fixture: blocking calls under a hot-path lock.

The PR 8 reader-stall class (and the WAL ``_rotate`` seal-fsync this
PR fixed): a lock every reader/writer crosses is held across an fsync
and a Future wait, so one slow disk flush stalls the whole tier.

Expected: two ``blocking-under-lock`` findings inside ``flush`` (the
fsync and the ``Future.result``); ``note`` is silent (the counter
bumps under a lock, but nothing blocks).
"""

import os
import threading


class HotTier:
    def __init__(self):
        self._lock = threading.Lock()  # lock-rank: 31 hot
        self._rows = {}                # guarded-by: _lock
        self._flushes = 0              # guarded-by: _lock

    def note(self):
        with self._lock:
            self._flushes += 1

    def flush(self, fh, fut):
        with self._lock:
            os.fsync(fh.fileno())      # BUG: disk flush under the hot lock
            merged = fut.result()      # BUG: cross-thread wait under it
            self._rows.update(merged)
            self._flushes += 1
        return merged
