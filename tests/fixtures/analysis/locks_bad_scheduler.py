"""KNOWN-BAD fixture: unlocked mutation of a `# guarded-by:` field.

Modeled on `serving/scheduler.py`: the admission queue is explicitly
annotated as guarded by the scheduler condition, but ``submit`` appends
to it without entering the ``with self._cond`` block (and ``close``
swaps it out correctly, proving the annotation matches real usage).

Expected: one `lock-guarded-mutation` finding on the ``submit`` append.
"""

import threading


class QueryScheduler:
    def __init__(self):
        self._cond = threading.Condition()
        self._queue = []   # guarded-by: _cond
        self._closed = False  # guarded-by: _cond

    def submit(self, item):
        # BUG under test: append outside the condition the field declares
        self._queue.append(item)
        with self._cond:
            self._cond.notify_all()

    def close(self):
        with self._cond:
            self._closed = True
            pending, self._queue = self._queue, []
        return pending
