"""KNOWN-BAD fixture: the PR 9 checkpoint-cover-before-drain race.

The shipped bug (caught by the chaos harness, fixed by the WAL's
``pending``/``applied_horizon`` protocol): the checkpoint captured the
pending-record set under the lock, released it to run the drain+save,
then CLEARED the set from the stale capture — wiping registrations a
concurrent producer added during the drain, so the next checkpoint's
cover retired acknowledged records whose effects never reached a store
(permanent acknowledged-row loss).

Expected: one ``atomicity-check-then-act`` finding on the second lock
scope of ``checkpoint`` (writes ``_pending`` back from the stale
capture without re-reading it).
"""

import threading


class MiniWal:
    def __init__(self):
        self._lock = threading.Lock()  # lock-rank: 41
        self._pending = set()          # guarded-by: _lock
        self._last_seq = -1            # guarded-by: _lock

    def append(self, seq):
        with self._lock:
            self._last_seq = seq
            self._pending.add(seq)

    def checkpoint(self, save):
        with self._lock:
            cover = self._last_seq
            captured = set(self._pending)
        save(cover)  # the drain + durable save, outside the lock
        with self._lock:
            if captured:
                # BUG under test: clears from the PRE-DRAIN capture —
                # a record logged during save() is wiped un-applied
                self._pending = set()
        return cover
