"""The examples/ scripts must run end-to-end (CPU) and return results."""

import runpy
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent


@pytest.mark.parametrize(
    "script",
    ["quickstart", "distributed_mesh", "streaming_hot_tier", "batch_and_update", "sql_and_joins"],
)
def test_example_runs(script, monkeypatch):
    monkeypatch.syspath_prepend(str(ROOT))  # import geomesa_tpu from any cwd
    mod = runpy.run_path(str(ROOT / "examples" / f"{script}.py"))
    out = mod["main"]()
    assert out is not None and len(out) > 0
