"""Driver benchmark: GDELT-shaped Z3 BBOX+time query mix on one TPU chip.

BASELINE.md config 1: Z3 point index, BBOX + time-range queries over a
GDELT-shaped point table. The baseline proxy is a NumPy full-columnar CPU
scan of the same predicate (the reference's geomesa-fs Parquet/CPU path is
JVM and cannot run here; a vectorized in-memory CPU scan is a *stronger*
baseline than a Parquet file scan).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline", ...}.
Env knobs: GEOMESA_BENCH_N (points, default 100M), GEOMESA_BENCH_QUERIES.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N = int(os.environ.get("GEOMESA_BENCH_N", 100_000_000))
N_QUERIES = int(os.environ.get("GEOMESA_BENCH_QUERIES", 40))
SEED = 42


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build_store(n):
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType

    rng = np.random.default_rng(SEED)
    # GDELT-shaped: world-wide events clustered around population centers —
    # approximate with a mixture of uniform background + gaussian clusters
    n_clustered = n // 2
    n_uniform = n - n_clustered
    cx = rng.uniform(-160, 160, 64)
    cy = rng.uniform(-55, 65, 64)
    which = rng.integers(0, 64, n_clustered)
    x = np.concatenate(
        [
            rng.uniform(-180, 180, n_uniform),
            np.clip(cx[which] + rng.normal(0, 3.0, n_clustered), -180, 180),
        ]
    )
    y = np.concatenate(
        [
            rng.uniform(-90, 90, n_uniform),
            np.clip(cy[which] + rng.normal(0, 2.0, n_clustered), -90, 90),
        ]
    )
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    span_ms = 120 * 86400_000
    t = t0 + rng.integers(0, span_ms, n)

    sft = FeatureType.from_spec("gdelt", "dtg:Date,*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z3"
    ds = DataStore()
    ds.create_schema(sft)
    fc = FeatureCollection.from_columns(sft, np.arange(n), {"dtg": t, "geom": (x, y)})
    t_in = time.perf_counter()
    ds.write("gdelt", fc, check_ids=False)
    ingest_s = time.perf_counter() - t_in
    return ds, (x, y, t, t0, span_ms), ingest_s


def make_queries(t0, span_ms):
    rng = np.random.default_rng(SEED + 1)
    qs = []
    for i in range(N_QUERIES):
        # selectivity mix: small city-scale boxes through continent-scale
        w = float(rng.choice([1.0, 2.0, 5.0, 10.0, 20.0, 40.0]))
        h = w / 2
        qx = rng.uniform(-175, 175 - w)
        qy = rng.uniform(-85, 85 - h)
        dur_ms = int(rng.choice([6, 24, 72, 168, 24 * 14]) * 3600_000)
        start = int(t0 + rng.integers(0, span_ms - dur_ms))
        lo = np.datetime64(start, "ms")
        hi = np.datetime64(start + dur_ms, "ms")
        qs.append(
            (
                f"bbox(geom, {qx:.4f}, {qy:.4f}, {qx + w:.4f}, {qy + h:.4f}) "
                f"AND dtg DURING {lo}Z/{hi}Z",
                (qx, qy, qx + w, qy + h, start, start + dur_ms),
            )
        )
    return qs


def brute_force_times(data, queries, k=6):
    """CPU columnar baseline on the first k queries, extrapolated."""
    x, y, t, _, _ = data
    times = []
    for _, (x0, y0, x1, y1, tlo, thi) in queries[:k]:
        s = time.perf_counter()
        m = (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1) & (t >= tlo) & (t < thi)
        n_hits = int(m.sum())
        idx = np.nonzero(m)[0]
        times.append(time.perf_counter() - s)
        del m, idx
    return float(np.mean(times)), n_hits


def main():
    import jax

    platform = os.environ.get("GEOMESA_BENCH_PLATFORM")
    if platform:  # e.g. "cpu" for off-TPU verification runs
        jax.config.update("jax_platforms", platform)
    log(f"devices: {jax.devices()}")
    log(f"building {N:,} point store ...")
    t_build = time.perf_counter()
    ds, data, ingest_s = build_store(N)
    log(f"store built in {time.perf_counter() - t_build:.1f}s (index sort+place {ingest_s:.1f}s)")
    table = ds.table("gdelt", "z3")
    log(f"device bytes: {table.nbytes_device / 1e9:.2f} GB")

    queries = make_queries(data[3], data[4])

    # warmup: run the whole mix once untimed so every pad-bucket shape is
    # compiled (first compile is slow over the tunnel; steady-state is what
    # the metric measures)
    t_warm = time.perf_counter()
    for i, (q, _) in enumerate(queries):
        s = time.perf_counter()
        ds.query("gdelt", q)
        log(f"warmup {i}: {time.perf_counter() - s:.2f}s")
    log(f"warmup done in {time.perf_counter() - t_warm:.1f}s")

    lat = []
    hits = 0
    t_all = time.perf_counter()
    for q, _ in queries:
        s = time.perf_counter()
        out = ds.query("gdelt", q)
        lat.append(time.perf_counter() - s)
        hits += len(out)
    wall = time.perf_counter() - t_all
    lat_ms = np.array(lat) * 1e3

    base_mean, _ = brute_force_times(data, queries)
    tpu_mean = float(np.mean(lat))
    vs_baseline = base_mean / tpu_mean

    result = {
        "metric": "gdelt_z3_bbox_time_features_per_sec_per_chip",
        "value": round(hits / wall, 1),
        "unit": "features/s",
        "vs_baseline": round(vs_baseline, 2),
        "n_points": N,
        "n_queries": N_QUERIES,
        "hits_total": hits,
        "latency_p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "latency_p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "latency_mean_ms": round(tpu_mean * 1e3, 2),
        "cpu_baseline_mean_ms": round(base_mean * 1e3, 2),
        "ingest_rate_per_s": round(N / ingest_s, 1),
        "device_gb": round(table.nbytes_device / 1e9, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
