"""Driver benchmark: all five BASELINE.md configs on one TPU chip.

- config 1 (primary; printed first AND repeated as the final line so any
  single-line parser reads it): Z3 point index, BBOX + time-range queries
  over a GDELT-shaped table (default N=500M — 8 GB of device columns,
  ~half of v5e HBM).
- config 2: Z2 point index, BBOX-only queries (OSM-GPS-shaped).
- config 3: XZ2 polygon index, ST_Intersects queries over building-
  footprint-shaped rectangles (default N3=200M — the OSM building layer
  is ~500M footprints; 50M in rounds 3-4 understated the table the
  baseline has to scan).
- config 4: grid-partitioned spatial join, points x admin polygons.
- config 5: kNN process over trajectory-shaped points.

The baseline proxy for every config is a vectorized NumPy full-columnar
CPU scan of the same predicate (the reference's geomesa-fs Parquet/CPU
path is JVM and cannot run here; an in-memory columnar scan is a
*stronger* baseline than a Parquet file scan).

Measured queries are DISJOINT from warmup queries: both draw from the
same shape/selectivity buckets but with different seeds, so the timed
set proves no per-query host state is reused (VERDICT r3 weak #4).
Warmup still compiles every (bucket, flags) kernel variant because
variants are keyed by shape bucket, not query values.

Prints one JSON line per config, config 1 first. Env knobs:
GEOMESA_BENCH_N (config-1 points), GEOMESA_BENCH_N2, GEOMESA_BENCH_N3,
GEOMESA_BENCH_N4, GEOMESA_BENCH_N5, GEOMESA_BENCH_QUERIES,
GEOMESA_BENCH_CONFIGS (e.g. "1" or "1,2,3"; named scenarios "cache",
"serving", "ingest", "fused", "pip_join", "stream", "wal", "knn",
"obs", "ops", "standing", "replica", "serve_http"),
GEOMESA_BENCH_PLATFORM
(e.g. "cpu" for off-TPU verification). Supervisor knobs (see main()):
GEOMESA_BENCH_INIT_TIMEOUT (child device-init watchdog, s),
GEOMESA_BENCH_INIT_RETRIES (attempts), GEOMESA_BENCH_ATTEMPT_TIMEOUT
(per-attempt wall clock, s). GEOMESA_BENCH_CHILD=1 is reserved — it marks
the supervised child process and disables the supervisor wrapper.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time

import numpy as np

N1 = int(os.environ.get("GEOMESA_BENCH_N", 500_000_000))
N2 = int(os.environ.get("GEOMESA_BENCH_N2", 200_000_000))
N3 = int(os.environ.get("GEOMESA_BENCH_N3", 200_000_000))
N_QUERIES = int(os.environ.get("GEOMESA_BENCH_QUERIES", 40))
CONFIGS = os.environ.get("GEOMESA_BENCH_CONFIGS", "1,2,3,4,5").split(",")
SEED = 42


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def gdelt_points(n, rng):
    """World-wide events clustered around population centers: uniform
    background + gaussian clusters."""
    n_clustered = n // 2
    n_uniform = n - n_clustered
    cx = rng.uniform(-160, 160, 64)
    cy = rng.uniform(-55, 65, 64)
    which = rng.integers(0, 64, n_clustered)
    x = np.concatenate(
        [
            rng.uniform(-180, 180, n_uniform),
            np.clip(cx[which] + rng.normal(0, 3.0, n_clustered), -180, 180),
        ]
    )
    y = np.concatenate(
        [
            rng.uniform(-90, 90, n_uniform),
            np.clip(cy[which] + rng.normal(0, 2.0, n_clustered), -90, 90),
        ]
    )
    return x, y


def box_queries(rng, n_queries):
    """Selectivity mix: city-scale through continent-scale boxes."""
    out = []
    for _ in range(n_queries):
        w = float(rng.choice([1.0, 2.0, 5.0, 10.0, 20.0, 40.0]))
        h = w / 2
        qx = rng.uniform(-175, 175 - w)
        qy = rng.uniform(-85, 85 - h)
        out.append((qx, qy, qx + w, qy + h))
    return out


def time_windows(rng, n_queries, t0, span_ms):
    out = []
    for _ in range(n_queries):
        dur_ms = int(rng.choice([6, 24, 72, 168, 24 * 14]) * 3600_000)
        start = int(t0 + rng.integers(0, span_ms - dur_ms))
        out.append((start, start + dur_ms))
    return out


def run_queries(ds, type_name, queries, label):
    """(latencies s, total hits) — warmup pass then a timed pass over a
    DISJOINT measured set."""
    warmup, measured = queries
    t_warm = time.perf_counter()
    for i, q in enumerate(warmup):
        s = time.perf_counter()
        ds.query(type_name, q)
        if i < 3 or time.perf_counter() - s > 1.0:
            log(f"[{label}] warmup {i}: {time.perf_counter() - s:.2f}s")
    # one small batch compiles the canonical fused multi-query variant
    # (fixed chunk shape), so the timed query_many pass stays compile-free
    s = time.perf_counter()
    ds.query_many(type_name, warmup[:6])
    log(f"[{label}] warmup done in {time.perf_counter() - t_warm:.1f}s "
        f"(fused batch {time.perf_counter() - s:.2f}s)")

    lat, hits = [], 0
    t_all = time.perf_counter()
    for q in measured:
        s = time.perf_counter()
        out = ds.query(type_name, q)
        lat.append(time.perf_counter() - s)
        hits += len(out)
    return np.array(lat), hits, time.perf_counter() - t_all


def result_line(metric, lat, hits, wall, base_mean, extra):
    lat_ms = lat * 1e3
    rec = {
        "metric": metric,
        "value": round(hits / wall, 1),
        "unit": "features/s",
        "vs_baseline": round(base_mean / float(np.mean(lat)), 2),
        "n_queries": len(lat),
        "hits_total": hits,
        "latency_p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "latency_p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "latency_mean_ms": round(float(np.mean(lat_ms)), 2),
        "cpu_baseline_mean_ms": round(base_mean * 1e3, 2),
    }
    rec.update(extra)
    print(json.dumps(rec), flush=True)
    return rec


# ------------------------------------------------------------- config 1


def config1_z3():
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType

    n = N1
    rng = np.random.default_rng(SEED)
    log(f"[z3] building {n:,} point store ...")
    x, y = gdelt_points(n, rng)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    span_ms = 120 * 86400_000
    t = t0 + rng.integers(0, span_ms, n)

    sft = FeatureType.from_spec("gdelt", "dtg:Date,*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z3"
    if n > 600_000_000:
        # the 1B-row north-star configuration: packed-time device layout
        # (12 B/row -> 12 GB at 1e9; the 16 B/row (tbin, toff) layout
        # would blow the v5e's 16 GB HBM). Results identical — tick
        # boundaries refine on host (tests/test_packed_time.py)
        sft.user_data["geomesa.z3.packed-time"] = "true"
    ds = DataStore()
    ds.create_schema(sft)
    fc = FeatureCollection.from_columns(sft, np.arange(n), {"dtg": t, "geom": (x, y)})
    t_in = time.perf_counter()
    ds.write("gdelt", fc, check_ids=False)
    ingest_s = time.perf_counter() - t_in
    table = ds.table("gdelt", "z3")
    log(f"[z3] ingest {ingest_s:.1f}s, device {table.nbytes_device / 1e9:.2f} GB")

    def qset(seed):
        r = np.random.default_rng(seed)
        boxes = box_queries(r, N_QUERIES)
        wins = time_windows(r, N_QUERIES, t0, span_ms)
        qs = []
        for (x0, y0, x1, y1), (lo, hi) in zip(boxes, wins):
            qs.append(
                (
                    f"bbox(geom, {x0:.4f}, {y0:.4f}, {x1:.4f}, {y1:.4f}) AND dtg DURING "
                    f"{np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z",
                    (x0, y0, x1, y1, lo, hi),
                )
            )
        return qs

    warmup = [q for q, _ in qset(SEED + 1)]
    measured_full = qset(SEED + 2)  # disjoint from warmup, same buckets
    measured = [q for q, _ in measured_full]

    lat, hits, wall = run_queries(ds, "gdelt", (warmup, measured), "z3")

    # pipelined throughput: same measured set through query_many (all
    # device scans dispatch before any pull — hides the per-query link
    # round-trip; per-query latency above is unchanged by this)
    t_pipe = time.perf_counter()
    outs = ds.query_many("gdelt", measured)
    pipe_wall = time.perf_counter() - t_pipe
    pipe_hits = sum(len(o) for o in outs)
    assert pipe_hits == hits, (pipe_hits, hits)

    # CPU columnar baseline on a sample of the measured set
    times = []
    for _, (x0, y0, x1, y1, lo, hi) in measured_full[:6]:
        s = time.perf_counter()
        m = (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1) & (t >= lo) & (t < hi)
        np.nonzero(m)[0]
        times.append(time.perf_counter() - s)
        del m
    base_mean = float(np.mean(times))

    rec = result_line(
        "gdelt_z3_bbox_time_features_per_sec_per_chip", lat, hits, wall, base_mean,
        {
            "n_points": n,
            "ingest_rate_per_s": round(n / ingest_s, 1),
            "device_gb": round(table.nbytes_device / 1e9, 3),
            "pipelined_features_per_sec": round(pipe_hits / pipe_wall, 1),
            **LINK_PROFILE,
        },
    )
    del ds, fc, table, x, y, t
    gc.collect()
    return rec


# ------------------------------------------------------------- config 2


def config2_z2():
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType

    n = N2
    rng = np.random.default_rng(SEED + 10)
    log(f"[z2] building {n:,} point store ...")
    x, y = gdelt_points(n, rng)  # OSM-GPS-shaped: clustered + background

    sft = FeatureType.from_spec("osm", "*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z2"
    ds = DataStore()
    ds.create_schema(sft)
    fc = FeatureCollection.from_columns(sft, np.arange(n), {"geom": (x, y)})
    t_in = time.perf_counter()
    ds.write("osm", fc, check_ids=False)
    ingest_s = time.perf_counter() - t_in
    table = ds.table("osm", "z2")
    log(f"[z2] ingest {ingest_s:.1f}s, device {table.nbytes_device / 1e9:.2f} GB")

    def qset(seed):
        r = np.random.default_rng(seed)
        return [
            (f"bbox(geom, {x0:.4f}, {y0:.4f}, {x1:.4f}, {y1:.4f})", (x0, y0, x1, y1))
            for x0, y0, x1, y1 in box_queries(r, N_QUERIES)
        ]

    warmup = [q for q, _ in qset(SEED + 11)]
    measured_full = qset(SEED + 12)
    measured = [q for q, _ in measured_full]
    lat, hits, wall = run_queries(ds, "osm", (warmup, measured), "z2")

    t_pipe = time.perf_counter()
    outs = ds.query_many("osm", measured)
    pipe_wall = time.perf_counter() - t_pipe
    pipe_hits = sum(len(o) for o in outs)
    assert pipe_hits == hits, (pipe_hits, hits)

    times = []
    for _, (x0, y0, x1, y1) in measured_full[:6]:
        s = time.perf_counter()
        m = (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
        np.nonzero(m)[0]
        times.append(time.perf_counter() - s)
        del m
    base_mean = float(np.mean(times))

    rec = result_line(
        "osm_z2_bbox_features_per_sec_per_chip", lat, hits, wall, base_mean,
        {
            "n_points": n,
            "ingest_rate_per_s": round(n / ingest_s, 1),
            "device_gb": round(table.nbytes_device / 1e9, 3),
            "pipelined_features_per_sec": round(pipe_hits / pipe_wall, 1),
        },
    )
    del ds, fc, table, x, y
    gc.collect()
    return rec


# ------------------------------------------------------------- config 3


def config3_xz2():
    from geomesa_tpu import geometry as geo
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType

    n = N3
    rng = np.random.default_rng(SEED + 20)
    log(f"[xz2] building {n:,} polygon store ...")
    # building-footprint-shaped rectangles clustered in "cities"
    cx = rng.uniform(-160, 160, 256)
    cy = rng.uniform(-55, 65, 256)
    which = rng.integers(0, 256, n)
    x0 = np.clip(cx[which] + rng.normal(0, 0.5, n), -179.9, 179.8)
    y0 = np.clip(cy[which] + rng.normal(0, 0.4, n), -89.9, 89.8)
    w = rng.uniform(0.0002, 0.002, n)  # ~20-200 m
    h = rng.uniform(0.0002, 0.002, n)
    col = geo.PackedGeometryColumn.from_boxes(x0, y0, x0 + w, y0 + h)

    sft = FeatureType.from_spec("bld", "*geom:Polygon:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "xz2"
    ds = DataStore()
    ds.create_schema(sft)
    fc = FeatureCollection.from_columns(sft, np.arange(n), {"geom": col})
    t_in = time.perf_counter()
    ds.write("bld", fc, check_ids=False)
    ingest_s = time.perf_counter() - t_in
    table = ds.table("bld", "xz2")
    log(f"[xz2] ingest {ingest_s:.1f}s, device {table.nbytes_device / 1e9:.2f} GB")

    def qset(seed):
        r = np.random.default_rng(seed)
        qs = []
        for _ in range(N_QUERIES):
            c = r.integers(0, 256)
            qw = float(r.choice([0.02, 0.05, 0.1, 0.5, 2.0]))
            qx = cx[c] + r.uniform(-1, 1)
            qy = cy[c] + r.uniform(-0.8, 0.8)
            poly = (
                f"POLYGON(({qx:.4f} {qy:.4f}, {qx + qw:.4f} {qy:.4f}, "
                f"{qx + qw:.4f} {qy + qw:.4f}, {qx:.4f} {qy + qw:.4f}, "
                f"{qx:.4f} {qy:.4f}))"
            )
            qs.append((f"INTERSECTS(geom, {poly})", (qx, qy, qx + qw, qy + qw)))
        return qs

    warmup = [q for q, _ in qset(SEED + 21)]
    measured_full = qset(SEED + 22)
    measured = [q for q, _ in measured_full]
    lat, hits, wall = run_queries(ds, "bld", (warmup, measured), "xz2")

    t_pipe = time.perf_counter()
    outs = ds.query_many("bld", measured)
    pipe_wall = time.perf_counter() - t_pipe
    pipe_hits = sum(len(o) for o in outs)
    assert pipe_hits == hits, (pipe_hits, hits)

    bx0, by0 = col.bboxes[:, 0], col.bboxes[:, 1]
    bx1, by1 = col.bboxes[:, 2], col.bboxes[:, 3]
    times = []
    for _, (qx0, qy0, qx1, qy1) in measured_full[:6]:
        s = time.perf_counter()
        m = (bx0 <= qx1) & (bx1 >= qx0) & (by0 <= qy1) & (by1 >= qy0)
        np.nonzero(m)[0]
        times.append(time.perf_counter() - s)
        del m
    base_mean = float(np.mean(times))

    rec = result_line(
        "osm_xz2_intersects_features_per_sec_per_chip", lat, hits, wall, base_mean,
        {
            "n_polygons": n,
            "ingest_rate_per_s": round(n / ingest_s, 1),
            "device_gb": round(table.nbytes_device / 1e9, 3),
            "pipelined_features_per_sec": round(pipe_hits / pipe_wall, 1),
        },
    )
    del ds, fc, table, col
    gc.collect()
    return rec


# ------------------------------------------------------ ingest scenario


def _rss_bytes() -> int:
    """Current resident set size of this process (Linux /proc)."""
    with open("/proc/self/statm") as fh:
        return int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")


def _malloc_trim() -> None:
    """Release freed-but-retained allocator arenas before a baseline RSS
    capture, so the measured ratios compare live bytes, not glibc
    retention. NOT called while sampling a phase's peak — the peak stays
    conservative (what an OOM killer would actually see)."""
    try:
        import ctypes

        ctypes.CDLL("libc.so.6").malloc_trim(0)
    except OSError:
        pass


class _RssSampler:
    """Background peak-RSS sampler (the compaction memory-model proof:
    ru_maxrss is a process-lifetime high-water mark, useless for scoping
    one phase)."""

    def __init__(self, interval_s: float = 0.02):
        import threading

        self.interval_s = interval_s
        self.peak = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            self.peak = max(self.peak, _rss_bytes())
            self._stop.wait(self.interval_s)

    def __enter__(self):
        self.peak = _rss_bytes()
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join()
        self.peak = max(self.peak, _rss_bytes())


def _ingest_column_set_bytes(ds, type_name: str) -> int:
    """Host bytes attributable to one type's column set: feature columns
    + ids + every index's key columns + the resident table columns (RAM
    on a CPU backend)."""
    from geomesa_tpu.ingest.pipeline import _chunk_nbytes

    total = 0
    for fc in ds._chunks.get(type_name, []):
        total += _chunk_nbytes(fc, {})
    for (t, name), parts in ds._key_chunks.items():
        if t != type_name:
            continue
        for k in parts:
            total += int(k.bins.nbytes) + int(k.zs.nbytes)
            total += sum(int(v.nbytes) for v in k.device_cols.values())
    for (t, name), table in ds._tables.items():
        if t == type_name:
            total += int(table.nbytes_device)  # RAM on a CPU backend
            # the table's host half: sorted key copies + the permutation
            for arr in (table.perm, table.bins, table.zs):
                total += int(np.asarray(arr).nbytes)
    return total


def _table_fingerprint(ds, type_name: str) -> str:
    """blake2b over every index table's sorted keys, block layout, and
    the stats sketch JSON — the bit-identity check between the sequential
    and pipelined ingest paths."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for (t, name) in sorted(ds._tables):
        if t != type_name:
            continue
        tab = ds._tables[(t, name)]
        h.update(f"{name}:{tab.n}:{tab.block}:{tab.n_blocks}".encode())
        h.update(np.ascontiguousarray(tab.bins).tobytes())
        h.update(np.ascontiguousarray(tab.zs).tobytes())
        for k in tab.col_names:
            h.update(np.asarray(tab.cols3[k]).tobytes())
    stats = ds.stats_for(type_name)
    if stats is not None:
        h.update(json.dumps(stats.to_json(), default=str, sort_keys=True).encode())
    return h.hexdigest()


def config_ingest(out_path: "str | None" = None):
    """Pipelined multi-core ingest scenario (docs/ingest.md): sequential
    ``write()`` loop vs the staged BulkLoader at 1/2/4 workers on a
    GDELT-shaped bulk load, with a bit-identity check between the paths,
    plus a compaction row proving the bounded-memory streamed merge
    (peak RSS vs the column set). CPU-runnable. Env knobs:
    GEOMESA_BENCH_INGEST_N (rows), GEOMESA_BENCH_INGEST_CHUNK (rows per
    ingest chunk), GEOMESA_BENCH_INGEST_WORKERS (comma list),
    GEOMESA_BENCH_INGEST_COMPACT_N (compaction-row table size)."""
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.ingest import BulkLoader, PipelineConfig
    from geomesa_tpu.sft import FeatureType

    n = int(os.environ.get("GEOMESA_BENCH_INGEST_N", 20_000_000))
    chunk = int(os.environ.get("GEOMESA_BENCH_INGEST_CHUNK", 1_000_000))
    workers_list = [
        int(w) for w in os.environ.get(
            "GEOMESA_BENCH_INGEST_WORKERS", "1,2,4"
        ).split(",")
    ]
    compact_n = int(os.environ.get("GEOMESA_BENCH_INGEST_COMPACT_N", 100_000_000))
    SPEC = "dtg:Date,*geom:Point:srid=4326"
    T0 = 1_704_067_200_000  # 2024-01-01
    SPAN = 80 * 86_400_000

    # -- compaction peak-RSS row (the bounded-memory merge proof) --------
    # run FIRST so the RSS baseline is the bare process (interpreter,
    # jax, XLA) with no leftover arenas from the throughput comparison
    gc.collect()
    _malloc_trim()
    rss_baseline = _rss_bytes()
    log(f"[ingest] compaction row: building {compact_n:,}-row z3 table ...")
    # a GDELT-shaped row: time + point + a payload attribute
    sft = FeatureType.from_spec("cmp", "val:Double," + SPEC)
    sft.user_data["geomesa.indices.enabled"] = "z3"
    ds = DataStore()
    ds.create_schema(sft)
    crng = np.random.default_rng(SEED + 81)
    loader = BulkLoader(ds, "cmp", check_ids=False)
    step = 4_000_000
    for s in range(0, compact_n, step):
        m = min(step, compact_n - s)
        x, y = gdelt_points(m, crng)
        loader.put(FeatureCollection.from_columns(
            sft, np.arange(s, s + m, dtype=np.int64),
            {"val": crng.uniform(0, 1, m),
             "dtg": T0 + crng.integers(0, SPAN, m), "geom": (x, y)},
        ))
    loader.close()
    del loader
    gc.collect()
    delta_rows = max(compact_n // 64, 1)
    x, y = gdelt_points(delta_rows, crng)
    ds.write("cmp", FeatureCollection.from_columns(
        sft, np.arange(compact_n, compact_n + delta_rows, dtype=np.int64),
        {"val": crng.uniform(0, 1, delta_rows),
         "dtg": T0 + crng.integers(0, SPAN, delta_rows), "geom": (x, y)},
    ), check_ids=False)
    gc.collect()
    _malloc_trim()
    column_set = _ingest_column_set_bytes(ds, "cmp")
    with _RssSampler() as rss:
        before = rss.peak
        t0 = time.perf_counter()
        ds.compact("cmp")
        compact_s = time.perf_counter() - t0
    peak_extra = rss.peak - before
    # store-attributable peak (minus the pre-store process baseline) vs
    # the column set: the "no doubling" criterion
    peak_over_cs = (rss.peak - rss_baseline) / max(column_set, 1)
    # TPU-host model: on a real accelerator host the device columns live
    # in HBM, not host RSS — the CPU backend double-counts them (old +
    # freshly-built table both resident at the swap). Subtract them from
    # both sides for the host-memory-model ratio docs/ingest.md states.
    dev = sum(
        int(t.nbytes_device) for (tn, _), t in ds._tables.items() if tn == "cmp"
    )
    host_cs = max(column_set - dev, 1)
    # clamp at 0: at CI-sized tables the streamed build's real extra is
    # below the modeled 2x device subtraction, which would otherwise
    # publish a negative (nonsense) ratio
    host_peak = max((rss.peak - rss_baseline) - 2 * dev, 0)
    peak_over_cs_host = host_peak / host_cs
    # exactness spot-check after the streamed merge
    probe = ds.count("cmp", "bbox(geom, -10, -10, 0, 0)")
    compaction = {
        "n_rows": compact_n,
        "delta_rows": delta_rows,
        "seconds": round(compact_s, 2),
        "column_set_bytes": column_set,
        "rss_baseline_bytes": rss_baseline,
        "rss_before_bytes": before,
        "rss_peak_bytes": rss.peak,
        "peak_extra_bytes": peak_extra,
        "peak_over_column_set": round(peak_over_cs, 3),
        "peak_over_column_set_host_model": round(peak_over_cs_host, 3),
        "probe_hits": int(probe),
    }
    log(
        f"[ingest] compaction: {compact_s:.1f}s, column set "
        f"{column_set / 1e9:.2f} GB, peak RSS {rss.peak / 1e9:.2f} GB "
        f"(store-attributed {peak_over_cs:.2f}x column set, "
        f"+{peak_extra / 1e9:.2f} GB during compact)"
    )
    del ds
    gc.collect()


    log(f"[ingest] generating {n:,} rows in {chunk:,}-row chunks ...")
    rng = np.random.default_rng(SEED + 80)
    raw = []  # shared immutable arrays: both paths ingest identical data
    for s in range(0, n, chunk):
        m = min(chunk, n - s)
        x, y = gdelt_points(m, rng)
        raw.append((
            np.arange(s, s + m, dtype=np.int64),
            T0 + rng.integers(0, SPAN, m),
            x, y,
        ))

    def run_ingest(body) -> tuple:
        """(wall seconds, store, fingerprint) for one full load."""
        sft = FeatureType.from_spec("ing", SPEC)
        sft.user_data["geomesa.indices.enabled"] = "z3,z2"
        ds = DataStore()
        ds.create_schema(sft)
        chunks = [
            FeatureCollection.from_columns(
                sft, ids, {"dtg": t, "geom": (x, y)}
            )
            for ids, t, x, y in raw
        ]
        t0 = time.perf_counter()
        body(ds, chunks)
        wall = time.perf_counter() - t0
        return wall, ds, _table_fingerprint(ds, "ing")

    def seq_body(ds, chunks):
        for fc in chunks:
            ds.write("ing", fc, check_ids=False)
        ds.compact("ing")  # bulk loads end compacted on both paths

    log("[ingest] sequential write() loop ...")
    seq_wall, ds, seq_fp = run_ingest(seq_body)
    del ds
    gc.collect()
    seq_rate = n / seq_wall
    log(f"[ingest] sequential: {seq_wall:.1f}s ({seq_rate:,.0f} rows/s)")

    rows = []
    stage_seconds = {}
    for w in workers_list:
        def pipe_body(ds, chunks, w=w):
            loader = BulkLoader(
                ds, "ing", check_ids=False,
                config=PipelineConfig(workers=w),
            )
            for fc in chunks:
                loader.put(fc)
            res = loader.close()
            stage_seconds[w] = {
                k: round(v, 2) for k, v in res.stage_seconds.items()
            }

        wall, ds, fp = run_ingest(pipe_body)
        del ds
        gc.collect()
        identical = fp == seq_fp
        row = {
            "workers": w,
            "seconds": round(wall, 2),
            "rows_per_s": round(n / wall),
            "speedup": round(seq_wall / wall, 2),
            "identical_tables": identical,
            "stage_seconds": stage_seconds.get(w, {}),
        }
        rows.append(row)
        log(
            f"[ingest] pipelined x{w}: {wall:.1f}s "
            f"({n / wall:,.0f} rows/s, {row['speedup']}x, "
            f"identical={identical}) stages={row['stage_seconds']}"
        )

    import jax

    headline = max(rows, key=lambda r: r["workers"])
    payload = {
        "n_rows": n,
        "chunk_rows": chunk,
        "platform": jax.default_backend(),
        "host_cores": os.cpu_count(),
        "sequential": {
            "seconds": round(seq_wall, 2), "rows_per_s": round(seq_rate),
        },
        "pipelined": rows,
        "compaction": compaction,
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_INGEST.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec = {
        "metric": "ingest_pipelined_speedup",
        "value": headline["speedup"],
        "unit": "x",
        "workers": headline["workers"],
        "rows_per_s": headline["rows_per_s"],
        "sequential_rows_per_s": round(seq_rate),
        "identical_tables": headline["identical_tables"],
        "compaction_peak_over_column_set": compaction["peak_over_column_set"],
        "n_rows": n,
    }
    print(json.dumps(rec), flush=True)
    return rec


# ------------------------------------------------------- cache scenario


def config_cache(out_path: "str | None" = None):
    """Query/aggregation cache tier scenario (docs/caching.md): repeat-
    query and shifted-bbox workloads on a cache-enabled store, reporting
    hit rate and warm-cache speedup. Emits BENCH_CACHE.json next to this
    file (or at ``out_path``). Env knobs: GEOMESA_BENCH_CACHE_N (points),
    GEOMESA_BENCH_CACHE_QUERIES (distinct queries per workload)."""
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.planning.hints import QueryHints
    from geomesa_tpu.sft import FeatureType

    n = int(os.environ.get("GEOMESA_BENCH_CACHE_N", 5_000_000))
    n_q = int(os.environ.get("GEOMESA_BENCH_CACHE_QUERIES", 24))
    rng = np.random.default_rng(SEED + 60)
    log(f"[cache] building {n:,} point store ...")
    x, y = gdelt_points(n, rng)
    sft = FeatureType.from_spec("dash", "*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z2"
    reg = MetricsRegistry()
    ds = DataStore(metrics=reg, cache=True)
    ds.create_schema(sft)
    ds.write("dash", FeatureCollection.from_columns(
        sft, np.arange(n), {"geom": (x, y)}), check_ids=False)

    boxes = box_queries(np.random.default_rng(SEED + 61), n_q)
    queries = [
        f"bbox(geom, {x0:.4f}, {y0:.4f}, {x1:.4f}, {y1:.4f})"
        for x0, y0, x1, y1 in boxes
    ]
    bypass = QueryHints(cache="bypass")

    # -- repeat-query workload (the dashboard refresh) -------------------
    for q in queries:  # compile kernels; no cache interaction
        ds.query("dash", q, hints=bypass)
    def _timed_pass(run):
        """Two passes, per-query min: the noise floor under scheduler
        jitter (a 3x run-to-run swing on identical scans is common on a
        contended host; noise only ever ADDS time)."""
        a = []
        for q in queries:
            s = time.perf_counter()
            run(q)
            a.append(time.perf_counter() - s)
        b = []
        for q in queries:
            s = time.perf_counter()
            run(q)
            b.append(time.perf_counter() - s)
        return np.minimum(a, b)

    cold = _timed_pass(  # honest uncached latency, cache bypassed
        lambda q: ds.query("dash", q, hints=bypass)
    )
    for q in queries:  # populate
        ds.query("dash", q)
    h0, m0 = reg.counters["geomesa.cache.hit"], reg.counters["geomesa.cache.miss"]
    hits_total = 0

    def _warm(q):
        nonlocal hits_total
        hits_total += len(ds.query("dash", q))

    warm = _timed_pass(_warm)  # the repeat passes: served warm
    h1, m1 = reg.counters["geomesa.cache.hit"], reg.counters["geomesa.cache.miss"]
    hit_rate = (h1 - h0) / max((h1 - h0) + (m1 - m0), 1)
    # speedup over the WORKLOAD (total cold / total warm): the dashboard
    # refresh is the whole query set, and totals weight the expensive
    # queries the cache exists for — per-query medians flip on boxes whose
    # uncached scan is already sub-ms
    speedup = float(np.sum(cold)) / max(float(np.sum(warm)), 1e-9)
    repeat = {
        "n_queries": n_q,
        "hit_rate": round(hit_rate, 4),
        "speedup": round(speedup, 2),
        "uncached_total_ms": round(float(np.sum(cold)) * 1e3, 3),
        "warm_total_ms": round(float(np.sum(warm)) * 1e3, 3),
        "uncached_median_ms": round(float(np.median(cold)) * 1e3, 3),
        "warm_median_ms": round(float(np.median(warm)) * 1e3, 3),
        "warm_p99_ms": round(float(np.percentile(np.array(warm) * 1e3, 99)), 3),
    }
    log(f"[cache] repeat-query: hit rate {hit_rate:.2%}, speedup {speedup:.1f}x")

    # -- shifted-bbox workload (the dashboard pan) -----------------------
    # count() composes per-tile aggregates: a pan re-scans only the edge
    # strips, the interior comes from the tile cache
    shift_cold = []
    for (x0, y0, x1, y1), q in zip(boxes, queries):
        s = time.perf_counter()
        n_plain = len(ds.query("dash", q, hints=bypass))
        shift_cold.append(time.perf_counter() - s)
        assert ds.count("dash", q) == n_plain  # fills tiles + exactness
    r0 = reg.counters.get("geomesa.cache.tile.reused", 0)
    f0 = reg.counters.get("geomesa.cache.tile.filled", 0)
    g0 = reg.counters.get("geomesa.cache.tile.gated", 0)
    panned = []  # pan each box by ~10% of its width
    for x0, y0, x1, y1 in boxes:
        dx = (x1 - x0) * 0.1
        panned.append(
            f"bbox(geom, {x0 + dx:.4f}, {y0:.4f}, "
            f"{min(x1 + dx, 180.0):.4f}, {y1:.4f})"
        )
    for q in panned:  # compile + plan-memo warmup, same as the cold loop
        ds.query("dash", q, hints=bypass)
    shift_warm = []
    for q in panned:
        s = time.perf_counter()
        ds.count("dash", q)
        shift_warm.append(time.perf_counter() - s)
    r1 = reg.counters.get("geomesa.cache.tile.reused", 0)
    f1 = reg.counters.get("geomesa.cache.tile.filled", 0)
    g1 = reg.counters.get("geomesa.cache.tile.gated", 0)
    reused_frac = (r1 - r0) / max((r1 - r0) + (f1 - f0), 1)
    shifted = {
        "n_queries": n_q,
        "tiles_reused_frac": round(reused_frac, 4),
        # compositions the adaptive cost gate skipped: on backends where
        # fragmented edge scans price near a full scan, the gate keeps
        # the pan workload at plain-scan parity instead of composing at
        # a loss — 0 reuse + high gated is the gate doing its job
        "gated": g1 - g0,
        "uncached_scan_median_ms": round(float(np.median(shift_cold)) * 1e3, 3),
        "shifted_count_median_ms": round(float(np.median(shift_warm)) * 1e3, 3),
        "speedup": round(
            float(np.median(shift_cold)) / max(float(np.median(shift_warm)), 1e-9), 2
        ),
    }
    log(f"[cache] shifted-bbox: {reused_frac:.2%} tiles reused, "
        f"{shifted['speedup']}x vs plain scan")

    import jax

    payload = {
        "n_points": n,
        "platform": jax.default_backend(),
        "repeat_query": repeat,
        "shifted_bbox": shifted,
        "cache_stats": ds.cache.stats(),
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_CACHE.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec = {
        "metric": "cache_repeat_query_speedup",
        "value": repeat["speedup"],
        "unit": "x",
        "hit_rate": repeat["hit_rate"],
        "tiles_reused_frac": shifted["tiles_reused_frac"],
        "n_points": n,
        "hits_total": hits_total,
    }
    print(json.dumps(rec), flush=True)
    return rec


# ------------------------------------------------------- drift scenario


def config_drift(out_path: "str | None" = None):
    """Workload-drift self-tuning scenario (docs/tuning.md "The drift
    gate"): one dashboard workload served by a FROZEN store (an
    operator-pinned cache-admission threshold), a SELF-TUNED store (the
    same pin, ``cache_min_cost`` controller armed) and an ORACLE store
    (the threshold an operator who had seen the drift coming would
    pick). Phase 1 is a steady hotspot whose scans cost more than the
    pinned threshold — all three serve repeats warm. Then the hotspot
    MOVES and the new queries' scans are cheaper than the pin: the
    frozen store stops admitting and re-scans every repeat, while the
    armed controller senses the hit collapse and relaxes the floor.
    Reported: the frozen store's own pre/post-drift QPS ratio, the
    oracle/tuned post-drift ratio, the recorded decisions, and the
    disarmed bit-identity flag. Emits BENCH_DRIFT.json (or
    GEOMESA_BENCH_DRIFT_OUT / ``out_path``); scripts/bench_gate.py's
    ``config_drift`` bounds are the teeth. Env knobs:
    GEOMESA_BENCH_DRIFT_N (points), GEOMESA_BENCH_DRIFT_QUERIES,
    GEOMESA_BENCH_DRIFT_REPS (measured passes per phase)."""
    from geomesa_tpu import conf as gconf
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.planning.explain import Explainer
    from geomesa_tpu.planning.hints import QueryHints
    from geomesa_tpu.sft import FeatureType

    n = int(os.environ.get("GEOMESA_BENCH_DRIFT_N", 1_000_000))
    n_q = int(os.environ.get("GEOMESA_BENCH_DRIFT_QUERIES", 12))
    reps = int(os.environ.get("GEOMESA_BENCH_DRIFT_REPS", 6))
    rng = np.random.default_rng(SEED + 90)
    log(f"[drift] building 3x {n:,} point stores ...")
    x = rng.uniform(-180.0, 180.0, n)
    y = rng.uniform(-90.0, 90.0, n)
    ids = np.arange(n)

    def build(min_cost_s, tuned=False):
        # the pin IS the knob: each store runs with its own
        # ``geomesa.cache.min.cost`` setting (the cache snapshots it at
        # build; the armed controller reads it live as the value it is
        # allowed to move). Stores run strictly sequentially — the
        # caller clears the knob after each store's run.
        gconf.CACHE_MIN_COST.set(float(min_cost_s))
        sft = FeatureType.from_spec("dash", "*geom:Point:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "z2"
        reg = MetricsRegistry()
        ds = DataStore(metrics=reg, cache=True)
        ds.create_schema(sft)
        ds.write("dash", FeatureCollection.from_columns(
            sft, ids, {"geom": (x, y)}), check_ids=False)
        mgr = None
        if tuned:
            # controller pulses ride the query path: twice per pass
            mgr = ds.attach_tuning(enabled=True, interval=max(1, n_q // 2))
        return ds, reg, mgr

    def star(cx, cy, r_out, r_in, points=60):
        # a concave 120-vertex star: the PIP refinement over its
        # candidates is a STRUCTURAL cost floor (vertex count x
        # candidate count), not a statistical one — scan-noise on a
        # shared host cannot push it near a plain bbox probe
        th = np.linspace(0.0, 2.0 * np.pi, 2 * points, endpoint=False)
        rr = np.where(np.arange(2 * points) % 2 == 0, r_out, r_in)
        xs, ys = cx + rr * np.cos(th), cy + rr * np.sin(th)
        coords = ", ".join(f"{a:.4f} {b:.4f}" for a, b in zip(xs, ys))
        return f"POLYGON(({coords}, {xs[0]:.4f} {ys[0]:.4f}))"

    # hotspot A: concave-polygon region dashboards — expensive scans
    # (device PIP over tens of k candidates). The drift moves the
    # dashboard to hotspot B: drill-down bboxes in the east whose
    # scans bottom out near the probe floor — far below any admission
    # threshold tuned for A.
    arng = np.random.default_rng(SEED + 91)
    qa = [
        f"INTERSECTS(geom, {star(float(arng.uniform(-130.0, -50.0)), float(arng.uniform(-35.0, 35.0)), 40.0, 18.0)})"
        for _ in range(n_q)
    ]
    brng = np.random.default_rng(SEED + 92)
    qb = []
    for _ in range(n_q):
        x0 = float(brng.uniform(5.0, 173.0))
        y0 = float(brng.uniform(-85.0, 84.0))
        qb.append(
            f"bbox(geom, {x0:.4f}, {y0:.4f}, {x0 + 1.5:.4f}, {y0 + 1.0:.4f})"
        )
    bypass = QueryHints(cache="bypass")

    # calibrate the operator's frozen pin between the two hotspots'
    # measured scan costs (machine-dependent), inside the controller's
    # [0, 50 ms] range
    probe_ds, _, _ = build(0.0)
    gconf.CACHE_MIN_COST.clear()
    for q in qa + qb:  # compile kernels off the clock
        probe_ds.query("dash", q, hints=bypass)

    def _cost(ds, queries):
        out = []
        for q in queries:
            s = time.perf_counter()
            ds.query("dash", q, hints=bypass)
            out.append(time.perf_counter() - s)
        return float(np.median(out))

    t_hi = _cost(probe_ds, qa)
    t_lo = _cost(probe_ds, qb)
    # split the measured costs: B scans must price BELOW the pin (the
    # frozen store stops admitting after the drift) and A scans above
    # it (the pin looked right when it was set). Geometric mean keeps
    # equal RELATIVE margins on both sides of the wide polygon-vs-bbox
    # gap; the controller's range caps the pin at 50 ms either way.
    thr = float(np.sqrt(max(t_lo, 1e-6) * max(t_hi, 1e-6)))
    if 1.25 * t_lo <= 0.8 * t_hi:
        thr = max(1.25 * t_lo, min(thr, 0.8 * t_hi))
    thr = min(thr, 0.05)
    log(f"[drift] scan cost: hotspot A {t_hi * 1e3:.1f}ms, "
        f"B {t_lo * 1e3:.1f}ms -> frozen pin {thr * 1e3:.1f}ms")
    if not (t_lo < thr < t_hi):  # pragma: no cover - host-dependent
        log("[drift] WARNING: could not place the pin between the "
            "hotspots' costs; the scenario premise is weak on this host")
    probe_ds.close()
    del probe_ds
    gc.collect()

    def qps(ds, queries, passes):
        t0 = time.perf_counter()
        for _ in range(passes):
            for q in queries:
                ds.query("dash", q)
        return (passes * len(queries)) / (time.perf_counter() - t0)

    def run(ds):
        for q in qa + qb:  # compile both hotspots off the clock
            ds.query("dash", q, hints=bypass)
        for _ in range(2):  # phase 1 populate
            for q in qa:
                ds.query("dash", q)
        pre = qps(ds, qa, reps)  # steady hotspot, served warm
        # the drift: the hotspot moves. Every store gets the same
        # adaptation window (the tuned one senses the hit collapse in
        # it; the frozen one just re-scans), then the same measurement.
        for _ in range(6):
            for q in qb:
                ds.query("dash", q)
        post = qps(ds, qb, reps)
        return pre, post

    results = {}
    decisions = []
    final_min_cost = None
    for name, min_cost, tuned in (
        ("frozen", thr, False), ("oracle", 0.0, False),
        ("tuned", thr, True),
    ):
        ds, reg, mgr = build(min_cost, tuned=tuned)
        try:
            pre, post = run(ds)
            results[name] = {
                "pin_ms": round(min_cost * 1e3, 3),
                "qps_pre": round(pre, 1),
                "qps_post": round(post, 1),
            }
            log(f"[drift] {name}: pre {pre:.0f} q/s -> post {post:.0f} q/s")
            if mgr is not None:
                rep = mgr.report()
                decisions = [
                    d for d in rep["decisions"]
                    if d.get("controller") == "cache_min_cost"
                ]
                final_min_cost = ds.cache.result.conf.min_cost_s
                results[name]["final_pin_ms"] = round(final_min_cost * 1e3, 3)
                results[name]["pulses"] = rep["pulses"]
            ds.close()
        finally:
            gconf.CACHE_MIN_COST.clear()
        del ds
        gc.collect()

    # the off switch: a DISARMED manager must leave a store
    # bit-identical to one without the tier (plans, explains, results)
    def small_store():
        sft = FeatureType.from_spec("dash", "*geom:Point:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "z2"
        ds = DataStore(metrics=MetricsRegistry(), cache=True)
        ds.create_schema(sft)
        k = min(n, 50_000)
        ds.write("dash", FeatureCollection.from_columns(
            sft, ids[:k], {"geom": (x[:k], y[:k])}), check_ids=False)
        return ds

    plain, disarmed = small_store(), small_store()
    disarmed.attach_tuning(enabled=False)

    def _strip(e):  # timing lines differ run to run; everything else may not
        return [l for l in e.lines if "ms" not in l]

    identical = True
    for q in (qa + qb)[:8]:
        e1, e2 = Explainer(), Explainer()
        r1 = plain.query("dash", q, explain=e1)
        r2 = disarmed.query("dash", q, explain=e2)
        if (
            not np.array_equal(np.asarray(r1.ids), np.asarray(r2.ids))
            or _strip(e1) != _strip(e2)
        ):
            identical = False
    plain.close()
    disarmed.close()

    frozen_degradation = (
        results["frozen"]["qps_pre"]
        / max(results["frozen"]["qps_post"], 1e-9)
    )
    tuned_over_oracle = (
        results["oracle"]["qps_post"]
        / max(results["tuned"]["qps_post"], 1e-9)
    )
    row = {
        "scenario": "config_drift",
        "n_points": n,
        "n_queries": n_q,
        "reps": reps,
        "pin_ms": round(thr * 1e3, 3),
        "hotspot_scan_ms": {
            "pre": round(t_hi * 1e3, 3), "post": round(t_lo * 1e3, 3),
        },
        "frozen": results["frozen"],
        "oracle": results["oracle"],
        "tuned": results["tuned"],
        "frozen_degradation": round(frozen_degradation, 3),
        "tuned_over_oracle": round(tuned_over_oracle, 3),
        "decisions_recorded": len(decisions),
        "decisions": decisions[:8],
        "disarmed_identical": identical,
        "identical": identical,
    }
    log(f"[drift] frozen degraded {frozen_degradation:.1f}x; tuned holds "
        f"{1 / max(tuned_over_oracle, 1e-9):.2f}x of oracle; "
        f"{len(decisions)} decisions; disarmed identical: {identical}")

    import jax

    payload = {"platform": jax.default_backend(), "rows": [row]}
    if out_path is None:
        out_path = os.environ.get("GEOMESA_BENCH_DRIFT_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_DRIFT.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec = {
        "metric": "drift_frozen_degradation",
        "value": round(frozen_degradation, 3),
        "unit": "x",
        "tuned_over_oracle": round(tuned_over_oracle, 3),
        "decisions_recorded": len(decisions),
        "disarmed_identical": identical,
        "n_points": n,
    }
    print(json.dumps(rec), flush=True)
    return rec


# ----------------------------------------------------- serving scenario


def config_serving(out_path: "str | None" = None):
    """Concurrent query serving scenario (docs/serving.md): QPS and
    p50/p99 latency at 8/32/128 concurrent clients, the micro-batch
    scheduler vs the naive per-thread ``execute()`` baseline, reporting
    the mean fused batch size. Emits BENCH_SERVING.json next to this
    file (or at ``out_path``). CPU-runnable. Env knobs:
    GEOMESA_BENCH_SERVING_N (points), GEOMESA_BENCH_SERVING_CLIENTS
    (comma list), GEOMESA_BENCH_SERVING_Q (target total queries per
    client count)."""
    import threading

    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.sft import FeatureType

    n = int(os.environ.get("GEOMESA_BENCH_SERVING_N", 2_000_000))
    clients_list = [
        int(c) for c in os.environ.get(
            "GEOMESA_BENCH_SERVING_CLIENTS", "8,32,128"
        ).split(",")
    ]
    total_q = int(os.environ.get("GEOMESA_BENCH_SERVING_Q", 384))
    rng = np.random.default_rng(SEED + 70)
    log(f"[serving] building {n:,} point store ...")
    x, y = gdelt_points(n, rng)
    sft = FeatureType.from_spec("srv", "*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z2"
    reg = MetricsRegistry()
    ds = DataStore(metrics=reg)
    ds.create_schema(sft)
    ds.write("srv", FeatureCollection.from_columns(
        sft, np.arange(n), {"geom": (x, y)}), check_ids=False)

    # distinct city-scale boxes (small results: per-query overhead is
    # what serving amortizes), one disjoint slice per client
    qrng = np.random.default_rng(SEED + 71)
    def qbox():
        w = float(qrng.choice([0.5, 1.0, 2.0]))
        qx = qrng.uniform(-175, 175 - w)
        qy = qrng.uniform(-85, 85 - w / 2)
        return f"bbox(geom, {qx:.4f}, {qy:.4f}, {qx + w:.4f}, {qy + w / 2:.4f})"

    pool = [qbox() for _ in range(total_q)]
    for q in pool[:8]:  # compile the single-query variants
        ds.query("srv", q)
    ds.query_many("srv", pool[:8])  # compile the fused chunk variant
    for q in pool:  # warm the scan-config memo for BOTH runs (fairness)
        ds.planner.plan("srv", q)

    def run(clients, body):
        """``clients`` threads, each running ``body`` over its slice;
        returns (per-query latencies, total hits, wall seconds)."""
        per = max(1, total_q // clients)
        lat: list[float] = []
        hits = [0]
        lock = threading.Lock()
        start = threading.Barrier(clients + 1)

        def worker(qs):
            loc, h = [], 0
            start.wait()
            for q in qs:
                s = time.perf_counter()
                h += body(q)
                loc.append(time.perf_counter() - s)
            with lock:
                lat.extend(loc)
                hits[0] += h

        threads = [
            threading.Thread(target=worker, args=(pool[i * per:(i + 1) * per],))
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        return np.array(lat), hits[0], time.perf_counter() - t0

    rows = []
    for clients in clients_list:
        nl, nh, nw = run(clients, lambda q: len(ds.query("srv", q)))
        sched = ds.serve()
        b0 = reg.counters.get("geomesa.serving.batches", 0)
        q0 = reg.counters.get("geomesa.serving.batched_queries", 0)
        c0 = reg.counters.get("geomesa.serving.coalesced", 0)
        sl, sh, sw = run(clients, lambda q: len(sched.query("srv", q)))
        sched.close()
        b1 = reg.counters.get("geomesa.serving.batches", 0)
        q1 = reg.counters.get("geomesa.serving.batched_queries", 0)
        c1 = reg.counters.get("geomesa.serving.coalesced", 0)
        assert nh == sh, (nh, sh)  # scheduler results == naive results
        mean_batch = (q1 - q0) / max(b1 - b0, 1)
        row = {
            "clients": clients,
            "queries": len(nl),
            "hits_total": int(nh),
            "naive": {
                "qps": round(len(nl) / nw, 1),
                "p50_ms": round(float(np.percentile(nl * 1e3, 50)), 3),
                "p99_ms": round(float(np.percentile(nl * 1e3, 99)), 3),
            },
            "scheduler": {
                "qps": round(len(sl) / sw, 1),
                "p50_ms": round(float(np.percentile(sl * 1e3, 50)), 3),
                "p99_ms": round(float(np.percentile(sl * 1e3, 99)), 3),
                "mean_fused_batch": round(mean_batch, 2),
                "coalesced": c1 - c0,
            },
        }
        row["speedup"] = round(
            row["scheduler"]["qps"] / max(row["naive"]["qps"], 1e-9), 2
        )
        rows.append(row)
        log(
            f"[serving] {clients} clients: scheduler {row['scheduler']['qps']}"
            f" qps vs naive {row['naive']['qps']} qps "
            f"({row['speedup']}x), mean fused batch {mean_batch:.1f}"
        )

    import jax

    payload = {
        "n_points": n,
        "platform": jax.default_backend(),
        "rows": rows,
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVING.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    headline = min(rows, key=lambda r: abs(r["clients"] - 32))
    rec = {
        "metric": "serving_scheduler_qps_32_clients",
        "value": headline["scheduler"]["qps"],
        "unit": "queries/s",
        "vs_baseline": headline["speedup"],
        "naive_qps": headline["naive"]["qps"],
        "mean_fused_batch": headline["scheduler"]["mean_fused_batch"],
        "latency_p50_ms": headline["scheduler"]["p50_ms"],
        "latency_p99_ms": headline["scheduler"]["p99_ms"],
        "n_points": n,
    }
    print(json.dumps(rec), flush=True)
    return rec


# ------------------------------------------------ observability scenario


def config_obs(out_path: "str | None" = None):
    """Observability overhead + fidelity scenario (docs/observability.md):
    serving QPS through the scheduler with tracing OFF (both arming
    knobs 0 — the disarmed no-op check), SAMPLED (1/64) and FULL
    (every root), on identical query pools; plus (a) live-histogram
    p99 vs the offline numpy percentile of the same latencies, and
    (b) a captured slow-query trace of a fused batched query whose
    top-level phases must cover the root wall. Emits BENCH_OBS.json
    (or ``out_path``; env GEOMESA_BENCH_OBS_OUT), gated by
    scripts/bench_gate.py. CPU-runnable. Env knobs:
    GEOMESA_BENCH_OBS_N (points), GEOMESA_BENCH_OBS_CLIENTS,
    GEOMESA_BENCH_OBS_Q (total queries per mode)."""
    import threading

    from geomesa_tpu import conf, obs
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.metrics import HIST_EDGES, MetricsRegistry
    from geomesa_tpu.sft import FeatureType

    n = int(os.environ.get("GEOMESA_BENCH_OBS_N", 2_000_000))
    clients = int(os.environ.get("GEOMESA_BENCH_OBS_CLIENTS", 4))
    total_q = int(os.environ.get("GEOMESA_BENCH_OBS_Q", 1024))
    out_path = out_path or os.environ.get("GEOMESA_BENCH_OBS_OUT")
    rng = np.random.default_rng(SEED + 90)
    log(f"[obs] building {n:,} point store ...")
    x, y = gdelt_points(n, rng)
    sft = FeatureType.from_spec("srv", "*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z2"
    reg = MetricsRegistry()
    ds = DataStore(metrics=reg)
    ds.create_schema(sft)
    ds.write("srv", FeatureCollection.from_columns(
        sft, np.arange(n), {"geom": (x, y)}), check_ids=False)

    qrng = np.random.default_rng(SEED + 91)

    def qbox():
        w = float(qrng.choice([0.5, 1.0, 2.0]))
        qx = qrng.uniform(-175, 175 - w)
        qy = qrng.uniform(-85, 85 - w / 2)
        return f"bbox(geom, {qx:.4f}, {qy:.4f}, {qx + w:.4f}, {qy + w / 2:.4f})"

    pool = [qbox() for _ in range(total_q)]
    for q in pool[:8]:
        ds.query("srv", q)
    ds.query_many("srv", pool[:8])
    for q in pool:
        ds.planner.plan("srv", q)

    def run_clients(body):
        per = max(1, total_q // clients)
        lat: list[float] = []
        hits = [0]
        lock = threading.Lock()
        start = threading.Barrier(clients + 1)

        def worker(qs):
            loc, h = [], 0
            start.wait()
            for q in qs:
                s = time.perf_counter()
                h += body(q)
                loc.append(time.perf_counter() - s)
            with lock:
                lat.extend(loc)
                hits[0] += h

        threads = [
            threading.Thread(target=worker, args=(pool[i * per:(i + 1) * per],))
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        return np.array(lat), hits[0], time.perf_counter() - t0

    def arm(sample, slow_ms):
        conf.OBS_TRACE_SAMPLE.set(sample)
        conf.OBS_SLOW_MS.set(slow_ms)
        obs.install(obs.Tracer())

    modes = {"off": (0, 0.0), "sampled": (64, 0.0), "full": (1, 0.0)}
    results: dict = {}
    hits_by_mode: dict = {}
    try:
        # untimed warm pass: compiles every fused batch-size variant the
        # concurrent load will hit, so mode ordering cannot bias the
        # overhead ratios
        arm(0, 0.0)
        sched = ds.serve()
        run_clients(lambda q: len(sched.query("srv", q)))
        sched.close()
        # median-of-5 per mode, modes INTERLEAVED round-robin so slow
        # machine drift (thermal, page cache) hits every mode equally
        # instead of biasing whichever ran last; the median (not the
        # best) is the robust center the overhead ratios divide
        runs: dict = {m: [] for m in modes}
        for _rep in range(5):
            for mode, (sample, slow_ms) in modes.items():
                arm(sample, slow_ms)
                sched = ds.serve()
                lat, hits, wall = run_clients(
                    lambda q: len(sched.query("srv", q))
                )
                sched.close()
                runs[mode].append({
                    "qps": round(len(lat) / wall, 1),
                    "p50_ms": round(float(np.percentile(lat * 1e3, 50)), 3),
                    "p99_ms": round(float(np.percentile(lat * 1e3, 99)), 3),
                    "traces_retained": len(obs.tracer().traces()),
                })
                hits_by_mode[mode] = hits
        for mode in modes:
            ordered = sorted(runs[mode], key=lambda r: r["qps"])
            results[mode] = dict(ordered[len(ordered) // 2])
            results[mode]["qps_runs"] = [r["qps"] for r in runs[mode]]
            log(
                f"[obs] {mode}: {results[mode]['qps']} qps median of "
                f"{results[mode]['qps_runs']}"
            )

        # -- live histogram p99 vs offline percentile (same latencies) --
        arm(0, 0.0)
        hreg = MetricsRegistry()
        ds.metrics = hreg
        offline: list[float] = []
        for q in pool:
            plan = ds.planner.plan("srv", q)
            t0 = time.perf_counter()
            ds.planner.execute(plan)
            offline.append(time.perf_counter() - t0)
        hist_p99 = hreg.histogram_quantile("geomesa.query.scan", 0.99)
        off_p99 = float(np.percentile(offline, 99))
        from bisect import bisect_left

        bucket_delta = abs(
            bisect_left(HIST_EDGES, hist_p99) - bisect_left(HIST_EDGES, off_p99)
        )
        ds.metrics = reg

        # -- slow-query capture of a fused batched query ----------------
        arm(0, 0.0001)  # always-slow threshold: every root captures
        sched = ds.serve()
        burst = pool[:32]
        futs = [sched.submit("srv", q) for q in burst]
        for f in futs:
            f.result(60)
        sched.close()
        slow = obs.tracer().slow_queries()
        serving = [
            e for e in slow
            if any(
                s["name"] == "dispatch" for s in e["trace"]["spans"]
            )
        ]
        entry = serving[-1]
        top = [
            s for s in entry["trace"]["spans"]
            if s["parent_id"] is not None and any(
                r["span_id"] == s["parent_id"] and r["parent_id"] is None
                for r in entry["trace"]["spans"]
            )
        ]
        phase_names = {s["name"] for s in top}
        cover = sum(s["dur_ms"] for s in top) / max(entry["wall_ms"], 1e-9)
        slow_trace = {
            "n_phases": len(phase_names),
            "phases": sorted(phase_names),
            "wall_ms": entry["wall_ms"],
            "phase_cover": round(min(cover, 1.0), 4),
            "fingerprint_strategy": entry["fingerprint"].get("strategy"),
        }
        log(
            f"[obs] slow trace: {slow_trace['n_phases']} phases, "
            f"cover {slow_trace['phase_cover']:.3f}"
        )
    finally:
        conf.OBS_TRACE_SAMPLE.clear()
        conf.OBS_SLOW_MS.clear()
        obs.install(obs.Tracer())

    identical = hits_by_mode["off"] == hits_by_mode["sampled"] == hits_by_mode["full"]
    row = {
        "scenario": "serving_obs",
        "clients": clients,
        "queries": total_q,
        "hits_total": int(hits_by_mode["off"]),
        "identical": bool(identical),
        "off": results["off"],
        "sampled": results["sampled"],
        "full": results["full"],
        "sampled_over_off": round(
            results["sampled"]["qps"] / max(results["off"]["qps"], 1e-9), 4
        ),
        "full_over_off": round(
            results["full"]["qps"] / max(results["off"]["qps"], 1e-9), 4
        ),
        "hist_p99": {
            "live_ms": round(hist_p99 * 1e3, 3),
            "offline_ms": round(off_p99 * 1e3, 3),
            "bucket_delta": int(bucket_delta),
        },
        "slow_trace": slow_trace,
    }
    # disarmed overhead vs the committed serving baseline, when the
    # scales match (same points, a row at the same client count)
    try:
        base = json.load(open(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVING.json"
        )))
        if base.get("n_points") == n:
            for brow in base.get("rows", []):
                if brow.get("clients") == clients:
                    row["off_over_serving_baseline"] = round(
                        results["off"]["qps"]
                        / max(brow["scheduler"]["qps"], 1e-9), 4
                    )
    except (OSError, ValueError, KeyError):
        pass

    import jax

    payload = {
        "n_points": n,
        "platform": jax.default_backend(),
        "rows": [row],
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_OBS.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec = {
        "metric": "obs_sampled_over_off_qps_ratio",
        "value": row["sampled_over_off"],
        "unit": "ratio",
        "off_qps": results["off"]["qps"],
        "sampled_qps": results["sampled"]["qps"],
        "full_qps": results["full"]["qps"],
        "hist_p99_bucket_delta": row["hist_p99"]["bucket_delta"],
        "slow_trace_phases": slow_trace["n_phases"],
        "slow_trace_cover": slow_trace["phase_cover"],
        "n_points": n,
    }
    print(json.dumps(rec), flush=True)
    return rec


# ----------------------------------------------------- ops-plane scenario


def config_ops(out_path: "str | None" = None):
    """Ops-plane scenario (docs/observability.md "The ops plane"):
    sustained serving QPS with and without a 1 Hz ``/metrics`` +
    ``/health`` HTTP scraper attached (interleaved reps, median), the
    estimate-vs-actual recording coverage over every executed scan,
    and the stale-stats loop demonstrated end to end on a store
    mutated through the accumulate-only fold path WITHOUT re-analyzing
    (flag raised), then cleared by ``analyze_stats``. Emits
    BENCH_OPS_PLANE.json (or ``out_path``; env
    GEOMESA_BENCH_OPS_PLANE_OUT), gated by scripts/bench_gate.py.
    CPU-runnable. Env knobs: GEOMESA_BENCH_OPS_PLANE_N (points),
    GEOMESA_BENCH_OPS_PLANE_CLIENTS, GEOMESA_BENCH_OPS_PLANE_Q
    (queries per rep)."""
    import threading
    import urllib.request

    from geomesa_tpu import conf as _conf
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.obs.ops import HealthMonitor
    from geomesa_tpu.sft import FeatureType

    n = int(os.environ.get("GEOMESA_BENCH_OPS_PLANE_N", 1_000_000))
    clients = int(os.environ.get("GEOMESA_BENCH_OPS_PLANE_CLIENTS", 4))
    total_q = int(os.environ.get("GEOMESA_BENCH_OPS_PLANE_Q", 768))
    out_path = out_path or os.environ.get("GEOMESA_BENCH_OPS_PLANE_OUT")
    rng = np.random.default_rng(SEED + 95)
    log(f"[ops] building {n:,} point store ...")
    x, y = gdelt_points(n, rng)
    sft = FeatureType.from_spec("srv", "*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z2"
    reg = MetricsRegistry()
    ds = DataStore(metrics=reg)
    ds.create_schema(sft)
    ds.write("srv", FeatureCollection.from_columns(
        sft, np.arange(n), {"geom": (x, y)}), check_ids=False)

    qrng = np.random.default_rng(SEED + 96)

    def qbox():
        w = float(qrng.choice([0.5, 1.0, 2.0]))
        qx = qrng.uniform(-175, 175 - w)
        qy = qrng.uniform(-85, 85 - w / 2)
        return f"bbox(geom, {qx:.4f}, {qy:.4f}, {qx + w:.4f}, {qy + w / 2:.4f})"

    pool = [qbox() for _ in range(total_q)]
    for q in pool[:8]:
        ds.query("srv", q)
    ds.query_many("srv", pool[:8])
    for q in pool:
        ds.planner.plan("srv", q)

    def run_clients(sched):
        per = max(1, total_q // clients)
        hits = [0]
        lock = threading.Lock()
        start = threading.Barrier(clients + 1)

        def worker(qs):
            h = 0
            start.wait()
            for q in qs:
                h += len(sched.query("srv", q))
            with lock:
                hits[0] += h

        threads = [
            threading.Thread(target=worker, args=(pool[i * per:(i + 1) * per],))
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return clients * per / wall, hits[0], wall

    # untimed warm pass: compiles every fused batch-size variant
    sched = ds.serve()
    run_clients(sched)
    sched.close()

    srv = ds.serve_ops()
    scrapes_before = reg.counter_value("geomesa.obs.ops.scrapes")

    def run_mode(scraped: bool):
        sched = ds.serve()
        stop = threading.Event()
        scraper = None
        scrape_errs: list = []
        if scraped:
            def scrape_loop():
                # the 1 Hz operator: one /metrics + /health round per
                # second while the serving load runs (at least one
                # round even on a sub-second rep). Errors propagate —
                # a silently dead scraper would measure an UNSCRAPED
                # run and pass the overhead gate vacuously.
                try:
                    while True:
                        for path in ("/metrics", "/health"):
                            urllib.request.urlopen(
                                srv.url + path, timeout=30
                            ).read()
                        if stop.wait(1.0):
                            return
                except BaseException as e:
                    scrape_errs.append(e)

            scraper = threading.Thread(target=scrape_loop)
            scraper.start()
        try:
            qps, hits, wall = run_clients(sched)
        finally:
            stop.set()
            if scraper is not None:
                scraper.join()
            sched.close()
        if scrape_errs:
            raise RuntimeError(f"ops scraper died: {scrape_errs[0]!r}")
        return {"qps": round(qps, 1), "wall_s": round(wall, 2)}, hits

    # interleaved reps, median by qps (the config_obs convention: slow
    # host drift hits both modes equally)
    runs = {"unscraped": [], "scraped": []}
    hits_by_mode = {}
    for _rep in range(5):
        for mode, scraped in (("unscraped", False), ("scraped", True)):
            r, hits = run_mode(scraped)
            runs[mode].append(r)
            hits_by_mode[mode] = hits
    results = {}
    for mode in runs:
        ordered = sorted(runs[mode], key=lambda r: r["qps"])
        results[mode] = dict(ordered[len(ordered) // 2])
        results[mode]["qps_runs"] = [r["qps"] for r in runs[mode]]
        log(f"[ops] {mode}: {results[mode]['qps']} qps median of "
            f"{results[mode]['qps_runs']}")
    n_scrapes = reg.counter_value("geomesa.obs.ops.scrapes") - scrapes_before
    # belt + braces on top of the scraper error propagation: every
    # scraped rep makes at least one /metrics + /health round
    if n_scrapes < 2 * len(runs["scraped"]):
        raise RuntimeError(
            f"only {n_scrapes} scrapes over {len(runs['scraped'])} scraped "
            "reps — the scraped mode did not actually scrape"
        )

    # -- estimate coverage over the whole serving phase ------------------
    executed = reg.counter_value("geomesa.query.count")
    recorded = ds.accuracy.sample_count()
    coverage = recorded / max(executed, 1)
    log(f"[ops] estimates recorded for {recorded}/{executed} scans "
        f"({coverage:.4f})")

    # -- the stale-stats loop, demonstrated ------------------------------
    # a deliberately mutated-WITHOUT-analyze store: every row moves far
    # away through the accumulate-only fold path (docs/streaming.md's
    # documented sketch drift), so the sketches keep claiming the old
    # region is dense while scans there come back empty
    _conf.PLAN_ESTIMATE_MIN_COUNT.set(16)
    mut = np.random.default_rng(SEED + 97)
    move_n = 100_000
    mds = DataStore(metrics=MetricsRegistry())
    msft = FeatureType.from_spec("mut", "*geom:Point:srid=4326")
    msft.user_data["geomesa.indices.enabled"] = "z2"
    mds.create_schema(msft)
    mds.write("mut", FeatureCollection.from_columns(
        msft, np.arange(move_n),
        {"geom": (mut.uniform(-50, 50, move_n), mut.uniform(-50, 50, move_n))},
    ), check_ids=False)
    mds.fold_upsert("mut", FeatureCollection.from_columns(
        msft, np.arange(move_n),
        {"geom": (mut.uniform(100, 140, move_n), mut.uniform(60, 85, move_n))},
    ))
    mon = HealthMonitor(mds)
    stale_probe = [
        f"bbox(geom, {qx:.2f}, {qy:.2f}, {qx + 4:.2f}, {qy + 4:.2f})"
        for qx, qy in zip(
            mut.uniform(-48, 44, 24), mut.uniform(-48, 44, 24)
        )
    ]  # the vacated region: estimates stay high, scans come back empty
    for q in stale_probe:
        mds.query("mut", q)
    report = mon.evaluate()
    stale_demonstrated = int(any(
        r["reason"] == "stats.stale" for r in report["reasons"]
    ))
    log(f"[ops] stale flagged: {bool(stale_demonstrated)} "
        f"({[r['reason'] for r in report['reasons']]})")
    # the documented remedy clears it
    mds.analyze_stats("mut")
    mds.accuracy.reset("mut")
    for q in stale_probe:
        mds.query("mut", q)
    report = mon.evaluate()
    stale_cleared = int(not any(
        r["reason"] == "stats.stale" for r in report["reasons"]
    ))
    log(f"[ops] stale cleared by analyze_stats: {bool(stale_cleared)}")
    _conf.PLAN_ESTIMATE_MIN_COUNT.clear()
    srv.close()

    row = {
        "scenario": "ops_plane",
        "clients": clients,
        "queries_per_rep": total_q,
        "identical": bool(
            hits_by_mode["unscraped"] == hits_by_mode["scraped"]
        ),
        "unscraped": results["unscraped"],
        "scraped": results["scraped"],
        "qps_unscraped": results["unscraped"]["qps"],
        "qps_scraped": results["scraped"]["qps"],
        "scraped_over_unscraped": round(
            results["scraped"]["qps"]
            / max(results["unscraped"]["qps"], 1e-9), 4
        ),
        "scrapes": int(n_scrapes),
        "estimate_coverage": round(coverage, 4),
        "estimates_recorded": int(recorded),
        "scans_executed": int(executed),
        "stale_demonstrated": stale_demonstrated,
        "stale_cleared": stale_cleared,
    }

    import jax

    payload = {
        "n_points": n,
        "platform": jax.default_backend(),
        "rows": [row],
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_OPS_PLANE.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec = {
        "metric": "ops_scraped_over_unscraped_qps_ratio",
        "value": row["scraped_over_unscraped"],
        "unit": "ratio",
        "unscraped_qps": row["qps_unscraped"],
        "scraped_qps": row["qps_scraped"],
        "scrapes": row["scrapes"],
        "estimate_coverage": row["estimate_coverage"],
        "stale_demonstrated": row["stale_demonstrated"],
        "stale_cleared": row["stale_cleared"],
        "n_points": n,
    }
    print(json.dumps(rec), flush=True)
    return rec


# ----------------------------------------------------- fused scenario


def config_fused(out_path: "str | None" = None):
    """Fused-coverage scenario (docs/serving.md "Fused coverage",
    PERF.md §12): the round-6 fusion tiers — (a) an XZ2 extent table's
    box batch (wide-only plane layout), (b) a z2 polygon-INTERSECTS
    batch through the fused device-PIP edge stacks, (c) a mesh-sharded
    z2 box+polygon batch under shard_map (skipped below 2 devices) —
    each timed FUSED (one `scan_submit_many` dispatch set) vs PER-QUERY
    (serialized `scan_submit` dispatch+pull, what independent callers
    pay), with bit-identity asserted between the paths on every leg.
    Emits BENCH_FUSED.json next to this file (or at ``out_path``).
    CPU-runnable. Env knobs: GEOMESA_BENCH_FUSED_N (rows per table),
    GEOMESA_BENCH_FUSED_Q (queries per batch),
    GEOMESA_BENCH_FUSED_REPEAT (timing repeats, best-of)."""
    import jax

    from geomesa_tpu import geometry as geo
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.filter.predicates import BBox, Intersects
    from geomesa_tpu.sft import FeatureType

    n = int(os.environ.get("GEOMESA_BENCH_FUSED_N", 2_000_000))
    n_q = int(os.environ.get("GEOMESA_BENCH_FUSED_Q", 32))
    repeat = int(os.environ.get("GEOMESA_BENCH_FUSED_REPEAT", 5))
    rng = np.random.default_rng(SEED + 80)

    def star(cx, cy, r, n_arms=5):
        a = np.linspace(0, 2 * np.pi, 2 * n_arms + 1)[:-1]
        rad = np.where(np.arange(2 * n_arms) % 2 == 0, r, 0.4 * r)
        return geo.Polygon(
            [(cx + rr * np.cos(t), cy + rr * np.sin(t)) for t, rr in zip(a, rad)]
        )

    def time_paths(table, cfgs, label):
        """(row dict) fused vs per-query dispatch over the same configs,
        best-of-``repeat``, bit-identity asserted. Two baselines:
        ``per_query_ms`` serializes dispatch+pull (what independent
        callers pay); ``pipelined_ms`` dispatches every query before any
        pull (the pre-round-6 scan_submit_many fallback these configs
        used to take) — the honest "before" of the fusion PR."""
        seq = [table.scan_submit(c)() for c in cfgs]  # warm single-query
        fus = [f() for f in table.scan_submit_many(list(cfgs))]  # warm fused
        identical = all(
            np.array_equal(ra, rb) and np.array_equal(ca, cb)
            for (ra, ca), (rb, cb) in zip(seq, fus)
        )
        assert identical, label  # recorded either way (python -O safe)
        t_seq = min(
            _timed(lambda: [table.scan_submit(c)() for c in cfgs])
            for _ in range(repeat)
        )
        t_pipe = min(
            _timed(lambda: [f() for f in [table.scan_submit(c) for c in cfgs]])
            for _ in range(repeat)
        )
        t_fus = min(
            _timed(lambda: [f() for f in table.scan_submit_many(list(cfgs))])
            for _ in range(repeat)
        )
        row = {
            "scenario": label,
            "queries": len(cfgs),
            "per_query_ms": round(t_seq / len(cfgs) * 1e3, 3),
            "pipelined_ms": round(t_pipe / len(cfgs) * 1e3, 3),
            "fused_ms": round(t_fus / len(cfgs) * 1e3, 3),
            "speedup": round(t_seq / max(t_fus, 1e-9), 2),
            "speedup_vs_pipelined": round(t_pipe / max(t_fus, 1e-9), 2),
            "identical": identical,
        }
        log(
            f"[fused] {label}: {row['per_query_ms']} ms/q per-query / "
            f"{row['pipelined_ms']} ms/q pipelined vs "
            f"{row['fused_ms']} ms/q fused = {row['speedup']}x "
            f"({row['speedup_vs_pipelined']}x vs pipelined)"
        )
        return row

    def _timed(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    rows = []

    # -- (a) XZ2 extent box batch ---------------------------------------
    log(f"[fused] building {n:,}-extent xz2 store ...")
    ex0, ey0 = gdelt_points(n, rng)
    exts = geo.PackedGeometryColumn.from_boxes(
        ex0, ey0,
        ex0 + rng.uniform(0.005, 0.5, n).astype(ex0.dtype),
        ey0 + rng.uniform(0.005, 0.4, n).astype(ey0.dtype),
    )
    sft_x = FeatureType.from_spec("fx", "*geom:Polygon:srid=4326")
    sft_x.user_data["geomesa.indices.enabled"] = "xz2"
    ds = DataStore()
    ds.create_schema(sft_x)
    ds.write("fx", FeatureCollection.from_columns(
        sft_x, np.arange(n), {"geom": exts}), check_ids=False)
    idx = next(i for i in ds.indexes("fx") if i.name == "xz2")
    qrng = np.random.default_rng(SEED + 81)

    def small_box():
        w = float(qrng.choice([0.5, 1.0, 2.0]))
        qx = qrng.uniform(-170, 170 - w)
        qy = qrng.uniform(-80, 80 - w / 2)
        return BBox("geom", qx, qy, qx + w, qy + w / 2)

    cfgs = [idx.scan_config(small_box()) for _ in range(n_q)]
    rows.append(time_paths(ds.table("fx", "xz2"), cfgs, "xz2_box_batch"))

    # -- (b) z2 polygon-INTERSECTS (device PIP) batch -------------------
    log(f"[fused] building {n:,}-point z2 store ...")
    px, py = gdelt_points(n, rng)
    sft_p = FeatureType.from_spec("fp", "*geom:Point:srid=4326")
    sft_p.user_data["geomesa.indices.enabled"] = "z2"
    ds.create_schema(sft_p)
    ds.write("fp", FeatureCollection.from_columns(
        sft_p, np.arange(n), {"geom": (px, py)}), check_ids=False)
    idx_p = next(i for i in ds.indexes("fp") if i.name == "z2")
    cfgs = [
        idx_p.scan_config(Intersects("geom", star(
            float(qrng.uniform(-150, 150)), float(qrng.uniform(-70, 70)),
            float(qrng.choice([0.5, 1.0, 2.0])),
            n_arms=int(qrng.choice([4, 5, 8])),
        )))
        for _ in range(n_q)
    ]
    # the polygon tier: PIP edges pre-round-7, raster intervals (with
    # host residue) by default since — either way a device polygon leg
    assert all(
        c is not None and (c.poly is not None or c.rast is not None)
        for c in cfgs
    )
    rows.append(time_paths(ds.table("fp", "z2"), cfgs, "z2_polygon_pip_batch"))

    # -- (c) mesh-sharded box+polygon batch -----------------------------
    n_dev = len(jax.devices())
    if n_dev >= 2:
        from geomesa_tpu.parallel import make_mesh

        log(f"[fused] building mesh{n_dev} z2 store ...")
        ds_m = DataStore(mesh=make_mesh(n_dev))
        ds_m.create_schema(sft_p)
        ds_m.write("fp", FeatureCollection.from_columns(
            sft_p, np.arange(n), {"geom": (px, py)}), check_ids=False)
        idx_m = next(i for i in ds_m.indexes("fp") if i.name == "z2")
        cfgs = []
        for k in range(n_q):
            if k % 3 == 0:
                cfgs.append(idx_m.scan_config(Intersects("geom", star(
                    float(qrng.uniform(-150, 150)), float(qrng.uniform(-70, 70)),
                    1.0,
                ))))
            else:
                cfgs.append(idx_m.scan_config(small_box()))
        rows.append(time_paths(
            ds_m.table("fp", "z2"), cfgs, f"mesh{n_dev}_mixed_batch"
        ))
    else:
        log("[fused] mesh leg skipped: single device")
        rows.append({"scenario": "mesh_mixed_batch", "skipped": "single device"})

    payload = {
        "n_rows": n,
        "queries_per_batch": n_q,
        "platform": jax.default_backend(),
        "rows": rows,
    }
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_FUSED.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    timed = [r for r in rows if "speedup" in r]
    rec = {
        "metric": "fused_coverage_min_speedup",
        "value": min(r["speedup"] for r in timed),
        "unit": "x",
        "min_vs_pipelined": min(r["speedup_vs_pipelined"] for r in timed),
        "rows": rows,
        "n_rows": n,
    }
    print(json.dumps(rec), flush=True)
    return rec


# ------------------------------------------- raster PIP + join scenario


def config_pip_join(out_path: "str | None" = None):
    """Raster-interval polygon approximations + adaptive joins (round 7,
    docs/joins.md, PERF.md §13): the polygon-heavy workloads the raster
    tier targets, each measured with rasters OFF (the round-6 exact
    device-PIP path) vs ON, end-to-end (fused kernel batch + host
    residue refinement), with bit-identity of the refined hit sets
    computed in-bench —

    - ``z2_polygon_pip_batch``: 32 concave polygon-INTERSECTS queries
      (16..256-edge jagged stars) over an n-point z2 store, one fused
      scan_submit_many dispatch set per batch;
    - ``z2_polygon_join``: spatial_join_indexed over 128 concave
      polygons (the broadcast-join shape with a non-rectangular left
      side);
    - ``host_grid_join``: the storeless grid join, exact vs adaptive
      (sampled-selectivity raster strategy).

    Emits BENCH_PIP_JOIN.json next to this file (or at ``out_path`` /
    env GEOMESA_BENCH_PIP_OUT — use a SCRATCH path when producing the
    fresh side of a gate comparison, so the committed baseline is not
    clobbered); ``scripts/bench_gate.py`` compares a fresh run against
    the recorded baseline and fails on >20% fused-PIP regression. Env
    knobs: GEOMESA_BENCH_PIP_N (rows), GEOMESA_BENCH_PIP_Q
    (queries/batch), GEOMESA_BENCH_PIP_REPEAT (best-of)."""
    import jax

    from geomesa_tpu import geometry as geo
    from geomesa_tpu.conf import RASTER_ENABLED
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.filter import raster as fr
    from geomesa_tpu.filter.predicates import Intersects
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.sft import FeatureType
    from geomesa_tpu.sql.join import spatial_join, spatial_join_indexed

    n = int(os.environ.get("GEOMESA_BENCH_PIP_N", 2_000_000))
    n_q = int(os.environ.get("GEOMESA_BENCH_PIP_Q", 32))
    repeat = int(os.environ.get("GEOMESA_BENCH_PIP_REPEAT", 3))
    rng = np.random.default_rng(SEED + 90)

    def jagged(cx, cy, r, n_arms, seed):
        srng = np.random.default_rng(seed)
        a = np.linspace(0, 2 * np.pi, 2 * n_arms + 1)[:-1]
        rad = np.where(
            np.arange(2 * n_arms) % 2 == 0, r,
            r * srng.uniform(0.3, 0.7, 2 * n_arms),
        )
        return geo.Polygon(
            [(cx + rr * np.cos(t), cy + rr * np.sin(t)) for t, rr in zip(a, rad)]
        )

    log(f"[pip_join] building {n:,}-point z2 store ...")
    px, py = gdelt_points(n, rng)
    sft = FeatureType.from_spec("fp", "*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z2"
    ds = DataStore()
    ds.create_schema(sft)
    ds.write("fp", FeatureCollection.from_columns(
        sft, np.arange(n), {"geom": (px, py)}), check_ids=False)
    idx = next(i for i in ds.indexes("fp") if i.name == "z2")
    table = ds.table("fp", "z2")
    qrng = np.random.default_rng(SEED + 91)
    # the issue's workload: up-to-256-edge polygon stacks (arms 8..127
    # -> 16..254 edges, every fused E bucket incl. the XLA ladder top)
    polys = [
        jagged(
            float(qrng.uniform(-150, 150)), float(qrng.uniform(-60, 60)),
            float(qrng.choice([0.5, 1.0, 2.0])),
            int(qrng.choice([8, 16, 50, 127])), seed=k,
        )
        for k in range(n_q)
    ]

    def _timed(fn):
        t0 = time.perf_counter()
        out = fn()
        return time.perf_counter() - t0, out

    def resolve_batch(cfgs):
        """Fused batch + exact host residue refinement -> per-query
        sorted true-hit ordinal arrays (what the planner produces)."""
        outs = [f() for f in table.scan_submit_many(list(cfgs))]
        final = []
        for p, (rows, cert) in zip(polys, outs):
            unc = np.flatnonzero(~cert)
            keep = cert.copy()
            if len(unc):
                ux, uy = px[rows[unc]], py[rows[unc]]
                ok = geo.points_in_polygon(ux, uy, p)
                nb = np.flatnonzero(~ok)  # intersects: boundary counts
                if len(nb):
                    ok[nb] = geo.points_on_boundary(ux[nb], uy[nb], p)
                keep[unc] = ok
            final.append(np.sort(rows[keep]))
        return final

    def run_batch(label):
        ds.planner.invalidate_config_memo()
        fr.clear_cache()
        cfgs = [idx.scan_config(Intersects("geom", p)) for p in polys]
        resolve_batch(cfgs)  # warm compiles
        best = min(_timed(lambda: resolve_batch(cfgs))[0] for _ in range(repeat))
        final = resolve_batch(cfgs)
        log(f"[pip_join] {label}: {best / n_q * 1e3:.2f} ms/q")
        return best, final, cfgs

    RASTER_ENABLED.set(False)
    t_off, final_off, cfgs_off = run_batch("exact (raster off)")
    RASTER_ENABLED.set(None)
    t_on, final_on, cfgs_on = run_batch("raster on")
    identical = all(
        np.array_equal(a, b) for a, b in zip(final_off, final_on)
    )
    assert identical  # recorded either way (python -O safe)
    rows = [{
        "scenario": "z2_polygon_pip_batch",
        "queries": n_q,
        "exact_ms_per_q": round(t_off / n_q * 1e3, 3),
        "raster_ms_per_q": round(t_on / n_q * 1e3, 3),
        "speedup": round(t_off / max(t_on, 1e-9), 2),
        "identical": bool(identical),
        "rasterized_queries": int(sum(c.rast is not None for c in cfgs_on)),
    }]
    log(f"[pip_join] z2_polygon_pip_batch speedup {rows[0]['speedup']}x")

    # -- polygon-heavy indexed join --------------------------------------
    n_poly = int(os.environ.get("GEOMESA_BENCH_PIP_POLYS", 128))
    jrng = np.random.default_rng(SEED + 92)
    jpolys = [
        jagged(
            float(jrng.uniform(-150, 150)), float(jrng.uniform(-60, 60)),
            float(jrng.uniform(1.0, 6.0)), int(jrng.choice([8, 16, 50, 127])),
            seed=1000 + k,
        )
        for k in range(n_poly)
    ]
    gsft = FeatureType.from_spec("adm", "*geom:Polygon:srid=4326")
    left = FeatureCollection.from_columns(
        gsft, np.arange(n_poly),
        {"geom": geo.PackedGeometryColumn.from_geometries(jpolys)},
    )

    def run_join(label, enabled):
        RASTER_ENABLED.set(enabled if not enabled else None)
        ds.planner.invalidate_config_memo()
        fr.clear_cache()
        spatial_join_indexed(ds, "fp", left, "intersects")  # warm
        best, pairs = None, None
        for _ in range(repeat):
            t, out = _timed(
                lambda: spatial_join_indexed(ds, "fp", left, "intersects")
            )
            if best is None or t < best:
                best, pairs = t, out
        log(f"[pip_join] join {label}: {best * 1e3:.0f} ms, {len(pairs[0])} pairs")
        return best, pairs

    t_joff, p_off = run_join("exact (raster off)", False)
    t_jon, p_on = run_join("raster on", True)
    RASTER_ENABLED.set(None)
    join_identical = np.array_equal(p_off[0], p_on[0]) and np.array_equal(
        p_off[1], p_on[1]
    )
    assert join_identical
    rows.append({
        "scenario": "z2_polygon_join",
        "polygons": n_poly,
        "pairs": int(len(p_on[0])),
        "exact_ms": round(t_joff * 1e3, 1),
        "raster_ms": round(t_jon * 1e3, 1),
        "speedup": round(t_joff / max(t_jon, 1e-9), 2),
        "identical": bool(join_identical),
    })
    log(f"[pip_join] z2_polygon_join speedup {rows[-1]['speedup']}x")

    # -- host grid join: exact vs adaptive -------------------------------
    sub = min(n, 2_000_000)
    right = FeatureCollection.from_columns(
        sft, np.arange(sub), {"geom": (px[:sub], py[:sub])}
    )
    m = MetricsRegistry()
    t_hex, h_ex = _timed(
        lambda: spatial_join(left, right, "intersects", strategy="exact")
    )
    t_had, h_ad = _timed(
        lambda: spatial_join(
            left, right, "intersects", strategy="auto", metrics=m
        )
    )
    host_identical = np.array_equal(h_ex[0], h_ad[0]) and np.array_equal(
        h_ex[1], h_ad[1]
    )
    assert host_identical
    rows.append({
        "scenario": "host_grid_join",
        "pairs": int(len(h_ex[0])),
        "exact_ms": round(t_hex * 1e3, 1),
        "adaptive_ms": round(t_had * 1e3, 1),
        "speedup": round(t_hex / max(t_had, 1e-9), 2),
        "identical": bool(host_identical),
        "raster_partitions": m.counter_value("geomesa.join.strategy.raster"),
        "exact_partitions": m.counter_value("geomesa.join.strategy.exact"),
    })
    log(f"[pip_join] host_grid_join speedup {rows[-1]['speedup']}x")

    payload = {
        "n_rows": n,
        "queries_per_batch": n_q,
        "platform": jax.default_backend(),
        "rows": rows,
    }
    if out_path is None:
        out_path = os.environ.get("GEOMESA_BENCH_PIP_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_PIP_JOIN.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec = {
        "metric": "z2_polygon_pip_batch_raster_speedup",
        "value": rows[0]["speedup"],
        "unit": "x",
        "raster_ms_per_q": rows[0]["raster_ms_per_q"],
        "exact_ms_per_q": rows[0]["exact_ms_per_q"],
        "join_speedup": rows[1]["speedup"],
        "rows": rows,
        "n_rows": n,
    }
    print(json.dumps(rec), flush=True)
    return rec


# ---------------------------------------------------- streaming scenario


def config_stream(out_path: "str | None" = None):
    """Production streaming tier scenario (round 9, docs/streaming.md):
    sustained micro-batch ingest through the LambdaStore while a
    concurrent mixed query workload runs against the hot+cold merge.

    The moving-objects workload: a cold z3 store of N tracked objects;
    each flush batch is half UPDATES of existing ids (objects reporting
    new positions with fresh timestamps) and half NEW ids (arrivals).
    Two ingest paths at the same batch sizes:

    - ``legacy``: the pre-round-9 per-flush full persist —
      ``write`` + ``persist_hot(incremental=False)``, a delete-and-
      rewrite recompaction of the whole cold table per flush;
    - ``streamed``: ``write`` + micro-batch ``flush()`` — appends ride
      the O(batch) delta tier, updates hold in the exact hot overlay
      and fold incrementally past ``geomesa.stream.fold.rows``
      (``DataStore.fold_upsert``), with a final full persist included
      in the measured wall clock.

    During the streamed run, client threads issue mixed bbox/bbox+time
    queries through ``LambdaStore.query`` with the cold store's
    QueryScheduler attached (fused dispatches + shedding while ingest
    runs); their p50/p99 are recorded against the declared SLO. The
    legacy baseline runs WITHOUT the query load (favoring the
    baseline). Exactness is computed in-bench: after the run, every
    probe query against the streamed store must return the same id set
    and attribute values as a fresh batch-loaded oracle holding the
    expected final state -> the ``identical`` flag
    ``scripts/bench_gate.py`` enforces.

    Emits BENCH_STREAM.json next to this file (or at ``out_path`` / env
    GEOMESA_BENCH_STREAM_OUT — use a SCRATCH path when producing the
    fresh side of a gate comparison). Env knobs:
    GEOMESA_BENCH_STREAM_N (cold rows), GEOMESA_BENCH_STREAM_BATCH
    (rows per flush), GEOMESA_BENCH_STREAM_FLUSHES,
    GEOMESA_BENCH_STREAM_CLIENTS (query threads),
    GEOMESA_BENCH_STREAM_SLO_MS (query p99 SLO)."""
    import threading

    from geomesa_tpu import geometry as geo
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.sft import FeatureType
    from geomesa_tpu.streaming import LambdaStore, StreamConfig

    n = int(os.environ.get("GEOMESA_BENCH_STREAM_N", 3_000_000))
    batch = int(os.environ.get("GEOMESA_BENCH_STREAM_BATCH", 20_000))
    flushes = int(os.environ.get("GEOMESA_BENCH_STREAM_FLUSHES", 24))
    # the legacy baseline's per-flush cost is stationary (O(table) each
    # flush): fewer flushes measure the same rate in half the wall —
    # and bias FOR the baseline, since its table is smaller on average
    legacy_flushes = int(os.environ.get(
        "GEOMESA_BENCH_STREAM_LEGACY_FLUSHES", max(min(flushes, 12), 1)
    ))
    # query load sized to the host: half the cores as open-loop
    # dashboard clients (a 2-core CI box gets 2 clients; a serving host
    # scales up via the env knobs)
    clients = int(os.environ.get(
        "GEOMESA_BENCH_STREAM_CLIENTS", max(2, (os.cpu_count() or 2) // 2)
    ))
    poll_ms = float(os.environ.get("GEOMESA_BENCH_STREAM_POLL_MS", 150.0))
    # declared p99 SLO for dashboard reads under sustained ingest on the
    # SHARED 2-core CPU CI host (p50 sits ~50-60 ms; the tail is core
    # contention with the flush stages plus neighbor load — serving
    # hosts with spare cores run far tighter; observed p99 across runs
    # spans ~200-800 ms on this box)
    slo_ms = float(os.environ.get("GEOMESA_BENCH_STREAM_SLO_MS", 1000.0))
    t0_ms = 1_717_200_000_000  # 2024-06-01T00:00:00Z
    day = 86_400_000
    spec = "name:String,dtg:Date,*geom:Point:srid=4326"

    def build():
        rng = np.random.default_rng(SEED + 90)
        ds = DataStore()
        sft = FeatureType.from_spec("mv", spec)
        ds.create_schema(sft)
        ds.write("mv", FeatureCollection.from_columns(
            sft, np.arange(n).astype(str), {
                "name": np.array(["v"] * n),
                "dtg": t0_ms + rng.integers(0, 7 * day, n),
                "geom": (rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)),
            }), check_ids=False)
        ds.compact("mv")
        return ds

    # the message stream (the producer side): prebuilt so both runs
    # ingest the identical sequence
    log(f"[stream] building {flushes} x {batch:,}-row message stream ...")
    rng = np.random.default_rng(SEED + 91)
    stream = []
    state: dict = {}
    for k in range(flushes):
        upd = rng.choice(n, batch // 2, replace=False)
        ids = [str(i) for i in upd] + [
            f"new{k}_{j}" for j in range(batch - batch // 2)
        ]
        xs = rng.uniform(-170, 170, batch)
        ys = rng.uniform(-80, 80, batch)
        ts = t0_ms + 8 * day + rng.integers(0, day, batch).astype(np.int64)
        rows = [
            {"name": f"r{k}", "dtg": int(ts[j]),
             "geom": geo.Point(float(xs[j]), float(ys[j]))}
            for j in range(batch)
        ]
        stream.append((rows, ids))
        for j, fid in enumerate(ids):
            state[fid] = (f"r{k}", float(xs[j]), float(ys[j]), int(ts[j]))

    def qpool(seed):
        # city/regional dashboard windows: small boxes (the serving
        # bench's scale) so the query mix models live dashboards, not
        # continental exports
        qrng = np.random.default_rng(seed)
        out = []
        for _ in range(256):
            w = float(qrng.choice([0.5, 1.0, 2.0]))
            qx = qrng.uniform(-165, 165 - w)
            qy = qrng.uniform(-75, 75 - w / 2)
            q = f"bbox(geom, {qx:.3f}, {qy:.3f}, {qx + w:.3f}, {qy + w / 2:.3f})"
            if qrng.random() < 0.3:
                q += (" AND dtg DURING "
                      "2024-06-01T00:00:00Z/2024-06-10T00:00:00Z")
            out.append(q)
        return out

    # -- legacy baseline: full persist per flush, no query load ----------
    log(f"[stream] building {n:,}-row cold store (legacy run) ...")
    ds = build()
    lam = LambdaStore(ds, "mv")
    t0 = time.perf_counter()
    for rows, ids in stream[:legacy_flushes]:
        for s in range(0, len(rows), 2048):  # same consumer loop shape
            lam.write(
                [dict(r) for r in rows[s : s + 2048]], ids=ids[s : s + 2048]
            )
        lam.persist_hot(incremental=False)
    legacy_s = time.perf_counter() - t0
    legacy_rps = legacy_flushes * batch / legacy_s
    lam.close()
    log(f"[stream] legacy full-persist path: {legacy_rps:,.0f} rows/s")

    # -- streamed run: micro-batch flushes + concurrent query load -------
    log(f"[stream] building {n:,}-row cold store (streamed run) ...")
    reg = MetricsRegistry()
    ds = build()
    ds.metrics = reg
    # fold threshold above the run's total updates: the ONE fold happens
    # at the explicit final persist, whose window is timed separately
    # below (the "GC pause" of the LSM design — queries inside it queue
    # behind the O(table) device re-upload)
    lam = LambdaStore(ds, "mv", config=StreamConfig(
        fold_rows=batch * flushes + 1,
    ))
    lam.serve()
    # compile EVERY scan-kernel variant (single-query ladder + the fused
    # multi-query shapes, all predicate-flag combos) before the clock
    # starts: a first-hit XLA compile landing mid-run would show up in
    # the measured p99 as a ~second-long straggler
    ds.warmup("mv")
    for q in qpool(SEED + 92)[:8]:
        lam.query(q)
    ds.query_many("mv", qpool(SEED + 92)[8:16])
    stop = threading.Event()
    lat: list = []
    lat_lock = threading.Lock()

    def client(seed):
        # open-loop dashboard poll: one query per poll interval (a
        # closed-loop hammer would just consume every spare core and
        # measure CPU contention, not serving latency at a stated load)
        pool = qpool(seed)
        local = []
        i = 0
        while not stop.is_set():
            s = time.perf_counter()
            lam.query(pool[i % len(pool)])
            dt = time.perf_counter() - s
            local.append((s, dt))
            i += 1
            stop.wait(max(poll_ms / 1e3 - dt, 0.0))
        with lat_lock:
            lat.extend(local)

    threads = [
        threading.Thread(target=client, args=(SEED + 100 + c,))
        for c in range(clients)
    ]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    for rows, ids in stream:
        # the consumer loop: messages apply in small sub-batches (a real
        # stream consumer polls continuously; one monolithic 20k-row
        # write would hold the interpreter in a single burst)
        for s in range(0, len(rows), 2048):
            lam.write(
                [dict(r) for r in rows[s : s + 2048]], ids=ids[s : s + 2048]
            )
        lam.flush()
    fold_t0 = time.perf_counter()
    lam.persist_hot()  # the final fold is part of the measured wall
    fold_t1 = streamed_s = time.perf_counter()
    streamed_s -= t0
    stop.set()
    for t in threads:
        t.join()
    streamed_rps = flushes * batch / streamed_s
    # SLO accounting: steady-state micro-batch queries vs queries that
    # overlapped the fold window. Round 11 killed the monolithic pause
    # (pre-staged parse/keys + sliced publishes + scheduler yielding —
    # docs/streaming.md "Incremental fold"): the window is now a train
    # of bounded per-slice pauses, reported as a histogram, and the
    # in-window query p99 is gated against steady state
    steady = np.array([d for s, d in lat if s + d < fold_t0]) * 1e3
    in_fold = np.array([d for s, d in lat if s + d >= fold_t0]) * 1e3
    p50 = float(np.percentile(steady, 50)) if len(steady) else 0.0
    p99 = float(np.percentile(steady, 99)) if len(steady) else 0.0
    fold_p99 = float(np.percentile(in_fold, 99)) if len(in_fold) else 0.0
    report = getattr(ds, "last_fold_report", None) or {}
    slice_ms = np.array(report.get("slice_s", [])) * 1e3
    fold_hist = {
        "count": int(len(slice_ms)),
        "p50_ms": round(float(np.percentile(slice_ms, 50)), 2) if len(slice_ms) else 0.0,
        "p99_ms": round(float(np.percentile(slice_ms, 99)), 2) if len(slice_ms) else 0.0,
        "max_ms": round(float(slice_ms.max()), 2) if len(slice_ms) else 0.0,
    }
    prestaged = reg.counter_value("geomesa.stream.fold.prestaged")
    log(
        f"[stream] streamed path: {streamed_rps:,.0f} rows/s with "
        f"{len(lat)} concurrent queries (steady p99 {p99:.1f} ms; "
        f"fold window {fold_t1 - fold_t0:.2f}s over {fold_hist['count']} "
        f"slices, max slice pause {fold_hist['max_ms']:.0f} ms, "
        f"in-window p99 {fold_p99:.1f} ms, {prestaged} rows pre-staged)"
    )

    # -- exactness: streamed store vs batch-loaded oracle ----------------
    log("[stream] exactness: batch-loaded oracle comparison ...")
    oracle = DataStore()
    osft = FeatureType.from_spec("mv", spec)
    oracle.create_schema(osft)
    base_rng = np.random.default_rng(SEED + 90)  # replay build()'s draws
    bt = t0_ms + base_rng.integers(0, 7 * day, n)  # dtg drawn first
    bx = base_rng.uniform(-170, 170, n)
    by = base_rng.uniform(-80, 80, n)
    # expected final state: the original rows, overridden by the stream
    oids = np.arange(n).astype(str).tolist() + sorted(
        fid for fid in state if not fid.isdigit()
    )
    names, oxs, oys, ots = [], [], [], []
    for i, fid in enumerate(oids):
        if fid in state:
            nm, x, y, tms = state[fid]
        else:
            nm, x, y, tms = "v", float(bx[i]), float(by[i]), int(bt[i])
        names.append(nm), oxs.append(x), oys.append(y), ots.append(tms)
    oracle.write("mv", FeatureCollection.from_columns(osft, oids, {
        "name": np.array(names),
        "dtg": np.array(ots, np.int64),
        "geom": (np.array(oxs), np.array(oys)),
    }), check_ids=False)
    identical = True
    for q in qpool(SEED + 93)[:24]:
        got = lam.query(q)
        want = oracle.query("mv", q)
        gi = np.argsort(got.ids)
        wi = np.argsort(want.ids)
        gg, wg = got.geom_column, want.geom_column
        same = (
            len(got) == len(want)
            and np.array_equal(np.asarray(got.ids)[gi], np.asarray(want.ids)[wi])
            and np.array_equal(
                np.asarray(got.columns["name"])[gi],
                np.asarray(want.columns["name"])[wi],
            )
            # every attribute, bit-for-bit: a fold bug that drifted
            # coordinates or timestamps while keeping rows inside the
            # probe boxes must break the identical flag, not pass it
            and np.array_equal(gg.x[gi], wg.x[wi])
            and np.array_equal(gg.y[gi], wg.y[wi])
            and np.array_equal(
                np.asarray(got.columns["dtg"], np.int64)[gi],
                np.asarray(want.columns["dtg"], np.int64)[wi],
            )
        )
        if not same:
            identical = False
            log(f"[stream] MISMATCH on {q}")
    lam.close()
    ds.scheduler.close()

    speedup = streamed_rps / max(legacy_rps, 1e-9)
    slo_met = bool(p99 <= slo_ms) if len(steady) else True
    # the round-11 acceptance bar: query p99 INSIDE the fold window must
    # stay within 2x the steady-state p99 (the pause-kill claim, gated by
    # scripts/bench_gate.py FRESH_BOUNDS as a within-run invariant)
    fold_over_steady = round(fold_p99 / max(p99, 1e-9), 2) if len(in_fold) else 0.0
    row = {
        "scenario": "stream_sustained",
        "cold_rows": n,
        "batch_rows": batch,
        "flushes": flushes,
        # absolute rows/s and latencies are HOST-dependent (the round-9
        # baseline ran on 2 cores; round 11 re-pinned on 1): record the
        # run's core count so a baseline comparison across hosts is
        # interpretable in the artifact itself
        "host_cores": os.cpu_count(),
        "legacy_rows_per_s": round(legacy_rps, 1),
        "streamed_rows_per_s": round(streamed_rps, 1),
        "speedup": round(speedup, 2),
        "identical": identical,
        "query": {
            "clients": clients,
            "poll_ms": poll_ms,
            "queries": int(len(lat)),
            "p50_ms": round(p50, 2),
            "p99_ms": round(p99, 2),
            "slo_ms": slo_ms,
            "slo_met": slo_met,
            "fold_window_s": round(fold_t1 - fold_t0, 2),
            "in_fold_queries": int(len(in_fold)),
            "fold_window_p99_ms": round(fold_p99, 2),
            "fold_window_p99_over_steady": fold_over_steady,
        },
        "fold": {
            "rows": int(report.get("rows", 0)),
            "slices": int(report.get("slices", 0)),
            "prestaged_rows": int(prestaged),
            "slice_pause_ms": fold_hist,
        },
        **LINK_PROFILE,
    }
    log(
        f"[stream] sustained {streamed_rps:,.0f} vs legacy "
        f"{legacy_rps:,.0f} rows/s = {speedup:.2f}x, identical={identical}, "
        f"steady p99 {p99:.1f} ms (SLO {slo_ms:.0f} ms, met={slo_met}), "
        f"fold-window p99 {fold_p99:.1f} ms = {fold_over_steady}x steady"
    )

    import jax

    payload = {
        "platform": jax.default_backend(),
        "rows": [row],
    }
    if out_path is None:
        out_path = os.environ.get("GEOMESA_BENCH_STREAM_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_STREAM.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec = {
        "metric": "stream_sustained_rows_per_s",
        "value": row["streamed_rows_per_s"],
        "unit": "rows/s",
        "vs_baseline": row["speedup"],
        "identical": identical,
        "query_p99_ms": row["query"]["p99_ms"],
        "slo_met": slo_met,
        "cold_rows": n,
    }
    print(json.dumps(rec), flush=True)
    return rec


def config_knn(out_path: "str | None" = None):
    """Batched kNN throughput scenario (round 11; VERDICT weak #5's
    34.7 q/s vs the 60 q/s bar): ``knn_many`` over trajectory-shaped
    points — every pending query's speculative wide window rides ONE
    ``planner.submit_many`` sweep per round, fusing into shared
    ``block_scan_multi`` dispatches (round 11 halved the per-query
    windows: the estimate radius resolves from the wide window's own
    result, see process/knn.py).

    Exactness is computed in-bench: every measured query's result must
    match (ids, in order) both the per-point ``knn_search`` protocol and
    a brute-force full-scan haversine top-k oracle -> the ``identical``
    flag ``scripts/bench_gate.py`` enforces alongside the q/s floor.

    Emits BENCH_KNN.json (or ``out_path`` / env GEOMESA_BENCH_KNN_OUT —
    use a SCRATCH path for the fresh side of a gate comparison). Env:
    GEOMESA_BENCH_KNN_N (points), GEOMESA_BENCH_KNN_QUERIES."""
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.process import knn_many, knn_search
    from geomesa_tpu.process.knn import haversine_m
    from geomesa_tpu.sft import FeatureType

    n = int(os.environ.get("GEOMESA_BENCH_KNN_N", 2_000_000))
    n_q = int(os.environ.get("GEOMESA_BENCH_KNN_QUERIES", 64))
    k = 10
    rng = np.random.default_rng(SEED + 50)
    n_tracks = max(n // 4000, 8)
    per = n // n_tracks
    sx = rng.uniform(-170, 170, n_tracks)
    sy = rng.uniform(-75, 75, n_tracks)
    x = np.clip(
        (sx[:, None] + np.cumsum(rng.normal(0, 0.02, (n_tracks, per)), axis=1)).ravel(),
        -180, 180,
    )
    y = np.clip(
        (sy[:, None] + np.cumsum(rng.normal(0, 0.015, (n_tracks, per)), axis=1)).ravel(),
        -90, 90,
    )
    log(f"[knn] building {len(x):,} point store ...")
    sft = FeatureType.from_spec("ais", "*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z2"
    ds = DataStore()
    ds.create_schema(sft)
    ds.write(
        "ais",
        FeatureCollection.from_columns(sft, np.arange(len(x)), {"geom": (x, y)}),
        check_ids=False,
    )
    qs = [
        (float(rng.uniform(-150, 150)), float(rng.uniform(-60, 60)))
        for _ in range(n_q)
    ]
    knn_search(ds, "ais", *qs[0], k=k)  # warmup compiles
    knn_many(ds, "ais", qs[:3], k=k)    # + the fused batch variants

    best = None
    for _ in range(2):  # best-of-2: shared-host noise
        t0 = time.perf_counter()
        outs = knn_many(ds, "ais", qs, k=k)
        wall = time.perf_counter() - t0
        best = wall if best is None else min(best, wall)
    qps = n_q / best

    log("[knn] exactness: per-point + brute-force oracle comparison ...")
    identical = True
    for i, (qx, qy) in enumerate(qs):
        got = [str(v) for v in outs[i].ids.tolist()]
        single = [
            str(v) for v in knn_search(ds, "ais", qx, qy, k=k).ids.tolist()
        ]
        if got != single:
            identical = False
            log(f"[knn] MISMATCH vs per-point at query {i}")
        d = haversine_m(x, y, qx, qy)
        kth = np.partition(d, k - 1)[k - 1]
        sub = np.nonzero(d <= kth)[0]
        want = sub[np.argsort(d[sub], kind="stable")][:k]
        if kth <= 1_000_000.0 and got != [str(j) for j in want.tolist()]:
            identical = False
            log(f"[knn] MISMATCH vs brute oracle at query {i}")

    row = {
        "scenario": "knn_batched",
        "n_points": int(len(x)),
        "queries": n_q,
        "k": k,
        "host_cores": os.cpu_count(),
        "batched_qps": round(qps, 1),
        "batched_wall_s": round(best, 3),
        "identical": identical,
        **LINK_PROFILE,
    }
    log(f"[knn] batched {qps:.1f} q/s over {n_q} queries, identical={identical}")

    import jax

    payload = {"platform": jax.default_backend(), "rows": [row]}
    if out_path is None:
        out_path = os.environ.get("GEOMESA_BENCH_KNN_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_KNN.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec = {
        "metric": "knn_batched_queries_per_sec",
        "value": row["batched_qps"],
        "unit": "q/s",
        "vs_baseline": round(qps / 60.0, 2),  # the VERDICT 60 q/s bar
        "identical": identical,
        "n_points": int(len(x)),
    }
    print(json.dumps(rec), flush=True)
    return rec


def config_wal(out_path: "str | None" = None):
    """Streaming WAL overhead + recovery scenario (ISSUE 10,
    docs/durability.md "Streaming WAL"): the SAME micro-batch
    write+flush workload runs four times — no WAL, then WAL under
    ``sync=off`` / ``interval`` / ``always`` — and sustained rows/s is
    recorded for each; then a separate run streams
    ``GEOMESA_BENCH_WAL_REPLAY`` rows (with periodic flush watermarks),
    hard-kills, and times ``LambdaStore.recover`` end to end.

    Exactness is computed in-bench: after the ``sync=always`` run the
    store is recovered from disk and every probe query must return the
    same ids and values as the live (never-killed) store — the
    ``identical`` flag ``scripts/bench_gate.py`` enforces, alongside the
    within-run bound that ``sync=interval`` throughput stays within 15%
    of the no-WAL path.

    Emits BENCH_WAL.json (or ``out_path`` / GEOMESA_BENCH_WAL_OUT — use
    a scratch path for the fresh side of a gate run). Env knobs:
    GEOMESA_BENCH_WAL_COLD (cold rows), GEOMESA_BENCH_WAL_N (streamed
    rows per mode), GEOMESA_BENCH_WAL_BATCH, GEOMESA_BENCH_WAL_REPLAY
    (rows in the recovery run)."""
    import shutil
    import tempfile

    from geomesa_tpu import geometry as geo
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType
    from geomesa_tpu.storage import persist
    from geomesa_tpu.streaming import LambdaStore, StreamConfig, WalConfig

    n_cold = int(os.environ.get("GEOMESA_BENCH_WAL_COLD", 200_000))
    n_stream = int(os.environ.get("GEOMESA_BENCH_WAL_N", 400_000))
    batch = int(os.environ.get("GEOMESA_BENCH_WAL_BATCH", 20_000))
    n_replay = int(os.environ.get("GEOMESA_BENCH_WAL_REPLAY", 1_000_000))
    t0_ms = 1_717_200_000_000
    spec = "name:String,dtg:Date,*geom:Point:srid=4326"

    def build_root(base_dir):
        rng = np.random.default_rng(SEED + 95)
        ds = DataStore()
        sft = FeatureType.from_spec("mv", spec)
        ds.create_schema(sft)
        if n_cold:
            ds.write("mv", FeatureCollection.from_columns(
                sft, np.arange(n_cold).astype(str), {
                    "name": np.array(["v"] * n_cold),
                    "dtg": t0_ms + rng.integers(0, 86_400_000, n_cold),
                    "geom": (rng.uniform(-170, 170, n_cold),
                             rng.uniform(-80, 80, n_cold)),
                }), check_ids=False)
            ds.compact("mv")
        root = os.path.join(base_dir, "s")
        persist.save(ds, root)
        return ds, root

    def message_stream(n):
        """Prebuilt (ids, rows) batches: half updates of cold ids, half
        arrivals — identical across every mode."""
        rng = np.random.default_rng(SEED + 96)
        out = []
        arrivals = 0
        for s in range(0, n, batch):
            k = min(batch, n - s)
            ids, rows = [], []
            upd = rng.integers(0, max(n_cold, 1), k // 2)
            xs = rng.uniform(-170, 170, k)
            ys = rng.uniform(-80, 80, k)
            for j in range(k):
                if j < k // 2 and n_cold:
                    ids.append(str(int(upd[j])))
                else:
                    arrivals += 1
                    ids.append(f"a{arrivals}")
                rows.append({
                    "name": "u", "dtg": t0_ms + s + j,
                    "geom": geo.Point(float(xs[j]), float(ys[j])),
                })
            out.append((ids, rows))
        return out

    stream = message_stream(n_stream)
    probes = [
        "bbox(geom, -40, -40, 0, 0)", "bbox(geom, 10, 10, 60, 50)",
        "IN ('0', '1', 'a1', 'a2')",
    ]

    def run_mode(mode):
        """One full streamed run; returns (rows/s, lam, root, tmp)."""
        tmp = tempfile.mkdtemp(prefix="geomesa_wal_bench_")
        ds, root = build_root(tmp)
        kw = {}
        if mode != "nowal":
            kw = dict(
                wal_dir=os.path.join(root, "_wal"),
                wal_config=WalConfig(sync=mode),
            )
        lam = LambdaStore(ds, "mv", config=StreamConfig(), **kw)
        t0 = time.perf_counter()
        for ids, rows in stream:
            lam.write(rows, ids=ids)
            lam.flush()
        dt = time.perf_counter() - t0
        return n_stream / dt, lam, root, tmp

    # warmup: one discarded short run so the first MEASURED mode does
    # not pay the fold/scan kernel compilations for everyone
    log("[wal] warmup ...")
    tmpw = tempfile.mkdtemp(prefix="geomesa_wal_warm_")
    dsw, _rootw = build_root(tmpw)
    lamw = LambdaStore(dsw, "mv", config=StreamConfig())
    for ids, rows in stream[: max(1, min(3, len(stream)))]:
        lamw.write(rows, ids=ids)
        lamw.flush()
    lamw.close()
    shutil.rmtree(tmpw, ignore_errors=True)

    # best-of-N per mode: the measured window is seconds on a SHARED CI
    # host, and a neighbor's burst during one mode would otherwise read
    # as WAL overhead (or mask it); every repeat streams the identical
    # prebuilt message sequence
    repeat = int(os.environ.get("GEOMESA_BENCH_WAL_REPEAT", 2))
    results = {}
    keep = {}
    for mode in ("nowal", "off", "interval", "always"):
        best = 0.0
        for r in range(max(repeat, 1)):
            rps, lam, root, tmp = run_mode(mode)
            best = max(best, rps)
            last = r == max(repeat, 1) - 1
            if mode == "always" and last:
                keep = {"lam": lam, "root": root, "tmp": tmp}
            else:
                lam.close()
                shutil.rmtree(tmp, ignore_errors=True)
        results[mode] = best
        log(f"[wal] {mode}: {best:,.0f} rows/s (best of {repeat})")

    # exactness: hard-kill the sync=always store and recover from disk
    lam, root = keep["lam"], keep["root"]
    live = [sorted(zip(
        (str(i) for i in lam.query(q).ids.tolist()),
        (str(v) for v in np.asarray(lam.query(q).columns["name"]).tolist()),
    )) for q in probes]
    lam.wal.crash()
    lam.flusher.close()
    rec = LambdaStore.recover(root)
    recovered = [sorted(zip(
        (str(i) for i in rec.query(q).ids.tolist()),
        (str(v) for v in np.asarray(rec.query(q).columns["name"]).tolist()),
    )) for q in probes]
    identical = bool(
        recovered == live and rec.cold.store_health.status == "ok"
    )
    rec.close()
    shutil.rmtree(keep["tmp"], ignore_errors=True)

    # recovery throughput: stream n_replay rows (periodic flushes leave
    # watermarks in the log), hard-kill, time the full recover()
    tmp = tempfile.mkdtemp(prefix="geomesa_wal_replay_")
    ds, root = build_root(tmp)
    lam = LambdaStore(
        ds, "mv", config=StreamConfig(),
        wal_dir=os.path.join(root, "_wal"),
        wal_config=WalConfig(sync="off"),  # isolate REPLAY cost
    )
    rng = np.random.default_rng(SEED + 97)
    for s in range(0, n_replay, batch):
        k = min(batch, n_replay - s)
        xs = rng.uniform(-170, 170, k)
        ys = rng.uniform(-80, 80, k)
        lam.write(
            [{"name": "r", "dtg": t0_ms + s + j,
              "geom": geo.Point(float(xs[j]), float(ys[j]))}
             for j in range(k)],
            ids=[f"r{s + j}" for j in range(k)],
        )
        lam.flush()
    lam.wal.sync()  # sync=off: drains the app buffer (no fsync)
    lam.wal.crash()
    lam.flusher.close()
    # best-of-N like the stream modes: recovery is idempotent off the
    # same on-disk root, and a neighbor's burst during the one measured
    # window would otherwise read as replay cost
    recover_s = float("inf")
    for _ in range(max(repeat, 1)):
        t0 = time.perf_counter()
        rec = LambdaStore.recover(root)
        recover_s = min(recover_s, time.perf_counter() - t0)
        replayed = len(rec.cold.features("mv")) + len(rec.hot) - n_cold
        rec.close()
    shutil.rmtree(tmp, ignore_errors=True)

    interval_over_nowal = results["interval"] / results["nowal"]
    row = {
        "scenario": "stream_wal",
        "cold_rows": n_cold, "streamed_rows": n_stream, "batch": batch,
        "nowal_rows_per_s": round(results["nowal"], 1),
        "wal_off_rows_per_s": round(results["off"], 1),
        "wal_interval_rows_per_s": round(results["interval"], 1),
        "wal_always_rows_per_s": round(results["always"], 1),
        "interval_over_nowal": round(interval_over_nowal, 4),
        "identical": identical,
    }
    replay_row = {
        "scenario": "wal_replay",
        "replay_rows": n_replay, "replayed_rows": int(replayed),
        "recover_s": round(recover_s, 3),
        "replay_rows_per_s": round(n_replay / recover_s, 1),
        # exactness proxy the gate enforces: recovery surfaced every
        # streamed row, none lost, none invented
        "identical": bool(int(replayed) == n_replay),
    }
    log(
        f"[wal] interval/nowal = {interval_over_nowal:.3f}, "
        f"always = {results['always'] / results['nowal']:.3f}x of nowal, "
        f"identical={identical}; replay {n_replay:,} rows in "
        f"{recover_s:.1f}s = {n_replay / recover_s:,.0f} rows/s"
    )

    import jax

    payload = {"platform": jax.default_backend(), "rows": [row, replay_row]}
    if out_path is None:
        out_path = os.environ.get("GEOMESA_BENCH_WAL_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_WAL.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec_line = {
        "metric": "wal_interval_rows_per_s",
        "value": row["wal_interval_rows_per_s"],
        "unit": "rows/s",
        "interval_over_nowal": row["interval_over_nowal"],
        "identical": identical,
        "replay_rows_per_s": replay_row["replay_rows_per_s"],
    }
    print(json.dumps(rec_line), flush=True)
    return rec_line


# ------------------------------------------------- config standing


def config_standing(out_path: "str | None" = None):
    """Standing-query matching scenario (ISSUE 14, docs/standing.md):
    >= 1M persistent geofence subscriptions indexed by the inverted
    SubscriptionIndex, probed by a sustained ingest stream.

    Within ONE run it measures: (1) sustained ingest rows/s with the
    matcher OFF vs ON (the matcher rides the write ack path — the gate
    holds the ON rate at >= 0.9x OFF); (2) pure per-event matching cost
    through the inverted index vs a NAIVE all-subscription evaluation
    (vectorized bbox prefilter over every registered subscription +
    exact ragged PIP on the bbox hits — not a strawman) on a sampled
    event set, the >= 50x algorithmic-win floor; (3) match-set
    exactness vs a per-event shapely oracle over bbox-candidate pairs
    (complete: truth and matches are both subsets of the bbox
    candidates) — the ``identical`` flag; (4) the alert-latency p99
    off the live ``geomesa.standing.latency`` histogram.

    The subscription population is deliberately mixed: ~1M tiny squares
    (the routing-scale test — most register 1-4 PARTIAL cells), a
    dense-polygon hotspot (jagged stars across the FUSED_E_BUCKETS
    ladder, where 20% of events cluster, so the fused kernel path
    engages), and large convex fences whose interiors classify FULL
    (zero-geometry-work matches).

    Emits BENCH_GEOFENCE.json (or ``out_path`` /
    GEOMESA_BENCH_GEOFENCE_OUT — use a scratch path for the fresh side
    of a gate run). Env knobs: GEOMESA_BENCH_GEOFENCE_SUBS,
    GEOMESA_BENCH_GEOFENCE_N, GEOMESA_BENCH_GEOFENCE_BATCH,
    GEOMESA_BENCH_GEOFENCE_ORACLE (sampled oracle events),
    GEOMESA_BENCH_GEOFENCE_NAIVE (sampled naive events)."""
    from shapely.geometry import Point as SPoint
    from shapely.geometry import Polygon as SPolygon

    from geomesa_tpu import geometry as geo
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.scan import block_kernels as bk
    from geomesa_tpu.sft import FeatureType
    from geomesa_tpu.streaming import LambdaStore, StreamConfig
    from geomesa_tpu.streaming.standing import _ragged_pip

    import shutil
    import tempfile

    n_subs = int(os.environ.get("GEOMESA_BENCH_GEOFENCE_SUBS", 1_000_000))
    n_events = int(os.environ.get("GEOMESA_BENCH_GEOFENCE_N", 200_000))
    batch = int(os.environ.get("GEOMESA_BENCH_GEOFENCE_BATCH", 20_000))
    n_oracle = int(os.environ.get("GEOMESA_BENCH_GEOFENCE_ORACLE", 1_500))
    n_naive = int(os.environ.get("GEOMESA_BENCH_GEOFENCE_NAIVE", 16))
    t0_ms = 1_717_200_000_000
    spec = "name:String,dtg:Date,*geom:Point:srid=4326"
    rng = np.random.default_rng(SEED + 140)

    # -- the subscription population -------------------------------------
    log(f"[standing] building {n_subs:,} tiny geofences ...")
    cx = rng.uniform(-170, 170, n_subs)
    cy = rng.uniform(-80, 80, n_subs)
    w = rng.uniform(0.005, 0.03, n_subs)
    tiny = [
        geo.Polygon([
            (cx[i] - w[i], cy[i] - w[i]), (cx[i] + w[i], cy[i] - w[i]),
            (cx[i] + w[i], cy[i] + w[i]), (cx[i] - w[i], cy[i] + w[i]),
            (cx[i] - w[i], cy[i] - w[i]),
        ])
        for i in range(n_subs)
    ]

    def star(scx, scy, r, n_arms, seed):
        srng = np.random.default_rng(seed)
        a = np.linspace(0, 2 * np.pi, 2 * n_arms + 1)[:-1]
        rad = np.where(np.arange(2 * n_arms) % 2 == 0, r,
                       r * srng.uniform(0.3, 0.7, 2 * n_arms))
        return geo.Polygon([
            (scx + rr * np.cos(t), scy + rr * np.sin(t))
            for t, rr in zip(a, rad)
        ])

    def ring(scx, scy, r, n=24):
        a = np.linspace(0, 2 * np.pi, n + 1)
        return geo.Polygon([
            (scx + r * np.cos(t), scy + r * np.sin(t)) for t in a
        ])

    # the hotspot: dense stars across the E ladder + FULL-cell fences
    HOT = (0.0, 10.0, 12.0, 22.0)  # x0, y0, x1, y1
    dense = []
    for k in range(96):
        arms = int(rng.integers(8, 121))  # E buckets 32..256
        dense.append((f"dense{k}", star(
            float(rng.uniform(HOT[0] + 2, HOT[2] - 2)),
            float(rng.uniform(HOT[1] + 2, HOT[3] - 2)),
            float(rng.uniform(0.8, 2.5)), arms, seed=SEED + k,
        )))
    for k in range(16):
        dense.append((f"fence{k}", ring(
            float(rng.uniform(-160, 160)), float(rng.uniform(-70, 70)),
            float(rng.uniform(2.0, 4.0)),
        )))
    all_ids = [f"s{i}" for i in range(n_subs)] + [i for i, _ in dense]
    all_geoms = tiny + [g for _, g in dense]

    # -- the event stream (identical across every mode) -------------------
    n_hot = n_events // 5
    ex = np.concatenate([
        rng.uniform(-170, 170, n_events - n_hot),
        rng.uniform(HOT[0], HOT[2], n_hot),
    ])
    ey = np.concatenate([
        rng.uniform(-80, 80, n_events - n_hot),
        rng.uniform(HOT[1], HOT[3], n_hot),
    ])
    order = rng.permutation(n_events)
    ex, ey = ex[order], ey[order]
    batches = []
    for s in range(0, n_events, batch):
        k = min(batch, n_events - s)
        batches.append((
            [f"e{s + j}" for j in range(k)],
            [{"name": "e", "dtg": t0_ms + s + j,
              "geom": geo.Point(float(ex[s + j]), float(ey[s + j]))}
             for j in range(k)],
        ))

    def ingest_run(engine_on: bool):
        """One full streamed run over the prebuilt batches — DURABLE
        (WAL-backed, default sync policy): the production configuration
        this tier rides on, for both modes, so the ingest ratio isolates
        the matcher's cost; returns (rows/s, engine|None)."""
        ds = DataStore()
        ds.metrics = MetricsRegistry()
        ds.create_schema(FeatureType.from_spec("ev", spec))
        root = tempfile.mkdtemp(prefix="bench_standing_")
        tmp_roots.append(root)
        lam = LambdaStore(
            ds, "ev", config=StreamConfig(),
            wal_dir=os.path.join(root, "_wal"),
        )
        eng = None
        if engine_on:
            eng = lam.standing()
            eng.index.register_geofences(all_ids, all_geoms)
            for e in bk.FUSED_E_BUCKETS:
                eng.matcher.warmup(e, n_rows=batch, gate=eng.gate)
        # warmup (compiles the fold/scan paths outside the window)
        wids, wrows = batches[0]
        lam.write(wrows, ids=[f"w{j}" for j in range(len(wids))])
        lam.flush()
        t0 = time.perf_counter()
        for ids, rows in batches:
            lam.write(rows, ids=ids)
            lam.flush()
        dt = time.perf_counter() - t0
        rate = n_events / dt
        label = "matcher-on" if engine_on else "matcher-off"
        log(f"[standing] ingest {label}: {rate:,.0f} rows/s")
        if not engine_on:
            lam.close()
        return rate, eng, lam

    tmp_roots: list = []
    off_rate, _, _ = ingest_run(False)
    on_rate, eng, lam = ingest_run(True)
    reg = lam.cold.metrics
    alerts = reg.counter_value("geomesa.standing.alerts")
    fused = reg.counter_value("geomesa.standing.fused")
    p99_ms = reg.histogram_quantile("geomesa.standing.latency", 0.99) * 1e3

    # -- pure matcher cost per event (inverted) ---------------------------
    t0 = time.perf_counter()
    for s in range(0, n_events, batch):
        k = min(batch, n_events - s)
        eng.match_points(ex[s : s + k], ey[s : s + k])
    inverted_us = (time.perf_counter() - t0) / n_events * 1e6

    # -- naive all-subscription evaluation on a sample --------------------
    kind, eoff, segs, bbox, _rect = eng.index._ensure_arrays()
    sample = rng.choice(n_events, size=n_naive, replace=False)
    t0 = time.perf_counter()
    naive_pairs = 0
    for e in sample.tolist():
        px, py = float(ex[e]), float(ey[e])
        cand = np.flatnonzero(
            (bbox[:, 0] <= px) & (bbox[:, 2] >= px)
            & (bbox[:, 1] <= py) & (bbox[:, 3] >= py)
        )
        if len(cand):
            inside = _ragged_pip(
                np.full(len(cand), px), np.full(len(cand), py),
                cand.astype(np.int64), eoff, segs,
            )
            naive_pairs += int(inside.sum())
    naive_us = (time.perf_counter() - t0) / n_naive * 1e6
    speedup = naive_us / max(inverted_us, 1e-9)
    log(
        f"[standing] naive {naive_us:,.0f} us/event vs inverted "
        f"{inverted_us:,.1f} us/event = {speedup:,.0f}x "
        f"(alerts {alerts:,}, fused {fused}, p99 {p99_ms:.2f} ms)"
    )

    # -- per-event shapely oracle (complete over bbox candidates) ---------
    osample = rng.choice(n_events, size=n_oracle, replace=False)
    opt, oords = eng.match_points(ex[osample], ey[osample])
    got = set(zip(opt.tolist(), oords.tolist()))
    shp_cache: dict = {}
    identical = True
    for row, e in enumerate(osample.tolist()):
        px, py = float(ex[e]), float(ey[e])
        cand = np.flatnonzero(
            (bbox[:, 0] <= px) & (bbox[:, 2] >= px)
            & (bbox[:, 1] <= py) & (bbox[:, 3] >= py)
        )
        pt = SPoint(px, py)
        for o in cand.tolist():
            sp = shp_cache.get(o)
            if sp is None:
                g = all_geoms[o]
                sp = shp_cache[o] = SPolygon(
                    g.shell, [h for h in g.holes]
                )
            if sp.covers(pt) != ((row, o) in got):
                if sp.boundary.distance(pt) <= 1e-9:
                    continue  # exact-boundary tie: either answer exact
                identical = False
                log(f"[standing] ORACLE MISMATCH event {e} sub {o}")
    lam.close()
    for r in tmp_roots:
        shutil.rmtree(r, ignore_errors=True)

    row = {
        "scenario": "standing_geofence",
        "subscriptions": len(all_ids), "events": n_events, "batch": batch,
        "matcher_off_rows_per_s": round(off_rate, 1),
        "matcher_on_rows_per_s": round(on_rate, 1),
        "ingest_ratio": round(on_rate / off_rate, 4),
        "naive_us_per_event": round(naive_us, 1),
        "inverted_us_per_event": round(inverted_us, 2),
        "speedup_vs_naive": round(speedup, 1),
        "alerts": int(alerts), "fused_dispatches": int(fused),
        "alert_p99_ms": round(p99_ms, 3),
        "oracle_events": int(n_oracle),
        "identical": bool(identical),
    }
    import jax

    payload = {"platform": jax.default_backend(), "rows": [row]}
    if out_path is None:
        out_path = os.environ.get(
            "GEOMESA_BENCH_GEOFENCE_OUT"
        ) or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_GEOFENCE.json",
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec_line = {
        "metric": "speedup_vs_naive", "value": row["speedup_vs_naive"],
        "unit": "x", "ingest_ratio": row["ingest_ratio"],
        "alert_p99_ms": row["alert_p99_ms"], "identical": identical,
    }
    print(json.dumps(rec_line), flush=True)
    return rec_line


# ------------------------------------------------------------- config 4


def config4_join():
    """Spatial join: GDELT-shaped points x admin-polygon-shaped rectangles
    (BASELINE config 4; the geomesa-spark broadcast join — the point side
    is the GeoMesa-INDEXED relation, so the join runs as pipelined device
    scans against the store's z2 table, round-5 spatial_join_indexed).
    Baseline: the ungridded per-polygon scan (bbox mask over ALL points) —
    what a naive executor does without the index."""
    from geomesa_tpu import geometry as geo
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType
    from geomesa_tpu.sql.join import spatial_join, spatial_join_indexed

    n_pts = int(os.environ.get("GEOMESA_BENCH_N4", 20_000_000))
    n_poly = 256
    rng = np.random.default_rng(SEED + 30)
    x, y = gdelt_points(n_pts, rng)
    px0 = rng.uniform(-170, 150, n_poly)
    py0 = rng.uniform(-80, 60, n_poly)
    pw = rng.uniform(1, 12, n_poly)
    ph = rng.uniform(1, 8, n_poly)
    polys = geo.PackedGeometryColumn.from_boxes(px0, py0, px0 + pw, py0 + ph)

    psft = FeatureType.from_spec("pts", "*geom:Point:srid=4326")
    psft.user_data["geomesa.indices.enabled"] = "z2"
    gsft = FeatureType.from_spec("adm", "*geom:Polygon:srid=4326")
    poly_fc = FeatureCollection.from_columns(gsft, np.arange(n_poly), {"geom": polys})
    ds = DataStore()
    ds.create_schema(psft)
    log(f"[join] building {n_pts:,} point store ...")
    ds.write("pts", FeatureCollection.from_columns(
        psft, np.arange(n_pts), {"geom": (x, y)}), check_ids=False)

    spatial_join_indexed(ds, "pts", poly_fc, "contains")  # warmup compiles
    lats = []
    for _ in range(3):
        t0 = time.perf_counter()
        li, ri = spatial_join_indexed(ds, "pts", poly_fc, "contains")
        lats.append(time.perf_counter() - t0)
    t_join = float(np.median(lats))

    # host grid join on the same data, for the record (the r4 path)
    t0 = time.perf_counter()
    hl, hr = spatial_join(poly_fc, ds.features("pts"), "contains")
    t_host = time.perf_counter() - t0
    assert len(hl) == len(li), (len(hl), len(li))

    # baseline: ungridded per-polygon bbox mask, sampled + extrapolated
    for _ in range(2):
        t0 = time.perf_counter()
        total = 0
        for p in range(min(n_poly, 16)):
            bx0, by0, bx1, by1 = px0[p], py0[p], px0[p] + pw[p], py0[p] + ph[p]
            m = (x >= bx0) & (x <= bx1) & (y >= by0) & (y <= by1)
            total += int(m.sum())
        base = (time.perf_counter() - t0) * (n_poly / 16)

    rec = result_line(
        "gdelt_join_pairs_per_sec", np.array([t_join]), len(li), t_join, base,
        {
            "n_points": n_pts, "n_polygons": n_poly, "pairs": len(li),
            "host_grid_join_ms": round(t_host * 1e3, 1),
        },
    )
    del ds, x, y
    gc.collect()
    return rec


# ------------------------------------------------------------- config 5


def config5_knn():
    """kNN process on AIS-trajectory-shaped points (BASELINE config 5).
    Baseline: full haversine + argpartition over every point per query."""
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.process import knn_search
    from geomesa_tpu.process.knn import haversine_m
    from geomesa_tpu.sft import FeatureType

    n = int(os.environ.get("GEOMESA_BENCH_N5", 20_000_000))
    rng = np.random.default_rng(SEED + 40)
    # trajectory-shaped: random walks from seed ports
    n_tracks = 2000
    per = n // n_tracks
    sx = rng.uniform(-170, 170, n_tracks)
    sy = rng.uniform(-75, 75, n_tracks)
    x = np.clip(
        (sx[:, None] + np.cumsum(rng.normal(0, 0.02, (n_tracks, per)), axis=1)).ravel(),
        -180, 180,
    )
    y = np.clip(
        (sy[:, None] + np.cumsum(rng.normal(0, 0.015, (n_tracks, per)), axis=1)).ravel(),
        -90, 90,
    )
    sft = FeatureType.from_spec("ais", "*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z2"
    ds = DataStore()
    ds.create_schema(sft)
    ds.write("ais", FeatureCollection.from_columns(sft, np.arange(len(x)), {"geom": (x, y)}), check_ids=False)

    qs = [(float(rng.uniform(-150, 150)), float(rng.uniform(-60, 60))) for _ in range(20)]
    knn_search(ds, "ais", *qs[0], k=10)  # warmup compiles
    from geomesa_tpu.process import knn_many

    knn_many(ds, "ais", qs[:3], k=10)  # warms the fused batch variant
    lat = []
    t_all = time.perf_counter()
    for qx, qy in qs:
        s = time.perf_counter()
        out = knn_search(ds, "ais", qx, qy, k=10)
        lat.append(time.perf_counter() - s)
    wall = time.perf_counter() - t_all

    # pipelined batch: all window scans dispatch before any pull
    t0 = time.perf_counter()
    outs = knn_many(ds, "ais", qs, k=10)
    batch_wall = time.perf_counter() - t0
    batch_hits = sum(len(o) for o in outs)
    # sparse regions may hold < k within the distance cutoff; that is
    # valid output — require only a sane, non-empty batch
    assert 0 < batch_hits <= 10 * len(qs)

    t0 = time.perf_counter()
    for qx, qy in qs[:4]:  # baseline sampled
        d = haversine_m(x, y, qx, qy)
        np.argpartition(d, 10)[:10]
    base = (time.perf_counter() - t0) / 4

    return result_line(
        "ais_knn_queries", np.array(lat), 10 * len(qs), wall, base,
        {
            "n_points": len(x), "k": 10,
            "batched_queries_per_sec": round(len(qs) / batch_wall, 1),
        },
    )


# --------------------------------------------------- config replica


def config_replica(out_path: "str | None" = None):
    """WAL-shipping replication scenario (docs/replication.md): three
    measurements in one run, emitted as BENCH_REPLICA.json.

    1. **Read scaling** — the same probe mix runs full-tilt against
       each store in isolation (leader, follower 1, follower 2; one
       measured window per store — in deployment each replica is its
       own host, so in-process thread concurrency would only measure
       the bench host's GIL/device contention, not topology capacity).
       Aggregate QPS at two followers (the three rates summed) must
       clear 1.5x the leader-alone rate: a follower that bootstraps
       wrong or serves reads an order slower than the leader fails the
       gate.
    2. **Bounded staleness** — sustained micro-batch ingest with the
       shipper and a follower's apply loop running as threads; the
       follower's measured staleness watermark histogram
       (``geomesa.replica.staleness.ms``) yields the p99 the gate
       bounds.
    3. **Failover** — mid-ingest the leader WAL hard-kills
       (``wal.crash()``, the kill-9 simulation); a follower promotes
       with ``leader_wal_dir`` pointing at the dead leader's on-disk
       WAL. Promote latency is recorded and the gate enforces ZERO
       acknowledged rows lost and zero rows invented.

    Env knobs: GEOMESA_BENCH_REPLICA_COLD (cold rows),
    GEOMESA_BENCH_REPLICA_N (streamed rows), GEOMESA_BENCH_REPLICA_BATCH,
    GEOMESA_BENCH_REPLICA_READ_S (seconds per read topology),
    GEOMESA_BENCH_REPLICA_OUT (fresh-side output path)."""
    import shutil
    import tempfile

    from geomesa_tpu import geometry as geo
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType
    from geomesa_tpu.storage import persist
    from geomesa_tpu.streaming import (
        LambdaStore, PipeTransport, ReplicaStore, SegmentShipper,
        StreamConfig, WalConfig,
    )

    n_cold = int(os.environ.get("GEOMESA_BENCH_REPLICA_COLD", 60_000))
    n_stream = int(os.environ.get("GEOMESA_BENCH_REPLICA_N", 40_000))
    batch = int(os.environ.get("GEOMESA_BENCH_REPLICA_BATCH", 2_000))
    read_s = float(os.environ.get("GEOMESA_BENCH_REPLICA_READ_S", 2.0))
    t0_ms = 1_717_200_000_000
    spec = "name:String,dtg:Date,*geom:Point:srid=4326"
    tmp = tempfile.mkdtemp(prefix="geomesa_replica_bench_")

    rng = np.random.default_rng(SEED + 98)
    ds = DataStore()
    sft = FeatureType.from_spec("rv", spec)
    ds.create_schema(sft)
    ds.write("rv", FeatureCollection.from_columns(
        sft, np.arange(n_cold).astype(str), {
            "name": np.array(["v"] * n_cold),
            "dtg": t0_ms + rng.integers(0, 86_400_000, n_cold),
            "geom": (rng.uniform(-170, 170, n_cold),
                     rng.uniform(-80, 80, n_cold)),
        }), check_ids=False)
    ds.compact("rv")
    root = os.path.join(tmp, "s")
    persist.save(ds, root)
    lam = LambdaStore(
        ds, "rv", config=StreamConfig(),
        wal_dir=os.path.join(root, "_wal"),
        wal_config=WalConfig(sync="always"),
    )
    ship = SegmentShipper(lam, giveup_s=2.0)
    fols = []
    for i in range(2):
        a, b = PipeTransport.pair()
        fol = ReplicaStore(
            root, os.path.join(tmp, f"f{i}", "_wal"), b, type_name="rv",
            config=StreamConfig(),
        )
        ship.attach(a, name=f"f{i}")
        fols.append(fol)
    ship.pump()
    for fol in fols:
        fol.drain()

    # 1. read scaling: each store measured full-tilt in isolation,
    # aggregate = the summed independent rates (see docstring)
    probes = [
        "bbox(geom, -40, -40, 0, 0)", "bbox(geom, 10, 10, 60, 50)",
        "bbox(geom, -170, -80, -100, 0)",
    ]
    for store in (lam, *fols):
        for q in probes:
            store.query(q)  # warm the scan kernels per store
    # exactness: a caught-up follower answers every probe with exactly
    # the leader's ids (the `identical` flag the gate enforces)
    reads_identical = all(
        sorted(str(i) for i in fol.query(q).ids.tolist())
        == sorted(str(i) for i in lam.query(q).ids.tolist())
        for q in probes for fol in fols
    )

    def measure(store):
        n = 0
        t0 = time.perf_counter()
        while True:
            store.query(probes[n % len(probes)])
            n += 1
            dt = time.perf_counter() - t0
            if dt >= read_s:
                return n / dt

    rates = [measure(s) for s in (lam, *fols)]
    qps = {k: sum(rates[: k + 1]) for k in (0, 1, 2)}
    scaling = qps[2] / max(qps[0], 1e-9)
    log(
        f"[replica] read QPS 0f={qps[0]:,.0f} 1f={qps[1]:,.0f} "
        f"2f={qps[2]:,.0f} (x{scaling:.2f} at 2 followers)"
    )

    # 2. bounded staleness under sustained ingest (shipper + apply
    # threads live), rolling straight into 3. the mid-ingest kill
    ship.start()
    for fol in fols:
        fol.start()
    acked: list = []
    kill_at = max(1, (n_stream // batch) * 7 // 10)
    promoted_s = None
    for bi, s in enumerate(range(0, n_stream, batch)):
        k = min(batch, n_stream - s)
        xs = rng.uniform(-170, 170, k)
        ys = rng.uniform(-80, 80, k)
        ids = [f"r{s + j}" for j in range(k)]
        lam.write(
            [{"name": "r", "dtg": t0_ms + s + j,
              "geom": geo.Point(float(xs[j]), float(ys[j]))}
             for j in range(k)],
            ids=ids,
        )
        acked.extend(ids)  # sync=always: the return IS the ack
        if bi + 1 == kill_at:
            lam.wal.crash()  # kill -9: the leader is gone mid-ingest
            break
    stale_p99_s = max(
        fol.metrics.histogram_quantile("geomesa.replica.staleness.ms", 0.99)
        for fol in fols
    )
    ship.stop()
    for fol in fols:
        fol.stop()
    t0 = time.perf_counter()
    fols[0].promote(leader_wal_dir=os.path.join(root, "_wal"))
    promoted_s = time.perf_counter() - t0
    got = {
        str(i) for i in fols[0].query("INCLUDE").ids.tolist()
    }
    attempted = set(acked) | {str(i) for i in range(n_cold)}
    acked_loss = sum(1 for fid in acked if fid not in got)
    invented = sum(1 for fid in got if fid not in attempted)
    # the lagging (non-promoted) follower may be behind but may never
    # hold a row that was never written
    lagging = {str(i) for i in fols[1].query("INCLUDE").ids.tolist()}
    lagging_honest = lagging <= attempted
    log(
        f"[replica] staleness p99 {stale_p99_s * 1e3:.1f} ms; promote "
        f"{promoted_s * 1e3:.0f} ms, acked={len(acked):,} "
        f"loss={acked_loss} invented={invented}"
    )
    lam.flusher.close()
    for fol in fols:
        fol.close()
    shutil.rmtree(tmp, ignore_errors=True)

    rows = [
        {
            "scenario": "replica_scaling",
            "cold_rows": n_cold, "read_s": read_s,
            "qps_0f": round(qps[0], 1), "qps_1f": round(qps[1], 1),
            "qps_2f": round(qps[2], 1),
            "qps_scaling_2f": round(scaling, 3),
            "identical": bool(reads_identical),
        },
        {
            "scenario": "replica_staleness",
            "streamed_rows": len(acked), "batch": batch,
            "staleness_p99_ms": round(stale_p99_s * 1e3, 2),
            "identical": bool(lagging_honest),
        },
        {
            "scenario": "replica_failover",
            "promote_s": round(promoted_s, 4),
            "acked_rows": len(acked),
            "acked_loss": int(acked_loss), "invented": int(invented),
            "identical": bool(acked_loss == 0 and invented == 0),
        },
    ]

    import jax

    payload = {"platform": jax.default_backend(), "rows": rows}
    if out_path is None:
        out_path = os.environ.get(
            "GEOMESA_BENCH_REPLICA_OUT"
        ) or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_REPLICA.json",
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec_line = {
        "metric": "replica_qps_scaling_2f",
        "value": rows[0]["qps_scaling_2f"],
        "unit": "x",
        "staleness_p99_ms": rows[1]["staleness_p99_ms"],
        "promote_s": rows[2]["promote_s"],
        "acked_loss": int(acked_loss), "invented": int(invented),
    }
    print(json.dumps(rec_line), flush=True)
    return rec_line


def config_serve_http(out_path: "str | None" = None):
    """Data-plane scenario (docs/serving.md "The data plane"): one
    WAL-backed LambdaStore mounted on a real socket, three measurements
    emitted as BENCH_SERVE_HTTP.json.

    1. **Mixed closed-loop** — reader threads and an ingest thread in
       closed loops through the stdlib DataClient; read QPS, ingest
       rows/s, and the ``identical`` flag: the streamed GeoJSON bytes
       for a probe query equal the in-process exporter's bytes exactly.
    2. **Adversarial-tenant fairness** — a compliant tenant's
       closed-loop read p99 is measured alone, then again under a
       volumetric flood: an adversarial tenant hammers the same
       listener from several threads with cheap requests, submitting
       far beyond its admission quota of 1 (shed retries back off only
       by the server's own Retry-After hint). The quota bounds the
       adversary to at most one query in any micro-batch and the 429
       path answers without touching the dispatch plane, so the
       compliant tenant's p99 barely moves. The gate bounds the
       degradation ratio at 1.5x and requires the adversary to have
       been visibly shed (429s accounted per tenant — never silent
       queueing).
    3. **Ack durability** — every HTTP-acked ingest row must survive
       ``wal.crash()`` (kill -9) + ``LambdaStore.recover``: zero acked
       rows lost, zero invented.

    Env knobs: GEOMESA_BENCH_SERVE_COLD (cold rows),
    GEOMESA_BENCH_SERVE_READ_S (seconds per mixed loop),
    GEOMESA_BENCH_SERVE_FAIR_S (seconds per fairness loop),
    GEOMESA_BENCH_SERVE_OUT (fresh-side output path)."""
    import shutil
    import tempfile
    import threading

    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.io.exporters import _geojson
    from geomesa_tpu.serving import DataClient, ServeError
    from geomesa_tpu.sft import FeatureType
    from geomesa_tpu.storage import persist
    from geomesa_tpu.streaming import LambdaStore, StreamConfig, WalConfig

    n_cold = int(os.environ.get("GEOMESA_BENCH_SERVE_COLD", 40_000))
    read_s = float(os.environ.get("GEOMESA_BENCH_SERVE_READ_S", 2.0))
    fair_s = float(os.environ.get("GEOMESA_BENCH_SERVE_FAIR_S", 6.0))
    t0_ms = 1_717_200_000_000
    tmp = tempfile.mkdtemp(prefix="geomesa_serve_bench_")
    rng = np.random.default_rng(SEED + 99)

    ds = DataStore()
    sft = FeatureType.from_spec("sv", "name:String,dtg:Date,*geom:Point:srid=4326")
    ds.create_schema(sft)
    ds.write("sv", FeatureCollection.from_columns(
        sft, np.arange(n_cold).astype(str), {
            "name": np.array(["v"] * n_cold),
            "dtg": t0_ms + rng.integers(0, 86_400_000, n_cold),
            "geom": (rng.uniform(-170, 170, n_cold),
                     rng.uniform(-80, 80, n_cold)),
        }), check_ids=False)
    ds.compact("sv")
    root = os.path.join(tmp, "s")
    persist.save(ds, root)
    lam = LambdaStore(
        ds, "sv", config=StreamConfig(),
        wal_dir=os.path.join(root, "_wal"),
        wal_config=WalConfig(sync="always"),
    )
    srv = lam.serve(port=0)
    probes = [
        "bbox(geom, -40, -40, 0, 0)", "bbox(geom, 10, 10, 60, 50)",
        "bbox(geom, -170, -80, -100, 0)",
    ]
    warm = DataClient(srv.url, keep_alive=True)
    for q in probes:
        warm.query("sv", cql=q)  # warm scan kernels through the socket

    # 1. wire == in-process, then the mixed closed loop
    from urllib.parse import quote

    _, _, raw = warm.request("GET", "/query/sv?cql=" + quote(probes[0]))
    identical = raw == _geojson(lam.query(probes[0])).encode()

    stop = threading.Event()
    reads = [0, 0]
    ing_rows = [0]

    def reader(slot):
        c = DataClient(srv.url, keep_alive=True)
        while not stop.is_set():
            c.query("sv", cql=probes[reads[slot] % len(probes)], limit=256)
            reads[slot] += 1

    def ingester():
        c = DataClient(srv.url, keep_alive=True)
        b = 0
        while not stop.is_set():
            k = 200
            feats = [
                {"type": "Feature", "id": f"m{b}-{j}",
                 "geometry": {"type": "Point",
                              "coordinates": [float(b % 90), float(j % 45)]},
                 "properties": {"name": "m", "dtg": t0_ms + b * k + j}}
                for j in range(k)
            ]
            ack = c.ingest("sv", {"type": "FeatureCollection",
                                  "features": feats})
            ing_rows[0] += ack["acked"]
            b += 1

    ts = [threading.Thread(target=reader, args=(i,)) for i in range(2)]
    ts.append(threading.Thread(target=ingester))
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    time.sleep(read_s)
    stop.set()
    for t in ts:
        t.join(30)
    dt = time.perf_counter() - t0
    read_qps = sum(reads) / dt
    ingest_rows_per_s = ing_rows[0] / dt
    log(
        f"[serve_http] mixed: {read_qps:,.0f} read q/s, "
        f"{ingest_rows_per_s:,.0f} ingested rows/s, identical={identical}"
    )

    # 2. adversarial-tenant fairness: compliant p99 alone vs flooded
    def compliant_loop(seconds, warm_s=1.0):
        # the first second is discarded: the adaptive window and the
        # per-tenant state settle before anything lands in the p99
        c = DataClient(srv.url, tenant="compliant", keep_alive=True)
        lats: list = []
        t0 = time.perf_counter()
        i = 0
        while True:
            q0 = time.perf_counter()
            c.query("sv", cql=probes[i % len(probes)], limit=256)
            if q0 - t0 >= warm_s:
                lats.append(time.perf_counter() - q0)
            i += 1
            if time.perf_counter() - t0 >= warm_s + seconds:
                return lats

    iso = compliant_loop(fair_s)
    srv.tenants.configure("adversary", queue_max=1)
    flood_stop = threading.Event()

    def adversary():
        c = DataClient(srv.url, tenant="adversary", timeout=10.0,
                       keep_alive=True)
        cheap = "bbox(geom, 3.0, 3.0, 3.5, 3.5)"  # volumetric: tiny probes
        while not flood_stop.is_set():
            try:
                c.query("sv", cql=cheap, limit=1)
            except ServeError as e:  # shed 429: back off by the hint only
                time.sleep(min(e.retry_after or 0.05, 0.25))
            except OSError:
                pass

    floods = [threading.Thread(target=adversary) for _ in range(3)]
    for t in floods:
        t.start()
    try:
        flooded = compliant_loop(fair_s)
    finally:
        flood_stop.set()
        for t in floods:
            t.join(30)
    p99_iso = float(np.percentile(np.array(iso) * 1e3, 99))
    p99_flood = float(np.percentile(np.array(flooded) * 1e3, 99))
    degradation = p99_flood / max(p99_iso, 1e-9)
    trep = {r["tenant"]: r for r in srv.tenants.report()["tenants"]}
    adversary_shed = int(trep.get("adversary", {}).get("shed", 0))
    log(
        f"[serve_http] fairness: compliant p99 {p99_iso:.1f} ms alone, "
        f"{p99_flood:.1f} ms flooded (x{degradation:.2f}); adversary "
        f"shed {adversary_shed:,} of "
        f"{trep.get('adversary', {}).get('submitted', 0):,} submitted"
    )

    # 3. ack durability: HTTP-acked rows survive kill -9 + recover
    dur = DataClient(srv.url, keep_alive=True)
    acked: list = []
    for b in range(10):
        feats = [
            {"type": "Feature", "id": f"dur{b}-{j}",
             "geometry": {"type": "Point",
                          "coordinates": [float(b), float(j % 80)]},
             "properties": {"name": "d", "dtg": t0_ms + b * 100 + j}}
            for j in range(100)
        ]
        ack = dur.ingest("sv", {"type": "FeatureCollection",
                                "features": feats})
        if ack["acked"] == 100 and ack["durable"]:
            acked.extend(f"dur{b}-{j}" for j in range(100))
    srv.close()
    lam.wal.crash()  # kill -9: no close, no checkpoint
    rec = LambdaStore.recover(root)
    got = {str(i) for i in rec.query("INCLUDE").ids.tolist()}
    acked_loss = sum(1 for fid in acked if fid not in got)
    # everything the run ever POSTed carries an "m"/"dur" prefix and the
    # cold rows are plain indices — anything else came from nowhere
    attempted = {str(i) for i in range(n_cold)}
    invented = sum(
        1 for fid in got
        if fid not in attempted and not fid.startswith(("m", "dur"))
    )
    log(
        f"[serve_http] durability: acked={len(acked):,} loss={acked_loss} "
        f"invented={invented}"
    )
    lam.flusher.close()
    rec.close()
    shutil.rmtree(tmp, ignore_errors=True)

    rows = [
        {
            "scenario": "serve_http_mixed",
            "cold_rows": n_cold, "read_s": read_s,
            "read_qps": round(read_qps, 1),
            "ingest_rows_per_s": round(ingest_rows_per_s, 1),
            "ingested_rows": int(ing_rows[0]),
            "identical": bool(identical),
        },
        {
            "scenario": "serve_http_fairness",
            "compliant_requests": len(iso) + len(flooded),
            "compliant_p99_isolated_ms": round(p99_iso, 3),
            "compliant_p99_flood_ms": round(p99_flood, 3),
            "degradation": round(degradation, 3),
            "adversary_shed": adversary_shed,
            "identical": True,
        },
        {
            "scenario": "serve_http_durability",
            "acked_rows": len(acked),
            "acked_loss": int(acked_loss),
            "invented": int(invented),
            "identical": bool(acked_loss == 0 and invented == 0),
        },
    ]

    import jax

    payload = {"platform": jax.default_backend(), "rows": rows}
    if out_path is None:
        out_path = os.environ.get(
            "GEOMESA_BENCH_SERVE_OUT"
        ) or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_SERVE_HTTP.json",
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec_line = {
        "metric": "serve_http_read_qps",
        "value": rows[0]["read_qps"],
        "unit": "q/s",
        "degradation": rows[1]["degradation"],
        "adversary_shed": adversary_shed,
        "acked_loss": int(acked_loss), "invented": int(invented),
    }
    print(json.dumps(rec_line), flush=True)
    return rec_line


def config_tiles(out_path: "str | None" = None):
    """Live map-tile scenario (docs/tiles.md): one cache-backed
    DataStore mounted on a real socket, two measurements emitted as
    BENCH_TILES.json.

    1. **Precomposed vs from-scratch at matched workload** — a reader
       fetches a fixed tile working set (zooms 1..3, Arrow grid
       format) in a closed loop through the stdlib DataClient while an
       ingest thread POSTs paced localized batches; then the SAME tile
       set is served with ``mode=fresh`` (the from-scratch oracle)
       under the same sustained ingest. Per-zoom speedup = fresh p50 /
       warm p50 over the steady-state tiles outside the write
       footprint; the gate requires >=5x at every measured zoom, plus
       a p99 ceiling over EVERY fetch (recomposes and ingest stalls
       included) and a cache-hit floor. The ``identical`` flag is the
       in-bench oracle: after the loops, every sampled tile's warm
       Arrow bytes equal its ``mode=fresh`` bytes at zooms 0..3.
    2. **Scoped invalidation, both directions** — with the pyramid
       fully warm, one localized ingest batch lands; a tile far from
       the write must keep answering 304 to its old ETag (still warm,
       zero aggregation work) while the touched tile recomposes under
       a new ETag.

    Env knobs: GEOMESA_BENCH_TILES_COLD (cold rows),
    GEOMESA_BENCH_TILES_S (seconds for the warm closed loop),
    GEOMESA_BENCH_TILES_OUT (fresh-side output path)."""
    import threading

    from geomesa_tpu import conf
    from geomesa_tpu.cache import CacheConfig
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.serving import DataClient
    from geomesa_tpu.sft import FeatureType

    n_cold = int(os.environ.get("GEOMESA_BENCH_TILES_COLD", 60_000))
    read_s = float(os.environ.get("GEOMESA_BENCH_TILES_S", 2.0))
    t0_ms = 1_717_200_000_000
    rng = np.random.default_rng(SEED + 123)

    ds = DataStore(metrics=MetricsRegistry(),
                   cache=CacheConfig(max_bytes=1 << 24))
    sft = FeatureType.from_spec(
        "tl", "name:String,dtg:Date,*geom:Point:srid=4326"
    )
    ds.create_schema(sft)
    ds.write("tl", FeatureCollection.from_columns(
        sft, np.arange(n_cold).astype(str), {
            "name": np.array(["t"] * n_cold),
            "dtg": t0_ms + rng.integers(0, 86_400_000, n_cold),
            "geom": (rng.uniform(-170, 170, n_cold),
                     rng.uniform(-80, 80, n_cold)),
        }), check_ids=False)
    ds.compact("tl")

    # px=128 bounds the Arrow body at 128 KB/tile so the loop measures
    # the serving tier, not loopback bulk transfer
    conf.TILES_PX.set(128)
    try:
        srv = ds.serve(port=0)
        warm = DataClient(srv.url, keep_alive=True)
        # the tile working set: every z1 tile, 16 each at z2/z3
        tile_sets: dict = {}
        for z in (1, 2, 3):
            allt = [(z, x, y) for x in range(2 ** (z + 1))
                    for y in range(2 ** z)]
            if len(allt) > 16:
                pick = sorted(rng.choice(len(allt), 16, replace=False))
                allt = [allt[i] for i in pick]
            tile_sets[z] = allt
        working = [t for z in (1, 2, 3) for t in tile_sets[z]]
        # fetching both roots composes the ENTIRE pyramid once
        for x in (0, 1):
            warm.tile("tl", "count", 0, x, 0, fmt="arrow")

        # sustained localized ingest: every batch lands in lon
        # [95, 111] x lat [25, 44] — inside z3 tile (12, 2) and far
        # from z3 tile (0, 0)
        stop = threading.Event()
        ing_rows = [0]

        def ingester():
            # paced, not closed-loop: each POST costs tens of ms of
            # host CPU (JSON parse + sorted write + invalidation), so
            # an unthrottled loop starves the readers and measures the
            # GIL, not the pyramid; ~1.5k rows/s in 100-row quanta is
            # sustained ingest that still re-dirties the working set
            # many times per second, with bounded per-POST stalls
            c = DataClient(srv.url, keep_alive=True)
            b = 0
            while not stop.is_set():
                k = 100
                r = np.random.default_rng(SEED + b)
                xs = r.uniform(95.0, 111.0, k)
                ys = r.uniform(25.0, 44.0, k)
                feats = [
                    {"type": "Feature", "id": f"mt{b}-{j}",
                     "geometry": {"type": "Point",
                                  "coordinates": [float(xs[j]),
                                                  float(ys[j])]},
                     "properties": {"name": "m", "dtg": t0_ms + b * k + j}}
                    for j in range(k)
                ]
                ack = c.ingest("tl", {"type": "FeatureCollection",
                                      "features": feats})
                ing_rows[0] += ack["acked"]
                b += 1
                stop.wait(0.05)

        def fetch_loop(seconds, mode=None, passes=None):
            """Closed loop over the working set; (tile, seconds) samples."""
            c = DataClient(srv.url, keep_alive=True)
            lats: list = []
            t0 = time.perf_counter()
            i = 0
            while True:
                tile = working[i % len(working)]
                q0 = time.perf_counter()
                c.tile("tl", "count", *tile, fmt="arrow", mode=mode)
                lats.append((tile, time.perf_counter() - q0))
                i += 1
                if passes is not None:
                    if i >= passes * len(working):
                        return lats
                elif time.perf_counter() - t0 >= seconds:
                    return lats

        def touches_writes(tile):
            """Does this tile's bbox intersect the ingest footprint?"""
            z, x, y = tile
            w = 360.0 / 2 ** (z + 1)
            lo_x, lo_y = -180.0 + x * w, 90.0 - (y + 1) * w
            return not (lo_x + w < 95.0 or lo_x > 111.0
                        or lo_y + w < 25.0 or lo_y > 44.0)

        ing = threading.Thread(target=ingester)
        ing.start()
        try:
            c0 = ds.metrics.counter_value("geomesa.tiles.compose")
            t0 = time.perf_counter()
            warm_lats = fetch_loop(read_s)
            warm_dt = time.perf_counter() - t0
            composes = ds.metrics.counter_value(
                "geomesa.tiles.compose"
            ) - c0
            fresh_lats = fetch_loop(0, mode="fresh", passes=2)
        finally:
            stop.set()
            ing.join(30)

        # warm_p99 and hit_ratio cover EVERY fetch — including the
        # tiles the ingest keeps re-dirtying, whose refetches pay the
        # recompose (the amortized maintenance cost). The per-zoom
        # speedup is computed on the steady-state tiles OUTSIDE the
        # write footprint (same tiles both sides): a recomposing tile's
        # cost is ~one leaf scan by construction — the same work the
        # from-scratch path pays on every request — so folding it into
        # the warm mean would just measure how often this loop happens
        # to land on the handful of touched tiles, not the serving path
        warm_ms = np.array([s * 1e3 for _, s in warm_lats])
        warm_p99 = float(np.percentile(warm_ms, 99))
        hit_ratio = 1.0 - composes / max(len(warm_lats), 1)
        # medians, not means: a fetch that lands behind an in-flight
        # ingest POST stalls for the POST's GIL hold on either side of
        # the comparison — that tail is real and gated via warm_p99_ms,
        # but inside the speedup ratio it is multiplicative noise
        per_zoom = {}
        for z in (1, 2, 3):
            steady = [t for t in tile_sets[z] if not touches_writes(t)]
            w = np.array([s for t, s in warm_lats if t in steady])
            f = np.array([s for t, s in fresh_lats if t in steady])
            per_zoom[str(z)] = {
                "steady_tiles": len(steady),
                "warm_ms_p50": round(float(np.median(w)) * 1e3, 3),
                "fresh_ms_p50": round(float(np.median(f)) * 1e3, 3),
                "speedup": round(float(np.median(f) / np.median(w)), 2),
            }
        speedup_min = min(v["speedup"] for v in per_zoom.values())
        log(
            f"[tiles] warm {len(warm_lats) / warm_dt:,.0f} fetch/s "
            f"p99 {warm_p99:.2f} ms, hit ratio {hit_ratio:.3f}, "
            f"speedup min x{speedup_min:.1f} "
            f"({ {z: v['speedup'] for z, v in per_zoom.items()} }), "
            f"{ing_rows[0]:,} rows ingested alongside"
        )

        # in-bench bit-identity oracle: warm bytes == from-scratch bytes
        identical = True
        checked = 0
        for z in (0, 1, 2, 3):
            allt = [(x, y) for x in range(2 ** (z + 1))
                    for y in range(2 ** z)]
            if len(allt) > 12:
                pick = sorted(rng.choice(len(allt), 12, replace=False))
                allt = [allt[i] for i in pick]
            for x, y in allt:
                _, _, wb = warm.tile("tl", "count", z, x, y, fmt="arrow")
                _, _, fb = warm.tile("tl", "count", z, x, y, fmt="arrow",
                                     mode="fresh")
                identical = identical and wb == fb
                checked += 1
        log(f"[tiles] identity: {checked} tiles swept, "
            f"identical={identical}")

        # 2. scoped invalidation, both directions
        for x in (0, 1):  # re-warm everything the loops dirtied
            warm.tile("tl", "count", 0, x, 0, fmt="arrow")
        far, touched = (3, 0, 0), (3, 12, 2)
        _, far_h, _ = warm.tile("tl", "count", *far, fmt="arrow")
        _, tch_h, _ = warm.tile("tl", "count", *touched, fmt="arrow")
        k = 64
        feats = [
            {"type": "Feature", "id": f"inv-{j}",
             "geometry": {"type": "Point",
                          "coordinates": [100.0 + (j % 8), 30.0 + j % 12]},
             "properties": {"name": "i", "dtg": t0_ms + j}}
            for j in range(k)
        ]
        warm.ingest("tl", {"type": "FeatureCollection", "features": feats})
        st_far, far_h2, _ = warm.tile("tl", "count", *far, fmt="arrow",
                                      etag=far_h["ETag"])
        st_t, tch_h2, _ = warm.tile("tl", "count", *touched, fmt="arrow",
                                    etag=tch_h["ETag"])
        far_304 = st_far == 304 and far_h2["ETag"] == far_h["ETag"]
        touched_recomposed = st_t == 200 and tch_h2["ETag"] != tch_h["ETag"]
        log(
            f"[tiles] invalidation: far tile {far} -> {st_far} "
            f"(etag kept={far_h2['ETag'] == far_h['ETag']}), touched "
            f"{touched} -> {st_t} (etag moved="
            f"{tch_h2['ETag'] != tch_h['ETag']})"
        )
        srv.close()
    finally:
        conf.TILES_PX.clear()

    rows = [
        {
            "scenario": "tiles_serving",
            "cold_rows": n_cold, "read_s": read_s,
            "zooms_measured": len(per_zoom),
            "working_set_tiles": len(working),
            "fetch_per_s": round(len(warm_lats) / warm_dt, 1),
            "warm_p99_ms": round(warm_p99, 3),
            "hit_ratio": round(hit_ratio, 4),
            "per_zoom": per_zoom,
            "speedup_min": speedup_min,
            "ingest_rows_alongside": int(ing_rows[0]),
            "identity_tiles_checked": checked,
            "identical": bool(identical),
        },
        {
            "scenario": "tiles_invalidation",
            "warmed_tiles": len(working),
            "far_304": bool(far_304),
            "touched_recomposed": bool(touched_recomposed),
            "identical": bool(far_304 and touched_recomposed),
        },
    ]

    import jax

    payload = {"platform": jax.default_backend(), "rows": rows}
    if out_path is None:
        out_path = os.environ.get(
            "GEOMESA_BENCH_TILES_OUT"
        ) or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_TILES.json",
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec_line = {
        "metric": "tiles_speedup_min",
        "value": speedup_min,
        "unit": "x",
        "warm_p99_ms": rows[0]["warm_p99_ms"],
        "hit_ratio": rows[0]["hit_ratio"],
        "identical": bool(identical),
        "far_304": bool(far_304),
        "touched_recomposed": bool(touched_recomposed),
    }
    print(json.dumps(rec_line), flush=True)
    return rec_line


def config_pod(out_path: "str | None" = None):
    """Multi-host pod scenario (docs/distributed.md): H=4 sim hosts
    against the H=1 flat mesh on the SAME device budget, emitted as
    BENCH_POD.json.

    1. **Selective scan** — a closed loop of small-bbox queries against
       a ``DataStore(mesh=host_group)`` (per-host contiguous shards;
       non-owning hosts do zero work) vs the identical store on the
       flat single-process mesh over the same devices. The speedup is
       REAL wall-clock work reduction — fewer, smaller per-host legs —
       and the ``identical`` flag is the in-bench differential: every
       probe (and a fused ``query_many`` batch) answers with exactly
       the flat store's ids.
    2. **Host-local ingest** — the collection partitions by owner and
       each host's pipelined ``BulkLoader`` leg is timed IN ISOLATION;
       the pod wall-clock is the slowest host's leg (in deployment each
       host is its own machine, so in-process thread concurrency would
       only measure this bench host's single-core contention, not pod
       capacity — the replica read-scaling measurement's reasoning).
       The ``identical`` flag checks the union of per-host shards
       answers exactly like the flat store.

    Needs >= hosts devices (CPU runs: XLA_FLAGS=
    --xla_force_host_platform_device_count=8). Env knobs:
    GEOMESA_BENCH_POD_HOSTS, GEOMESA_BENCH_POD_N (scan rows),
    GEOMESA_BENCH_POD_INGEST_N, GEOMESA_BENCH_POD_READ_S,
    GEOMESA_BENCH_POD_OUT (fresh-side output path)."""
    import zlib

    import jax

    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.ingest.pipeline import BulkLoader
    from geomesa_tpu.pod import make_host_group
    from geomesa_tpu.sft import FeatureType

    hosts = int(os.environ.get("GEOMESA_BENCH_POD_HOSTS", 4))
    n_scan = int(os.environ.get("GEOMESA_BENCH_POD_N", 150_000))
    n_ingest = int(os.environ.get("GEOMESA_BENCH_POD_INGEST_N", 400_000))
    read_s = float(os.environ.get("GEOMESA_BENCH_POD_READ_S", 3.0))
    n_dev = len(jax.devices())
    if n_dev < hosts:
        raise RuntimeError(
            f"config_pod needs >= {hosts} devices, found {n_dev}; on CPU "
            "set XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    group = make_host_group(
        hosts=hosts, devices_per_host=n_dev // hosts, driver="sim"
    )
    t0_ms = 1_704_067_200_000
    spec = "dtg:Date,*geom:Point:srid=4326"

    def point_fc(sft, n, seed):
        rng = np.random.default_rng(seed)
        return FeatureCollection.from_columns(
            sft, np.arange(n).astype(str),
            {"dtg": t0_ms + rng.integers(0, 20 * 86_400_000, n),
             "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n))},
        )

    def build(mesh, n, seed):
        sft = FeatureType.from_spec("pp", spec)
        ds = DataStore(mesh=mesh)
        ds.create_schema(sft)
        ds.write("pp", point_fc(sft, n, seed), check_ids=False)
        ds.compact("pp")
        return ds

    # 1. selective scan: pod vs flat, same devices, same rows
    pod = build(group, n_scan, SEED + 120)
    flat = build(group.flat_mesh(), n_scan, SEED + 120)
    rng = np.random.default_rng(SEED + 121)
    probes = []
    for _ in range(12):
        x0, y0 = rng.uniform(-55, 40), rng.uniform(-40, 30)
        probes.append(
            f"bbox(geom, {x0:.3f}, {y0:.3f}, {x0 + 4:.3f}, {y0 + 3:.3f})"
        )

    def ids_of(fc):
        return sorted(np.asarray(fc.ids, dtype=str).tolist())

    for ds in (pod, flat):
        for q in probes:
            ds.query("pp", q)  # warm the per-variant kernels
    scan_identical = all(
        ids_of(pod.query("pp", q)) == ids_of(flat.query("pp", q))
        for q in probes
    ) and all(
        ids_of(a) == ids_of(b)
        for a, b in zip(pod.query_many("pp", probes),
                        flat.query_many("pp", probes))
    )

    def measure(ds):
        k = 0
        t0 = time.perf_counter()
        while True:
            ds.query("pp", probes[k % len(probes)])
            k += 1
            dt = time.perf_counter() - t0
            if dt >= read_s:
                return k / dt

    pod_qps = measure(pod)
    flat_qps = measure(flat)
    scan_speedup = pod_qps / max(flat_qps, 1e-9)
    log(
        f"[pod] selective scan H={hosts}: {pod_qps:,.1f} q/s vs flat "
        f"{flat_qps:,.1f} q/s (x{scan_speedup:.2f}), identical="
        f"{scan_identical}"
    )

    # 2. host-local ingest: per-owner partitions, each host's loader
    # leg timed in isolation; pod wall = the slowest host's leg
    sft = FeatureType.from_spec("pp", spec)
    fc = point_fc(sft, n_ingest, SEED + 122)
    owners = np.array(
        [zlib.crc32(str(i).encode()) % hosts for i in fc.ids], np.int64
    )

    def load(mesh, sub):
        ds = DataStore(mesh=mesh)
        ds.create_schema(FeatureType.from_spec("pp", spec))
        t0 = time.perf_counter()
        loader = BulkLoader(ds, "pp")
        loader.put(sub)
        loader.close()
        return ds, time.perf_counter() - t0

    flat_ing, flat_s = load(group.flat_mesh(), fc)
    host_stores, host_s = [], []
    for h in range(hosts):
        ds, t = load(group.mesh(h), fc.take(np.flatnonzero(owners == h)))
        host_stores.append(ds)
        host_s.append(t)
    pod_model_s = max(host_s)
    ingest_speedup = flat_s / max(pod_model_s, 1e-9)
    ing_q = "bbox(geom, -20, -15, 10, 12)"
    union_ids = sorted(
        i for ds in host_stores
        for i in np.asarray(ds.query("pp", ing_q).ids, dtype=str).tolist()
    )
    ingest_identical = (
        union_ids == ids_of(flat_ing.query("pp", ing_q))
        and sum(ds.count("pp") for ds in host_stores)
        == flat_ing.count("pp") == n_ingest
    )
    log(
        f"[pod] host-local ingest: flat {flat_s:.2f}s vs slowest host "
        f"{pod_model_s:.2f}s (x{ingest_speedup:.2f} host-parallel "
        f"model), identical={ingest_identical}"
    )

    rows = [
        {
            "scenario": "pod_scan",
            "hosts": hosts, "devices": n_dev, "rows": n_scan,
            "read_s": read_s,
            "pod_qps": round(pod_qps, 1),
            "flat_qps": round(flat_qps, 1),
            "scan_speedup": round(scan_speedup, 3),
            "identical": bool(scan_identical),
        },
        {
            "scenario": "pod_ingest",
            "hosts": hosts, "rows": n_ingest,
            "flat_s": round(flat_s, 4),
            "host_s": [round(t, 4) for t in host_s],
            "pod_model_s": round(pod_model_s, 4),
            "ingest_speedup": round(ingest_speedup, 3),
            "identical": bool(ingest_identical),
        },
    ]

    payload = {"platform": jax.default_backend(), "rows": rows}
    if out_path is None:
        out_path = os.environ.get("GEOMESA_BENCH_POD_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_POD.json"
        )
    try:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=2)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not write {out_path}: {e}")

    rec_line = {
        "metric": "pod_scan_speedup",
        "value": rows[0]["scan_speedup"],
        "unit": "x",
        "ingest_speedup": rows[1]["ingest_speedup"],
        "identical": bool(scan_identical and ingest_identical),
    }
    print(json.dumps(rec_line), flush=True)
    return rec_line


def child_main():
    """One bench attempt in THIS process (device init + all configs)."""
    import threading

    # device-claim watchdog, armed BEFORE the jax import: a wedged TPU
    # lease can block either jax.devices() (PJRT init) or — in the
    # import-time variant observed late round 5, PERF.md §10 — the
    # tunnel plugin's import itself; fail loudly either way instead of
    # hanging until the supervisor's 2.5 h attempt timeout
    init_timeout = float(os.environ.get("GEOMESA_BENCH_INIT_TIMEOUT", 600))
    ready = threading.Event()

    def watchdog():
        if not ready.wait(init_timeout):
            log(
                f"FATAL: device init did not complete within {init_timeout:.0f}s "
                "(TPU claim wedged?); aborting bench"
            )
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()

    import jax

    platform = os.environ.get("GEOMESA_BENCH_PLATFORM")
    if platform:  # e.g. "cpu" for off-TPU verification runs
        jax.config.update("jax_platforms", platform)
    log(f"devices: {jax.devices()}")
    ready.set()
    _probe_link()
    runners = {
        "1": config1_z3, "2": config2_z2, "3": config3_xz2,
        "4": config4_join, "5": config5_knn, "cache": config_cache,
        "serving": config_serving, "ingest": config_ingest,
        "fused": config_fused, "pip_join": config_pip_join,
        "stream": config_stream, "wal": config_wal, "knn": config_knn,
        "obs": config_obs, "standing": config_standing,
        "ops": config_ops, "replica": config_replica,
        "serve_http": config_serve_http, "tiles": config_tiles,
        "drift": config_drift, "pod": config_pod,
    }
    results: dict[str, dict] = {}
    for c in CONFIGS:
        c = c.strip()
        t0 = time.perf_counter()
        results[c] = runners[c]()
        log(f"[config {c}] total {time.perf_counter() - t0:.1f}s")
    if len(results) > 1 and results.get("1") is not None:
        # repeat the headline (config 1) as the LAST line too: a driver
        # parsing either the first or the final JSON line gets the
        # north-star metric, not whichever config happened to run last
        print(json.dumps(results["1"]), flush=True)


LINK_PROFILE: dict = {}


def _probe_link():
    """Sanity-check the host<->device link against the constants the
    scan design is tuned for (PERF.md §1: ~66 ms pull floor, ~30 MB/s;
    VERDICT r4 weak #8 — the load-bearing numbers were measured once and
    never re-validated). Logged and attached to the config-1 row so a
    changed deployment link is visible in the artifact of record."""
    import jax
    import jax.numpy as jnp

    try:
        small = jnp.zeros((8, 128), jnp.float32) + 1  # compile + settle
        jax.device_get(small)
        t0 = time.perf_counter()
        jax.device_get(small)
        t_small = time.perf_counter() - t0
        big = jnp.zeros((1024, 1024), jnp.float32) + 1  # 4 MiB
        jax.device_get(big)
        t0 = time.perf_counter()
        jax.device_get(big)
        t_big = time.perf_counter() - t0
        rtt_ms = t_small * 1e3
        LINK_PROFILE.update(link_rtt_ms=round(rtt_ms, 1))
        # bandwidth from the SIZE DELTA of the two pulls; on a fast link
        # the delta drowns in noise (t_big <= t_small) — omit rather than
        # record an absurd number in the artifact of record
        d_bytes = big.nbytes - small.nbytes
        mbps = None
        if t_big > t_small * 1.2:
            mbps = d_bytes / 1e6 / (t_big - t_small)
            LINK_PROFILE.update(link_pull_mb_s=round(mbps, 1))
        log(
            f"link probe: pull floor ~{rtt_ms:.1f} ms, "
            + (f"~{mbps:.0f} MB/s" if mbps else "bandwidth not resolvable")
        )
        if rtt_ms > 200 or (mbps is not None and mbps < 10):
            log(
                "WARNING: link profile far from the PERF.md §1 constants "
                "the M-bucket ladder / one-pull design are tuned for"
            )
        # round 11 (VERDICT weak #8): re-derive the fused-chunk slot cap
        # and M-bucket floor from the MEASURED link instead of trusting
        # the 66 ms-era hand tuning, installed before any table builds or
        # warmups so every compiled shape uses them; the chosen constants
        # ride LINK_PROFILE into each scenario row (PERF.md §14)
        from geomesa_tpu.scan import block_kernels as bk

        derived = bk.derive_link_constants(rtt_ms, mbps)
        bk.set_link_constants(derived)
        LINK_PROFILE.update(
            fused_chunk_slots=derived["fused_chunk_slots"],
            m_floor=derived["m_floor"],
        )
        log(
            f"link-derived constants: fused_chunk_slots="
            f"{derived['fused_chunk_slots']}, m_floor={derived['m_floor']}"
        )
    except Exception as e:  # pragma: no cover - probe must never kill a run
        log(f"link probe failed: {e}")


LAST_GOOD = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_LAST_GOOD.json")


def _load_last_good() -> dict | None:
    try:
        with open(LAST_GOOD) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _store_last_good(rows: list[dict]):
    try:
        with open(LAST_GOOD, "w") as f:
            json.dump({"recorded_unix": time.time(), "rows": rows}, f, indent=1)
    except OSError as e:  # pragma: no cover - read-only checkout
        log(f"WARNING: could not update {LAST_GOOD}: {e}")


def main():
    """Supervisor: run the bench in a CHILD process so a wedged TPU lease
    (PJRT init hanging, the round-4 failure mode — BENCH_r04.json rc=3) can
    be retried in a fresh process after backoff. If the device never comes
    up, emit the last good recorded rows marked "degraded" so the driver
    always parses a result line instead of recording rc=3/parsed:null."""
    import subprocess

    if os.environ.get("GEOMESA_BENCH_CHILD") == "1":
        child_main()
        return

    attempts = int(os.environ.get("GEOMESA_BENCH_INIT_RETRIES", 3))
    attempt_timeout = float(os.environ.get("GEOMESA_BENCH_ATTEMPT_TIMEOUT", 9000))
    rows: dict[str, dict] = {}  # metric -> row, from the best attempt so far
    last_rc = None
    for attempt in range(attempts):
        if attempt:
            backoff = 60.0 * attempt
            log(f"bench attempt {attempt} failed (rc={last_rc}); retrying in {backoff:.0f}s")
            time.sleep(backoff)
        env = dict(os.environ, GEOMESA_BENCH_CHILD="1")
        if attempt and last_rc == 3 and "GEOMESA_BENCH_INIT_TIMEOUT" not in os.environ:
            # the first attempt already proved the lease wedged after the
            # full default init window; a HEALTHY init takes <10 s
            # (PERF.md §10), so retries fail fast and the driver gets the
            # degraded rows in ~19 min total instead of ~35. An
            # operator-set init timeout is honored as-is on every attempt
            # (deployments where init legitimately takes minutes).
            env["GEOMESA_BENCH_INIT_TIMEOUT"] = "180"
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE, env=env, text=True,
        )
        deadline = time.monotonic() + attempt_timeout
        got: list[str] = []
        try:
            import threading

            # line-buffering with an overall wall-clock bound: a mid-run
            # device hang (lease wedge AFTER init) must not stall the
            # driver. Lines are buffered (not passed through live) so a
            # failed attempt's partial rows never appear un-marked next to
            # the degraded rows the fallback emits (progress still streams
            # on stderr, which the child inherits).
            def _watch():
                if proc.poll() is None:
                    try:
                        proc.wait(timeout=max(deadline - time.monotonic(), 1))
                    except subprocess.TimeoutExpired:
                        log(f"bench attempt exceeded {attempt_timeout:.0f}s; killing child")
                        proc.kill()

            t = threading.Thread(target=_watch, daemon=True)
            t.start()
            for line in proc.stdout:
                line = line.rstrip("\n")
                if line:
                    got.append(line)
            last_rc = proc.wait()
        finally:
            if proc.poll() is None:
                proc.kill()
        parsed = []
        for line in got:
            try:
                rec = json.loads(line)
                if isinstance(rec, dict) and "metric" in rec:
                    parsed.append(rec)
            except ValueError:
                pass
        for rec in parsed:
            rows[rec["metric"]] = rec
        if last_rc == 0 and parsed:
            for line in got:
                print(line, flush=True)
            # record as last-good only for a full-scale full-fidelity TPU
            # run: CPU verification / reduced-N / subset / reduced-query
            # overrides must not replace the rows the degraded path serves
            supervisor_knobs = {
                "GEOMESA_BENCH_INIT_TIMEOUT", "GEOMESA_BENCH_INIT_RETRIES",
                "GEOMESA_BENCH_ATTEMPT_TIMEOUT",
            }
            overridden = [
                k for k in os.environ
                if k.startswith("GEOMESA_BENCH_") and k not in supervisor_knobs
            ]
            if not overridden:
                _store_last_good(list(rows.values()))
            else:
                log(f"not recording last-good (overrides: {sorted(overridden)})")
            return
    # every attempt failed: fall back to (partial rows from failed attempts,
    # then) the last good recorded run, explicitly marked degraded
    log(f"all {attempts} bench attempts failed (last rc={last_rc})")
    stored = _load_last_good()
    out_rows = list(rows.values())
    if not out_rows and stored:
        out_rows = [dict(r) for r in stored.get("rows", [])]
        age_h = (time.time() - stored.get("recorded_unix", 0)) / 3600
        for r in out_rows:
            r["degraded_recorded_hours_ago"] = round(age_h, 1)
    if not out_rows:
        out_rows = [{
            "metric": "gdelt_z3_bbox_time_features_per_sec_per_chip",
            "value": 0.0, "unit": "features/s", "vs_baseline": 0.0,
        }]
    headline = None
    for r in out_rows:
        r["degraded"] = True
        r["degraded_reason"] = (
            f"TPU device init/run failed after {attempts} attempts (last rc="
            f"{last_rc}); rows are the last good recorded measurements"
            if not rows else
            f"bench run incomplete (last rc={last_rc}); rows measured this run"
        )
        print(json.dumps(r), flush=True)
        if r["metric"].startswith("gdelt_z3"):
            headline = r
    if headline is not None and len(out_rows) > 1:
        print(json.dumps(headline), flush=True)


if __name__ == "__main__":
    main()
