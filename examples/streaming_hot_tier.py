"""Streaming hot tier + hot/cold lambda store (Kafka/Lambda analogue).

Run: JAX_PLATFORMS=cpu python examples/streaming_hot_tier.py
"""

import numpy as np

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu import geometry as geo
from geomesa_tpu.streaming import LambdaStore, StreamingFeatureCache


def main():
    sft = FeatureType.from_spec("ships", "mmsi:String,*geom:Point:srid=4326")

    # live keyed state: latest message per id wins, spatial queries served
    # from a bucket grid index
    cache = StreamingFeatureCache(sft)
    events = []
    cache.listeners.append(lambda ev, fid, row: events.append((ev, fid)))
    cache.upsert(
        [{"mmsi": "a", "geom": geo.Point(1.0, 1.0)},
         {"mmsi": "b", "geom": geo.Point(50.0, 10.0)}],
        ids=["a", "b"],
    )
    cache.upsert([{"mmsi": "a", "geom": geo.Point(2.0, 1.5)}], ids=["a"])
    live = cache.query("bbox(geom, 0, 0, 10, 10)")
    print(f"live hits: {len(live)}; events: {events}")

    # hot/cold: recent rows in the cache, history in the columnar store
    cold = DataStore()
    cold.create_schema(sft)
    store = LambdaStore(cold, "ships")
    rng = np.random.default_rng(2)
    n = 50_000
    store.write(FeatureCollection.from_columns(
        sft, np.arange(n),
        {
            "mmsi": np.array([f"m{i % 500}" for i in range(n)]),
            "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
        },
    ).to_rows())
    store.persist_hot()  # flush hot -> cold
    out = store.query("bbox(geom, -10, -10, 10, 10)")
    print(f"lambda-store hits: {len(out)}")
    return out


if __name__ == "__main__":
    main()
