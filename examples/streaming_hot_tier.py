"""Streaming hot tier + hot/cold lambda store (Kafka/Lambda analogue).

Run: JAX_PLATFORMS=cpu python examples/streaming_hot_tier.py
"""

import numpy as np

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu import geometry as geo
from geomesa_tpu.streaming import LambdaStore, StreamingFeatureCache


def main():
    sft = FeatureType.from_spec("ships", "mmsi:String,*geom:Point:srid=4326")

    # live keyed state: latest message per id wins, spatial queries served
    # from a bucket grid index
    cache = StreamingFeatureCache(sft)
    events = []
    cache.listeners.append(lambda ev, fid, row: events.append((ev, fid)))
    cache.upsert(
        [{"mmsi": "a", "geom": geo.Point(1.0, 1.0)},
         {"mmsi": "b", "geom": geo.Point(50.0, 10.0)}],
        ids=["a", "b"],
    )
    cache.upsert([{"mmsi": "a", "geom": geo.Point(2.0, 1.5)}], ids=["a"])
    live = cache.query("bbox(geom, 0, 0, 10, 10)")
    print(f"live hits: {len(live)}; events: {events}")

    # hot/cold: recent rows in the cache, history in the columnar store
    cold = DataStore()
    cold.create_schema(sft)
    store = LambdaStore(cold, "ships")
    rng = np.random.default_rng(2)
    n = 50_000
    store.write(FeatureCollection.from_columns(
        sft, np.arange(n),
        {
            "mmsi": np.array([f"m{i % 500}" for i in range(n)]),
            "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
        },
    ).to_rows())
    store.persist_hot()  # full persist: hot -> cold
    out = store.query("bbox(geom, -10, -10, 10, 10)")
    print(f"lambda-store hits: {len(out)}")

    # sustained ingest (docs/streaming.md): micro-batch flush() publishes
    # NEW ids O(batch); updates hold in the exact hot overlay until the
    # incremental fold. With serve() attached, the cold half of every
    # query admits through the scheduler while ingest runs.
    store.serve()
    store.write(
        [{"mmsi": "m7", "geom": geo.Point(3.0, 3.0)},   # update of id "7"
         {"mmsi": "new", "geom": geo.Point(4.0, 4.0)}],  # arrival
        ids=["7", "live1"],
    )
    flushed = store.flush()      # publishes the arrival; update stays hot
    merged = store.query("bbox(geom, 0, 0, 10, 10)")
    print(f"micro-batch flushed {flushed}; merged hits: {len(merged)}")
    store.persist_hot()          # the fold drains the overlay
    store.close()
    return out


if __name__ == "__main__":
    main()
