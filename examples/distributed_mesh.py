"""Distributed store over a device mesh: same API, sharded execution.

Run (no TPU pod needed — 8 virtual CPU devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/distributed_mesh.py
"""

import numpy as np

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu.parallel import make_mesh


def main():
    mesh = make_mesh(8)
    sft = FeatureType.from_spec("pts", "dtg:Date,*geom:Point:srid=4326")
    ds = DataStore(mesh=mesh)
    ds.create_schema(sft)

    n = 100_000
    rng = np.random.default_rng(1)
    t0 = np.datetime64("2024-06-01", "ms").astype(np.int64)
    ds.write("pts", FeatureCollection.from_columns(
        sft, np.arange(n),
        {
            "dtg": t0 + rng.integers(0, 10 * 86_400_000, n),
            "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
        },
    ), check_ids=False)

    # every query fans out over the mesh and merges with collectives
    out = ds.query("pts", "bbox(geom, -30, -30, 30, 30)")
    print(f"{len(out)} hits across {mesh.devices.size} devices")

    # pipelined batch: all device scans dispatch before any pull
    outs = ds.query_many("pts", [
        f"bbox(geom, {x0}, -20, {x0 + 30}, 20)" for x0 in range(-90, 90, 30)
    ])
    print("batched hit counts:", [len(o) for o in outs])
    return outs


if __name__ == "__main__":
    main()
