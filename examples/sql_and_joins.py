"""SQL front-end, indexed spatial join, polygon queries and CRS — the
round-5 analytics surface in one script.

Run: JAX_PLATFORMS=cpu python examples/sql_and_joins.py
"""

import numpy as np


def main():
    from geomesa_tpu import DataStore, FeatureCollection, FeatureType
    from geomesa_tpu import geometry as geo
    from geomesa_tpu.planning.hints import QueryHints
    from geomesa_tpu.sql import spatial_join_indexed, sql_query

    rng = np.random.default_rng(7)
    n = 200_000
    sft = FeatureType.from_spec(
        "ships", "name:String:index=true,*geom:Point:srid=4326"
    )
    sft.user_data["geomesa.indices.enabled"] = "z2"
    ds = DataStore()
    ds.create_schema(sft)
    ds.write("ships", FeatureCollection.from_columns(
        sft, np.arange(n),
        {"name": np.array([f"v{i % 500:03d}" for i in range(n)]),
         "geom": (rng.uniform(-90, 90, n), rng.uniform(-45, 45, n))},
    ), check_ids=False)

    # 1. SQL with ST_ predicate push-down: the polygon INTERSECTS rides
    #    the z2 index AND the device point-in-polygon kernel tier
    rows = sql_query(ds, (
        "SELECT name, st_x(geom) AS lon, st_y(geom) AS lat FROM ships "
        "WHERE st_intersects(geom, st_geomfromwkt("
        "'POLYGON((-20 -15, 25 -20, 30 12, 0 18, -25 8, -20 -15))')) "
        "ORDER BY lon LIMIT 25"
    ))
    print(f"SQL polygon query: {len(rows)} rows, cols {list(rows.columns)}")

    # 2. indexed spatial join: admin cells x the ship store, every left
    #    geometry one pipelined device scan
    cx = rng.uniform(-80, 70, 32)
    cy = rng.uniform(-40, 30, 32)
    cells = geo.PackedGeometryColumn.from_boxes(cx, cy, cx + 8, cy + 6)
    adm = FeatureCollection.from_columns(
        FeatureType.from_spec("adm", "*geom:Polygon:srid=4326"),
        np.arange(32), {"geom": cells},
    )
    li, ri = spatial_join_indexed(ds, "ships", adm, "contains")
    per_cell = np.bincount(li, minlength=32)
    print(f"join: {len(li)} pairs; busiest cell holds {per_cell.max()} ships")

    # 3. reproject results to web mercator for a mapping client
    merc = ds.query(
        "ships", "bbox(geom, -10, -10, 10, 10)",
        hints=QueryHints(reproject="EPSG:3857"),
    )
    print(f"mercator rows: {len(merc)}, "
          f"x range ±{float(np.abs(merc.geom_column.x).max()):.0f} m")
    return rows


if __name__ == "__main__":
    main()
