"""Batched query/density pipelines and the transactional update surface.

Run: JAX_PLATFORMS=cpu python examples/batch_and_update.py

- ``query_many`` / ``density_many`` dispatch every request's device work
  before pulling any result, overlapping the per-call link roundtrip
  (PERF.md §4e: ~5-8x throughput on a tunneled TPU).
- ``upsert`` replaces features by id; ``modify_features`` rewrites
  attribute values with index keys re-derived, so geometry/time updates
  move rows to their new index cells.
"""

import numpy as np

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu import geometry as geo


def main():
    sft = FeatureType.from_spec(
        "fleet", "callsign:String,dtg:Date,*geom:Point:srid=4326"
    )
    ds = DataStore()
    ds.create_schema(sft)

    n = 100_000
    rng = np.random.default_rng(7)
    t0 = np.datetime64("2024-06-01", "ms").astype(np.int64)
    ds.write("fleet", FeatureCollection.from_columns(
        sft, np.arange(n).astype(str),
        {
            "callsign": np.array([f"V{i % 50}" for i in range(n)], dtype=object),
            "dtg": t0 + rng.integers(0, 7 * 86_400_000, n),
            "geom": (rng.uniform(-30, 30, n), rng.uniform(-20, 20, n)),
        },
    ))

    # a batch of region queries: one pipelined pull instead of four
    boxes = [(-30, -20, 0, 0), (0, 0, 30, 20), (-30, 0, 0, 20), (0, -20, 30, 0)]
    queries = [f"bbox(geom, {a}, {b}, {c}, {d})" for a, b, c, d in boxes]
    results = ds.query_many("fleet", queries)
    print("region hit counts:", [len(r) for r in results])

    # a 2x2 heatmap frame: every tile's grid kernel dispatches up front
    tiles = ds.density_many(
        "fleet", [(q, box) for q, box in zip(queries, boxes)],
        width=128, height=128,
    )
    print("tile masses:", [int(t.sum()) for t in tiles])

    # vessel V7 reports a corrected position: move every fix, then verify
    # the rows are found at the new location through the index
    moved = ds.modify_features(
        "fleet", {"geom": geo.Point(150.0, 45.0)}, "callsign = 'V7'"
    )
    relocated = ds.query("fleet", "bbox(geom, 149, 44, 151, 46)")
    print(f"moved {moved} fixes; index now finds {len(relocated)} at the new spot")

    # late-arriving corrected records replace their originals by id
    fix = FeatureCollection.from_columns(
        sft, ["0", "1"],
        {
            "callsign": np.array(["V0", "V0"], dtype=object),
            "dtg": np.array([t0, t0]),
            "geom": (np.array([10.0, 10.1]), np.array([5.0, 5.1])),
        },
    )
    ds.upsert("fleet", fix)
    assert ds.count("fleet") == n  # replaced, not appended
    return results


if __name__ == "__main__":
    main()
