"""Quickstart: schema -> ingest -> query -> aggregate -> export.

Run: JAX_PLATFORMS=cpu python examples/quickstart.py
(on a TPU host, drop the env var — the same code runs the Pallas path)
"""

import numpy as np

from geomesa_tpu import DataStore, FeatureCollection, FeatureType


def main():
    sft = FeatureType.from_spec(
        "events", "name:String:index=true,dtg:Date,*geom:Point:srid=4326"
    )
    ds = DataStore()
    ds.create_schema(sft)

    n = 200_000
    rng = np.random.default_rng(0)
    t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
    ds.write("events", FeatureCollection.from_columns(
        sft, np.arange(n).astype(str),
        {
            "name": np.array([f"n{i % 100}" for i in range(n)]),
            "dtg": t0 + rng.integers(0, 30 * 86_400_000, n),
            "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
        },
    ))

    q = ("bbox(geom, -20, -10, 40, 35) AND "
         "dtg DURING 2024-01-03T00:00:00Z/2024-01-20T00:00:00Z")
    hits = ds.query("events", q)
    print(f"{len(hits)} hits; estimate was {ds.estimate_count('events', q)}")

    grid = ds.density("events", q, width=128, height=128)
    print(f"density grid sums to {grid.sum():.0f}")

    print(ds.explain("events", "bbox(geom, 0, 0, 10, 10) OR name = 'n7'"))

    from geomesa_tpu.io import export

    csv = export(hits.take(np.arange(min(3, len(hits)))), "csv")
    print(csv.splitlines()[0])
    return hits


if __name__ == "__main__":
    main()
