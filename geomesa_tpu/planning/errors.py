"""Planning/execution errors and the deadline check — a leaf module so the
storage layer can enforce deadlines without importing the planner
(planner -> storage is the real dependency direction)."""

from __future__ import annotations

import time
from dataclasses import dataclass


class QueryGuardError(Exception):
    """A query guard rejected the plan (reference planning/guard/)."""


class QueryTimeout(Exception):
    """A query exceeded its deadline (reference per-plan timeouts +
    ThreadManagement.scala: scans are registered with a timeout and killed
    when overdue; here the single-controller design checks wall-clock at
    every stage boundary — before/after each device call and around host
    refinement — and aborts the query). Carries ``elapsed_s``/``budget_s``
    so callers and audit sinks can report how far over budget the scan
    ran (None when the deadline was a bare monotonic cutoff)."""

    def __init__(self, msg: str, elapsed_s: float | None = None,
                 budget_s: float | None = None):
        super().__init__(msg)
        self.elapsed_s = elapsed_s
        self.budget_s = budget_s


@dataclass(frozen=True)
class Deadline:
    """A query's wall-clock budget: monotonic start + cutoff. Floats
    (bare cutoffs) are still accepted by :func:`check_deadline` for
    back-compat; the object form lets QueryTimeout report elapsed vs
    budget."""

    start: float    # time.monotonic() at plan/execute entry
    budget_s: float
    cutoff: float   # start + budget_s

    def remaining(self) -> float:
        return self.cutoff - time.monotonic()


def check_deadline(deadline: "Deadline | float | None", stage: str) -> None:
    """Raise QueryTimeout when a monotonic deadline has passed."""
    if deadline is None:
        return
    now = time.monotonic()
    if isinstance(deadline, Deadline):
        if now > deadline.cutoff:
            elapsed = now - deadline.start
            raise QueryTimeout(
                f"query deadline exceeded during {stage} "
                f"(elapsed {elapsed:.3f}s > budget {deadline.budget_s:.3f}s)",
                elapsed_s=elapsed, budget_s=deadline.budget_s,
            )
    elif now > deadline:
        raise QueryTimeout(f"query deadline exceeded during {stage}")


def deadline_from(timeout: float | None) -> Deadline | None:
    """A Deadline for a timeout in seconds, or None."""
    if timeout is None:
        return None
    now = time.monotonic()
    return Deadline(start=now, budget_s=timeout, cutoff=now + timeout)
