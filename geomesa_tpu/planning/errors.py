"""Planning/execution errors and the deadline check — a leaf module so the
storage layer can enforce deadlines without importing the planner
(planner -> storage is the real dependency direction)."""

from __future__ import annotations

import time


class QueryGuardError(Exception):
    """A query guard rejected the plan (reference planning/guard/)."""


class QueryTimeout(Exception):
    """A query exceeded its deadline (reference per-plan timeouts +
    ThreadManagement.scala: scans are registered with a timeout and killed
    when overdue; here the single-controller design checks wall-clock at
    every stage boundary — before/after each device call and around host
    refinement — and aborts the query)."""


def check_deadline(deadline: float | None, stage: str) -> None:
    """Raise QueryTimeout when a monotonic deadline has passed."""
    if deadline is not None and time.monotonic() > deadline:
        raise QueryTimeout(f"query deadline exceeded during {stage}")


def deadline_from(timeout: float | None) -> float | None:
    """Monotonic cutoff for a timeout in seconds, or None."""
    return None if timeout is None else time.monotonic() + timeout
