"""Per-query hints: the reference's QueryHints tier.

Reference: /root/reference/geomesa-index-api/src/main/scala/org/
locationtech/geomesa/index/conf/QueryHints.scala — DENSITY_*, STATS_*,
BIN_*, SAMPLING, LOOSE_BBOX, plus GeoTools-level transforms/sort/limit.
Here hints are one typed dataclass handed to DataStore.query (or implied by
the dedicated density/stats/bin entry points).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


@dataclass
class QueryHints:
    """Options applied around the core scan.

    - ``transforms``: attribute-name projection of the result columns
      (reference query transforms / relational projection)
    - ``sort_by``: attribute to sort results by; prefix ``-`` for
      descending (reference SORT_FIELDS hint)
    - ``sample``: keep roughly this fraction of hits, (0, 1]; applied as a
      deterministic stride after refinement (reference SamplingIterator)
    - ``sample_by``: stratify sampling per value of this attribute
      (reference SAMPLE_BY hint)
    - ``loose``: accept the widened device mask without exact host
      refinement of spatial/temporal predicates — the reference's
      LOOSE_BBOX fast path. Non-indexed predicates are still applied.
    - ``offset``: skip this many results after sorting (reference
      GeoTools Query.startIndex paging; pair with the query ``limit`` for
      stable pages under a ``sort_by``)
    - ``timeout``: wall-clock budget in seconds for this query; checked at
      stage boundaries, raises QueryTimeout when exceeded (reference
      per-plan timeouts + ThreadManagement scan registration). Overrides
      the store-level ``query_timeout`` default.
    - ``cache``: per-query result-cache control (stores configured with a
      cache tier; docs/caching.md). ``None`` = normal probe/populate;
      ``"bypass"`` = skip the cache entirely (probe AND populate — for
      one-off queries that must not pollute it); ``"pin"`` = cache this
      result regardless of the cost-admission threshold and exempt it
      from LRU eviction (dashboards' hottest queries). Pinned entries are
      still invalidated by mutations and TTL.
    """

    transforms: Optional[Sequence[str]] = None
    sort_by: Optional[str] = None
    offset: Optional[int] = None
    sample: Optional[float] = None
    sample_by: Optional[str] = None
    loose: bool = False
    timeout: Optional[float] = None
    # reproject result geometries from the store-native EPSG:4326 to this
    # CRS (reference QueryPlanner.scala:292 reprojection hints); applied
    # after refinement, before transforms. Unsupported CRSs raise.
    reproject: Optional[str] = None
    cache: Optional[str] = None  # None | "bypass" | "pin"

    def validate(self) -> None:
        if self.cache not in (None, "bypass", "pin"):
            raise ValueError(
                f"cache hint must be None, 'bypass' or 'pin', got {self.cache!r}"
            )
        if self.reproject is not None:
            from geomesa_tpu.crs import normalize_crs

            normalize_crs(self.reproject)  # raises on unsupported
        if self.sample is not None and not (0.0 < self.sample <= 1.0):
            raise ValueError(f"sample must be in (0, 1], got {self.sample}")
        if self.timeout is not None and self.timeout <= 0:
            raise ValueError(f"timeout must be positive, got {self.timeout}")
        if self.offset is not None and (
            not isinstance(self.offset, (int, np.integer)) or self.offset < 0
        ):
            raise ValueError(f"offset must be a non-negative int, got {self.offset!r}")
