"""QueryPlanner: pick an index, build a scan plan, execute, refine.

Reference call stack (SURVEY.md §3.1): QueryPlanner.runQuery ->
StrategyDecider.getFilterPlan -> keySpace.getIndexValues/getRanges ->
adapter.createQueryPlan -> scan -> client-side reduce
(/root/reference/geomesa-index-api/src/main/scala/org/locationtech/
geomesa/index/planning/QueryPlanner.scala:40-161, StrategyDecider.scala:
47-181). The TPU pipeline: extract filter values -> per-index ScanConfig ->
priority/cost selection -> tile-pruned device scan -> host gather ->
residual full-filter refinement (the `useFullFilter` tier, always exact
f64) -> limit.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter import ecql
from geomesa_tpu.filter.extract import extract_ids
from geomesa_tpu.filter.predicates import Filter, Include
from geomesa_tpu.index.api import ScanConfig
from geomesa_tpu.obs.trace import span as _ospan
from geomesa_tpu.obs.trace import tracer as _otracer
from geomesa_tpu.planning.explain import Explainer, ExplainNull

# index selection priority when multiple indexes can serve a filter;
# mirrors the reference's cost multipliers (SpatioTemporalFilterStrategy:
# z3 = 1.1 with bounded time; SpatialFilterStrategy z2 = 2.0; attribute =
# 1.0 with equality...). Lower = preferred.
INDEX_PRIORITY = {
    "z3": 1.1, "xz3": 1.1, "s3": 1.2,
    "z2": 2.0, "xz2": 2.0, "s2": 2.1,
    "attr": 2.5, "id": 0.5,
}


def index_priority(name: str) -> float:
    """Cost multiplier for an index name; attribute indexes are named
    ``attr_<attribute>`` and share the ``attr`` multiplier."""
    return INDEX_PRIORITY.get(name, INDEX_PRIORITY.get(name.split("_")[0], 3.0))


@dataclass
class QueryPlan:
    """A chosen execution strategy for one query."""

    type_name: str
    filter: Filter
    index: Optional[str]  # None = full-table host scan
    config: Optional[ScanConfig]
    ids: Optional[list] = None  # id-lookup plan
    limit: Optional[int] = None
    planning_s: float = 0.0  # wall-clock spent planning (audit/metrics)
    # multi-index union plan (reference FilterSplitter OR options): each
    # sub-plan scans one DNF disjunct on its own index; results dedup-union
    union: Optional[list["QueryPlan"]] = None
    # degraded-mode notices (quarantined partitions excluded from results);
    # populated at plan time from the store's health, counted at execute
    warnings: Optional[list] = None
    # result-cache outcome for this execution ("hit"|"miss"|"coalesced"|
    # None = cache not consulted) + time spent probing the cache, kept
    # SEPARATE from scan time so cache regressions are attributable in
    # explain traces and the geomesa.query.cache_probe timer
    cache_status: Optional[str] = None
    cache_probe_s: float = 0.0
    # serving-tier attribution (geomesa_tpu.serving): wall-clock this plan
    # spent queued behind the micro-batch window before its fused dispatch
    # — kept SEPARATE from scan time so queue wait is attributable in
    # explain traces and the geomesa.serving.queue_wait histogram
    queue_wait_s: float = 0.0
    # estimate accountability (docs/observability.md): the stats-sketch
    # row estimate resolved at plan time (None = no sketch covered the
    # filter, or geomesa.plan.estimate.enabled off) and the rows the
    # executed scan actually produced — record_query feeds the pair into
    # the geomesa.plan.estimate.error histogram + per-index accuracy
    estimated_rows: Optional[float] = None
    actual_rows: Optional[int] = None

    @property
    def strategy(self) -> str:
        if self.union is not None:
            return "union(" + "+".join(p.strategy for p in self.union) + ")"
        if self.ids is not None:
            return "id-lookup"
        if self.index is None:
            return "full-scan"
        return self.index


# re-exported for existing importers; the definitions live in the leaf
# module planning.errors so the storage layer can use them too
from geomesa_tpu.planning.errors import (  # noqa: E402
    QueryGuardError, QueryTimeout, check_deadline, deadline_from,
)


def _filter_leaf_kinds(
    f: Filter, geom_field: str | None, dtg_field: str | None
) -> set | None:
    """The set of predicate kinds ({"spatial", "temporal"}) this filter is
    built from, or None when any predicate is outside the indexable
    spatio-temporal subset (And of leaves; Or only of same-kind leaves)."""
    from geomesa_tpu.filter.predicates import (
        And, BBox, Between, Cmp, During, Include, Intersects, Or,
    )

    def leaf_kind(p) -> str | None:
        if isinstance(p, (BBox, Intersects)) and p.prop == geom_field:
            return "spatial"
        if isinstance(p, (During, Between)) and p.prop == dtg_field:
            return "temporal"
        if isinstance(p, Cmp) and p.prop == dtg_field and p.op in ("<", "<=", ">", ">=", "="):
            return "temporal"
        return None

    def walk(p) -> set | None:
        if isinstance(p, Include):
            return set()
        if isinstance(p, And):
            out: set = set()
            for c in p.filters:
                k = walk(c)
                if k is None:
                    return None
                out |= k
            return out
        if isinstance(p, Or):
            kinds = {leaf_kind(c) for c in p.filters}
            return kinds if len(kinds) == 1 and None not in kinds else None
        k = leaf_kind(p)
        return {k} if k else None

    return walk(f)


def _referenced_props(f: Filter) -> set:
    """Every attribute name a filter tree references (``prop`` fields of
    leaf predicates, recursing into And/Or/Not)."""
    from geomesa_tpu.filter.predicates import And, Not, Or

    out: set = set()
    if isinstance(f, (And, Or)):
        for c in f.filters:
            out |= _referenced_props(c)
    elif isinstance(f, Not):
        out |= _referenced_props(f.filter)
    else:
        prop = getattr(f, "prop", None)
        if prop is not None:
            out.add(prop)
    return out


def mask_decides_filter(
    f: Filter, config: Optional[ScanConfig], sft, for_aggregation: bool = False
) -> bool:
    """True when the device scan mask decides this filter entirely, so
    loose mode / aggregation push-down may skip host refinement. Requires
    (a) every predicate to be an indexable spatial/temporal leaf, (b) the
    config to be precise on both axes, and (c) the chosen index to actually
    enforce each predicate kind present — an atemporal index (z2) leaves
    ``windows=None`` and must not satisfy a temporal filter. Gate for the
    LOOSE_BBOX fast path (reference Z3IndexKeySpace.useFullFilter,
    Z3IndexKeySpace.scala:240-254).

    ``for_aggregation``: device aggregation kernels evaluate the BOX wide
    plane only — a polygon-tier config (config.poly / config.rast)
    decides the filter for row scans (certainty vector + host
    boundary-residue refinement) but NOT for gather-free aggregations,
    which would count the whole bbox."""
    if config is None or not (config.geom_precise and config.time_precise):
        return False
    if for_aggregation and (config.poly is not None or config.rast is not None):
        return False
    kinds = _filter_leaf_kinds(f, sft.geom_field, sft.dtg_field)
    if kinds is None:
        return False
    if "spatial" in kinds and config.boxes is None:
        return False
    if "temporal" in kinds and config.windows is None:
        return False
    return True


# scan-config memo bound: repeated dashboard queries re-plan constantly;
# the z/xz range decomposition is the dominant planning cost and a PURE
# function of (index instance, filter), so memoizing it is always safe
_CONFIG_MEMO_MAX = 4096


class QueryPlanner:
    """Plans and runs queries for one DataStore."""

    def __init__(self, store):
        import threading

        self.store = store
        # (index instance, canonical filter key) -> ScanConfig | None.
        # Keyed by the index OBJECT, so a dropped-and-recreated schema
        # (fresh index instances, possibly different resolution) can never
        # serve a stale decomposition; LRU-bounded. The lock makes
        # concurrent plan() calls safe (the serving tier plans in caller
        # threads): an OrderedDict mutating under two threads corrupts.
        self._config_memo: "OrderedDict" = OrderedDict()
        self._memo_lock = threading.Lock()
        self._memo_epoch = 0  # bumped by every invalidation (see below)
        # plan-feedback hook (docs/tuning.md): an armed tuning tier
        # installs its IndexReweighter here; None (the default, and the
        # disarmed state) keeps cost() bit-identical to the static
        # multipliers. Reads are lock-free (immutable table swap).
        self.reweighter = None

    @property
    def mutation_epoch(self) -> int:
        """Monotonic count of committed mutations (config-memo
        invalidations). The serving tier scopes in-window coalescing to
        one epoch so a query admitted after a write never shares a
        pre-write leader's result."""
        return self._memo_epoch

    def invalidate_config_memo(self) -> None:
        """Drop every memoized scan config. The store calls this after
        EVERY committed mutation: scan_config is pure only between
        mutations (bin_range clamping in z3/xz3/s2/attribute indexes
        depends on the data), so a memo entry may not outlive a write.
        Bumping the epoch stops a decomposition computed BEFORE the
        mutation (outside the lock) from being inserted after it."""
        with self._memo_lock:
            self._memo_epoch += 1
            self._config_memo.clear()

    def _scan_config(self, idx, f: Filter):
        """``idx.scan_config(f)`` through the memo (planner half of the
        cache tier's "probe before scan": a warm repeat query skips the
        range decomposition entirely). Only valid between mutations —
        see invalidate_config_memo. The decomposition itself runs outside
        the lock: two racing planners may both compute (benign — the
        result is pure), but never block each other on it."""
        from geomesa_tpu.filter.predicates import canonical_key

        key = (idx, canonical_key(f))
        with _ospan("plan.probe", index=idx.name):
            with self._memo_lock:
                memo = self._config_memo
                if key in memo:
                    memo.move_to_end(key)
                    return memo[key]
                epoch = self._memo_epoch
        with _ospan("plan.decompose", index=idx.name):
            cfg = idx.scan_config(f)
        with self._memo_lock:
            if self._memo_epoch != epoch:
                # a mutation invalidated mid-compute: this decomposition
                # reflects pre-write data — usable for THIS query (the
                # inherent plan/execute race) but never memoizable
                return cfg
            memo = self._config_memo
            memo[key] = cfg
            while len(memo) > _CONFIG_MEMO_MAX:
                memo.popitem(last=False)
        return cfg

    # -- planning --------------------------------------------------------
    def plan(
        self,
        type_name: str,
        f: "Filter | str",
        limit: Optional[int] = None,
        explain: Explainer | None = None,
        intercept: bool = True,
        guard: "bool | None" = None,
    ) -> QueryPlan:
        """``intercept=False`` skips the interceptor rewrite — for internal
        maintenance scans (age-off sweeps, delete_features, which guards
        must not reject either: ``guard`` defaults to ``intercept``) and
        for callers that already applied the rewrite themselves (pass
        ``guard=True`` to keep guarding those)."""
        if guard is None:
            guard = intercept
        t0 = time.perf_counter()
        exp = explain or ExplainNull()
        with _ospan("plan", type=type_name):
            if isinstance(f, str):
                f = ecql.parse(f)
            from geomesa_tpu.filter.predicates import normalize_antimeridian

            f = normalize_antimeridian(f)
            if intercept:
                f = self.store.apply_interceptors(type_name, f)
                # attribute-level visibility closes at PLAN depth: a predicate
                # over a hidden attribute would evaluate against the hidden
                # values during scan/refinement, letting unauthorized auths
                # reconstruct them by probing (the reference's cell-level
                # visibility makes the cell unreadable to the scan itself)
                self._check_attr_visibility(type_name, f)
            exp(f"Planning query on '{type_name}': {type(f).__name__}")

            plan = self._select(type_name, f, limit, exp)
            self._estimate_rows(plan, exp)
            if guard:
                self.store.apply_guards(plan)
            # degraded mode: a store that quarantined damaged partitions at
            # load answers from the survivors and WARNS instead of raising
            health = getattr(self.store, "health", None)
            if health is not None:
                w = health.warning_for(type_name)
                if w is not None:
                    plan.warnings = [w]
                    exp.warn(w)
        plan.planning_s = time.perf_counter() - t0
        return plan

    def _estimate_rows(self, plan: QueryPlan, exp) -> None:
        """Resolve the stats-sketch row estimate for a finished plan
        (docs/observability.md "Estimate accountability"): the marginal-
        histogram selectivity product first, the z-prefix sketch of the
        chosen index as the fallback — the same two tiers
        ``estimate_count`` trusts. Skipped for id lookups (exact by
        construction — they would dilute the staleness signal with
        perfect scores) and disjoint plans (nothing scans)."""
        from geomesa_tpu import conf

        if not conf.PLAN_ESTIMATE.get():
            return
        if plan.ids is not None or (
            plan.config is not None and plan.config.disjoint
        ):
            return
        stats = self.store.stats_for(plan.type_name)
        if stats is None:
            return
        if isinstance(plan.filter, Include):
            est = float(stats.total_count())
        else:
            sft = self.store.get_schema(plan.type_name)
            est = stats.estimate_filter(sft, plan.filter)
            if est is None and plan.index is not None and plan.config is not None:
                est = stats.estimate_scan(plan.index, plan.config)
        if est is not None:
            plan.estimated_rows = float(est)
            exp(f"Estimated rows: ~{est:.0f} (stats sketch)")

    def _check_attr_visibility(self, type_name: str, f: Filter) -> None:
        auths = getattr(self.store, "auths", None)
        if auths is None:
            return
        sft = self.store.get_schema(type_name)
        from geomesa_tpu.security import visible

        hidden = {
            a.name
            for a in sft.attributes
            if a.options.get("vis")
            and not visible(str(a.options["vis"]), frozenset(auths))
        }
        if not hidden:
            return
        used = _referenced_props(f)
        blocked = sorted(hidden & used)
        if blocked:
            raise QueryGuardError(
                f"filter references attribute(s) {blocked} whose "
                "visibility the configured auths do not satisfy"
            )

    def _select(
        self, type_name: str, f: Filter, limit: Optional[int], exp
    ) -> QueryPlan:
        plan = self._select_single(type_name, f, limit, exp)
        if plan.index is not None or plan.ids is not None:
            return plan
        # no single index serves the whole filter: try a multi-index union
        # over the DNF disjuncts (reference FilterSplitter.scala:61-147)
        union = self._select_union(type_name, f, limit, exp)
        return union if union is not None else plan

    def _select_union(
        self, type_name: str, f: Filter, limit: Optional[int], exp
    ) -> Optional[QueryPlan]:
        from geomesa_tpu.filter.dnf import rewrite_dnf

        disjuncts = rewrite_dnf(f)
        if disjuncts is None or len(disjuncts) < 2:
            return None
        subs: list[QueryPlan] = []
        for d in disjuncts:
            sp = self._select_single(type_name, d, None, exp)
            if sp.config is not None and sp.config.disjoint:
                exp("Union: disjunct unsatisfiable, dropped")
                continue  # contributes nothing to the union
            if sp.index is None and sp.ids is None:
                exp("Union: a disjunct needs a full scan -> single-scan plan")
                return None  # one full scan beats full scan + index scans
            subs.append(sp)
        if not subs:
            return QueryPlan(type_name, f, None, ScanConfig.empty("union"), ids=[])
        if len(subs) == 1:
            # every other disjunct was unsatisfiable: the live branch IS the
            # query (its disjunct filter is equivalent to the whole filter)
            exp(f"Strategy: {subs[0].strategy} (other disjuncts unsatisfiable)")
            subs[0].limit = limit
            return subs[0]
        exp(
            f"Strategy: union of {len(subs)} index scans ("
            + ", ".join(s.strategy for s in subs) + ")"
        )
        return QueryPlan(type_name, f, None, None, limit=limit, union=subs)

    def _select_single(
        self, type_name: str, f: Filter, limit: Optional[int], exp
    ) -> QueryPlan:
        # id filters take absolute priority (reference IdFilterStrategy)
        ids = extract_ids(f)
        if ids.disjoint:
            exp("Id extraction: disjoint -> empty plan")
            return QueryPlan(type_name, f, None, ScanConfig.empty("id"), ids=[])
        if ids.values:
            exp(f"Strategy: id-lookup ({len(ids.values)} ids)")
            return QueryPlan(type_name, f, "id", None, ids=list(ids.values), limit=limit)

        indexes = self.store.indexes(type_name)
        options: list[tuple[float, str, ScanConfig]] = []
        for idx in indexes:
            cfg = self._scan_config(idx, f)
            if cfg is None:
                continue
            if cfg.disjoint:
                exp(f"Index {idx.name}: filter disjoint -> empty plan")
                return QueryPlan(type_name, f, idx.name, cfg, limit=limit)
            cost = self.cost(type_name, idx.name, cfg, exp)
            options.append((cost, idx.name, cfg))
            exp(
                f"Index {idx.name}: {cfg.n_ranges} ranges, cost {cost:.1f}"
            )
        if not options:
            exp("Strategy: full-table host scan (no index serves this filter)")
            return QueryPlan(type_name, f, None, None, limit=limit)
        options.sort(key=lambda o: o[0])
        cost, name, cfg = options[0]
        exp(f"Strategy: {name} (cost {cost:.1f})")
        return QueryPlan(type_name, f, name, cfg, limit=limit)

    def cost(self, type_name: str, index_name: str, cfg: ScanConfig, exp) -> float:
        """Cost = estimated rows scanned x index multiplier (reference
        CostBasedStrategyDecider: stats.getCount x costMultiplier,
        StrategyDecider.scala:143-180). The primary estimator is exact —
        the sum of the searchsorted row spans the ranges cover, since the
        sorted keys are host-resident; the sketch estimate (Z3Histogram)
        and the bare priority constant are fallbacks. An armed tuning
        tier inflates the multiplier of an index whose row estimates
        chronically miss (docs/tuning.md leg a) — bounded, hysteretic,
        and explain-traced; factor 1.0 (or no reweighter) leaves the
        cost bit-identical to the static decision."""
        mult = index_priority(index_name)
        rw = self.reweighter
        if rw is not None:
            fac = rw.factor(type_name, index_name)
            if fac != 1.0:
                mult *= fac
                exp(
                    f"Index {index_name}: estimate-accuracy reweight "
                    f"x{fac:.2f} (docs/tuning.md)"
                )
        try:
            table = self.store.table(type_name, index_name)
        except KeyError:
            return mult  # no data written yet
        rows = sum(hi - lo for lo, hi in table.candidate_spans(cfg))
        return (rows + 1) * mult

    # -- execution -------------------------------------------------------
    def execute(
        self,
        plan: QueryPlan,
        explain: Explainer | None = None,
        hints=None,
        deadline=None,
    ) -> FeatureCollection:
        """``deadline``: an optional pre-anchored Deadline (the serving
        tier charges queue wait against the caller's budget); default
        starts the clock here, from the hint/store timeout."""
        t0 = time.perf_counter()
        try:
            out = self._execute_or_cached(plan, explain, hints, deadline)
        except QueryTimeout:
            self._record_timeout(plan)
            raise
        self.store.record_query(plan, len(out), time.perf_counter() - t0)
        return out

    def _execute_or_cached(
        self,
        plan: QueryPlan,
        explain: Explainer | None = None,
        hints=None,
        deadline=None,
    ) -> FeatureCollection:
        """The result-cache tier around :meth:`_execute` (docs/caching.md):
        probe by canonical fingerprint, single-flight the scan on a miss,
        populate under cost-aware admission. Generation validation inside
        the cache guarantees a served entry reflects every committed
        mutation; the ``cache`` hint bypasses or pins per query."""
        cache = getattr(self.store, "cache", None)
        mode = getattr(hints, "cache", None) if hints is not None else None
        if cache is None or not cache.result.enabled or mode == "bypass":
            return self._execute(plan, explain, hints, deadline=deadline)
        exp = explain or ExplainNull()
        sft = self.store.get_schema(plan.type_name)
        key = cache.fingerprint_plan(
            plan, hints, sft, getattr(self.store, "auths", None)
        )
        key_range = cache.key_range(plan.filter, sft)

        def compute():
            s0 = time.perf_counter()
            value = self._execute(plan, explain, hints, deadline=deadline)
            return value, time.perf_counter() - s0

        t_probe = time.perf_counter()
        out, status, probe_s = cache.result.get_or_compute(
            key, plan.type_name, key_range, compute, pinned=(mode == "pin")
        )
        plan.cache_status = status
        plan.cache_probe_s = probe_s
        # the probe phase is the get_or_compute prefix BEFORE any scan:
        # recorded retroactively from the measured probe_s so a hit's
        # trace shows probe ~= the whole execute
        tr = _otracer()
        tr.add_span(
            tr.current(), "probe", t0=t_probe, end=t_probe + probe_s,
            status=status,
        )
        exp(f"cache: {status} (probe {probe_s * 1e3:.3f}ms, key {key[:12]})")
        return out

    def _record_timeout(self, plan) -> None:
        """A timed-out scan must still be recorded (reference audit writes
        failed scans too): bump the timeout counter so overdue queries are
        visible in metrics instead of vanishing with the exception."""
        metrics = getattr(self.store, "metrics", None)
        if metrics is not None:
            metrics.counter("geomesa.query.timeout")

    def _deadline(self, hints):
        """Monotonic cutoff from the hint timeout or the store default."""
        timeout = getattr(hints, "timeout", None) if hints is not None else None
        if timeout is None:
            timeout = getattr(self.store, "query_timeout", None)
        return deadline_from(timeout)

    def _execute(
        self,
        plan: QueryPlan,
        explain: Explainer | None = None,
        hints=None,
        skip_visibility: bool = False,
        deadline=None,
    ) -> FeatureCollection:
        exp = explain or ExplainNull()
        if hints is not None:
            hints.validate()
        if deadline is None:
            deadline = self._deadline(hints)
        prog = getattr(self.store, "_fold_progress", {}).get(plan.type_name)
        if prog is not None:
            # lock-free snapshot of the sliced-fold progress surface
            # (docs/streaming.md): the query is interleaving with an
            # in-flight incremental fold — visible in explain alongside
            # the geomesa.stream.fold.progress gauge
            exp(f"Streaming fold in progress: slice {prog[0]}/{prog[1]}")

        if plan.union is not None:
            return self._execute_union(plan, exp, hints, deadline)

        certain = None
        if plan.ids is not None:  # id lookup
            # one snapshot resolves AND gathers: a fold publishing in
            # between cannot shift the ordinals under the gather
            chunks = self.store.chunk_snapshot(plan.type_name)
            ordinals = self.store.id_lookup(
                plan.type_name, plan.ids, chunks=chunks
            )
            candidates = self.store.gather(
                plan.type_name, ordinals, chunks=chunks
            )
        elif plan.index is None:  # full host scan
            fc = self.store.features(plan.type_name)
            check_deadline(deadline, "full-table scan start")
            with _ospan("scan", index="full"):
                with exp.span("Full-table host scan"):
                    mask = plan.filter.evaluate(fc.batch)
            check_deadline(deadline, "full-table scan")
            self._note_actual(plan, int(mask.sum()), exp)
            with _ospan("decode", candidates=plan.actual_rows):
                return self._post(
                    fc.mask(mask), plan, hints, exp, skip_visibility
                )
        elif plan.index is not None and self.store.row_count(plan.type_name) == 0:
            # schema exists but nothing written yet: no index tables
            candidates = self.store.features(plan.type_name)
        else:
            # simple index scan: the shared dispatch/finish implementation
            # (finish runs immediately here; query_many defers it)
            return self._submit_simple(
                plan, exp, hints, skip_visibility, deadline=deadline
            )()

        return self._refine_and_post(
            plan, candidates, certain, hints, exp, deadline, skip_visibility
        )

    def _submit_simple(self, plan, exp, hints, skip_visibility=False,
                       finish_scan=None, deadline=None, chunks=None):
        """Dispatch a simple index-scan plan's device work now; return
        ``finish()`` -> FeatureCollection. ONE implementation serves both
        the synchronous path (_execute calls finish immediately) and the
        pipelined path (execute_many defers it). By default the deadline
        clock starts when finish() runs — matching sequential semantics,
        so a late pull in a long batch doesn't spuriously time out; an
        explicit ``deadline`` (a Deadline) overrides that — the serving
        tier anchors it at ADMISSION so queue wait is charged against
        the caller's budget instead of restarting it at dispatch.

        Candidates gather through ``store.gather`` (per-chunk takes), so
        a delta tier freshly grown by a streaming flush never makes a
        query pay the whole-table chunk concat. The chunk snapshot is
        PINNED at dispatch, next to the table capture: the scan's
        ordinals are table ordinals, and a fold/delete publishing during
        the dispatch->finish window must not shift the rows they gather
        (renumbering publishes swap in a fresh chunk list and leave the
        pinned one untouched).

        ``finish_scan``: an already-dispatched scan's finish (submit_many's
        fused group scans); default dispatches this plan's own scan.
        ``chunks``: the chunk snapshot captured when that scan was
        dispatched (submit_many); default captures one here."""
        if finish_scan is None:
            with _ospan("dispatch", index=plan.index):
                table, chunks = self.store.pin_scan_state(
                    plan.type_name, plan.index
                )
                finish_scan = table.scan_submit(plan.config, deadline=None)
        elif chunks is None:
            chunks = self.store.chunk_snapshot(plan.type_name)

        def finish(deadline=deadline) -> FeatureCollection:
            if deadline is None:
                deadline = self._deadline(hints)
            with _ospan("scan", index=plan.index):
                with exp.span(f"Device scan [{plan.index}]"):
                    # single-chip and distributed tables share one engine
                    # and one contract: (ordinals, certainty vector)
                    ordinals, certain = finish_scan()
                check_deadline(deadline, "scan result pull")
            exp(f"Candidates: {len(ordinals)}")
            with _ospan("decode", candidates=len(ordinals)):
                candidates = self.store.gather(
                    plan.type_name, ordinals, chunks=chunks
                )
                return self._refine_and_post(
                    plan, candidates, certain, hints, exp, deadline,
                    skip_visibility,
                )

        return finish

    def _refine_and_post(
        self, plan, candidates, certain, hints, exp, deadline, skip_visibility=False
    ):
        """Refinement tiers (reference Z3IndexKeySpace.useFullFilter,
        Z3IndexKeySpace.scala:240-254, automatic since round 3):
        - the device mask decides the filter: only *uncertain* boundary
          rows (wide & ~inner; f32/offset rounding) re-check on host;
        - `loose` hint: accept the widened mask outright (reference
          LOOSE_BBOX semantics);
        - otherwise: exact full-filter refinement over all candidates."""
        decided = mask_decides_filter(
            plan.filter, plan.config, self.store.get_schema(plan.type_name)
        )
        loose_ok = hints is not None and getattr(hints, "loose", False) and decided
        if loose_ok or (decided and isinstance(plan.filter, Include)):
            exp("Loose mode: device mask accepted without refinement")
        elif decided and certain is not None:
            unc = np.flatnonzero(~certain)
            exp(f"Refinement: {len(unc)} uncertain of {len(certain)} candidates")
            if len(unc):
                check_deadline(deadline, "boundary refinement start")
                with exp.span("Boundary refinement"):
                    sub_mask = plan.filter.evaluate(candidates.take(unc).batch)
                keep = certain.copy()
                keep[unc] = sub_mask
                # all-true keep: `candidates` is already a fresh gather
                # (fc.take above), so skipping the re-gather is safe and
                # halves the host cost when refinement drops nothing
                if not bool(keep.all()):
                    candidates = candidates.mask(keep)
        elif not isinstance(plan.filter, Include):
            check_deadline(deadline, "residual refinement start")
            with exp.span("Residual filter refinement"):
                mask = plan.filter.evaluate(candidates.batch)
            if not bool(np.all(mask)):  # see all-true note above
                candidates = candidates.mask(mask)
        check_deadline(deadline, "refinement")
        # estimate accountability: the POST-refinement row count — what
        # the sketch estimate actually predicts (filter selectivity) —
        # before _post's limit/visibility stages distort it. The
        # pre-refinement candidate count would charge index
        # over-selection (a z2 scan serving a temporal filter) to the
        # sketches, flagging fresh stats stale forever.
        self._note_actual(plan, len(candidates), exp)
        return self._post(candidates, plan, hints, exp, skip_visibility)

    @staticmethod
    def _note_actual(plan, actual: int, exp) -> None:
        """Record one executed plan's matched-row count next to its
        sketch estimate (explain line; record_query feeds the pair to
        the error histogram and the per-index accuracy windows)."""
        plan.actual_rows = actual
        if plan.estimated_rows is not None:
            from geomesa_tpu.obs.accuracy import error_factor

            exp(
                f"Estimate vs actual: ~{plan.estimated_rows:.0f} est / "
                f"{actual} matched "
                f"({error_factor(plan.estimated_rows, actual):.2f}x)"
            )

    # -- pipelined multi-query execution ---------------------------------
    def _is_simple(self, plan: QueryPlan) -> bool:
        """True when the plan is a plain index scan whose device work can
        dispatch ahead of finish() (no union/id/full-scan special-casing).
        ONE predicate shared by submit and submit_many so their routing
        can never drift."""
        return (
            plan.union is None
            and plan.ids is None
            and plan.index is not None
            and plan.config is not None
            and self.store.row_count(plan.type_name) > 0
        )

    def submit(self, plan: QueryPlan, explain: Explainer | None = None,
               hints=None, deadline=None):
        """Stage one query: dispatch its device scan NOW, return a zero-arg
        ``finish()`` producing the FeatureCollection. Plans without a
        simple index scan (unions, id lookups, full scans) fall back to
        synchronous execution inside finish(); an explicit ``deadline``
        (a pre-anchored Deadline — the serving tier's admission time)
        bounds both paths, default starts each budget at finish()."""
        exp = explain or ExplainNull()
        if not self._is_simple(plan):
            return lambda: self.execute(
                plan, explain=exp, hints=hints, deadline=deadline
            )
        if hints is not None:
            hints.validate()
        return self._record_wrap(plan, self._submit_simple(
            plan, exp, hints, deadline=deadline
        ))

    def _record_wrap(self, plan, inner):
        """finish() wrapper adding query auditing (record_query timing) —
        ONE implementation for submit and submit_many's fused finishes, so
        batched and single queries are always audited identically."""

        def finish() -> FeatureCollection:
            t0 = time.perf_counter()
            try:
                out = inner()
            except QueryTimeout:
                self._record_timeout(plan)
                raise
            self.store.record_query(plan, len(out), time.perf_counter() - t0)
            return out

        return finish

    def submit_many(self, plans, hints=None, explains=None, deadlines=None) -> list:
        """Stage MANY queries: like per-plan :meth:`submit`, but simple
        index-scan plans sharing a (type, index) table route through the
        table's fused multi-query kernel (``scan_submit_many`` — one
        device dispatch per kernel-variant group instead of one per
        query). Returns one ``finish()`` per plan, in input order.
        Non-simple plans (unions, id lookups, full scans) fall back to
        :meth:`submit`, which executes them synchronously inside their
        finish() — only simple index scans dispatch ahead of the pulls.

        ``hints``: one QueryHints applied to every plan, or a sequence
        aligned with ``plans`` — the serving tier (geomesa_tpu.serving)
        batches independent callers carrying DIFFERENT hints into one
        fused dispatch; hints shape only post-processing and deadlines,
        never the device scan, so mixed-hints plans still fuse.
        ``explains``: optional per-plan Explainer sequence — fused
        members trace their device scan/refinement like sequential
        execution. ``deadlines``: optional per-plan Deadline sequence
        anchoring each plan's budget (fused scans AND non-simple
        fallbacks) at an earlier instant — the serving tier's admission
        time — instead of at its finish()."""
        def aligned(seq, what):
            if seq is None:
                return [None] * len(plans)
            if len(seq) != len(plans):
                raise ValueError(
                    f"{what} sequence length {len(seq)} != plans {len(plans)}"
                )
            return list(seq)

        if isinstance(hints, (list, tuple)):
            per = aligned(hints, "hints")
        else:
            per = [hints] * len(plans)
        exps = aligned(explains, "explains")
        dls = aligned(deadlines, "deadlines")
        finishes: list = [None] * len(plans)
        groups: dict[tuple, list[int]] = {}
        for j, plan in enumerate(plans):
            if not self._is_simple(plan):
                finishes[j] = self.submit(
                    plan, explain=exps[j], hints=per[j], deadline=dls[j]
                )
            else:
                groups.setdefault((plan.type_name, plan.index), []).append(j)
        seen: set = set()  # validate each distinct hints object once
        for idxs in groups.values():
            for j in idxs:
                h = per[j]
                if h is not None and id(h) not in seen:
                    seen.add(id(h))
                    h.validate()
        for (tname, iname), idxs in groups.items():
            table, chunks = self.store.pin_scan_state(tname, iname)
            many = getattr(table, "scan_submit_many", None)
            if many is None or len(idxs) == 1:
                for j in idxs:
                    finishes[j] = self.submit(
                        plans[j], explain=exps[j], hints=per[j],
                        deadline=dls[j],
                    )
                continue
            scan_fins = many([plans[j].config for j in idxs])
            for j, scan_fin in zip(idxs, scan_fins):
                plan = plans[j]
                finishes[j] = self._record_wrap(plan, self._submit_simple(
                    plan, exps[j] or ExplainNull(), per[j],
                    finish_scan=scan_fin, deadline=dls[j], chunks=chunks,
                ))
        return finishes

    def execute_many(self, plans, hints=None) -> list:
        """Execute several plans with overlapped device work: every scan
        dispatches before any result is pulled, so per-query round-trip
        latency pipelines instead of serializing (a throughput API — the
        reference gets the same effect from server-side thread pools,
        utils/AbstractBatchScan; here jax async dispatch provides it).
        Scans sharing a table additionally fuse into one kernel dispatch
        per variant group (submit_many)."""
        finishes = self.submit_many(plans, hints=hints)
        return [f() for f in finishes]

    def _execute_union(self, plan: QueryPlan, exp, hints, deadline) -> FeatureCollection:
        """Run every union branch on its own index and dedup-union by
        feature id (reference: per-option scans merged client-side with
        deduplication, FilterSplitter OR semantics). Each branch refines
        with its own disjunct filter, so the union is exact. The query's
        ONE deadline bounds all branches: each gets the remaining budget,
        not a fresh one. Branches skip visibility — it runs once over the
        union in the final _post."""
        from geomesa_tpu.planning.hints import QueryHints

        parts = []
        for sp in plan.union:
            sub_hints = None
            if deadline is not None:
                check_deadline(deadline, f"union branch [{sp.strategy}]")
                sub_hints = QueryHints(timeout=max(deadline.remaining(), 1e-9))
            with exp.span(f"Union branch [{sp.strategy}]"):
                parts.append(
                    self._execute(sp, explain=exp, hints=sub_hints, skip_visibility=True)
                )
        check_deadline(deadline, "union merge")
        nonempty = [p for p in parts if len(p)]
        if not nonempty:
            self._note_actual(plan, 0, exp)
            return self._post(parts[0], plan, hints, exp)
        out = nonempty[0] if len(nonempty) == 1 else FeatureCollection.concat(nonempty)
        _, first = np.unique(np.asarray(out.ids), return_index=True)
        if len(first) != len(out):
            exp(f"Union dedup: {len(out)} -> {len(first)} rows")
            out = out.take(np.sort(first))
        # the union's matched rows BEFORE _post's limit/visibility
        # stages: record_query's hits fallback would compare the sketch
        # estimate against a truncated result (see _note_actual)
        self._note_actual(plan, len(out), exp)
        return self._post(out, plan, hints, exp)

    def _post(self, out, plan, hints, exp, skip_visibility: bool = False):
        """Client-side reduce pipeline: visibility -> sample -> sort ->
        offset -> limit -> project (reference QueryPlanner.scala:66-102
        runs the same stages after the scan: reducer, sort, startIndex,
        maxFeatures, projection)."""
        # row-level security: mask rows whose visibility label the store's
        # auths cannot satisfy (reference VisibilityEvaluator tier)
        auths = None if skip_visibility else getattr(self.store, "auths", None)
        if auths is not None:
            from geomesa_tpu.security import (
                VIS_FIELD_KEY, visibility_mask, visible,
            )

            sft = self.store.get_schema(plan.type_name)
            vis_field = sft.user_data.get(VIS_FIELD_KEY)
            if vis_field and len(out):
                out = out.mask(visibility_mask(out.columns[vis_field], auths))
                exp(f"Visibility filter: {len(out)} visible")
            # attribute-level security (reference geomesa-security
            # SecurityUtils per-attribute labels): an attribute whose
            # ``vis=<label>`` option the auths cannot satisfy is PROJECTED
            # OUT of the result — rows stay visible, the value does not
            hidden = [
                a.name
                for a in out.sft.attributes
                if a.options.get("vis")
                and not visible(str(a.options["vis"]), frozenset(auths))
            ]
            if hidden:
                keep = [a.name for a in out.sft.attributes if a.name not in hidden]
                out = out.project(keep)
                exp(f"Attribute visibility: hid {hidden}")
        exp(f"Hits: {len(out)}")
        if hints is not None:  # validated at _execute entry
            if hints.sample is not None:
                out = out.sample(hints.sample, hints.sample_by)
                exp(f"Sampled: {len(out)}")
            if hints.sort_by:
                out = out.sort_values(hints.sort_by)
        off = hints.offset if hints is not None and hints.offset else 0
        if off or (plan.limit is not None and len(out) > plan.limit):
            # one gather for the page: materializing the whole post-offset
            # tail before the limit would copy every column of a large
            # result just to keep a page of it
            lo = min(off, len(out))
            hi = len(out) if plan.limit is None else min(lo + plan.limit, len(out))
            out = out.take(np.arange(lo, hi))
            if off:
                exp(f"Offset {off}: rows [{lo}, {hi})")
        if hints is not None and hints.reproject is not None:
            from geomesa_tpu.crs import reproject_collection

            out = reproject_collection(out, hints.reproject)
            exp(f"Reprojected to {hints.reproject}")
        if hints is not None and hints.transforms is not None:
            out = out.transform(hints.transforms)
        return out
