"""Query planning: filter -> index selection -> scan plan -> execution.

The reference's planning package (/root/reference/geomesa-index-api/src/
main/scala/org/locationtech/geomesa/index/planning/): QueryPlanner
orchestrates FilterSplitter -> StrategyDecider -> QueryPlan -> scan.
"""

from geomesa_tpu.planning.explain import Explainer
from geomesa_tpu.planning.planner import QueryPlan, QueryPlanner

__all__ = ["Explainer", "QueryPlan", "QueryPlanner"]
