"""Query guards and interceptors: reject or rewrite dangerous queries
before they scan.

Reference: the planner's guard SPI (/root/reference/geomesa-index-api/src/
main/scala/org/locationtech/geomesa/index/planning/guard/ —
FullTableScanQueryGuard.scala:39-48, TemporalQueryGuard.scala,
GraduatedQueryGuard.scala) and QueryInterceptor.scala, hooked at
QueryPlanner.scala:155. Guards inspect the *plan* (chosen strategy +
extracted values) and raise QueryGuardError; interceptors rewrite the
filter before planning.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol, Sequence, runtime_checkable

from geomesa_tpu.filter.extract import extract_geometries, extract_intervals, geometry_bounds
from geomesa_tpu.filter.predicates import Filter, Include
from geomesa_tpu.planning.planner import QueryGuardError, QueryPlan

WHOLE_WORLD_AREA = 360.0 * 180.0


def _union_area(boxes) -> float:
    """Exact union area of axis-aligned boxes via coordinate compression
    (OR'd boxes may overlap; summing would double-count and falsely trip
    the guard)."""
    import numpy as np

    b = np.asarray(list(boxes), dtype=np.float64).reshape(-1, 4)
    xs = np.unique(np.concatenate([b[:, 0], b[:, 2]]))
    ys = np.unique(np.concatenate([b[:, 1], b[:, 3]]))
    if len(xs) < 2 or len(ys) < 2:
        return 0.0
    cx = (xs[:-1] + xs[1:]) / 2
    cy = (ys[:-1] + ys[1:]) / 2
    covered = np.zeros((len(cy), len(cx)), dtype=bool)
    for x0, y0, x1, y1 in b:
        covered |= (
            ((cx >= x0) & (cx <= x1))[None, :] & ((cy >= y0) & (cy <= y1))[:, None]
        )
    wx = np.diff(xs)[None, :]
    wy = np.diff(ys)[:, None]
    return float((covered * wx * wy).sum())


def _practical_span(intervals) -> int:
    """Total queried milliseconds, with intervals open above (e.g. the
    `dtg >= now-ttl` an AgeOffInterceptor appends) clamped to the wall
    clock: the guard bounds *scannable history*, and no history exists
    past now — an astronomically-open upper endpoint must not reject a
    bounded-below recency query."""
    import time

    now = int(time.time() * 1000)
    total = 0
    for iv in intervals:
        hi = min(iv.hi, max(now, iv.lo))
        total += max(hi - iv.lo, 0)
    return total


@runtime_checkable
class QueryInterceptor(Protocol):
    """Rewrites a filter before planning (reference QueryInterceptor SPI).
    Return the (possibly unchanged) filter, or raise QueryGuardError."""

    def rewrite(self, type_name: str, f: Filter) -> Filter: ...


@runtime_checkable
class QueryGuard(Protocol):
    """Inspects a finished plan; raises QueryGuardError to reject it."""

    def guard(self, plan: QueryPlan, sft) -> None: ...


class FullTableScanGuard:
    """Reject plans that fall through to a full-table scan (reference
    FullTableScanQueryGuard.scala:39-48). Include — an explicit
    "everything" query — is allowed, matching the reference."""

    def guard(self, plan: QueryPlan, sft) -> None:
        if plan.strategy == "full-scan" and not isinstance(plan.filter, Include):
            raise QueryGuardError(
                f"query on {plan.type_name!r} requires a full-table scan, "
                "which is disabled"
            )


@dataclass
class AgeOffInterceptor:
    """Hide features older than ``ttl_ms`` from every query (reference
    AgeOffFilter/AgeOffIterator, geomesa-accumulo/.../iterators/
    AgeOffIterator.scala: rows past their TTL stop being visible before
    compaction physically removes them). Queries rewrite with an extra
    dtg >= now-ttl conjunct — the planner's z3 window then prunes the
    expired rows at scan time; DataStore.age_off() is the physical
    removal.

    Scope: only schemas whose time attribute is named ``dtg_field``
    (``applies_to``, consulted by DataStore.apply_interceptors) — a
    store hosting an atemporal or differently-named type must not have
    its queries rewritten against a missing column. ``type_name``
    restricts the TTL to one feature type."""

    ttl_ms: int
    dtg_field: str = "dtg"
    type_name: "str | None" = None
    now_ms: "int | None" = None  # fixed clock for tests; None = wall clock

    def applies_to(self, sft) -> bool:
        if self.type_name is not None and sft.name != self.type_name:
            return False
        return sft.dtg_field == self.dtg_field

    def rewrite(self, type_name: str, f: Filter) -> Filter:
        import time

        from geomesa_tpu.filter.predicates import And, Cmp

        now = self.now_ms if self.now_ms is not None else int(time.time() * 1000)
        cutoff = Cmp(self.dtg_field, ">=", now - self.ttl_ms)
        return cutoff if isinstance(f, Include) else And((f, cutoff))


@dataclass
class TemporalQueryGuard:
    """Require a bounded temporal constraint no longer than ``max_ms``
    (reference TemporalQueryGuard, configured there and here by the
    `geomesa.guard.temporal.max.duration` property). Applies only to
    schemas with a time attribute. The guard is opt-in, exactly like
    the reference: install it via ``DataStore(guards=[...])``; leaving
    ``max_ms`` unset resolves the property tier (environment-
    overridable), the same idiom as ServingConfig/PipelineConfig."""

    max_ms: "int | None" = None

    def __post_init__(self):
        if self.max_ms is None:
            from geomesa_tpu.conf import GUARD_TEMPORAL_MAX

            self.max_ms = int(GUARD_TEMPORAL_MAX.get())

    @staticmethod
    def from_properties() -> "TemporalQueryGuard":
        return TemporalQueryGuard()

    def guard(self, plan: QueryPlan, sft) -> None:
        if sft.dtg_field is None or plan.ids is not None:
            return
        intervals = extract_intervals(plan.filter, sft.dtg_field)
        if intervals.disjoint:
            return
        if not intervals.values:
            raise QueryGuardError(
                f"query on {plan.type_name!r} requires a temporal filter on "
                f"{sft.dtg_field!r}"
            )
        span = _practical_span(intervals.values)
        if span > self.max_ms:
            raise QueryGuardError(
                f"temporal filter spans {span}ms, over the {self.max_ms}ms limit"
            )


@dataclass
class SizeBound:
    """One graduated tier: queries within ``area_deg2`` (None = any extent)
    may span at most ``max_duration_ms`` (None = unbounded)."""

    area_deg2: float | None
    max_duration_ms: int | None


@dataclass
class GraduatedQueryGuard:
    """Stricter duration limits for larger spatial extents (reference
    GraduatedQueryGuard: small boxes may query long histories, wide boxes
    only short ones). ``bounds`` must be ordered smallest-area first."""

    bounds: Sequence[SizeBound]

    def guard(self, plan: QueryPlan, sft) -> None:
        if plan.ids is not None or sft.geom_field is None:
            return
        geoms = extract_geometries(plan.filter, sft.geom_field)
        if geoms.disjoint:
            return
        if geoms.values:
            area = _union_area(geometry_bounds(geoms))
        else:
            area = WHOLE_WORLD_AREA
        limit = None
        for b in self.bounds:
            if b.area_deg2 is None or area <= b.area_deg2:
                limit = b.max_duration_ms
                break
        if limit is None:
            return
        if sft.dtg_field is None:
            return
        intervals = extract_intervals(plan.filter, sft.dtg_field)
        if intervals.disjoint:
            return
        if not intervals.values:
            raise QueryGuardError(
                f"queries over {area:.1f} deg^2 require a temporal filter"
            )
        span = _practical_span(intervals.values)
        if span > limit:
            raise QueryGuardError(
                f"queries over {area:.1f} deg^2 may span at most {limit}ms "
                f"(got {span}ms)"
            )
