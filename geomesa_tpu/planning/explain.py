"""Explainer: tree-structured query-plan tracing.

Reference: /root/reference/geomesa-index-api/src/main/scala/org/
locationtech/geomesa/index/utils/Explainer.scala — nested push/pop spans
surfaced by the CLI `explain` command. Same shape here: an Explainer
collects indented lines; ExplainString renders them, ExplainNull is the
no-op used on the hot path.
"""

from __future__ import annotations

import time
from contextlib import contextmanager


class Explainer:
    """Collects an indented plan trace."""

    def __init__(self):
        self._lines: list[str] = []
        self._warnings: list[str] = []
        self._depth = 0

    def __call__(self, msg: str) -> "Explainer":
        self._lines.append("  " * self._depth + str(msg))
        return self

    def warn(self, msg: str) -> "Explainer":
        """Record a query warning (degraded-mode results, disabled fast
        paths): shows in the trace AND collects separately so callers can
        surface warnings without parsing the trail."""
        self._warnings.append(str(msg))
        return self(f"WARNING: {msg}")

    @property
    def warnings(self) -> list[str]:
        return list(self._warnings)

    @contextmanager
    def span(self, msg: str):
        """Nested section with wall-clock timing (MethodProfiling.profile)."""
        self(msg)
        self._depth += 1
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            dt = (time.perf_counter() - t0) * 1e3
            self(f"took {dt:.2f}ms")
            self._depth -= 1

    def render(self) -> str:
        return "\n".join(self._lines)

    @property
    def lines(self) -> list[str]:
        return list(self._lines)


class ExplainNull(Explainer):
    """No-op explainer for the hot path."""

    def __call__(self, msg: str) -> "Explainer":
        return self

    def warn(self, msg: str) -> "Explainer":
        return self

    @contextmanager
    def span(self, msg: str):
        yield self

    def render(self) -> str:
        return ""
