"""Native (C++) runtime tier: build-on-demand, ctypes-bound, with exact
numpy fallback.

The compute path is JAX/XLA (device); this is the *host runtime* native
tier — the analogue of the reference's server-side JVM plugin code for the
ingest hot loop (see geomesa_native.cpp). The library builds lazily with
g++ the first time it's needed and caches next to the source; every entry
point has a pure-numpy fallback, so the package works identically without
a toolchain (set GEOMESA_TPU_NO_NATIVE=1 to force the fallback).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

_DIR = Path(__file__).resolve().parent
_SRC = _DIR / "geomesa_native.cpp"
_LIB = _DIR / "build" / "libgeomesa_native.so"

_lock = threading.Lock()
_lib: "ctypes.CDLL | None | bool" = None  # None = untried, False = unavailable


def _build() -> bool:
    _LIB.parent.mkdir(exist_ok=True)
    # -ffp-contract=off: the point-in-polygon ray cast promises bit-exact
    # parity with numpy's two-rounding float sequence; fused multiply-adds
    # (default under -O3 on FMA targets) would round differently for
    # points lying exactly on slanted edges
    base = [
        "g++", "-O3", "-ffp-contract=off", "-shared", "-fPIC",
        str(_SRC), "-o", str(_LIB),
    ]
    for extra in (["-fopenmp"], []):  # prefer threaded; fall back
        try:
            r = subprocess.run(
                base[:2] + extra + base[2:],
                capture_output=True,
                timeout=120,
            )
            if r.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            return False
    return False


def _load():
    global _lib
    if _lib is not None:
        return _lib if _lib is not False else None
    with _lock:
        if _lib is not None:
            return _lib if _lib is not False else None
        if os.environ.get("GEOMESA_TPU_NO_NATIVE"):
            _lib = False
            return None
        try:
            if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
                if not _build():
                    _lib = False
                    return None
            lib = ctypes.CDLL(str(_LIB))
        except OSError:
            _lib = False
            return None
        u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
        f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.morton2.argtypes = [u64p, u64p, ctypes.c_int64, u64p]
        lib.morton2_decode.argtypes = [u64p, ctypes.c_int64, u64p, u64p]
        lib.morton3.argtypes = [u64p, u64p, u64p, ctypes.c_int64, u64p]
        lib.morton3_decode.argtypes = [u64p, ctypes.c_int64, u64p, u64p, u64p]
        lib.z3_write_keys.argtypes = [
            f64p, f64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_double, ctypes.c_int32, u64p, i32p, f32p, f32p, i32p,
        ]
        lib.z3_write_keys.restype = ctypes.c_int32
        lib.z2_write_keys.argtypes = [f64p, f64p, ctypes.c_int64, u64p, f32p, f32p]
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.sort_bins_z.argtypes = [i32p, u64p, ctypes.c_int64, u32p]
        for name, tp in (
            ("gather_f32", f32p), ("gather_i32", i32p), ("gather_i64", i64p),
            ("gather_u64", u64p), ("gather_f64", f64p),
        ):
            getattr(lib, name).argtypes = [tp, u32p, ctypes.c_int64, tp]
        for name, tp in (("gather_rows_f32", f32p), ("gather_rows_f64", f64p)):
            getattr(lib, name).argtypes = [
                tp, u32p, ctypes.c_int64, ctypes.c_int64, tp
            ]
        lib.points_in_polygon_cpp.argtypes = [
            f64p, f64p, ctypes.c_int64, f64p, i64p, ctypes.c_int64, i32p, u8p
        ]
        lib.zranges_cpp.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
            u64p, u64p, u64p, u64p,
            ctypes.c_int64, ctypes.c_int64,
            u64p, u64p, u8p, ctypes.c_int64,
        ]
        lib.zranges_cpp.restype = ctypes.c_int64
        lib.bitmask_count.argtypes = [i32p, ctypes.c_int64, ctypes.c_int64]
        lib.bitmask_count.restype = ctypes.c_int64
        lib.bitmask_decode_pair.argtypes = [
            i32p, i32p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            i64p, u8p,
        ]
        lib.bitmask_decode_pair.restype = ctypes.c_int64
        lib.merge_rows_spans.argtypes = [
            i64p, i64p, ctypes.c_int64, i64p, u8p, ctypes.c_int64, i64p, u8p,
        ]
        lib.merge_rows_spans.restype = ctypes.c_int64
        lib.counting_argsort.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_int64, u32p,
        ]
        lib.bitmask_decode.argtypes = [
            i32p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, i64p,
        ]
        lib.bitmask_decode.restype = ctypes.c_int64
        lib.xz_index.argtypes = [
            f64p, f64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            i64p, i64p,
        ]
        lib.xz_ranges.argtypes = [
            ctypes.c_int32, ctypes.c_int32, i64p, f64p, f64p,
            ctypes.c_int64, ctypes.c_int64, u64p, u64p, u8p, ctypes.c_int64,
        ]
        lib.xz_ranges.restype = ctypes.c_int64
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


def morton2(x, y) -> "np.ndarray | None":
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.uint64)
    y = np.ascontiguousarray(y, dtype=np.uint64)
    out = np.empty(len(x), dtype=np.uint64)
    lib.morton2(x, y, len(x), out)
    return out


def morton3(x, y, t) -> "np.ndarray | None":
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.uint64)
    y = np.ascontiguousarray(y, dtype=np.uint64)
    t = np.ascontiguousarray(t, dtype=np.uint64)
    out = np.empty(len(x), dtype=np.uint64)
    lib.morton3(x, y, t, len(x), out)
    return out


def morton3_decode(z):
    lib = _load()
    if lib is None:
        return None
    z = np.ascontiguousarray(z, dtype=np.uint64)
    x = np.empty(len(z), dtype=np.uint64)
    y = np.empty(len(z), dtype=np.uint64)
    t = np.empty(len(z), dtype=np.uint64)
    lib.morton3_decode(z, len(z), x, y, t)
    return x, y, t


# fixed-width periods the native binning supports: millis/bin, offset divisor
_FIXED_PERIODS = {"day": (86_400_000, 1), "week": (604_800_000, 1000)}


def z3_write_keys(x, y, millis, period: str, max_offset: int, max_bin: int):
    """Fused (bins, zs, device cols) for fixed-width periods, or None when
    native is unavailable / the period is calendar-based."""
    lib = _load()
    cfg = _FIXED_PERIODS.get(period)
    if lib is None or cfg is None:
        return None
    bin_ms, off_div = cfg
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    millis = np.ascontiguousarray(millis, dtype=np.int64)
    n = len(x)
    z = np.empty(n, dtype=np.uint64)
    bins = np.empty(n, dtype=np.int32)
    xf = np.empty(n, dtype=np.float32)
    yf = np.empty(n, dtype=np.float32)
    toff = np.empty(n, dtype=np.int32)
    status = lib.z3_write_keys(
        x, y, millis, n, bin_ms, off_div, float(max_offset), max_bin,
        z, bins, xf, yf, toff,
    )
    if status == 1:
        raise ValueError(f"pre-epoch timestamp(s) not supported by period {period}")
    if status == 2:
        raise ValueError(
            f"timestamp(s) past the max representable date for period {period}"
        )
    return bins, z, {"x": xf, "y": yf, "tbin": bins, "toff": toff}


def z2_write_keys(x, y):
    """Fused (zs, device cols) for the z2 index, or None."""
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    n = len(x)
    z = np.empty(n, dtype=np.uint64)
    xf = np.empty(n, dtype=np.float32)
    yf = np.empty(n, dtype=np.float32)
    lib.z2_write_keys(x, y, n, z, xf, yf)
    return z, {"x": xf, "y": yf}


def sort_bins_z(bins, zs) -> "np.ndarray | None":
    """Stable argsort by (bin, z) via LSD radix — the ingest sort hot path
    (np.lexsort replacement; ~10x at 100M rows). Returns uint32 perm, or
    None when native is unavailable or n >= 2^32."""
    lib = _load()
    if lib is None or len(zs) >= (1 << 32):
        return None
    bins = np.ascontiguousarray(bins, dtype=np.int32)
    zs = np.ascontiguousarray(zs, dtype=np.uint64)
    perm = np.empty(len(zs), dtype=np.uint32)
    lib.sort_bins_z(bins, zs, len(zs), perm)
    return perm


_GATHERS = {
    np.dtype(np.float32): "gather_f32",
    np.dtype(np.int32): "gather_i32",
    np.dtype(np.int64): "gather_i64",
    np.dtype(np.uint64): "gather_u64",
    np.dtype(np.float64): "gather_f64",
}


def take(src: np.ndarray, idx: np.ndarray) -> "np.ndarray | None":
    """out[i] = src[idx[i]] for the supported dtypes, or None."""
    lib = _load()
    name = _GATHERS.get(src.dtype)
    if lib is None or name is None or src.ndim != 1:
        return None
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, dtype=np.uint32)
    out = np.empty(len(idx), dtype=src.dtype)
    getattr(lib, name)(src, idx, len(idx), out)
    return out


_ROW_GATHERS = {
    np.dtype(np.float32): "gather_rows_f32",
    np.dtype(np.float64): "gather_rows_f64",
}


def take_rows(src: np.ndarray, idx: np.ndarray) -> "np.ndarray | None":
    """out[i, :] = src[idx[i], :] for f32/f64 [n, width] arrays, or None.
    The threaded row gather hides the random-access memory latency that
    dominates numpy fancy indexing on multi-100k-row result pulls."""
    lib = _load()
    name = _ROW_GATHERS.get(src.dtype)
    if lib is None or name is None or src.ndim != 2:
        return None
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, dtype=np.uint32)
    out = np.empty((len(idx), src.shape[1]), dtype=src.dtype)
    getattr(lib, name)(src, idx, len(idx), src.shape[1], out)
    return out


def bitmask_decode_pair(wide, inner, bids, n_real: int, block: int):
    """(rows i64, certain bool) from wide/inner bit planes — the scan
    decode hot path (see geomesa_native.cpp), or None when native is
    unavailable. ~25x the numpy unpackbits route on large pulls."""
    lib = _load()
    if lib is None or n_real == 0:
        return None
    wide = np.ascontiguousarray(wide[:n_real], dtype=np.int32)
    inner = np.ascontiguousarray(inner[:n_real], dtype=np.int32)
    bids = np.ascontiguousarray(bids[:n_real], dtype=np.int64)
    pack = wide.shape[1]
    count = lib.bitmask_count(wide, n_real, pack)
    rows = np.empty(count, dtype=np.int64)
    cert = np.empty(count, dtype=np.uint8)
    k = lib.bitmask_decode_pair(wide, inner, bids, n_real, pack, block, rows, cert)
    assert k == count
    return rows, cert.astype(bool)


def xz_index(lo, hi, dims: int, g: int, subtree) -> "np.ndarray | None":
    """Element boxes ([n, dims] normalized lo/hi) -> XZ sequence codes, or
    None. ``subtree`` is XZSFC.subtree_size (len g+2) so native and Python
    agree on the preorder arithmetic. The extent-table ingest hot loop."""
    lib = _load()
    if lib is None or dims > 4:  # C++ cell buffers are fixed at 4 dims
        return None
    lo = np.ascontiguousarray(lo, dtype=np.float64)
    hi = np.ascontiguousarray(hi, dtype=np.float64)
    sub = np.ascontiguousarray(subtree, dtype=np.int64)
    n = lo.shape[0]
    out = np.empty(n, dtype=np.int64)
    lib.xz_index(lo.reshape(-1), hi.reshape(-1), n, int(dims), int(g), sub, out)
    return out


def xz_ranges(dims: int, g: int, subtree, qlo, qhi, max_ranges: int):
    """Covering XZ sequence-code ranges of normalized query boxes (C++
    BFS + merge, ~100x the python pass at g=12). Returns (lo u64[k],
    hi u64[k], contained bool[k]) or None when native is unavailable."""
    lib = _load()
    if lib is None or dims > 4:
        return None
    qlo = np.ascontiguousarray(qlo, dtype=np.float64)
    qhi = np.ascontiguousarray(qhi, dtype=np.float64)
    sub = np.ascontiguousarray(subtree, dtype=np.int64)
    nq = qlo.shape[0] if qlo.ndim == 2 else len(qlo) // dims
    cap = max(int(max_ranges) * 2 + 64, 256)
    lo = np.empty(cap, dtype=np.uint64)
    hi = np.empty(cap, dtype=np.uint64)
    cont = np.empty(cap, dtype=np.uint8)
    n = lib.xz_ranges(
        dims, g, sub, qlo.reshape(-1), qhi.reshape(-1), nq,
        int(max_ranges), lo, hi, cont, cap,
    )
    if n < 0:
        return None
    return lo[:n].copy(), hi[:n].copy(), cont[:n].astype(bool)


def bitmask_decode(wide, bids, n_real: int, block: int):
    """Ascending rows from a wide bit plane (no certainty — extent scans
    skip the inner plane), or None when native is unavailable."""
    lib = _load()
    if lib is None or n_real == 0:
        return None
    wide = np.ascontiguousarray(wide[:n_real], dtype=np.int32)
    bids = np.ascontiguousarray(bids[:n_real], dtype=np.int64)
    pack = wide.shape[1]
    count = lib.bitmask_count(wide, n_real, pack)
    rows = np.empty(count, dtype=np.int64)
    k = lib.bitmask_decode(wide, bids, n_real, pack, block, rows)
    assert k == count
    return rows


def merge_rows_spans(spans, rows, cert):
    """(rows, certain) union of contained spans (certain) and ascending
    kernel rows, deduplicated — one C++ two-pointer pass, or None."""
    lib = _load()
    if lib is None:
        return None
    lo = np.ascontiguousarray([s[0] for s in spans], dtype=np.int64)
    hi = np.ascontiguousarray([s[1] for s in spans], dtype=np.int64)
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    cert8 = np.ascontiguousarray(cert, dtype=np.uint8)
    cap = int((hi - lo).sum()) + len(rows)
    out_rows = np.empty(cap, dtype=np.int64)
    out_cert = np.empty(cap, dtype=np.uint8)
    k = lib.merge_rows_spans(lo, hi, len(lo), rows, cert8, len(rows), out_rows, out_cert)
    return out_rows[:k], out_cert[:k].astype(bool)


def counting_argsort(keys, n_buckets: int) -> "np.ndarray | None":
    """Stable O(n) argsort of int keys in [0, n_buckets) — the spatial
    join's cell-id sort (np.argsort stable is n log n). Returns uint32
    perm, or None when native is unavailable, n >= 2^32, or any key is
    out of range (the C++ indexes its offsets vector by key unchecked)."""
    lib = _load()
    if lib is None or len(keys) >= (1 << 32) or n_buckets > (1 << 31) - 2:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    if len(keys) and (keys.min() < 0 or keys.max() >= n_buckets):
        return None
    keys = keys.astype(np.int32)
    perm = np.empty(len(keys), dtype=np.uint32)
    lib.counting_argsort(keys, len(keys), int(n_buckets), perm)
    return perm


def zranges(dims, bits_per_dim, mins, maxes, inner_mins, inner_maxes,
            max_ranges, max_recurse):
    """Covering z-ranges of a union of ordinal boxes (C++ BFS + zdiv
    tightening; see geomesa_native.cpp zranges_cpp). Containment is
    classified against the inner boxes. Returns (lo u64[k], hi u64[k],
    contained bool[k]) or None when native is unavailable."""
    lib = _load()
    if lib is None:
        return None
    mins = np.ascontiguousarray(mins, dtype=np.uint64)
    maxes = np.ascontiguousarray(maxes, dtype=np.uint64)
    inner_mins = np.ascontiguousarray(inner_mins, dtype=np.uint64)
    inner_maxes = np.ascontiguousarray(inner_maxes, dtype=np.uint64)
    nbox = len(mins) // dims if mins.ndim == 1 else len(mins)
    cap = max(int(max_ranges) * 2 + 64, 256)
    lo = np.empty(cap, dtype=np.uint64)
    hi = np.empty(cap, dtype=np.uint64)
    cont = np.empty(cap, dtype=np.uint8)
    n = lib.zranges_cpp(
        dims, bits_per_dim, nbox,
        mins.reshape(-1), maxes.reshape(-1),
        inner_mins.reshape(-1), inner_maxes.reshape(-1),
        int(max_ranges), int(max_recurse), lo, hi, cont, cap,
    )
    if n < 0:
        return None
    return lo[:n].copy(), hi[:n].copy(), cont[:n].astype(bool)


def points_in_polygon(px, py, rings, ring_part) -> "np.ndarray | None":
    """Even-odd point-in-polygon over flattened rings, or None when the
    native library is unavailable. ``rings`` is a list of closed [k, 2]
    f64 rings; ``ring_part[r]`` groups rings into multipolygon parts
    (within a part parity XORs; parts OR). Crossing semantics match
    geometry.points_in_ring exactly."""
    lib = _load()
    if lib is None:
        return None
    px = np.ascontiguousarray(px, dtype=np.float64)
    py = np.ascontiguousarray(py, dtype=np.float64)
    verts = np.ascontiguousarray(
        np.concatenate(rings, axis=0), dtype=np.float64
    )
    offsets = np.concatenate(
        [[0], np.cumsum([len(r) for r in rings])]
    ).astype(np.int64)
    part = np.ascontiguousarray(ring_part, dtype=np.int32)
    out = np.empty(len(px), dtype=np.uint8)
    lib.points_in_polygon_cpp(
        px, py, len(px), verts, offsets, len(rings), part, out
    )
    return out.astype(bool)
