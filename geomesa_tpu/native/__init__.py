"""Native (C++) runtime tier: build-on-demand, ctypes-bound, with exact
numpy fallback.

The compute path is JAX/XLA (device); this is the *host runtime* native
tier — the analogue of the reference's server-side JVM plugin code for the
ingest hot loop (see geomesa_native.cpp). The library builds lazily with
g++ the first time it's needed and caches next to the source; every entry
point has a pure-numpy fallback, so the package works identically without
a toolchain (set GEOMESA_TPU_NO_NATIVE=1 to force the fallback).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

_DIR = Path(__file__).resolve().parent
_SRC = _DIR / "geomesa_native.cpp"
_LIB = _DIR / "build" / "libgeomesa_native.so"

_lock = threading.Lock()
_lib: "ctypes.CDLL | None | bool" = None  # None = untried, False = unavailable


def _build() -> bool:
    _LIB.parent.mkdir(exist_ok=True)
    base = ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", str(_LIB)]
    for extra in (["-fopenmp"], []):  # prefer threaded; fall back
        try:
            r = subprocess.run(
                base[:2] + extra + base[2:],
                capture_output=True,
                timeout=120,
            )
            if r.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            return False
    return False


def _load():
    global _lib
    if _lib is not None:
        return _lib if _lib is not False else None
    with _lock:
        if _lib is not None:
            return _lib if _lib is not False else None
        if os.environ.get("GEOMESA_TPU_NO_NATIVE"):
            _lib = False
            return None
        try:
            if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
                if not _build():
                    _lib = False
                    return None
            lib = ctypes.CDLL(str(_LIB))
        except OSError:
            _lib = False
            return None
        u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
        f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.morton2.argtypes = [u64p, u64p, ctypes.c_int64, u64p]
        lib.morton2_decode.argtypes = [u64p, ctypes.c_int64, u64p, u64p]
        lib.morton3.argtypes = [u64p, u64p, u64p, ctypes.c_int64, u64p]
        lib.morton3_decode.argtypes = [u64p, ctypes.c_int64, u64p, u64p, u64p]
        lib.z3_write_keys.argtypes = [
            f64p, f64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_double, ctypes.c_int32, u64p, i32p, f32p, f32p, i32p,
        ]
        lib.z3_write_keys.restype = ctypes.c_int32
        lib.z2_write_keys.argtypes = [f64p, f64p, ctypes.c_int64, u64p, f32p, f32p]
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


def morton2(x, y) -> "np.ndarray | None":
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.uint64)
    y = np.ascontiguousarray(y, dtype=np.uint64)
    out = np.empty(len(x), dtype=np.uint64)
    lib.morton2(x, y, len(x), out)
    return out


def morton3(x, y, t) -> "np.ndarray | None":
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.uint64)
    y = np.ascontiguousarray(y, dtype=np.uint64)
    t = np.ascontiguousarray(t, dtype=np.uint64)
    out = np.empty(len(x), dtype=np.uint64)
    lib.morton3(x, y, t, len(x), out)
    return out


def morton3_decode(z):
    lib = _load()
    if lib is None:
        return None
    z = np.ascontiguousarray(z, dtype=np.uint64)
    x = np.empty(len(z), dtype=np.uint64)
    y = np.empty(len(z), dtype=np.uint64)
    t = np.empty(len(z), dtype=np.uint64)
    lib.morton3_decode(z, len(z), x, y, t)
    return x, y, t


# fixed-width periods the native binning supports: millis/bin, offset divisor
_FIXED_PERIODS = {"day": (86_400_000, 1), "week": (604_800_000, 1000)}


def z3_write_keys(x, y, millis, period: str, max_offset: int, max_bin: int):
    """Fused (bins, zs, device cols) for fixed-width periods, or None when
    native is unavailable / the period is calendar-based."""
    lib = _load()
    cfg = _FIXED_PERIODS.get(period)
    if lib is None or cfg is None:
        return None
    bin_ms, off_div = cfg
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    millis = np.ascontiguousarray(millis, dtype=np.int64)
    n = len(x)
    z = np.empty(n, dtype=np.uint64)
    bins = np.empty(n, dtype=np.int32)
    xf = np.empty(n, dtype=np.float32)
    yf = np.empty(n, dtype=np.float32)
    toff = np.empty(n, dtype=np.int32)
    status = lib.z3_write_keys(
        x, y, millis, n, bin_ms, off_div, float(max_offset), max_bin,
        z, bins, xf, yf, toff,
    )
    if status == 1:
        raise ValueError(f"pre-epoch timestamp(s) not supported by period {period}")
    if status == 2:
        raise ValueError(
            f"timestamp(s) past the max representable date for period {period}"
        )
    return bins, z, {"x": xf, "y": yf, "tbin": bins, "toff": toff}


def z2_write_keys(x, y):
    """Fused (zs, device cols) for the z2 index, or None."""
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    n = len(x)
    z = np.empty(n, dtype=np.uint64)
    xf = np.empty(n, dtype=np.float32)
    yf = np.empty(n, dtype=np.float32)
    lib.z2_write_keys(x, y, n, z, xf, yf)
    return z, {"x": xf, "y": yf}
