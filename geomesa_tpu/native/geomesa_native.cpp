// Native ingest hot path: fused write-key encoding.
//
// The reference's ingest hot loop is per-feature JVM code — normalize +
// Z3.split interleave + row byte assembly (reference
// geomesa-index-api/.../index/z3/Z3IndexKeySpace.scala:63-95 over
// geomesa-z3/.../zorder/sfcurve/Z3.scala:73-91). Here the equivalent tier
// is one fused multithreaded C++ pass per ingest batch: epoch-millis
// binning, lon/lat/time bit-normalization, Morton interleave, and the f32
// device-column conversion, writing all five output columns in a single
// traversal (the numpy path materializes ~10 temporaries).
//
// Semantics are bit-exact with geomesa_tpu.curve (zorder.py / normalize.py
// / binnedtime.py); tests/test_native.py asserts exact equality.
//
// Build: g++ -O3 -shared -fPIC [-fopenmp] geomesa_native.cpp -o libgeomesa_native.so

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <utility>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- morton

static inline uint64_t split2(uint64_t x) {
  x &= 0x7FFFFFFFull;
  x = (x ^ (x << 32)) & 0x00000000FFFFFFFFull;
  x = (x ^ (x << 16)) & 0x0000FFFF0000FFFFull;
  x = (x ^ (x << 8)) & 0x00FF00FF00FF00FFull;
  x = (x ^ (x << 4)) & 0x0F0F0F0F0F0F0F0Full;
  x = (x ^ (x << 2)) & 0x3333333333333333ull;
  x = (x ^ (x << 1)) & 0x5555555555555555ull;
  return x;
}

static inline uint64_t combine2(uint64_t z) {
  uint64_t x = z & 0x5555555555555555ull;
  x = (x ^ (x >> 1)) & 0x3333333333333333ull;
  x = (x ^ (x >> 2)) & 0x0F0F0F0F0F0F0F0Full;
  x = (x ^ (x >> 4)) & 0x00FF00FF00FF00FFull;
  x = (x ^ (x >> 8)) & 0x0000FFFF0000FFFFull;
  x = (x ^ (x >> 16)) & 0x00000000FFFFFFFFull;
  return x;
}

static inline uint64_t split3(uint64_t x) {
  x &= 0x1FFFFFull;
  x = (x | (x << 32)) & 0x1F00000000FFFFull;
  x = (x | (x << 16)) & 0x1F0000FF0000FFull;
  x = (x | (x << 8)) & 0x100F00F00F00F00Full;
  x = (x | (x << 4)) & 0x10C30C30C30C30C3ull;
  x = (x | (x << 2)) & 0x1249249249249249ull;
  return x;
}

static inline uint64_t combine3(uint64_t z) {
  uint64_t x = z & 0x1249249249249249ull;
  x = (x ^ (x >> 2)) & 0x10C30C30C30C30C3ull;
  x = (x ^ (x >> 4)) & 0x100F00F00F00F00Full;
  x = (x ^ (x >> 8)) & 0x1F0000FF0000FFull;
  x = (x ^ (x >> 16)) & 0x1F00000000FFFFull;
  x = (x ^ (x >> 32)) & 0x1FFFFFull;
  return x;
}

void morton2(const uint64_t* x, const uint64_t* y, int64_t n, uint64_t* out) {
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    out[i] = split2(x[i]) | (split2(y[i]) << 1);
  }
}

void morton2_decode(const uint64_t* z, int64_t n, uint64_t* x, uint64_t* y) {
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    x[i] = combine2(z[i]);
    y[i] = combine2(z[i] >> 1);
  }
}

void morton3(const uint64_t* x, const uint64_t* y, const uint64_t* t, int64_t n,
             uint64_t* out) {
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    out[i] = split3(x[i]) | (split3(y[i]) << 1) | (split3(t[i]) << 2);
  }
}

void morton3_decode(const uint64_t* z, int64_t n, uint64_t* x, uint64_t* y,
                    uint64_t* t) {
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    x[i] = combine3(z[i]);
    y[i] = combine3(z[i] >> 1);
    t[i] = combine3(z[i] >> 2);
  }
}

// ----------------------------------------------------------- normalization
// Bit-exact with NormalizedDimension.normalize: floor((d - min) * bins /
// (max - min)) clamped to [0, 2^p - 1]; the normalizer is computed once in
// double, matching numpy's scalar broadcast.

static inline int64_t normalize(double d, double mn, double normalizer,
                                int64_t max_index) {
  int64_t i = (int64_t)std::floor((d - mn) * normalizer);
  if (i < 0) i = 0;
  if (i > max_index) i = max_index;
  return i;
}

// ------------------------------------------------------------- write keys

// Fixed-width periods only (day: bin_ms=86400000, off_div=1; week:
// bin_ms=604800000, off_div=1000). Calendar periods (month/year) stay on
// the numpy path. Returns 0 ok, 1 pre-epoch input, 2 bin overflow.
int32_t z3_write_keys(const double* x, const double* y, const int64_t* millis,
                      int64_t n, int64_t bin_ms, int64_t off_div,
                      double max_off, int32_t max_bin, uint64_t* out_z,
                      int32_t* out_bin, float* out_xf, float* out_yf,
                      int32_t* out_toff) {
  const double lon_norm = 2097152.0 / 360.0;  // 2^21 / (180 - -180)
  const double lat_norm = 2097152.0 / 180.0;
  const double t_norm = 2097152.0 / max_off;  // NormalizedTime(21, max_off)
  const int64_t max_index = 2097151;          // 2^21 - 1
  int32_t status = 0;
#pragma omp parallel for reduction(max : status)
  for (int64_t i = 0; i < n; ++i) {
    int64_t ms = millis[i];
    if (ms < 0) {
      status = status > 1 ? status : 1;
      continue;
    }
    int64_t bin = ms / bin_ms;
    int64_t off = (ms - bin * bin_ms) / off_div;
    if (bin > (int64_t)max_bin) {
      status = 2;
      continue;
    }
    uint64_t xi = (uint64_t)normalize(x[i], -180.0, lon_norm, max_index);
    uint64_t yi = (uint64_t)normalize(y[i], -90.0, lat_norm, max_index);
    uint64_t ti = (uint64_t)normalize((double)off, 0.0, t_norm, max_index);
    out_z[i] = split3(xi) | (split3(yi) << 1) | (split3(ti) << 2);
    out_bin[i] = (int32_t)bin;
    out_xf[i] = (float)x[i];
    out_yf[i] = (float)y[i];
    out_toff[i] = (int32_t)off;
  }
  return status;
}

void z2_write_keys(const double* x, const double* y, int64_t n, uint64_t* out_z,
                   float* out_xf, float* out_yf) {
  const double lon_norm = 2147483648.0 / 360.0;  // 2^31 / 360
  const double lat_norm = 2147483648.0 / 180.0;
  const int64_t max_index = 2147483647;  // 2^31 - 1
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    uint64_t xi = (uint64_t)normalize(x[i], -180.0, lon_norm, max_index);
    uint64_t yi = (uint64_t)normalize(y[i], -90.0, lat_norm, max_index);
    out_z[i] = split2(xi) | (split2(yi) << 1);
    out_xf[i] = (float)x[i];
    out_yf[i] = (float)y[i];
  }
  return;
}

}  // extern "C"

// ------------------------------------------------------------ radix sort
// Ingest-path argsort by (bin, z): LSD radix with u32 payload, replacing
// np.lexsort's comparison sort (the reference gets sorted order for free
// from its KV backends; here the sorted columnar table is built in one
// batch pass — SURVEY §7 hard part (c)). 8-bit digits; passes whose
// histogram collapses to a single bucket are skipped (high z bytes and
// small bin counts make most of the 10 nominal passes no-ops).


static int radix_pass_u64_w(const uint64_t* key, const uint32_t* idx, int64_t n,
                            int shift, int bits, uint64_t* key_out,
                            uint32_t* idx_out, int64_t* hist) {
  const uint64_t mask = ((uint64_t)1 << bits) - 1;
  const int64_t buckets = (int64_t)1 << bits;
  std::fill(hist, hist + buckets, 0);
  for (int64_t i = 0; i < n; ++i) hist[(key[i] >> shift) & mask]++;
  int64_t nonzero = 0;
  for (int64_t b = 0; b < buckets; ++b) nonzero += hist[b] != 0;
  if (nonzero <= 1) return 0;  // all keys share this digit: skip
  int64_t acc = 0;
  for (int64_t b = 0; b < buckets; ++b) {
    const int64_t c = hist[b];
    hist[b] = acc;
    acc += c;
  }
  for (int64_t i = 0; i < n; ++i) {
    int64_t& o = hist[(key[i] >> shift) & mask];
    key_out[o] = key[i];
    idx_out[o] = idx[i];
    ++o;
  }
  return 1;
}

// argsort by (bins asc, zs asc), stable; out_perm must hold n uint32.
// 16-bit digits (4 z passes + 1 bin pass vs 8+4 at 8 bits) for large n,
// 8-bit digits below 1M rows where the 512 KB histogram dominates.
extern "C" void sort_bins_z(const int32_t* bins, const uint64_t* zs, int64_t n,
                 uint32_t* out_perm) {
  const int bits = n >= (1 << 20) ? 16 : 8;
  std::vector<int64_t> hist((size_t)1 << bits);
  std::vector<uint64_t> ka(n), kb(n);
  std::vector<uint32_t> ia(n), ib(n);
  for (int64_t i = 0; i < n; ++i) { ka[i] = zs[i]; ia[i] = (uint32_t)i; }
  uint64_t* k0 = ka.data(); uint64_t* k1 = kb.data();
  uint32_t* i0 = ia.data(); uint32_t* i1 = ib.data();
  for (int shift = 0; shift < 64; shift += bits) {
    if (radix_pass_u64_w(k0, i0, n, shift, bits, k1, i1, hist.data())) {
      std::swap(k0, k1);
      std::swap(i0, i1);
    }
  }
  // bin passes: rebuild key as bin (u16 range) of the current order
  for (int64_t i = 0; i < n; ++i) k0[i] = (uint64_t)(uint32_t)bins[i0[i]];
  for (int shift = 0; shift < 32; shift += bits) {
    if (radix_pass_u64_w(k0, i0, n, shift, bits, k1, i1, hist.data())) {
      std::swap(k0, k1);
      std::swap(i0, i1);
    }
  }
  std::memcpy(out_perm, i0, n * sizeof(uint32_t));
}

// permutation gathers for building sorted device/host columns
extern "C" void gather_f32(const float* src, const uint32_t* idx, int64_t n, float* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[idx[i]];
}
extern "C" void gather_i32(const int32_t* src, const uint32_t* idx, int64_t n, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[idx[i]];
}
extern "C" void gather_i64(const int64_t* src, const uint32_t* idx, int64_t n, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[idx[i]];
}
extern "C" void gather_u64(const uint64_t* src, const uint32_t* idx, int64_t n, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[idx[i]];
}
extern "C" void gather_f64(const double* src, const uint32_t* idx, int64_t n, double* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[idx[i]];
}

// row gathers for [n, width] arrays (packed-geometry coords/bboxes):
// out[i, :] = src[idx[i], :]. The random-row reads are memory-latency
// bound; threads hide the misses.
extern "C" void gather_rows_f64(const double* src, const uint32_t* idx,
                                int64_t n, int64_t width, double* out) {
#pragma omp parallel for schedule(static) if (n > 65536)
  for (int64_t i = 0; i < n; ++i) {
    const double* s = src + (int64_t)idx[i] * width;
    double* o = out + i * width;
    for (int64_t w = 0; w < width; ++w) o[w] = s[w];
  }
}
extern "C" void gather_rows_f32(const float* src, const uint32_t* idx,
                                int64_t n, int64_t width, float* out) {
#pragma omp parallel for schedule(static) if (n > 65536)
  for (int64_t i = 0; i < n; ++i) {
    const float* s = src + (int64_t)idx[i] * width;
    float* o = out + i * width;
    for (int64_t w = 0; w < width; ++w) o[w] = s[w];
  }
}

// ----------------------------------------------- point-in-polygon refine
// Host refinement hot loop for polygon queries over point stores: the
// numpy even-odd ray cast materializes an [n_points, n_edges] matrix
// (800 MB at 1M x 100); this streams edges per point in registers with
// the SAME crossing construction (spans half-open in y, intersection x
// strictly right of the point), threaded over points.
// rings are verts[ring_offsets[r] : ring_offsets[r+1]] (closed);
// ring_part[r] groups rings into polygon parts: within a part parity
// XORs (holes subtract), across parts the results OR (multi-polygon).
extern "C" void points_in_polygon_cpp(
    const double* px, const double* py, int64_t n,
    const double* verts /* [total_verts, 2] */,
    const int64_t* ring_offsets, int64_t n_rings,
    const int32_t* ring_part, uint8_t* out) {
#pragma omp parallel for schedule(static) if (n > 16384)
  for (int64_t i = 0; i < n; ++i) {
    const double x = px[i], y = py[i];
    bool any = false;
    bool parity = false;
    int32_t cur_part = n_rings ? ring_part[0] : 0;
    for (int64_t r = 0; r < n_rings; ++r) {
      if (ring_part[r] != cur_part) {
        any |= parity;
        parity = false;
        cur_part = ring_part[r];
      }
      const int64_t a = ring_offsets[r], b = ring_offsets[r + 1];
      int64_t crossings = 0;
      for (int64_t e = a; e + 1 < b; ++e) {
        const double y1 = verts[2 * e + 1], y2 = verts[2 * e + 3];
        if ((y1 <= y) != (y2 <= y)) {
          const double x1 = verts[2 * e], x2 = verts[2 * e + 2];
          const double t = (y - y1) / (y2 - y1);
          if (x1 + t * (x2 - x1) > x) ++crossings;
        }
      }
      if (crossings & 1) parity = !parity;
    }
    out[i] = (any | parity) ? 1 : 0;
  }
}

// -------------------------------------------------------- z-range BFS
// Query planning hot path: covering z-ranges for a union of ordinal boxes
// (reference ZN.zranges quad/oct BFS + Tropf/Herzog zdiv tightening,
// geomesa-z3/.../sfcurve/ZN.scala:110-242, :309-361). The Python
// implementation (curve/zranges.py) costs 100-300 ms per query; this is
// the same algorithm in C++ at <1 ms. Containment is classified against a
// separate *inner* ordinal box so that contained-range rows are certain
// hits at f64 precision (ScanConfig.contained -> no refinement).

struct ZCurveOps {
  int dims;
  int bits_per_dim;
  uint64_t (*split)(uint64_t);
  uint64_t (*combine)(uint64_t);
};

static uint64_t z2_index_(const uint64_t* p) { return split2(p[0]) | (split2(p[1]) << 1); }
static uint64_t z3_index_(const uint64_t* p) {
  return split3(p[0]) | (split3(p[1]) << 1) | (split3(p[2]) << 2);
}

static void z_decode(const ZCurveOps& ops, uint64_t z, uint64_t* out) {
  for (int d = 0; d < ops.dims; ++d) out[d] = ops.combine(z >> d);
}

static uint64_t z_index(const ZCurveOps& ops, const uint64_t* p) {
  return ops.dims == 2 ? z2_index_(p) : z3_index_(p);
}

// 2 = cell fully inside some inner box, 1 = overlaps some outer box, 0 = no
static int classify(const uint64_t* lo, const uint64_t* hi, int dims, int64_t nbox,
                    const uint64_t* mins, const uint64_t* maxes,
                    const uint64_t* imins, const uint64_t* imaxes) {
  for (int64_t b = 0; b < nbox; ++b) {
    bool contained = true;
    for (int d = 0; d < dims; ++d)
      if (lo[d] < imins[b * dims + d] || hi[d] > imaxes[b * dims + d]) {
        contained = false;
        break;
      }
    if (contained) return 2;
  }
  for (int64_t b = 0; b < nbox; ++b) {
    bool overlap = true;
    for (int d = 0; d < dims; ++d)
      if (lo[d] > maxes[b * dims + d] || hi[d] < mins[b * dims + d]) {
        overlap = false;
        break;
      }
    if (overlap) return 1;
  }
  return 0;
}

struct ZRange { uint64_t lo, hi; uint8_t contained; };

// Tropf/Herzog LITMAX/BIGMIN: mirrors curve/zorder.py zdiv.
static void zdiv_cpp(const ZCurveOps& ops, uint64_t zmin, uint64_t zmax,
                     uint64_t zval, uint64_t* litmax_out, uint64_t* bigmin_out) {
  int dims = ops.dims;
  int total = dims * ops.bits_per_dim;
  uint64_t litmax = zmin, bigmin = zmax;
  uint64_t zmin_ = zmin, zmax_ = zmax;
  for (int i = total - 1; i >= 0; --i) {
    uint64_t bit = 1ull << i;
    int dim = i % dims;
    int bl = i / dims + 1;  // 1-based dim-local bit index
    int v = (zval & bit) ? 1 : 0;
    int mn = (zmin_ & bit) ? 1 : 0;
    int mx = (zmax_ & bit) ? 1 : 0;
    uint64_t mask = ops.split((1ull << bl) - 1) << dim;
    if (v == 0 && mn == 0 && mx == 1) {
      uint64_t pat_hi = ops.split(1ull << (bl - 1)) << dim;
      uint64_t pat_lo = ops.split(((1ull << (bl - 1)) - 1)) << dim;
      bigmin = (zmin_ & ~mask) | pat_hi;
      zmax_ = (zmax_ & ~mask) | pat_lo;
    } else if (v == 0 && mn == 1 && mx == 1) {
      bigmin = zmin_;
      break;
    } else if (v == 1 && mn == 0 && mx == 0) {
      litmax = zmax_;
      break;
    } else if (v == 1 && mn == 0 && mx == 1) {
      uint64_t pat_hi = ops.split(1ull << (bl - 1)) << dim;
      uint64_t pat_lo = ops.split(((1ull << (bl - 1)) - 1)) << dim;
      litmax = (zmax_ & ~mask) | pat_lo;
      zmin_ = (zmin_ & ~mask) | pat_hi;
    }
  }
  *litmax_out = litmax;
  *bigmin_out = bigmin;
}

static bool in_some_box(const ZCurveOps& ops, uint64_t z, int64_t nbox,
                        const uint64_t* mins, const uint64_t* maxes) {
  uint64_t pt[3];
  z_decode(ops, z, pt);
  for (int64_t b = 0; b < nbox; ++b) {
    bool in = true;
    for (int d = 0; d < ops.dims; ++d)
      if (pt[d] < mins[b * ops.dims + d] || pt[d] > maxes[b * ops.dims + d]) {
        in = false;
        break;
      }
    if (in) return true;
  }
  return false;
}

// Covering ranges for a union of ordinal boxes. Returns the number of
// ranges written (<= cap), or -1 if cap was too small.
extern "C" int64_t zranges_cpp(int32_t dims, int32_t bits_per_dim, int64_t nbox,
                    const uint64_t* mins, const uint64_t* maxes,
                    const uint64_t* imins, const uint64_t* imaxes,
                    int64_t max_ranges, int64_t max_recurse,
                    uint64_t* out_lo, uint64_t* out_hi, uint8_t* out_cont,
                    int64_t cap) {
  ZCurveOps ops = dims == 2 ? ZCurveOps{2, bits_per_dim, split2, combine2}
                            : ZCurveOps{3, bits_per_dim, split3, combine3};
  int total = dims * bits_per_dim;
  int children = 1 << dims;

  // corner z's + longest common prefix aligned to dims bits
  std::vector<uint64_t> zmins(nbox), zmaxes(nbox);
  for (int64_t b = 0; b < nbox; ++b) {
    zmins[b] = z_index(ops, mins + b * dims);
    zmaxes[b] = z_index(ops, maxes + b * dims);
  }
  int offset = total;
  while (offset > 0) {
    int nxt = offset - dims;
    uint64_t bits = zmins[0] >> nxt;
    bool same = true;
    for (int64_t b = 0; b < nbox && same; ++b)
      same = (zmins[b] >> nxt) == bits && (zmaxes[b] >> nxt) == bits;
    if (same) offset = nxt; else break;
  }
  uint64_t prefix = (zmins[0] >> offset) << offset;

  std::vector<ZRange> ranges;
  std::vector<std::pair<uint64_t, int>> level{{prefix, offset}}, nxt_level;
  uint64_t lo_pt[3], hi_pt[3];
  int recursions = 0;
  while (!level.empty() && recursions < max_recurse &&
         (int64_t)(ranges.size() + level.size() * children) < max_ranges * 2) {
    nxt_level.clear();
    for (auto& cell : level) {
      uint64_t zp = cell.first;
      int free_bits = cell.second;
      if (free_bits == 0) {
        z_decode(ops, zp, lo_pt);
        int c = classify(lo_pt, lo_pt, dims, nbox, mins, maxes, imins, imaxes);
        if (c) ranges.push_back({zp, zp, (uint8_t)(c == 2)});
        continue;
      }
      int child_bits = free_bits - dims;
      for (int q = 0; q < children; ++q) {
        uint64_t cp = zp | ((uint64_t)q << child_bits);
        uint64_t cmax = cp | ((child_bits ? (1ull << child_bits) : 0) - (child_bits ? 1ull : 0));
        z_decode(ops, cp, lo_pt);
        z_decode(ops, cmax, hi_pt);
        int c = classify(lo_pt, hi_pt, dims, nbox, mins, maxes, imins, imaxes);
        if (c == 2) {
          ranges.push_back({cp, cmax, 1});
        } else if (c == 1) {
          if (child_bits == 0) ranges.push_back({cp, cp, 0});
          else nxt_level.push_back({cp, child_bits});
        }
      }
    }
    level.swap(nxt_level);
    ++recursions;
  }
  for (auto& cell : level)
    ranges.push_back({cell.first, cell.first | ((1ull << cell.second) - 1), 0});

  // sort + merge adjacent/overlapping
  std::sort(ranges.begin(), ranges.end(), [](const ZRange& a, const ZRange& b) {
    return a.lo != b.lo ? a.lo < b.lo : a.hi < b.hi;
  });
  // merge only same-kind neighbors (BFS cells are disjoint, so ranges can
  // only be adjacent): a contained range glued to an overlapping one keeps
  // its no-refinement guarantee instead of degrading the pair
  std::vector<ZRange> merged;
  for (auto& r : ranges) {
    if (!merged.empty() && merged.back().hi != ~0ull &&
        r.lo <= merged.back().hi + 1 && r.contained == merged.back().contained) {
      if (r.hi > merged.back().hi) merged.back().hi = r.hi;
    } else {
      merged.push_back(r);
    }
  }
  // reduce below max_ranges by closing the smallest gaps first
  while ((int64_t)merged.size() > max_ranges) {
    // single pass: close all gaps below a threshold found by nth_element
    int64_t k = merged.size() - max_ranges;
    std::vector<uint64_t> gaps(merged.size() - 1);
    for (size_t i = 0; i + 1 < merged.size(); ++i)
      gaps[i] = merged[i + 1].lo - merged[i].hi;
    std::vector<uint64_t> g2(gaps);
    std::nth_element(g2.begin(), g2.begin() + (k - 1), g2.end());
    uint64_t cutoff = g2[k - 1];
    std::vector<ZRange> out;
    out.push_back(merged[0]);
    int64_t closed = 0;
    for (size_t i = 1; i < merged.size(); ++i) {
      if (closed < k && gaps[i - 1] <= cutoff) {
        out.back().hi = merged[i].hi > out.back().hi ? merged[i].hi : out.back().hi;
        out.back().contained = 0;
        ++closed;
      } else {
        out.push_back(merged[i]);
      }
    }
    merged.swap(out);
  }

  // tighten endpoints to in-union z-values (zdiv post-pass; mirrors
  // curve/zranges.py _tighten_ranges against the *outer* boxes)
  std::vector<ZRange> out;
  for (auto& r : merged) {
    bool has_lo = false, has_hi = false;
    uint64_t lo = 0, hi = 0;
    for (int64_t b = 0; b < nbox; ++b) {
      uint64_t zmin = zmins[b], zmax = zmaxes[b];
      if (zmax < r.lo || zmin > r.hi) continue;
      uint64_t cand;
      if (r.lo <= zmin) cand = zmin;
      else if (in_some_box(ops, r.lo, 1, mins + b * dims, maxes + b * dims)) cand = r.lo;
      else { uint64_t lm, bm; zdiv_cpp(ops, zmin, zmax, r.lo, &lm, &bm); cand = bm; }
      if (cand <= r.hi && (!has_lo || cand < lo)) { lo = cand; has_lo = true; }
      if (r.hi >= zmax) cand = zmax;
      else if (in_some_box(ops, r.hi, 1, mins + b * dims, maxes + b * dims)) cand = r.hi;
      else { uint64_t lm, bm; zdiv_cpp(ops, zmin, zmax, r.hi, &lm, &bm); cand = lm; }
      if (cand >= r.lo && (!has_hi || cand > hi)) { hi = cand; has_hi = true; }
    }
    if (!has_lo || !has_hi || lo > hi) continue;
    out.push_back({lo, hi, r.contained});
  }

  if ((int64_t)out.size() > cap) return -1;
  for (size_t i = 0; i < out.size(); ++i) {
    out_lo[i] = out[i].lo;
    out_hi[i] = out[i].hi;
    out_cont[i] = out[i].contained;
  }
  return (int64_t)out.size();
}

// ---------------------------------------------------------------------------
// bitmask decode: the scan pull's host decode hot path
// (geomesa_tpu/scan/block_kernels.py decode_bits_pair; bit b of word
// [blk, j, lane] = local row (j*32 + b)*128 + lane). The numpy route
// (unpackbits + transpose + nonzero + fancy index) costs ~25x this.
// ---------------------------------------------------------------------------

extern "C" int64_t bitmask_count(const int32_t* wide, int64_t n_real,
                                 int64_t pack) {
  const uint32_t* w = (const uint32_t*)wide;
  int64_t words = n_real * pack * 128;
  int64_t total = 0;
  for (int64_t i = 0; i < words; ++i) total += __builtin_popcount(w[i]);
  return total;
}

extern "C" int64_t bitmask_decode_pair(const int32_t* wide,
                                       const int32_t* inner,
                                       const int64_t* bids, int64_t n_real,
                                       int64_t pack, int64_t block,
                                       int64_t* rows_out, uint8_t* cert_out) {
  const uint32_t* w = (const uint32_t*)wide;
  const uint32_t* in = (const uint32_t*)inner;
  int64_t k = 0;
  for (int64_t blk = 0; blk < n_real; ++blk) {
    int64_t base = bids[blk] * block;
    for (int64_t j = 0; j < pack; ++j) {
      const uint32_t* wrow = w + (blk * pack + j) * 128;
      const uint32_t* irow = in + (blk * pack + j) * 128;
      uint32_t any = 0;
      for (int lane = 0; lane < 128; ++lane) any |= wrow[lane];
      if (!any) continue;  // sparse planes: skip empty sub-blocks cheaply
      for (int b = 0; b < 32; ++b) {
        if (!(any & (1u << b))) continue;
        const uint32_t bit = 1u << b;
        const int64_t rbase = base + (j * 32 + b) * 128;
        for (int lane = 0; lane < 128; ++lane) {
          if (wrow[lane] & bit) {
            rows_out[k] = rbase + lane;
            cert_out[k] = (irow[lane] & bit) ? 1 : 0;
            ++k;
          }
        }
      }
    }
  }
  return k;
}

// ---------------------------------------------------------------------------
// contained-span merge: emit the union of contained-span rows (all certain)
// and kernel rows (with their certainty), ascending, deduplicating kernel
// rows that fall inside a span — one two-pointer pass replacing the
// span_rows + rows_in_spans + positional-merge numpy pipeline.
// ---------------------------------------------------------------------------

extern "C" int64_t merge_rows_spans(const int64_t* span_lo,
                                    const int64_t* span_hi, int64_t n_spans,
                                    const int64_t* rows, const uint8_t* cert,
                                    int64_t n_rows, int64_t* out_rows,
                                    uint8_t* out_cert) {
  int64_t k = 0, r = 0;
  for (int64_t s = 0; s < n_spans; ++s) {
    const int64_t lo = span_lo[s], hi = span_hi[s];  // [lo, hi)
    // kernel rows strictly before this span
    while (r < n_rows && rows[r] < lo) {
      out_rows[k] = rows[r];
      out_cert[k] = cert[r];
      ++k; ++r;
    }
    // the span itself (all rows certain)
    for (int64_t v = lo; v < hi; ++v) {
      out_rows[k] = v;
      out_cert[k] = 1;
      ++k;
    }
    // skip kernel duplicates inside the span
    while (r < n_rows && rows[r] < hi) ++r;
  }
  while (r < n_rows) {
    out_rows[k] = rows[r];
    out_cert[k] = cert[r];
    ++k; ++r;
  }
  return k;
}

// ---------------------------------------------------------------------------
// counting argsort: stable O(n) argsort of small-integer keys (grid cell
// ids in the spatial join; np.argsort's n log n dominated join setup).
// ---------------------------------------------------------------------------

extern "C" void counting_argsort(const int32_t* keys, int64_t n,
                                 int64_t n_buckets, uint32_t* perm) {
  std::vector<int64_t> offsets(static_cast<size_t>(n_buckets) + 1, 0);
  for (int64_t i = 0; i < n; ++i) ++offsets[keys[i] + 1];
  for (int64_t b = 0; b < n_buckets; ++b) offsets[b + 1] += offsets[b];
  for (int64_t i = 0; i < n; ++i)
    perm[offsets[keys[i]]++] = static_cast<uint32_t>(i);
}

// wide-only decode (extent scans skip the inner plane entirely)
extern "C" int64_t bitmask_decode(const int32_t* wide, const int64_t* bids,
                                  int64_t n_real, int64_t pack, int64_t block,
                                  int64_t* rows_out) {
  const uint32_t* w = (const uint32_t*)wide;
  int64_t k = 0;
  for (int64_t blk = 0; blk < n_real; ++blk) {
    int64_t base = bids[blk] * block;
    for (int64_t j = 0; j < pack; ++j) {
      const uint32_t* wrow = w + (blk * pack + j) * 128;
      uint32_t any = 0;
      for (int lane = 0; lane < 128; ++lane) any |= wrow[lane];
      if (!any) continue;
      for (int b = 0; b < 32; ++b) {
        if (!(any & (1u << b))) continue;
        const uint32_t bit = 1u << b;
        const int64_t rbase = base + (j * 32 + b) * 128;
        for (int lane = 0; lane < 128; ++lane) {
          if (wrow[lane] & bit) rows_out[k++] = rbase + lane;
        }
      }
    }
  }
  return k;
}

// ---------------------------------------------------------------------------
// XZ index write path: element boxes -> XZ sequence codes (the extent-table
// analogue of z3_write_keys). Same construction as curve/xzsfc.py
// XZSFC.length_at + sequence_code (Boehm et al. XZ-ordering, re-derived;
// reference XZ2SFC.index:54-77): deepest level whose enlarged cell still
// contains the element, then the preorder code of the cell holding the
// element's low corner at that level. One scalar pass per element replaces
// ~2*g full-array numpy passes.
// ---------------------------------------------------------------------------

extern "C" void xz_index(const double* lo, const double* hi, int64_t n,
                         int32_t dims, int32_t g, const int64_t* subtree,
                         int64_t* out) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t e = 0; e < n; ++e) {
    const double* el = lo + e * dims;
    const double* eh = hi + e * dims;
    double extent = 0.0;
    for (int32_t d = 0; d < dims; ++d)
      extent = std::max(extent, eh[d] - el[d]);
    int64_t l1 = (int64_t)std::floor(std::log(std::max(extent, 1e-300)) /
                                     std::log(0.5));
    if (l1 > g) l1 = g;
    const int64_t lp = std::min<int64_t>(l1 + 1, g);
    const double w2 = std::ldexp(1.0, (int)-lp);  // 0.5^lp, exact
    bool fits = true;
    for (int32_t d = 0; d < dims; ++d) {
      const double anchor = std::floor(el[d] / w2) * w2;
      if (eh[d] > anchor + 2.0 * w2) { fits = false; break; }
    }
    int64_t length = fits ? lp : std::max<int64_t>(l1, 0);
    if (length > g) length = g;
    int64_t cs = 0;
    double clo[4] = {0, 0, 0, 0}, chi[4] = {1, 1, 1, 1};
    for (int64_t i = 0; i < length; ++i) {
      int64_t q = 0;
      for (int32_t d = 0; d < dims; ++d) {
        const double c = (clo[d] + chi[d]) * 0.5;
        if (el[d] >= c) { q |= (int64_t)1 << d; clo[d] = c; }
        else chi[d] = c;
      }
      cs += 1 + q * subtree[i + 1];
    }
    out[e] = cs;
  }
}

// ---------------------------------------------------------------------------
// XZ range decomposition: covering sequence-code ranges of query boxes.
// Same BFS + budget + merge semantics as curve/xzsfc.py XZSFC.ranges
// (re-derived XZ-ordering construction; reference XZ2SFC.ranges:146-252):
// a cell whose ENLARGED extent is contained in a query covers its whole
// subtree (contained=true, no row filter); an overlapping cell emits its
// own code and recurses. Per-level budget of 2*max_ranges, then a
// sort+merge that only glues same-kind neighbors and closes the smallest
// gaps to reach max_ranges. Python's per-cell numpy ops cost 3-116 ms per
// query at g=12; this pass is ~100x cheaper.
// ---------------------------------------------------------------------------

namespace {
struct XzCell {
  double lo[4];
  int32_t level;
  int64_t cs;
};
struct XzRange {
  uint64_t lo, hi;
  uint8_t contained;
};
}  // namespace

extern "C" int64_t xz_ranges(int32_t dims, int32_t g, const int64_t* subtree,
                             const double* qlo, const double* qhi, int64_t nq,
                             int64_t max_ranges, uint64_t* out_lo,
                             uint64_t* out_hi, uint8_t* out_cont,
                             int64_t cap) {
  if (dims > 4) return -1;
  const int32_t children = 1 << dims;
  std::vector<XzCell> level_cells, nxt;
  XzCell root{};
  for (int32_t d = 0; d < dims; ++d) root.lo[d] = 0.0;
  root.level = 0;
  root.cs = 0;
  level_cells.push_back(root);
  std::vector<XzRange> ranges;

  while (!level_cells.empty()) {
    nxt.clear();
    const int64_t budget_left = max_ranges * 2 - (int64_t)ranges.size();
    if (budget_left <= 0) break;
    for (const XzCell& c : level_cells) {
      const double w = std::ldexp(1.0, -c.level);
      bool contained = false, overlaps = false;
      for (int64_t q = 0; q < nq && !contained; ++q) {
        bool cont = true;
        for (int32_t d = 0; d < dims; ++d) {
          if (!(qlo[q * dims + d] <= c.lo[d] &&
                qhi[q * dims + d] >= c.lo[d] + 2.0 * w)) {
            cont = false;
            break;
          }
        }
        contained |= cont;
      }
      if (contained) {
        ranges.push_back({(uint64_t)c.cs,
                          (uint64_t)(c.cs + subtree[c.level] - 1), 1});
        continue;
      }
      for (int64_t q = 0; q < nq && !overlaps; ++q) {
        bool ov = true;
        for (int32_t d = 0; d < dims; ++d) {
          if (!(qlo[q * dims + d] <= c.lo[d] + 2.0 * w &&
                qhi[q * dims + d] >= c.lo[d])) {
            ov = false;
            break;
          }
        }
        overlaps |= ov;
      }
      if (!overlaps) continue;
      ranges.push_back({(uint64_t)c.cs, (uint64_t)c.cs, 0});
      if (c.level < g) {
        const int64_t sub = subtree[c.level + 1];
        const double half = w * 0.5;
        for (int32_t q = 0; q < children; ++q) {
          XzCell ch{};
          for (int32_t d = 0; d < dims; ++d)
            ch.lo[d] = c.lo[d] + (((q >> d) & 1) ? half : 0.0);
          ch.level = c.level + 1;
          ch.cs = c.cs + 1 + q * sub;
          nxt.push_back(ch);
        }
      }
    }
    level_cells.swap(nxt);
  }
  // budget exhausted: whole subtrees for unprocessed overlapping cells
  for (const XzCell& c : level_cells) {
    const double w = std::ldexp(1.0, -c.level);
    bool overlaps = false;
    for (int64_t q = 0; q < nq && !overlaps; ++q) {
      bool ov = true;
      for (int32_t d = 0; d < dims; ++d) {
        if (!(qlo[q * dims + d] <= c.lo[d] + 2.0 * w &&
              qhi[q * dims + d] >= c.lo[d])) {
          ov = false;
          break;
        }
      }
      overlaps |= ov;
    }
    if (overlaps)
      ranges.push_back({(uint64_t)c.cs,
                        (uint64_t)(c.cs + subtree[c.level] - 1), 0});
  }

  if (ranges.empty()) return 0;
  // sort + merge same-kind neighbors (curve/zranges.py merge_ranges)
  std::sort(ranges.begin(), ranges.end(), [](const XzRange& a, const XzRange& b) {
    return a.lo != b.lo ? a.lo < b.lo : a.hi < b.hi;
  });
  std::vector<XzRange> merged;
  merged.push_back(ranges[0]);
  for (size_t i = 1; i < ranges.size(); ++i) {
    XzRange& last = merged.back();
    const XzRange& r = ranges[i];
    if (r.lo <= last.hi + 1 && r.contained == last.contained) {
      last.hi = std::max(last.hi, r.hi);
    } else {
      merged.push_back(r);
    }
  }
  if (max_ranges > 0 && (int64_t)merged.size() > max_ranges) {
    const int64_t k = (int64_t)merged.size() - max_ranges;
    std::vector<int64_t> gap_idx(merged.size() - 1);
    for (size_t i = 0; i + 1 < merged.size(); ++i) gap_idx[i] = (int64_t)i;
    std::nth_element(
        gap_idx.begin(), gap_idx.begin() + (k - 1), gap_idx.end(),
        [&](int64_t a, int64_t b) {
          return merged[a + 1].lo - merged[a].hi < merged[b + 1].lo - merged[b].hi;
        });
    std::vector<uint8_t> close(merged.size() - 1, 0);
    for (int64_t i = 0; i < k; ++i) close[gap_idx[i]] = 1;
    std::vector<XzRange> out;
    out.push_back(merged[0]);
    for (size_t i = 1; i < merged.size(); ++i) {
      if (close[i - 1]) {
        out.back().hi = std::max(out.back().hi, merged[i].hi);
        out.back().contained = 0;
      } else {
        out.push_back(merged[i]);
      }
    }
    merged.swap(out);
  }
  if ((int64_t)merged.size() > cap) return -1;
  for (size_t i = 0; i < merged.size(); ++i) {
    out_lo[i] = merged[i].lo;
    out_hi[i] = merged[i].hi;
    out_cont[i] = merged[i].contained;
  }
  return (int64_t)merged.size();
}
