// Native ingest hot path: fused write-key encoding.
//
// The reference's ingest hot loop is per-feature JVM code — normalize +
// Z3.split interleave + row byte assembly (reference
// geomesa-index-api/.../index/z3/Z3IndexKeySpace.scala:63-95 over
// geomesa-z3/.../zorder/sfcurve/Z3.scala:73-91). Here the equivalent tier
// is one fused multithreaded C++ pass per ingest batch: epoch-millis
// binning, lon/lat/time bit-normalization, Morton interleave, and the f32
// device-column conversion, writing all five output columns in a single
// traversal (the numpy path materializes ~10 temporaries).
//
// Semantics are bit-exact with geomesa_tpu.curve (zorder.py / normalize.py
// / binnedtime.py); tests/test_native.py asserts exact equality.
//
// Build: g++ -O3 -shared -fPIC [-fopenmp] geomesa_native.cpp -o libgeomesa_native.so

#include <cmath>
#include <cstdint>

extern "C" {

// ---------------------------------------------------------------- morton

static inline uint64_t split2(uint64_t x) {
  x &= 0x7FFFFFFFull;
  x = (x ^ (x << 32)) & 0x00000000FFFFFFFFull;
  x = (x ^ (x << 16)) & 0x0000FFFF0000FFFFull;
  x = (x ^ (x << 8)) & 0x00FF00FF00FF00FFull;
  x = (x ^ (x << 4)) & 0x0F0F0F0F0F0F0F0Full;
  x = (x ^ (x << 2)) & 0x3333333333333333ull;
  x = (x ^ (x << 1)) & 0x5555555555555555ull;
  return x;
}

static inline uint64_t combine2(uint64_t z) {
  uint64_t x = z & 0x5555555555555555ull;
  x = (x ^ (x >> 1)) & 0x3333333333333333ull;
  x = (x ^ (x >> 2)) & 0x0F0F0F0F0F0F0F0Full;
  x = (x ^ (x >> 4)) & 0x00FF00FF00FF00FFull;
  x = (x ^ (x >> 8)) & 0x0000FFFF0000FFFFull;
  x = (x ^ (x >> 16)) & 0x00000000FFFFFFFFull;
  return x;
}

static inline uint64_t split3(uint64_t x) {
  x &= 0x1FFFFFull;
  x = (x | (x << 32)) & 0x1F00000000FFFFull;
  x = (x | (x << 16)) & 0x1F0000FF0000FFull;
  x = (x | (x << 8)) & 0x100F00F00F00F00Full;
  x = (x | (x << 4)) & 0x10C30C30C30C30C3ull;
  x = (x | (x << 2)) & 0x1249249249249249ull;
  return x;
}

static inline uint64_t combine3(uint64_t z) {
  uint64_t x = z & 0x1249249249249249ull;
  x = (x ^ (x >> 2)) & 0x10C30C30C30C30C3ull;
  x = (x ^ (x >> 4)) & 0x100F00F00F00F00Full;
  x = (x ^ (x >> 8)) & 0x1F0000FF0000FFull;
  x = (x ^ (x >> 16)) & 0x1F00000000FFFFull;
  x = (x ^ (x >> 32)) & 0x1FFFFFull;
  return x;
}

void morton2(const uint64_t* x, const uint64_t* y, int64_t n, uint64_t* out) {
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    out[i] = split2(x[i]) | (split2(y[i]) << 1);
  }
}

void morton2_decode(const uint64_t* z, int64_t n, uint64_t* x, uint64_t* y) {
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    x[i] = combine2(z[i]);
    y[i] = combine2(z[i] >> 1);
  }
}

void morton3(const uint64_t* x, const uint64_t* y, const uint64_t* t, int64_t n,
             uint64_t* out) {
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    out[i] = split3(x[i]) | (split3(y[i]) << 1) | (split3(t[i]) << 2);
  }
}

void morton3_decode(const uint64_t* z, int64_t n, uint64_t* x, uint64_t* y,
                    uint64_t* t) {
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    x[i] = combine3(z[i]);
    y[i] = combine3(z[i] >> 1);
    t[i] = combine3(z[i] >> 2);
  }
}

// ----------------------------------------------------------- normalization
// Bit-exact with NormalizedDimension.normalize: floor((d - min) * bins /
// (max - min)) clamped to [0, 2^p - 1]; the normalizer is computed once in
// double, matching numpy's scalar broadcast.

static inline int64_t normalize(double d, double mn, double normalizer,
                                int64_t max_index) {
  int64_t i = (int64_t)std::floor((d - mn) * normalizer);
  if (i < 0) i = 0;
  if (i > max_index) i = max_index;
  return i;
}

// ------------------------------------------------------------- write keys

// Fixed-width periods only (day: bin_ms=86400000, off_div=1; week:
// bin_ms=604800000, off_div=1000). Calendar periods (month/year) stay on
// the numpy path. Returns 0 ok, 1 pre-epoch input, 2 bin overflow.
int32_t z3_write_keys(const double* x, const double* y, const int64_t* millis,
                      int64_t n, int64_t bin_ms, int64_t off_div,
                      double max_off, int32_t max_bin, uint64_t* out_z,
                      int32_t* out_bin, float* out_xf, float* out_yf,
                      int32_t* out_toff) {
  const double lon_norm = 2097152.0 / 360.0;  // 2^21 / (180 - -180)
  const double lat_norm = 2097152.0 / 180.0;
  const double t_norm = 2097152.0 / max_off;  // NormalizedTime(21, max_off)
  const int64_t max_index = 2097151;          // 2^21 - 1
  int32_t status = 0;
#pragma omp parallel for reduction(max : status)
  for (int64_t i = 0; i < n; ++i) {
    int64_t ms = millis[i];
    if (ms < 0) {
      status = status > 1 ? status : 1;
      continue;
    }
    int64_t bin = ms / bin_ms;
    int64_t off = (ms - bin * bin_ms) / off_div;
    if (bin > (int64_t)max_bin) {
      status = 2;
      continue;
    }
    uint64_t xi = (uint64_t)normalize(x[i], -180.0, lon_norm, max_index);
    uint64_t yi = (uint64_t)normalize(y[i], -90.0, lat_norm, max_index);
    uint64_t ti = (uint64_t)normalize((double)off, 0.0, t_norm, max_index);
    out_z[i] = split3(xi) | (split3(yi) << 1) | (split3(ti) << 2);
    out_bin[i] = (int32_t)bin;
    out_xf[i] = (float)x[i];
    out_yf[i] = (float)y[i];
    out_toff[i] = (int32_t)off;
  }
  return status;
}

void z2_write_keys(const double* x, const double* y, int64_t n, uint64_t* out_z,
                   float* out_xf, float* out_yf) {
  const double lon_norm = 2147483648.0 / 360.0;  // 2^31 / 360
  const double lat_norm = 2147483648.0 / 180.0;
  const int64_t max_index = 2147483647;  // 2^31 - 1
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    uint64_t xi = (uint64_t)normalize(x[i], -180.0, lon_norm, max_index);
    uint64_t yi = (uint64_t)normalize(y[i], -90.0, lat_norm, max_index);
    out_z[i] = split2(xi) | (split2(yi) << 1);
    out_xf[i] = (float)x[i];
    out_yf[i] = (float)y[i];
  }
  return;
}

}  // extern "C"
