"""Federated read views over multiple stores.

Reference: MergedDataStoreView + RouteSelector (/root/reference/
geomesa-index-api/src/main/scala/org/locationtech/geomesa/index/view/
MergedDataStoreView.scala, RouteSelector.scala) — a read-only DataStore
facade that fans a query out to N underlying stores and concatenates
results, or routes each query to exactly one store by attribute.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import Filter, INCLUDE


class MergedView:
    """Read-only union over stores sharing a schema (MergedDataStoreView).
    Duplicate ids keep the first store's row (store order = precedence).

    With a ``limit``, each store is asked for at most ``limit`` rows (the
    reference pushes maxFeatures per store the same way); if a later
    store's first ``limit`` rows are mostly duplicates the merged result
    may come up short even though more matches exist — the same caveat the
    reference's merged view carries."""

    def __init__(self, stores: Sequence, type_name: str):
        if not stores:
            raise ValueError("need at least one store")
        self.stores = list(stores)
        self.type_name = type_name
        specs = {s.get_schema(type_name).to_spec() for s in stores}
        if len(specs) != 1:
            raise ValueError(f"stores disagree on schema: {specs}")

    def get_schema(self, type_name: str | None = None):
        return self.stores[0].get_schema(type_name or self.type_name)

    def query(self, f: "Filter | str" = INCLUDE, limit: Optional[int] = None) -> FeatureCollection:
        parts = []
        seen: set = set()
        kept = 0
        for s in self.stores:
            if limit is not None and kept >= limit:
                break
            # limit pushes down per store (dedup only removes rows, so each
            # store needs at most `limit` of them — reference maxFeatures)
            out = s.query(self.type_name, f, limit=limit)
            if len(out) == 0:
                continue
            keep = np.array([i not in seen for i in out.ids.tolist()])
            seen.update(out.ids.tolist())
            out = out.mask(keep)
            if len(out):
                parts.append(out)
                kept += len(out)
        if not parts:
            return self.stores[0].features(self.type_name).take(
                np.zeros(0, dtype=np.int64)
            )
        merged = parts[0] if len(parts) == 1 else FeatureCollection.concat(parts)
        if limit is not None and len(merged) > limit:
            merged = merged.take(np.arange(limit))
        return merged

    def count(self, f: "Filter | str" = INCLUDE) -> int:
        return len(self.query(f))

    def density(
        self, f, envelope: tuple, width: int = 256, height: int = 256
    ) -> np.ndarray:
        """Sum of the member stores' device density grids (the reference
        merged view runs DensityScan per store and sums client-side).
        Duplicate-id rows present in several stores count once per store
        here — the aggregation trade-off the reference documents for
        merged views."""
        grid = None
        for s in self.stores:
            g = s.density(
                self.type_name, f, envelope=envelope, width=width, height=height
            )
            grid = g if grid is None else grid + g
        return grid

    def bounds(self, f: "Filter | str" = INCLUDE, estimate: bool = True):
        """Union envelope over member stores."""
        env = None
        for s in self.stores:
            b = s.bounds(self.type_name, f, estimate=estimate)
            if b is None:
                continue
            env = b if env is None else (
                min(env[0], b[0]), min(env[1], b[1]),
                max(env[2], b[2]), max(env[3], b[3]),
            )
        return env


class RoutedView:
    """Route each query to exactly one store by a router function over the
    filter (reference RouteSelectorByAttribute: e.g. coarse vs fine stores
    chosen by query attributes). ``router(filter) -> store index``; a None
    route falls back to ``default``."""

    def __init__(
        self,
        stores: Sequence,
        type_name: str,
        router: Callable[[Filter], Optional[int]],
        default: int = 0,
    ):
        self.stores = list(stores)
        self.type_name = type_name
        self.router = router
        self.default = default

    def query(self, f: "Filter | str" = INCLUDE, limit: Optional[int] = None) -> FeatureCollection:
        from geomesa_tpu.filter import ecql

        if isinstance(f, str):
            f = ecql.parse(f)
        route = self.router(f)
        store = self.stores[self.default if route is None else route]
        return store.query(self.type_name, f, limit=limit)

    def count(self, f: "Filter | str" = INCLUDE) -> int:
        return len(self.query(f))
