"""IndexAdapter SPI: the pluggable backend seam.

Reference: IndexAdapter (/root/reference/geomesa-index-api/src/main/scala/
org/locationtech/geomesa/index/api/IndexAdapter.scala:27-86) — every
backend (Accumulo/HBase/Cassandra/Redis/fs/...) implements one interface
(createTable / deleteTables / createWriter / createQueryPlan) and the
DataStore is backend-agnostic. Here the contract is columnar: an adapter
turns (keyspace, sorted write keys) into a *scan surface* — any object
with the IndexTable interface (scan/count/density/bounds_stats/
candidate_spans/nbytes_device) — and owns its lifecycle. The built-in
adapter is the in-process HBM-resident table (single-chip or mesh-
sharded); alternative adapters can host tables elsewhere (e.g. a
host-memory XLA-CPU tier, or a remote pool) without touching the
DataStore or planner."""

from __future__ import annotations

from typing import Optional, Protocol, runtime_checkable

from geomesa_tpu.fault import fault_point, with_retries
from geomesa_tpu.index.api import IndexKeySpace, WriteKeys


@runtime_checkable
class IndexAdapter(Protocol):
    """Backend SPI (reference IndexAdapter.createTable/deleteTables)."""

    def create_table(
        self,
        keyspace: IndexKeySpace,
        keys: WriteKeys,
        old=None,
        main_rows: int = 0,
        sorted_state=None,
    ):
        """Build (or incrementally update from ``old``) the scan surface
        for one index. ``old`` is this adapter's previous table for the
        index (or None); ``main_rows`` is the row count ``old`` was built
        from — rows past it in ``keys`` are the freshly-compacted delta.
        ``sorted_state``: an optional precomputed stable (bin, z) argsort
        of ``keys`` (the pipelined ingest's merged runs) — adapters may
        use it to skip their own sort; ignoring it is always correct.
        DataStore only passes it to adapters whose signature accepts it,
        so implementations predating the kwarg keep working."""
        ...

    def delete_table(self, table) -> None:
        """Release a table's resources (reference deleteTables)."""
        ...


def _bump_generations(adapter, keyspace) -> None:
    """Generation hook for table rebuilds: a DataStore with a cache tier
    sets ``adapter.generations`` (cache.GenerationTracker) and every
    create_table bumps the owning type — compaction is a mutation path in
    the invalidation contract (docs/caching.md), conservatively scoped to
    the whole type since the adapter sees sort keys, not filters."""
    generations = getattr(adapter, "generations", None)
    sft = getattr(keyspace, "sft", None)
    if generations is not None and sft is not None:
        generations.bump(sft.name)


class InProcessAdapter:
    """The built-in backend: HBM-resident sorted columnar tables, mesh-
    sharded when a mesh is configured. Single-chip updates take the
    partition-preserving merge path (storage.table.merged_table)."""

    def __init__(self, mesh=None, tile: Optional[int] = None):
        self.mesh = mesh
        self.tile = tile
        self.generations = None  # set by DataStore.attach_cache

    def create_table(
        self, keyspace, keys, old=None, main_rows: int = 0, sorted_state=None
    ):
        from geomesa_tpu.storage.table import IndexTable, merged_table

        # table builds are pure functions of (keyspace, keys), so a
        # transient IO fault (OSError; fault-injectable) is safely
        # retried. Device/runtime errors are NOT retried — an XLA
        # failure is not known-transient and masking it would hide
        # real bugs.
        def attempt():
            fault_point("adapter.create_table")
            kwargs: dict = {"tile": self.tile} if self.tile else {}
            if self.mesh is not None:
                from geomesa_tpu.parallel import DistributedIndexTable
                from geomesa_tpu.pod.hostgroup import HostGroup

                if isinstance(self.mesh, HostGroup):
                    from geomesa_tpu.pod.table import PodIndexTable

                    # a host group rides the mesh seam: per-host
                    # contiguous shards instead of one flat deal
                    return PodIndexTable(keyspace, keys, self.mesh, **kwargs)
                # mesh tables re-sort (their deal layout derives from the
                # sort anyway); ignoring sorted_state is correct
                return DistributedIndexTable(keyspace, keys, self.mesh, **kwargs)
            if sorted_state is not None and len(sorted_state) == len(keys.zs) > 0:
                # the pipelined ingest already merged the stable (bin, z)
                # order: build straight from it, no radix sort
                return IndexTable(
                    keyspace, keys, sorted_state=sorted_state, **kwargs
                )
            if (
                isinstance(old, IndexTable)
                and old.n == main_rows
                and 0 < main_rows < len(keys.zs)
            ):
                from geomesa_tpu.datastore import _slice_keys

                return merged_table(
                    old, keys, _slice_keys(keys, main_rows), **kwargs
                )
            return IndexTable(keyspace, keys, **kwargs)

        table = with_retries(attempt)
        _bump_generations(self, keyspace)
        return table

    def fold_table(
        self,
        keyspace,
        old,
        merged_keys,
        keep_ordinal=None,
        ordinal_map=None,
        delta_keys=None,
        delta_perm=None,
    ):
        """Incremental replace-merge (storage.table.folded_table): fold a
        delete + insert batch into ``old`` without a whole-table re-sort,
        bit-identical to a full recompaction. Returns the folded table,
        or None when this adapter/table cannot fold (mesh-sharded tables,
        secondary-sort-word indexes, foreign table classes) — the caller
        then takes the full rebuild path. Optional SPI method: DataStore
        probes it with hasattr, so custom adapters without it keep
        working. Deliberately does NOT run the whole-type generation bump
        ``create_table`` does — the fold's caller owns SCOPED bumps over
        the touched key ranges (docs/streaming.md), which is what lets
        unrelated cached entries survive a streaming flush."""
        from geomesa_tpu.storage.table import IndexTable, folded_table

        if self.mesh is not None or merged_keys.sub is not None:
            return None
        if (
            not isinstance(old, IndexTable)
            or type(old)._place_cols is not IndexTable._place_cols
        ):
            return None  # subclasses own their layout; rebuild instead

        def attempt():
            fault_point("adapter.create_table")
            return folded_table(
                old, merged_keys, keep_ordinal, ordinal_map, delta_keys,
                delta_perm=delta_perm, tile=self.tile,
            )

        return with_retries(attempt)

    def delete_table(self, table) -> None:
        pass  # device arrays free with the last reference


class HostTable(object):
    """Pure-host scan surface: the same IndexTable contract with NO jax
    anywhere — sorted keys + numpy predicate masks (the reference's
    in-memory CQEngine backend tier). The second IndexAdapter
    implementation, proving the SPI seam: DataStore/planner code runs
    unmodified against it."""

    def __init__(self, keyspace, keys: WriteKeys, tile=None, sorted_state=None):
        from geomesa_tpu.storage.table import SortedKeys

        self._sk = SortedKeys(keyspace, keys, tile or 0, sorted_state=sorted_state)
        self.keyspace = keyspace
        # sorted host copies of the predicate columns
        self._cols = {
            k: v[self._sk.perm] for k, v in keys.device_cols.items()
        }
        self.extent = "gxmin" in self._cols
        self.nbytes_device = 0  # nothing lives on a device

    # -- SortedKeys passthroughs ----------------------------------------
    @property
    def n(self):
        return self._sk.n

    @property
    def perm(self):
        return self._sk.perm

    def candidate_spans(self, config):
        return self._sk.candidate_spans(config)

    def candidate_spans_split(self, config):
        return self._sk.candidate_spans_split(config)

    # -- scan surface ----------------------------------------------------
    def _wide_rows(self, config) -> "np.ndarray":
        """Sorted-table row ids passing the WIDE predicate within the
        candidate spans (numpy; bit-compatible with the kernel's wide
        plane via delta_wide_mask)."""
        import numpy as np

        from geomesa_tpu.storage.delta import delta_wide_mask
        from geomesa_tpu.storage.table import _span_rows

        spans = self.candidate_spans(config)
        rows = _span_rows(spans)
        if len(rows) == 0:
            return rows
        sub = WriteKeys(
            bins=self._sk.bins[rows],
            zs=self._sk.zs[rows],
            device_cols={k: v[rows] for k, v in self._cols.items()},
        )
        m = delta_wide_mask(
            config, sub,
            packed_shift=getattr(self.keyspace, "packed_time", None),
        )
        return rows[m]

    def scan(self, config, deadline=None):
        return self.scan_submit(config, deadline=deadline)()

    def scan_submit(self, config, deadline=None):
        import numpy as np

        if config.disjoint or self.n == 0:
            return lambda: (np.zeros(0, np.int64), np.zeros(0, bool))
        rows = self._wide_rows(config)
        out = (
            self._sk.perm[rows].astype(np.int64),
            np.zeros(len(rows), bool),  # wide-only: always refine
        )
        return lambda: out

    def scan_submit_many(self, configs, deadline=None):
        """Same contract as IndexTable.scan_submit_many (one finish per
        config); a host table has no dispatch overhead to amortize, so
        this is the per-query loop."""
        return [self.scan_submit(c, deadline=deadline) for c in configs]

    def count(self, config) -> int:
        return int(len(self._wide_rows(config)))

    # -- aggregation surface (wide semantics, like the device kernels;
    # the representative-xy and grid-scatter rules are SHARED with the
    # delta tier — one implementation, storage.delta) ------------------
    def density(self, config, envelope, width, height):
        return self.density_submit(config, envelope, width, height)()

    def density_submit(self, config, envelope, width, height):
        from geomesa_tpu.storage.delta import rep_xy, scatter_density

        rows = self._wide_rows(config)
        x, y = rep_xy(self._cols, rows)
        grid = scatter_density(x, y, envelope, width, height)
        return lambda: grid

    def bounds_stats(self, config):
        from geomesa_tpu.storage.delta import rep_xy

        rows = self._wide_rows(config)
        if len(rows) == 0:
            return 0, None
        x, y = rep_xy(self._cols, rows)
        return len(rows), (
            float(x.min()), float(y.min()), float(x.max()), float(y.max())
        )

    def warmup(self) -> int:
        return 0  # nothing to compile


class HostAdapter:
    """IndexAdapter producing HostTable scan surfaces (no device, no
    jax): the drop-in backend for environments without an accelerator or
    for tiny reference stores in tests. Compactions rebuild the sort
    outright (no merged_table fast path) — acceptable at this tier's
    scale; thread ``old``'s sort state through if it ever fronts big
    data."""

    def __init__(self, tile=None):
        self.tile = tile

    def create_table(
        self, keyspace, keys, old=None, main_rows: int = 0, sorted_state=None
    ):
        def attempt():
            fault_point("adapter.create_table")
            if sorted_state is not None and len(sorted_state) != len(keys.zs):
                return HostTable(keyspace, keys, tile=self.tile)
            return HostTable(
                keyspace, keys, tile=self.tile, sorted_state=sorted_state
            )

        return with_retries(attempt)

    def delete_table(self, table) -> None:
        pass
