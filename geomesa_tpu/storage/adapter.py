"""IndexAdapter SPI: the pluggable backend seam.

Reference: IndexAdapter (/root/reference/geomesa-index-api/src/main/scala/
org/locationtech/geomesa/index/api/IndexAdapter.scala:27-86) — every
backend (Accumulo/HBase/Cassandra/Redis/fs/...) implements one interface
(createTable / deleteTables / createWriter / createQueryPlan) and the
DataStore is backend-agnostic. Here the contract is columnar: an adapter
turns (keyspace, sorted write keys) into a *scan surface* — any object
with the IndexTable interface (scan/count/density/bounds_stats/
candidate_spans/nbytes_device) — and owns its lifecycle. The built-in
adapter is the in-process HBM-resident table (single-chip or mesh-
sharded); alternative adapters can host tables elsewhere (e.g. a
host-memory XLA-CPU tier, or a remote pool) without touching the
DataStore or planner."""

from __future__ import annotations

from typing import Optional, Protocol, runtime_checkable

from geomesa_tpu.index.api import IndexKeySpace, WriteKeys


@runtime_checkable
class IndexAdapter(Protocol):
    """Backend SPI (reference IndexAdapter.createTable/deleteTables)."""

    def create_table(
        self,
        keyspace: IndexKeySpace,
        keys: WriteKeys,
        old=None,
        main_rows: int = 0,
    ):
        """Build (or incrementally update from ``old``) the scan surface
        for one index. ``old`` is this adapter's previous table for the
        index (or None); ``main_rows`` is the row count ``old`` was built
        from — rows past it in ``keys`` are the freshly-compacted delta."""
        ...

    def delete_table(self, table) -> None:
        """Release a table's resources (reference deleteTables)."""
        ...


class InProcessAdapter:
    """The built-in backend: HBM-resident sorted columnar tables, mesh-
    sharded when a mesh is configured. Single-chip updates take the
    partition-preserving merge path (storage.table.merged_table)."""

    def __init__(self, mesh=None, tile: Optional[int] = None):
        self.mesh = mesh
        self.tile = tile

    def create_table(self, keyspace, keys, old=None, main_rows: int = 0):
        from geomesa_tpu.storage.table import IndexTable, merged_table

        kwargs: dict = {"tile": self.tile} if self.tile else {}
        if self.mesh is not None:
            from geomesa_tpu.parallel import DistributedIndexTable

            return DistributedIndexTable(keyspace, keys, self.mesh, **kwargs)
        if (
            isinstance(old, IndexTable)
            and old.n == main_rows
            and 0 < main_rows < len(keys.zs)
        ):
            from geomesa_tpu.datastore import _slice_keys

            return merged_table(old, keys, _slice_keys(keys, main_rows), **kwargs)
        return IndexTable(keyspace, keys, **kwargs)

    def delete_table(self, table) -> None:
        pass  # device arrays free with the last reference
