"""Store persistence: save/load a DataStore's schemas and data to disk.

Reference: the filesystem datastore (geomesa-fs, SURVEY.md §2.4) — a
directory layout of metadata + columnar data files
(/root/reference/geomesa-fs/geomesa-fs-storage/geomesa-fs-storage-common/
src/main/scala/org/locationtech/geomesa/fs/storage/common/metadata/
FileBasedMetadata.scala, parquet/ParquetFileSystemStorage.scala). The TPU
redesign persists each feature type as one .npz of its columns (the
Parquet-file analogue: columnar, compressed) plus a JSON metadata document
(schema spec + user data), and rebuilds index tables on load — indexes are
derived state, exactly as the reference rebuilds query state from
metadata + files.

Layout:  <root>/metadata.json
         <root>/<type_name>.npz
"""

from __future__ import annotations

import json
import os

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import PointColumn
from geomesa_tpu.sft import FeatureType

FORMAT_VERSION = 1


import re

_SAFE_NAME = re.compile(r"^[A-Za-z0-9_.-]+$")


def save(store, root: str) -> None:
    """Persist every schema + feature batch under ``root``."""
    os.makedirs(root, exist_ok=True)
    meta: dict = {"version": FORMAT_VERSION, "types": {}}
    for name in store.type_names():
        if not _SAFE_NAME.match(name):
            raise ValueError(
                f"feature type name {name!r} is not filesystem-safe "
                "([A-Za-z0-9_.-] only) — cannot persist"
            )
        sft = store.get_schema(name)
        meta["types"][name] = {
            "spec": sft.to_spec(),
            "user_data": {str(k): str(v) for k, v in sft.user_data.items()},
        }
        fc = store.features(name)
        np.savez_compressed(
            os.path.join(root, f"{name}.npz"), **_pack_columns(sft, fc)
        )
    tmp = os.path.join(root, "metadata.json.tmp")
    with open(tmp, "w") as fh:
        json.dump(meta, fh, indent=2)
    os.replace(tmp, os.path.join(root, "metadata.json"))


def load(root: str, **store_kwargs):
    """Rebuild a DataStore (indexes re-derived) from a saved directory."""
    from geomesa_tpu.datastore import DataStore

    with open(os.path.join(root, "metadata.json")) as fh:
        meta = json.load(fh)
    if meta.get("version") != FORMAT_VERSION:
        raise ValueError(f"unsupported store format {meta.get('version')!r}")
    store = DataStore(**store_kwargs)
    for name, info in meta["types"].items():
        if not _SAFE_NAME.match(name):
            raise ValueError(f"unsafe feature type name in metadata: {name!r}")
        sft = FeatureType.from_spec(name, info["spec"])
        sft.user_data.update(info.get("user_data", {}))
        store.create_schema(sft)
        with np.load(os.path.join(root, f"{name}.npz"), allow_pickle=False) as z:
            fc = _unpack_columns(sft, z)
        if len(fc):
            store.write(name, fc, check_ids=False)
    return store


def _pack_columns(sft: FeatureType, fc: FeatureCollection) -> dict:
    out: dict = {"__ids__": fc.ids}
    for name, col in fc.columns.items():
        if isinstance(col, PointColumn):
            out[f"pt:{name}:x"] = col.x
            out[f"pt:{name}:y"] = col.y
        elif isinstance(col, geo.PackedGeometryColumn):
            out[f"pg:{name}:coords"] = col.coords
            out[f"pg:{name}:ring_offsets"] = col.ring_offsets
            out[f"pg:{name}:part_ring_offsets"] = col.part_ring_offsets
            out[f"pg:{name}:geom_part_offsets"] = col.geom_part_offsets
            out[f"pg:{name}:types"] = col.types
            out[f"pg:{name}:bboxes"] = col.bboxes
        else:
            out[f"col:{name}"] = np.asarray(col)
    return out


def _unpack_columns(sft: FeatureType, z) -> FeatureCollection:
    cols: dict = {}
    names = set(z.files)
    for attr in sft.attributes:
        n = attr.name
        if f"pt:{n}:x" in names:
            cols[n] = PointColumn(z[f"pt:{n}:x"], z[f"pt:{n}:y"])
        elif f"pg:{n}:coords" in names:
            cols[n] = geo.PackedGeometryColumn(
                coords=z[f"pg:{n}:coords"],
                ring_offsets=z[f"pg:{n}:ring_offsets"],
                part_ring_offsets=z[f"pg:{n}:part_ring_offsets"],
                geom_part_offsets=z[f"pg:{n}:geom_part_offsets"],
                types=z[f"pg:{n}:types"],
                bboxes=z[f"pg:{n}:bboxes"],
            )
        elif f"col:{n}" in names:
            cols[n] = z[f"col:{n}"]
        else:
            raise KeyError(f"column {n!r} missing from saved store")
    return FeatureCollection(sft, z["__ids__"], cols)
