"""Store persistence: save/load a DataStore's schemas and data to disk.

Reference: the filesystem datastore (geomesa-fs, SURVEY.md §2.4) — a
partition-scheme directory layout of metadata + columnar data files
(/root/reference/geomesa-fs/geomesa-fs-storage/geomesa-fs-storage-common/
src/main/scala/org/locationtech/geomesa/fs/storage/common/partitions/
DateTimeScheme et al., metadata/FileBasedMetadata.scala,
parquet/ParquetFileSystemStorage.scala). Each feature type persists as
.npz column files (the Parquet-file analogue: columnar, compressed), one
file per coarse time partition (partition = dtg // PARTITION_MS, the
DateTimeScheme analogue; atemporal types collapse to a single partition
0). Saves are INCREMENTAL: a partition whose content signature matches
the manifest is skipped, so steady-state appends rewrite only the
partitions they touched.

Format v3 is CRASH-SAFE (the durability model; docs/durability.md):

- every file lands via temp-file + fsync + ``os.replace`` — no reader
  ever sees a torn file;
- partition files are *content-addressed* (``p<NNNN>-<sig16>.npz``): a
  changed partition gets a NEW name, the committed file it replaces
  stays on disk until the manifest commits, so the old manifest keeps
  describing a complete old store at every instant;
- ``metadata.json`` (written LAST, atomically) carries a per-partition
  blake2b file checksum + byte length; its rename is the commit point —
  a crash anywhere leaves either the old or the new store, never a mix;
- unreferenced files are garbage-collected only AFTER the commit;
- ``load()`` verifies every partition against the manifest, moves
  damaged files to ``<root>/_quarantine/`` with a machine-readable
  report, rebuilds indexes from the survivors, and marks the store's
  :class:`StoreHealth` degraded so queries carry a warning instead of
  silently serving a hole.

Every IO step is a named ``fault_point`` (geomesa_tpu.fault) and the
transient-failure steps run under bounded exponential-backoff retry.

Index tables are rebuilt on load — indexes are derived state, exactly as
the reference rebuilds query state from metadata + files.

Layout:  <root>/metadata.json               (manifest; the commit point)
         <root>/<type>/p<NNNN>-<sig>.npz    (content-addressed partitions)
         <root>/_quarantine/                (damaged files + report.json)
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import time
from dataclasses import dataclass, field

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.fault import atomic_write, fault_point, with_retries
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import PointColumn
from geomesa_tpu.sft import FeatureType

FORMAT_VERSION = 3
PARTITION_MS = 28 * 86_400_000  # ~monthly time partitions (DateTimeScheme)
QUARANTINE_DIR = "_quarantine"

_SAFE_NAME = re.compile(r"^[A-Za-z0-9_.-]+$")


class StoreCorruptionError(ValueError):
    """The store's manifest (or, with ``on_damage="raise"``, a data file)
    is damaged beyond what degraded-mode loading can contain."""


@dataclass
class DamageRecord:
    """One damaged/missing partition file found during load — the
    machine-readable unit of ``_quarantine/report.json``."""

    type_name: str
    file: str                 # manifest-relative file name
    reason: str               # "missing"|"truncated"|"checksum"|"unreadable"|"manifest"
    detail: str = ""
    rows_lost: int = 0        # manifest row count of the damaged partition
    quarantined_to: str | None = None
    # first sighting: False when report.json already records this file —
    # re-loading a degraded store must not re-count old damage in metrics
    fresh: bool = True

    def to_json(self) -> dict:
        return {
            "type": self.type_name,
            "file": self.file,
            "reason": self.reason,
            "detail": self.detail,
            "rows_lost": self.rows_lost,
            "quarantined_to": self.quarantined_to,
        }


@dataclass
class StoreHealth:
    """Damage accounting surfaced as ``DataStore.store_health``. A store
    that loaded with quarantined partitions answers queries in DEGRADED
    mode: results are exact over the surviving rows, and every plan over
    a damaged type carries a warning (planner + metrics counter)."""

    damage: list[DamageRecord] = field(default_factory=list)

    @property
    def status(self) -> str:
        return "degraded" if self.damage else "ok"

    @property
    def ok(self) -> bool:
        return not self.damage

    def degraded_types(self) -> set:
        return {d.type_name for d in self.damage}

    def damage_for(self, type_name: str) -> list[DamageRecord]:
        return [d for d in self.damage if d.type_name == type_name]

    def warning_for(self, type_name: str) -> str | None:
        """The per-query degraded-mode warning, or None when healthy."""
        recs = self.damage_for(type_name)
        if not recs:
            return None
        rows = sum(r.rows_lost for r in recs)
        return (
            f"results for {type_name!r} exclude {len(recs)} quarantined "
            f"partition(s) (~{rows} rows): "
            + ", ".join(f"{r.file} [{r.reason}]" for r in recs)
        )


def _id_token(v) -> bytes:
    """Unambiguous per-id encoding: length-prefixed + type-tagged, so
    ``"1"``/``1``/``b"1"`` hash apart and an id containing the old
    ``\\n`` separator cannot alias a neighboring pair."""
    if isinstance(v, bytes):
        tag, payload = b"b", v
    elif isinstance(v, str):
        tag, payload = b"s", v.encode("utf-8")
    elif isinstance(v, (bool, np.bool_)):
        tag, payload = b"B", b"1" if v else b"0"
    elif isinstance(v, (int, np.integer)):
        tag, payload = b"i", str(int(v)).encode()
    elif isinstance(v, (float, np.floating)):
        tag, payload = b"f", repr(float(v)).encode()
    else:
        tag, payload = b"o", str(v).encode("utf-8")
    return len(payload).to_bytes(8, "little") + tag + payload


def _hash_packed(h, packed: dict) -> None:
    """Fold a partition's packed columns into a digest. String arrays
    hash through a width-independent length-prefixed encoding — numpy
    unicode itemsize grows with the longest value ANYWHERE in the type,
    and padding bytes must not change untouched partitions' signatures."""
    for key in sorted(packed):
        a = np.asarray(packed[key])
        h.update(b"\x00k" + key.encode())
        if a.dtype.kind in ("U", "S"):
            h.update(b"\x00s")
            for v in a.tolist():
                payload = v.encode("utf-8") if isinstance(v, str) else v
                h.update(len(payload).to_bytes(8, "little") + payload)
        else:
            h.update(b"\x00n" + str(a.dtype).encode() + str(a.shape).encode())
            h.update(np.ascontiguousarray(a).tobytes())


def _signature(ids: np.ndarray, packed: dict) -> str:
    """Content signature of a partition: row count + ids + the packed
    column BYTES. Ids alone detect membership changes, but updates
    (upsert / modify_features / the streaming flush) replace VALUES under
    unchanged ids — the value bytes must be covered or the incremental
    skip silently persists stale data. Ids additionally hash in a
    type-tagged, length-prefixed encoding so an object-dtype mix of
    str/bytes/int ids cannot collide through a common ``str()`` form.
    blake2b streams at memory bandwidth; the cost of hashing unchanged
    partitions is far below rewriting (compressing) them."""
    h = hashlib.blake2b(digest_size=16)
    ids = np.asarray(ids)
    h.update(str(len(ids)).encode())
    if ids.dtype.kind in ("U", "S", "O"):
        for v in ids:
            h.update(_id_token(v))
    else:
        h.update(np.ascontiguousarray(ids).tobytes())
    _hash_packed(h, packed)
    return h.hexdigest()


def _partition_ids(fc: FeatureCollection, dtg_field: str | None) -> np.ndarray:
    if dtg_field is None or len(fc) == 0:
        return np.zeros(len(fc), dtype=np.int64)
    return np.asarray(fc.columns[dtg_field], dtype=np.int64) // PARTITION_MS


# -- durable file primitives ------------------------------------------------

def _write_partition(final_path: str, packed: dict) -> dict:
    """Durably write one partition file: serialize in memory, digest the
    exact bytes, land them atomically (fault.atomic_write), retried on
    transient IO errors. Returns the manifest entry fragment
    {"checksum", "bytes"}."""

    def attempt() -> dict:
        buf = io.BytesIO()
        np.savez_compressed(buf, **packed)
        data = buf.getvalue()
        checksum = hashlib.blake2b(data, digest_size=16).hexdigest()
        atomic_write(final_path, data, point="persist.partition")
        # post-commit point: bit_flip/partial_write here damage the
        # DURABLE bytes after their checksum was recorded — the silent
        # media-corruption scenario load() must catch
        fault_point("persist.partition.commit", final_path)
        return {"checksum": checksum, "bytes": len(data)}

    return with_retries(attempt)


def _commit_manifest(root: str, meta: dict) -> None:
    """The commit point: metadata.json lands atomically, LAST."""
    meta_path = os.path.join(root, "metadata.json")

    def attempt() -> None:
        atomic_write(
            meta_path, json.dumps(meta, indent=2).encode(),
            point="persist.manifest",
        )
        fault_point("persist.manifest.commit", meta_path)

    with_retries(attempt)


def _read_manifest(root: str) -> dict | None:
    """Best-effort read of the existing manifest (for incremental reuse);
    None when absent or unreadable — save() then rewrites everything."""
    meta_path = os.path.join(root, "metadata.json")
    if not os.path.exists(meta_path):
        return None
    try:
        with open(meta_path) as fh:
            return json.load(fh)
    except (ValueError, OSError):
        return None


# -- save -------------------------------------------------------------------

def save(store, root: str) -> None:
    """Persist every schema + feature batch under ``root``. Incremental:
    partitions whose content signature matches the committed manifest are
    not rewritten. Crash-safe: a failure at ANY point (fault-injectable;
    see geomesa_tpu.fault) leaves either the previous committed store or
    the new one — never a torn mix."""
    root = str(root)
    os.makedirs(root, exist_ok=True)
    old = _read_manifest(root)
    old_parts: dict = {}
    if old is not None and old.get("version") == FORMAT_VERSION:
        for t, info in old.get("types", {}).items():
            old_parts[t] = info.get("partitions", {})
    meta: dict = {"version": FORMAT_VERSION, "types": {}}
    referenced: dict[str, set] = {}
    for name in store.type_names():
        if not _SAFE_NAME.match(name) or name == QUARANTINE_DIR:
            raise ValueError(
                f"feature type name {name!r} is not filesystem-safe "
                f"([A-Za-z0-9_.-] only, not {QUARANTINE_DIR!r}) — "
                "cannot persist"
            )
        sft = store.get_schema(name)
        info = {
            "spec": sft.to_spec(),
            "user_data": {str(k): str(v) for k, v in sft.user_data.items()},
        }
        fc = store.features(name)
        parts = _partition_ids(fc, sft.dtg_field)
        tdir = os.path.join(root, name)
        os.makedirs(tdir, exist_ok=True)
        manifest: dict = {}
        prev = old_parts.get(name, {})
        for p in np.unique(parts) if len(fc) else []:
            idx = np.flatnonzero(parts == p)
            sub = fc.take(idx)
            packed = _pack_columns(sft, sub)
            sig = _signature(np.asarray(sub.ids), packed)
            pkey = f"p{int(p)}"
            pe = prev.get(pkey)
            if (
                isinstance(pe, dict)
                and pe.get("sig") == sig
                and os.path.exists(os.path.join(tdir, str(pe.get("file"))))
            ):
                manifest[pkey] = pe  # unchanged: reuse the committed file
                continue
            fname = f"{pkey}-{sig[:16]}.npz"
            entry = _write_partition(os.path.join(tdir, fname), packed)
            manifest[pkey] = {
                "file": fname, "sig": sig, "rows": int(len(idx)), **entry,
            }
        info["partitions"] = manifest
        meta["types"][name] = info
        referenced[name] = {e["file"] for e in manifest.values()}
    _commit_manifest(root, meta)
    _collect_garbage(root, referenced)


def _collect_garbage(root: str, referenced: dict) -> None:
    """Drop files the committed manifest no longer references: replaced
    partition versions, stale tmps, pre-v3 layouts, and whole directories
    of types the store no longer has (delete_schema'd data must not
    linger on disk). Runs strictly AFTER the manifest commit; a crash
    here only leaves orphans, which load() ignores and the next save()
    sweeps."""
    fault_point("persist.gc", root)

    def _rm(path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass

    for entry in os.listdir(root):
        path = os.path.join(root, entry)
        if os.path.isdir(path):
            if entry == QUARANTINE_DIR:
                continue
            keep = referenced.get(entry, set())  # dropped type: keep nothing
            for f in os.listdir(path):
                if f not in keep and (f.endswith(".npz") or f.endswith(".tmp")):
                    _rm(os.path.join(path, f))
            if entry not in referenced:
                try:
                    os.rmdir(path)  # only succeeds when fully swept
                except OSError:
                    pass
        elif entry.endswith(".npz"):
            # root-level npz files are pre-v3 layouts (current types'
            # legacy copies, or dropped types') — all superseded
            _rm(path)


# -- load -------------------------------------------------------------------

def _manifest_int(v, default: int = 0) -> int:
    """Tolerant int for manifest fields: garbage in a torn entry must
    read as a verification mismatch, not abort the load."""
    try:
        return int(v)
    except (TypeError, ValueError):
        return default


def _read_bytes(path: str) -> bytes:
    """One full read of a partition file, retried on transient IO
    errors — both the checksum and np.load consume this single buffer,
    so the load path reads every file exactly once."""

    def attempt() -> bytes:
        fault_point("load.partition.read", path)
        with open(path, "rb") as fh:
            return fh.read()

    return with_retries(attempt)


def _quarantine(root: str, type_name: str, path: str, fname: str,
                reason: str, detail: str, rows: int) -> DamageRecord:
    """Move a damaged file under ``<root>/_quarantine/<type>/`` and
    append a machine-readable record to ``_quarantine/report.json``.
    All filesystem work here is best-effort: a store on a read-only
    mount must still LOAD degraded (in-memory health intact) even when
    nothing can be moved or logged."""
    qdir = os.path.join(root, QUARANTINE_DIR, type_name)
    dest: str | None = None
    if os.path.exists(path):
        try:
            os.makedirs(qdir, exist_ok=True)
            dest = os.path.join(qdir, fname)
            os.replace(path, dest)
        except OSError:
            dest = None
    rec = DamageRecord(
        type_name=type_name, file=fname, reason=reason, detail=detail,
        rows_lost=rows,
        quarantined_to=(
            os.path.relpath(dest, root) if dest is not None else None
        ),
    )
    try:
        rec.fresh = _append_damage_record(root, rec)
    except OSError:
        pass
    return rec


def _append_damage_record(root: str, rec: DamageRecord) -> bool:
    """One report.json record per damaged FILE: re-loading an
    already-degraded store re-detects the same hole every time (the
    quarantined file now reads as "missing") and must not inflate the
    report with a duplicate record per load. Returns whether the record
    was new."""
    report = os.path.join(root, QUARANTINE_DIR, "report.json")
    os.makedirs(os.path.dirname(report), exist_ok=True)
    records: list = []
    if os.path.exists(report):
        try:
            with open(report) as fh:
                records = json.load(fh).get("damage", [])
        except (ValueError, OSError):
            records = []
    if any(
        r.get("type") == rec.type_name and r.get("file") == rec.file
        for r in records
    ):
        return False
    records.append({**rec.to_json(), "time": time.time()})
    atomic_write(report, json.dumps({"damage": records}, indent=2).encode())
    return True


def _load_npz(path: str, sft: FeatureType) -> FeatureCollection:
    def attempt() -> FeatureCollection:
        fault_point("load.partition.read", path)
        with np.load(path, allow_pickle=False) as z:
            return _unpack_columns(sft, z)

    return with_retries(attempt)


def load(root: str, on_damage: str = "quarantine", **store_kwargs):
    """Rebuild a DataStore (indexes re-derived) from a saved directory.
    Reads the v3 checksummed layout plus the legacy v1/v2 layouts.

    v3 loads are VERIFIED: every partition file is checked against the
    manifest's byte length + blake2b checksum and must unpack cleanly.
    Damage handling (``on_damage``):

    - ``"quarantine"`` (default): damaged files move to
      ``<root>/_quarantine/`` with a machine-readable ``report.json``
      record; the store loads the surviving partitions and its
      ``store_health`` turns DEGRADED (queries warn, metrics count);
    - ``"raise"``: raise :class:`StoreCorruptionError` on first damage.
    """
    from geomesa_tpu.datastore import DataStore

    root = str(root)
    if on_damage not in ("quarantine", "raise"):
        raise ValueError(f"on_damage must be 'quarantine' or 'raise', got {on_damage!r}")
    meta_path = os.path.join(root, "metadata.json")
    try:
        with open(meta_path) as fh:
            meta = json.load(fh)
    except ValueError as e:
        raise StoreCorruptionError(
            f"store manifest {meta_path} is not valid JSON: {e}"
        ) from e
    if meta.get("version") not in (1, 2, FORMAT_VERSION):
        raise ValueError(f"unsupported store format {meta.get('version')!r}")
    store = DataStore(**store_kwargs)
    health = StoreHealth()
    for name, info in meta["types"].items():
        if not _SAFE_NAME.match(name) or name == QUARANTINE_DIR:
            raise StoreCorruptionError(
                f"unsafe feature type name in metadata: {name!r}"
            )
        sft = FeatureType.from_spec(name, info["spec"])
        sft.user_data.update(info.get("user_data", {}))
        store.create_schema(sft)
        if meta.get("version") == FORMAT_VERSION:
            pieces = _load_v3_type(root, name, sft, info, health, on_damage)
        else:
            pieces = _load_legacy_type(root, name, sft, info)
        pieces = [p for p in pieces if len(p)]
        if pieces:
            # one batch through the staged ingest pipeline: key encoding
            # and the (bin, z) sorts for the different indexes run on
            # worker threads in parallel, and the pre-merged sort feeds
            # the table build directly (a single chunk keeps the stats
            # fold identical to the old single-write path)
            from geomesa_tpu.ingest import BulkLoader

            fc = pieces[0] if len(pieces) == 1 else FeatureCollection.concat(pieces)
            loader = BulkLoader(store, name, check_ids=False)
            loader.put(fc)
            loader.close()
    store.health = health
    cache = getattr(store, "cache", None)
    if cache is not None:
        # a reload is a mutation over EVERY loaded type — including one
        # that loads zero rows: the on-disk state may be older than what
        # warm entries saw (unsaved writes roll back across a crash), and
        # the write-path bumps above only fire when rows actually loaded
        for name in meta["types"]:
            cache.on_mutation(name)
    if cache is not None and health.damage:
        # degraded-mode contract (docs/caching.md): a warm cache passed
        # through ``load(root, cache=...)`` must not keep entries over a
        # quarantined partition's key range — bump + eagerly drop them,
        # don't just warn
        for d in health.damage:
            cache.on_quarantine(d.type_name, _partition_interval(d.file))
    fresh = sum(1 for d in health.damage if d.fresh)
    if fresh:
        from geomesa_tpu.metrics import resolve

        resolve(getattr(store, "metrics", None)).counter(
            "geomesa.store.quarantined", fresh
        )
    return store


_PART_FILE = re.compile(r"^p(-?\d+)")


def _partition_interval(fname) -> "tuple[int, int] | None":
    """The [lo_ms, hi_ms) time interval a partition file covers, parsed
    from its ``p<NNNN>[-sig]`` name (partition = dtg // PARTITION_MS, so
    the cache tier's generation buckets align 1:1). None when the name is
    unparsable — the quarantine bump then covers the whole time axis."""
    m = _PART_FILE.match(str(fname))
    if m is None:
        return None
    p = int(m.group(1))
    return (p * PARTITION_MS, (p + 1) * PARTITION_MS)


def _load_v3_type(root: str, name: str, sft: FeatureType, info: dict,
                  health: StoreHealth, on_damage: str) -> list:
    pieces: list = []
    for pkey in sorted(info.get("partitions", {})):
        entry = info["partitions"][pkey]
        if not isinstance(entry, dict):
            entry = {}
        fname = str(entry.get("file", ""))
        if not _SAFE_NAME.match(fname):
            # a torn/hostile manifest entry is ITS OWN damage, contained
            # per-entry like any other: the intact types and partitions
            # must still load (never join paths with an unsafe name)
            if on_damage == "raise":
                raise StoreCorruptionError(
                    f"manifest entry {name}/{pkey} has an unsafe or "
                    f"missing file name: {fname!r}"
                )
            health.damage.append(_quarantine(
                root, name, "", fname or pkey, "manifest",
                f"unsafe or missing file name: {fname!r}",
                _manifest_int(entry.get("rows")),
            ))
            continue
        path = os.path.join(root, name, fname)
        rows = _manifest_int(entry.get("rows"))
        reason, detail = None, ""
        if not os.path.exists(path):
            reason = "missing"
        else:
            # one read serves verification AND unpacking; an OSError here
            # (past retries) is a transient media failure, not damage —
            # propagate rather than quarantining possibly-healthy data
            data = _read_bytes(path)
            if len(data) != _manifest_int(entry.get("bytes"), default=-1):
                reason = "truncated"
            elif (
                hashlib.blake2b(data, digest_size=16).hexdigest()
                != entry.get("checksum")
            ):
                reason = "checksum"
            else:
                try:
                    with np.load(io.BytesIO(data), allow_pickle=False) as z:
                        pieces.append(_unpack_columns(sft, z))
                    continue
                except Exception as e:  # zip/np damage past the checksum
                    reason, detail = "unreadable", f"{type(e).__name__}: {e}"
        if on_damage == "raise":
            raise StoreCorruptionError(
                f"partition {name}/{fname} failed verification ({reason}"
                + (f": {detail}" if detail else "") + ")"
            )
        health.damage.append(
            _quarantine(root, name, path, fname, reason, detail, rows)
        )
    return pieces


def _load_legacy_type(root: str, name: str, sft: FeatureType, info: dict) -> list:
    """The pre-v3 unverified layouts: v2 per-partition files under a
    manifest of content signatures, v1 one npz per type."""
    pieces: list = []
    if "partitions" in info:
        for fname in sorted(info["partitions"]):
            if not _SAFE_NAME.match(fname):
                raise ValueError(f"unsafe partition file name: {fname!r}")
            pieces.append(_load_npz(os.path.join(root, name, fname), sft))
    else:
        pieces.append(_load_npz(os.path.join(root, f"{name}.npz"), sft))
    return pieces


def damage_report(root: str) -> list[dict]:
    """The quarantine log for a store directory (machine-readable; every
    record carries type/file/reason/rows_lost/quarantined_to/time)."""
    report = os.path.join(str(root), QUARANTINE_DIR, "report.json")
    if not os.path.exists(report):
        return []
    with open(report) as fh:
        return json.load(fh).get("damage", [])


# -- column packing ---------------------------------------------------------

def _plain_array(col) -> np.ndarray:
    """npz-safe array: object columns (python strings, possibly None)
    become fixed-width unicode — loading is allow_pickle=False, so an
    object array would fail the round-trip."""
    a = np.asarray(col)
    if a.dtype.kind == "O":
        a = np.array(["" if v is None else str(v) for v in a])
    return a


def _pack_columns(sft: FeatureType, fc: FeatureCollection) -> dict:
    types = {a.name: a.type for a in sft.attributes}
    out: dict = {"__ids__": _plain_array(fc.ids)}
    for name, col in fc.columns.items():
        if isinstance(col, PointColumn):
            out[f"pt:{name}:x"] = col.x
            out[f"pt:{name}:y"] = col.y
        elif isinstance(col, geo.PackedGeometryColumn):
            out[f"pg:{name}:coords"] = col.coords
            out[f"pg:{name}:ring_offsets"] = col.ring_offsets
            out[f"pg:{name}:part_ring_offsets"] = col.part_ring_offsets
            out[f"pg:{name}:geom_part_offsets"] = col.geom_part_offsets
            out[f"pg:{name}:types"] = col.types
            out[f"pg:{name}:bboxes"] = col.bboxes
        elif types.get(name) == "Bytes":
            # variable-length binary: one concatenated buffer + offsets +
            # null mask (str()-ing bytes would corrupt them; a mask keeps
            # None distinct from a genuinely empty payload)
            arr = np.asarray(col)
            vals = [b"" if v is None else bytes(v) for v in arr]
            out[f"by:{name}:data"] = np.frombuffer(
                b"".join(vals), dtype=np.uint8
            )
            out[f"by:{name}:offsets"] = np.cumsum(
                [0] + [len(v) for v in vals]
            ).astype(np.int64)
            out[f"by:{name}:null"] = np.array(
                [v is None for v in arr], dtype=bool
            )
        else:
            out[f"col:{name}"] = _plain_array(col)
    return out


def _unpack_columns(sft: FeatureType, z) -> FeatureCollection:
    cols: dict = {}
    names = set(z.files)
    for attr in sft.attributes:
        n = attr.name
        if f"pt:{n}:x" in names:
            cols[n] = PointColumn(z[f"pt:{n}:x"], z[f"pt:{n}:y"])
        elif f"pg:{n}:coords" in names:
            cols[n] = geo.PackedGeometryColumn(
                coords=z[f"pg:{n}:coords"],
                ring_offsets=z[f"pg:{n}:ring_offsets"],
                part_ring_offsets=z[f"pg:{n}:part_ring_offsets"],
                geom_part_offsets=z[f"pg:{n}:geom_part_offsets"],
                types=z[f"pg:{n}:types"],
                bboxes=z[f"pg:{n}:bboxes"],
            )
        elif f"by:{n}:data" in names:
            data = z[f"by:{n}:data"].tobytes()
            offs = z[f"by:{n}:offsets"]
            null = z[f"by:{n}:null"] if f"by:{n}:null" in names else None
            vals = np.empty(len(offs) - 1, dtype=object)
            vals[:] = [
                None if null is not None and null[i]
                else data[offs[i] : offs[i + 1]]
                for i in range(len(offs) - 1)
            ]
            cols[n] = vals
        elif f"col:{n}" in names:
            cols[n] = z[f"col:{n}"]
        else:
            raise KeyError(f"column {n!r} missing from saved store")
    return FeatureCollection(sft, z["__ids__"], cols)
