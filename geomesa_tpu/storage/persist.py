"""Store persistence: save/load a DataStore's schemas and data to disk.

Reference: the filesystem datastore (geomesa-fs, SURVEY.md §2.4) — a
partition-scheme directory layout of metadata + columnar data files
(/root/reference/geomesa-fs/geomesa-fs-storage/geomesa-fs-storage-common/
src/main/scala/org/locationtech/geomesa/fs/storage/common/partitions/
DateTimeScheme et al., metadata/FileBasedMetadata.scala,
parquet/ParquetFileSystemStorage.scala). Each feature type persists as
.npz column files (the Parquet-file analogue: columnar, compressed):

- atemporal types: one file, ``<type>.npz``;
- types with a time attribute: one file per coarse time partition
  (``<type>/p<NNNN>.npz``, partition = dtg // PARTITION_MS — the
  DateTimeScheme analogue). Saves are INCREMENTAL: a partition whose
  content signature matches the manifest is skipped, so steady-state
  appends rewrite only the partitions they touched (the reference's
  per-partition file writes).

Index tables are rebuilt on load — indexes are derived state, exactly as
the reference rebuilds query state from metadata + files.

Layout:  <root>/metadata.json      (schema specs + partition manifest)
         <root>/<type>.npz         (atemporal)
         <root>/<type>/p<NNNN>.npz (time-partitioned)
"""

from __future__ import annotations

import json
import os

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import PointColumn
from geomesa_tpu.sft import FeatureType

FORMAT_VERSION = 2
PARTITION_MS = 28 * 86_400_000  # ~monthly time partitions (DateTimeScheme)


import hashlib
import re

_SAFE_NAME = re.compile(r"^[A-Za-z0-9_.-]+$")


def _signature(fc: FeatureCollection, idx: np.ndarray) -> str:
    """Cheap content signature of a partition's rows: ids + count. Rows
    are append-only between saves, so (count, id digest) detects any
    membership change; blake2b streams at memory bandwidth. Ids hash in a
    width-independent encoding — the numpy unicode itemsize grows with the
    longest id ANYWHERE in the type, and padding bytes must not change
    untouched partitions' signatures."""
    h = hashlib.blake2b(digest_size=16)
    ids = np.asarray(fc.ids)[idx]
    h.update(str(len(idx)).encode())
    if ids.dtype.kind in ("U", "S", "O"):
        h.update(b"\n".join(str(v).encode("utf-8") for v in ids))
    else:
        h.update(np.ascontiguousarray(ids).tobytes())
    return h.hexdigest()


def _partition_ids(fc: FeatureCollection, dtg_field: str | None) -> np.ndarray:
    if dtg_field is None or len(fc) == 0:
        return np.zeros(len(fc), dtype=np.int64)
    return np.asarray(fc.columns[dtg_field], dtype=np.int64) // PARTITION_MS


def save(store, root: str) -> None:
    """Persist every schema + feature batch under ``root``. Incremental:
    time partitions whose content signature matches the existing manifest
    are not rewritten."""
    os.makedirs(root, exist_ok=True)
    old_manifest: dict = {}
    meta_path = os.path.join(root, "metadata.json")
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as fh:
                old = json.load(fh)
            if old.get("version") == FORMAT_VERSION:
                for t, info in old.get("types", {}).items():
                    old_manifest[t] = info.get("partitions", {})
        except (ValueError, OSError):
            pass
    meta: dict = {"version": FORMAT_VERSION, "types": {}}
    for name in store.type_names():
        if not _SAFE_NAME.match(name):
            raise ValueError(
                f"feature type name {name!r} is not filesystem-safe "
                "([A-Za-z0-9_.-] only) — cannot persist"
            )
        sft = store.get_schema(name)
        info = {
            "spec": sft.to_spec(),
            "user_data": {str(k): str(v) for k, v in sft.user_data.items()},
        }
        fc = store.features(name)
        if sft.dtg_field is None:
            np.savez_compressed(
                os.path.join(root, f"{name}.npz"), **_pack_columns(sft, fc)
            )
        else:
            parts = _partition_ids(fc, sft.dtg_field)
            tdir = os.path.join(root, name)
            os.makedirs(tdir, exist_ok=True)
            manifest: dict = {}
            prev = old_manifest.get(name, {})
            kept: set = set()
            for p in np.unique(parts):
                idx = np.flatnonzero(parts == p)
                sig = _signature(fc, idx)
                fname = f"p{int(p)}.npz"
                kept.add(fname)
                manifest[fname] = sig
                if prev.get(fname) == sig and os.path.exists(
                    os.path.join(tdir, fname)
                ):
                    continue  # unchanged partition: skip the rewrite
                np.savez_compressed(
                    os.path.join(tdir, fname), **_pack_columns(sft, fc.take(idx))
                )
            for stale in set(os.listdir(tdir)) - kept:  # removed partitions
                if stale.endswith(".npz"):
                    os.remove(os.path.join(tdir, stale))
            info["partitions"] = manifest
        meta["types"][name] = info
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(meta, fh, indent=2)
    os.replace(tmp, meta_path)


def load(root: str, **store_kwargs):
    """Rebuild a DataStore (indexes re-derived) from a saved directory.
    Reads both the v2 partitioned layout and the v1 single-file layout."""
    from geomesa_tpu.datastore import DataStore

    with open(os.path.join(root, "metadata.json")) as fh:
        meta = json.load(fh)
    if meta.get("version") not in (1, FORMAT_VERSION):
        raise ValueError(f"unsupported store format {meta.get('version')!r}")
    store = DataStore(**store_kwargs)
    for name, info in meta["types"].items():
        if not _SAFE_NAME.match(name):
            raise ValueError(f"unsafe feature type name in metadata: {name!r}")
        sft = FeatureType.from_spec(name, info["spec"])
        sft.user_data.update(info.get("user_data", {}))
        store.create_schema(sft)
        pieces: list[FeatureCollection] = []
        if "partitions" in info:
            for fname in sorted(info["partitions"]):
                if not _SAFE_NAME.match(fname):
                    raise ValueError(f"unsafe partition file name: {fname!r}")
                with np.load(os.path.join(root, name, fname), allow_pickle=False) as z:
                    pieces.append(_unpack_columns(sft, z))
        else:
            with np.load(os.path.join(root, f"{name}.npz"), allow_pickle=False) as z:
                pieces.append(_unpack_columns(sft, z))
        pieces = [p for p in pieces if len(p)]
        if pieces:
            fc = pieces[0] if len(pieces) == 1 else FeatureCollection.concat(pieces)
            store.write(name, fc, check_ids=False)
    return store


def _plain_array(col) -> np.ndarray:
    """npz-safe array: object columns (python strings, possibly None)
    become fixed-width unicode — loading is allow_pickle=False, so an
    object array would fail the round-trip."""
    a = np.asarray(col)
    if a.dtype.kind == "O":
        a = np.array(["" if v is None else str(v) for v in a])
    return a


def _pack_columns(sft: FeatureType, fc: FeatureCollection) -> dict:
    types = {a.name: a.type for a in sft.attributes}
    out: dict = {"__ids__": _plain_array(fc.ids)}
    for name, col in fc.columns.items():
        if isinstance(col, PointColumn):
            out[f"pt:{name}:x"] = col.x
            out[f"pt:{name}:y"] = col.y
        elif isinstance(col, geo.PackedGeometryColumn):
            out[f"pg:{name}:coords"] = col.coords
            out[f"pg:{name}:ring_offsets"] = col.ring_offsets
            out[f"pg:{name}:part_ring_offsets"] = col.part_ring_offsets
            out[f"pg:{name}:geom_part_offsets"] = col.geom_part_offsets
            out[f"pg:{name}:types"] = col.types
            out[f"pg:{name}:bboxes"] = col.bboxes
        elif types.get(name) == "Bytes":
            # variable-length binary: one concatenated buffer + offsets +
            # null mask (str()-ing bytes would corrupt them; a mask keeps
            # None distinct from a genuinely empty payload)
            arr = np.asarray(col)
            vals = [b"" if v is None else bytes(v) for v in arr]
            out[f"by:{name}:data"] = np.frombuffer(
                b"".join(vals), dtype=np.uint8
            )
            out[f"by:{name}:offsets"] = np.cumsum(
                [0] + [len(v) for v in vals]
            ).astype(np.int64)
            out[f"by:{name}:null"] = np.array(
                [v is None for v in arr], dtype=bool
            )
        else:
            out[f"col:{name}"] = _plain_array(col)
    return out


def _unpack_columns(sft: FeatureType, z) -> FeatureCollection:
    cols: dict = {}
    names = set(z.files)
    for attr in sft.attributes:
        n = attr.name
        if f"pt:{n}:x" in names:
            cols[n] = PointColumn(z[f"pt:{n}:x"], z[f"pt:{n}:y"])
        elif f"pg:{n}:coords" in names:
            cols[n] = geo.PackedGeometryColumn(
                coords=z[f"pg:{n}:coords"],
                ring_offsets=z[f"pg:{n}:ring_offsets"],
                part_ring_offsets=z[f"pg:{n}:part_ring_offsets"],
                geom_part_offsets=z[f"pg:{n}:geom_part_offsets"],
                types=z[f"pg:{n}:types"],
                bboxes=z[f"pg:{n}:bboxes"],
            )
        elif f"by:{n}:data" in names:
            data = z[f"by:{n}:data"].tobytes()
            offs = z[f"by:{n}:offsets"]
            null = z[f"by:{n}:null"] if f"by:{n}:null" in names else None
            vals = np.empty(len(offs) - 1, dtype=object)
            vals[:] = [
                None if null is not None and null[i]
                else data[offs[i] : offs[i + 1]]
                for i in range(len(offs) - 1)
            ]
            cols[n] = vals
        elif f"col:{n}" in names:
            cols[n] = z[f"col:{n}"]
        else:
            raise KeyError(f"column {n!r} missing from saved store")
    return FeatureCollection(sft, z["__ids__"], cols)
