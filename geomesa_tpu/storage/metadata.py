"""Catalog metadata: a small KV tier with caching.

Reference: GeoMesaMetadata / TableBasedMetadata (/root/reference/
geomesa-index-api/src/main/scala/org/locationtech/geomesa/index/metadata/
GeoMesaMetadata.scala, TableBasedMetadata.scala) — every store keeps a
per-catalog key-value table of schema specs, user data, table names and
stats, fronted by an expiring read cache with explicit invalidation.

Here the same contract has two backends: in-memory (the default in-process
store) and file-backed (one file per key under a directory, atomic
replace writes — the FileBasedMetadata analogue used by persistence), both
behind a read cache."""

from __future__ import annotations

import os
import re
from typing import Iterator, Optional, Protocol, runtime_checkable

from geomesa_tpu.fault import atomic_write, with_retries

_SAFE_KEY = re.compile(r"^[A-Za-z0-9_.~/-]+$")


@runtime_checkable
class Metadata(Protocol):
    """The GeoMesaMetadata contract (get/insert/remove/scan + cache
    control)."""

    def get(self, key: str) -> Optional[str]: ...

    def insert(self, key: str, value: str) -> None: ...

    def remove(self, key: str) -> None: ...

    def scan(self, prefix: str) -> Iterator[tuple[str, str]]: ...

    def invalidate(self) -> None: ...


class InMemoryMetadata:
    """Dict-backed catalog (the in-process default; reference
    InMemoryMetadata used by TestGeoMesaDataStore)."""

    def __init__(self):
        self._kv: dict[str, str] = {}

    def get(self, key: str) -> Optional[str]:
        return self._kv.get(key)

    def insert(self, key: str, value: str) -> None:
        self._kv[key] = str(value)

    def remove(self, key: str) -> None:
        self._kv.pop(key, None)

    def scan(self, prefix: str):
        for k in sorted(self._kv):
            if k.startswith(prefix):
                yield k, self._kv[k]

    def invalidate(self) -> None:
        pass


class FileMetadata:
    """One file per key under ``root`` with atomic-replace writes (the
    FileBasedMetadata analogue). Keys may contain '/' (subdirectories);
    every path segment is validated filesystem-safe."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        if not _SAFE_KEY.match(key) or ".." in key.split("/"):
            raise ValueError(f"metadata key {key!r} is not filesystem-safe")
        return os.path.join(self.root, *key.split("/"))

    def get(self, key: str) -> Optional[str]:
        try:
            with open(self._path(key)) as fh:
                return fh.read()
        except FileNotFoundError:
            return None

    def insert(self, key: str, value: str) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)

        # same durability discipline as the persist tier (fault.
        # atomic_write), retried on transient IO faults — a crashed
        # insert leaves the old value, never a torn file
        with_retries(
            lambda: atomic_write(
                path, str(value).encode("utf-8"), point="metadata"
            )
        )

    def remove(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def scan(self, prefix: str):
        for dirpath, _dirs, files in sorted(os.walk(self.root)):
            for f in sorted(files):
                if f.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, f), self.root)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    with open(os.path.join(dirpath, f)) as fh:
                        yield key, fh.read()

    def invalidate(self) -> None:
        pass


class CachedMetadata:
    """Read-through cache over any Metadata backend (the TableBasedMetadata
    caching tier): reads hit the cache, writes update both, ``invalidate``
    drops the cache so external changes become visible."""

    def __init__(self, backend: Metadata):
        self.backend = backend
        self._cache: dict[str, Optional[str]] = {}

    def get(self, key: str) -> Optional[str]:
        if key not in self._cache:
            self._cache[key] = self.backend.get(key)
        return self._cache[key]

    def insert(self, key: str, value: str) -> None:
        self.backend.insert(key, value)
        self._cache[key] = str(value)

    def remove(self, key: str) -> None:
        self.backend.remove(key)
        self._cache[key] = None

    def scan(self, prefix: str):
        # scans always hit the backend (prefix coverage of the cache is
        # unknowable); individual results refresh the cache
        for k, v in self.backend.scan(prefix):
            self._cache[k] = v
            yield k, v

    def invalidate(self) -> None:
        self._cache.clear()
        self.backend.invalidate()
