"""IndexTable: one index's sorted, device-resident columnar table.

The reference materializes each index as a sorted KV table (Accumulo/HBase
tablets; write path Z3IndexKeySpace.toIndexKey + IndexWriter, /root/
reference/geomesa-index-api/src/main/scala/org/locationtech/geomesa/index/
z3/Z3IndexKeySpace.scala:63-95). Here the same logical layout is a
struct-of-arrays table sorted lexicographically by (bin, z):

- host side: the sort keys (bins i32, zs u64), per-bin segment offsets, and
  the permutation back to the backing FeatureCollection — used for
  range -> row-span -> block pruning (the analogue of seeking scan ranges
  in a tablet server). The sort itself is the native radix argsort
  (geomesa_tpu.native.sort_bins_z) — the LSM "flush" hot path;
- device side: the predicate columns, laid out [n_blocks, SUB, 128]
  (BLOCK = SUB*128 rows) so candidate blocks DMA straight into VMEM for
  the Pallas bitmask kernel (geomesa_tpu.scan.block_kernels). Pad rows
  carry never-matching sentinels.

Query execution (round-3 redesign, see PERF.md): ONE device call + ONE
batched pull per query. The host turns covering z-ranges into row spans
(searchsorted) and block ids; rows in *contained* ranges (reference
ZN.zranges contained semantics, ZN.scala:110-242 — classified here against
shrunk inner ordinals so containment is exact at f64) are taken from the
spans directly with no device work and no refinement; remaining blocks go
through the kernel, which returns wide + inner bit planes. Host refinement
then touches only `wide & ~inner` boundary rows.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.index.api import IndexKeySpace, ScanConfig, WriteKeys
from geomesa_tpu.planning.errors import check_deadline
from geomesa_tpu.scan import block_kernels as bk


_SENTINELS = {
    "x": np.float32(np.inf),
    "y": np.float32(np.inf),
    "gxmin": np.float32(np.inf),
    "gymin": np.float32(np.inf),
    "gxmax": np.float32(-np.inf),
    "gymax": np.float32(-np.inf),
    "tbin": np.int32(-1),
    "toff": np.int32(0),
    "tw": np.int32(-1),  # packed-time: bin -1 never matches
}

# Canonical fused-dispatch shape (scan_submit_many): every multi-member
# chunk pads its slot list to EXACTLY the table's ``fused_slots`` and its
# param stacks to FUSED_CHUNK_Q, so there is ONE fused kernel variant per
# (projected columns, predicate flags) — compiled at warmup, zero
# query-time recompiles (the same doctrine as the single-query M-bucket
# ladder). ``fused_slots`` is FUSED_CHUNK_SLOTS clamped down to the
# table's own block-count bucket: the kernel's scan cost is proportional
# to slots whether they are real or pads, so a 123-block table padding to
# 2048 slots would scan 16x its own size per dispatch (the serving
# bench's CPU regression at 32 clients). The fixed size also bounds
# device memory: plane bytes — and, on the XLA fallback, the column
# gathers — scale with the chunk's slot count, not the whole batch.
# 2048 slots = 4.2M rows per dispatch at the default tile; greedy packing
# keeps pad waste small, and members broader than half a chunk take the
# single-query ladder instead. A table growing past its block-count
# bucket compiles the next fused shape on first use — the same
# growth-triggered compile the single-query ladder already has (new
# buckets past warmup's table size), softened by the persistent compile
# cache; re-run warmup() after major growth to take it off the hot path.
FUSED_CHUNK_SLOTS = 2048
FUSED_CHUNK_Q = 128


def _block_rows(tile: "int | None") -> int:
    """Rows per device scan block for a ``tile`` request (the ONE rounding
    rule, shared by IndexTable.__init__ and the fold-plan eligibility
    check so they can never drift)."""
    return bk.BLOCK if tile is None else max(4096, -(-int(tile) // 4096) * 4096)


def _device_fold_enabled() -> bool:
    """Whether folded_table may build device columns through the
    device-side fold plan (geomesa.stream.fold.device). 'on' forces it;
    'auto' (the default) uses it only on a TPU backend, where the
    O(touched)-vs-O(table) LINK transfer is the cost that matters — on
    the CPU backend every "transfer" is a memcpy, while the plan's
    eager device ops re-specialize per slice shape, so the host
    gather + upload path is strictly faster there (measured: ~3x lower
    slice pause on the CPU stream bench)."""
    import jax

    from geomesa_tpu.conf import STREAM_FOLD_DEVICE

    mode = str(STREAM_FOLD_DEVICE.get()).lower()
    if mode in ("on", "1", "true"):
        return True
    return mode == "auto" and jax.default_backend() == "tpu"


class SortedKeys:
    """Host-side sorted key structure shared by the single-device and
    distributed tables: the (bin, z) lexicographic sort, the permutation
    back to feature ordinals, and searchsorted range -> row-span pruning
    (the analogue of seeking scan ranges in a tablet server)."""

    def __init__(
        self,
        keyspace: IndexKeySpace,
        keys: WriteKeys,
        tile: int,
        sorted_state: "np.ndarray | None" = None,
    ):
        self.keyspace = keyspace
        self.tile = tile
        n = len(keys.bins)
        self.n = n

        if sorted_state is not None:
            # the caller already knows the sort order (merge compaction:
            # storage.table.merged_table) — skip the radix sort entirely
            perm = sorted_state
            self.rows_sorted = 0
        elif keys.sub is not None:
            # secondary sort words (string attribute indexes): full
            # lexicographic (bin, z, sub[0], ..., sub[W-1]) order so
            # z-tie runs stay value-sorted and candidate_spans can narrow
            # boundary runs (np.lexsort: LAST key is most significant)
            sub_keys = tuple(
                keys.sub[:, j] for j in range(keys.sub.shape[1] - 1, -1, -1)
            )
            perm = np.lexsort(sub_keys + (keys.zs, keys.bins))
            self.rows_sorted = n
        else:
            from geomesa_tpu import native

            perm = native.sort_bins_z(keys.bins, keys.zs)
            if perm is None:
                perm = np.lexsort((keys.zs, keys.bins))
            self.rows_sorted = n
        self.perm = perm  # table row -> feature ordinal (u32 or i64)
        self.bins = _take(keys.bins, perm)
        self.zs = _take(keys.zs, perm)
        self.subkeys = keys.sub[perm] if keys.sub is not None else None  # [n, W]

        # per-bin segments for searchsorted pruning. self.bins is sorted
        # (it IS the primary sort key), so the segment boundaries come
        # from one linear diff pass — np.unique's O(n log n) sort here
        # was a measurable slice of every table build (the round-11 fold
        # profile: ~60 ms per 3M-row build, x2 indexes x slices)
        if n:
            starts = np.concatenate([
                [0], np.flatnonzero(self.bins[1:] != self.bins[:-1]) + 1
            ])
            self.ubins = self.bins[starts]
        else:
            starts = np.zeros(0, np.int64)
            self.ubins = self.bins[:0]
        self.bin_starts = np.append(starts, n).astype(np.int64)

    def _narrow_lo(self, a: int, ae: int, words: np.ndarray) -> int:
        """First row >= the bound within the primary tie-run [a, ae):
        descend word by word — rows below the word are dropped, the
        word-tie run recurses, and final-level ties stay included."""
        for j in range(self.subkeys.shape[1]):
            if ae <= a:
                return a
            col = self.subkeys[a:ae, j]
            w = words[j] if j < len(words) else 0
            left = a + int(np.searchsorted(col, w, side="left"))
            right = a + int(np.searchsorted(col, w, side="right"))
            if right <= left:
                return left  # no exact ties at this word: done
            a, ae = left, right
        return a

    def _narrow_hi(self, hs: int, z: int, words: np.ndarray) -> int:
        """One past the last row <= the bound within the primary tie-run
        [hs, z): rows below the word are kept whole, the word-tie run
        recurses, rows above are dropped."""
        U64 = np.uint64(0xFFFFFFFFFFFFFFFF)
        for j in range(self.subkeys.shape[1]):
            if z <= hs:
                return z
            col = self.subkeys[hs:z, j]
            w = words[j] if j < len(words) else U64
            left = hs + int(np.searchsorted(col, w, side="left"))
            right = hs + int(np.searchsorted(col, w, side="right"))
            if right <= left:
                return left  # everything below the word is included
            hs, z = left, right
        return z

    def pad_cols(self, keys: WriteKeys, n_pad: int) -> dict:
        """Sorted device columns padded to n_pad rows with never-matching
        sentinels."""
        cols = {}
        for name, col in keys.device_cols.items():
            out = np.full(n_pad, _SENTINELS[name], dtype=col.dtype)
            out[: self.n] = _take(col, self.perm)
            cols[name] = out
        return cols

    # -- pruning ---------------------------------------------------------
    def candidate_spans(self, config: ScanConfig) -> list[tuple[int, int]]:
        """Merged, sorted row spans [lo, hi) covering ALL scan ranges
        (contained + overlapping) — the cost estimator's input."""
        overlap, contained = self.candidate_spans_split(config)
        return _merge_spans(overlap + contained)

    def candidate_spans_split(self, config: ScanConfig):
        """(overlap_spans, contained_spans): row spans [lo, hi) of the
        non-contained vs contained scan ranges. Contained ranges' rows are
        certain hits (no device predicate, no refinement) when
        ``config.contained_exact`` — otherwise they are folded into the
        overlap set by the caller."""
        cont_flags = config.range_contained
        use_contained = config.contained_exact and cont_flags is not None
        overlap: list[tuple[int, int]] = []
        contained: list[tuple[int, int]] = []
        for b in np.unique(config.range_bins):
            i = int(np.searchsorted(self.ubins, b))
            if i >= len(self.ubins) or self.ubins[i] != b:
                continue
            s, e = int(self.bin_starts[i]), int(self.bin_starts[i + 1])
            sel = config.range_bins == b
            seg = self.zs[s:e]
            lo = np.searchsorted(seg, config.range_lo[sel], side="left") + s
            hi = np.searchsorted(seg, config.range_hi[sel], side="right") + s
            if self.subkeys is not None and config.range_lo2 is not None:
                # narrow each range's boundary TIE-RUNS by the secondary
                # sort words: rows sharing the lo (hi) primary code are
                # value-sorted by the word columns, so long-string bounds
                # prune exactly past the 8-byte prefix (VERDICT r4 weak
                # #4; ties at every word stay INCLUDED — superset, host
                # refinement is exact)
                lo_end = np.searchsorted(seg, config.range_lo[sel], side="right") + s
                hi_start = np.searchsorted(seg, config.range_hi[sel], side="left") + s
                lo2 = config.range_lo2[sel]
                hi2 = config.range_hi2[sel]
                for k in range(len(lo)):
                    lo[k] = self._narrow_lo(int(lo[k]), int(lo_end[k]), lo2[k])
                    hi[k] = self._narrow_hi(int(hi_start[k]), int(hi[k]), hi2[k])
            if use_contained:
                cf = cont_flags[sel]
            else:
                cf = np.zeros(int(sel.sum()), dtype=bool)
            for a, z, c in zip(lo.tolist(), hi.tolist(), cf.tolist()):
                if z > a:
                    (contained if c else overlap).append((a, z))
        return _merge_spans(overlap), _merge_spans(contained)

def _take(col: np.ndarray, perm: np.ndarray) -> np.ndarray:
    from geomesa_tpu import native

    if perm.dtype == np.uint32:
        out = native.take(col, perm)
        if out is not None:
            return out
    return col[perm]


def _merge_spans(spans: list[tuple[int, int]]) -> list[tuple[int, int]]:
    if not spans:
        return []
    spans = sorted(spans)
    merged = [spans[0]]
    for a, z in spans[1:]:
        if a <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], z))
        else:
            merged.append((a, z))
    return merged


def _span_rows(spans: list[tuple[int, int]]) -> np.ndarray:
    if not spans:
        return np.zeros(0, np.int64)
    return np.concatenate([np.arange(a, z, dtype=np.int64) for a, z in spans])


def _merge_sorted_rows(cont_rows: np.ndarray, kr: np.ndarray, kc: np.ndarray):
    """Merge two ascending row runs — contained rows (all certain) and
    kernel rows with their certainty — in O(n) via the positional two-run
    merge (an argsort over the concatenation costs n log n and dominated
    large-query latency; see PERF.md)."""
    nm, nd = len(cont_rows), len(kr)
    if nd == 0:
        return cont_rows, np.ones(nm, bool)
    if nm == 0:
        return kr, kc
    pos = np.searchsorted(cont_rows, kr)
    kr_dest = pos + np.arange(nd, dtype=np.int64)
    cont_dest = np.arange(nm, dtype=np.int64) + np.searchsorted(
        pos, np.arange(nm, dtype=np.int64), side="right"
    )
    rows = np.empty(nm + nd, np.int64)
    certain = np.empty(nm + nd, bool)
    rows[cont_dest] = cont_rows
    certain[cont_dest] = True
    rows[kr_dest] = kr
    certain[kr_dest] = kc
    return rows, certain


def _spans_intersect(rng: tuple[int, int], spans: list[tuple[int, int]]) -> bool:
    """True when [rng.lo, rng.hi) intersects any [lo, hi) span."""
    lo, hi = rng
    for a, z in spans:
        if a < hi and z > lo:
            return True
    return False


def _rows_in_spans(rows: np.ndarray, spans: list[tuple[int, int]]) -> np.ndarray:
    """Boolean mask: which sorted ``rows`` fall inside any [lo, hi) span."""
    if not spans or len(rows) == 0:
        return np.zeros(len(rows), dtype=bool)
    los = np.array([s[0] for s in spans], dtype=np.int64)
    his = np.array([s[1] for s in spans], dtype=np.int64)
    idx = np.searchsorted(los, rows, side="right") - 1
    ok = idx >= 0
    return ok & (rows < his[np.clip(idx, 0, len(his) - 1)])


class IndexTable(SortedKeys):
    """Sorted columnar table for one (feature type, index) pair.

    This class is the WHOLE scan engine: subclasses (the distributed table,
    parallel.dtable) override only the device hooks — ``_round_blocks`` /
    ``_place_cols`` for layout and ``_device_scan`` / ``_device_pops`` /
    ``_device_density_submit`` / ``_device_bounds`` for execution — so the
    single-chip and multi-chip paths share one pruning + exactness-tier +
    decode pipeline (the reference runs the same coprocessor push-down on
    every region server, geomesa-hbase-rpc/.../GeoMesaCoprocessor.scala:
    28-79; rounds 2-3 had diverging engines, VERDICT r3 #1).
    """

    def __init__(
        self,
        keyspace: IndexKeySpace,
        keys: WriteKeys,
        tile: int | None = None,
        device=None,
        sorted_state: "np.ndarray | None" = None,
        reuse: "tuple[IndexTable, int] | None" = None,
        fold_plan: "tuple | None" = None,
    ):
        # device scan granularity: BLOCK rows (Pallas layout constraint:
        # SUB multiple of 32 sublanes); `tile` requests are rounded up
        block = _block_rows(tile)
        super().__init__(keyspace, keys, block, sorted_state=sorted_state)
        self.block = block
        self.sub = block // bk.LANES

        import geomesa_tpu

        geomesa_tpu.enable_compile_cache()
        n_blocks = self._round_blocks(max(1, -(-self.n // block)))
        self.n_blocks = n_blocks
        self.n_pad = n_blocks * block
        self.col_names = tuple(sorted(keys.device_cols))
        self.extent = "gxmin" in keys.device_cols
        # projection accounting for the most recent kernel call
        self.last_scan_cols: tuple = ()
        self.last_scan_bytes = 0
        # ``reuse``: (old table, first changed sorted row) — merge
        # compaction keeps every device block before the first insertion
        # point and uploads only the changed suffix
        self._reuse = reuse
        if (
            fold_plan is not None
            and type(self)._place_cols is IndexTable._place_cols
        ):
            # device-side fold plan (round 11, docs/streaming.md
            # "Incremental fold"): the folded columns are computed ON
            # DEVICE from the old table's resident blocks plus an
            # O(touched) upload, instead of re-gathering and re-uploading
            # the O(table) sorted suffix over the link
            self._fold_cols_device(fold_plan, device)
        elif type(self)._place_cols is IndexTable._place_cols:
            # bounded-memory build: sort-gather each column in
            # block-aligned spans and upload it before touching the next —
            # host peak is ONE padded column, never a second full copy of
            # the column set (the 1B compaction OOM; docs/ingest.md)
            self._stream_cols(keys, device)
        else:
            # subclasses (the distributed table) own their layout via the
            # whole-dict hook; they get the classic padded column set
            self._place_cols(self.pad_cols(keys, self.n_pad), device)

    # -- layout hooks ----------------------------------------------------
    def _round_blocks(self, n_blocks: int) -> int:
        """Block-count rounding hook (the distributed table rounds up to a
        multiple of the mesh size)."""
        return n_blocks

    # per-table probed slot cap: pod host groups stamp one per host shard
    # so a slow host's bigger amortization bucket stays its own (None =
    # the process-wide link constants)
    _slot_cap: "int | None" = None

    @property
    def fused_slots(self) -> int:
        """Slot count of THIS table's canonical fused-dispatch shape:
        FUSED_CHUNK_SLOTS clamped down to the table's own block-count
        bucket (see the constants' doctrine note) — still one static
        shape per (columns, flags), but a small table never scans a
        multiple of its own size in pad slots. For the distributed table
        this is the PER-DEVICE slot bucket. The cap itself is
        link-derived (bk.fused_slot_cap: the hand-tuned 2048 on the 66 ms
        design link, smaller on a measured fast link — bench.py installs
        the probe-derived constants before warmup, per host via
        ``_slot_cap`` under a pod host group)."""
        return min(bk.fused_slot_cap(self._slot_cap), bk.bucket_of(self.n_blocks))

    @property
    def fused_pack_capacity(self) -> int:
        """Candidate-block capacity the chunk packer fills per fused
        chunk. Equal to ``fused_slots`` on a single-device table; the
        distributed table multiplies by the mesh size (its candidates
        split round-robin across devices, each padded to ``fused_slots``
        local slots)."""
        return self.fused_slots

    def _fused_supported(self) -> bool:
        """Whether scan_submit_many may dispatch fused chunks on this
        table: true for the base engine, and for subclasses that override
        the device seam ONLY IF they also provide their own
        ``_submit_fused_chunk`` (DistributedIndexTable's shard_map fused
        dispatch) — otherwise the fused kernel would silently bypass the
        subclass's device hooks."""
        return (
            type(self)._device_scan_submit is IndexTable._device_scan_submit
            or type(self)._submit_fused_chunk is not IndexTable._submit_fused_chunk
        )

    def _reuse_prefix(self, col_names) -> tuple:
        """(old table, first reusable block count) from ``self._reuse``,
        or (None, 0) when nothing can be reused."""
        if self._reuse is not None:
            cand, first_row = self._reuse
            if cand.block == self.block and set(cand.col_names) == set(col_names):
                return cand, min(
                    first_row // self.block, cand.n_blocks, self.n_blocks
                )
        return None, 0

    def _place_cols(self, cols: dict, device) -> None:
        """Put the padded columns on device in the [n_blocks, SUB, 128]
        scan layout. With ``self._reuse`` set, device blocks before the
        first changed row are taken from the old table (prefix rows are
        byte-identical) and only the suffix is uploaded."""
        import jax
        import jax.numpy as jnp

        old, first_block = self._reuse_prefix(set(cols))
        self.rows_uploaded = (self.n_blocks - first_block) * self.block
        self.cols3 = {}
        for k, v in cols.items():
            v3 = v.reshape(self.n_blocks, self.sub, bk.LANES)
            if old is not None and first_block > 0:
                suffix = jax.device_put(v3[first_block:], device) if device else jax.device_put(v3[first_block:])
                self.cols3[k] = jnp.concatenate([old.cols3[k][:first_block], suffix])
            else:
                self.cols3[k] = jax.device_put(v3, device) if device else jax.device_put(v3)

    def _stream_cols(self, keys: WriteKeys, device) -> None:
        """Bounded-memory `_place_cols`: build and upload the sorted
        padded columns ONE AT A TIME, gathering each through block-aligned
        spans of ``geomesa.tpu.compact.span.rows`` rows, and release the
        host copy before the next column starts. The classic path
        materialized every sorted column simultaneously — at 1B rows that
        is a second full copy of the column set next to the unsorted
        source, which OOM'd a 125 GB host (ISSUE 4; docs/ingest.md).
        Keeps the merge-compaction suffix reuse: with ``self._reuse`` set,
        only rows past the first changed block are gathered/uploaded."""
        import jax
        import jax.numpy as jnp

        from geomesa_tpu.conf import COMPACT_SPAN_ROWS

        old, first_block = self._reuse_prefix(set(keys.device_cols))
        self.rows_uploaded = (self.n_blocks - first_block) * self.block
        lo = first_block * self.block  # first sorted row to (re)build
        span = max(self.block, (COMPACT_SPAN_ROWS.get() // self.block) * self.block)
        self.cols3 = {}
        for k in self.col_names:
            col = keys.device_cols[k]
            out = np.empty(self.n_pad - lo, dtype=col.dtype)
            for s in range(lo, self.n, span):
                e = min(s + span, self.n)
                out[s - lo : e - lo] = _take(col, self.perm[s:e])
            out[self.n - lo :] = _SENTINELS[k]  # pad rows never match
            v3 = out.reshape(self.n_blocks - first_block, self.sub, bk.LANES)
            suffix = jax.device_put(v3, device) if device else jax.device_put(v3)
            if old is not None and first_block > 0:
                self.cols3[k] = jnp.concatenate(
                    [old.cols3[k][:first_block], suffix]
                )
            else:
                self.cols3[k] = suffix
            del out, v3, suffix

    def _fold_cols_device(self, plan, device) -> None:
        """Fold-plan device build (round 11): the new sorted columns are a
        pure permutation of the OLD table's device-resident rows plus the
        delta's — so instead of host-gathering and uploading the changed
        O(table) suffix (``_stream_cols``), ship only the fold's
        *description* (removed sorted positions, insert destinations, the
        delta's sorted rows — all O(touched)) and let the device compute
        each new row's source:

        - a non-insert destination ``i`` holds survivor rank
          ``r = i - #inserts<=i``; its OLD sorted position solves
          ``p = r + #removed<=p`` via one searchsorted over
          ``removed - arange`` (survivors-before-each-removal, a
          non-decreasing key);
        - an insert destination takes its value from the uploaded sorted
          delta rows;
        - pad rows past ``self.n`` take the never-matching sentinels.

        One gather per column over HBM — fold-time cost, never on the
        query path (the "no gathers" doctrine in scan/block_kernels.py
        guards kernels, not maintenance). Bit-identical to the host
        rebuild: every value is a copy of an old-table or delta value
        (tests/test_streaming_tier.py pins cols3 equality both ways).
        ``rows_uploaded`` records the rows that actually crossed the
        link — the fold's O(touched) claim, surfaced by the bench."""
        import jax
        import jax.numpy as jnp

        old, removed, delta_dest, delta_sorted_cols = plan
        nr, nd = len(removed), len(delta_dest)
        # i32 position math: the fold plan is gated to < 2**31 padded rows
        # (the u32-perm regime; the 1B single-chip layout is well inside)
        i = jnp.arange(self.n_pad, dtype=jnp.int32)
        if nd:
            dd = jnp.asarray(np.asarray(delta_dest, np.int32))
            k_ins = jnp.searchsorted(dd, i, side="right").astype(jnp.int32)
            is_ins = (k_ins > 0) & (dd[jnp.clip(k_ins - 1, 0, nd - 1)] == i)
            ins_idx = jnp.clip(k_ins - 1, 0, nd - 1)
        else:
            k_ins = jnp.zeros(self.n_pad, jnp.int32)
            is_ins = None
            ins_idx = None
        r = i - k_ins
        if nr:
            rem_adj = jnp.asarray(
                np.asarray(removed, np.int64) - np.arange(nr, dtype=np.int64)
            ).astype(jnp.int32)
            src = r + jnp.searchsorted(rem_adj, r, side="right").astype(jnp.int32)
        else:
            src = r
        src = jnp.clip(src, 0, max(old.n_pad - 1, 0))
        valid = i < self.n
        self.rows_uploaded = nd  # only the delta rows cross the link
        self.cols3 = {}
        for k in self.col_names:
            old_flat = old.cols3[k].reshape(-1)
            vals = jnp.take(old_flat, src)
            if is_ins is not None:
                dcol = np.asarray(delta_sorted_cols[k])
                dvals = jax.device_put(dcol, device) if device else jnp.asarray(dcol)
                vals = jnp.where(is_ins, jnp.take(dvals, ins_idx), vals)
            vals = jnp.where(valid, vals, _SENTINELS[k].astype(vals.dtype))
            self.cols3[k] = vals.reshape(self.n_blocks, self.sub, bk.LANES)

    # -- scanning --------------------------------------------------------
    def candidate_blocks(self, spans: list[tuple[int, int]]) -> np.ndarray:
        if not spans:
            return np.zeros(0, np.int64)
        ids = [
            np.arange(a // self.block, (z - 1) // self.block + 1, dtype=np.int64)
            for a, z in spans
        ]
        return np.unique(np.concatenate(ids))

    def scan(self, config: ScanConfig, deadline=None) -> tuple[np.ndarray, np.ndarray]:
        """One-call device scan. Returns (ordinals, certain):

        - ``ordinals``: feature ordinals of all candidate hits, ascending in
          table order (wide predicate — a superset of true hits only where
          ``certain`` is False);
        - ``certain``: per-row True when the row is a guaranteed f64-exact
          hit of the index's spatial/temporal constraint (inner predicate or
          contained range) — the planner refines only the rest.

        ``deadline``: optional ``time.monotonic()`` cutoff; the scan checks
        it at stage boundaries and raises QueryTimeout when overdue
        (reference ThreadManagement scan timeouts).
        """
        return self.scan_submit(config, deadline=deadline)()

    def scan_submit(self, config: ScanConfig, deadline=None):
        """Pipelined form of :meth:`scan`: dispatch the device work NOW,
        return a zero-arg ``finish()`` producing (ordinals, certain).

        jax dispatch is asynchronous — submitting several queries' kernels
        before pulling any result overlaps their device work and hides the
        per-pull link latency behind computation (DataStore.query_many).
        """
        if config.disjoint or self.n == 0:
            return lambda: (np.zeros(0, np.int64), np.zeros(0, bool))
        check_deadline(deadline, "range pruning")
        overlap, contained = self.candidate_spans_split(config)
        has_pred = config.boxes is not None or config.windows is not None

        if not has_pred:
            # pure range scan (attribute index primary): spans are row-exact
            cont_rows = _span_rows(contained)
            rows = np.union1d(_span_rows(overlap), cont_rows) if overlap else cont_rows
            out = (self.perm[rows].astype(np.int64), np.ones(len(rows), bool))
            return lambda: out

        blocks = self.candidate_blocks(overlap)
        if len(blocks) == 0:
            cont_rows = _span_rows(contained)
            out = (self.perm[cont_rows].astype(np.int64), np.ones(len(cont_rows), bool))
            return lambda: out

        check_deadline(deadline, "device scan dispatch")
        return self._make_finish(
            self._device_scan_submit(blocks, config), config, overlap, contained, deadline
        )

    def _make_finish(self, finish_device, config, overlap, contained, deadline):
        """finish() closure over a dispatched device scan: decode +
        _post_decode. Shared by scan_submit and scan_submit_many's
        single-member groups so the two can never drift."""

        def finish() -> tuple[np.ndarray, np.ndarray]:
            rows, certain = finish_device()
            check_deadline(deadline, "bitmask decode")
            return self._post_decode(rows, certain, config, overlap, contained)

        return finish

    def _post_decode(self, rows, certain, config, overlap, contained):
        """Decoded kernel rows -> (feature ordinals, certain): span
        clipping, contained-span union (all certain; native two-pointer
        dedup when available), permutation to feature ordinals. Shared by
        the per-query and fused scan paths."""
        if config.clip_rows:
            keep = _rows_in_spans(rows, _merge_spans(overlap + contained))
            rows, certain = rows[keep], certain[keep]
        if contained:
            from geomesa_tpu import native

            merged = native.merge_rows_spans(contained, rows, certain)
            if merged is not None:
                rows, certain = merged
            else:
                dup = _rows_in_spans(rows, contained)
                rows, certain = _merge_sorted_rows(
                    _span_rows(contained), rows[~dup], certain[~dup]
                )
        return self.perm[rows].astype(np.int64), certain

    def scan_submit_many(self, configs: list, deadline=None):
        """Fused form of :meth:`scan_submit` for MANY queries (round 5):
        groups eligible configs by kernel variant and dispatches ONE fused
        kernel per chunk (`bk.block_scan_multi`, every chunk padded to the
        canonical FUSED_CHUNK_SLOTS x FUSED_CHUNK_Q shape) instead of one
        dispatch per query — slot i of the fused grid scans block bids[i]
        with query qids[i]'s params. Returns one
        ``finish() -> (ordinals, certain)`` PER config, in input order;
        a chunk's planes pull once (on its first member's finish) but each
        member decodes lazily, so callers that discard some results (kNN's
        speculative wide windows) never pay their decode.

        Per-query dispatch overhead (~2 ms submit + serialized kernel
        launches) dominated many-small-query workloads: the indexed
        spatial join's 256 per-polygon scans spent ~2.1 s of which <10 ms
        was host refinement (BENCH_ALL_r05 config 4). Round 6 widened
        eligibility to EVERY kernel-backed config: polygon-INTERSECTS
        members fuse through the chunk's [Q, E, 128] edge stack (the
        device PIP tier, selected per slot), extent/XZ members fuse on
        their wide-only plane, and the distributed table dispatches the
        whole chunk under shard_map. Only pure range scans (row-exact, no
        kernel) and empty/disjoint configs fall back to
        :meth:`scan_submit` per query, still dispatched before any pull.

        This is the TPU shape of the reference's server-side batch scans
        (geomesa-utils/.../utils/AbstractBatchScan.scala threads one
        range per pooled scanner; geomesa-hbase/.../HBaseQueryPlan.scala:
        43-54 fans ranges over CachedThreadPool): instead of threads
        hiding per-range latency, one kernel grid scans every (query,
        block) slot and the host decodes per-query segments.
        """
        if not self._fused_supported():
            # subclass re-routes the device seam without providing its own
            # fused chunk dispatch: the fused kernel would bypass the seam
            # — keep per-query dispatches, still pipelined
            return [self.scan_submit(c, deadline=deadline) for c in configs]

        n_q = len(configs)
        finishes: list = [None] * n_q
        # groups: variant key -> [(j, config, bids_padded?, ...)]
        groups: dict[tuple, list] = {}
        for j, config in enumerate(configs):
            if config.disjoint or self.n == 0:
                out = (np.zeros(0, np.int64), np.zeros(0, bool))
                finishes[j] = lambda out=out: out
                continue
            check_deadline(deadline, "range pruning")
            has_pred = config.boxes is not None or config.windows is not None
            if not has_pred:
                # pure range scans (attribute-index primaries) keep the
                # per-query path: spans are row-exact, no kernel runs.
                # PIP-edge polygon configs FUSE (round 6): their chunks
                # carry a [Q, E, 128] edge stack and a per-slot selector,
                # grouped per E bucket so polygon batches share dispatches
                # without taxing box chunks with edge work
                finishes[j] = self.scan_submit(config, deadline=deadline)
                continue
            overlap, contained = self.candidate_spans_split(config)
            blocks = self.candidate_blocks(overlap)
            if len(blocks) == 0:
                cont_rows = _span_rows(contained)
                out = (self.perm[cont_rows].astype(np.int64), np.ones(len(cont_rows), bool))
                finishes[j] = lambda out=out: out
                continue
            blocks = self._full_or(blocks)
            names = self._scan_cols(config)
            # the E and R buckets are part of the variant key: box
            # queries group at E = R = 0 (their slots keep the round-5
            # zero-edge kernel cost and the Pallas path), polygons group
            # per fused bucket — a 256-edge member must not inflate
            # every box slot to 256-edge PIP work, nor demote the chunk
            # past PALLAS_MAX_EDGES/RINTS to the XLA variant, just to
            # share one dispatch
            e_bucket = (
                0 if self.extent
                else bk.fused_e_bucket(bk.n_edges_of(config.poly))
            )
            r_bucket = (
                0 if self.extent
                else bk.fused_r_bucket(bk.n_rints_of(config.rast))
            )
            key = (
                names, config.boxes is not None, config.windows is not None,
                e_bucket, r_bucket,
            )
            groups.setdefault(key, []).append((j, config, blocks, overlap, contained))

        slots = self.fused_pack_capacity
        for (names, has_boxes, has_windows, _e, _r), group_members in groups.items():
            # pack members into fixed-shape chunks (fused_pack_capacity /
            # FUSED_CHUNK_Q — see the constants' doctrine note). Broad
            # members (> half a chunk, e.g. _full_or expansions) dispatch
            # alone on the single-query bucket ladder; the rest pack
            # greedily in input order.
            chunks: list[list] = []
            cur: list = []
            cur_blocks = 0
            for m in group_members:
                nb = len(m[2])
                if nb > slots // 2:
                    chunks.append([m])
                    continue
                if cur and (
                    cur_blocks + nb > slots
                    or len(cur) == FUSED_CHUNK_Q
                ):
                    chunks.append(cur)
                    cur, cur_blocks = [], 0
                cur.append(m)
                cur_blocks += nb
            if cur:
                chunks.append(cur)
            for members in chunks:
                self._submit_fused_chunk(
                    members, names, has_boxes, has_windows, finishes, deadline
                )

        return finishes

    def _fused_route_single(self, members, finishes, deadline) -> bool:
        """Route single-member / near-empty chunks to the plain
        single-query kernel (the fixed fused shape would waste most of
        its scan work on pads); returns True when routed. Shared by the
        single-device and distributed fused dispatches."""
        if len(members) == 1 or (
            # near-empty AND few members: past a handful of queries the
            # per-dispatch overhead (~2 ms each) outweighs scanning the
            # canonical shape's pad slots (~ms), so larger chunks always
            # fuse even when sparse
            len(members) <= 8
            and sum(len(m[2]) for m in members) < self.fused_pack_capacity // 8
        ):
            for j, config, blocks, overlap, contained in members:
                finishes[j] = self._make_finish(
                    self._device_scan_submit(blocks, config),
                    config, overlap, contained, deadline,
                )
            return True
        return False

    def _fused_param_stacks(self, members):
        """(boxes, wins) [FUSED_CHUNK_Q, 8, 128] per-query param stacks
        for one fused chunk — shared by the single-device and distributed
        dispatches so the packing can never drift."""
        boxes = np.zeros((FUSED_CHUNK_Q, 8, bk.LANES), np.float32)
        wins = np.zeros((FUSED_CHUNK_Q, 8, bk.LANES), np.int32)
        for q, m in enumerate(members):
            boxes[q], wins[q] = self._params(m[1])
        return boxes, wins

    @staticmethod
    def _fused_pull(wide, inner):
        """Start the async device->host copies for a fused chunk's planes
        NOW (see _device_scan_submit on why) and return a memoized
        ``group_pull() -> (wide_h, inner_h)``: the chunk pulls ONCE, on
        its first member's finish, and members decode lazily. Shared by
        the single-device and distributed dispatches."""
        import jax

        for plane in (wide, inner):
            if plane is not None and hasattr(plane, "copy_to_host_async"):
                plane.copy_to_host_async()
        pulled: dict = {}

        def group_pull():
            if "planes" not in pulled:
                wide_h, inner_h = jax.device_get((wide, inner))
                pulled["planes"] = (
                    np.asarray(wide_h),
                    None if inner_h is None else np.asarray(inner_h),
                )
            return pulled["planes"]

        return group_pull

    def _chunk_edge_stack(self, members):
        """(chunk_E, edges [FUSED_CHUNK_Q, chunk_E, 128] | None, pip [Q]
        bool) for one fused chunk: the per-query PIP edge stack, sized to
        the chunk's largest member polygon and zero-padded per query
        (pack_edges pad rows never cross and are never near). Extent
        tables ignore polygon edges in BOTH scan paths (bbox-intersects
        is the device test), so their chunks always ride E = 0."""
        pip = np.zeros(len(members), bool)
        if self.extent:
            return 0, None, pip
        chunk_e = bk.fused_e_bucket(
            max(bk.n_edges_of(m[1].poly) for m in members)
        )
        if chunk_e == 0:
            return 0, None, pip
        edges = np.zeros((FUSED_CHUNK_Q, chunk_e, bk.LANES), np.float32)
        for q, m in enumerate(members):
            poly = m[1].poly
            if poly is not None:
                edges[q, : poly.shape[0]] = poly
                pip[q] = True
        return chunk_e, edges, pip

    def _chunk_raster_stack(self, members):
        """(chunk_R, rasts [FUSED_CHUNK_Q, 1 + chunk_R, 128] | None,
        rast [Q] bool) for one fused chunk: the per-query raster-interval
        stack (RasterApprox.pack_block header + intervals), sized to the
        chunk's largest member raster and zero-padded per query (pad
        interval rows never match; an all-zero header classifies every
        row out-of-grid, and such slots never select the polygon leg).
        Extent tables ride R = 0 like they ride E = 0."""
        has = np.zeros(len(members), bool)
        if self.extent:
            return 0, None, has
        chunk_r = bk.fused_r_bucket(
            max(bk.n_rints_of(m[1].rast) for m in members)
        )
        if chunk_r == 0:
            return 0, None, has
        rasts = np.zeros((FUSED_CHUNK_Q, 1 + chunk_r, bk.LANES), np.float32)
        for q, m in enumerate(members):
            rast = m[1].rast
            if rast is not None:
                rasts[q, : rast.shape[0]] = rast
                has[q] = True
        return chunk_r, rasts, has

    def _submit_fused_chunk(
        self, members, names, has_boxes, has_windows, finishes, deadline
    ):
        """Dispatch one fused chunk (scan_submit_many): single-member or
        near-empty chunks take the plain single-query kernel; real
        batches share one block_scan_multi call — box AND polygon-PIP
        members together, selected per slot — and decode per-member slot
        segments."""
        slots = self.fused_slots
        if self._fused_route_single(members, finishes, deadline):
            return
        check_deadline(deadline, "device scan dispatch")
        boxes, wins = self._fused_param_stacks(members)
        chunk_e, edges, pip = self._chunk_edge_stack(members)
        chunk_r, rasts, has_rast = self._chunk_raster_stack(members)
        poly_slot = pip | has_rast
        bid_parts: list[np.ndarray] = []
        qid_parts: list[np.ndarray] = []
        segs: list[tuple[int, int]] = []  # slot segment per member
        pos = 0
        for q, (j, config, blocks, _, _) in enumerate(members):
            bid_parts.append(blocks.astype(np.int32))
            qid_parts.append(np.full(len(blocks), q, np.int32))
            segs.append((pos, pos + len(blocks)))
            pos += len(blocks)
        bids, n_real = bk.pad_bids(
            np.concatenate(bid_parts), self.n_blocks, bucket=slots
        )
        self._record_scan(names, len(bids))
        qids = np.zeros(len(bids), np.int32)
        qids[:n_real] = np.concatenate(qid_parts)
        spip = None
        if chunk_e or chunk_r:
            spip = poly_slot[qids].astype(np.int32)
            spip[n_real:] = 0  # pad slots keep the (cheaper) box leg
        wide, inner = bk.block_scan_multi(
            self._cols_args(names), bids, qids, boxes, wins,
            col_names=names, has_boxes=has_boxes, has_windows=has_windows,
            extent=self.extent, edges=edges, spip=spip, n_edges=chunk_e,
            rasts=rasts, n_rints=chunk_r,
        )
        group_pull = self._fused_pull(wide, inner)

        def member_finish(k):
            j, config, blocks, overlap, contained = members[k]
            s, e = segs[k]
            wide_h, inner_h = group_pull()
            check_deadline(deadline, "bitmask decode")
            rows, certain = bk.decode_bits_pair(
                np.ascontiguousarray(wide_h[s:e]),
                None if inner_h is None else np.ascontiguousarray(inner_h[s:e]),
                blocks, e - s,
            )
            return self._post_decode(rows, certain, config, overlap, contained)

        for k, (j, *_rest) in enumerate(members):
            finishes[j] = lambda k=k, f=member_finish: f(k)

    # -- device hooks ----------------------------------------------------
    def _params(self, config: ScanConfig):
        """(boxes, windows) packed [8, 128] kernel param blocks (wide +
        inner planes). Packed-time tables (the 1B layout) convert window
        offsets to device ticks first — floor-wide / shrink-inner, so
        tick-boundary rows refine on host like f32 box edges."""
        boxes = bk.pack_boxes(config.boxes, config.boxes_inner)
        shift = getattr(self.keyspace, "packed_time", None)
        if shift is not None and config.windows is not None:
            from geomesa_tpu.index.z3 import windows_to_ticks

            wide = bk.merge_window_slots(
                windows_to_ticks(config.windows, shift, inner=False),
                overflow="widen",
            )
            wi = config.windows_inner
            if wi is not None:
                wi = np.asarray(windows_to_ticks(wi, shift, inner=True))
                wi = wi[wi[:, 1] <= wi[:, 2]] if len(wi) else wi
            inner = (
                bk.merge_window_slots(wi, overflow="drop")
                if wi is not None and len(wi) else None
            )
            return boxes, bk.pack_windows(wide, inner)
        wins = bk.pack_windows(
            bk.merge_window_slots_wide(config), bk.merge_window_slots_inner(config)
        )
        return boxes, wins

    def _full_or(self, blocks: np.ndarray) -> np.ndarray:
        """Past the largest static M bucket, scan every block — one static
        shape per table instead of an unbounded bucket ladder."""
        if len(blocks) > bk.M_BUCKETS[-1]:
            return np.arange(self.n_blocks, dtype=np.int64)
        return blocks

    # -- column projection (reference ColumnGroups, index/conf/
    # ColumnGroups.scala: scans fetch only the column families the query
    # needs; here a scan variant's BlockSpecs DMA only the projected
    # device columns — a time-only query ships no x/y blocks) ------------
    def _coord_cols(self) -> set:
        want = {"gxmin", "gymin", "gxmax", "gymax"} if self.extent else {"x", "y"}
        return want & set(self.col_names)

    def _scan_cols(self, config: ScanConfig) -> tuple:
        """Device columns this scan's predicate actually reads."""
        names: set = set()
        if config.boxes is not None:
            names |= self._coord_cols()
        if config.windows is not None:
            names |= {"tbin", "toff", "tw"} & set(self.col_names)
        if not names:
            # no predicate: one validity column (sentinel test in _masks)
            for v in ("x", "gxmin", "tw", "tbin"):
                if v in self.col_names:
                    names = {v}
                    break
        return tuple(sorted(names))

    def _agg_cols(self, config: ScanConfig) -> tuple:
        """Aggregations additionally read the representative coordinates."""
        return tuple(sorted(set(self._scan_cols(config)) | self._coord_cols()))

    def _kernel_kwargs(self, config: ScanConfig, names: tuple | None = None) -> dict:
        return dict(
            col_names=names if names is not None else self._scan_cols(config),
            has_boxes=config.boxes is not None,
            has_windows=config.windows is not None,
            extent=self.extent,
        )

    def _scan_kernel_kwargs(self, config: ScanConfig, names: tuple) -> dict:
        """Kernel kwargs for the SCAN path only: adds the device PIP and
        raster-interval tiers (aggregation kernels keep the box test —
        their wide-plane math cannot carry the near-band / boundary-cell
        uncertainty, so poly configs take the host aggregation path via
        mask_decides_filter)."""
        kw = self._kernel_kwargs(config, names)
        if config.poly is not None and not self.extent:
            kw["edges"] = config.poly
            kw["n_edges"] = bk.n_edges_of(config.poly)
        if config.rast is not None and not self.extent:
            kw["rast"] = config.rast
            kw["n_rints"] = bk.n_rints_of(config.rast)
        return kw

    def _cols_args(self, names: tuple) -> tuple:
        return tuple(self.cols3[k] for k in names)

    def _record_scan(self, names: tuple, n_blocks: int) -> None:
        """Projection accounting: what the last kernel call DMA'd."""
        self.last_scan_cols = names
        self.last_scan_bytes = sum(
            int(self.cols3[k].dtype.itemsize) for k in names
        ) * n_blocks * self.block

    def _device_scan(self, blocks: np.ndarray, config: ScanConfig):
        """Kernel call over candidate blocks -> (rows, certain)."""
        return self._device_scan_submit(blocks, config)()

    def _device_scan_submit(self, blocks: np.ndarray, config: ScanConfig):
        """Dispatch the scan kernel now; return finish() -> (rows, certain).
        The device-hook seam the distributed table overrides."""
        import jax

        blocks = self._full_or(blocks)
        bids, n_real = bk.pad_bids(blocks, self.n_blocks)
        boxes, wins = self._params(config)
        names = self._scan_cols(config)
        self._record_scan(names, len(bids))
        wide, inner = bk.block_scan(
            self._cols_args(names), bids, boxes, wins,
            **self._scan_kernel_kwargs(config, names),
        )
        # start the device->host copy as soon as the kernel finishes: the
        # tunneled link overlaps in-flight transfers, but a blocking
        # device_get pays a full serialized roundtrip per query — measured
        # 40 pulls 2.6 s -> 73 ms with async copies (PERF.md §4e), which is
        # what makes query_many's pipelining actually pipeline
        for plane in (wide, inner):
            if plane is not None and hasattr(plane, "copy_to_host_async"):
                plane.copy_to_host_async()

        def finish():
            # inner is None on extent box scans (skip_inner_plane): pull
            # and decode the wide plane only — half the per-query bytes
            wide_h, inner_h = jax.device_get((wide, inner))
            inner_h = None if inner_h is None else np.asarray(inner_h)
            return bk.decode_bits_pair(np.asarray(wide_h), inner_h, bids, n_real)

        return finish

    def _device_pops(self, blocks: np.ndarray, config: ScanConfig):
        """Per-candidate-block wide-hit counts -> (pops [n] i64, global
        block ids [n] i64). Pulls M ints, never bit planes."""
        import jax

        from geomesa_tpu.scan import aggregations

        blocks = self._full_or(blocks)
        bids, n_real = bk.pad_bids(blocks, self.n_blocks)
        boxes, wins = self._params(config)
        names = self._scan_cols(config)
        self._record_scan(names, len(bids))
        pops = aggregations.block_pops(
            self._cols_args(names), bids, boxes, wins,
            **self._kernel_kwargs(config, names),
        )
        pops = np.asarray(jax.device_get(pops))[:n_real].astype(np.int64)
        return pops, bids[:n_real].astype(np.int64)

    def _device_density_submit(self, blocks, config, grid_bounds, width, height):
        """Dispatch the density kernel now (host copy started async);
        return finish() -> [height, width] grid."""
        import jax

        from geomesa_tpu.scan import aggregations

        blocks = self._full_or(blocks)
        bids, _ = bk.pad_bids(blocks, self.n_blocks, pad=-1)
        boxes, wins = self._params(config)
        names = self._agg_cols(config)
        self._record_scan(names, len(bids))
        grid = aggregations.block_density(
            self._cols_args(names), bids, boxes, wins, grid_bounds,
            width=width, height=height, **self._kernel_kwargs(config, names),
        )
        if hasattr(grid, "copy_to_host_async"):
            grid.copy_to_host_async()
        return lambda: np.asarray(jax.device_get(grid))

    def _device_bounds(self, blocks, config):
        """(count, envelope | None) over wide-predicate hits."""
        import jax

        from geomesa_tpu.scan import aggregations

        blocks = self._full_or(blocks)
        bids, n_real = bk.pad_bids(blocks, self.n_blocks, pad=-1)
        boxes, wins = self._params(config)
        names = self._agg_cols(config)
        self._record_scan(names, len(bids))
        stats = aggregations.block_bounds(
            self._cols_args(names), bids, boxes, wins,
            **self._kernel_kwargs(config, names),
        )
        return aggregations.reduce_bounds(jax.device_get(stats), n_real)

    # -- counting --------------------------------------------------------
    def count(self, config: ScanConfig) -> int:
        """Wide-predicate hit count (superset semantics where the config is
        imprecise; exact counting goes through scan + refinement).

        Avoids materializing row ids: contained spans count by length,
        other candidate blocks count by device-side popcount of their wide
        bit plane; only blocks *straddling* a contained span (which would
        double-count its rows) are decoded."""
        if config.disjoint or self.n == 0:
            return 0
        overlap, contained = self.candidate_spans_split(config)
        cont_total = sum(z - a for a, z in contained)
        has_pred = config.boxes is not None or config.windows is not None
        if not has_pred:
            return cont_total + sum(z - a for a, z in overlap)
        if config.clip_rows:  # span-exact clipping needs the rows
            rows, _ = self.scan(config)
            return len(rows)
        blocks = self.candidate_blocks(overlap)
        if len(blocks) == 0:
            return cont_total
        pops, gbids = self._device_pops(blocks, config)
        if not contained:
            return int(pops.sum())
        straddle = np.array(
            [_spans_intersect((b * self.block, (b + 1) * self.block), contained) for b in gbids]
        )
        total = int(pops[~straddle].sum()) + cont_total
        if straddle.any():
            rows, _ = self._device_scan(gbids[straddle], config)
            total += int((~_rows_in_spans(rows, contained)).sum())
        return total

    # -- aggregation push-down -------------------------------------------
    def _agg_blocks(self, config: ScanConfig) -> np.ndarray:
        """Candidate blocks over ALL scan ranges (contained rows pass the
        wide predicate, so aggregations just run the kernel over them)."""
        overlap, contained = self.candidate_spans_split(config)
        return self.candidate_blocks(_merge_spans(overlap + contained))

    def bounds_stats(self, config: ScanConfig):
        """(count, (xmin, ymin, xmax, ymax)) of matching rows on device (the
        StatsScan Count/MinMax(geom) fast path; loose f32 semantics)."""
        if config.disjoint or self.n == 0:
            return 0, None
        blocks = self._agg_blocks(config)
        if len(blocks) == 0:
            return 0, None
        return self._device_bounds(blocks, config)

    def density(self, config: ScanConfig, bounds, width: int, height: int) -> np.ndarray:
        """[height, width] density grid over ``bounds`` computed on device
        (the DensityScan push-down tier; see geomesa_tpu.scan.aggregations)."""
        return self.density_submit(config, bounds, width, height)()

    def density_submit(self, config: ScanConfig, bounds, width: int, height: int):
        """Pipelined form of :meth:`density`: dispatch the grid kernel now,
        return finish() -> grid. A batch of map tiles submits every tile's
        kernel before pulling any grid (DataStore.density_many)."""
        if config.disjoint or self.n == 0:
            return lambda: np.zeros((height, width), dtype=np.float32)
        blocks = self._agg_blocks(config)
        if len(blocks) == 0:
            return lambda: np.zeros((height, width), dtype=np.float32)
        gb = np.asarray(bounds, dtype=np.float32).reshape(4)
        return self._device_density_submit(blocks, config, gb, width, height)

    # -- warmup ----------------------------------------------------------
    def warmup(self) -> int:
        """Pre-compile the scan-kernel variants this table can hit, so the
        first real query never pays the (potentially tens-of-seconds) XLA
        compile. Variants are keyed by (M bucket, projected columns,
        predicate flags); this drives the shared device hook
        (``_device_scan_submit`` — so the distributed table warms its
        shard_map variants too) once per ladder bucket up to the table
        size, for the table's natural flag combinations — plus the one
        canonical fused multi-query shape per flag combo
        (scan_submit_many's fixed FUSED_CHUNK_SLOTS/FUSED_CHUNK_Q chunk).
        Returns the number of kernel calls issued."""
        if self.n == 0:
            return 0
        # every ladder bucket at or below n_blocks, PLUS the bucket that
        # n_blocks itself pads into (a query touching between the largest
        # whole bucket and n_blocks compiles that one), plus the full-scan
        # shape past the ladder
        sizes = sorted({
            *(m for m in bk.M_BUCKETS if m <= self.n_blocks),
            min(bk.bucket_of(self.n_blocks), max(self.n_blocks, bk.M_BUCKETS[0])),
        })
        if self.n_blocks > bk.M_BUCKETS[-1]:
            sizes.append(bk.M_BUCKETS[-1] + 1)  # triggers the full-scan shape
        has_windows = bool({"tbin", "tw"} & set(self.col_names))
        # (False, False) is the attribute-only / no-predicate variant
        # (validity-column projection) — real queries hit it too
        flag_combos = [(True, False), (False, False)]
        if has_windows:
            flag_combos = [(True, True), (True, False), (False, True), (False, False)]
        def make_cfg(has_boxes: bool, has_w: bool) -> ScanConfig:
            return ScanConfig(
                index="warmup",
                range_bins=np.zeros(1, np.int32),
                range_lo=np.zeros(1, np.uint64),
                range_hi=np.zeros(1, np.uint64),
                boxes=np.array([[0.0, 0.0, 1e-6, 1e-6]], np.float32)
                if has_boxes else None,
                windows=np.array([[0, 0, 0]], np.int32) if has_w else None,
            )

        calls = 0
        for m in sizes:
            blocks = np.arange(min(m, self.n_blocks), dtype=np.int64)
            for has_boxes, has_w in flag_combos:
                self._device_scan_submit(blocks, make_cfg(has_boxes, has_w))()
                calls += 1
        # the canonical fused multi-query variants (scan_submit_many):
        # fixed (fused_slots, FUSED_CHUNK_Q) shape means ONE compile per
        # (predicate-flag combo, E bucket, R bucket) covers every future
        # batch. E = R = 0 is the box-only chunk; point tables
        # additionally warm the PIP-fused E ladder and the
        # raster-interval R ladder (polygon members always carry a bbox,
        # so only has_boxes combos can hit them). Mixed E x R shapes
        # (the non-default device-residue mode) compile on first use.
        if self._fused_supported():
            pip_ok = not self.extent and {"x", "y"} <= set(self.col_names)
            for has_boxes, has_w in flag_combos:
                if not (has_boxes or has_w):
                    continue  # fused path requires a predicate
                e_ladder = [(0, 0)] + (
                    [(e, 0) for e in bk.FUSED_E_BUCKETS]
                    + [(0, r) for r in bk.FUSED_R_BUCKETS]
                    if (pip_ok and has_boxes) else []
                )
                for n_e, n_r in e_ladder:
                    cfg = make_cfg(has_boxes, has_w)
                    if n_e:
                        cfg.poly = np.zeros((n_e, bk.LANES), np.float32)
                    if n_r:
                        cfg.rast = np.zeros((1 + n_r, bk.LANES), np.float32)
                        cfg.rast[1:, 0] = 1.0  # pad intervals never match
                    names = self._scan_cols(cfg)
                    # half a chunk of round-robin blocks per member:
                    # enough real slots to clear the small-batch routing
                    # threshold (and to touch every mesh device), same
                    # compile key as any future fused dispatch
                    blk = (
                        np.arange(max(self.fused_pack_capacity // 4, 1))
                        % self.n_blocks
                    ).astype(np.int64)
                    fused_fins: list = [None, None]
                    self._submit_fused_chunk(
                        [(0, cfg, blk, [], []), (1, cfg, blk, [], [])],
                        names, has_boxes, has_w, fused_fins, None,
                    )
                    for f in fused_fins:
                        f()
                    calls += 1
        return calls

    @property
    def nbytes_device(self) -> int:
        return sum(int(v.nbytes) for v in self.cols3.values())


def folded_table(
    old: IndexTable,
    merged_keys: WriteKeys,
    keep_ordinal: "np.ndarray | None",
    ordinal_map: "np.ndarray | None",
    delta_keys: WriteKeys,
    delta_perm: "np.ndarray | None" = None,
    tile: int | None = None,
) -> IndexTable:
    """Incremental replace-merge: fold a delete + insert batch into a
    sorted table WITHOUT the whole-table radix sort (the streaming
    hot->cold merge; docs/streaming.md). :func:`merged_table` handles
    pure appends; an upsert flush also *removes* the replaced rows'
    keys, which round 8 and earlier paid for with a full recompaction
    (``_main_rows = 0`` -> re-sort + re-upload the entire table per
    flush). Here:

    - survivors keep their relative sorted order (dropping rows from a
      sorted sequence preserves sortedness), so no survivor re-sorts;
    - the delta radix-sorts alone (or arrives pre-sorted from the
      stream flusher's shard-sort stage as ``delta_perm``) and two-run
      merges into the survivor order with ``side='right'`` ties — new
      rows land AFTER equal-key survivors, exactly where the stable
      whole-table sort of ``concat(survivors, delta)`` puts them, so
      the result is bit-identical to a full recompaction (the
      differential matrix in tests/test_streaming_tier.py pins
      ``perm``/``bins``/``zs`` and every device column);
    - device blocks before the first touched sorted row are reused
      as-is (the ``reuse`` seam ``_stream_cols`` already honors), so
      the re-uploaded bytes scale with the flush's key locality, not N.

    ``merged_keys`` must be ``concat(masked old keys, delta_keys)`` in
    ordinal order; ``keep_ordinal`` is the survivor mask over OLD
    feature ordinals (None = nothing deleted) and ``ordinal_map`` maps
    old ordinals to post-delete ordinals (None when nothing deleted).
    Tables with a secondary sort word rebuild outright, like
    :func:`merged_table`.
    """
    nd = len(delta_keys.zs)
    if old.n == 0 or merged_keys.sub is not None:
        return IndexTable(old.keyspace, merged_keys, tile=tile)

    from geomesa_tpu import native

    if keep_ordinal is None:
        keep_sorted = None
        nm = old.n
        sbins, szs = old.bins, old.zs
        sperm = np.asarray(old.perm, dtype=np.int64)
        first_del = old.n
    else:
        # survivor mask in SORTED order: a sorted row survives when its
        # feature ordinal does
        keep_sorted = keep_ordinal[np.asarray(old.perm, dtype=np.int64)]
        nm = int(keep_sorted.sum())
        if nm == 0:
            return IndexTable(old.keyspace, merged_keys, tile=tile)
        sbins = old.bins[keep_sorted]
        szs = old.zs[keep_sorted]
        sperm = ordinal_map[np.asarray(old.perm, dtype=np.int64)[keep_sorted]]
        first_del = int(np.argmax(~keep_sorted)) if not keep_sorted.all() else old.n

    if nd == 0:
        perm = sperm
        first_change = first_del
    else:
        if delta_perm is not None and len(delta_perm) == nd:
            dperm = np.asarray(delta_perm, dtype=np.int64)
        else:
            dperm = native.sort_bins_z(delta_keys.bins, delta_keys.zs)
            if dperm is None:
                dperm = np.lexsort((delta_keys.zs, delta_keys.bins))
            dperm = np.asarray(dperm, dtype=np.int64)
        db = delta_keys.bins[dperm]
        dz = delta_keys.zs[dperm]

        # per-bin survivor segments for the insertion searchsorted
        subins, sstarts = np.unique(sbins, return_index=True)
        sstarts = np.append(sstarts, nm).astype(np.int64)
        pos = np.empty(nd, np.int64)
        for b in np.unique(db):
            i = int(np.searchsorted(subins, b))
            if i < len(subins) and subins[i] == b:
                s, e = int(sstarts[i]), int(sstarts[i + 1])
            else:
                s = e = int(sstarts[i]) if i < len(sstarts) else nm
            sel = db == b
            # side='right': delta rows land AFTER equal-key survivors —
            # the stable concat-sort tie order (survivors hold lower
            # ordinals in merged_keys)
            pos[sel] = np.searchsorted(szs[s:e], dz[sel], side="right") + s

        main_dest = np.arange(nm, dtype=np.int64) + np.searchsorted(
            pos, np.arange(nm, dtype=np.int64), side="right"
        )
        delta_dest = pos + np.arange(nd, dtype=np.int64)
        perm = np.empty(nm + nd, dtype=np.int64)
        perm[main_dest] = sperm
        perm[delta_dest] = nm + dperm
        first_change = min(first_del, int(pos.min()))
    if len(perm) < 2**32:
        perm = perm.astype(np.uint32)  # keep the native take() fast path

    fold_plan = None
    if _device_fold_enabled() and getattr(old, "cols3", None) is not None:
        removed = (
            np.flatnonzero(~keep_sorted) if keep_sorted is not None
            else np.zeros(0, np.int64)
        )
        if (
            old.block == _block_rows(tile)
            and set(old.col_names) == set(merged_keys.device_cols)
            and max(old.n_pad, nm + nd) < 2**31  # i32 position math
        ):
            delta_sorted_cols = (
                {k: v[dperm] for k, v in delta_keys.device_cols.items()}
                if nd else {}
            )
            dest = delta_dest if nd else np.zeros(0, np.int64)
            fold_plan = (old, removed, dest, delta_sorted_cols)

    table = IndexTable(
        old.keyspace, merged_keys, tile=tile,
        sorted_state=perm, reuse=(old, first_change), fold_plan=fold_plan,
    )
    table.rows_sorted = nd
    return table


def merged_table(
    old: IndexTable, merged_keys: WriteKeys, delta_keys: WriteKeys, tile: int | None = None
) -> IndexTable:
    """Merge-based minor compaction (the TimePartition analogue, reference
    index/conf/partition/TimePartition.scala): because the table is sorted
    by (bin, z), time partitions are CONTIGUOUS SEGMENTS of the sorted
    order — so folding a delta in needs no global re-sort, only a radix
    sort of the delta itself plus a positional merge, and every device
    block before the first insertion point is reused as-is. For the
    streaming steady state (recent-time appends land in the last bins) the
    re-sorted + re-uploaded data is proportional to the delta's time
    locality, not to N (VERDICT r3 #4: round-3 compaction concatenated and
    radix-re-sorted the entire table on every minor compaction).

    ``merged_keys`` must be ``concat(old keys, delta_keys)`` in ordinal
    order: delta feature ordinals follow the old table's.
    """
    nm, nd = old.n, len(delta_keys.zs)
    if nm == 0 or nd == 0 or merged_keys.sub is not None:
        # tables with a secondary sort word (string attribute indexes)
        # rebuild outright: the positional merge below compares (bin, z)
        # only, which would interleave z-tie runs out of sub order and
        # break the boundary-run narrowing in candidate_spans
        return IndexTable(old.keyspace, merged_keys, tile=tile)

    from geomesa_tpu import native

    dperm = native.sort_bins_z(delta_keys.bins, delta_keys.zs)
    if dperm is None:
        dperm = np.lexsort((delta_keys.zs, delta_keys.bins))
    db = delta_keys.bins[dperm]
    dz = delta_keys.zs[dperm]

    # insertion position in the old sorted order for every delta row,
    # resolved per bin segment (lexicographic (bin, z) searchsorted)
    pos = np.empty(nd, np.int64)
    for b in np.unique(db):
        i = int(np.searchsorted(old.ubins, b))
        if i < len(old.ubins) and old.ubins[i] == b:
            s, e = int(old.bin_starts[i]), int(old.bin_starts[i + 1])
        else:
            # bin absent from the old table: insert at the segment boundary
            s = e = int(old.bin_starts[i]) if i < len(old.bin_starts) else nm
        sel = db == b
        pos[sel] = np.searchsorted(old.zs[s:e], dz[sel], side="left") + s

    # classic stable two-run merge by destination index
    main_dest = np.arange(nm, dtype=np.int64) + np.searchsorted(
        pos, np.arange(nm, dtype=np.int64), side="right"
    )
    delta_dest = pos + np.arange(nd, dtype=np.int64)
    perm = np.empty(nm + nd, dtype=np.int64)
    perm[main_dest] = np.asarray(old.perm, dtype=np.int64)
    perm[delta_dest] = nm + np.asarray(dperm, dtype=np.int64)
    if nm + nd < 2**32:
        perm = perm.astype(np.uint32)  # keep the native take() fast path

    table = IndexTable(
        old.keyspace, merged_keys, tile=tile,
        sorted_state=perm, reuse=(old, int(pos.min())),
    )
    table.rows_sorted = nd
    return table
