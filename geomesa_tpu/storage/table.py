"""IndexTable: one index's sorted, device-resident columnar table.

The reference materializes each index as a sorted KV table (Accumulo/HBase
tablets; write path Z3IndexKeySpace.toIndexKey + IndexWriter, /root/
reference/geomesa-index-api/src/main/scala/org/locationtech/geomesa/index/
z3/Z3IndexKeySpace.scala:63-95). Here the same logical layout is a
struct-of-arrays table sorted lexicographically by (bin, z):

- host side: the sort keys (bins i32, zs u64), the per-bin segment offsets,
  and the permutation back to the backing FeatureCollection — used for
  range -> row-span -> tile pruning (the analogue of seeking scan ranges in
  a tablet server);
- device side: the predicate columns the scan kernel tests, padded to a
  multiple of the tile size with never-matching sentinels and pushed to
  device memory once at build.

Mutability: like an LSM store, appends land in the build path (write() in
the DataStore concatenates + re-sorts the delta with the existing table —
the Lambda-store hot/cold pattern; see geomesa_tpu.datastore).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.index.api import IndexKeySpace, ScanConfig, WriteKeys
from geomesa_tpu.scan import kernels

DEFAULT_TILE = 2048
# tile-prune only when candidates are under this fraction of the table;
# past it a straight linear scan is cheaper than a big gather
FULL_SCAN_FRACTION = 0.5

_SENTINELS = {
    "x": np.float32(np.inf),
    "y": np.float32(np.inf),
    "gxmin": np.float32(np.inf),
    "gymin": np.float32(np.inf),
    "gxmax": np.float32(-np.inf),
    "gymax": np.float32(-np.inf),
    "tbin": np.int32(-1),
    "toff": np.int32(0),
}


class SortedKeys:
    """Host-side sorted key structure shared by the single-device and
    distributed tables: the (bin, z) lexicographic sort, the permutation
    back to feature ordinals, and searchsorted range -> row-span pruning
    (the analogue of seeking scan ranges in a tablet server)."""

    def __init__(self, keyspace: IndexKeySpace, keys: WriteKeys, tile: int):
        self.keyspace = keyspace
        self.tile = tile
        n = len(keys.bins)
        self.n = n

        order = np.lexsort((keys.zs, keys.bins))
        self.bins = keys.bins[order]
        self.zs = keys.zs[order]
        self.perm = order.astype(np.int64)  # table row -> feature ordinal

        # per-bin segments for searchsorted pruning
        self.ubins, starts = np.unique(self.bins, return_index=True)
        self.bin_starts = np.append(starts, n).astype(np.int64)

    def pad_cols(self, keys: WriteKeys, n_pad: int) -> dict:
        """Sorted device columns padded to n_pad rows with never-matching
        sentinels."""
        cols = {}
        for name, col in keys.device_cols.items():
            out = np.full(n_pad, _SENTINELS[name], dtype=col.dtype)
            out[: self.n] = col[self.perm]
            cols[name] = out
        return cols

    # -- pruning ---------------------------------------------------------
    def candidate_spans(self, config: ScanConfig) -> list[tuple[int, int]]:
        """Merged, sorted row spans [lo, hi) covering the scan ranges."""
        spans: list[tuple[int, int]] = []
        for b in np.unique(config.range_bins):
            i = int(np.searchsorted(self.ubins, b))
            if i >= len(self.ubins) or self.ubins[i] != b:
                continue
            s, e = int(self.bin_starts[i]), int(self.bin_starts[i + 1])
            sel = config.range_bins == b
            seg = self.zs[s:e]
            lo = np.searchsorted(seg, config.range_lo[sel], side="left") + s
            hi = np.searchsorted(seg, config.range_hi[sel], side="right") + s
            for a, z in zip(lo.tolist(), hi.tolist()):
                if z > a:
                    spans.append((a, z))
        spans.sort()
        merged: list[tuple[int, int]] = []
        for a, z in spans:
            if merged and a <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], z))
            else:
                merged.append((a, z))
        return merged

    def candidate_tiles(self, config: ScanConfig) -> np.ndarray:
        """Sorted unique tile ids covering the scan ranges (subclasses set
        ``n_tiles``); falls back to every tile when pruning would not pay
        off (past FULL_SCAN_FRACTION a linear scan beats a big gather)."""
        spans = self.candidate_spans(config)
        if not spans:
            return np.zeros(0, dtype=np.int64)
        tiles: list[np.ndarray] = []
        covered = 0
        for a, z in spans:
            t0, t1 = a // self.tile, (z - 1) // self.tile
            tiles.append(np.arange(t0, t1 + 1, dtype=np.int64))
            covered += t1 - t0 + 1
            if covered >= self.n_tiles * FULL_SCAN_FRACTION:
                return np.arange(self.n_tiles, dtype=np.int64)
        return np.unique(np.concatenate(tiles))


class IndexTable(SortedKeys):
    """Sorted columnar table for one (feature type, index) pair."""

    def __init__(
        self,
        keyspace: IndexKeySpace,
        keys: WriteKeys,
        tile: int = DEFAULT_TILE,
        device=None,
    ):
        super().__init__(keyspace, keys, tile)

        # device columns, padded to a whole number of tiles
        n_pad = max(tile, ((self.n + tile - 1) // tile) * tile)
        self.n_pad = n_pad
        self.n_tiles = n_pad // tile
        cols = self.pad_cols(keys, n_pad)
        self.cols = {
            k: (jax.device_put(v, device) if device else jnp.asarray(v))
            for k, v in cols.items()
        }
        self.host_cols = cols

    # -- scanning --------------------------------------------------------
    def scan(self, config: ScanConfig, cap_hint: int = 4096) -> np.ndarray:
        """Run the device scan; return matching *feature ordinals* (into the
        backing FeatureCollection), ascending in table order."""
        if config.disjoint or self.n == 0:
            return np.zeros(0, dtype=np.int64)
        tiles = self.candidate_tiles(config)
        if len(tiles) == 0:
            return np.zeros(0, dtype=np.int64)
        tile_ids = kernels.pad_tiles(tiles)
        boxes = kernels.pad_boxes(config.boxes) if config.boxes is not None else None
        windows = (
            kernels.pad_windows(config.windows) if config.windows is not None else None
        )
        cap = kernels.pad_pow2(cap_hint, 4096)
        max_possible = len(tiles) * self.tile
        pallas = kernels.pallas_mode(self.tile, self.n_pad)
        while True:
            count, rows = kernels.tile_scan(
                self.cols,
                tile_ids,
                boxes,
                windows,
                tile=self.tile,
                cap=min(cap, kernels.pad_pow2(max_possible, 4096)),
                extent_mode=config.extent_mode,
                pallas=pallas,
            )
            count = int(count)
            if count <= cap or cap >= max_possible:
                break
            cap = kernels.pad_pow2(count, cap * 4)
        rows = np.asarray(rows[:count])
        return self.perm[rows]

    def count(self, config: ScanConfig) -> int:
        """Count rows matching the device predicate (loose semantics: f32
        widened boxes; exact counting goes through scan + refinement)."""
        if config.disjoint or self.n == 0:
            return 0
        tiles = self.candidate_tiles(config)
        if len(tiles) == 0:
            return 0
        return int(
            kernels.tile_count(
                self.cols,
                kernels.pad_tiles(tiles),
                kernels.pad_boxes(config.boxes) if config.boxes is not None else None,
                kernels.pad_windows(config.windows)
                if config.windows is not None
                else None,
                tile=self.tile,
                extent_mode=config.extent_mode,
                pallas=kernels.pallas_mode(self.tile, self.n_pad),
            )
        )

    def bounds_stats(self, config: ScanConfig):
        """(count, xmin, xmax, ymin, ymax) of matching rows on device (the
        StatsScan Count/MinMax(geom) fast path; loose f32 semantics).
        Returns (0, None) bounds when nothing matches."""
        from geomesa_tpu.scan import aggregations

        if config.disjoint or self.n == 0:
            return 0, None
        tiles = self.candidate_tiles(config)
        if len(tiles) == 0:
            return 0, None
        cnt, xmin, xmax, ymin, ymax = aggregations.tile_bounds_stats(
            self.cols,
            kernels.pad_tiles(tiles),
            kernels.pad_boxes(config.boxes) if config.boxes is not None else None,
            kernels.pad_windows(config.windows) if config.windows is not None else None,
            tile=self.tile,
            extent_mode=config.extent_mode,
        )
        cnt = int(cnt)
        if cnt == 0:
            return 0, None
        return cnt, (float(xmin), float(ymin), float(xmax), float(ymax))

    def density(
        self, config: ScanConfig, bounds, width: int, height: int
    ) -> np.ndarray:
        """[height, width] density grid over ``bounds`` computed on device
        (the DensityScan push-down tier; see geomesa_tpu.scan.aggregations)."""
        from geomesa_tpu.scan import aggregations

        if config.disjoint or self.n == 0:
            return np.zeros((height, width), dtype=np.float32)
        tiles = self.candidate_tiles(config)
        if len(tiles) == 0:
            return np.zeros((height, width), dtype=np.float32)
        grid = aggregations.tile_density(
            self.cols,
            kernels.pad_tiles(tiles),
            kernels.pad_boxes(config.boxes) if config.boxes is not None else None,
            kernels.pad_windows(config.windows) if config.windows is not None else None,
            jnp.asarray(np.asarray(bounds, dtype=np.float32)),
            tile=self.tile,
            width=width,
            height=height,
            extent_mode=config.extent_mode,
        )
        return np.asarray(grid)

    @property
    def nbytes_device(self) -> int:
        return sum(int(v.nbytes) for v in self.cols.values())
