"""LSM-style delta tier: recent writes live host-side until compaction.

The reference gets incremental sorted inserts for free from its KV backends
(Accumulo/HBase memtables + minor compaction); the TPU analogue is a small
host-resident unsorted delta per index that absorbs appends, scanned
exactly with vectorized NumPy, while the big sorted device table (the
"SSTable") only rebuilds when the delta outgrows its threshold — write()
cost is proportional to the batch, not the table (SURVEY §7 hard part (c);
reference Lambda hot/cold tiering, lambda/data/LambdaDataStore.scala).

Delta hits are always re-refined by the planner (certain=False): the host
predicate here mirrors the kernel's *wide* semantics.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.index.api import ScanConfig, WriteKeys


def concat_keys(parts: list[WriteKeys], consume: bool = False) -> WriteKeys:
    """Concatenate per-chunk write keys. ``consume=True`` releases each
    part's arrays as their column finishes concatenating, so the transient
    peak is one column set + one column — NOT the full doubled set. Only
    safe on parts the caller exclusively owns (the pipelined ingest's
    staged chunks); parts already published in a store may be shared with
    concurrent readers and must never be consumed."""
    if len(parts) == 1:
        return parts[0]
    names = tuple(parts[0].device_cols)
    sub = _concat_sub(parts)
    if consume:
        for p in parts:
            p.sub = None
    device_cols = {}
    for name in names:
        device_cols[name] = np.concatenate(
            [p.device_cols.pop(name) if consume else p.device_cols[name]
             for p in parts]
        )
    bins = np.concatenate([p.bins for p in parts])
    zs = np.concatenate([p.zs for p in parts])
    if consume:
        for p in parts:
            p.bins = p.bins[:0]
            p.zs = p.zs[:0]
    return WriteKeys(bins=bins, zs=zs, device_cols=device_cols, sub=sub)


def _concat_sub(parts: list[WriteKeys]) -> "np.ndarray | None":
    """Concatenate variable-width secondary sort words, zero-padding
    narrower batches to the widest word count (0 is the correct pad: a
    shorter string sorts before any extension)."""
    subs = [p.sub for p in parts]
    if all(s is None for s in subs):
        return None
    w = max(s.shape[1] for s in subs if s is not None)
    out = []
    for p, s in zip(parts, subs):
        if s is None:
            s = np.zeros((len(p.bins), w), dtype=np.uint64)
        elif s.shape[1] < w:
            s = np.pad(s, ((0, 0), (0, w - s.shape[1])))
        out.append(s)
    return np.concatenate(out)


def delta_wide_mask(
    config: ScanConfig, keys: WriteKeys, packed_shift: "int | None" = None
) -> np.ndarray:
    """Wide-predicate mask over delta rows (bit-compatible with the kernel's
    wide plane: f32 widened boxes, per-bin windows, bbox-intersects for
    extents; value-range check for predicate-free attribute scans).
    ``packed_shift``: the keyspace's packed-time tick shift (tw column)."""
    cols = keys.device_cols
    n = len(keys.zs)
    m = np.ones(n, dtype=bool)
    if config.boxes is not None:
        if "gxmin" in cols:
            hit = np.zeros(n, dtype=bool)
            for x0, y0, x1, y1 in np.asarray(config.boxes, np.float32):
                hit |= (
                    (cols["gxmin"] <= x1)
                    & (cols["gxmax"] >= x0)
                    & (cols["gymin"] <= y1)
                    & (cols["gymax"] >= y0)
                )
        else:
            x, y = cols["x"], cols["y"]
            hit = np.zeros(n, dtype=bool)
            for x0, y0, x1, y1 in np.asarray(config.boxes, np.float32):
                hit |= (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
        m &= hit
    if config.windows is not None:
        if "tw" in cols:
            # packed-time delta rows: wide tick semantics (floor), same
            # as the kernel — refinement stays exact (delta hits are
            # always uncertain)
            from geomesa_tpu.index.z3 import unpack_tw, windows_to_ticks

            tb, to = unpack_tw(cols["tw"])
            wins = windows_to_ticks(config.windows, packed_shift, inner=False)
        else:
            tb, to = cols["tbin"], cols["toff"]
            wins = config.windows
        hit = np.zeros(n, dtype=bool)
        for b, lo, hi in np.asarray(wins, np.int64):
            hit |= (tb == b) & (to >= lo) & (to <= hi)
        m &= hit
    if config.boxes is None and config.windows is None:
        # pure range scan (attribute primary): match the sort-key ranges
        hit = np.zeros(n, dtype=bool)
        zs = keys.zs
        for b, lo, hi in zip(
            config.range_bins.tolist(),
            config.range_lo.tolist(),
            config.range_hi.tolist(),
        ):
            hit |= (keys.bins == b) & (zs >= lo) & (zs <= hi)
        m &= hit
    elif config.clip_rows:
        # attribute index with secondary predicate: rows must also be in a
        # value range
        hit = np.zeros(n, dtype=bool)
        zs = keys.zs
        for b, lo, hi in zip(
            config.range_bins.tolist(),
            config.range_lo.tolist(),
            config.range_hi.tolist(),
        ):
            hit |= (keys.bins == b) & (zs >= lo) & (zs <= hi)
        m &= hit
    return m


def rep_xy(cols: dict, rows) -> tuple:
    """Representative coordinate per row: the point itself, or the bbox
    midpoint for extent columns — the ONE rule shared by the delta tier,
    the host adapter and (semantically) the device aggregation kernels."""
    if "x" in cols:
        return cols["x"][rows], cols["y"][rows]
    x = (cols["gxmin"][rows] + cols["gxmax"][rows]) * 0.5
    y = (cols["gymin"][rows] + cols["gymax"][rows]) * 0.5
    return x, y


def scatter_density(x, y, envelope, width: int, height: int, grid=None):
    """Clip + scatter-add points into a [height, width] f32 grid (wide
    density semantics; shared by the delta tier and the host adapter)."""
    x0, y0, x1, y1 = (float(v) for v in envelope)
    inb = (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
    px = np.clip(((x - x0) / max(x1 - x0, 1e-12) * width).astype(np.int64), 0, width - 1)
    py = np.clip(((y - y0) / max(y1 - y0, 1e-12) * height).astype(np.int64), 0, height - 1)
    if grid is None:
        grid = np.zeros((height, width), np.float32)
    flat = grid.reshape(-1)
    np.add.at(flat, (py * width + px)[inb], np.float32(1))
    return flat.reshape(height, width)


class TieredTable:
    """Main device table + host delta, presenting the IndexTable scan
    surface. Delta hits are uncertain (always refined)."""

    def __init__(self, main, delta_keys: WriteKeys, base_ordinal: int):
        self.main = main
        self.delta = delta_keys
        self.base = base_ordinal
        self.keyspace = main.keyspace

    @property
    def n(self) -> int:
        return self.main.n + len(self.delta.zs)

    def _delta_hits(self, config: ScanConfig) -> np.ndarray:
        if config.disjoint or len(self.delta.zs) == 0:
            return np.zeros(0, np.int64)
        return self.base + np.flatnonzero(
            delta_wide_mask(
                config, self.delta,
                packed_shift=getattr(self.keyspace, "packed_time", None),
            )
        )

    def scan(self, config: ScanConfig, deadline=None):
        return self.scan_submit(config, deadline=deadline)()

    def scan_submit(self, config: ScanConfig, deadline=None):
        """Pipelined scan (see IndexTable.scan_submit): the device main-
        table scan dispatches now; the host delta scan runs at finish."""
        finish_main = self.main.scan_submit(config, deadline=deadline)

        def finish():
            ordinals, certain = finish_main()
            d = self._delta_hits(config)
            if len(d) == 0:
                return ordinals, certain
            return (
                np.concatenate([ordinals, d]),
                np.concatenate([certain, np.zeros(len(d), bool)]),
            )

        return finish

    def scan_submit_many(self, configs, deadline=None):
        """Fused multi-query scan over the main table (one kernel dispatch
        per variant chunk — IndexTable.scan_submit_many), each query's
        host delta hits appended at its finish like scan_submit. Returns
        one finish() per config (lazy per-member decode preserved)."""
        fins_main = self.main.scan_submit_many(configs, deadline=deadline)

        def make_finish(config, fin):
            def finish():
                ordinals, certain = fin()
                d = self._delta_hits(config)
                if len(d):
                    ordinals = np.concatenate([ordinals, d])
                    certain = np.concatenate([certain, np.zeros(len(d), bool)])
                return ordinals, certain

            return finish

        return [make_finish(c, f) for c, f in zip(configs, fins_main)]

    def count(self, config: ScanConfig) -> int:
        return self.main.count(config) + len(self._delta_hits(config))

    def candidate_spans(self, config: ScanConfig):
        """Cost-estimator view: main spans plus the whole delta as one
        pseudo-span (a cheap upper bound — the delta is scanned linearly)."""
        spans = list(self.main.candidate_spans(config))
        if len(self.delta.zs):
            spans.append((self.main.n, self.main.n + len(self.delta.zs)))
        return spans

    def bounds_stats(self, config: ScanConfig):
        cnt, env = self.main.bounds_stats(config)
        d = self._delta_hits(config)
        if len(d) == 0:
            return cnt, env
        x, y = rep_xy(self.delta.device_cols, d - self.base)
        denv = (float(x.min()), float(y.min()), float(x.max()), float(y.max()))
        if env is None:
            return cnt + len(d), denv
        return cnt + len(d), (
            min(env[0], denv[0]), min(env[1], denv[1]),
            max(env[2], denv[2]), max(env[3], denv[3]),
        )

    def density(self, config: ScanConfig, bounds, width: int, height: int):
        return self.density_submit(config, bounds, width, height)()

    def density_submit(self, config: ScanConfig, bounds, width: int, height: int):
        """Pipelined density: the main table's grid kernel dispatches now;
        finish() pulls it and scatters the host delta rows on top."""
        finish_main = self.main.density_submit(config, bounds, width, height)
        return lambda: self._density_apply_delta(
            finish_main(), config, bounds, width, height
        )

    def _density_apply_delta(self, grid, config: ScanConfig, bounds, width, height):
        d = self._delta_hits(config)
        if len(d):
            x, y = rep_xy(self.delta.device_cols, d - self.base)
            grid = scatter_density(x, y, bounds, width, height, grid=grid)
        return grid

    @property
    def nbytes_device(self) -> int:
        return self.main.nbytes_device
