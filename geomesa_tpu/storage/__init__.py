"""Storage layer: device-resident columnar tables sorted by index key.

The TPU analogue of the reference's backend tier (SURVEY.md §2.4): instead
of rows in a distributed KV store, each index owns a struct-of-arrays
table in HBM sorted by (bin, z), scanned by the kernels in
geomesa_tpu.scan.
"""

from geomesa_tpu.storage.table import IndexTable

__all__ = ["IndexTable"]
