"""Visibility security: per-feature labels evaluated against auths.

Reference: geomesa-security (/root/reference/geomesa-security/src/main/
scala/org/locationtech/geomesa/security/ — VisibilityEvaluator.scala,
AuthorizationsProvider). Visibility expressions use the Accumulo grammar:

    admin                  requires the "admin" auth
    admin&user             both
    admin|ops              either
    a&(b|c)                grouping; & binds tighter than |

Empty visibility = visible to everyone. A store configured with ``auths``
masks every query result through the evaluator (row-level security); the
visibility column is named by the schema's ``geomesa.vis.field`` user-data
key.
"""

from __future__ import annotations

import re
from functools import lru_cache

import numpy as np

VIS_FIELD_KEY = "geomesa.vis.field"

_TOKEN = re.compile(r"\s*(?:(?P<label>[\w.\-:]+)|(?P<op>[&|()]))")


@lru_cache(maxsize=4096)
def _compile(expression: str):
    """Parse a visibility expression into a nested tuple AST."""
    pos = 0
    text = expression

    def parse_or():
        nonlocal pos
        left = parse_and()
        while True:
            m = _TOKEN.match(text, pos)
            if m and m.group("op") == "|":
                pos = m.end()
                left = ("or", left, parse_and())
            else:
                return left

    def parse_and():
        nonlocal pos
        left = parse_atom()
        while True:
            m = _TOKEN.match(text, pos)
            if m and m.group("op") == "&":
                pos = m.end()
                left = ("and", left, parse_atom())
            else:
                return left

    def parse_atom():
        nonlocal pos
        m = _TOKEN.match(text, pos)
        if m is None:
            raise ValueError(f"bad visibility {expression!r} at {text[pos:]!r}")
        if m.group("label"):
            pos = m.end()
            return ("label", m.group("label"))
        if m.group("op") == "(":
            pos = m.end()
            inner = parse_or()
            m2 = _TOKEN.match(text, pos)
            if not m2 or m2.group("op") != ")":
                raise ValueError(f"unbalanced parens in {expression!r}")
            pos = m2.end()
            return inner
        raise ValueError(f"bad visibility {expression!r} at {text[pos:]!r}")

    ast = parse_or()
    if text[pos:].strip():
        # any leftover input is an error — a silently-truncated label like
        # "admin,ops" would otherwise grant access on its first token
        raise ValueError(f"trailing input in visibility {expression!r}")
    return ast


def _eval(ast, auths: frozenset) -> bool:
    kind = ast[0]
    if kind == "label":
        return ast[1] in auths
    if kind == "and":
        return _eval(ast[1], auths) and _eval(ast[2], auths)
    return _eval(ast[1], auths) or _eval(ast[2], auths)


def visible(expression: str, auths) -> bool:
    """Can ``auths`` see a feature labeled ``expression``? Empty/blank
    labels are public (reference VisibilityEvaluator)."""
    if not expression or not expression.strip():
        return True
    return _eval(_compile(expression.strip()), frozenset(auths))


def visibility_mask(labels: np.ndarray, auths) -> np.ndarray:
    """Boolean mask over a visibility-label column (distinct labels are
    few; evaluate each once)."""
    labels = np.asarray(labels)
    auths = frozenset(auths)
    out = np.zeros(len(labels), dtype=bool)
    for v in np.unique(labels):
        out[labels == v] = visible(str(v), auths)
    return out
