"""Visibility security: per-feature labels evaluated against auths.

Reference: geomesa-security (/root/reference/geomesa-security/src/main/
scala/org/locationtech/geomesa/security/ — VisibilityEvaluator.scala,
AuthorizationsProvider). Visibility expressions use the Accumulo grammar:

    admin                  requires the "admin" auth
    admin&user             both
    admin|ops              either
    a&(b|c)                grouping; & binds tighter than |

Empty visibility = visible to everyone. A store configured with ``auths``
masks every query result through the evaluator (row-level security); the
visibility column is named by the schema's ``geomesa.vis.field`` user-data
key.

Hostile input: labels arrive over the network once a store is served
(docs/serving.md "The data plane" — the ingest endpoint carries
client-authored visibility columns), so the parser is bounded: input
over :data:`MAX_EXPRESSION_LENGTH` or nested past
:data:`MAX_EXPRESSION_DEPTH` raises :class:`VisibilityError` (a
``ValueError``) instead of recursing toward a ``RecursionError`` that
would traceback a worker thread. Every rejection path raises the same
type, so callers can map it to one clean 4xx.
"""

from __future__ import annotations

import re
from functools import lru_cache

import numpy as np

VIS_FIELD_KEY = "geomesa.vis.field"

_TOKEN = re.compile(r"\s*(?:(?P<label>[\w.\-:]+)|(?P<op>[&|()]))")

#: hard cap on expression bytes accepted by the parser — a 4 KiB label
#: is already absurd; anything longer is an attack or a bug
MAX_EXPRESSION_LENGTH = 4096

#: hard cap on paren-nesting depth — the recursive-descent parser (and
#: the recursive evaluator) consume one stack frame per level, so an
#: unbounded "(((((..." from the network would otherwise RecursionError
MAX_EXPRESSION_DEPTH = 64


class VisibilityError(ValueError):
    """A visibility expression that does not parse (bad token,
    unbalanced parens, trailing input, over the length/depth caps).
    Subclasses ``ValueError`` so pre-existing callers keep working."""


def validate(expression: str) -> None:
    """Reject a malformed visibility label BEFORE it is stored: raises
    :class:`VisibilityError`, accepts empty/blank (public). The served
    ingest path runs every incoming distinct label through this so a
    hostile expression 4xxes at the door instead of detonating inside a
    later query's mask."""
    if expression and expression.strip():
        _compile(expression.strip())


@lru_cache(maxsize=4096)
def _compile(expression: str):
    """Parse a visibility expression into a nested tuple AST."""
    if len(expression) > MAX_EXPRESSION_LENGTH:
        raise VisibilityError(
            f"visibility expression over {MAX_EXPRESSION_LENGTH} chars "
            f"({len(expression)})"
        )
    pos = 0
    text = expression

    def parse_or(depth):
        nonlocal pos
        left = parse_and(depth)
        while True:
            m = _TOKEN.match(text, pos)
            if m and m.group("op") == "|":
                pos = m.end()
                left = ("or", left, parse_and(depth))
            else:
                return left

    def parse_and(depth):
        nonlocal pos
        left = parse_atom(depth)
        while True:
            m = _TOKEN.match(text, pos)
            if m and m.group("op") == "&":
                pos = m.end()
                left = ("and", left, parse_atom(depth))
            else:
                return left

    def parse_atom(depth):
        nonlocal pos
        m = _TOKEN.match(text, pos)
        if m is None:
            raise VisibilityError(
                f"bad visibility {expression!r} at {text[pos:]!r}"
            )
        if m.group("label"):
            pos = m.end()
            return ("label", m.group("label"))
        if m.group("op") == "(":
            if depth >= MAX_EXPRESSION_DEPTH:
                raise VisibilityError(
                    f"visibility expression nested past "
                    f"{MAX_EXPRESSION_DEPTH} levels"
                )
            pos = m.end()
            inner = parse_or(depth + 1)
            m2 = _TOKEN.match(text, pos)
            if not m2 or m2.group("op") != ")":
                raise VisibilityError(f"unbalanced parens in {expression!r}")
            pos = m2.end()
            return inner
        raise VisibilityError(
            f"bad visibility {expression!r} at {text[pos:]!r}"
        )

    ast = parse_or(0)
    if text[pos:].strip():
        # any leftover input is an error — a silently-truncated label like
        # "admin,ops" would otherwise grant access on its first token
        raise VisibilityError(f"trailing input in visibility {expression!r}")
    return ast


def _eval(ast, auths: frozenset) -> bool:
    kind = ast[0]
    if kind == "label":
        return ast[1] in auths
    if kind == "and":
        return _eval(ast[1], auths) and _eval(ast[2], auths)
    return _eval(ast[1], auths) or _eval(ast[2], auths)


def visible(expression: str, auths) -> bool:
    """Can ``auths`` see a feature labeled ``expression``? Empty/blank
    labels are public (reference VisibilityEvaluator)."""
    if not expression or not expression.strip():
        return True
    return _eval(_compile(expression.strip()), frozenset(auths))


def visibility_mask(labels: np.ndarray, auths) -> np.ndarray:
    """Boolean mask over a visibility-label column (distinct labels are
    few; evaluate each once). Object-dtype columns (mixed None/str from
    a network ingest) normalize first — ``None`` is public, like the
    empty label — so a hostile payload can neither crash ``np.unique``'s
    sort nor smuggle a non-string past the parser."""
    labels = np.asarray(labels)
    if labels.dtype == object:
        labels = np.array(
            ["" if v is None else str(v) for v in labels.tolist()]
        )
    auths = frozenset(auths)
    out = np.zeros(len(labels), dtype=bool)
    for v in np.unique(labels):
        out[labels == v] = visible(str(v), auths)
    return out
