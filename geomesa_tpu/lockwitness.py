"""Dynamic lock witness: prove the static lock model against reality.

The static tier (``analysis/lockmodel.py`` + the geomesa-race rules)
derives the lock-acquisition graph from the AST and checks it against
the declared rank order. A static model can drift from runtime truth in
both directions — an edge real control flow takes through a callback
the AST cannot resolve, or a registry entry for a lock nothing ever
acquires. This module closes the loop the way ``fault-point-unknown``
does for fault points: every :data:`~geomesa_tpu.analysis.lockmodel.LOCKS`
lock is constructed through :func:`witness`, and when the witness is
ARMED (``geomesa.tpu.lock.witness`` / env ``GEOMESA_TPU_LOCK_WITNESS=1``,
or :func:`enable` in a test) the lock wraps in a recording proxy:

- every acquisition while other witnessed locks are held records an
  acquisition-order EDGE ``held -> acquired`` (per thread, via a
  thread-local held stack; re-entrant re-acquisition of the same
  instance records nothing);
- two DISTINCT instances under the same registry name nesting records
  an ``aliased`` event instead of an edge (two hot caches wired
  through a FeatureStream sink are an instance-ORDER hazard the
  name-level graph cannot express — surfaced, not conflated);
- every :func:`geomesa_tpu.fault.fault_point` reached while a witnessed
  lock is held records a ``blocking`` event (fault points mark the IO/
  latency steps, so this is the runtime twin of the static
  blocking-under-lock rule).

``tests/test_lock_witness.py`` drives a workload over the concurrent
tiers under an armed witness and asserts, both directions: every
registry lock was actually witnessed, the observed graph is acyclic,
and it is a subgraph of the static model's predicted edges
(AST-derived + declared callback edges). :func:`dump` writes the
observed graph to ``geomesa.tpu.lock.witness.artifact`` (default
``/tmp/lock_witness.json``) so a CI failure is diagnosable from logs.

Disarmed (the default), :func:`witness` returns the inner lock object
unchanged — zero overhead, no wrapper in the acquire path. Armed, the
overhead is one thread-local list push/pop per acquire plus a dict
probe per NEW edge; the tier-1 overhead smoke pins the witnessed
suite at <= 1.5x the unwitnessed wall time.

This module deliberately records NO metrics (the MetricsRegistry lock
is itself witnessed — instrumenting the witness would recurse) and
imports nothing heavier than conf.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from geomesa_tpu import conf

#: module-level arm flag, mirrored by enable()/disable() — read on the
#: witness() construction path and by fault.fault_point's blocking hook
#: (an attribute probe, cheap enough for the disarmed hot path)
ENABLED: bool = bool(conf.LOCK_WITNESS.get())

_tls = threading.local()


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


class WitnessReport:
    """The process-global observation collector."""

    def __init__(self):
        self._lock = threading.Lock()
        self.edges: dict[tuple[str, str], int] = {}    # guarded-by: _lock
        self.aliased: dict[tuple[str, str], int] = {}  # guarded-by: _lock
        self.seen: set[str] = set()                    # guarded-by: _lock
        self.blocking: dict[tuple[str, str], int] = {}  # guarded-by: _lock

    def reset(self) -> None:
        with self._lock:
            self.edges = {}
            self.aliased = {}
            self.seen = set()
            self.blocking = {}

    def note_acquire(self, name: str, key: int) -> None:
        stack = _stack()
        pairs = []
        aliased = []
        fresh = name not in self.seen
        for held_name, held_key in stack:
            if held_name == name:
                if held_key != key:
                    aliased.append((held_name, name))
                continue
            pairs.append((held_name, name))
        if fresh or pairs or aliased:
            with self._lock:
                self.seen.add(name)
                for p in pairs:
                    self.edges[p] = self.edges.get(p, 0) + 1
                for p in aliased:
                    self.aliased[p] = self.aliased.get(p, 0) + 1
        stack.append((name, key))

    def note_release(self, name: str, key: int) -> None:
        stack = _stack()
        # LIFO in practice; tolerate out-of-order release by removing
        # the LAST matching frame
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == (name, key):
                del stack[i]
                return

    def note_blocking(self, point: str) -> None:
        stack = _stack()
        if not stack:
            return
        held = tuple(sorted({n for n, _ in stack}))
        with self._lock:
            for h in held:
                k = (h, point)
                self.blocking[k] = self.blocking.get(k, 0) + 1

    # -- analysis ---------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "seen": sorted(self.seen),
                "edges": sorted(self.edges),
                "edge_counts": {
                    f"{a} -> {b}": n for (a, b), n in sorted(self.edges.items())
                },
                "aliased": {
                    f"{a} ~ {b}": n
                    for (a, b), n in sorted(self.aliased.items())
                },
                "blocking": {
                    f"{lock} @ {point}": n
                    for (lock, point), n in sorted(self.blocking.items())
                },
            }

    def cycle(self) -> Optional[list[str]]:
        """One observed acquisition-order cycle (as a lock-name path),
        or None. Self-loops cannot occur (same-name pairs are recorded
        as aliased, never as edges)."""
        with self._lock:
            graph: dict[str, set[str]] = {}
            for a, b in self.edges:
                graph.setdefault(a, set()).add(b)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        path: list[str] = []

        def dfs(n: str) -> Optional[list[str]]:
            color[n] = GRAY
            path.append(n)
            for m in sorted(graph.get(n, ())):
                c = color.get(m, WHITE)
                if c == GRAY:
                    return path[path.index(m):] + [m]
                if c == WHITE:
                    found = dfs(m)
                    if found is not None:
                        return found
            path.pop()
            color[n] = BLACK
            return None

        for n in sorted(graph):
            if color[n] == WHITE:
                found = dfs(n)
                if found is not None:
                    return found
        return None


REPORT = WitnessReport()


class _WitnessedLock:
    """Recording proxy over a Lock/RLock. Delegates everything; the
    held-stack bookkeeping happens on successful acquire/release."""

    __slots__ = ("_inner", "_name")

    def __init__(self, inner, name: str):
        self._inner = inner
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            REPORT.note_acquire(self._name, id(self._inner))
        return ok

    def release(self) -> None:
        self._inner.release()
        REPORT.note_release(self._name, id(self._inner))

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _WitnessedCondition(_WitnessedLock):
    """Recording proxy over a Condition: wait() releases the underlying
    lock, so the held frame pops for the wait and re-pushes after
    (without edge recording — the held set across a wait was already
    recorded at the original acquire)."""

    __slots__ = ()

    def _pop_frames(self) -> int:
        stack = _stack()
        key = (self._name, id(self._inner))
        n = 0
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == key:
                del stack[i]
                n += 1
        return n

    def _push_frames(self, n: int) -> None:
        stack = _stack()
        key = (self._name, id(self._inner))
        for _ in range(n):
            stack.append(key)

    def wait(self, timeout: Optional[float] = None) -> bool:
        n = self._pop_frames()
        try:
            return self._inner.wait(timeout)
        finally:
            self._push_frames(max(n, 1))

    def wait_for(self, predicate, timeout: Optional[float] = None):
        n = self._pop_frames()
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._push_frames(max(n, 1))

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


def witness(lock, name: str):
    """Wrap one registry-declared lock in the recording proxy when the
    witness is armed; return it unchanged (zero overhead) otherwise.
    ``name`` must be the lock's ``LOCKS`` registry key (``Class.attr``)
    — the lock-order-cycle rule cross-checks the literal."""
    if not ENABLED:
        return lock
    if isinstance(lock, threading.Condition):
        return _WitnessedCondition(lock, name)
    return _WitnessedLock(lock, name)


def note_blocking(point: str) -> None:
    """fault.fault_point's hook: a fault point (an IO/latency step) was
    reached; record it against every witnessed lock currently held."""
    if ENABLED:
        REPORT.note_blocking(point)


def held_locks() -> tuple:
    """The witnessed locks the CALLING thread currently holds (tests)."""
    return tuple(n for n, _ in _stack())


def enable(reset: bool = True) -> None:
    """Arm the witness for locks constructed FROM NOW ON (existing
    objects keep their bare locks — construct the workload's stores
    after arming)."""
    global ENABLED
    ENABLED = True
    if reset:
        REPORT.reset()


def disable() -> None:
    global ENABLED
    ENABLED = False


def dump(path: "str | None" = None) -> str:
    """Write the observed graph/events as JSON to ``path`` (default:
    the ``geomesa.tpu.lock.witness.artifact`` knob) and return the
    path — the CI artifact the witness test always emits."""
    if path is None:
        path = str(conf.LOCK_WITNESS_ARTIFACT.get())
    payload = REPORT.snapshot()
    payload["cycle"] = REPORT.cycle()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
    return path
