"""Sharded (bin, z) ingest sort: parallel fixed-size chunk radix sorts +
a spanwise k-way merge.

The 1B-row validation put the whole-table (bin, z) radix argsort at ~55%
of single-core ingest wall (PERF.md §4f, §7). The pipeline splits the sort
in two so it overlaps the other stages:

1. every fixed-size chunk of keys radix-sorts independently (the native
   LSD pass, ``native.sort_bins_z``) as soon as its keys exist — chunks
   sort in parallel worker threads (ctypes releases the GIL) while later
   chunks are still parsing;
2. at finalize, the sorted runs k-way merge *per bin span*: each run is
   sorted by (bin, z), so a bin's rows are one contiguous span per run,
   and different bins merge independently (thread-parallel). Within a bin
   the k spans merge by a positional two-run tree (searchsorted + scatter,
   O(n log k)), ties resolved run-first so the result is EXACTLY the
   stable sort of the concatenated chunks — bit-identical to what
   ``native.sort_bins_z`` produces over the whole table.

Per the §4f negative result (bin segmentation regressed when stores have
~5 week bins or one bin: segments stay tens of millions of rows and the
partition pass is pure overhead), the merge only runs when the table has
at least ``geomesa.ingest.merge.min.bins`` distinct bins; below that the
finalize falls back to the proven whole-table LSD (the runs are simply
discarded and the concatenated keys sort once, memory-bandwidth bound).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


def sort_chunk(bins: np.ndarray, zs: np.ndarray) -> np.ndarray:
    """Stable argsort of one chunk by (bin, z) — the same native-radix/
    lexsort ladder SortedKeys uses, so chunk order matches the whole-table
    sort's tie behavior."""
    from geomesa_tpu import native

    perm = native.sort_bins_z(bins, zs)
    if perm is None:
        perm = np.lexsort((zs, bins))
    return perm


@dataclass
class SortRun:
    """One sorted run: a chunk's (or shard's) keys in (bin, z) order plus
    the GLOBAL row ordinals they came from. Runs are merged in list order,
    which must be ingest order for the merge to be stable."""

    bins: np.ndarray  # sorted asc
    zs: np.ndarray    # sorted asc within each bin
    gperm: np.ndarray  # int64 global ordinals, aligned with bins/zs

    @staticmethod
    def build(bins: np.ndarray, zs: np.ndarray, base: int) -> "SortRun":
        perm = sort_chunk(bins, zs)
        return SortRun(
            bins=bins[perm],
            zs=zs[perm],
            gperm=base + perm.astype(np.int64),
        )


def shard_runs(bins: np.ndarray, zs: np.ndarray, base: int, shard_rows: int) -> list[SortRun]:
    """Split one chunk's keys into fixed-size shards and sort each —
    shard order preserves ingest order, so the runs stay merge-stable."""
    n = len(zs)
    shard_rows = max(int(shard_rows), 1)
    return [
        SortRun.build(bins[s : s + shard_rows], zs[s : s + shard_rows], base + s)
        for s in range(0, n, shard_rows)
    ]


def _merge2(z1, p1, z2, p2):
    """Stable positional merge of two sorted z runs: run-1 rows precede
    tied run-2 rows (searchsorted side='right' both ways — the stability
    invariant the bit-identical guarantee rests on)."""
    n1, n2 = len(z1), len(z2)
    if n2 == 0:
        return z1, p1
    if n1 == 0:
        return z2, p2
    pos = np.searchsorted(z1, z2, side="right")
    dest2 = pos + np.arange(n2, dtype=np.int64)
    dest1 = np.arange(n1, dtype=np.int64) + np.searchsorted(
        pos, np.arange(n1, dtype=np.int64), side="right"
    )
    z = np.empty(n1 + n2, dtype=z1.dtype)
    p = np.empty(n1 + n2, dtype=np.int64)
    z[dest1] = z1
    z[dest2] = z2
    p[dest1] = p1
    p[dest2] = p2
    return z, p


def _merge_tree(parts: list) -> np.ndarray:
    """[(zs, gperm)] in run order -> merged gperm. Adjacent pairs merge
    level by level, preserving list order so stability composes."""
    while len(parts) > 1:
        nxt = []
        for i in range(0, len(parts) - 1, 2):
            z1, p1 = parts[i]
            z2, p2 = parts[i + 1]
            nxt.append(_merge2(z1, p1, z2, p2))
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0][1]


def distinct_bins(runs: list[SortRun]) -> np.ndarray:
    """Sorted distinct bins across all runs (each run's bins are sorted:
    per-run uniques are cheap)."""
    if not runs:
        return np.zeros(0, np.int32)
    return np.unique(np.concatenate([np.unique(r.bins) for r in runs]))


def merge_runs(runs: list[SortRun], pool=None, bins: "np.ndarray | None" = None) -> np.ndarray:
    """K-way merge of sorted runs -> the global stable (bin, z) argsort
    (int64 ordinals). ``pool``: an optional executor with ``map`` — bins
    are independent spans, so they merge in parallel. ``bins``: the
    precomputed :func:`distinct_bins` result (callers that already
    computed it for the merge/LSD gate pass it to skip a second full
    pass over the key columns)."""
    runs = [r for r in runs if len(r.zs)]
    if not runs:
        return np.zeros(0, np.int64)
    if len(runs) == 1:
        return runs[0].gperm
    n = sum(len(r.zs) for r in runs)
    if bins is None:
        bins = distinct_bins(runs)
    # per-run bin segmentation: run r's span for bins[i] is
    # [starts[r][i], starts[r][i+1]) via searchsorted on the sorted bins
    spans = []
    for r in runs:
        lo = np.searchsorted(r.bins, bins, side="left")
        hi = np.searchsorted(r.bins, bins, side="right")
        spans.append((lo, hi))
    counts = np.zeros(len(bins), np.int64)
    for lo, hi in spans:
        counts += hi - lo
    offs = np.concatenate([[0], np.cumsum(counts)])
    out = np.empty(n, np.int64)

    def merge_bin(i: int) -> None:
        parts = []
        for r, (lo, hi) in zip(runs, spans):
            s, e = int(lo[i]), int(hi[i])
            if e > s:
                parts.append((r.zs[s:e], r.gperm[s:e]))
        out[offs[i] : offs[i + 1]] = _merge_tree(parts)

    if pool is not None and len(bins) > 1:
        list(pool.map(merge_bin, range(len(bins))))
    else:
        for i in range(len(bins)):
            merge_bin(i)
    return out
