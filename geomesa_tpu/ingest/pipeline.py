"""Staged multi-core ingest pipeline (docs/ingest.md).

The write path used to be one host core: the 1B-row validation ingested at
348k rows/s with the (bin, z) radix argsort alone ~55% of wall (PERF.md
§4f, §7), against a measured ~1.7M rows/s CPU ceiling at 20M rows. The
pipeline overlaps the stages instead (the 3DPipe build/probe-overlap
argument, arxiv 2604.19982, and the saturate-the-host-cores case of
arxiv 1802.09488):

1. **parse** — converter workers over input splits (a process pool; the
   distributed-MapReduce-ingest analogue, see ``ingest.splits``);
2. **keys**  — z2/z3/xz write-key encoding per chunk in worker threads
   (the native passes release the GIL), plus the chunk's stats sketch;
3. **sort**  — fixed-size shards of each chunk's (bin, z) keys radix-sort
   in parallel (``ingest.sort``); the sorted runs k-way merge at finalize
   (or fall back to the whole-table LSD when bins are few, per the §4f
   negative result);
4. **write** — an ordered writer thread accounts each chunk and releases
   backpressure; the single ``finalize`` publishes every chunk atomically
   under the store's write lock and builds the device tables from the
   pre-merged permutations, overlapping per-index device uploads.

Backpressure: a bounded admission window (``geomesa.ingest.queue.depth``
chunks) gates ``put()`` until the ordered writer catches up, so stage
scratch (unsorted key copies, sort shards) stays bounded; the committed
data itself is host-resident by design (this is an in-process store).

Failure semantics: ANY stage failure — including injected faults
(geomesa_tpu.fault: ``ingest.split.read`` / ``ingest.parse`` /
``ingest.keys`` / ``ingest.sort`` / ``ingest.commit`` /
``ingest.finalize``) — aborts the whole ingest BEFORE the single publish
point, so the store never shows a partial bulk load and ``_quarantine/``
is untouched. Transient IO errors on split reads retry with bounded
backoff first (fault.with_retries).

Every stage records wall time into the ``geomesa.ingest.*`` metrics
family, so a bulk-load profile shows where the time lives.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

import numpy as np

from geomesa_tpu.fault import fault_point
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.ingest import sort as shsort
from geomesa_tpu.ingest.splits import (
    ConverterConfig,
    plan_splits,
    run_split_guarded,
)

STAGES = ("parse", "keys", "sort", "commit", "finalize")


class IngestError(RuntimeError):
    """An ingest failed; for parse-worker failures carries the worker's
    split index and formatted traceback (forked workers lose their stack
    otherwise)."""

    def __init__(self, message: str, split_index: "int | None" = None,
                 worker_traceback: "str | None" = None):
        super().__init__(message)
        self.split_index = split_index
        self.worker_traceback = worker_traceback


@dataclass
class IngestResult:
    written: int = 0
    errors: int = 0
    splits: int = 0
    # per-split parse-error counts, ordered by SPLIT index (not worker
    # completion): deterministic across runs and worker counts
    split_errors: list = field(default_factory=list)
    # per-stage wall seconds (geomesa.ingest.* timer mirror)
    stage_seconds: dict = field(default_factory=dict)
    # per-reason error counts aggregated over splits ("parse", or a
    # validator's "name: reason" — the CqlValidatorFactory-style
    # accounting; io.validators). errors == sum(error_reasons.values())
    error_reasons: dict = field(default_factory=dict)

    def add_reasons(self, reasons: dict) -> None:
        for r, n in reasons.items():
            self.error_reasons[r] = self.error_reasons.get(r, 0) + n


@dataclass
class PipelineConfig:
    """Knobs for the staged pipeline; ``from_properties`` resolves each
    from the typed property tier (geomesa_tpu.conf)."""

    workers: int = 0          # 0 = one per host core
    queue_depth: int = 4      # chunks admitted ahead of the ordered writer
    chunk_rows: int = 1 << 20  # fixed-size sort shard rows
    merge_min_bins: int = 2   # below this, finalize uses whole-table LSD

    @staticmethod
    def from_properties() -> "PipelineConfig":
        from geomesa_tpu import conf

        return PipelineConfig(
            workers=conf.INGEST_WORKERS.get(),
            queue_depth=conf.INGEST_QUEUE_DEPTH.get(),
            chunk_rows=conf.INGEST_CHUNK_ROWS.get(),
            merge_min_bins=conf.INGEST_MERGE_MIN_BINS.get(),
        )

    def resolved_workers(self) -> int:
        import os

        if self.workers and self.workers > 0:
            return int(self.workers)
        return max(1, os.cpu_count() or 1)


def _col_nbytes(col) -> int:
    if hasattr(col, "nbytes"):
        return int(col.nbytes)
    if hasattr(col, "x") and hasattr(col, "y"):  # PointColumn
        return int(col.x.nbytes) + int(col.y.nbytes)
    if hasattr(col, "coords"):  # PackedGeometryColumn
        return int(col.coords.nbytes) + int(col.bboxes.nbytes)
    return 0


def _chunk_nbytes(fc: FeatureCollection, keys_by_index: dict) -> int:
    total = int(np.asarray(fc.ids).nbytes)
    for col in fc.columns.values():
        total += _col_nbytes(col)
    for keys in keys_by_index.values():
        total += int(keys.bins.nbytes) + int(keys.zs.nbytes)
        total += sum(int(v.nbytes) for v in keys.device_cols.values())
        if keys.sub is not None:
            total += int(keys.sub.nbytes)
    return total


class _Chunk:
    __slots__ = ("idx", "base", "fc", "keys", "stats", "runs", "event", "error")

    def __init__(self, idx: int, base: int, fc: FeatureCollection):
        self.idx = idx
        self.base = base  # global row offset among staged chunks
        self.fc = fc
        self.keys: dict = {}
        self.stats = None
        self.runs: dict = {}  # index name -> list[SortRun]
        self.event = threading.Event()
        self.error: "BaseException | None" = None


class BulkLoader:
    """Staged multi-core bulk ingest for ONE feature type: ``put()``
    chunks (FeatureCollections or row mappings), then ``close()`` — the
    single atomic publish. Nothing is visible in the store until close()
    returns; any failure before that leaves the store untouched."""

    def __init__(self, store, type_name: str, config: "PipelineConfig | None" = None,
                 metrics=None, check_ids: bool = True):
        self.store = store
        self.type_name = type_name
        self.config = config if config is not None else PipelineConfig.from_properties()
        self.metrics = metrics if metrics is not None else getattr(store, "metrics", None)
        self.check_ids = check_ids
        workers = self.config.resolved_workers()
        # one shared pool for key + sort (+ finalize merge) tasks: no task
        # ever blocks on another task, so a bounded pool cannot deadlock
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, workers), thread_name_prefix="geomesa-ingest"
        )
        from geomesa_tpu.lockwitness import witness

        self._sem = threading.Semaphore(max(1, self.config.queue_depth))
        self._cv = witness(threading.Condition(), "BulkLoader._cv")
        self._chunks: list[_Chunk] = []           # guarded-by: _cv
        self._rows_staged = 0                     # guarded-by: _cv
        self._closed = False                      # guarded-by: _cv
        self._error: "BaseException | None" = None  # guarded-by: _cv
        self._writer: "threading.Thread | None" = None  # guarded-by: _cv
        self._stage_lock = witness(threading.Lock(), "BulkLoader._stage_lock")
        self._stage_s = {s: 0.0 for s in STAGES}  # guarded-by: _stage_lock
        self._peak_chunk_bytes = 0                # guarded-by: _stage_lock

    # -- bookkeeping ------------------------------------------------------
    def _count(self, name: str, inc: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.counter(name, inc)

    def _stage_time(self, stage: str, seconds: float) -> None:
        with self._stage_lock:
            self._stage_s[stage] += seconds
        if self.metrics is not None:
            self.metrics.timer_update(f"geomesa.ingest.{stage}", seconds)

    def _note_chunk_bytes(self, nbytes: int) -> None:
        with self._stage_lock:
            if nbytes > self._peak_chunk_bytes:
                self._peak_chunk_bytes = nbytes
        if self.metrics is not None:
            self.metrics.gauge(
                "geomesa.ingest.chunk_bytes_peak", self._peak_chunk_bytes
            )

    def _fail(self, e: BaseException) -> None:
        with self._cv:
            if self._error is None:
                self._error = e
            chunks = list(self._chunks)
            self._cv.notify_all()
        # release every chunk event: a cancelled encode/sort future would
        # otherwise never set its chunk's event and the writer (and any
        # join on it) would hang waiting for a stage that will never run
        for ch in chunks:
            ch.event.set()
        # the pipeline is dead: reap the worker threads NOW, not at some
        # later close()/abort() a caller whose put() raised may never
        # reach (a service doing repeated failing loads would otherwise
        # accumulate idle pools). Safe from inside a worker thread
        # (wait=False never joins); close()'s shutdown stays idempotent.
        self._pool.shutdown(wait=False, cancel_futures=True)

    def _raise_if_failed(self) -> None:
        if self._error is not None:
            raise self._error

    # -- producer ---------------------------------------------------------
    def put(self, features: "FeatureCollection | Sequence") -> int:
        """Stage one chunk. Blocks when the admission window is full
        (bounded backpressure, counted by geomesa.ingest.queue_full).
        Raises immediately if any pipeline stage already failed."""
        if self._closed:
            raise RuntimeError("BulkLoader is closed")
        self._raise_if_failed()
        sft = self.store.get_schema(self.type_name)
        if not isinstance(features, FeatureCollection):
            features = FeatureCollection.from_rows(sft, features)
        if len(features) == 0:
            return 0  # empty chunks are a no-op, exactly like write()
        if not self._sem.acquire(blocking=False):
            self._count("geomesa.ingest.queue_full")
            while not self._sem.acquire(timeout=0.05):
                self._raise_if_failed()
        try:
            self._raise_if_failed()
        except BaseException:
            self._sem.release()
            raise
        with self._cv:
            # chunk index and global base offset assign under the lock:
            # concurrent producers must never mint overlapping ordinal
            # ranges (the sort permutation is built from these bases)
            ch = _Chunk(len(self._chunks), self._rows_staged, features)
            self._rows_staged += len(features)
            self._chunks.append(ch)
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._writer_loop, name="geomesa-ingest-writer",
                    daemon=True,
                )
                self._writer.start()
            self._cv.notify_all()
        self._pool.submit(self._encode, ch)
        self._count("geomesa.ingest.chunks")
        return len(features)

    # -- key + sort stages --------------------------------------------------
    def _encode(self, ch: _Chunk) -> None:
        try:
            fault_point("ingest.keys")
            t0 = time.perf_counter()
            _, keys, stats = self.store._encode_batch(self.type_name, ch.fc)
            ch.keys, ch.stats = keys, stats
            self._stage_time("keys", time.perf_counter() - t0)
            self._note_chunk_bytes(_chunk_nbytes(ch.fc, keys))
            # sub-keyed indexes (string attribute indexes) keep the
            # lexsort path at compact; no run to pre-sort
            pending = [
                name for name, k in keys.items() if len(k.zs) and k.sub is None
            ]
            if not pending:
                ch.event.set()
                return
            remaining = [len(pending)]
            lock = threading.Lock()
            for name in pending:
                self._pool.submit(self._sort_index, ch, name, remaining, lock)
        except BaseException as e:
            ch.error = e
            ch.event.set()
            self._fail(e)

    def _sort_index(self, ch: _Chunk, name: str, remaining: list, lock) -> None:
        try:
            fault_point("ingest.sort")
            t0 = time.perf_counter()
            k = ch.keys[name]
            ch.runs[name] = shsort.shard_runs(
                k.bins, k.zs, ch.base, self.config.chunk_rows
            )
            self._stage_time("sort", time.perf_counter() - t0)
        except BaseException as e:
            ch.error = e
            self._fail(e)
        finally:
            with lock:
                remaining[0] -= 1
                done = remaining[0] == 0
            if done:
                ch.event.set()

    # -- ordered writer stage ----------------------------------------------
    def _writer_loop(self) -> None:
        i = 0
        while True:
            with self._cv:
                while (
                    not self._closed
                    and i >= len(self._chunks)
                    and self._error is None
                ):
                    self._cv.wait()
                if self._error is not None:
                    return
                if i >= len(self._chunks):
                    return  # closed and drained
                ch = self._chunks[i]
            ch.event.wait()
            if ch.error is not None:
                self._sem.release()
                return  # _fail already recorded it
            try:
                t0 = time.perf_counter()
                fault_point("ingest.commit")
                self._stage_time("commit", time.perf_counter() - t0)
            except BaseException as e:
                self._fail(e)
                return
            finally:
                self._sem.release()
            i += 1

    # -- finalize -----------------------------------------------------------
    def abort(self) -> None:
        """Tear the pipeline down without publishing (the store stays
        untouched). Used by drivers whose OWN stage failed (e.g. a parse
        worker) — close() after abort() re-raises."""
        self._fail(IngestError("ingest aborted"))
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._writer is not None:
            self._writer.join()
        self._pool.shutdown(wait=True, cancel_futures=True)

    def close(self) -> IngestResult:
        """Drain the stages, k-way-merge the sorted runs, and publish every
        staged chunk ATOMICALLY (one write-lock section: either all rows
        become visible, compacted, or none do)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._writer is not None:
            self._writer.join()
        try:
            self._raise_if_failed()
            result = IngestResult(stage_seconds=self._stage_s)
            if not self._chunks:
                return result
            t0 = time.perf_counter()
            fault_point("ingest.finalize")
            result.written = self._publish()
            self._stage_time("finalize", time.perf_counter() - t0)
            self._count("geomesa.ingest.rows", result.written)
            result.stage_seconds = dict(self._stage_s)
            return result
        finally:
            self._pool.shutdown(wait=True, cancel_futures=True)

    def _publish(self) -> int:
        from geomesa_tpu.storage.delta import concat_keys

        chunks = self._chunks
        fcs = [ch.fc for ch in chunks]
        stats_list = [ch.stats for ch in chunks]
        # one concatenated WriteKeys per index; the chunk keys are
        # loader-private until this point, so consume= frees each chunk's
        # arrays as its columns concatenate (bounded transient, not 2x)
        keys_by_index: dict = {}
        runs_by_index: dict = {}
        for name in chunks[0].keys:
            runs_by_index[name] = [
                r for ch in chunks for r in ch.runs.get(name, [])
            ]
            keys_by_index[name] = concat_keys(
                [ch.keys[name] for ch in chunks], consume=True
            )
        presorted: dict = {}
        # a presorted perm only applies when the new rows ARE the whole
        # table (_bulk_commit discards it otherwise): skip the O(n log k)
        # merge + n*8B perm allocation entirely for appends to non-empty
        # stores — the normal delta compaction handles those. (A writer
        # racing this unlocked peek just downgrades to the same fallback.)
        store_not_empty = any(
            len(c) for c in self.store._chunks.get(self.type_name, [])
        )
        for name in list(runs_by_index):
            runs = runs_by_index.pop(name)  # released once merged
            keys = keys_by_index[name]
            if store_not_empty or keys.sub is not None or not runs:
                continue
            bins = shsort.distinct_bins(runs)
            if len(bins) < self.config.merge_min_bins:
                # §4f negative result: few bins -> the spanwise merge has
                # nothing to parallelize; let compact run the proven
                # whole-table LSD instead
                continue
            perm = shsort.merge_runs(runs, pool=self._pool, bins=bins)
            del runs
            if len(perm) != len(keys.zs):
                continue
            if len(perm) < 2**32:
                perm = perm.astype(np.uint32)  # native take() fast path
            presorted[name] = perm
        # the sorted run copies (~20 B/row per z index) are merge input
        # only: drop them BEFORE the publish + device build, so they
        # don't ride on top of the compaction's bounded peak
        for ch in chunks:
            ch.runs.clear()
        return self.store._bulk_commit(
            self.type_name,
            fcs,
            keys_by_index,
            stats_list,
            check_ids=self.check_ids,
            presorted=presorted or None,
        )


def raise_split_failure(failure, splits) -> None:
    """Re-raise a worker-side SplitFailure as IngestError (shared by the
    pipelined and sequential-commit drivers so message format and
    attributes can never diverge)."""
    raise IngestError(
        f"ingest split {failure.split_index} "
        f"({splits[failure.split_index].path}) failed in a worker "
        f"[{failure.exc_type}]:\n{failure.tb}",
        split_index=failure.split_index,
        worker_traceback=failure.tb,
    )


def rebase_ids(fc: FeatureCollection, base: int) -> FeatureCollection:
    """Running-index ids restart per split AND per run: rebase onto the
    store's row count (same semantics as the sequential CLI path) so
    repeat ingests and multi-split inputs never collide."""
    return FeatureCollection(
        fc.sft, np.arange(base, base + len(fc)).astype(str), fc.columns
    )


def ingest_files(
    store,
    converter,
    paths: Sequence[str],
    workers: Optional[int] = None,
    id_prefix_splits: bool = True,
    split_bytes: "int | None" = None,
    config: "PipelineConfig | None" = None,
    metrics=None,
) -> IngestResult:
    """Pipelined file ingest: a process pool parses input splits (stage 1)
    feeding a :class:`BulkLoader` (stages 2-4). ``workers=0/1`` parses
    in-process (the reference's local ingest mode) but still pipelines key
    computation and sorting. Split parse-error counts aggregate into
    ``IngestResult.split_errors`` ordered by split; a failed worker raises
    :class:`IngestError` carrying the worker traceback, and the store is
    left untouched (atomic ingest)."""
    cfg = config if config is not None else PipelineConfig.from_properties()
    if workers is not None and workers > 0:
        cfg = replace(cfg, workers=workers)
    conv_cfg = ConverterConfig.of(converter)
    type_name = converter.sft.name
    splits = plan_splits(paths, converter.fmt, split_bytes)
    result = IngestResult(splits=len(splits))
    if not splits:
        return result
    if workers is None:
        import os

        workers = min(len(splits), os.cpu_count() or 1)
    loader = BulkLoader(store, type_name, config=cfg, metrics=metrics)
    rebase = id_prefix_splits and converter.id_field is None
    # running-index rebase: seed from the store ONCE, then track locally;
    # the loader publishes atomically so no other count can interleave
    base = len(store.features(type_name)) if rebase else 0

    def feed(res) -> None:
        nonlocal base
        idx, fc, errors, reasons, parse_s, failure = res
        loader._stage_time("parse", parse_s)
        if failure is not None:
            raise_split_failure(failure, splits)
        result.split_errors.append(errors)
        result.errors += errors
        result.add_reasons(reasons)
        loader._count("geomesa.ingest.errors", errors)
        if len(fc) == 0:
            return
        if rebase:
            fc = rebase_ids(fc, base)
            base += len(fc)
        loader.put(fc)

    tasks = [(conv_cfg, sp, i) for i, sp in enumerate(splits)]
    try:
        if workers <= 1 or len(splits) <= 1:
            for t in tasks:
                feed(run_split_guarded(t))
        else:
            import multiprocessing as mp

            ctx = mp.get_context("fork")
            with ctx.Pool(min(workers, len(splits))) as pool:
                # imap streams results in SPLIT order: the ordered feed
                # overlaps conversion, and error aggregation stays
                # deterministic whatever the completion order was
                for res in pool.imap(run_split_guarded, tasks):
                    feed(res)
    except BaseException:
        loader.abort()
        raise
    closed = loader.close()
    result.written = closed.written
    result.stage_seconds = closed.stage_seconds
    return result
