"""Multi-core ingest: splits, the staged pipeline, and the sharded sort.

See docs/ingest.md. The package absorbs the split logic that lived in
``geomesa_tpu.io.ingest`` (which remains as the sequential-commit
compatibility surface) and adds the staged, bounded-queue pipeline that
overlaps parse / key-encoding / sorting / publishing across host cores.
"""

from geomesa_tpu.ingest.pipeline import (  # noqa: F401
    BulkLoader,
    IngestError,
    IngestResult,
    PipelineConfig,
    ingest_files,
)
from geomesa_tpu.ingest.sort import (  # noqa: F401
    SortRun,
    merge_runs,
    shard_runs,
    sort_chunk,
)
# NOTE: SPLIT_BYTES is deliberately NOT re-exported — patching a
# re-exported int is a silent no-op; the canonical knob lives in
# geomesa_tpu.ingest.splits (and io.ingest keeps its own legacy copy,
# read at call time). Pass split_bytes= explicitly to plan_splits /
# ingest_files instead.
from geomesa_tpu.ingest.splits import (  # noqa: F401
    ConverterConfig,
    Split,
    SplitFailure,
    plan_splits,
    run_split,
    run_split_guarded,
)
