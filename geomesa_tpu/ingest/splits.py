"""Input splits + the picklable converter config (the mapper-side half
of the distributed ingest).

Reference: ConverterInputFormat (/root/reference/geomesa-jobs/src/main/
scala/org/locationtech/geomesa/jobs/mapreduce/) splits inputs at byte
ranges and mappers rebuild the converter from the job config. This module
absorbs the split logic that used to live in ``io/ingest.py`` (that module
re-exports for compatibility): large delimited files split at line
boundaries into byte-range tasks so one big CSV parallelizes like many
small files; JSON/XML/Avro documents stay whole.

Workers run :func:`run_split_guarded`: the split read is a named fault
point (``ingest.split.read``) under bounded retry, and any worker failure
— including a :class:`~geomesa_tpu.fault.InjectedCrash`, which a
``multiprocessing`` pool would otherwise turn into a hung worker — comes
back as a *value* carrying the formatted traceback, so the driver can
re-raise deterministically (ordered by split) instead of losing the
worker-side stack.
"""

from __future__ import annotations

import os
import time
import traceback
from dataclasses import dataclass, field
from typing import Optional, Sequence

from geomesa_tpu.fault import fault_point, with_retries
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.io.converters import Converter, FieldSpec
from geomesa_tpu.sft import FeatureType

# a split per ~32 MB keeps task granularity reasonable for big files
SPLIT_BYTES = 32 << 20


@dataclass
class ConverterConfig:
    """Picklable converter description (the mapper-side job config)."""

    spec: str
    type_name: str
    fields: Sequence[tuple]  # (name, transform)
    id_field: Optional[str]
    fmt: str
    delimiter: str
    skip_lines: int
    drop_errors: bool
    xml_feature_tag: Optional[str]
    user_data: dict = field(default_factory=dict)
    # validator NAMES (io.validators.parse_validators spec) — the
    # picklable form. Custom Validator OBJECTS cannot cross the pool:
    # they ride ``live_validators`` instead, which works for the
    # in-process (workers <= 1) driver paths and raises the clear error
    # at PICKLE time if a pool ever tries to ship them (__getstate__).
    validators: Optional[str] = None
    live_validators: "object | None" = None

    @staticmethod
    def of(conv: Converter) -> "ConverterConfig":
        from geomesa_tpu.io.validators import validator_spec

        try:
            vspec, live = validator_spec(conv.validators), None
        except ValueError:
            vspec, live = None, conv.validators
        return ConverterConfig(
            spec=conv.sft.to_spec(),
            type_name=conv.sft.name,
            fields=[(f.name, f.transform) for f in conv.fields],
            id_field=conv.id_field,
            fmt=conv.fmt,
            delimiter=conv.delimiter,
            skip_lines=conv.skip_lines,
            drop_errors=conv.drop_errors,
            xml_feature_tag=conv.xml_feature_tag,
            user_data=dict(conv.sft.user_data),
            validators=vspec,
            live_validators=live,
        )

    def __getstate__(self):
        if self.live_validators is not None:
            raise ValueError(
                "custom Validator objects are not picklable for "
                "multi-process ingest; pass validator NAMES or run with "
                "workers<=1"
            )
        return self.__dict__

    def build(self) -> Converter:
        sft = FeatureType.from_spec(self.type_name, self.spec)
        sft.user_data.update(self.user_data)
        return Converter(
            sft=sft,
            fields=[FieldSpec(n, t) for n, t in self.fields],
            id_field=self.id_field,
            fmt=self.fmt,
            delimiter=self.delimiter,
            skip_lines=self.skip_lines,
            drop_errors=self.drop_errors,
            xml_feature_tag=self.xml_feature_tag,
            validators=(
                self.validators if self.live_validators is None
                else self.live_validators
            ),
        )


@dataclass(frozen=True)
class Split:
    """One mapper task: a byte range of one input file (the
    ConverterInputFormat split analogue). ``skip_header`` drops the
    configured header lines (first split of a delimited file only)."""

    path: str
    start: int
    end: int  # exclusive
    skip_header: bool


def plan_splits(
    paths: Sequence[str], fmt: str, split_bytes: int | None = None
) -> list[Split]:
    """Input files -> mapper splits. Only delimited files split mid-file
    (line-oriented); JSON/XML/Avro documents stay whole."""
    if split_bytes is None:
        split_bytes = SPLIT_BYTES  # read at call time so tests/config can tune
    out: list[Split] = []
    for path in paths:
        size = os.path.getsize(path)
        if fmt != "delimited" or size <= split_bytes:
            out.append(Split(path, 0, size, True))
            continue
        with open(path, "rb") as fh:
            start = 0
            while start < size:
                end = min(start + split_bytes, size)
                if end < size:  # advance to the next line boundary
                    fh.seek(end)
                    fh.readline()
                    end = fh.tell()
                out.append(Split(path, start, end, start == 0))
                start = end
    return out


def _read_split(split: Split) -> bytes:
    """One split's bytes, retried on transient IO errors (fault point
    ``ingest.split.read``)."""

    def attempt() -> bytes:
        fault_point("ingest.split.read", split.path)
        with open(split.path, "rb") as fh:
            fh.seek(split.start)
            return fh.read(split.end - split.start)

    return with_retries(attempt)


def run_split(cfg: ConverterConfig, split: Split):
    """Mapper: parse one split ->
    (FeatureCollection, n_errors, {reason: count})."""
    conv = cfg.build()
    if not split.skip_header:
        conv.skip_lines = 0
    data = _read_split(split)
    fc = conv.convert(data)
    fault_point("ingest.parse", split.path)
    return fc, conv.errors, dict(conv.error_reasons)


@dataclass
class SplitFailure:
    """A worker-side failure, shipped back as a value: the original
    exception type name plus the full formatted traceback (a forked
    worker's stack is otherwise lost — and a BaseException like
    InjectedCrash would wedge the pool instead of surfacing)."""

    split_index: int
    exc_type: str
    tb: str


def run_split_guarded(args):
    """Pool entry point: ``(cfg, split, index)`` ->
    ``(index, fc | None, n_errors, {reason: count}, parse_seconds,
    SplitFailure | None)``."""
    cfg, split, index = args
    t0 = time.perf_counter()
    try:
        fc, errors, reasons = run_split(cfg, split)
        return index, fc, errors, reasons, time.perf_counter() - t0, None
    except BaseException as e:  # includes InjectedCrash: see SplitFailure
        return index, None, 0, {}, time.perf_counter() - t0, SplitFailure(
            split_index=index,
            exc_type=type(e).__name__,
            tb=traceback.format_exc(),
        )
