"""Metrics registry: counters, gauges, timers.

Reference: geomesa-metrics (/root/reference/geomesa-metrics/
geomesa-metrics-micrometer/.../MicrometerSetup.scala) — dropwizard/
micrometer registries. The TPU build keeps one process-local registry with
the same three instrument kinds; ``snapshot()`` is the scrape surface for
any exporter (prometheus text rendering included for parity with the
reference's default registry).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass


@dataclass
class Timer:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    def update(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class MetricsRegistry:
    """Process-local metrics: counter / gauge / timer by dotted name.

    Thread-safe: one lock covers counters, gauges and timers — a bare
    ``defaultdict`` ``+=`` is a read-modify-write that loses increments
    under concurrent callers, and ``snapshot()``/``render_prometheus()``
    iterate dicts that can resize mid-update. The ``time()``
    contextmanager stays lock-free around the timed body; only the final
    :meth:`timer_update` takes the lock."""

    def __init__(self):
        from geomesa_tpu.lockwitness import witness

        self._lock = witness(threading.Lock(), "MetricsRegistry._lock")
        self.counters: dict[str, int] = defaultdict(int)    # guarded-by: _lock
        self.gauges: dict[str, float] = {}                  # guarded-by: _lock
        self.timers: dict[str, Timer] = defaultdict(Timer)  # guarded-by: _lock

    def counter(self, name: str, inc: int = 1) -> None:
        with self._lock:
            self.counters[name] += inc

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def counter_value(self, name: str) -> int:
        """Current value of one counter (0 when never incremented) —
        locked read for callers asserting on strategy counters
        (geomesa.join.*, tests, bench gates)."""
        with self._lock:
            return self.counters.get(name, 0)

    def timer_update(self, name: str, seconds: float) -> None:
        """Record one timed duration (the locked half of :meth:`time`;
        also the entry point for callers that measured the span
        themselves, e.g. DataStore.record_query)."""
        with self._lock:
            self.timers[name].update(seconds)

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timer_update(name, time.perf_counter() - t0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "timers": {
                    k: {"count": t.count, "mean_s": t.mean_s, "max_s": t.max_s}
                    for k, t in self.timers.items()
                },
            }

    def render_prometheus(self) -> str:
        """Prometheus text exposition of the registry. Timers emit
        ``_seconds_count`` / ``_seconds_sum`` / ``_seconds_max`` so both
        mean latency and the p-worst observation are scrapeable."""
        with self._lock:
            counters = sorted(self.counters.items())
            gauges = sorted(self.gauges.items())
            timers = sorted(
                (k, t.count, t.total_s, t.max_s) for k, t in self.timers.items()
            )
        lines = []
        for k, v in counters:
            lines.append(f"# TYPE {_prom(k)} counter")
            lines.append(f"{_prom(k)} {v}")
        for k, v in gauges:
            lines.append(f"# TYPE {_prom(k)} gauge")
            lines.append(f"{_prom(k)} {v}")
        for k, count, total_s, max_s in timers:
            base = _prom(k)
            lines.append(f"# TYPE {base}_seconds summary")
            lines.append(f"{base}_seconds_count {count}")
            lines.append(f"{base}_seconds_sum {total_s}")
            # the max is its OWN gauge family: strict OpenMetrics parsers
            # allow only _sum/_count/quantile samples inside a summary
            lines.append(f"# TYPE {base}_seconds_max gauge")
            lines.append(f"{base}_seconds_max {max_s}")
        return "\n".join(lines) + "\n"


def _prom(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


# process-global fallback registry: components that run without a
# configured store registry (streaming listener sweeps, quarantine events
# during load) still record their error counters somewhere scrapeable
_GLOBAL = MetricsRegistry()


def global_registry() -> MetricsRegistry:
    """The process-global fallback MetricsRegistry."""
    return _GLOBAL


def resolve(metrics: MetricsRegistry | None) -> MetricsRegistry:
    """The given registry, or the process-global fallback when None."""
    return _GLOBAL if metrics is None else metrics
