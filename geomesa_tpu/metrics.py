"""Metrics registry: counters, gauges, timers, histograms.

Reference: geomesa-metrics (/root/reference/geomesa-metrics/
geomesa-metrics-micrometer/.../MicrometerSetup.scala) — dropwizard/
micrometer registries. The TPU build keeps one process-local registry with
the same instrument kinds; ``snapshot()`` is the scrape surface for
any exporter (prometheus text rendering included for parity with the
reference's default registry).

The :class:`Histogram` instrument (docs/observability.md) is the live
latency surface the mean-only :class:`Timer` cannot provide: fixed
log-spaced buckets (sqrt-2 growth from 1 µs, so every bucket is within
~41% of its neighbors), one index add per observation, and quantiles
computed only at snapshot/scrape time — so "what is query p99 right
now" is answerable from the registry without offline post-processing.
Histograms render as proper Prometheus ``histogram`` families
(cumulative ``_bucket{le=…}`` including ``+Inf``, ``_sum``, ``_count``);
timers keep their summary + ``_seconds_max`` gauge exposition unchanged.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Timer:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    def update(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


# histogram bucket upper edges: 1 µs growing by sqrt(2) — 64 finite
# buckets cover 1 µs .. ~50 min, so one fixed ladder serves every
# latency this system records (cache probes to fold pauses) with a
# worst-case quantile error of one bucket width (~41%, i.e. half a
# power of two). A 65th overflow bucket catches anything larger.
HIST_EDGES: tuple = tuple(1e-6 * (2.0 ** (i / 2.0)) for i in range(64))
_N_BUCKETS = len(HIST_EDGES) + 1  # + overflow (+Inf)


@dataclass
class Histogram:
    """Fixed-log-bucket latency histogram: ``record`` is one bisect plus
    one index add (lock-cheap on the hot path); quantiles are computed
    on demand from a snapshot, never maintained online."""

    counts: list = field(default_factory=lambda: [0] * _N_BUCKETS)
    count: int = 0
    sum_s: float = 0.0

    def record(self, seconds: float) -> None:
        self.counts[bisect_left(HIST_EDGES, seconds)] += 1
        self.count += 1
        self.sum_s += seconds

    def quantile(self, q: float) -> float:
        """The q-quantile (0..1) with linear interpolation inside the
        bucket — within one bucket width of the exact order statistic."""
        if self.count == 0:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = HIST_EDGES[i - 1] if i > 0 else 0.0
                hi = HIST_EDGES[i] if i < len(HIST_EDGES) else HIST_EDGES[-1] * 2.0
                frac = (target - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return HIST_EDGES[-1] * 2.0  # pragma: no cover - unreachable

    @property
    def mean_s(self) -> float:
        return self.sum_s / self.count if self.count else 0.0


class MetricsRegistry:
    """Process-local metrics: counter / gauge / timer by dotted name.

    Thread-safe: one lock covers counters, gauges and timers — a bare
    ``defaultdict`` ``+=`` is a read-modify-write that loses increments
    under concurrent callers, and ``snapshot()``/``render_prometheus()``
    iterate dicts that can resize mid-update. The ``time()``
    contextmanager stays lock-free around the timed body; only the final
    :meth:`timer_update` takes the lock."""

    def __init__(self):
        from geomesa_tpu.lockwitness import witness

        self._lock = witness(threading.Lock(), "MetricsRegistry._lock")
        self.counters: dict[str, int] = defaultdict(int)    # guarded-by: _lock
        self.gauges: dict[str, float] = {}                  # guarded-by: _lock
        self.timers: dict[str, Timer] = defaultdict(Timer)  # guarded-by: _lock
        self.histograms: dict[str, Histogram] = defaultdict(Histogram)  # guarded-by: _lock
        # optional observation hook (the SLO tracker wires itself here):
        # called AFTER the registry lock is released, so the hook's own
        # lock (SloTracker._lock, rank 78) never nests under the
        # innermost registry lock (rank 80)
        self.observer = None

    def counter(self, name: str, inc: int = 1) -> None:
        with self._lock:
            self.counters[name] += inc

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def counter_value(self, name: str) -> int:
        """Current value of one counter (0 when never incremented) —
        locked read for callers asserting on strategy counters
        (geomesa.join.*, tests, bench gates)."""
        with self._lock:
            return self.counters.get(name, 0)

    def timer_update(self, name: str, seconds: float) -> None:
        """Record one timed duration (the locked half of :meth:`time`;
        also the entry point for callers that measured the span
        themselves, e.g. DataStore.record_query)."""
        with self._lock:
            self.timers[name].update(seconds)

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timer_update(name, time.perf_counter() - t0)

    def observe(self, name: str, seconds: float) -> None:
        """Record one observation (in seconds) into a histogram — the
        live-quantile instrument for hot-path latencies (query latency,
        queue wait, fold slice pause, WAL fsync, flush stages). The
        locked work is one bisect + index add; the attached observer
        hook (SLO tracking) runs after the lock is released."""
        with self._lock:
            self.histograms[name].record(seconds)
            obs = self.observer
        if obs is not None:
            obs(name, seconds)

    def histogram_quantile(self, name: str, q: float) -> float:
        """The q-quantile (0..1) of one histogram, computed from a
        locked snapshot of its buckets (0.0 when never observed)."""
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                return 0.0
            counts, count = list(h.counts), h.count
        snap = Histogram(counts=counts, count=count)
        return snap.quantile(q)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "timers": {
                    k: {"count": t.count, "mean_s": t.mean_s, "max_s": t.max_s}
                    for k, t in self.timers.items()
                },
                "histograms": {
                    k: {
                        "count": h.count,
                        "mean_s": h.mean_s,
                        "p50_s": h.quantile(0.50),
                        "p99_s": h.quantile(0.99),
                    }
                    for k, h in self.histograms.items()
                },
            }

    def render_prometheus(self) -> str:
        """Prometheus text exposition of the registry. Timers emit
        ``_seconds_count`` / ``_seconds_sum`` / ``_seconds_max`` so both
        mean latency and the p-worst observation are scrapeable.
        Histograms emit spec-correct ``histogram`` families: CUMULATIVE
        ``_bucket{le=…}`` samples (every non-empty bucket plus the
        mandatory ``+Inf``, whose value equals ``_count``), ``_sum`` and
        ``_count`` — so ``histogram_quantile()`` works in PromQL
        unmodified."""
        with self._lock:
            counters = sorted(self.counters.items())
            gauges = sorted(self.gauges.items())
            timers = sorted(
                (k, t.count, t.total_s, t.max_s) for k, t in self.timers.items()
            )
            hists = sorted(
                (k, list(h.counts), h.count, h.sum_s)
                for k, h in self.histograms.items()
            )
        lines = []
        for k, v in counters:
            lines.append(f"# TYPE {_prom(k)} counter")
            lines.append(f"{_prom(k)} {v}")
        for k, v in gauges:
            lines.append(f"# TYPE {_prom(k)} gauge")
            lines.append(f"{_prom(k)} {v}")
        for k, count, total_s, max_s in timers:
            base = _prom(k)
            lines.append(f"# TYPE {base}_seconds summary")
            lines.append(f"{base}_seconds_count {count}")
            lines.append(f"{base}_seconds_sum {total_s}")
            # the max is its OWN gauge family: strict OpenMetrics parsers
            # allow only _sum/_count/quantile samples inside a summary
            lines.append(f"# TYPE {base}_seconds_max gauge")
            lines.append(f"{base}_seconds_max {max_s}")
        for k, counts, count, sum_s in hists:
            base = _prom(k)
            lines.append(f"# TYPE {base}_seconds histogram")
            cum = 0
            for i, c in enumerate(counts[:-1]):
                if c == 0:
                    continue  # sparse: empty interior buckets add nothing
                cum += c
                lines.append(
                    f'{base}_seconds_bucket{{le="{_le(HIST_EDGES[i])}"}} {cum}'
                )
            lines.append(f'{base}_seconds_bucket{{le="+Inf"}} {count}')
            lines.append(f"{base}_seconds_sum {sum_s}")
            lines.append(f"{base}_seconds_count {count}")
        return "\n".join(lines) + "\n"


def _prom(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


def _le(edge: float) -> str:
    """Bucket upper-edge label: shortest round-trippable decimal, so
    scrapes stay stable across runs and parsers re-read the exact
    float."""
    return repr(edge)


# process-global fallback registry: components that run without a
# configured store registry (streaming listener sweeps, quarantine events
# during load) still record their error counters somewhere scrapeable
_GLOBAL = MetricsRegistry()


def global_registry() -> MetricsRegistry:
    """The process-global fallback MetricsRegistry."""
    return _GLOBAL


def resolve(metrics: MetricsRegistry | None) -> MetricsRegistry:
    """The given registry, or the process-global fallback when None."""
    return _GLOBAL if metrics is None else metrics
