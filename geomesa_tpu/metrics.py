"""Metrics registry: counters, gauges, timers.

Reference: geomesa-metrics (/root/reference/geomesa-metrics/
geomesa-metrics-micrometer/.../MicrometerSetup.scala) — dropwizard/
micrometer registries. The TPU build keeps one process-local registry with
the same three instrument kinds; ``snapshot()`` is the scrape surface for
any exporter (prometheus text rendering included for parity with the
reference's default registry).
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass


@dataclass
class Timer:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    def update(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class MetricsRegistry:
    """Process-local metrics: counter / gauge / timer by dotted name."""

    def __init__(self):
        self.counters: dict[str, int] = defaultdict(int)
        self.gauges: dict[str, float] = {}
        self.timers: dict[str, Timer] = defaultdict(Timer)

    def counter(self, name: str, inc: int = 1) -> None:
        self.counters[name] += inc

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = float(value)

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timers[name].update(time.perf_counter() - t0)

    def snapshot(self) -> dict:
        return {
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "timers": {
                k: {"count": t.count, "mean_s": t.mean_s, "max_s": t.max_s}
                for k, t in self.timers.items()
            },
        }

    def render_prometheus(self) -> str:
        """Prometheus text exposition of the registry."""
        lines = []
        for k, v in sorted(self.counters.items()):
            lines.append(f"# TYPE {_prom(k)} counter")
            lines.append(f"{_prom(k)} {v}")
        for k, v in sorted(self.gauges.items()):
            lines.append(f"# TYPE {_prom(k)} gauge")
            lines.append(f"{_prom(k)} {v}")
        for k, t in sorted(self.timers.items()):
            base = _prom(k)
            lines.append(f"# TYPE {base}_seconds summary")
            lines.append(f"{base}_seconds_count {t.count}")
            lines.append(f"{base}_seconds_sum {t.total_s}")
        return "\n".join(lines) + "\n"


def _prom(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


# process-global fallback registry: components that run without a
# configured store registry (streaming listener sweeps, quarantine events
# during load) still record their error counters somewhere scrapeable
_GLOBAL = MetricsRegistry()


def global_registry() -> MetricsRegistry:
    """The process-global fallback MetricsRegistry."""
    return _GLOBAL


def resolve(metrics: MetricsRegistry | None) -> MetricsRegistry:
    """The given registry, or the process-global fallback when None."""
    return _GLOBAL if metrics is None else metrics
