"""geomesa-lint: project-specific static analysis for geomesa_tpu.

The reference GeoMesa enforces its cross-cutting contracts (index
metadata registration, iterator configuration keys) through JVM typing
and a plugin SPI; this Python reproduction has neither, and three PRs
paid for it at review time (the PR 5 fused-chunk grouping key that
omitted the edge-bucket dimension, PR 3's retrofitted MetricsRegistry
locking, ~30 ``geomesa.*`` knobs whose declarations, read sites and
docs can drift independently). This package encodes those hard-won
invariants as machine-checked rules — the ``CqlValidatorFactory``
named-validator move (already ported for ingest in ``io/validators.py``)
aimed at the codebase itself.

Layout:

- :mod:`~geomesa_tpu.analysis.core` — the Rule SPI, per-file AST cache,
  :class:`~geomesa_tpu.analysis.core.Finding` objects and the
  suppression baseline;
- :mod:`~geomesa_tpu.analysis.registries` — the shared source of truth
  for configuration knobs (``conf.py``), metric instrument names, and
  schema user-data keys, extracted from the AST (also consumed by
  ``tests/test_docs.py`` so docs and code compare against ONE registry);
- :mod:`~geomesa_tpu.analysis.rules` — the project-specific rule
  families (knob registry, metrics registry, fused variant key, lock
  discipline, kernel purity, script hygiene).

Run it via ``python scripts/check.py`` (human or ``--json`` output) or
through ``tests/test_static_analysis.py``, which makes a clean tree a
tier-1 invariant. Pure stdlib (ast/re/os): no jax import, so a full-repo
run costs well under the 10 s budget. See docs/analysis.md.
"""

from geomesa_tpu.analysis.core import (  # noqa: F401
    Finding,
    Project,
    Rule,
    load_baseline,
    run_rules,
)
from geomesa_tpu.analysis.rules import ALL_RULES  # noqa: F401


def run(root=None, rule_ids=None, baseline=None):
    """Analyze the repo at ``root`` (default: this checkout) with the
    shipped rules; returns (findings, suppressed) after baseline
    filtering. The one-call surface scripts/check.py and the tests use."""
    import os

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    project = Project.load(root)
    rules = [r for r in ALL_RULES if rule_ids is None or r.id in rule_ids]
    return run_rules(project, rules, baseline=baseline)
