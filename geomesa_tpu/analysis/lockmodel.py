"""The whole-repo lock model: registry, acquisition graph, rank order.

Every hard bug shipped since the lint suite landed has been a
concurrency race (the WAL/checkpoint acknowledged-loss races the PR 9
chaos harness caught, the ``_take_staged`` write-back and ``_rotate``
sync-horizon races of PR 11). The reference delegates this bug class to
the JVM memory model and battle-tested region-server code; this build
owns its lock-bearing modules outright, so — following the
lock-guarded-mutation precedent — the locking DESIGN itself becomes a
machine-checked artifact:

- :data:`LOCKS` — the registry, ONE entry per production lock: dotted
  ``Class.attr`` name, declared **rank** (locks may only be acquired in
  strictly increasing rank order — the FindBugs-era GoodLock discipline),
  a **hot** flag (scopes holding a hot lock must never block on IO,
  futures or sleeps — the blocking-under-lock rule), and the **guarded
  fields** the ``# guarded-by:`` annotations declare (cross-checked both
  directions);
- :data:`DECLARED_EDGES` — acquisition-order edges real control flow
  takes through CALLBACKS the AST cannot resolve (the hot tier's
  WAL/unstage hooks, fault points consulting a chaos schedule). Each
  carries its justification and still must respect the rank order;
- :class:`LockModel` — the compositional analysis (the RacerD move:
  per-method lock-acquisition summaries joined to a fixpoint, one level
  of ``self.attr`` type inference from constructor assignments): every
  lock construction site discovered, every statically visible
  acquisition edge derived with its witness location.

The model is consumed three ways: the ``analysis/rules/concurrency.py``
rule family (static tier), ``tests/test_lock_witness.py`` (the dynamic
tier proves observed runtime edges are a subgraph of the model and that
every registered lock is actually witnessed — both directions, the way
``fault-point-unknown`` proves fault points are reached), and the
``docs/concurrency.md`` registry table (``tests/test_docs.py`` derives
its honesty checks from :data:`LOCKS`).

Locks outside the concurrent tiers (a module-level memo lock with no
nesting, e.g. ``planning/planner.py``'s config-memo lock) are still
DISCOVERED and participate in cycle checks, but only locks in
:data:`ENFORCED_SCOPES` must carry a registry entry. Fixtures and
adopter code can declare ranks inline instead: a trailing
``# lock-rank: <N>`` (optionally ``# lock-rank: <N> hot``) comment on
the lock construction line, mirroring ``# guarded-by:``.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

from geomesa_tpu.analysis.core import (
    Project,
    SourceFile,
    call_name,
    const_str,
    self_attr,
)

#: mutual-exclusion constructors the model tracks (Semaphore/Event are
#: deliberately out: they are signaling primitives, not critical-section
#: owners, and the ordering discipline does not apply to them)
LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

#: the witness wrapper (geomesa_tpu/lockwitness.py): construction sites
#: read ``witness(threading.RLock(), "<Class.attr>")`` — the model (and
#: the lock-guarded-mutation rule) look through it
WITNESS_WRAPPER = "witness"

_RANK_RE = re.compile(r"#\s*lock-rank:\s*(\d+)(\s+hot)?")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(?:self\.)?(\w+)")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*(?:self\.)?(\w+)")


@dataclass(frozen=True)
class LockDecl:
    """One registry entry: the declared half of the lock model."""

    name: str            # "Class.attr" (the witness name, the doc name)
    path: str            # module that constructs it
    rank: int            # strict acquisition order: lower acquires first
    hot: bool = False    # hot-path lock: no blocking calls while held
    fields: tuple = ()   # the `# guarded-by:` fields it protects
    doc: str = ""


def _d(name, path, rank, hot=False, fields=(), doc=""):
    return LockDecl(name, path, rank, hot, tuple(fields), doc)


#: THE lock registry — single source of truth for rank order, hot-path
#: classification and guarded-field coverage across the concurrent
#: tiers. Rank numbers are sparse on purpose (new locks slot between
#: neighbors without renumbering). Outermost (lowest rank) first.
LOCKS: dict[str, LockDecl] = {d.name: d for d in [
    _d("HostGroup._probe_lock", "geomesa_tpu/pod/hostgroup.py", 6,
       fields=("link_rtts_ms", "slot_caps"),
       doc="per-host link profile (probed RTTs + derived fused slot "
           "caps): a LEAF acquired before any store/table lock — "
           "profiles install at group construction, before tables "
           "build, and shard builds only READ the caps after release"),
    _d("PodStore._route_lock", "geomesa_tpu/pod/store.py", 8,
       fields=("_next_id",),
       doc="pod-level id assignment for ownership routing: ranks BELOW "
           "every host store's locks (DataStore._write_lock 10 up) "
           "because a routed write next descends into one host's "
           "LambdaStore; held only around the id counter, never across "
           "host calls"),
    _d("DataStore._write_lock", "geomesa_tpu/datastore.py", 10,
       fields=("_publish_seq", "_fold_progress"),
       doc="store mutation lock: writes/compactions/folds serialize; "
           "outermost by design (long holds around device builds)"),
    _d("DataStore._id_lock", "geomesa_tpu/datastore.py", 12,
       doc="per-chunk id-index entry cache only; readers skip the "
           "write lock"),
    _d("SegmentShipper._lock", "geomesa_tpu/streaming/replica.py", 14,
       fields=("_followers", "_gave_up", "_seq"),
       doc="shipper bookkeeping only (follower table, give-up report, "
           "attach ids); never held across WAL reads, transport sends "
           "or metrics — the pump snapshots under it then ships "
           "outside"),
    _d("ReplicaStore._apply_lock", "geomesa_tpu/streaming/replica.py", 16,
       fields=("_replayed", "_term", "_marks"),
       doc="follower watermark state (replayed seqno, witnessed term, "
           "staleness marks); pure bookkeeping — apply/promote do all "
           "store, WAL and file work OUTSIDE it"),
    _d("QueryScheduler._cond", "geomesa_tpu/serving/scheduler.py", 20,
       hot=True,
       fields=("_queues", "_depth", "_closed", "_thread"),
       doc="admission queue condition: every submit/dispatch crosses it "
           "(per-tenant deques + the shared depth counter)"),
    _d("TenantRegistry._lock", "geomesa_tpu/serving/tenancy.py", 22,
       fields=("_tenants",),
       doc="multi-tenant fairness table (weights, quotas, accounting): "
           "a LEAF by design — the scheduler reads quotas/weights "
           "BEFORE taking its condition, accounting lands after locks "
           "release, and per-tenant SLO observations go through each "
           "tenant's own SloTracker lock after this one releases"),
    _d("BulkLoader._cv", "geomesa_tpu/ingest/pipeline.py", 24,
       fields=("_chunks", "_rows_staged", "_closed", "_error", "_writer"),
       doc="staged-chunk condition between producers and the ordered "
           "writer"),
    _d("BulkLoader._stage_lock", "geomesa_tpu/ingest/pipeline.py", 26,
       fields=("_stage_s", "_peak_chunk_bytes"),
       doc="stage wall-time accounting"),
    _d("StreamingFeatureCache._lock", "geomesa_tpu/streaming/cache.py", 30,
       hot=True,
       fields=("index", "_rows", "_ingest_ms", "_next_id", "_ids_version",
               "_live_cache", "_replaying"),
       doc="THE hot-tier lock: every streaming write, snapshot and "
           "query serializes here; WAL/unstage hooks run under it"),
    _d("StreamFlusher._stage_lock", "geomesa_tpu/streaming/flush.py", 34,
       fields=("_staged", "_staged_rows"),
       doc="pre-staged fold chunks; acquired under the hot lock by the "
           "delete/expire unstage hooks, so it ranks above it"),
    _d("StreamFlusher._pool_lock", "geomesa_tpu/streaming/flush.py", 36,
       fields=("_pool",),
       doc="flush worker-pool lifecycle"),
    _d("LambdaStore._sub_lock", "geomesa_tpu/streaming/store.py", 38,
       fields=("_sub_records",),
       doc="standing-subscription registry vs checkpoint re-log "
           "(docs/standing.md): subscribe/unsubscribe and the "
           "checkpoint's live-set re-log serialize so an acknowledged "
           "unsubscribe's rm record can never be outrun by a re-logged "
           "registration on replay; held AROUND the WAL appends and "
           "SubscriptionIndex mutations those paths make (rank above)"),
    _d("WriteAheadLog._sync_lock", "geomesa_tpu/streaming/wal.py", 40,
       fields=("_synced_seq", "_last_sync_t", "_durable_bytes"),
       doc="commit (write+fsync) order; fsync happens HERE, never under "
           "the append lock"),
    _d("WriteAheadLog._lock", "geomesa_tpu/streaming/wal.py", 42,
       hot=True,
       fields=("_buffer", "_pending", "_closed", "_fd", "_active_path",
               "_active_start", "_active_bytes", "_last_seq", "_term"),
       doc="append buffer/seqno/fd state: every acknowledged write "
           "crosses it, so nothing may block while holding it"),
    _d("SubscriptionIndex._lock", "geomesa_tpu/streaming/standing.py", 44,
       hot=True,
       fields=("_ids", "_by_id", "_alive", "_alive_arr", "_kind_l",
               "_attrs", "_edges_l", "_bbox_l", "_rect_l", "_prox",
               "_tube", "_rast", "_csr", "_overlay", "_overlay_n",
               "_bulk", "_arrays", "_kernel_blocks"),
       doc="the inverted subscription index: registrations, the CSR "
           "routing tables and the kernel-block memo; route() snapshots "
           "under it then expands candidates outside (pure numpy only "
           "while held — it sits on every batch's match path)"),
    _d("_MatchGate._lock", "geomesa_tpu/streaming/standing.py", 45,
       hot=True,
       fields=("_host", "_fused"),
       doc="fused/host cost-gate EWMAs: read by every batch's candidate "
           "pick and updated after every matcher path runs — pure "
           "arithmetic under it, no other lock ever held"),
    _d("WindowedAggregator._lock", "geomesa_tpu/streaming/standing.py", 46,
       hot=True,
       fields=("_panes",),
       doc="continuous-window pane partials: folded per batch on the "
           "match path, and under the hot-tier lock when the aggregator "
           "is wired as a FeatureStream sink (listeners fire under it)"),
    _d("AlertQueue._lock", "geomesa_tpu/streaming/standing.py", 48,
       hot=True,
       fields=("_q", "_n", "_dropped"),
       doc="bounded alert queue: producers enqueue on the match path, "
           "consumers drain concurrently; overflow drops under the "
           "lock, counters record after it releases"),
    _d("ResultCache._lock", "geomesa_tpu/cache/result.py", 50,
       hot=True,
       fields=("_entries", "_inflight", "_bytes"),
       doc="result-cache LRU + single-flight bookkeeping (probed at "
           "admission by the serving tier)"),
    _d("TileAggregateCache._lock", "geomesa_tpu/cache/tiles.py", 52,
       fields=("_tiles", "_scan_s", "_compose_s", "_probe"),
       doc="tile LRU + adaptive cost-gate EWMAs"),
    _d("TilePyramid._lock", "geomesa_tpu/tiles/pyramid.py", 54,
       fields=("_deltas", "_dirty_leaves", "_leaf_scan_s"),
       doc="pyramid delta accounting + leaf-scan cost EWMA: taken "
           "briefly by note_delta (under the store write lock) and "
           "after a leaf scan completes — never held across a scan or "
           "another cache tier's lock"),
    _d("GenerationTracker._lock", "geomesa_tpu/cache/generations.py", 60,
       hot=True,
       fields=("_tick", "_types"),
       doc="generation bumps/staleness checks; acquired under the hot "
           "and cache locks on every mutation"),
    _d("ChaosSpec._lock", "geomesa_tpu/fault.py", 70,
       hot=True,
       fields=("hits", "fired", "log"),
       doc="seeded chaos schedule state; consulted at fault points, "
           "which fire under arbitrary outer locks"),
    _d("EstimateAccuracy._lock", "geomesa_tpu/obs/accuracy.py", 74,
       hot=True,
       fields=("_windows", "_analyzing"),
       doc="per-(type, index) estimate-vs-actual error windows: fed on "
           "every query's record path (possibly under the store write "
           "lock — modify_features queries in-lock), read by /health; "
           "only arithmetic runs under it and it acquires no other "
           "lock"),
    _d("Tracer._lock", "geomesa_tpu/obs/trace.py", 76,
       hot=True,
       fields=("buffer", "slow", "_n_roots"),
       doc="trace retention rings + sampling counter: taken once per "
           "root begin/end, never per child span; nothing blocking "
           "runs under it and it acquires no other lock"),
    _d("TuningManager._lock", "geomesa_tpu/tuning/manager.py", 77,
       fields=("_queries", "_pulses", "_pulsing", "_decisions"),
       doc="tuning pacing counters + the decision ring + the pulse "
           "claim flag: a LEAF by design — every sense/adjust step "
           "(metrics reads, accuracy report, SLO burn, conf writes) "
           "runs OUTSIDE it between claim and release; only arithmetic "
           "and the deque extend ever hold it"),
    _d("TelemetryRecorder._lock", "geomesa_tpu/obs/ops.py", 79,
       fields=("_rings",),
       doc="telemetry history rings: the 1 Hz sampler appends points "
           "computed BEFORE the lock (the registry snapshot never runs "
           "under it), /debug/vars scrapes copy under it"),
    _d("SloTracker._lock", "geomesa_tpu/obs/slo.py", 78,
       hot=True,
       fields=("_windows",),
       doc="SLO sliding windows: observations arrive via the registry "
           "observer hook (invoked OUTSIDE the registry lock) under "
           "arbitrary store locks, so it nests innermost-but-one"),
    _d("MetricsRegistry._lock", "geomesa_tpu/metrics.py", 80,
       hot=True,
       fields=("counters", "gauges", "timers", "histograms"),
       doc="innermost by design: instruments are recorded under every "
           "other lock in the tree"),
]}

#: acquisition edges real control flow takes through callbacks the AST
#: cannot resolve statically (hooks, listeners, injected fault points).
#: Each entry: (source lock, acquired lock, justification). They are
#: part of the PREDICTED graph the dynamic witness checks against, and
#: the rank checker validates them like any AST-derived edge.
DECLARED_EDGES: list[tuple[str, str, str]] = [
    ("StreamingFeatureCache._lock", "WriteAheadLog._lock",
     "delete/expire log apply-then-record atomically under the hot lock "
     "via the after_remove/on_swept hooks (LambdaStore._removed_hook)"),
    ("StreamingFeatureCache._lock", "WriteAheadLog._sync_lock",
     "the hook's WAL append group-commits (sync=always) while the hot "
     "lock is held"),
    ("StreamingFeatureCache._lock", "StreamFlusher._stage_lock",
     "the delete/expire hooks unstage removed rows' pre-staged fold "
     "chunks under the hot lock"),
    ("StreamingFeatureCache._lock", "GenerationTracker._lock",
     "hot-tier mutations bump the wired cold-cache generations under "
     "the hot lock (_bump_gen)"),
    ("StreamingFeatureCache._lock", "MetricsRegistry._lock",
     "listener-error counters and hook-side instruments record under "
     "the hot lock"),
    ("StreamingFeatureCache._lock", "ChaosSpec._lock",
     "WAL fault points consulted by the hook path while the hot lock "
     "is held"),
    ("WriteAheadLog._sync_lock", "ChaosSpec._lock",
     "the stream.wal.sync fault point fires under the sync lock and "
     "consults an armed chaos schedule"),
    ("WriteAheadLog._lock", "ChaosSpec._lock",
     "the stream.wal.append fault point can re-fire inside retry paths "
     "holding the append lock"),
    ("DataStore._write_lock", "StreamingFeatureCache._lock",
     "fold/flush publishes run under the store write lock and snapshot "
     "or evict the hot tier"),
    ("DataStore._write_lock", "QueryScheduler._cond",
     "the sliced fold's pacer (fold_yield) waits for the scheduler's "
     "admission queue to drain between slices"),
    ("DataStore._write_lock", "StreamFlusher._stage_lock",
     "the fold consumes pre-staged chunks under the write lock"),
    ("DataStore._write_lock", "StreamFlusher._pool_lock",
     "the fold's commit path ensures the warm pool under the write lock"),
    ("DataStore._write_lock", "WriteAheadLog._sync_lock",
     "flush watermarks append (and group-commit) inside the publish"),
    ("DataStore._write_lock", "WriteAheadLog._lock",
     "flush watermarks append inside the publish"),
    ("DataStore._write_lock", "GenerationTracker._lock",
     "every committed mutation bumps generations"),
    ("DataStore._write_lock", "TileAggregateCache._lock",
     "mutation-side cache sweeps touch the tile tier"),
    ("DataStore._write_lock", "TilePyramid._lock",
     "every committed mutation's on_mutation forwards delta-to-tile "
     "accounting to the attached pyramid (note_delta) under the write "
     "lock"),
    ("DataStore._write_lock", "ResultCache._lock",
     "mutation-side cache sweeps touch the result tier"),
    ("DataStore._write_lock", "ChaosSpec._lock",
     "persist/flush fault points fire inside write-locked publishes"),
    ("DataStore._write_lock", "MetricsRegistry._lock",
     "publish/flush instruments record under the write lock"),
    ("QueryScheduler._cond", "MetricsRegistry._lock",
     "queue-full shed/backpressure counters record under the condition"),
    ("BulkLoader._cv", "MetricsRegistry._lock",
     "writer-loop stage accounting records under the condition"),
    ("DataStore._write_lock", "SloTracker._lock",
     "the sliced fold's per-slice histogram observation fans out to "
     "the attached SLO tracker through the registry observer hook "
     "(invoked after the registry lock releases, write lock still "
     "held)"),
    ("QueryScheduler._cond", "Tracer._lock",
     "a shed or closed-scheduler admission finishes the caller's trace "
     "root (Tracer.end retains it) while the condition is held"),
    ("DataStore._write_lock", "Tracer._lock",
     "maintenance ops that query inside their write-locked section "
     "(modify_features) begin/end the query's trace root there"),
    ("StreamingFeatureCache._lock", "SloTracker._lock",
     "the hook path's WAL fsync histogram observation reaches the SLO "
     "windows through the registry observer hook under the hot lock"),
    ("StreamingFeatureCache._lock", "WindowedAggregator._lock",
     "a WindowedAggregator wired as a FeatureStream sink folds rows "
     "inside the hot tier's listener callback, which fires under the "
     "hot lock (docs/standing.md 'Windows over a FeatureStream')"),
    ("LambdaStore._sub_lock", "SubscriptionIndex._lock",
     "subscribe/unsubscribe mutate the inverted index (register/"
     "unregister) while holding the subscription-registry lock — the "
     "lazily-attached engine is behind self.standing(), one hop past "
     "the AST's one-level attr inference"),
    ("LambdaStore._sub_lock", "ChaosSpec._lock",
     "the WAL append/sync fault points consult an armed chaos schedule "
     "inside log_subscribe/log_unsubscribe under the registry lock"),
    ("LambdaStore._sub_lock", "SloTracker._lock",
     "the subscribe-path WAL fsync histogram observation reaches the "
     "SLO windows through the registry observer hook under the "
     "registry lock"),
    ("DataStore._write_lock", "EstimateAccuracy._lock",
     "maintenance ops that query inside their write-locked section "
     "(modify_features) reach record_query's estimate-accountability "
     "record while the write lock is held"),
]

#: hot-lock blocking the design ACCEPTS, with its justification — the
#: witness excludes these (lock name, fault-point fnmatch pattern)
#: pairs from its no-blocking-under-hot-locks assertion; anything else
#: observed under a hot lock fails tier-1. Keep this list SHORT: every
#: entry is a documented latency cost on a hot path.
DECLARED_BLOCKING: list[tuple[str, str, str]] = [
    ("StreamingFeatureCache._lock", "stream.wal.*",
     "destructive ops (delete/expiry sweep) log APPLY-THEN-RECORD "
     "atomically under the hot lock — the WAL's documented durability "
     "asymmetry (streaming/store.py): a delete record can never outrun "
     "a later acknowledged re-upsert on replay. Deletes are rare next "
     "to writes, which log OUTSIDE the hot lock."),
]

#: production trees where every discovered lock MUST carry a LOCKS
#: entry (the concurrent tiers the model exists for). Locks discovered
#: elsewhere still join the graph; rank comes from inline annotations
#: when present.
ENFORCED_SCOPES = (
    "geomesa_tpu/streaming/", "geomesa_tpu/serving/", "geomesa_tpu/cache/",
    "geomesa_tpu/ingest/", "geomesa_tpu/metrics.py", "geomesa_tpu/fault.py",
    "geomesa_tpu/datastore.py", "geomesa_tpu/obs/", "geomesa_tpu/pod/",
)

#: attribute-name type hints for cross-class call resolution where the
#: constructor assignment is opaque (wired post-construction, or built
#: through a factory): attr name -> owning class name
ATTR_TYPE_HINTS = {
    "metrics": "MetricsRegistry",
    "generations": "GenerationTracker",
    "hot": "StreamingFeatureCache",
    "flusher": "StreamFlusher",
    "wal": "WriteAheadLog",
    "scheduler": "QueryScheduler",
    "slo": "SloTracker",
    "accuracy": "EstimateAccuracy",
    "recorder": "TelemetryRecorder",
}

# the model's presence marker (the FaultPointRule convention: staged
# mini-repos without this file skip registry-side checks)
MODEL_PATH = "geomesa_tpu/analysis/lockmodel.py"


@dataclass(frozen=True)
class LockSite:
    """One discovered lock construction."""

    name: str          # "Class.attr"
    cls: str
    attr: str
    path: str
    line: int
    kind: str          # lock | rlock | condition
    rank: Optional[int] = None    # inline `# lock-rank:` if any
    hot: bool = False             # inline annotation
    witness_name: Optional[str] = None  # the witness() name argument


@dataclass(frozen=True)
class LockEdge:
    """Lock ``dst`` acquired while ``src`` is statically held."""

    src: str
    dst: str
    path: str
    line: int
    via: str           # "" for direct nesting, else the resolved callee


def lock_ctor(node: ast.AST) -> "tuple[str, str | None] | None":
    """``(kind, witness_name)`` when ``node`` constructs a tracked lock:
    ``threading.RLock()`` directly, or wrapped as
    ``witness(threading.RLock(), "Class.attr")``."""
    if not isinstance(node, ast.Call):
        return None
    cn = call_name(node)
    if cn in LOCK_CTORS:
        return LOCK_CTORS[cn], None
    if cn == WITNESS_WRAPPER and node.args:
        inner = node.args[0]
        if isinstance(inner, ast.Call) and call_name(inner) in LOCK_CTORS:
            wname = (
                const_str(node.args[1]) if len(node.args) > 1 else None
            )
            return LOCK_CTORS[call_name(inner)], wname
    return None


def _class_methods(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class _ClassInfo:
    """Per-class analysis state."""

    def __init__(self, sf: SourceFile, node: ast.ClassDef):
        self.sf = sf
        self.node = node
        self.name = node.name
        self.locks: dict[str, LockSite] = {}      # attr -> site
        self.attr_types: dict[str, str] = {}      # attr -> class name
        self.guarded: dict[str, tuple[str, int]] = {}  # field -> (lock, line)
        self.methods: dict[str, ast.AST] = {
            m.name: m for m in _class_methods(node)
        }

    def lock_name(self, attr: str) -> str:
        return f"{self.name}.{attr}"


class LockModel:
    """The derived whole-repo lock model (cached per Project)."""

    def __init__(self):
        self.sites: dict[str, LockSite] = {}     # name -> site
        self.classes: dict[str, _ClassInfo] = {}
        self.edges: list[LockEdge] = []
        self._edge_keys: set[tuple[str, str]] = set()
        # per-(class, method) transitive acquisition summaries
        self._acquires: dict[tuple[str, str], set[str]] = {}

    # -- public surface ---------------------------------------------------
    @classmethod
    def of(cls, project: Project) -> "LockModel":
        cached = getattr(project, "_lint_lockmodel", None)
        if cached is not None:
            return cached
        model = cls()
        model._build(project)
        project._lint_lockmodel = model  # type: ignore[attr-defined]
        return model

    def rank_of(self, name: str) -> Optional[int]:
        d = LOCKS.get(name)
        if d is not None:
            return d.rank
        s = self.sites.get(name)
        return s.rank if s is not None else None

    def is_hot(self, name: str) -> bool:
        d = LOCKS.get(name)
        if d is not None:
            return d.hot
        s = self.sites.get(name)
        return bool(s is not None and s.hot)

    def predicted_edges(self) -> set[tuple[str, str]]:
        """The full predicted acquisition-order edge set: AST-derived
        plus declared (callback) edges — what the dynamic lock witness
        checks observed runtime edges against."""
        out = {(e.src, e.dst) for e in self.edges}
        out.update((a, b) for a, b, _ in DECLARED_EDGES)
        return out

    def cycles(self) -> list[list[str]]:
        """Elementary cycles (as lock-name paths) in the predicted
        graph, self-loops excluded (re-entrancy is checked separately).
        Deterministic order."""
        graph: dict[str, set[str]] = {}
        for a, b in self.predicted_edges():
            if a != b:
                graph.setdefault(a, set()).add(b)
        cycles: list[list[str]] = []
        seen_keys: set[tuple] = set()

        def dfs(start: str, node: str, path: list[str], on_path: set[str]):
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    cyc = path[:]
                    key = tuple(sorted(cyc))
                    if key not in seen_keys:
                        seen_keys.add(key)
                        cycles.append(cyc + [start])
                elif nxt not in on_path and nxt > start:
                    # canonical: only walk nodes ordered after the start,
                    # so each cycle is found once from its least node
                    on_path.add(nxt)
                    dfs(start, nxt, path + [nxt], on_path)
                    on_path.discard(nxt)

        for start in sorted(graph):
            dfs(start, start, [start], {start})
        return cycles

    # -- build ------------------------------------------------------------
    def _build(self, project: Project) -> None:
        for sf in project.python_files():
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    self._scan_class(sf, node)
        self._summarize()
        self._derive_edges()

    def _scan_class(self, sf: SourceFile, node: ast.ClassDef) -> None:
        info = _ClassInfo(sf, node)
        for method in _class_methods(node):
            locals_types: dict[str, str] = {}
            for stmt in ast.walk(method):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                value = stmt.value
                ctor = lock_ctor(value) if value is not None else None
                for t in targets:
                    attr = self_attr(t)
                    if attr is None:
                        # local typed from a project-class constructor:
                        # `wal = WriteAheadLog(...)` then `self.wal = wal`
                        if (
                            isinstance(t, ast.Name)
                            and isinstance(value, ast.Call)
                        ):
                            locals_types[t.id] = call_name(value)
                        continue
                    if ctor is not None:
                        kind, wname = ctor
                        line = sf.source_line(stmt.lineno)
                        m = _RANK_RE.search(line)
                        site = LockSite(
                            name=info.lock_name(attr), cls=info.name,
                            attr=attr, path=sf.relpath, line=stmt.lineno,
                            kind=kind,
                            rank=int(m.group(1)) if m else None,
                            hot=bool(m and m.group(2)),
                            witness_name=wname,
                        )
                        info.locks[attr] = site
                        # first site wins (same-named classes in
                        # fixtures shadow production entries only for
                        # their own synthetic class name)
                        self.sites.setdefault(site.name, site)
                        continue
                    # attribute type inference for call resolution
                    tname = None
                    if isinstance(value, ast.Call):
                        tname = call_name(value)
                    elif isinstance(value, ast.Name):
                        tname = locals_types.get(value.id)
                    if tname:
                        info.attr_types.setdefault(attr, tname)
                    gm = _GUARDED_RE.search(sf.source_line(stmt.lineno))
                    if gm:
                        info.guarded.setdefault(
                            attr, (gm.group(1), stmt.lineno)
                        )
        if info.locks or info.guarded:
            # same-named classes: production entry wins; fixtures use
            # unique class names by convention
            self.classes.setdefault(info.name, info)

    # -- method summaries (the compositional pass) ------------------------
    def _initial_held(self, info: _ClassInfo, method) -> set[str]:
        """Locks a method's BODY runs under by contract: `# holds-lock:`
        on or just under the def line, or the *_locked suffix when the
        class owns exactly one lock (multi-lock classes must annotate —
        guessing 'all locks' would fabricate edges from locks not
        actually held)."""
        held: set[str] = set()
        for attr in holds_lock_decls(info.sf, method):
            if attr in info.locks:
                held.add(info.lock_name(attr))
        if not held and method.name.endswith("_locked") and len(info.locks) == 1:
            held.add(info.lock_name(next(iter(info.locks))))
        return held

    def _resolve_call(self, info: _ClassInfo, node: ast.Call):
        """``(class name, method name)`` for self.m() / self.attr.m()
        calls the model can resolve, else None."""
        f = node.func
        if not isinstance(f, ast.Attribute):
            return None
        base = f.value
        if isinstance(base, ast.Name) and base.id == "self":
            if f.attr in info.methods:
                return (info.name, f.attr)
            return None
        attr = self_attr(base)
        if attr is not None:
            tname = info.attr_types.get(attr)
            if tname not in self.classes:
                # constructor assignment opaque (a factory like
                # `resolve(metrics)`, or wired post-construction):
                # fall back to the declared attribute-name hints
                tname = ATTR_TYPE_HINTS.get(attr)
            if tname in self.classes and f.attr in self.classes[tname].methods:
                return (tname, f.attr)
        return None

    def _direct_acquires(self, info: _ClassInfo, method) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(method):
            if isinstance(node, ast.With):
                for item in node.items:
                    attr = self_attr(item.context_expr)
                    if attr is not None and attr in info.locks:
                        out.add(info.lock_name(attr))
        return out

    def _summarize(self) -> None:
        """Fixpoint over resolved calls: acquires*(C.m) = direct with-
        acquisitions plus the summaries of every resolvable callee."""
        calls: dict[tuple[str, str], set[tuple[str, str]]] = {}
        for cname, info in self.classes.items():
            for mname, method in info.methods.items():
                key = (cname, mname)
                self._acquires[key] = self._direct_acquires(info, method)
                callees = set()
                for node in ast.walk(method):
                    if isinstance(node, ast.Call):
                        r = self._resolve_call(info, node)
                        if r is not None and r != key:
                            callees.add(r)
                calls[key] = callees
        changed = True
        while changed:
            changed = False
            for key, callees in calls.items():
                acc = self._acquires[key]
                before = len(acc)
                for c in callees:
                    acc |= self._acquires.get(c, set())
                if len(acc) != before:
                    changed = True

    # -- edge derivation ---------------------------------------------------
    def _add_edge(self, src: str, dst: str, path: str, line: int, via: str):
        if (src, dst) in self._edge_keys:
            return
        self._edge_keys.add((src, dst))
        self.edges.append(LockEdge(src, dst, path, line, via))

    def _derive_edges(self) -> None:
        for cname in sorted(self.classes):
            info = self.classes[cname]
            resolve = _lock_resolver(info)
            for mname in sorted(info.methods):
                method = info.methods[mname]

                def on_with(stmt, held, acquired, reacquired,
                            info=info, method=method):
                    for name in sorted(acquired):
                        for h in held:
                            self._add_edge(
                                h, name, info.sf.relpath, stmt.lineno, "",
                            )
                    # calls in the with items evaluate PRE-acquire
                    for item in stmt.items:
                        for node in ast.walk(item.context_expr):
                            if isinstance(node, ast.Call):
                                self._note_call(info, method, node, held)

                def on_stmt(stmt, held, info=info, method=method):
                    for node in ast.walk(stmt):
                        if isinstance(node, ast.Call):
                            self._note_call(info, method, node, held)
                    return False  # keep descending: nested With blocks
                    #               re-note their calls under the
                    #               larger held set (edges dedup)

                walk_held(
                    method.body, resolve, on_with, on_stmt,
                    frozenset(self._initial_held(info, method)),
                )

    def _note_call(self, info, method, node: ast.Call, held: set[str]):
        if not held:
            return
        r = self._resolve_call(info, node)
        if r is None:
            return
        for dst in sorted(self._acquires.get(r, set())):
            if dst in held:
                continue
            for h in held:
                self._add_edge(
                    h, dst, info.sf.relpath, node.lineno, f"{r[0]}.{r[1]}",
                )


def _lock_resolver(info: "_ClassInfo"):
    """resolve() for :func:`walk_held` tracking a class's locks by
    their registry-style ``Class.attr`` name."""
    def resolve(expr):
        attr = self_attr(expr)
        if attr is not None and attr in info.locks:
            return info.lock_name(attr)
        return None

    return resolve


def walk_held(stmts, resolve, on_with=None, on_stmt=None,
              held: frozenset = frozenset()) -> None:
    """THE shared held-set traversal — every lock-scope walker in the
    model and the concurrency rules goes through here, so statement-
    shape handling (try/if/for/while bodies, handlers) is fixed in ONE
    place.

    ``resolve(expr) -> token | None`` identifies tracked lock
    acquisitions in With items (token: whatever the client tracks —
    lock name or attr). Per With statement,
    ``on_with(stmt, held, acquired, reacquired)`` fires (``acquired``:
    tokens newly held by the body; ``reacquired``: already-held tokens
    the With re-enters), then the body walks under ``held | acquired``.
    Per other statement, ``on_stmt(stmt, held)`` fires first — a truthy
    return stops descent into that statement's nested blocks (for
    clients that scan the whole subtree themselves)."""
    held = frozenset(held)
    for stmt in stmts:
        if isinstance(stmt, ast.With):
            acquired: set = set()
            reacquired: set = set()
            for item in stmt.items:
                token = resolve(item.context_expr)
                if token is None:
                    continue
                (reacquired if token in held else acquired).add(token)
            if on_with is not None:
                on_with(stmt, held, acquired, reacquired)
            walk_held(stmt.body, resolve, on_with, on_stmt,
                      held | acquired)
            continue
        if on_stmt is not None and on_stmt(stmt, held):
            continue
        for sub in ("body", "orelse", "finalbody"):
            inner = getattr(stmt, sub, None)
            if inner:
                walk_held(inner, resolve, on_with, on_stmt, held)
        for h in getattr(stmt, "handlers", []) or []:
            walk_held(h.body, resolve, on_with, on_stmt, held)


def holds_lock_decls(sf: SourceFile, method) -> list[str]:
    """``# holds-lock:`` declarations of a method: on the ``def`` line
    or on the first body line (both placements exist in the tree)."""
    out = []
    lines = [method.lineno]
    if getattr(method, "body", None):
        lines.append(method.body[0].lineno)
    for ln in lines:
        m = _HOLDS_RE.search(sf.source_line(ln))
        if m:
            out.append(m.group(1))
    return out


def registry_line(project: Project, name: str) -> int:
    """The LOCKS declaration line of one registered name (for
    registry-side findings), falling back to 1."""
    sf = project.files.get(MODEL_PATH)
    if sf is not None:
        needle = f'"{name}"'
        for i, line in enumerate(sf.lines, start=1):
            if needle in line:
                return i
    return 1


def annotated_guards(model: LockModel) -> dict[str, set[str]]:
    """lock name -> the fields `# guarded-by:` comments attach to it,
    aggregated across all scanned classes (the code-side view the
    registry's ``fields`` tuples cross-check against)."""
    out: dict[str, set[str]] = {}
    for cname, info in model.classes.items():
        for fieldname, (lock, _line) in info.guarded.items():
            out.setdefault(f"{cname}.{lock}", set()).add(fieldname)
    return out
