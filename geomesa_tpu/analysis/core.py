"""Lint framework core: Rule SPI, AST cache, findings, suppressions.

Deliberately stdlib-only (ast / tokenize / re / os): the analyzer must
stay importable and fast in any environment — CI, a laptop without the
accelerator toolchain, a pre-commit hook — so a full-repo run fits the
< 10 s budget with room to spare.

The moving parts:

- :class:`SourceFile` — one parsed file: source text, AST (parsed once,
  shared by every rule via :class:`Project`'s cache), parent links,
  comment map and inline ``lint: ignore[rule-id]`` suppressions;
- :class:`Project` — the file set one analysis run covers
  (``geomesa_tpu/**.py`` + ``scripts/*.py`` + ``docs/*.md``; tests and
  fixtures are out of scope on purpose — they exercise bad patterns);
- :class:`Rule` — the SPI: subclass, set ``id``/``description``/
  ``fix_hint``, implement ``check(project) -> Iterable[Finding]``;
- :class:`Finding` — path:line + rule id + message + fix hint + a
  line-number-free ``key`` so baseline entries survive unrelated edits;
- the suppression baseline — a checked-in text file of finding keys;
  ``run_rules`` drops findings whose key is baselined (shipped EMPTY:
  every real violation in this tree is fixed, the baseline exists for
  future adopters mid-cleanup).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

# inline suppression: `# lint: ignore[rule-id]` (comma-separated ids) on
# the flagged line silences that rule there; `ignore[*]` silences all
_IGNORE_RE = re.compile(r"#\s*lint:\s*ignore\[([\w*,\s-]+)\]")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule_id: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    fix_hint: str = ""
    # stable identity for baselines: rule + path + a rule-chosen symbol
    # (offending name, enclosing def, ...) — NOT the line number, which
    # drifts under unrelated edits
    symbol: str = ""

    @property
    def key(self) -> str:
        return f"{self.rule_id}::{self.path}::{self.symbol or self.line}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        hint = f"\n    fix: {self.fix_hint}" if self.fix_hint else ""
        return f"{loc}: [{self.rule_id}] {self.message}{hint}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule_id,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "fix_hint": self.fix_hint,
            "key": self.key,
        }


class SourceFile:
    """One analyzed Python file: text, AST (cached), parent links,
    per-line suppressions. Rules never re-parse; they share this."""

    def __init__(self, root: str, relpath: str, text: "str | None" = None):
        self.relpath = relpath
        self.abspath = os.path.join(root, relpath)
        if text is None:
            with open(self.abspath, encoding="utf-8") as fh:
                text = fh.read()
        self.text = text
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[str] = None
        # initialized BEFORE the parse attempt: suppressed() must stay
        # callable (returning False) on files that fail to parse
        self.ignores: dict[int, set[str]] = {}
        try:
            self.tree = ast.parse(self.text, filename=relpath)
        except SyntaxError as e:  # surfaced as its own finding
            self.parse_error = f"{e.msg} (line {e.lineno})"
            return
        # parent links let rules walk outward (enclosing With / def)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._lint_parent = parent  # type: ignore[attr-defined]
        # rule-id suppressions by line
        for i, line in enumerate(self.lines, start=1):
            m = _IGNORE_RE.search(line)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                self.ignores[i] = ids

    # -- helpers rules lean on -------------------------------------------
    def line_of(self, node: ast.AST) -> int:
        return getattr(node, "lineno", 1)

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def parents(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = getattr(node, "_lint_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_lint_parent", None)

    def enclosing_function(self, node: ast.AST):
        for p in self.parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return p
        return None

    def enclosing_class(self, node: ast.AST):
        for p in self.parents(node):
            if isinstance(p, ast.ClassDef):
                return p
        return None

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        ids = self.ignores.get(lineno)
        return ids is not None and (rule_id in ids or "*" in ids)


class DocFile:
    """One markdown file (docs/*.md): raw text only."""

    def __init__(self, root: str, relpath: str):
        self.relpath = relpath
        with open(os.path.join(root, relpath), encoding="utf-8") as fh:
            self.text = fh.read()


class Project:
    """The file set of one analysis run, with the shared AST cache."""

    #: scanned python trees (repo-relative); tests/ and examples/ are
    #: deliberately out of scope — they stage bad patterns on purpose
    PY_ROOTS = ("geomesa_tpu", "scripts")
    DOC_ROOT = "docs"
    #: test tree loaded as RAW TEXT only (never linted): coverage-style
    #: rules (fault-point-unknown) check that names the production tree
    #: declares are actually exercised by some test
    TEST_ROOT = "tests"

    def __init__(self, root: str):
        self.root = root
        self.files: dict[str, SourceFile] = {}
        self.docs: dict[str, DocFile] = {}
        self.tests: dict[str, str] = {}  # relpath -> raw text

    @classmethod
    def load(cls, root: str) -> "Project":
        p = cls(root)
        for top in cls.PY_ROOTS:
            base = os.path.join(root, top)
            if not os.path.isdir(base):
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fn), root
                        ).replace(os.sep, "/")
                        p.files[rel] = SourceFile(root, rel)
        docdir = os.path.join(root, cls.DOC_ROOT)
        if os.path.isdir(docdir):
            for fn in sorted(os.listdir(docdir)):
                if fn.endswith(".md"):
                    rel = f"{cls.DOC_ROOT}/{fn}"
                    p.docs[rel] = DocFile(root, rel)
        testdir = os.path.join(root, cls.TEST_ROOT)
        if os.path.isdir(testdir):
            for dirpath, dirnames, filenames in os.walk(testdir):
                # fixtures stage rule inputs that never RUN: a fault
                # point named only in a fixture must not count as
                # test-exercised (the vacuous-coverage hole the
                # fault-point-unknown rule exists to close)
                dirnames[:] = [
                    d for d in dirnames
                    if d not in ("__pycache__", "fixtures")
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fn), root
                        ).replace(os.sep, "/")
                        try:
                            with open(
                                os.path.join(root, rel), encoding="utf-8"
                            ) as fh:
                                p.tests[rel] = fh.read()
                        except OSError:
                            continue
        return p

    def add_file(self, relpath: str, text: "str | None" = None) -> SourceFile:
        """Register one extra file into the cache. ``text`` stages
        content under a synthetic relpath (rule fixtures analyzed as if
        they lived in a scoped tree, e.g. geomesa_tpu/scan/) without a
        file existing there; None reads ``relpath`` from disk."""
        sf = SourceFile(self.root, relpath, text=text)
        self.files[relpath.replace(os.sep, "/")] = sf
        return sf

    def python_files(self, under: str | None = None) -> list[SourceFile]:
        out = [
            sf for rel, sf in sorted(self.files.items())
            if under is None or rel.startswith(under)
        ]
        return out


class Rule:
    """SPI: one named invariant. Subclasses set the class attributes and
    implement :meth:`check`; ``run_rules`` handles suppression filtering
    and ordering. Keep rules pure functions of the Project — no file
    writes, no imports of the analyzed code (AST only)."""

    id: str = ""
    description: str = ""
    fix_hint: str = ""

    def check(self, project: Project) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(
        self, sf_or_path, line: int, message: str,
        symbol: str = "", fix_hint: str | None = None,
    ) -> Finding:
        path = (
            sf_or_path.relpath
            if isinstance(sf_or_path, (SourceFile, DocFile))
            else sf_or_path
        )
        return Finding(
            rule_id=self.id,
            path=path,
            line=line,
            message=message,
            fix_hint=self.fix_hint if fix_hint is None else fix_hint,
            symbol=symbol,
        )


@dataclass
class RunResult:
    findings: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings


def load_baseline(path: str | None) -> set[str]:
    """Baseline file -> set of finding keys. Lines are ``Finding.key``
    strings; blank lines and ``#`` comments are ignored."""
    if path is None or not os.path.exists(path):
        return set()
    keys = set()
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line and not line.startswith("#"):
                keys.add(line)
    return keys


def default_baseline_path(root: str) -> str:
    return os.path.join(root, "geomesa_tpu", "analysis", "baseline.txt")


def run_rules(
    project: Project,
    rules: Iterable[Rule],
    baseline: "set[str] | str | None" = None,
) -> RunResult:
    """Run every rule over the project; returns findings split into
    (new, suppressed). ``baseline`` is a key set, a path, or None (the
    checked-in default)."""
    if baseline is None:
        baseline = default_baseline_path(project.root)
    if isinstance(baseline, str):
        baseline = load_baseline(baseline)

    result = RunResult()
    # a file that does not parse fails loudly before any rule runs —
    # but still through the baseline filter, so the documented
    # --write-baseline -> rerun-exits-0 adoption loop converges even
    # on trees carrying broken files
    parse_broken = False
    for sf in project.python_files():
        if sf.parse_error is not None:
            parse_broken = True
            f = Finding(
                rule_id="parse-error", path=sf.relpath, line=1,
                message=f"file does not parse: {sf.parse_error}",
                symbol="module",
            )
            (result.suppressed if f.key in baseline
             else result.findings).append(f)
    if parse_broken:
        return result

    for rule in rules:
        for f in rule.check(project):
            sf = project.files.get(f.path)
            if sf is not None and sf.suppressed(f.rule_id, f.line):
                result.suppressed.append(f)
            elif f.key in baseline:
                result.suppressed.append(f)
            else:
                result.findings.append(f)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    result.suppressed.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return result


# -- small AST utilities shared by the rule modules -----------------------


def call_name(node: ast.Call) -> str:
    """Trailing name of a call target: ``bk.fused_e_bucket(...)`` ->
    ``fused_e_bucket``; ``SystemProperty(...)`` -> ``SystemProperty``."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def names_in(node: ast.AST) -> set[str]:
    """Every bare Name and attribute-trailing name in a subtree."""
    out: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` -> ``x``; anything else -> None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None
