"""Kernel-purity rule family: recompile and concretization hazards.

The scan tier's whole design rests on static shapes (PERF.md: one
compiled variant per (M bucket, columns, flags, E, R); a cold variant
costs 20-40 s on the tunneled TPU). Three hazard classes creep in
through review:

- ``float()/int()/bool()`` coercion of a *traced* value inside a jitted
  function — concretizes the tracer (TracerError at best, silent
  per-value recompile at worst). Static arguments (``static_argnames``)
  are exempt: coercing those at trace time is the intended pattern;
- data-dependent output shapes (``jnp.nonzero``, ``unique``, one-arg
  ``where``, ...) inside a jitted function — the exact ops the
  bitmask-plane design exists to avoid (block_kernels module doc);
- ``warmup()`` coverage gaps: the warmup walks the variant ladders so
  production queries never compile; if the fused grouping key gains a
  dimension (an E/R-style bucket ladder) that warmup does not walk,
  first queries stall. Any class shipping both ``warmup`` and
  ``scan_submit_many`` must reference every ``fused_<dim>_bucket``
  ladder (the function or its ``FUSED_<DIM>_BUCKETS`` constant),
  directly or one call level down.
"""

from __future__ import annotations

import ast
import re

from geomesa_tpu.analysis.core import Project, Rule, call_name, names_in

KERNEL_SCOPES = ("geomesa_tpu/scan/", "geomesa_tpu/curve/")
COERCIONS = {"float", "int", "bool"}
DYNAMIC_SHAPE_CALLS = {
    "nonzero", "flatnonzero", "argwhere", "unique", "compress", "extract",
}
_DERIV_DEF_RE = re.compile(r"^fused_([a-z0-9]+)_bucket$")


def _jit_static_names(fn) -> "set[str] | None":
    """None when ``fn`` is not jitted; otherwise the set of static
    parameter names (from ``static_argnames``/``static_argnums``)."""
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        tail = (
            target.attr if isinstance(target, ast.Attribute)
            else target.id if isinstance(target, ast.Name) else ""
        )
        if tail == "jit":
            return _statics_of(dec, fn)
        if tail == "partial" and isinstance(dec, ast.Call):
            if any("jit" in names_in(a) for a in dec.args):
                return _statics_of(dec, fn)
    return None


def _statics_of(dec, fn) -> set[str]:
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    out: set[str] = set()
    if not isinstance(dec, ast.Call):
        return out
    for kw in dec.keywords:
        # jax accepts both the iterable and the bare-scalar forms:
        # static_argnames=("a", "b") / static_argnames="a",
        # static_argnums=(0, 1) / static_argnums=0
        elts = (
            kw.value.elts
            if isinstance(kw.value, (ast.Tuple, ast.List))
            else [kw.value]
        )
        if kw.arg == "static_argnames":
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
        if kw.arg == "static_argnums":
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    if 0 <= e.value < len(params):
                        out.add(params[e.value])
    return out


def _jit_functions(sf):
    if sf.tree is None:
        return
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            statics = _jit_static_names(node)
            if statics is not None:
                yield node, statics


class KernelTracedCoercionRule(Rule):
    id = "kernel-traced-coercion"
    description = (
        "no float()/int()/bool() coercion of traced values inside jitted "
        "scan/curve kernels (static_argnames are exempt)"
    )
    fix_hint = (
        "keep the value in jnp (astype / jnp.where), or hoist the "
        "coercion to the host caller; if the parameter is genuinely "
        "static, add it to static_argnames"
    )

    def check(self, project: Project):
        for sf in project.python_files():
            if not sf.relpath.startswith(KERNEL_SCOPES):
                continue
            for fn, statics in _jit_functions(sf):
                kwonly = [a.arg for a in fn.args.kwonlyargs]
                params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
                traced = (set(params) | set(kwonly)) - statics - {"self"}
                for node in ast.walk(fn):
                    if not (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in COERCIONS
                        and node.args
                    ):
                        continue
                    touched = names_in(node.args[0]) & traced
                    if touched:
                        yield self.finding(
                            sf, node.lineno,
                            f"{node.func.id}() coerces traced value(s) "
                            f"{sorted(touched)} inside jitted "
                            f"{fn.name}() — concretization/recompile "
                            "hazard",
                            # line-free key (the baseline contract):
                            # repeated same-shape coercions in one fn
                            # share a key, which suppresses together
                            symbol=(
                                f"{fn.name}:{node.func.id}:"
                                f"{','.join(sorted(touched))}"
                            ),
                        )


class KernelDynamicShapeRule(Rule):
    id = "kernel-dynamic-shape"
    description = (
        "no data-dependent output shapes (nonzero/unique/one-arg where/"
        "compress) inside jitted scan/curve kernels"
    )
    fix_hint = (
        "keep shapes static: emit packed bitmask planes (the "
        "block_kernels pattern) or masked reductions; decode on host"
    )

    def check(self, project: Project):
        for sf in project.python_files():
            if not sf.relpath.startswith(KERNEL_SCOPES):
                continue
            for fn, _statics in _jit_functions(sf):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    name = call_name(node)
                    hazard = name in DYNAMIC_SHAPE_CALLS or (
                        name == "where" and len(node.args) == 1
                    )
                    if hazard:
                        yield self.finding(
                            sf, node.lineno,
                            f"{name}() produces a data-dependent shape "
                            f"inside jitted {fn.name}()",
                            symbol=f"{fn.name}:{name}",
                        )


class WarmupCoverageRule(Rule):
    id = "warmup-coverage"
    description = (
        "warmup() must walk every fused_<dim>_bucket variant-key ladder "
        "(reference the derivation fn or its FUSED_<DIM>_BUCKETS "
        "constant) so no fused dispatch compiles at query time"
    )
    fix_hint = (
        "extend warmup's fused ladder loop with the new dimension's "
        "FUSED_<DIM>_BUCKETS entries"
    )

    #: where the ladder dimensions are declared
    KERNEL_MODULE = "geomesa_tpu/scan/block_kernels.py"

    def _dimensions(self, project: Project) -> list[str]:
        sf = project.files.get(self.KERNEL_MODULE)
        if sf is None or sf.tree is None:
            return []
        dims = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.FunctionDef):
                m = _DERIV_DEF_RE.match(node.name)
                if m:
                    dims.append(m.group(1))
        return sorted(dims)

    def check(self, project: Project):
        dims = self._dimensions(project)
        if not dims:
            return
        for sf in project.python_files():
            # host-only backends (no kernel dispatch) have nothing to warm
            if sf.tree is None or "block_scan_multi" not in sf.text:
                continue
            for cls in ast.walk(sf.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                methods = {
                    n.name: n for n in cls.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
                if "warmup" not in methods or "scan_submit_many" not in methods:
                    continue
                warm = methods["warmup"]
                names = names_in(warm)
                # one level of self-method indirection
                for callee in list(names):
                    if callee in methods and callee != "warmup":
                        names |= names_in(methods[callee])
                for dim in dims:
                    fn_name = f"fused_{dim}_bucket"
                    const = f"FUSED_{dim.upper()}_BUCKETS"
                    if fn_name not in names and const not in names:
                        yield self.finding(
                            sf, warm.lineno,
                            f"{cls.name}.warmup() never references "
                            f"{fn_name}()/{const}: the {dim.upper()} "
                            "variant-key ladder would compile at query "
                            "time",
                            symbol=f"{cls.name}.warmup:{dim}",
                        )
