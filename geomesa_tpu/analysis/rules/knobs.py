"""Knob-registry rule family: conf.py is the single source of truth.

The ~30 ``geomesa.*`` system properties in ``conf.py`` have three
failure modes this family kills (each has happened in review):

- a dotted name referenced in code/docstrings that no registry declares
  (a typo, or a knob someone removed while messages still cite it);
- a declared knob nothing reads (dead configuration — the operator sets
  it, nothing changes);
- a declared knob no doc mentions (undiscoverable configuration).

Docs are held to the same standard in reverse: every ``geomesa.*`` name
a docs/*.md file cites must resolve against the knob, metric or
user-data registry — so renaming a knob without its docs (or vice
versa) fails the build.
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.core import Project, Rule, const_str
from geomesa_tpu.analysis.registries import (
    USER_DATA_KEYS,
    Registries,
    extract_dotted,
)


def _tokens(text: str, tail_prefix: bool = False):
    """(name, wildcard) pairs from one string. ``tail_prefix``: the
    string is an f-string fragment, so a token the fragment ends with
    (followed by a ``.``) is a family prefix — ``f"geomesa.ingest.
    {stage}"`` names the geomesa.ingest.* family, not a literal."""
    for tok in extract_dotted(text):
        wildcard = tok.endswith(".*")
        name = tok[:-2] if wildcard else tok
        if tail_prefix and text.endswith(name + "."):
            wildcard = True
        yield name, wildcard


def _string_occurrences(sf):
    """(name, line, wildcard) for every geomesa.* dotted name inside the
    file's string constants — docstrings included (a stale knob citation
    in a docstring misleads exactly like one in an error message)."""
    if sf.tree is None:
        return
    for node in ast.walk(sf.tree):
        s = const_str(node)
        if s is None and isinstance(node, ast.JoinedStr):
            # f-strings: scan the literal fragments (tail_prefix on —
            # a fragment ending at a substitution names a family)
            for v in node.values:
                frag = const_str(v)
                if frag:
                    for name, wc in _tokens(frag, tail_prefix=True):
                        yield name, node.lineno, wc
            continue
        if s is None or "geomesa." not in s:
            continue
        # fragment Constants inside an f-string were already handled by
        # the JoinedStr branch above — ast.walk visits them again here
        if isinstance(getattr(node, "_lint_parent", None), ast.JoinedStr):
            continue
        for name, wc in _tokens(s):
            yield name, node.lineno, wc


class KnobUndeclaredRule(Rule):
    id = "knob-undeclared"
    description = (
        "every geomesa.* dotted name in code or docstrings must resolve "
        "against the knob (conf.py), metric, or user-data registry"
    )
    fix_hint = (
        "declare the knob as a SystemProperty in conf.py, fix the typo, "
        "or drop the stale reference"
    )

    def check(self, project: Project):
        regs = Registries.of(project)
        for sf in project.python_files():
            for name, line, wildcard in _string_occurrences(sf):
                if not regs.resolves(name, wildcard=wildcard):
                    yield self.finding(
                        sf, line,
                        f"undeclared name {name!r}: not a conf.py knob, "
                        "not a metric instrument, not a registered "
                        "user-data key",
                        symbol=name,
                    )


class KnobUnreadRule(Rule):
    id = "knob-unread"
    description = (
        "every SystemProperty declared in conf.py must have at least one "
        "read site (its variable referenced outside conf.py)"
    )
    fix_hint = (
        "wire the knob into the code path it configures, or delete the "
        "declaration (dead configuration misleads operators)"
    )

    def check(self, project: Project):
        regs = Registries.of(project)
        if not regs.knobs.knobs:
            return
        used: set[str] = set()
        for sf in project.python_files():
            if sf.relpath == regs.knobs.path or sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Name):
                    used.add(node.id)
                elif isinstance(node, ast.Attribute):
                    used.add(node.attr)
        for knob in regs.knobs.knobs.values():
            if knob.var and knob.var not in used:
                yield self.finding(
                    regs.knobs.path, knob.line,
                    f"knob {knob.name!r} ({knob.var}) is declared but "
                    "never read outside conf.py",
                    symbol=knob.name,
                )


class KnobUndocumentedRule(Rule):
    id = "knob-undocumented"
    description = (
        "every declared knob must be mentioned in at least one docs/*.md "
        "file (docs/config.md is the reference table)"
    )
    fix_hint = "add the knob to docs/config.md (name, default, effect)"

    def check(self, project: Project):
        regs = Registries.of(project)
        doc_text = "\n".join(d.text for d in project.docs.values())
        for knob in regs.knobs.knobs.values():
            if knob.name not in doc_text:
                yield self.finding(
                    regs.knobs.path, knob.line,
                    f"knob {knob.name!r} appears in no docs/*.md",
                    symbol=knob.name,
                )


class UserDataUnusedRule(Rule):
    id = "userdata-unused"
    description = (
        "every registered schema user-data key must have a use site in "
        "geomesa_tpu/ (the registry must not outlive the feature)"
    )
    fix_hint = (
        "remove the dead entry from analysis/registries.py USER_DATA_KEYS, "
        "or restore the code that reads the key"
    )

    def check(self, project: Project):
        regs_path = "geomesa_tpu/analysis/registries.py"
        if regs_path not in project.files:
            return  # staged mini-repos without the registry are exempt
        seen: set[str] = set()
        for sf in project.python_files("geomesa_tpu/"):
            if sf.relpath == regs_path:
                continue
            if sf.tree is None:
                continue
            for key in USER_DATA_KEYS:
                if key in sf.text:
                    seen.add(key)
        for key in USER_DATA_KEYS:
            if key not in seen:
                yield self.finding(
                    regs_path, 1,
                    f"user-data key {key!r} is registered but never used",
                    symbol=key,
                )


class DocUnknownNameRule(Rule):
    id = "doc-unknown-name"
    description = (
        "every geomesa.* dotted name cited in docs/*.md must resolve "
        "against the knob, metric, or user-data registry"
    )
    fix_hint = (
        "fix the doc to cite the real name, or (re)introduce the knob/"
        "metric the doc promises"
    )

    def check(self, project: Project):
        from geomesa_tpu.analysis.registries import doc_names

        regs = Registries.of(project)
        for dn in doc_names(project):
            if not regs.resolves(dn.name, wildcard=dn.wildcard):
                yield self.finding(
                    dn.path, dn.line,
                    f"doc cites unknown name {dn.name!r}",
                    symbol=dn.name,
                )
