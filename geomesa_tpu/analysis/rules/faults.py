"""Fault-point registry rule: the crash harness can't drift from code.

PR 1's durability claims rest on deterministic fault injection: a crash
test arms a NAMED point and asserts the recovery invariant. Names are
plain strings, so three silent failure modes exist — a typo'd or
renamed point the tests still arm (the fault never fires, the test
passes vacuously), a point the code fires that no registry documents,
and a registered point no test ever exercises (an untested crash
window). This rule machine-checks all three against
``registries.FAULT_POINTS`` — the same move PR 7 made for knobs and
metrics, applied to the fault-injection namespace (ISSUE 10).

The coverage direction reads the TEST tree (raw text, never linted): a
point counts as exercised when any test string names it exactly, arms
an ``fnmatch`` pattern matching it (``persist.*``), or embeds it in a
``GEOMESA_TPU_FAULTS``-style ``point:kind`` entry.
"""

from __future__ import annotations

import fnmatch

from geomesa_tpu.analysis.core import Project, Rule
from geomesa_tpu.analysis.registries import (
    FAULT_POINTS,
    fault_point_uses,
    test_string_tokens,
)

_REGS_PATH = "geomesa_tpu/analysis/registries.py"


def _registry_line(project: Project, name: str) -> int:
    """The FAULT_POINTS declaration line of one registered name (for
    registry-side findings), falling back to 1."""
    sf = project.files.get(_REGS_PATH)
    if sf is not None:
        needle = f'"{name}"'
        for i, line in enumerate(sf.lines, start=1):
            if needle in line:
                return i
    return 1


def _exercised(name: str, tokens: set[str]) -> bool:
    for tok in tokens:
        if tok == name:
            return True
        if ":" in tok and tok.split(":", 1)[0] == name:
            return True  # GEOMESA_TPU_FAULTS "point:kind[:...]" entry
        if "*" in tok and fnmatch.fnmatch(name, tok.split(":", 1)[0]):
            return True
    return False


class FaultPointRule(Rule):
    id = "fault-point-unknown"
    description = (
        "every fault_point()/atomic_write(point=) literal must be "
        "registered in registries.FAULT_POINTS, every registered point "
        "must have a code use site, and every registered point must be "
        "exercised by at least one test"
    )
    fix_hint = (
        "register the point in analysis/registries.py FAULT_POINTS (or "
        "fix the typo), and arm it from a test (fault.inject / "
        "fault.chaos / GEOMESA_TPU_FAULTS)"
    )

    def check(self, project: Project):
        if _REGS_PATH not in project.files:
            return  # staged mini-repos without the registry are exempt
        uses = fault_point_uses(project)
        used_names = {u.name for u in uses}
        for u in uses:
            if u.name not in FAULT_POINTS:
                yield self.finding(
                    u.path, u.line,
                    f"fault point {u.name!r} is not registered in "
                    "registries.FAULT_POINTS",
                    symbol=u.name,
                )
        for name in FAULT_POINTS:
            if name not in used_names:
                yield self.finding(
                    _REGS_PATH, _registry_line(project, name),
                    f"fault point {name!r} is registered but never "
                    "fired by any fault_point()/atomic_write() site",
                    symbol=f"unreached:{name}",
                )
        tokens = test_string_tokens(project)
        if not tokens:
            return  # no test tree in scope (mini repos)
        for name in sorted(FAULT_POINTS):
            if name in used_names and not _exercised(name, tokens):
                yield self.finding(
                    _REGS_PATH, _registry_line(project, name),
                    f"fault point {name!r} is never exercised by any "
                    "test (no literal, pattern, or env entry matches)",
                    symbol=f"unexercised:{name}",
                )
