"""Rule assembly: the shipped rule set, in deterministic order.

Adding a rule (docs/analysis.md "Adding a rule"): subclass
:class:`geomesa_tpu.analysis.core.Rule` in one of these modules (or a
new one), give it a unique kebab-case ``id``, a one-line
``description`` and a ``fix_hint``, append an instance here, document
the id in docs/analysis.md (tests/test_docs.py enforces that), and add
known-bad/known-good fixtures under tests/fixtures/analysis/.
"""

from geomesa_tpu.analysis.rules.concurrency import (
    BlockingUnderLockRule,
    CheckThenActRule,
    GuardedEscapeRule,
    LockOrderRule,
)
from geomesa_tpu.analysis.rules.controllers import ControllerRegistryRule
from geomesa_tpu.analysis.rules.faults import FaultPointRule
from geomesa_tpu.analysis.rules.fused import FusedVariantKeyRule
from geomesa_tpu.analysis.rules.kernels import (
    KernelDynamicShapeRule,
    KernelTracedCoercionRule,
    WarmupCoverageRule,
)
from geomesa_tpu.analysis.rules.knobs import (
    DocUnknownNameRule,
    KnobUndeclaredRule,
    KnobUndocumentedRule,
    KnobUnreadRule,
    UserDataUnusedRule,
)
from geomesa_tpu.analysis.rules.locks import LockDisciplineRule
from geomesa_tpu.analysis.rules.metrics import (
    MetricConventionRule,
    MetricTypeConflictRule,
)
from geomesa_tpu.analysis.rules.scripts import ScriptDocstringRule

ALL_RULES = [
    KnobUndeclaredRule(),
    KnobUnreadRule(),
    KnobUndocumentedRule(),
    UserDataUnusedRule(),
    DocUnknownNameRule(),
    MetricConventionRule(),
    MetricTypeConflictRule(),
    FaultPointRule(),
    ControllerRegistryRule(),
    FusedVariantKeyRule(),
    LockDisciplineRule(),
    LockOrderRule(),
    CheckThenActRule(),
    BlockingUnderLockRule(),
    GuardedEscapeRule(),
    KernelTracedCoercionRule(),
    KernelDynamicShapeRule(),
    WarmupCoverageRule(),
    ScriptDocstringRule(),
]
