"""Script-hygiene rule: scripts/ stays navigable.

The probe scripts are the repo's measurement provenance (PERF.md cites
them); a probe without a docstring stating what it measures is noise
the next session has to reverse-engineer. Knob hygiene inside scripts
is covered by the registry rules (scripts/ is inside their scan scope).
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.core import Project, Rule


class ScriptDocstringRule(Rule):
    id = "script-docstring"
    description = (
        "every scripts/*.py module carries a docstring stating what it "
        "probes/does and how to run it"
    )
    fix_hint = "add a module docstring (what it measures, how to run)"

    def check(self, project: Project):
        for sf in project.python_files("scripts/"):
            if sf.tree is None:
                continue
            doc = ast.get_docstring(sf.tree)
            if not doc or not doc.strip():
                yield self.finding(
                    sf, 1,
                    "script has no module docstring",
                    symbol="module",
                )
