"""Fused-variant-key rule: the PR 5 bug class, made impossible to repeat.

The fused multi-query scan groups eligible queries by a *variant key*
(``scan_submit_many``'s ``groups.setdefault(key, ...)``) and then builds
ONE set of kernel operands per chunk (``_chunk_edge_stack`` /
``_chunk_raster_stack`` -> ``block_scan_multi``). The operands' static
shapes are derived per chunk with the ``fused_<dim>_bucket`` ladder
functions — so every ladder dimension used on the chunk side MUST also
be derivable from the grouping key, or two queries with different
static shapes land in one chunk and the "shared" dispatch silently
recompiles per chunk (or worse, pads every member to the largest
member's shape, the PR 5 E-bucket defect: the key omitted the edge
bucket, so a 256-edge polygon member inflated every box slot in its
chunk to 256-edge PIP work and knocked the chunk off the Pallas path).

Static check, per module that references ``block_scan_multi``:

1. find grouping functions — any function containing
   ``<dict>.setdefault(key, ...)`` where ``key`` is (or flows from) a
   tuple;
2. compute the *key flow*: every function name and constant name that
   (transitively, through same-function assignments) contributes to the
   key tuple;
3. every ``fused_<dim>_bucket`` function called elsewhere in the module
   (the chunk-operand side) must appear in some grouping function's key
   flow. Each missing dimension is one finding.

Modules with chunk-side derivations but no grouping function (e.g. a
subclass overriding only ``_submit_fused_chunk``) are skipped: the
grouping lives in the base class whose module carries the check.

Round 11 widened the ladder pattern to ``fold_<dim>_bucket`` as well:
the incremental fold's device plan deliberately ships NO static-bucket
shapes today (eager device ops, nothing compile-keyed), but a future
fold-side ladder shaping fold operands would recreate exactly the PR 5
defect class — any ``fold_*_bucket`` derivation must likewise be
derivable from a grouping key the moment one appears.
"""

from __future__ import annotations

import ast
import re

from geomesa_tpu.analysis.core import Project, Rule, call_name

_DERIV_RE = re.compile(r"^(fused|fold)_[a-z0-9]+_bucket$")


def _function_defs(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _setdefault_key_exprs(fn):
    """The key expressions of every ``X.setdefault(key, ...)`` call in
    one function whose key is (or flows from) a TUPLE — the variant-key
    shape. Non-tuple setdefaults (incidental per-device binning and the
    like) must not make their function a 'grouping function', which
    would exempt its fused_*_bucket calls from the check."""
    assigns: dict[str, list[ast.AST]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigns.setdefault(t.id, []).append(node.value)
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and call_name(node) == "setdefault"
            and node.args
        ):
            key = node.args[0]
            is_tuple = isinstance(key, ast.Tuple) or (
                isinstance(key, ast.Name)
                and any(
                    isinstance(v, ast.Tuple)
                    for v in assigns.get(key.id, [])
                )
            )
            if is_tuple:
                yield key


def _key_flow(fn, key_expr) -> set[str]:
    """Names (variables, constants, called functions) contributing to a
    grouping key, following same-function assignments transitively."""
    # name -> the expressions assigned to it within fn
    assigns: dict[str, list[ast.AST]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigns.setdefault(t.id, []).append(node.value)

    flow: set[str] = set()
    queue: list[ast.AST] = [key_expr]
    seen_vars: set[str] = set()
    while queue:
        expr = queue.pop()
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                flow.add(call_name(n))
            elif isinstance(n, ast.Attribute):
                flow.add(n.attr)
            elif isinstance(n, ast.Name):
                flow.add(n.id)
                if n.id not in seen_vars:
                    seen_vars.add(n.id)
                    queue.extend(assigns.get(n.id, []))
    return flow


class FusedVariantKeyRule(Rule):
    id = "fused-key-dimension"
    description = (
        "every fused_<dim>_bucket / fold_<dim>_bucket ladder dimension "
        "used to shape chunk operands must be derivable from the chunk "
        "grouping key"
    )
    fix_hint = (
        "add the missing <dim>_bucket term to the grouping-key tuple in "
        "the scan_submit_many-style grouping function"
    )

    def check(self, project: Project):
        for sf in project.python_files():
            if sf.tree is None or "block_scan_multi" not in sf.text:
                continue
            fns = list(_function_defs(sf.tree))
            grouping = [
                (fn, key)
                for fn in fns
                for key in _setdefault_key_exprs(fn)
            ]
            if not grouping:
                continue
            grouping_fns = {fn for fn, _ in grouping}
            # chunk-side derivations: fused_*_bucket calls OUTSIDE any
            # grouping function
            required: dict[str, int] = {}
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    name = call_name(node)
                    if _DERIV_RE.match(name):
                        fn = sf.enclosing_function(node)
                        if fn not in grouping_fns:
                            required.setdefault(name, node.lineno)
            if not required:
                continue
            flows = [
                (fn, key, _key_flow(fn, key)) for fn, key in grouping
            ]
            for name, lineno in sorted(required.items()):
                if any(name in flow for _, _, flow in flows):
                    continue
                fn, key, _ = flows[0]
                yield self.finding(
                    sf, key.lineno,
                    f"chunk operands derive their static shape with "
                    f"{name}() (line {lineno}) but the fused grouping "
                    f"key in {fn.name}() does not include that "
                    "dimension: members with different "
                    f"{name.split('_')[1].upper()} buckets would share "
                    "one chunk (the PR 5 E-bucket defect class)",
                    symbol=f"{fn.name}:{name}",
                )
