"""Lock-discipline rule: a lightweight static race detector.

PR 3 retrofitted locking onto ``MetricsRegistry`` after review found
bare ``defaultdict`` read-modify-writes racing under concurrent
callers; the serving / cache / ingest tiers have since grown the same
shape (one lock, several guarded containers, helper methods that assume
the lock is held). This rule keeps those invariants machine-checked:

- **explicit**: an attribute assignment carrying a trailing
  ``# guarded-by: <lock>`` comment registers the attribute; every
  mutation of it must then happen inside ``with self.<lock>:`` (or in a
  method that declares ``# holds-lock: <lock>`` on its ``def`` line, or
  a ``*_locked``-suffixed method — the caller-holds-the-lock naming
  convention ``ResultCache._drop_locked`` established);
- **inferred** (``serving/``, ``cache/``, ``ingest/``, ``metrics.py``
  only): in a class that owns a ``threading.Lock/RLock/Condition``, an
  attribute mutated at least once under the lock is treated as guarded —
  mutations of it outside any lock are findings. Attributes never
  mutated under a lock are left alone (single-writer fields like the
  scheduler's adaptive window are legitimate), as are attributes
  guarded by two different locks (ambiguous; annotate explicitly).

``__init__``/``__post_init__`` are construction — exempt. Reads are
not checked (lock-free reads of monotonic state are a deliberate
pattern here; see ``QueryScheduler.window_s``).
"""

from __future__ import annotations

import ast
import re

from geomesa_tpu.analysis.core import Project, Rule, call_name, self_attr

LOCK_CTORS = {"Lock", "RLock", "Condition"}
MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "popleft",
    "clear", "update", "setdefault", "add", "discard", "appendleft",
    "move_to_end", "sort", "reverse",
}
CONSTRUCTORS = {"__init__", "__post_init__", "__new__"}
INFER_SCOPES = (
    "geomesa_tpu/serving/", "geomesa_tpu/cache/", "geomesa_tpu/ingest/",
    "geomesa_tpu/metrics.py",
)

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(?:self\.)?(\w+)")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*(?:self\.)?(\w+)")


def _class_methods(cls):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _lock_attrs(cls) -> set[str]:
    from geomesa_tpu.analysis.lockmodel import lock_ctor

    locks = set()
    for method in _class_methods(cls):
        for node in ast.walk(method):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                # direct ctor, or wrapped as witness(threading.RLock(), ...)
                if call_name(node.value) in LOCK_CTORS or (
                    lock_ctor(node.value) is not None
                ):
                    for t in node.targets:
                        attr = self_attr(t)
                        if attr is not None:
                            locks.add(attr)
    return locks


def _annotations(sf, cls) -> dict[str, tuple[str, int]]:
    """attr -> (lock, line) from trailing ``# guarded-by:`` comments on
    ``self.attr`` assignments anywhere in the class."""
    out: dict[str, tuple[str, int]] = {}
    for method in _class_methods(cls):
        for node in ast.walk(method):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                attr = self_attr(t)
                if attr is None:
                    continue
                m = _GUARDED_RE.search(sf.source_line(node.lineno))
                if m:
                    out[attr] = (m.group(1), node.lineno)
    return out


def _held_locks(sf, node, method, class_locks) -> set[str]:
    """Locks held at ``node``: enclosing ``with self.<lock>`` blocks,
    plus method-level holds-lock declarations and the *_locked naming
    convention (caller holds every class lock)."""
    held: set[str] = set()
    for p in sf.parents(node):
        if isinstance(p, ast.With):
            for item in p.items:
                attr = self_attr(item.context_expr)
                if attr is not None:
                    held.add(attr)
        if p is method:
            break
    if method.name.endswith("_locked"):
        held |= class_locks
    m = _HOLDS_RE.search(sf.source_line(method.lineno))
    if m:
        held.add(m.group(1))
    return held


def _mutation_targets(node):
    """(attr, is_container) mutations of self attributes in one
    statement/expression node."""
    def targets_of(t):
        attr = self_attr(t)
        if attr is not None:
            yield attr
        elif isinstance(t, ast.Subscript):
            attr = self_attr(t.value)
            if attr is not None:
                yield attr
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from targets_of(e)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            yield from targets_of(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if not (isinstance(node, ast.AnnAssign) and node.value is None):
            yield from targets_of(node.target)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            yield from targets_of(t)
    elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in MUTATORS:
            attr = self_attr(node.func.value)
            if attr is not None:
                yield attr


class LockDisciplineRule(Rule):
    id = "lock-guarded-mutation"
    description = (
        "attributes marked '# guarded-by: <lock>' (or inferred from "
        "consistent with-lock usage in serving/cache/ingest/metrics) may "
        "only be mutated while the lock is held"
    )
    fix_hint = (
        "wrap the mutation in 'with self.<lock>:', move it into a "
        "*_locked helper, or mark the method '# holds-lock: <lock>' if "
        "every caller already holds it"
    )

    def check(self, project: Project):
        for sf in project.python_files():
            if sf.tree is None:
                continue
            infer = sf.relpath.startswith(INFER_SCOPES)
            for cls in ast.walk(sf.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                locks = _lock_attrs(cls)
                annotated = _annotations(sf, cls)
                for attr, (lock, line) in annotated.items():
                    if locks and lock not in locks:
                        yield self.finding(
                            sf, line,
                            f"'# guarded-by: {lock}' on self.{attr} names "
                            f"no lock of {cls.name} (locks: "
                            f"{sorted(locks)})",
                            symbol=f"{cls.name}.{attr}:annotation",
                        )
                if not locks and not annotated:
                    continue
                # annotations stay ENFORCED even when the lock itself is
                # not declared in this class (inherited, or a dataclass
                # field): with-blocks name it, so held-ness still checks
                eff_locks = locks | {lk for lk, _ in annotated.values()}
                # site collection: attr -> [(line, held, method)]
                sites: dict[str, list] = {}
                for method in _class_methods(cls):
                    if method.name in CONSTRUCTORS:
                        continue
                    for node in ast.walk(method):
                        for attr in _mutation_targets(node):
                            if attr in eff_locks:
                                continue
                            held = _held_locks(sf, node, method, eff_locks)
                            sites.setdefault(attr, []).append(
                                (node.lineno, held, method.name)
                            )
                for attr, attr_sites in sorted(sites.items()):
                    required = annotated.get(attr, (None, 0))[0]
                    inferred = False
                    if required is None:
                        if not infer:
                            continue
                        locks_seen = {
                            lk for _, held, _ in attr_sites
                            for lk in held & locks
                        }
                        guarded = [
                            s for s in attr_sites if s[1] & locks
                        ]
                        if len(locks_seen) != 1 or not guarded:
                            continue  # unambiguous single-lock use only
                        required = next(iter(locks_seen))
                        inferred = True
                    for lineno, held, method_name in attr_sites:
                        if required in held:
                            continue
                        how = (
                            f"inferred from with-{required} usage"
                            if inferred else f"declared '# guarded-by: {required}'"
                        )
                        yield self.finding(
                            sf, lineno,
                            f"self.{attr} is mutated in {cls.name}."
                            f"{method_name}() without holding self."
                            f"{required} ({how})",
                            symbol=f"{cls.name}.{method_name}.{attr}",
                        )
