"""Controller-registry rule: every feedback controller is declared.

The tuning tier (docs/tuning.md) closes feedback loops around knobs, so
a bad controller is worse than a bad knob default — it keeps RE-applying
its mistake. The failure modes this rule kills are all silent at
runtime: a ``ControllerSpec`` someone added without registering it in
``registries.CONTROLLERS`` (no review surface, no doc obligation), a
registered controller whose spec was deleted (the registry lies), a
spec steering a knob conf.py never declared (the write-through goes
nowhere), inverted or non-literal bounds (the clamp can't be
machine-checked), and an objective metric no instrument site emits
(the controller hill-climbs noise forever). Same move ISSUE 10 made
for fault points, applied to the controller namespace.
"""

from __future__ import annotations

from geomesa_tpu.analysis.core import Project, Rule
from geomesa_tpu.analysis.registries import (
    CONTROLLERS,
    Registries,
    controller_spec_uses,
)

_REGS_PATH = "geomesa_tpu/analysis/registries.py"


def _registry_line(project: Project, name: str) -> int:
    sf = project.files.get(_REGS_PATH)
    if sf is not None:
        needle = f'"{name}"'
        for i, line in enumerate(sf.lines, start=1):
            if needle in line:
                return i
    return 1


class ControllerRegistryRule(Rule):
    id = "controller-registry"
    description = (
        "every ControllerSpec must be registered in "
        "registries.CONTROLLERS with literal bounds lo < hi, a knob "
        "declared in conf.py, and an objective metric some instrument "
        "site emits; every registered controller must have a spec"
    )
    fix_hint = (
        "register the controller in analysis/registries.py CONTROLLERS, "
        "declare the knob in conf.py, make lo/hi literal with lo < hi, "
        "and record the objective metric somewhere (or fix the typo)"
    )

    def check(self, project: Project):
        if _REGS_PATH not in project.files:
            return  # staged mini-repos without the registry are exempt
        regs = Registries.of(project)
        uses = controller_spec_uses(project)
        spec_names = {u.name for u in uses if u.name}
        for u in uses:
            if not u.name:
                yield self.finding(
                    u.path, u.line,
                    "ControllerSpec has no literal name= — an unnamed "
                    "spec cannot be registered or audited",
                    symbol="unnamed",
                )
                continue
            if u.name not in CONTROLLERS:
                yield self.finding(
                    u.path, u.line,
                    f"controller {u.name!r} is not registered in "
                    "registries.CONTROLLERS",
                    symbol=u.name,
                )
            if u.knob is None or not regs.knobs.resolves(u.knob):
                yield self.finding(
                    u.path, u.line,
                    f"controller {u.name!r} steers knob {u.knob!r} "
                    "which conf.py never declares — the write-through "
                    "goes nowhere",
                    symbol=f"knob:{u.name}",
                )
            if u.lo is None or u.hi is None or not u.lo < u.hi:
                yield self.finding(
                    u.path, u.line,
                    f"controller {u.name!r} bounds lo={u.lo!r} "
                    f"hi={u.hi!r} must be numeric literals with "
                    "lo < hi — non-literal or inverted bounds defeat "
                    "the clamp audit",
                    symbol=f"bounds:{u.name}",
                )
            if u.objective is None or not regs.metrics.resolves(u.objective):
                yield self.finding(
                    u.path, u.line,
                    f"controller {u.name!r} objective {u.objective!r} "
                    "is not emitted by any instrument site — it would "
                    "hill-climb noise",
                    symbol=f"objective:{u.name}",
                )
        for name in CONTROLLERS:
            if name not in spec_names:
                yield self.finding(
                    _REGS_PATH, _registry_line(project, name),
                    f"controller {name!r} is registered in CONTROLLERS "
                    "but no ControllerSpec declares it",
                    symbol=f"unbacked:{name}",
                )
