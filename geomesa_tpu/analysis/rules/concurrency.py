"""geomesa-race: the concurrency rule family over the lock model.

Four rules over :mod:`geomesa_tpu.analysis.lockmodel` — the defect
classes every post-PR-7 hard bug fell into, each replayed as a
must-fail fixture under ``tests/fixtures/analysis/``:

- **lock-order-cycle** — the static acquisition graph (lock B acquired
  in a scope that statically holds lock A, plus the registry's declared
  callback edges) must be acyclic AND respect the declared rank order;
  the registry itself is checked both directions (every discovered lock
  in the concurrent tiers registered, every entry backed by a real
  construction site, guarded-field lists agreeing with the
  ``# guarded-by:`` annotations, witness names matching);
- **atomicity-check-then-act** — a guarded field read under its lock in
  one scope must not feed a write-back to the same field in a LATER
  scope of the same function unless that scope re-reads the field (the
  ``_take_staged`` write-back and ``needs_recovery`` bug shape: state
  checked, lock dropped, stale conclusion acted on);
- **blocking-under-lock** — scopes holding a registry lock marked
  ``hot`` must not fsync, sleep, wait on futures/events, fire fault
  points (latency-injectable IO markers) or dispatch jax work (the
  PR 8 reader-stall class);
- **guarded-escape** — a ``# guarded-by:`` CONTAINER must not escape
  its lock wholesale (returned bare, or stored into an unguarded
  attribute) without a copy; scalars and immutables are exempt, and
  the swap-and-drain idiom (``out, self._f = self._f, {}`` into a
  local) stays legal.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from geomesa_tpu.analysis import lockmodel
from geomesa_tpu.analysis.core import Finding, Project, Rule, self_attr
from geomesa_tpu.analysis.lockmodel import (
    DECLARED_EDGES,
    ENFORCED_SCOPES,
    LOCKS,
    LockModel,
    annotated_guards,
    registry_line,
)

#: trailing call names that can block (or inject latency/IO) — illegal
#: while a hot lock is held. ``wait`` on the HELD lock itself is exempt
#: (Condition.wait releases it); ``os.write`` is deliberately absent
#: (buffered appends are the WAL's design; fsync is the stall).
BLOCKING_CALLS = {
    "fsync": "fsync",
    "sleep": "sleep",
    "result": "Future.result",
    "wait": "wait",
    "acquire": "blocking acquire",
    "admission_gap": "scheduler admission_gap",
    "fault_point": "fault_point (latency/IO-injectable)",
}

#: construction values that mark an annotated field as a MUTABLE
#: container (the guarded-escape rule's scope; scalars/immutables are
#: exempt — escaping an int is a copy by nature)
_CONTAINER_CTORS = {
    "dict", "list", "set", "OrderedDict", "defaultdict", "deque",
    "bytearray", "Counter",
}

#: copy-shaped wrappers that legitimize an escape
_COPY_CALLS = {
    "list", "dict", "set", "tuple", "sorted", "frozenset", "copy",
    "deepcopy", "bytes",
}


def _enforced(path: str) -> bool:
    return path.startswith(ENFORCED_SCOPES)


class LockOrderRule(Rule):
    id = "lock-order-cycle"
    description = (
        "the static lock-acquisition graph (incl. declared callback "
        "edges) must be acyclic and respect the LOCKS registry's rank "
        "order; every concurrent-tier lock must be registered with a "
        "rank, and registry entries must match the code"
    )
    fix_hint = (
        "register the lock (with a rank slotting into the order) in "
        "analysis/lockmodel.py LOCKS, or restructure so the inner "
        "acquisition moves outside the outer lock's scope"
    )

    def check(self, project: Project) -> Iterable[Finding]:
        model = LockModel.of(project)
        has_registry = lockmodel.MODEL_PATH in project.files

        # 1) discovery vs registry, both directions (the fault-point move)
        for name in sorted(model.sites):
            site = model.sites[name]
            if not _enforced(site.path):
                continue
            if has_registry and name not in LOCKS:
                yield self.finding(
                    site.path, site.line,
                    f"lock {name} has no LOCKS registry entry (no "
                    "declared rank): the order checker cannot place it",
                    symbol=f"unregistered:{name}",
                )
            elif name in LOCKS and site.witness_name is None:
                yield self.finding(
                    site.path, site.line,
                    f"registered lock {name} is constructed without the "
                    "lockwitness wrapper — the dynamic tier cannot "
                    "observe it",
                    symbol=f"unwitnessed:{name}",
                    fix_hint=(
                        "construct it as witness(threading.<ctor>(), "
                        f"\"{name}\")"
                    ),
                )
            elif site.witness_name is not None and site.witness_name != name:
                yield self.finding(
                    site.path, site.line,
                    f"lock {name} is witnessed under the wrong name "
                    f"{site.witness_name!r} — runtime edges would not "
                    "match the static model",
                    symbol=f"witness-name:{name}",
                )
        if has_registry:
            for name in sorted(LOCKS):
                if name not in model.sites:
                    yield self.finding(
                        lockmodel.MODEL_PATH, registry_line(project, name),
                        f"LOCKS entry {name} has no construction site in "
                        "the tree (renamed or removed lock)",
                        symbol=f"stale-entry:{name}",
                    )
            # guarded-field lists vs `# guarded-by:` annotations
            guards = annotated_guards(model)
            for name in sorted(LOCKS):
                decl = LOCKS[name]
                code_fields = guards.get(name, set())
                for f in sorted(set(decl.fields) - code_fields):
                    yield self.finding(
                        lockmodel.MODEL_PATH, registry_line(project, name),
                        f"LOCKS entry {name} declares guarded field "
                        f"{f!r} but no '# guarded-by:' annotation in the "
                        "code names it",
                        symbol=f"field-drift:{name}.{f}",
                    )
                for f in sorted(code_fields - set(decl.fields)):
                    site = model.sites.get(name)
                    yield self.finding(
                        lockmodel.MODEL_PATH, registry_line(project, name),
                        f"field {f!r} is annotated '# guarded-by:' under "
                        f"{name} but the LOCKS entry does not list it",
                        symbol=f"field-missing:{name}.{f}",
                    )

        # 2) rank order on every edge (AST-derived and declared alike)
        for edge in sorted(
            model.edges, key=lambda e: (e.path, e.line, e.src, e.dst)
        ):
            yield from self._check_edge(
                model, edge.src, edge.dst, edge.path, edge.line,
                f" (via {edge.via})" if edge.via else "",
            )
        if has_registry:
            for a, b, why in DECLARED_EDGES:
                yield from self._check_edge(
                    model, a, b, lockmodel.MODEL_PATH,
                    registry_line(project, a), f" (declared: {why})",
                )

        # 3) cycles in the predicted graph
        for cyc in model.cycles():
            anchor = model.sites.get(cyc[0])
            path = anchor.path if anchor is not None else lockmodel.MODEL_PATH
            line = anchor.line if anchor is not None else 1
            yield self.finding(
                path, line,
                "lock-order cycle: " + " -> ".join(cyc)
                + " — two threads taking these in opposite order deadlock",
                symbol="cycle:" + "|".join(sorted(set(cyc))),
            )

        # 4) re-entrant acquisition of a non-reentrant Lock
        for cname in sorted(model.classes):
            info = model.classes[cname]
            for mname in sorted(info.methods):
                method = info.methods[mname]
                yield from self._check_reentry(model, info, method)

    def _check_edge(self, model, src, dst, path, line, via):
        if src == dst:
            return
        ra, rb = model.rank_of(src), model.rank_of(dst)
        if ra is None or rb is None:
            return  # unranked locks are reported by the registry check
        if ra >= rb:
            yield self.finding(
                path, line,
                f"{dst} (rank {rb}) acquired while holding {src} "
                f"(rank {ra}){via}: violates the declared order — "
                "rank must strictly increase inward",
                symbol=f"rank:{src}->{dst}",
            )

    def _check_reentry(self, model, info, method):
        """`with self.L:` nested under itself when L is a plain Lock —
        a guaranteed self-deadlock."""
        findings: list[Finding] = []

        def on_with(stmt, held, acquired, reacquired):
            for name in sorted(reacquired):
                attr = name.split(".", 1)[1]
                if info.locks[attr].kind == "lock":
                    findings.append(self.finding(
                        info.sf.relpath, stmt.lineno,
                        f"{name} is a non-reentrant Lock acquired "
                        f"while already held in {info.name}."
                        f"{method.name}(): self-deadlock",
                        symbol=f"reentry:{name}.{method.name}",
                    ))

        lockmodel.walk_held(
            method.body, lockmodel._lock_resolver(info), on_with=on_with,
        )
        return findings


def _lock_scopes(info, method) -> list[tuple[str, ast.With]]:
    """Maximal (lock name, With node) scopes of a method, in statement
    order — nested re-acquisitions of the same lock are folded into the
    outer scope; DISTINCT scopes of the same lock are the rule's unit."""
    out: list[tuple[str, ast.With]] = []

    def on_with(stmt, held, acquired, reacquired):
        for name in sorted(acquired):
            out.append((name, stmt))

    lockmodel.walk_held(
        method.body, lockmodel._lock_resolver(info), on_with=on_with,
    )
    return out


_MUTATOR_NAMES = {
    "append", "extend", "insert", "remove", "pop", "popitem", "popleft",
    "clear", "update", "setdefault", "add", "discard", "appendleft",
    "move_to_end", "sort", "reverse",
}


def _scope_accesses(scope: ast.With):
    """(reads, mutations) of ``self.<attr>`` inside one lock scope.
    Reads exclude attribute accesses that only RECEIVE a mutating method
    call or appear as a store target — ``self.f.pop(k)`` is a mutation,
    not a re-read; ``self.f = x`` is a write."""
    reads: set[str] = set()
    mutations: set[str] = set()
    mutator_receivers: set[int] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATOR_NAMES:
                attr = self_attr(node.func.value)
                if attr is not None:
                    mutations.add(attr)
                    mutator_receivers.add(id(node.func.value))
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                attr = self_attr(t)
                if attr is None and isinstance(t, ast.Subscript):
                    attr = self_attr(t.value)
                    if attr is not None:
                        # subscript store reads the container first
                        reads.add(attr)
                if attr is not None:
                    mutations.add(attr)
            if isinstance(node, ast.AugAssign):
                attr = self_attr(node.target)
                if attr is not None:
                    reads.add(attr)  # += reads before writing
        if isinstance(node, ast.Delete):
            for t in node.targets:
                attr = self_attr(t)
                if attr is None and isinstance(t, ast.Subscript):
                    attr = self_attr(t.value)
                    if attr is not None:
                        reads.add(attr)
                if attr is not None:
                    mutations.add(attr)
    for node in ast.walk(scope):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and self_attr(node) is not None
            and id(node) not in mutator_receivers
        ):
            reads.add(node.attr)
    return reads, mutations


def _scope_local_taint(scope: ast.With, fields: set[str]) -> set[str]:
    """Local names a scope assigns from expressions reading any of
    ``fields`` — the values whose staleness the rule tracks."""
    tainted: set[str] = set()
    for node in ast.walk(scope):
        if not isinstance(node, ast.Assign):
            continue
        src_reads = {
            n.attr for n in ast.walk(node.value)
            if isinstance(n, ast.Attribute) and self_attr(n) is not None
        }
        if not (src_reads & fields):
            continue
        for t in node.targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    tainted.add(n.id)
    return tainted


def _propagate_taint(method, tainted: set[str]) -> set[str]:
    """Fixpoint one-function taint propagation: assignment targets,
    for-loop targets and mutated accumulators become tainted when fed
    by a tainted name."""
    tainted = set(tainted)
    for _ in range(len(tainted) + 16):
        added = False
        for node in ast.walk(method):
            names_in_value: set[str] = set()
            targets: list = []
            if isinstance(node, ast.Assign):
                names_in_value = {
                    n.id for n in ast.walk(node.value)
                    if isinstance(n, ast.Name)
                }
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                names_in_value = {
                    n.id for n in ast.walk(node.value)
                    if isinstance(n, ast.Name)
                }
                targets = [node.target]
            elif isinstance(node, ast.For):
                names_in_value = {
                    n.id for n in ast.walk(node.iter)
                    if isinstance(n, ast.Name)
                }
                targets = [node.target]
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATOR_NAMES
                and isinstance(node.func.value, ast.Name)
            ):
                arg_names = {
                    n.id
                    for a in list(node.args) + [k.value for k in node.keywords]
                    for n in ast.walk(a)
                    if isinstance(n, ast.Name)
                }
                if arg_names & tainted and node.func.value.id not in tainted:
                    tainted.add(node.func.value.id)
                    added = True
                continue
            if names_in_value & tainted:
                for t in targets:
                    for n in ast.walk(t):
                        if (
                            isinstance(n, ast.Name)
                            and isinstance(n.ctx, ast.Store)
                            and n.id not in tainted
                        ):
                            tainted.add(n.id)
                            added = True
        if not added:
            break
    return tainted


class CheckThenActRule(Rule):
    id = "atomicity-check-then-act"
    description = (
        "a guarded field read under its lock must not feed a write-back "
        "to the same field in a later lock scope of the same function "
        "unless that scope re-reads the field (stale-conclusion races: "
        "the _take_staged write-back / needs_recovery shape)"
    )
    fix_hint = (
        "merge the check and the act into ONE lock hold, or make the "
        "acting scope re-validate against the field's CURRENT value "
        "(identity/membership check) before writing back"
    )

    def check(self, project: Project) -> Iterable[Finding]:
        model = LockModel.of(project)
        for cname in sorted(model.classes):
            info = model.classes[cname]
            guarded_by_lock: dict[str, set[str]] = {}
            for fieldname, (lock, _line) in info.guarded.items():
                guarded_by_lock.setdefault(lock, set()).add(fieldname)
            if not guarded_by_lock:
                continue
            for mname in sorted(info.methods):
                if mname in ("__init__", "__post_init__", "__new__"):
                    continue
                method = info.methods[mname]
                scopes = _lock_scopes(info, method)
                for i, (lname_i, scope_i) in enumerate(scopes):
                    lock_attr = lname_i.split(".", 1)[1]
                    fields = guarded_by_lock.get(lock_attr, set())
                    if not fields:
                        continue
                    reads_i, _m = _scope_accesses(scope_i)
                    read_fields = reads_i & fields
                    if not read_fields:
                        continue
                    taint0 = _scope_local_taint(scope_i, read_fields)
                    if not taint0:
                        continue
                    tainted = _propagate_taint(method, taint0)
                    for lname_j, scope_j in scopes[i + 1:]:
                        if lname_j != lname_i or scope_j is scope_i:
                            continue
                        reads_j, mut_j = _scope_accesses(scope_j)
                        scope_names = {
                            n.id for n in ast.walk(scope_j)
                            if isinstance(n, ast.Name)
                        }
                        for f in sorted((mut_j & read_fields) - reads_j):
                            if not (scope_names & tainted):
                                continue
                            yield self.finding(
                                info.sf.relpath, scope_j.lineno,
                                f"self.{f} is written back in a later "
                                f"{lname_i} scope of {cname}.{mname}() "
                                "from state read in an earlier scope, "
                                "without re-reading the field — a "
                                "concurrent mutation between the scopes "
                                "is silently overwritten",
                                symbol=f"{cname}.{mname}.{f}",
                            )


class BlockingUnderLockRule(Rule):
    id = "blocking-under-lock"
    description = (
        "scopes holding a hot-path lock (LOCKS hot=True, or an inline "
        "'# lock-rank: N hot') must not fsync, sleep, wait on futures/"
        "events, fire fault points, or dispatch jax work"
    )
    fix_hint = (
        "capture state under the lock, release it, then do the blocking "
        "work (the WAL sync/rotate discipline); or demote the lock from "
        "hot if stalls under it are genuinely acceptable"
    )

    def check(self, project: Project) -> Iterable[Finding]:
        model = LockModel.of(project)
        for cname in sorted(model.classes):
            info = model.classes[cname]
            hot_attrs = {
                attr for attr in info.locks
                if model.is_hot(info.lock_name(attr))
            }
            if not hot_attrs:
                continue
            for mname in sorted(info.methods):
                method = info.methods[mname]
                findings: list = []

                def resolve(expr, hot_attrs=hot_attrs):
                    attr = self_attr(expr)
                    return attr if attr in hot_attrs else None

                def on_stmt(stmt, held, info=info, cname=cname,
                            mname=mname, findings=findings):
                    if held:
                        findings.extend(self._scan_block(
                            info, cname, mname, [stmt], held
                        ))
                        return True  # scanned the whole subtree already
                    return False

                # *_locked / holds-lock bodies of a hot lock run held
                held0 = frozenset(
                    a for a in self._declared_held(info, method)
                    if a in hot_attrs
                )
                lockmodel.walk_held(
                    method.body, resolve, on_stmt=on_stmt, held=held0,
                )
                yield from findings

    @staticmethod
    def _declared_held(info, method) -> set[str]:
        held = set(lockmodel.holds_lock_decls(info.sf, method))
        if held:
            return held
        if method.name.endswith("_locked") and len(info.locks) == 1:
            return set(info.locks)
        return set()

    def _scan_block(self, info, cname, mname, stmts, held):
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._blocking_kind(node, held)
                if hit is None:
                    continue
                locks = ", ".join(
                    sorted(info.lock_name(a) for a in held)
                )
                yield self.finding(
                    info.sf.relpath, node.lineno,
                    f"{hit} call while holding hot lock {locks} in "
                    f"{cname}.{mname}(): every thread crossing the lock "
                    "stalls behind it",
                    symbol=f"{cname}.{mname}:{hit.split(' ')[0]}",
                )

    @staticmethod
    def _blocking_kind(node: ast.Call, held) -> Optional[str]:
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else ""
        )
        if name in BLOCKING_CALLS:
            # Condition.wait/notify on the HELD lock itself is the
            # condition-variable protocol (wait releases the lock)
            if name == "wait" and isinstance(f, ast.Attribute):
                attr = self_attr(f.value)
                if attr is not None and attr in held:
                    return None
            return BLOCKING_CALLS[name]
        # jax dispatch: any call rooted at the jax / jnp namespace
        root = f
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name) and root.id in ("jax", "jnp"):
            return "jax dispatch"
        return None


class GuardedEscapeRule(Rule):
    id = "guarded-escape"
    description = (
        "a '# guarded-by:' container must not escape its lock wholesale "
        "— returned bare or stored into an unguarded attribute — without "
        "a copy (aliasing lets callers mutate/iterate it unlocked)"
    )
    fix_hint = (
        "return a copy (list(...)/dict(...)), or swap-and-drain into a "
        "local under the lock and return the local"
    )

    def check(self, project: Project) -> Iterable[Finding]:
        model = LockModel.of(project)
        for cname in sorted(model.classes):
            info = model.classes[cname]
            containers = self._container_fields(info)
            if not containers:
                continue
            guarded_fields = set(info.guarded)
            for mname in sorted(info.methods):
                if mname in ("__init__", "__post_init__", "__new__"):
                    continue
                method = info.methods[mname]
                for node in ast.walk(method):
                    if isinstance(node, ast.Return) and node.value is not None:
                        attr = self_attr(node.value)
                        if attr in containers:
                            yield self.finding(
                                info.sf.relpath, node.lineno,
                                f"guarded container self.{attr} returned "
                                f"bare from {cname}.{mname}(): callers "
                                "alias it outside "
                                f"self.{info.guarded[attr][0]}",
                                symbol=f"{cname}.{mname}.{attr}:return",
                            )
                    elif isinstance(node, ast.Assign):
                        src = self_attr(node.value)
                        if src not in containers:
                            continue
                        for t in node.targets:
                            dst = self_attr(t)
                            if dst is None or dst in guarded_fields:
                                continue
                            yield self.finding(
                                info.sf.relpath, node.lineno,
                                f"guarded container self.{src} stored "
                                f"into unguarded self.{dst} in "
                                f"{cname}.{mname}(): the alias escapes "
                                f"self.{info.guarded[src][0]}",
                                symbol=f"{cname}.{mname}.{src}:store",
                            )

    @staticmethod
    def _container_fields(info) -> set[str]:
        """Guarded fields whose initializing assignment builds a mutable
        container (scalars/immutables are exempt by construction)."""
        out: set[str] = set()
        for fieldname, (_lock, line) in info.guarded.items():
            found = None
            for node in ast.walk(info.node):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                if node.lineno != line:
                    continue
                found = node.value
                break
            if found is None:
                continue
            if isinstance(found, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                                  ast.DictComp, ast.SetComp)):
                out.add(fieldname)
            elif isinstance(found, ast.Call):
                from geomesa_tpu.analysis.core import call_name

                if call_name(found) in _CONTAINER_CTORS:
                    out.add(fieldname)
        return out
