"""Metrics-registry rule family.

The metrics surface is scraped by operators (``render_prometheus``) and
asserted on by benches and tests; two defects survive review easily:

- a name outside the ``geomesa.<area>.<name>`` convention (hyphens or
  uppercase break the Prometheus rename; a missing area segment lands
  the metric in nobody's dashboard);
- the same name used as two instrument kinds (a counter in one module,
  a gauge in another) — the registry would happily keep both, and the
  scrape would emit two conflicting TYPE lines.

Name collection includes one level of wrapper inference (``_count``,
``_drop_locked``-style helpers) and f-string families like
``f"geomesa.ingest.{stage}"`` — see analysis/registries.py.
"""

from __future__ import annotations

import re

from geomesa_tpu.analysis.core import Project, Rule
from geomesa_tpu.analysis.registries import Registries

# geomesa.<area>.<name...>: lowercase, digits, underscore; >= 2 segments
# after the geomesa. root so every instrument has an area
_NAME_RE = re.compile(r"^geomesa\.[a-z0-9_]+(\.[a-z0-9_]+)+$")
_PREFIX_RE = re.compile(r"^geomesa\.[a-z0-9_]+(\.[a-z0-9_]+)*\.$")


class MetricConventionRule(Rule):
    id = "metric-convention"
    description = (
        "metric names follow geomesa.<area>.<name> (lowercase, digits, "
        "underscores; at least one area segment)"
    )
    fix_hint = (
        "rename the instrument to geomesa.<area>.<name> — hyphens and "
        "uppercase break the Prometheus exposition rename"
    )

    def check(self, project: Project):
        regs = Registries.of(project)
        for use in regs.metrics.uses:
            pattern = _PREFIX_RE if use.is_prefix else _NAME_RE
            if not pattern.match(use.name):
                kind = "family prefix" if use.is_prefix else "name"
                yield self.finding(
                    use.path, use.line,
                    f"metric {kind} {use.name!r} violates the "
                    "geomesa.<area>.<name> convention",
                    symbol=use.name,
                )


class MetricTypeConflictRule(Rule):
    id = "metric-type-conflict"
    description = (
        "one metric name must map to one instrument kind (counter, "
        "gauge, or timer) across the whole tree"
    )
    fix_hint = (
        "split the name (e.g. .count vs .bytes) so each instrument owns "
        "its own family"
    )

    def check(self, project: Project):
        regs = Registries.of(project)
        for name, uses in sorted(regs.metrics.by_name().items()):
            kinds = {u.instrument for u in uses}
            if len(kinds) > 1:
                sites = ", ".join(
                    f"{u.path}:{u.line} ({u.instrument})" for u in uses
                )
                first = min(uses, key=lambda u: (u.path, u.line))
                yield self.finding(
                    first.path, first.line,
                    f"metric {name!r} used as {len(kinds)} instrument "
                    f"kinds: {sites}",
                    symbol=name,
                )
