"""The shared name registries: knobs, metrics, schema user-data keys.

Every ``geomesa.*`` dotted name in this codebase belongs to exactly one
of three namespaces:

1. **configuration knobs** — declared as typed ``SystemProperty`` objects
   in ``geomesa_tpu/conf.py`` (the GeoMesaSystemProperties analogue);
2. **metric instruments** — counter/gauge/timer names passed to
   ``MetricsRegistry`` methods (directly, or through one level of
   wrapper such as ``BulkLoader._count`` / ``ResultCache._drop_locked``,
   which this module infers from the AST);
3. **schema user-data keys** — per-SFT settings carried in
   ``FeatureType.user_data`` and interchange metadata (the reference's
   SimpleFeatureTypes configs), registered explicitly in
   :data:`USER_DATA_KEYS` below.

This module extracts all three from the AST and is the ONE source of
truth the lint rules, ``tests/test_docs.py`` and docs comparisons use —
so a knob or metric renamed in code without its docs (or vice versa)
fails the build instead of drifting.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from geomesa_tpu.analysis.core import Project, SourceFile, call_name, const_str

# -- schema user-data / interchange metadata keys -------------------------
# The third namespace is small and deliberately explicit: unlike knobs
# (typed declarations) and metrics (instrument calls), user-data keys
# have no single declaration form in code, so the registry IS the
# declaration. A key listed here but never read is itself a finding
# (userdata-unused); a geomesa.* literal matching none of the three
# registries is an undeclared-name finding.
USER_DATA_KEYS: dict[str, str] = {
    "geomesa.crs": "coordinate reference system of the schema's geometries",
    "geomesa.geom": "default geometry field name (Avro/Arrow interchange)",
    "geomesa.sft.spec": "serialized FeatureType spec (Arrow/Parquet metadata)",
    "geomesa.sft.name": "feature type name (Arrow/Parquet metadata)",
    "geomesa.index.dtg": "override of the default time attribute",
    "geomesa.z3.interval": "Z3 time-binning period (day/week/month/year)",
    "geomesa.z3.packed-time": "opt the schema into the packed i32 time column",
    "geomesa.xz.precision": "XZ curve resolution (g in the XZ papers)",
    "geomesa.z.splits": "Z-index shard-bit count",
    "geomesa.attr.splits": "attribute-index shard-bit count",
    "geomesa.indices.enabled": "restrict which index types a schema builds",
    "geomesa.feature.expiry": "age-off TTL spec (reference age-off configs)",
    "geomesa.vis.field": "attribute carrying per-feature visibility labels",
}

# -- fault points ---------------------------------------------------------
# The FOURTH dotted-name namespace (PR 10): every ``fault.fault_point``
# name in the tree. Like USER_DATA_KEYS, the registry IS the declaration
# — fault points have no typed declaration form in code — and the
# ``fault-point-unknown`` rule machine-checks three directions: a
# literal used in code must be registered here, a registered name must
# have a code use site, and a registered name must be exercised by at
# least one test (directly, or through an fnmatch pattern a test arms).
# ``fault.atomic_write(..., point="X")`` contributes the derived pair
# ``X.write`` / ``X.rename``.
FAULT_POINTS: dict[str, str] = {
    # crash-safe persistence (storage/persist.py; docs/durability.md)
    "persist.partition.write": "before a partition file's tmp write",
    "persist.partition.rename": "before a partition's atomic rename",
    "persist.partition.commit": "after the rename (durable bytes)",
    "persist.manifest.write": "before the manifest's tmp write",
    "persist.manifest.rename": "before the manifest commit rename",
    "persist.manifest.commit": "after the manifest commit (durable)",
    "persist.gc": "before post-commit garbage collection",
    "load.partition.read": "before reading a partition on load",
    # catalog metadata (storage/metadata.py FileMetadata)
    "metadata.write": "before a catalog KV tmp write",
    "metadata.rename": "before a catalog KV atomic rename",
    # index-table (re)build (storage/adapter.py)
    "adapter.create_table": "before an index table (re)build",
    # pipelined ingest (ingest/; docs/ingest.md)
    "ingest.split.read": "before reading an input split",
    "ingest.parse": "before converting a split's records",
    "ingest.keys": "before a chunk's key encoding",
    "ingest.sort": "before a chunk's shard radix sort",
    "ingest.commit": "before a chunk's staged commit",
    "ingest.finalize": "before the one atomic ingest publish",
    # streaming flush (streaming/flush.py, store.py; docs/streaming.md)
    "stream.flush.parse": "before a flush micro-chunk's parse stage",
    "stream.flush.keys": "before a flush micro-chunk's key stage",
    "stream.flush.sort": "before a flush micro-chunk's shard sort",
    "streaming.persist": "before the one atomic hot->cold publish",
    "streaming.evict": "between the cold commit and the hot eviction",
    # incremental sliced fold (datastore.fold_upsert; docs/streaming.md)
    "stream.fold.stage": "before pre-staging update chunks at micro-flush",
    "stream.fold.slice": "before building one fold slice",
    "stream.fold.publish": "before a fold slice's atomic publish",
    # streaming WAL (streaming/wal.py; docs/durability.md)
    "stream.wal.append": "before a WAL record is encoded/buffered",
    "stream.wal.sync": "before a WAL fsync (group commit)",
    "stream.wal.rotate": "before sealing/rotating the active segment",
    "stream.wal.truncate": "before cutting a torn WAL tail",
    "stream.wal.replay": "before replaying a WAL segment on recovery",
    # standing-query matching (streaming/standing.py; docs/standing.md)
    "standing.match": "before a batch's route+match pipeline runs",
    "standing.deliver": "before a batch's alerts enqueue/windows fold",
    # WAL shipping / replication (streaming/replica.py; docs/replication.md)
    "replica.ship.segment": "before the shipper reads a segment chunk",
    "replica.apply": "before a follower appends+applies a shipped chunk",
    "replica.promote": "at the entry of a follower's promotion",
    "replica.fence": "before a stale-term shipment is refused",
    # map-tile pyramid (tiles/pyramid.py; docs/tiles.md)
    "tiles.compose": "before a pyramid tile composes (leaf scan or child fold)",
    "tiles.leaf.scan": "before a leaf tile's backing row scan",
    # multi-host pod tier (pod/; docs/distributed.md)
    "pod.dispatch": "before one host's scan/ingest leg is dispatched",
    "pod.join": "before per-host results merge at the coordinator",
    "pod.wal.route": "before a routed slice reaches its owning host's WAL",
    "pod.wal.replay": "before a killed host's WAL replay on rejoin",
}

# -- controllers ----------------------------------------------------------
# The FIFTH dotted-name namespace (PR 19; docs/tuning.md): the store's
# AUTO-TUNED knob surface. A knob a controller writes online is a
# bigger contract than a knob an operator sets — it must declare hard
# bounds (the controller may never leave them) and an objective metric
# that actually exists (a controller optimizing a metric nobody
# records would hill-climb noise). Like USER_DATA_KEYS and
# FAULT_POINTS, this registry IS the declaration; the
# ``controller-registry`` rule machine-checks both directions (every
# ``ControllerSpec`` literal registered here, every name here backed
# by a spec) plus the per-spec contract: knob resolves in the knob
# registry, ``lo < hi`` present, objective resolves in the metrics
# registry.
CONTROLLERS: dict[str, str] = {
    "cache_min_cost": (
        "result-cache admission cost threshold, tuned against the "
        "cache-hit rate (cache/result.py admission gate)"
    ),
    "fused_chunk_slots": (
        "fused transfer chunk slot count, derived from measured link "
        "RTT on the doubling ladder (scan/block_kernels.py)"
    ),
    "fold_slice_rows": (
        "incremental fold slice size, tuned against the slice-pause "
        "p99 (datastore.fold_upsert)"
    ),
    "flush_chunk_rows": (
        "stream flush batch rows, tuned against flushed-row "
        "throughput (streaming/flush.py)"
    ),
}


# metric instrument methods on MetricsRegistry, by instrument kind
INSTRUMENT_METHODS = {
    "counter": "counter",
    "counter_value": "counter",
    "gauge": "gauge",
    "timer_update": "timer",
    "time": "timer",
    # the live-quantile instrument (docs/observability.md): observe()
    # records, histogram_quantile() reads — both name a histogram, so
    # convention/type-conflict/doc rules cover the family
    "observe": "histogram",
    "histogram_quantile": "histogram",
}

# reference-GeoMesa names the migration guide legitimately cites while
# mapping them to this build's equivalents — resolvable on purpose, so
# the doc rule doesn't force rewording honest reference citations
REFERENCE_NAMES: dict[str, str] = {
    "geomesa.table.partition": (
        "reference table-partitioning key (docs/migration.md maps it to "
        "the merge-compaction contiguous-segment design)"
    ),
}

# dotted-name extraction: geomesa.x[.y]*, optionally a `.*` family
# wildcard (docstrings say "the geomesa.ingest.* family"). Segments
# never end with punctuation (sentence dots stay out), and the negative
# lookbehind keeps matches out of URLs ("http://geomesa.org") and java
# namespaces ("org.geomesa.tpu").
DOTTED_RE = re.compile(
    r"(?<![a-z0-9_.\-/:])geomesa\.[a-z0-9_]+(?:[.\-][a-z0-9_]+)*(?:\.\*)?"
)


def extract_dotted(text: str) -> list[str]:
    """All geomesa.* dotted names in a text blob (a trailing ``.*``
    marks a family wildcard and is kept for the caller to classify)."""
    return [tok for tok in DOTTED_RE.findall(text) if "." in tok]


# -- knobs ----------------------------------------------------------------


@dataclass(frozen=True)
class Knob:
    name: str          # dotted property name
    var: str           # module-level variable in conf.py
    doc: str           # declaration doc text
    default_src: str   # source of the default expression
    line: int


@dataclass
class KnobRegistry:
    knobs: dict[str, Knob] = field(default_factory=dict)
    by_var: dict[str, Knob] = field(default_factory=dict)
    path: str = "geomesa_tpu/conf.py"

    @classmethod
    def load(cls, project: Project) -> "KnobRegistry":
        reg = cls()
        sf = project.files.get(reg.path)
        if sf is None or sf.tree is None:
            return reg
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            if call_name(node.value) != "SystemProperty":
                continue
            args = node.value.args
            name = const_str(args[0]) if args else None
            if name is None:
                continue
            var = (
                node.targets[0].id
                if node.targets and isinstance(node.targets[0], ast.Name)
                else ""
            )
            doc = ""
            if len(args) > 3:
                doc = const_str(args[3]) or ""
            for kw in node.value.keywords:
                if kw.arg == "doc":
                    doc = const_str(kw.value) or ""
            default_src = ast.unparse(args[1]) if len(args) > 1 else ""
            knob = Knob(name, var, doc, default_src, node.lineno)
            reg.knobs[name] = knob
            if var:
                reg.by_var[var] = knob
        return reg

    def resolves(self, name: str) -> bool:
        return name in self.knobs


# -- metrics --------------------------------------------------------------


@dataclass(frozen=True)
class MetricUse:
    name: str         # concrete name, or prefix when is_prefix
    instrument: str   # counter | gauge | timer
    path: str
    line: int
    is_prefix: bool = False  # f-string family, e.g. geomesa.ingest.<stage>


@dataclass
class MetricRegistry:
    uses: list[MetricUse] = field(default_factory=list)

    @classmethod
    def collect(cls, project: Project) -> "MetricRegistry":
        reg = cls()
        wrappers = _infer_wrappers(project)
        for sf in project.python_files():
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = call_name(node)
                instrument = INSTRUMENT_METHODS.get(fname)
                if instrument is not None:
                    candidates = [(instrument, 0)]
                else:
                    # wrapper call: same-named wrappers may disagree on
                    # the name-param position, and an attribute call may
                    # be a bound method (self consumed, args shift by 1)
                    # OR a module attribute (no shift) — try every
                    # candidate position, first geomesa literal wins
                    cands = wrappers.get(fname)
                    if not cands:
                        continue
                    candidates = []
                    for instr, pos in sorted(cands):
                        if isinstance(node.func, ast.Attribute):
                            candidates += [(instr, pos - 1), (instr, pos)]
                        else:
                            candidates.append((instr, pos))
                for instrument, arg_idx in candidates:
                    if not 0 <= arg_idx < len(node.args):
                        continue  # incl. bound-vs-bare mismatch (< 0)
                    use = _classify_name_arg(
                        node.args[arg_idx], instrument, sf, node
                    )
                    if use is not None:
                        reg.uses.append(use)
                        break
        return reg

    def names(self) -> set[str]:
        # memoized: resolves() runs once per geomesa.* occurrence over
        # the whole tree, and self.uses is frozen after collect()
        cached = getattr(self, "_names", None)
        if cached is None:
            cached = {u.name for u in self.uses if not u.is_prefix}
            self._names = cached
        return cached

    def prefixes(self) -> set[str]:
        cached = getattr(self, "_prefixes", None)
        if cached is None:
            cached = {u.name for u in self.uses if u.is_prefix}
            self._prefixes = cached
        return cached

    def resolves(self, name: str) -> bool:
        if name in self.names():
            return True
        return any(name.startswith(p) for p in self.prefixes())

    def by_name(self) -> dict[str, list[MetricUse]]:
        out: dict[str, list[MetricUse]] = {}
        for u in self.uses:
            out.setdefault(u.name, []).append(u)
        return out


def _classify_name_arg(arg, instrument, sf: SourceFile, node) -> "MetricUse | None":
    s = const_str(arg)
    if s is not None:
        if s.startswith("geomesa."):
            return MetricUse(s, instrument, sf.relpath, node.lineno)
        return None
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = const_str(arg.values[0])
        if head and head.startswith("geomesa."):
            return MetricUse(
                head, instrument, sf.relpath, node.lineno, is_prefix=True
            )
    return None


def _infer_wrappers(project: Project) -> dict[str, set]:
    """One level of wrapper inference: a function whose parameter is
    passed as the name argument of a direct instrument call is itself an
    instrument call site (``_count`` -> counter, ``_drop_locked``'s
    ``counter`` param -> counter). Maps func name -> set of
    (instrument, param position including self) — a SET because
    same-named wrappers in different classes may disagree on the
    position; call sites try every candidate."""
    out: dict[str, set] = {}
    for sf in project.python_files():
        if sf.tree is None:
            continue
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in fn.args.args]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                instrument = INSTRUMENT_METHODS.get(call_name(node))
                if instrument is None or not node.args:
                    continue
                a0 = node.args[0]
                if isinstance(a0, ast.Name) and a0.id in params:
                    out.setdefault(fn.name, set()).add(
                        (instrument, params.index(a0.id))
                    )
    return out


# -- fault-point occurrences ----------------------------------------------


@dataclass(frozen=True)
class FaultPointUse:
    name: str
    path: str
    line: int
    via: str  # "fault_point" | "atomic_write"


def fault_point_uses(project: Project) -> list[FaultPointUse]:
    """Every literal fault-point name the production tree can fire:
    ``fault_point("X")`` first arguments, plus the ``X.write``/
    ``X.rename`` pair an ``atomic_write(..., point="X")`` call derives.
    Non-literal names (f-strings, variables) are skipped — they are
    covered at their literal call sites."""
    out: list[FaultPointUse] = []
    for sf in project.python_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = call_name(node)
            if fname == "fault_point" and node.args:
                s = const_str(node.args[0])
                if s is not None:
                    out.append(
                        FaultPointUse(s, sf.relpath, node.lineno, fname)
                    )
            elif fname == "atomic_write":
                for kw in node.keywords:
                    if kw.arg == "point":
                        s = const_str(kw.value)
                        if s is not None:
                            for suffix in (".write", ".rename"):
                                out.append(FaultPointUse(
                                    s + suffix, sf.relpath,
                                    node.lineno, fname,
                                ))
    return out


def test_string_tokens(project: Project) -> set[str]:
    """Every quoted string token in the test tree that could name or
    match a fault point (contains a dot) — the coverage side of the
    fault-point-unknown rule. Cached on the project (one regex pass)."""
    cached = getattr(project, "_lint_test_tokens", None)
    if cached is not None:
        return cached
    tokens: set[str] = set()
    pattern = re.compile(r"[\"']([A-Za-z0-9_.*/:-]+)[\"']")
    for text in project.tests.values():
        for tok in pattern.findall(text):
            if "." in tok:
                tokens.add(tok)
    project._lint_test_tokens = tokens  # type: ignore[attr-defined]
    return tokens


# -- controller-spec occurrences ------------------------------------------


@dataclass(frozen=True)
class ControllerSpecUse:
    """One ``ControllerSpec(...)`` literal call site, with the fields
    the controller-registry rule checks. Non-literal field values come
    through as None and are reported as missing — a spec whose bounds
    are computed cannot be machine-checked, so it does not pass."""

    name: "str | None"
    knob: "str | None"
    lo: "float | None"
    hi: "float | None"
    objective: "str | None"
    path: str
    line: int


def _const_num(node) -> "float | None":
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return float(node.value)
    return None


def controller_spec_uses(project: Project) -> list[ControllerSpecUse]:
    """Every ``ControllerSpec(...)`` call in the production tree with
    its literal name/knob/bounds/objective fields (keyword or
    positional, matching the dataclass field order)."""
    fields = ("name", "knob", "lo", "hi", "objective")
    out: list[ControllerSpecUse] = []
    for sf in project.python_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) != "ControllerSpec":
                continue
            got: dict = {f: None for f in fields}
            for i, arg in enumerate(node.args[:5]):
                f = fields[i]
                got[f] = _const_num(arg) if f in ("lo", "hi") else const_str(arg)
            for kw in node.keywords:
                if kw.arg in ("lo", "hi"):
                    got[kw.arg] = _const_num(kw.value)
                elif kw.arg in fields:
                    got[kw.arg] = const_str(kw.value)
            out.append(ControllerSpecUse(
                got["name"], got["knob"], got["lo"], got["hi"],
                got["objective"], sf.relpath, node.lineno,
            ))
    return out


# -- doc occurrences ------------------------------------------------------


@dataclass(frozen=True)
class DocName:
    name: str
    path: str
    line: int
    wildcard: bool  # "geomesa.ingest.*" family mention


def doc_names(project: Project) -> list[DocName]:
    """Every geomesa.* dotted name mentioned in docs/*.md, with lines."""
    out = []
    for rel, doc in sorted(project.docs.items()):
        for i, line in enumerate(doc.text.splitlines(), start=1):
            for tok in extract_dotted(line):
                wildcard = tok.endswith(".*")
                out.append(DocName(tok[:-2] if wildcard else tok, rel, i, wildcard))
    return out


# -- the bundle rules share ----------------------------------------------


@dataclass
class Registries:
    knobs: KnobRegistry
    metrics: MetricRegistry

    @classmethod
    def of(cls, project: Project) -> "Registries":
        cached = getattr(project, "_lint_registries", None)
        if cached is not None:
            return cached
        reg = cls(
            knobs=KnobRegistry.load(project),
            metrics=MetricRegistry.collect(project),
        )
        project._lint_registries = reg  # type: ignore[attr-defined]
        return reg

    def resolves(self, name: str, wildcard: bool = False) -> bool:
        """Does a dotted name resolve in ANY namespace? Wildcards
        (``geomesa.ingest.*``) resolve when any registered name or
        family lives under the prefix; a bare family head (prose like
        "the geomesa.ingest stage timers", or an f-string prefix)
        resolves against registered prefix families the same way."""
        if wildcard:
            prefix = name if name.endswith(".") else name + "."
            return (
                any(k.startswith(prefix) for k in self.knobs.knobs)
                or any(m.startswith(prefix) for m in self.metrics.names())
                or any(p.startswith(prefix) or prefix.startswith(p)
                       for p in self.metrics.prefixes())
                or any(u.startswith(prefix) for u in USER_DATA_KEYS)
            )
        return (
            self.knobs.resolves(name)
            or self.metrics.resolves(name)
            or name in USER_DATA_KEYS
            or name in REFERENCE_NAMES
            or (name + ".") in self.metrics.prefixes()
        )
