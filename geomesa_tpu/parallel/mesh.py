"""Device mesh helpers for the distributed scan path.

The reference spreads hot ranges over tablet servers with a 1-byte shard
prefix (/root/reference/geomesa-index-api/src/main/scala/org/locationtech/
geomesa/index/api/ShardStrategy.scala:21-80) and fans scans out over
server-side RPC. The TPU equivalent is a 1-D ``jax.sharding.Mesh`` over the
chips of a slice: table tiles are dealt round-robin across the mesh axis so
any z-range's rows land on every device, scans run under ``shard_map``, and
partial results merge with XLA collectives over ICI (psum / all_gather)
instead of coprocessor RPC.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"


def make_mesh(n_devices: int | None = None, axis: str = SHARD_AXIS) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"asked for {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def shard_spec(mesh: Mesh) -> NamedSharding:
    """Sharding for [D, ...] arrays split along the mesh axis."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
