"""Device mesh helpers for the distributed scan path.

The reference spreads hot ranges over tablet servers with a 1-byte shard
prefix (/root/reference/geomesa-index-api/src/main/scala/org/locationtech/
geomesa/index/api/ShardStrategy.scala:21-80) and fans scans out over
server-side RPC. The TPU equivalent is a 1-D ``jax.sharding.Mesh`` over the
chips of a slice: table tiles are dealt round-robin across the mesh axis so
any z-range's rows land on every device, scans run under ``shard_map``, and
partial results merge with XLA collectives over ICI (psum / all_gather)
instead of coprocessor RPC.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"


def make_mesh(n_devices: int | None = None, axis: str = SHARD_AXIS) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"asked for {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def make_multihost_mesh(
    hosts: int | None = None,
    devices_per_host: int | None = None,
    axis: str = SHARD_AXIS,
) -> Mesh:
    """A 1-D scan mesh over a multi-host slice, devices ordered HOST-MAJOR.

    Multi-host layout guidance (SURVEY §2.6 distributed comm backend):
    the scan path needs only a 1-D axis — each device scans its own
    HBM-resident blocks, and the only cross-device traffic is the
    collective merge (psum for aggregations) plus the host pull of each
    device's packed planes. Host-major ordering keeps every contiguous
    ``devices_per_host`` run of the axis inside one host, so the XLA
    collective schedule does its ring/tree phase over ICI within hosts
    and crosses DCN once per host group — the same hierarchy the
    reference gets from per-regionserver aggregation + client-side merge
    (GeoMesaCoprocessor), with DCN in place of the client RPC fan-in.

    Under ``jax.distributed`` each process contributes its local devices
    (jax.devices() is already globally host-major); single-process runs
    (tests, the virtual CPU mesh) reshape the local devices the same way
    so the layout is testable without a pod.
    """
    devs = jax.devices()
    if hosts is None:
        hosts = max(getattr(jax, "process_count", lambda: 1)(), 1)
    if devices_per_host is None:
        if len(devs) % hosts:
            raise ValueError(
                f"{len(devs)} devices do not divide over {hosts} hosts"
            )
        devices_per_host = len(devs) // hosts
    return Mesh(
        np.array(_host_major(devs, hosts, devices_per_host)), (axis,)
    )


def host_major_slices(devs, hosts: int, devices_per_host: int) -> list:
    """Per-host device slices, host-major: ``out[h]`` is host h's
    ``devices_per_host`` devices. Devices group by ``process_index``
    (real multi-process pods); single-process runs (tests, the virtual
    CPU mesh) slice the one process's devices into synthetic host
    groups, which preserves the layout semantics without a pod. This is
    the shared layout authority: ``make_multihost_mesh`` concatenates
    the slices into one flat scan axis, and the pod host-group tier
    (geomesa_tpu.pod) builds one PER-HOST shard mesh from each slice —
    both see the same device-to-host assignment."""
    by_host: dict = {}
    for d in devs:
        by_host.setdefault(getattr(d, "process_index", 0), []).append(d)
    if len(by_host) >= hosts > 1:
        out = []
        for h in sorted(by_host)[:hosts]:
            hd = by_host[h]
            if len(hd) < devices_per_host:
                raise ValueError(
                    f"host {h} has {len(hd)} devices, need {devices_per_host}"
                )
            out.append(hd[:devices_per_host])
        return out
    n = hosts * devices_per_host
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    return [
        list(devs[h * devices_per_host : (h + 1) * devices_per_host])
        for h in range(hosts)
    ]


def _host_major(devs, hosts: int, devices_per_host: int) -> list:
    """Flat host-major device order (see ``host_major_slices``)."""
    return [d for hd in host_major_slices(devs, hosts, devices_per_host) for d in hd]


def shard_spec(mesh: Mesh) -> NamedSharding:
    """Sharding for [D, ...] arrays split along the mesh axis."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
