"""Multi-device execution: mesh sharding + collective-merged scans.

SURVEY.md §2.6 mapping: shard fan-out -> round-robin tile dealing over a
``jax.sharding.Mesh``; coprocessor aggregation -> ``psum``/``all_gather``
under ``shard_map``.
"""

from geomesa_tpu.parallel.dtable import DistributedIndexTable
from geomesa_tpu.parallel.mesh import SHARD_AXIS, make_mesh, make_multihost_mesh

__all__ = ["DistributedIndexTable", "make_mesh", "make_multihost_mesh", "SHARD_AXIS"]
