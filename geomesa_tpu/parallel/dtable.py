"""DistributedIndexTable: one index sharded over a device mesh.

Layout: the sorted table is cut into fixed-size tiles which are dealt
round-robin across the mesh axis (global tile t -> device ``t % D``, local
slot ``t // D``). Round-robin is the ShardStrategy analogue (/root/
reference/geomesa-index-api/src/main/scala/org/locationtech/geomesa/index/
api/ShardStrategy.scala:21-80): because consecutive z-runs interleave
across chips, any query's candidate ranges fan out over the whole mesh
instead of hot-spotting one device.

Scan execution is a ``shard_map`` program: every device masks its own
candidate tiles (same fused predicate as the single-device kernel), counts
merge with ``psum`` and row ids with ``all_gather`` over ICI — the
coprocessor-aggregation tier of the reference (rpc/coprocessor/
GeoMesaCoprocessor.scala:28-79) collapsed into XLA collectives.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from geomesa_tpu.index.api import IndexKeySpace, ScanConfig, WriteKeys
from geomesa_tpu.scan import kernels
from geomesa_tpu.scan.kernels import pad_pow2
from geomesa_tpu.storage.table import DEFAULT_TILE, SortedKeys


@lru_cache(maxsize=64)
def _build_scan(mesh, names, tile, cap, extent_mode, has_boxes, has_windows, count_only):
    """jit(shard_map(local scan)) for one static configuration.

    Local in-block shapes: cols [1, L], tile_ids [1, T]; boxes/windows are
    replicated. Outputs are replicated: per-device counts [D] and, unless
    count_only, per-device local row ids [D, cap] (-1 past each count).
    """
    axis = mesh.axis_names[0]

    def body(tile_ids, boxes, windows, *col_arrays):
        cols = {k: v[0] for k, v in zip(names, col_arrays)}
        m, base = kernels._tile_mask(
            cols,
            tile_ids[0],
            boxes if has_boxes else None,
            windows if has_windows else None,
            tile,
            extent_mode,
        )
        cnt = m.sum(dtype=jnp.int32)
        cnt_all = lax.all_gather(cnt, axis)
        if count_only:
            return (cnt_all,)
        _, rows = kernels.compact_rows(m, base, cap)
        rows_all = lax.all_gather(rows, axis)
        return cnt_all, rows_all

    n_cols = len(names)
    in_specs = (P(axis, None), P(), P()) + (P(axis, None),) * n_cols
    out_specs = (P(),) if count_only else (P(), P())
    return jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    )


@lru_cache(maxsize=64)
def _build_density(mesh, names, tile, width, height, extent_mode, has_boxes, has_windows):
    """jit(shard_map(local density + psum)): every device renders its own
    candidate tiles onto the grid, partial grids merge over ICI with psum —
    the coprocessor-aggregation merge collapsed into one collective."""
    from geomesa_tpu.scan import aggregations

    axis = mesh.axis_names[0]

    def body(tile_ids, boxes, windows, grid_bounds, *col_arrays):
        cols = {k: v[0] for k, v in zip(names, col_arrays)}
        grid = aggregations.tile_density(
            cols,
            tile_ids[0],
            boxes if has_boxes else None,
            windows if has_windows else None,
            grid_bounds,
            tile=tile,
            width=width,
            height=height,
            extent_mode=extent_mode,
        )
        return lax.psum(grid, axis)

    n_cols = len(names)
    in_specs = (P(axis, None), P(), P(), P()) + (P(axis, None),) * n_cols
    return jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=P(), check_vma=False)
    )


class DistributedIndexTable(SortedKeys):
    """Sorted columnar index table sharded over a 1-D mesh."""

    def __init__(
        self,
        keyspace: IndexKeySpace,
        keys: WriteKeys,
        mesh: Mesh,
        tile: int = DEFAULT_TILE,
    ):
        super().__init__(keyspace, keys, tile)
        self.mesh = mesh
        self.n_devices = mesh.devices.size
        D = self.n_devices

        # pad tiles to a multiple of D, deal round-robin
        n_tiles = max(1, -(-self.n // tile))
        n_tiles = -(-n_tiles // D) * D
        self.n_tiles = n_tiles
        self.n_pad = n_tiles * tile
        self.tiles_per_device = n_tiles // D
        L = self.tiles_per_device * tile

        cols = self.pad_cols(keys, self.n_pad)
        # [n_tiles, tile] -> deal: stacked[d, j] = global tile j*D + d
        deal = (
            np.arange(n_tiles).reshape(self.tiles_per_device, D).T
        )  # [D, tiles_per_device]
        spec = NamedSharding(mesh, P(mesh.axis_names[0], None))
        self.col_names = tuple(sorted(cols))
        self.cols = {
            k: jax.device_put(
                cols[k].reshape(n_tiles, tile)[deal].reshape(D, L), spec
            )
            for k in self.col_names
        }
        self._shard_spec = spec
        self._rep_spec = NamedSharding(mesh, P())

    # -- pruning ---------------------------------------------------------
    def candidate_tiles_per_device(self, config: ScanConfig) -> np.ndarray | None:
        """[D, T_pad] local tile slots covering the scan ranges (-1 = pad),
        or None when nothing matches. Global tile expansion is shared with
        the single-device table (SortedKeys.candidate_tiles); only the
        round-robin deal is distributed-specific."""
        D = self.n_devices
        gtiles = self.candidate_tiles(config)
        if len(gtiles) == 0:
            return None
        # global tile t -> (device t % D, local slot t // D)
        per_dev = [gtiles[gtiles % D == d] // D for d in range(D)]
        t_pad = pad_pow2(max(len(p) for p in per_dev), 4, factor=4)
        out = np.full((D, t_pad), -1, dtype=np.int32)
        for d, p in enumerate(per_dev):
            out[d, : len(p)] = p
        return out

    # -- scanning --------------------------------------------------------
    def _args(self, config: ScanConfig, tiles: np.ndarray):
        boxes = (
            kernels.pad_boxes(config.boxes)
            if config.boxes is not None
            else jnp.zeros((1, 4), jnp.float32)
        )
        windows = (
            kernels.pad_windows(config.windows)
            if config.windows is not None
            else jnp.zeros((1, 3), jnp.int32)
        )
        tiles_dev = jax.device_put(tiles, self._shard_spec)
        boxes = jax.device_put(boxes, self._rep_spec)
        windows = jax.device_put(windows, self._rep_spec)
        return tiles_dev, boxes, windows

    def scan(self, config: ScanConfig, cap_hint: int = 4096) -> np.ndarray:
        """Distributed scan; returns matching feature ordinals ascending in
        table order, exactly matching the single-device result."""
        if config.disjoint or self.n == 0:
            return np.zeros(0, dtype=np.int64)
        tiles = self.candidate_tiles_per_device(config)
        if tiles is None:
            return np.zeros(0, dtype=np.int64)
        D = self.n_devices
        has_boxes = config.boxes is not None
        has_windows = config.windows is not None
        max_possible = int((tiles >= 0).sum(axis=1).max()) * self.tile
        cap = min(pad_pow2(cap_hint, 4096), pad_pow2(max_possible, 4096))
        col_args = tuple(self.cols[k] for k in self.col_names)
        while True:
            fn = _build_scan(
                self.mesh, self.col_names, self.tile, cap,
                config.extent_mode, has_boxes, has_windows, False,
            )
            tiles_dev, boxes, windows = self._args(config, tiles)
            cnt_all, rows_all = fn(tiles_dev, boxes, windows, *col_args)
            cnt_all = np.asarray(cnt_all)
            if cnt_all.max(initial=0) <= cap or cap >= max_possible:
                break
            cap = pad_pow2(int(cnt_all.max()), cap * 4)
        rows_all = np.asarray(rows_all)
        out: list[np.ndarray] = []
        for d in range(D):
            local = rows_all[d, : cnt_all[d]].astype(np.int64)
            # local row -> global padded row: tile slot j, offset o
            j, o = local // self.tile, local % self.tile
            out.append((j * D + d) * self.tile + o)
        rows = np.sort(np.concatenate(out)) if out else np.zeros(0, np.int64)
        return self.perm[rows]

    def count(self, config: ScanConfig) -> int:
        """Loose count via psum-merged per-device counts."""
        if config.disjoint or self.n == 0:
            return 0
        tiles = self.candidate_tiles_per_device(config)
        if tiles is None:
            return 0
        fn = _build_scan(
            self.mesh, self.col_names, self.tile, 0,
            config.extent_mode, config.boxes is not None,
            config.windows is not None, True,
        )
        tiles_dev, boxes, windows = self._args(config, tiles)
        (cnt_all,) = fn(tiles_dev, boxes, windows, *(self.cols[k] for k in self.col_names))
        return int(np.asarray(cnt_all).sum())

    def density(
        self, config: ScanConfig, bounds, width: int, height: int
    ) -> np.ndarray:
        """psum-merged density grid, equal to the single-device result."""
        if config.disjoint or self.n == 0:
            return np.zeros((height, width), dtype=np.float32)
        tiles = self.candidate_tiles_per_device(config)
        if tiles is None:
            return np.zeros((height, width), dtype=np.float32)
        fn = _build_density(
            self.mesh, self.col_names, self.tile, width, height,
            config.extent_mode, config.boxes is not None, config.windows is not None,
        )
        tiles_dev, boxes, windows = self._args(config, tiles)
        gb = jax.device_put(
            jnp.asarray(np.asarray(bounds, dtype=np.float32)), self._rep_spec
        )
        grid = fn(tiles_dev, boxes, windows, gb, *(self.cols[k] for k in self.col_names))
        return np.asarray(grid)

    @property
    def nbytes_device(self) -> int:
        return sum(int(v.nbytes) for v in self.cols.values())
